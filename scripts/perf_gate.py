#!/usr/bin/env python
"""perf_gate — the dispatch-cost regression gate.

Two modes, both against the committed budgets
(``scripts/perf_budgets.json``):

1. ``--bench BENCH_*.json`` (default: BENCH_partial.json): pure-JSON
   comparison of a bench artifact's stage/executor p99s,
   dispatches-per-row and dispatches-per-barrier against the budgets.
   No jax import — runs in ~100ms, safe anywhere. Fields a (seed)
   artifact does not carry are SKIPPED with a note, never failed: the
   gate tightens as artifacts grow richer, it does not brick old ones.

2. ``--smoke``: a CPU-cheap q5 steady-state microbench run in-process
   with the dispatch-wall profiler armed — asserts the steady-state
   device-dispatch count per barrier and the host-python ms/row stay
   under budget. This is the tier-1 CI smoke: the fragment-fusion work
   (ROADMAP open item 1) drives dispatches-per-barrier toward 1; this
   gate makes sure nothing silently drives it the other way.

Exit code: 0 = within budget, 1 = regression, 2 = usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGETS = os.path.join(ROOT, "scripts", "perf_budgets.json")
DEFAULT_BENCH = os.path.join(ROOT, "BENCH_partial.json")


def _load(path: str):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# mode 1: bench-artifact comparison (pure JSON)
# ---------------------------------------------------------------------------


def _stage_p99(bench: dict, stage: str) -> float:
    """Max p99 of one stage across every fragment label set, over all
    ``*barrier_stage_ms`` blocks in the artifact."""
    worst = 0.0
    for key, block in bench.items():
        if not key.endswith("barrier_stage_ms") or not isinstance(block, dict):
            continue
        for lbl, row in block.items():
            if f"stage={stage}" in lbl and isinstance(row, dict):
                worst = max(worst, float(row.get("p99", 0.0)))
    return worst


def check_bench(bench: dict, budgets: dict, verbose=True):
    """Returns (violations, skipped) lists of strings."""
    b = budgets.get("bench", {})
    violations, skipped = [], []

    def note(msg):
        if verbose:
            print(f"[perf_gate] {msg}")

    for stage, mx in b.get("stage_p99_ms", {}).items():
        got = _stage_p99(bench, stage)
        if got == 0.0:
            skipped.append(f"stage {stage}: no observations in artifact")
            continue
        if got > mx:
            violations.append(
                f"stage {stage} p99 {got:.2f}ms > budget {mx}ms"
            )
        else:
            note(f"stage {stage} p99 {got:.2f}ms <= {mx}ms ok")
    for key, mx in b.get("scalar_max", {}).items():
        if key not in bench:
            skipped.append(f"{key}: absent from artifact")
            continue
        got = float(bench[key])
        if got > mx:
            violations.append(f"{key} = {got} > budget {mx}")
        else:
            note(f"{key} = {got} <= {mx} ok")
    for q, mx in b.get("dispatches_per_row_max", {}).items():
        key = f"{q}_dispatches_per_row"
        if key not in bench:
            skipped.append(f"{key}: absent from artifact")
            continue
        got = float(bench[key])
        if got > mx:
            violations.append(
                f"{q}: {got} device dispatches/row > budget {mx} "
                "(per-op dispatch regression — see PROFILE.md worklist)"
            )
        else:
            note(f"{q}: {got} dispatches/row <= {mx} ok")
    for q, mx in b.get("dispatches_per_barrier_max", {}).items():
        key = f"{q}_dispatches_per_barrier"
        if key not in bench:
            skipped.append(f"{key}: absent from artifact")
            continue
        got = float(bench[key])
        if got > mx:
            violations.append(
                f"{q}: {got} device dispatches/barrier > budget {mx}"
            )
        else:
            note(f"{q}: {got} dispatches/barrier <= {mx} ok")
    # steady-state recompile-hazard budget (PR 9): after warmup, ZERO
    # novel abstract input signatures per query — a nonzero count means
    # a shape escaped the bucket lattice and the run was re-tracing
    for q, mx in b.get("recompile_hazards_max", {}).items():
        key = f"{q}_recompile_hazards"
        if key not in bench:
            skipped.append(f"{key}: absent from artifact")
            continue
        got = float(bench[key])
        if got > mx:
            violations.append(
                f"{q}: {got:.0f} post-warmup recompile hazards > budget "
                f"{mx} (shape escaped the bucket lattice — see "
                f"{q}_shape_governor in the artifact)"
            )
        else:
            note(f"{q}: {got:.0f} recompile hazards <= {mx} ok")
    # padding-overhead backstop: the price of bucketed shapes is
    # masked dead lanes; a pathological wasted-lane fraction (e.g. the
    # governor pinning everything at a huge bucket) must not land
    # silently. Calibrated loose: pow2 tables at <=50% load are >=50%
    # padding BY DESIGN.
    for q, mx in b.get("padding_wasted_frac_max", {}).items():
        blk = bench.get(f"{q}_padding")
        if not isinstance(blk, dict) or "wasted_lane_frac" not in blk:
            skipped.append(f"{q}_padding: absent from artifact")
            continue
        got = float(blk["wasted_lane_frac"])
        if got > mx:
            violations.append(
                f"{q}: padded-state wasted-lane fraction {got} > "
                f"budget {mx}"
            )
        else:
            note(f"{q}: wasted-lane fraction {got} <= {mx} ok")
    # roofline budgets (PR 11): the modeled-traffic padding fraction
    # per query (the price of bucketed shapes, now measured from the
    # compiled executable + telemetry lanes instead of a device scan)
    # and the per-bucket compile cost of every analyzed program
    rb = budgets.get("roofline", {})
    for q, mx in rb.get("padding_bytes_frac_max", {}).items():
        blk = bench.get(f"{q}_roofline")
        if not isinstance(blk, dict) or "padding_bytes_frac" not in blk:
            skipped.append(f"{q}_roofline: absent from artifact")
            continue
        got = float(blk["padding_bytes_frac"])
        if got > mx:
            violations.append(
                f"{q}: modeled padding-bytes fraction {got} > budget "
                f"{mx} (masked-lane waste dominates the fused "
                "program's traffic)"
            )
        else:
            note(f"{q}: padding-bytes fraction {got} <= {mx} ok")
    cms = rb.get("compile_ms_max")
    if cms:
        for q in ("q5", "q5u", "q7", "q8"):
            blk = bench.get(f"{q}_roofline")
            progs = (blk or {}).get("programs")
            if not isinstance(progs, dict):
                continue
            for key, p in progs.items():
                got = float(p.get("compile_ms", 0.0))
                if got > cms:
                    violations.append(
                        f"{q}: program {key} compiled in {got:.0f}ms > "
                        f"budget {cms}ms per bucket"
                    )
                else:
                    note(f"{q}: {key} compile {got:.0f}ms <= {cms}ms ok")
    # executor-attribution coverage: when the artifact carries the
    # per-executor decomposition it must actually explain the dispatch
    # stage (≥ coverage_min of the stage total), or the breakdown has
    # rotted into decoration
    cov_min = b.get("executor_coverage_min")
    if cov_min:
        for q in ("q5", "q5u", "q7", "q8"):
            blk = bench.get(f"{q}_executor_ms")
            if not isinstance(blk, dict):
                skipped.append(f"{q}_executor_ms: absent from artifact")
                continue
            cov = executor_coverage(bench, q)
            if cov is None:
                skipped.append(f"{q}: no dispatch-stage data to cover")
            elif cov < cov_min:
                violations.append(
                    f"{q}: executor attribution covers only "
                    f"{cov:.0%} of the dispatch stage (< {cov_min:.0%})"
                )
            else:
                note(f"{q}: executor attribution covers {cov:.0%} ok")
    # freshness fields (PR 16): bench artifacts stamp a {q}_freshness
    # block (p50/p99/n per lane) from the pipeline's own samples; when
    # present, the commit->visible p99 is held to the SLO budget and an
    # empty sample set is a violation (the lane went dark), while an
    # absent block is a skip (older artifacts stay comparable)
    fb = budgets.get("freshness", {})
    fmx = fb.get("bench_commit_to_visible_p99_ms_max")
    if fmx:
        for q in ("q5", "q5u", "q7", "q8"):
            blk = bench.get(f"{q}_freshness")
            if not isinstance(blk, dict):
                skipped.append(f"{q}_freshness: absent from artifact")
                continue
            c2v = blk.get("commit_to_visible_ms") or {}
            if not c2v.get("n"):
                violations.append(
                    f"{q}: {q}_freshness stamped but carries no "
                    "commit->visible samples — the lane went dark"
                )
                continue
            got = float(c2v.get("p99", 0.0))
            if got > fmx:
                violations.append(
                    f"{q}: commit->visible p99 {got}ms > budget "
                    f"bench_commit_to_visible_p99_ms_max={fmx}"
                )
            else:
                note(f"{q}: commit->visible p99 {got}ms <= {fmx}ms ok")
    return violations, skipped


def executor_coverage(bench: dict, q: str):
    """Fraction of the query's dispatch-stage total explained by its
    per-executor (flush + barrier_apply, host + device-wait) sums."""
    stage_key = "barrier_stage_ms" if q == "q5u" else f"{q}_barrier_stage_ms"
    stages = bench.get(stage_key) or {}
    disp = sum(
        float(row.get("sum", 0.0))
        for lbl, row in stages.items()
        if "stage=dispatch" in lbl and isinstance(row, dict)
    )
    if disp <= 0:
        return None
    blk = bench.get(f"{q}_executor_ms") or {}
    covered = 0.0
    for hist in ("executor_ms", "executor_device_wait_ms"):
        for lbl, row in (blk.get(hist) or {}).items():
            if ("phase=flush" in lbl or "phase=barrier_apply" in lbl) and (
                isinstance(row, dict)
            ):
                covered += float(row.get("sum", 0.0))
    return covered / disp


# ---------------------------------------------------------------------------
# mode 3: fusion-feasibility regression gate (static, CPU, in-process)
# ---------------------------------------------------------------------------

DEFAULT_FUSION_BASELINE = os.path.join(ROOT, "FUSION_REPORT.json")


def run_fusion_gate(
    budgets: dict,
    baseline_path: str = None,
    current_path: str = None,
):
    """Re-run the fusion analyzer over the Nexmark corpus and compare
    against the committed FUSION_REPORT.json baseline: per fragment,
    the fusible executor prefix must not SHRINK and the host-sync
    count must not GROW (plus the optional absolute per-fragment
    ``max_host_sync_points`` budget). This is the ratchet for ROADMAP
    item 1 — every fusion PR moves prefixes up and sync counts down,
    and nothing moves them back silently. Returns (violations,
    skipped)."""
    baseline_path = baseline_path or DEFAULT_FUSION_BASELINE
    try:
        baseline = _load(baseline_path)
    except (OSError, json.JSONDecodeError) as e:
        return [], [f"fusion baseline unreadable ({e}) — gate skipped"]
    if current_path:
        # reuse an analysis another CI stage already paid for (the
        # `lint --fusion-report --json` output, or its __fusion__ key)
        try:
            current = _load(current_path)
        except (OSError, json.JSONDecodeError) as e:
            return [f"fusion current-report unreadable: {e}"], []
        current = current.get("__fusion__", current)
    else:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if ROOT not in sys.path:
            sys.path.insert(0, ROOT)
        import jax

        jax.config.update("jax_platforms", "cpu")
        from risingwave_tpu.analysis.fusion_analyzer import (
            analyze_nexmark,
        )

        current = analyze_nexmark(deep=True)
    fb = budgets.get("fusion", {})
    max_sync = fb.get("max_host_sync_points", {})
    violations, skipped = [], []
    for q, base_rep in baseline.items():
        if q.startswith("_"):
            continue
        if q not in current:
            # a vanished query loses ALL its ratchet coverage — that
            # is a regression, not a skip (fragments two checks below
            # get the same treatment)
            violations.append(
                f"fusion: query {q!r} vanished from the analysis "
                "(baseline still lists it)"
            )
            continue
        base_frags = {
            f["fragment"]: f for f in base_rep.get("fragments", ())
        }
        cur_frags = {
            f["fragment"]: f for f in current[q]["fragments"]
        }
        for name, bf in base_frags.items():
            cf = cur_frags.get(name)
            if cf is None:
                violations.append(
                    f"fusion {q}: fragment {name!r} vanished from the "
                    "analysis (baseline still lists it)"
                )
                continue
            if cf["fusible_prefix"] < bf["fusible_prefix"]:
                violations.append(
                    f"fusion {q}/{name}: fusible prefix regressed "
                    f"{bf['fusible_prefix']} -> {cf['fusible_prefix']}"
                )
            if cf["host_sync_points"] > bf["host_sync_points"]:
                violations.append(
                    f"fusion {q}/{name}: host-sync points grew "
                    f"{bf['host_sync_points']} -> "
                    f"{cf['host_sync_points']}"
                )
            # fallback syncs are outside the fusibility verdict (the
            # fused step compiles them away) but still run per barrier
            # wherever the fallback path executes (e.g. an epoch-
            # batched agg feeding a join) — a regression adding reads
            # there must not slip past the gate
            if cf.get("fallback_sync_points", 0) > bf.get(
                "fallback_sync_points", 0
            ):
                violations.append(
                    f"fusion {q}/{name}: fallback-sync points grew "
                    f"{bf.get('fallback_sync_points', 0)} -> "
                    f"{cf.get('fallback_sync_points', 0)}"
                )
            if bf.get("whole_chain_fusible") and not cf.get(
                "whole_chain_fusible"
            ):
                violations.append(
                    f"fusion {q}/{name}: whole-chain fusible proof lost"
                )
        mx = max_sync.get(q)
        if mx is not None:
            total = current[q]["summary"]["host_sync_points"]
            if total > mx:
                violations.append(
                    f"fusion {q}: {total} host-sync points > budget {mx}"
                )
        # shape-stability ratchet (PR 9): per-code blocker ceilings —
        # RW-E803/E806 are pinned at ZERO for the whole corpus (q7's
        # wedge class must never return), and no code may regress
        # above its committed-baseline count
        cur_codes = current[q]["summary"].get("blockers_by_code", {})
        base_codes = base_rep.get("summary", {}).get(
            "blockers_by_code", {}
        )
        for code, mx in fb.get("max_blocker_codes", {}).items():
            got = int(cur_codes.get(code, 0))
            if got > mx:
                violations.append(
                    f"fusion {q}: {got} {code} finding(s) > budget {mx}"
                    + (
                        " (the q7 wedge class regressed: an executor "
                        "lost its window_buckets lattice)"
                        if code in ("RW-E803", "RW-E806")
                        else ""
                    )
                )
        for code, n in cur_codes.items():
            if int(n) > int(base_codes.get(code, 0)):
                violations.append(
                    f"fusion {q}: blocker {code} count grew "
                    f"{base_codes.get(code, 0)} -> {n} vs baseline"
                )
    return violations, skipped


# ---------------------------------------------------------------------------
# mode 3b: mesh-readiness regression gate (static, CPU, subprocess)
# ---------------------------------------------------------------------------

DEFAULT_MESH_BASELINE = os.path.join(ROOT, "MESH_REPORT.json")


def run_mesh_static_gate(
    budgets: dict,
    baseline_path: str = None,
    current_path: str = None,
):
    """Re-run the mesh analyzer over the sharded corpus and compare
    against the committed MESH_REPORT.json baseline: per fragment, the
    host-routed exchange-edge count (RW-E901 + RW-E907) must not GROW
    and an SPMD-fusibility proof must not be LOST; per query, no E9xx
    code's blocker count may grow past its committed count. This is
    the ratchet for ROADMAP item 3 — the collective-exchange arc moves
    edge counts down and proofs up, and nothing moves them back
    silently. Without ``current_path`` the analysis runs in a fresh
    subprocess (``lint --mesh-report`` owns its 8-virtual-device
    mesh, which cannot be conjured after this process touched jax).
    Returns (violations, skipped)."""
    baseline_path = baseline_path or DEFAULT_MESH_BASELINE
    try:
        baseline = _load(baseline_path)
    except (OSError, json.JSONDecodeError) as e:
        return [], [f"mesh baseline unreadable ({e}) — gate skipped"]
    if current_path:
        try:
            current = _load(current_path)
        except (OSError, json.JSONDecodeError) as e:
            return [f"mesh current-report unreadable: {e}"], []
        current = current.get("__mesh__", current)
    else:
        import subprocess

        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the child claims its own mesh
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "risingwave_tpu",
                "lint",
                "--mesh-report",
                "--json",
            ],
            capture_output=True,
            text=True,
            cwd=ROOT,
            env=env,
        )
        if proc.returncode != 0:
            return [
                "mesh: `lint --mesh-report` failed "
                f"(exit {proc.returncode}): "
                f"{(proc.stderr or proc.stdout).strip()[-400:]}"
            ], []
        try:
            current = json.loads(proc.stdout)
        except json.JSONDecodeError as e:
            return [f"mesh: analyzer emitted unparsable JSON: {e}"], []
    violations, skipped = [], []
    for q, base_rep in baseline.items():
        if q.startswith("_") or q in ("ranking", "top_cost"):
            continue
        if q not in current:
            violations.append(
                f"mesh: query {q!r} vanished from the analysis "
                "(baseline still lists it)"
            )
            continue
        base_frags = {
            f["fragment"]: f for f in base_rep.get("fragments", ())
        }
        cur_frags = {
            f["fragment"]: f for f in current[q]["fragments"]
        }
        for name, bf in base_frags.items():
            cf = cur_frags.get(name)
            if cf is None:
                violations.append(
                    f"mesh {q}: fragment {name!r} vanished from the "
                    "analysis (baseline still lists it)"
                )
                continue
            if cf["host_routed_edges"] > bf["host_routed_edges"]:
                violations.append(
                    f"mesh {q}/{name}: host-routed exchange edges grew "
                    f"{bf['host_routed_edges']} -> "
                    f"{cf['host_routed_edges']}"
                )
            if bf.get("spmd_fusible") and not cf.get("spmd_fusible"):
                violations.append(
                    f"mesh {q}/{name}: SPMD-fusibility proof lost"
                )
        # per-code ratchet: no E9xx class may grow past its committed
        # count (the committed blockers are the worklist, not a quota)
        cur_codes = current[q]["summary"].get("blockers_by_code", {})
        base_codes = base_rep.get("summary", {}).get(
            "blockers_by_code", {}
        )
        for code, n in cur_codes.items():
            if int(n) > int(base_codes.get(code, 0)):
                violations.append(
                    f"mesh {q}: blocker {code} count grew "
                    f"{base_codes.get(code, 0)} -> {n} vs baseline"
                )
        bsum = base_rep.get("summary", {})
        csum = current[q]["summary"]
        if csum.get("spmd_fusible_fragments", 0) < bsum.get(
            "spmd_fusible_fragments", 0
        ):
            violations.append(
                f"mesh {q}: SPMD-fusible fragments shrank "
                f"{bsum.get('spmd_fusible_fragments', 0)} -> "
                f"{csum.get('spmd_fusible_fragments', 0)}"
            )
    return violations, skipped


# ---------------------------------------------------------------------------
# mode 4: black-box recorder gate (host cost + crash-survival smoke)
# ---------------------------------------------------------------------------


def run_blackbox_gate(budgets: dict):
    """Two checks so the black box can never silently rot:

    1. Recorder cost microbench: N records through the REAL
       record+persist path (worst case: fsync every record) — the host
       ms/barrier the recorder adds and the fsync-stall p99 must stay
       under ``blackbox.host_ms_per_barrier_max`` /
       ``fsync_p99_ms_max`` (the recorder rides EVERY barrier; the
       <1%-of-steady-barrier contract from PROFILE.md round 10).
    2. Reader smoke (write ring -> kill -> parse): a subprocess writes
       a segment in a loop, the parent SIGKILLs it mid-write (safe: a
       CPU-pinned process, not a tunnel client) and the reader CLI
       must still reconstruct a monotonic timeline.

    Returns (violations, report)."""
    import signal
    import subprocess
    import tempfile
    import time
    from types import SimpleNamespace

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    from risingwave_tpu.blackbox import FlightRecorder, read_segment
    from risingwave_tpu.metrics import REGISTRY

    bb = budgets.get("blackbox", {})
    violations = []
    report = {}
    with tempfile.TemporaryDirectory() as tmp:
        rec = FlightRecorder()
        rec.configure(dir=tmp, fsync_interval_s=0.0)  # worst case
        REGISTRY.histograms.pop("blackbox_fsync_ms", None)
        n = 300
        t0 = time.perf_counter()
        for i in range(n):
            rec.record_barrier(
                SimpleNamespace(
                    epoch=i + 1,
                    seq=i + 1,
                    checkpoint=i % 4 == 0,
                    wall_ms=10.0,
                    stages_ms={"ingest": 1.0, "dispatch": 8.0},
                    achieved_bw_frac=0.01,
                    chunk_bytes=1 << 20,
                    state_bytes=1 << 22,
                )
            )
        ms_per_rec = (time.perf_counter() - t0) / n * 1e3
        rec.close()
        h = REGISTRY.histograms.get("blackbox_fsync_ms")
        fsync_p99 = h.percentile(99) if h is not None else 0.0
        report["host_ms_per_barrier"] = round(ms_per_rec, 4)
        report["fsync_p99_ms"] = round(fsync_p99, 3)
        mx = bb.get("host_ms_per_barrier_max")
        if mx is not None and ms_per_rec > mx:
            violations.append(
                f"blackbox: {ms_per_rec:.3f} recorder ms/barrier > "
                f"budget {mx} (the recorder rides EVERY barrier)"
            )
        mx = bb.get("fsync_p99_ms_max")
        if mx is not None and fsync_p99 > mx:
            violations.append(
                f"blackbox: fsync stall p99 {fsync_p99:.1f}ms > budget {mx}"
            )
        doc = read_segment(tmp)
        if len(doc["records"]) != n or not doc["monotonic"]:
            violations.append(
                f"blackbox: clean segment misparsed "
                f"({len(doc['records'])}/{n} records, "
                f"monotonic={doc['monotonic']})"
            )
    # -- reader smoke: write ring -> SIGKILL -> parse --------------------
    with tempfile.TemporaryDirectory() as tmp:
        child_code = (
            "import os, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "sys.path.insert(0, %r)\n"
            "from types import SimpleNamespace\n"
            "from risingwave_tpu.blackbox import FlightRecorder\n"
            "rec = FlightRecorder()\n"
            "rec.configure(dir=%r, fsync_interval_s=0.1)\n"
            "i = 0\n"
            "while True:\n"
            "    i += 1\n"
            "    rec.record_barrier(SimpleNamespace(\n"
            "        epoch=i, seq=i, checkpoint=False, wall_ms=1.0,\n"
            "        stages_ms={'dispatch': 1.0}, achieved_bw_frac=0,\n"
            "        chunk_bytes=0, state_bytes=0))\n"
            "    if i == 40:\n"
            "        print('WROTE40', flush=True)\n"
        ) % (ROOT, tmp)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", child_code],
            stdout=subprocess.PIPE,
            text=True,
            env=env,
        )
        try:
            line = proc.stdout.readline()
            # mid-write murder: exactly the r04/r05 failure mode
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
            proc.wait(timeout=10)
            line = ""
        if "WROTE40" not in line:
            violations.append("blackbox: reader-smoke child never wrote")
        else:
            cli = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "risingwave_tpu",
                    "blackbox",
                    tmp,
                    "--json",
                ],
                capture_output=True,
                text=True,
                env=env,
                cwd=ROOT,
            )
            ok = False
            if cli.returncode == 0:
                try:
                    doc = json.loads(cli.stdout.strip().splitlines()[-1])
                    ok = doc["monotonic"] and len(doc["records"]) >= 40
                    report["killed_segment_records"] = len(doc["records"])
                except (ValueError, KeyError, IndexError):
                    ok = False
            if not ok:
                violations.append(
                    "blackbox: reader CLI failed to reconstruct a "
                    f"SIGKILLed segment (rc={cli.returncode}, "
                    f"stderr={cli.stderr[-200:]!r})"
                )
    return violations, report


# ---------------------------------------------------------------------------
# mode 5: device-roofline gate (telemetry overhead + modeled bytes)
# ---------------------------------------------------------------------------


def _q5_steady_setup(events: int, fused: bool):
    """The q5 steady-state harness SHARED by the smoke and roofline
    gates: one pipeline, optional fusion, one fixed chunk pushed every
    epoch (fresh keys would grow the table — a legitimate recompile,
    not the regression these gates hunt). Returns ``(q5, wrappers,
    epoch_fn, rows_per_epoch)``."""
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.queries.nexmark_q import build_q5_lite

    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    wrappers = []
    if fused:
        from risingwave_tpu.runtime.fused_step import fuse_pipeline

        wrappers = fuse_pipeline(q5.pipeline, label="q5")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    bid = gen.next_chunks(events, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )
    rows = int(bid.valid.sum())

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    return q5, wrappers, epoch, rows


def run_roofline_gate(budgets: dict, epochs: int = 4, events: int = 2_000):
    """Three checks so the device-observability layer can never
    silently rot or get expensive:

    1. Telemetry host overhead: the fused telemetry lanes ride the
       existing staged-scalar read, so their ONLY cost is host-side
       decode+record — measured here against the steady fused-barrier
       wall and budgeted < ``telemetry_overhead_frac_max`` (the <1%
       contract).
    2. Modeled bytes exist: an armed deviceprof must produce a nonzero
       modeled-traffic figure for the fused q5 program (the byte
       accounting the roofline replaces host guesses with).
    3. Dispatch neutrality: telemetry+analysis armed, the steady fused
       barrier still costs exactly ONE device dispatch.

    Returns (violations, report)."""
    import time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.deviceprof import DEVICEPROF
    from risingwave_tpu.profiler import PROFILER

    rb = budgets.get("roofline", {})
    violations, report = [], {}
    DEVICEPROF.reset()
    DEVICEPROF.arm()
    _q5, _wrappers, epoch, _rows = _q5_steady_setup(events, fused=True)
    try:
        epoch()
        epoch()  # warm: compiles land outside the window
        DEVICEPROF.flush_analyses()  # deferred AOT analyses too
        DEVICEPROF.telemetry_host_ms = 0.0
        PROFILER.reset()
        PROFILER.enable(fence=False)
        per = []
        t0 = time.perf_counter()
        for _ in range(epochs):
            base = PROFILER.total_dispatches()
            epoch()
            per.append(PROFILER.total_dispatches() - base)
        wall_ms = (time.perf_counter() - t0) * 1e3
    finally:
        PROFILER.disable()
        PROFILER.reset()
    tel_ms = DEVICEPROF.telemetry_host_ms
    frac = tel_ms / wall_ms if wall_ms > 0 else 0.0
    DEVICEPROF.flush_analyses()  # any bucket the steady window minted
    model = DEVICEPROF.steady_model()
    report = {
        "telemetry_host_ms": round(tel_ms, 4),
        "steady_wall_ms": round(wall_ms, 2),
        "telemetry_overhead_frac": round(frac, 5),
        "modeled_bytes": model["modeled_bytes"],
        "padding_frac": model["padding_frac"],
        "dispatches_per_barrier": per,
    }
    mx = rb.get("telemetry_overhead_frac_max")
    if mx is not None and frac > mx:
        violations.append(
            f"roofline: telemetry host overhead {frac:.4f} of the "
            f"steady barrier > budget {mx} (the lanes must ride the "
            "existing staged read, not become a new cost)"
        )
    if not model["modeled_bytes"]:
        violations.append(
            "roofline: armed deviceprof produced NO modeled bytes for "
            "the fused q5 program — the byte accounting regressed to "
            "host guesses"
        )
    if per and max(per) > 1:
        violations.append(
            f"roofline: telemetry armed, steady fused barrier costs "
            f"{max(per):.0f} dispatches (must stay 1 — observability "
            "added a dispatch)"
        )
    DEVICEPROF.disarm()
    DEVICEPROF.reset()
    return violations, report


def run_serving_gate(budgets: dict):
    """The shared-arrangement serving gate (ROADMAP item 4, PR 12):
    run a CI-scale registration storm + concurrent-reader serving
    phase in-process (scripts/bench_serving.run_serving) and hold the
    structural invariants:

    - compile count bounded by plan-shape families, NOT MV count
      (constant lifting + arrangement attach);
    - arrangements == families (every further CREATE attached);
    - N shared MVs hold ~1x one private MV's device state
      (bytes_per_mv_ratio);
    - barrier p99 stays flat after the storm and bounded under
      concurrent reader load;
    - registry publish overhead < 1%% of the steady barrier;
    - zero reader errors (the lock-free path never serves torn or
      failed reads)."""
    b = budgets.get("serving", {})
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    from bench_serving import run_serving

    rep = run_serving(
        mvs=int(b.get("storm_mvs", 48)),
        families=int(b.get("families", 3)),
        readers=int(b.get("readers", 8)),
        read_seconds=float(b.get("read_seconds", 1.2)),
        exec_mode="graph",
        verbose=False,
    )
    v = []

    def gate(metric, budget_key, default, cmp="<="):
        if budget_key not in b and default is None:
            return
        budget = float(b.get(budget_key, default))
        val = float(rep[metric])
        bad = val > budget if cmp == "<=" else val < budget
        if bad:
            v.append(
                f"serving {metric} {val} violates budget "
                f"{budget_key}={budget}"
            )

    if rep["compile_programs"] < 0:
        # the compile-count invariant is this gate's headline: an
        # unreadable jit cache must fail loudly, not pass vacuously
        v.append(
            "serving compile_programs unreadable (jax jit cache API "
            "changed?) — the O(families) compile invariant cannot be "
            "gated"
        )
    else:
        gate("compile_programs", "compile_programs_max", 10)
    gate("arrangements", "arrangements_max", rep["families"])
    gate("bytes_per_mv_ratio", "bytes_per_mv_ratio_max", 0.2)
    gate(
        "barrier_p99_ms_post_storm", "post_storm_barrier_p99_ms_max", 150
    )
    gate(
        "barrier_p99_ms_under_read_load",
        "under_read_barrier_p99_ms_max",
        400,
    )
    gate("reader_p99_ms", "reader_p99_ms_max", 150)
    gate("registry_overhead_frac", "registry_overhead_frac_max", 0.01)
    gate("reads_per_s", "reads_per_s_min", 50, cmp=">=")
    gate("reader_error_count", "reader_errors_max", 0)
    if rep["arrangement_refs"] != rep["mvs"]:
        v.append(
            f"serving arrangement_refs {rep['arrangement_refs']} != "
            f"storm mvs {rep['mvs']} (an attach was lost)"
        )
    return v, rep


# ---------------------------------------------------------------------------
# mode 7: end-to-end freshness SLO gate (commit->visible, CPU, in-process)
# ---------------------------------------------------------------------------


def run_freshness_gate(budgets: dict, epochs: int = 6, events: int = 2_000):
    """The end-to-end freshness SLO gate (ROADMAP observability, PR 16):
    drive the fused q5 chain through a REAL StreamingRuntime — so every
    barrier runs the full _begin_trace -> dispatch -> publish ->
    _observe_freshness lifecycle, not a bare pipeline.barrier() — and
    hold five contracts:

    1. Commit->visible SLO: p99 of the per-barrier barrier-open ->
       snapshot-visible wall stays under
       ``commit_to_visible_p99_ms_max`` (the SLO the north star's
       "<1s freshness" claim is written in, at CPU smoke scale).
    2. The frontier is threaded: with a watermark injected every epoch,
       every steady barrier lands an ``event_time_lag_ms`` sample,
       p99-bounded by ``event_time_lag_p99_ms_max``.
    3. Dispatch neutrality: freshness armed, the steady fused barrier
       still costs at most ``fused_dispatches_per_barrier_max`` device
       dispatches (host-timestamps-only contract: tracking may never
       add a dispatch).
    4. Tracking overhead: FRESHNESS.host_ms (observe + backpressure
       attribution, self-measured) < ``tracking_overhead_frac_max`` of
       the steady window wall (the same <1% budget the blackbox ring
       and telemetry lanes live under).
    5. Attribution exists: the barrier trace names a
       ``backpressure_fragment`` (a slow barrier must name its
       bottleneck, not just a number).

    Returns (violations, report)."""
    import time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.freshness import FRESHNESS
    from risingwave_tpu.profiler import PROFILER
    from risingwave_tpu.queries.nexmark_q import build_q5_lite
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.runtime.fused_step import fuse_pipeline

    fb = budgets.get("freshness", {})
    violations, report = [], {}
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    wrappers = fuse_pipeline(q5.pipeline, label="q5")
    if not wrappers:
        violations.append(
            "freshness: q5 did not fuse — the gate must measure the "
            "fused path (de-fusion regression)"
        )
        return violations, report
    rt = StreamingRuntime(store=None)
    rt.register("q5_mv", q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    bid = gen.next_chunks(events, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )

    def epoch(measure=None):
        # one fixed chunk per epoch (fresh keys would grow the table —
        # a legitimate recompile, not what this gate hunts) + a wall-
        # clock watermark so the event-time frontier advances. The
        # watermark WALK costs its own hop-executor dispatch (data-
        # plane work, identical with tracking off), so the neutrality
        # window brackets rt.barrier() alone: the full _begin_trace ->
        # dispatch -> publish -> _observe_freshness lifecycle.
        rt.push("q5_mv", bid)
        q5.pipeline.watermark("date_time", int(time.time() * 1000))
        if measure is None:
            rt.barrier()
        else:
            base = PROFILER.total_dispatches()
            rt.barrier()
            measure.append(PROFILER.total_dispatches() - base)

    epoch()
    epoch()  # warm: compiles + first-flush paths land outside the window
    FRESHNESS.reset()
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        t0 = time.perf_counter()
        for _ in range(epochs):
            epoch(measure=per)
        wall_ms = (time.perf_counter() - t0) * 1e3
    finally:
        PROFILER.disable()
        PROFILER.reset()

    rows = [r for r in FRESHNESS.history(limit=4096) if r["mv"] == "q5_mv"]

    def _p99(key):
        vals = sorted(
            r[key] for r in rows if isinstance(r.get(key), (int, float))
        )
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    c2v_p99 = _p99("commit_to_visible_ms")
    s2v_p99 = _p99("source_to_visible_ms")
    lag_p99 = _p99("event_time_lag_ms")
    frac = FRESHNESS.host_ms / wall_ms if wall_ms > 0 else 0.0
    tr = rt.last_epoch_trace
    bp_frag = getattr(tr, "backpressure_fragment", None) if tr else None
    report = {
        "freshness_samples": len(rows),
        "commit_to_visible_p99_ms": c2v_p99,
        "source_to_visible_p99_ms": s2v_p99,
        "event_time_lag_p99_ms": lag_p99,
        "tracking_host_ms": round(FRESHNESS.host_ms, 4),
        "steady_wall_ms": round(wall_ms, 2),
        "tracking_overhead_frac": round(frac, 5),
        "dispatches_per_barrier": per,
        "backpressure_fragment": bp_frag,
    }
    if len(rows) < epochs:
        violations.append(
            f"freshness: only {len(rows)} samples for {epochs} steady "
            "barriers — the runtime stopped observing freshness"
        )
    for key, val in (
        ("commit_to_visible_p99_ms_max", c2v_p99),
        ("source_to_visible_p99_ms_max", s2v_p99),
        ("event_time_lag_p99_ms_max", lag_p99),
    ):
        mx = fb.get(key)
        if mx is None:
            continue
        if val is None:
            violations.append(
                f"freshness: no samples to hold {key} against — the "
                f"{key.replace('_p99_ms_max', '')} lane went dark"
            )
        elif val > mx:
            violations.append(
                f"freshness: p99 {val:.1f}ms > budget {key}={mx} (SLO "
                "violated at CPU smoke scale)"
            )
    mx = fb.get("fused_dispatches_per_barrier_max")
    if mx is not None and per and max(per) > mx:
        violations.append(
            f"freshness: tracking armed, steady fused barrier costs "
            f"{max(per):.0f} dispatches > budget {mx} — freshness "
            "tracking added a device dispatch"
        )
    mx = fb.get("tracking_overhead_frac_max")
    if mx is not None and frac > mx:
        violations.append(
            f"freshness: host tracking overhead {frac:.4f} of the "
            f"steady barrier > budget {mx} (must stay host-cheap)"
        )
    if bp_frag is None:
        violations.append(
            "freshness: no backpressure_fragment verdict on the last "
            "barrier trace — attribution went dark"
        )
    return violations, report


def _overload_workload():
    """A compact governed workload for the overload gate: skewed-key
    storm source (offset-addressed, checkpointable) -> HashAgg(count,
    sum) -> host MV on a real StreamingRuntime, with the agg wired to
    the cold tier and a lagging commit lane — the same physics the
    tier-1 chaos tests drive, at CI scale. Returns a ``make`` thunk
    satisfying the OverloadChaosRunner workload contract."""
    import numpy as np
    import jax.numpy as jnp

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.ops.agg import AggCall
    from risingwave_tpu.runtime import SourceManager, StreamingRuntime
    from risingwave_tpu.runtime.pipeline import Pipeline
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import (
        CheckpointManager,
        Checkpointable,
        StateDelta,
    )

    cap = 1 << 9

    class _Split:
        split_id = "storm-0"

    class _Storm(Checkpointable):
        table_id = "storm.src"

        def __init__(self, seed, hot=48):
            self.seed = seed
            self.hot = hot
            self.offset = 0
            self._committed = 0
            self.splits = [_Split()]

        def discover(self):
            pass

        def _key(self, i):
            h = (i * 2654435761 + self.seed * 40503) & 0xFFFFFFFF
            if h % 3 == 0:
                return h % self.hot
            return self.hot + (h % (256 + i // 3))

        def poll(self, max_rows_per_split, capacity, only=None):
            n, chunks = int(max_rows_per_split), []
            while n > 0:
                take = min(n, capacity)
                idx = np.arange(
                    self.offset, self.offset + take, dtype=np.int64
                )
                keys = np.asarray(
                    [self._key(int(i)) for i in idx], np.int64
                )
                chunks.append(
                    StreamChunk.from_numpy(
                        {"k": keys, "v": (idx % 97).astype(np.int64)},
                        capacity,
                    )
                )
                self.offset += take
                n -= take
            return chunks

        def checkpoint_delta(self):
            if self.offset == self._committed:
                return []
            self._committed = self.offset
            return [
                StateDelta(
                    "storm.src",
                    {"k": np.zeros(1, np.int64)},
                    {"offset": np.asarray([self.offset], np.int64)},
                    np.zeros(1, bool),
                    ("k",),
                )
            ]

        def restore_state(self, table_id, key_cols, value_cols):
            off = value_cols.get("offset") if value_cols else None
            self.offset = (
                int(off[0]) if off is not None and len(off) else 0
            )
            self._committed = self.offset

    class _Governed:
        K_COMMIT = 8

        def __init__(self, seed):
            self.agg = HashAggExecutor(
                group_keys=("k",),
                calls=(
                    AggCall("count_star", None, "cnt"),
                    AggCall("sum", "v", "s"),
                ),
                schema_dtypes={"k": jnp.int64, "v": jnp.int64},
                capacity=cap,
                out_cap=1 << 11,
                table_id="storm.agg",
            )
            self.mview = MaterializeExecutor(
                pk=("k",), columns=("cnt", "s"), table_id="storm.mv"
            )
            self.runtime = StreamingRuntime(store=None)
            self.runtime.register(
                "storm", Pipeline([self.agg, self.mview])
            )
            self.sources = SourceManager()
            self.src = _Storm(seed)
            self.sources.register("bids", self.src)
            self.fragment_of = {"bids": "storm"}
            self.mgr = CheckpointManager(MemObjectStore())
            self.agg.cold_reader = lambda keys: self.mgr.get_rows(
                "storm.agg", keys
            )
            self._epoch = 0

        def ingest(self, max_rows):
            if max_rows <= 0:
                return 0
            before = self.src.offset
            for ch in self.sources.poll(
                "bids", max_rows_per_split=max_rows, capacity=cap
            ):
                self.runtime.push("storm", ch)
            return self.src.offset - before

        def barrier(self):
            self.runtime.barrier()
            self._epoch += 1
            if self._epoch % self.K_COMMIT == 0:
                self.mgr.commit_epoch(
                    self._epoch << 16,
                    [self.agg, self.mview, self.src],
                )

        def drain(self):
            self._epoch += 1
            self.mgr.commit_epoch(
                self._epoch << 16, [self.agg, self.mview, self.src]
            )

        def mv(self):
            return self.mview.snapshot()

    return _Governed


def run_overload_gate(
    budgets: dict, storm_rows: int = 4_000, burst_rows: int = 1_000
):
    """The overload-protection gate (ROADMAP robustness, PR 17), two
    legs:

    1. CHAOS LEG — the seeded OverloadChaosRunner at CI scale: a
       bursty skewed-key storm against the memory-governed runtime.
       The runner itself enforces zero OOM (ledger <= budget on every
       governed barrier), zero wedge (lag, never loss), and descent
       back to NORMAL; the gate additionally holds the governed MV
       bit-identical to the unthrottled twin, bounds ladder flapping
       (``throttle_flaps_max``) and bounds how many post-storm
       barriers recovery may take (``recover_within_barriers_max``).
    2. STEADY LEG — a calm governed run with generous budget: the
       governor's self-measured host_ms must stay under
       ``governor_overhead_frac_max`` of the steady barrier wall (the
       same <1% class as freshness tracking and the blackbox ring),
       and the ledger must reconcile against an independent
       ``state_nbytes()`` walk within ``ledger_drift_frac_max`` (a
       stale or double-charged ledger is an OOM-by-lies).

    Returns (violations, report)."""
    import time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.sim import OverloadChaosRunner, chaos_seed

    ob = budgets.get("overload", {})
    violations, report = [], {}
    make = _overload_workload()
    seed = chaos_seed(11)

    # -- leg 1: the storm ------------------------------------------------
    runner = OverloadChaosRunner(
        make=lambda: make(seed),
        seed=seed,
        storm_rows=storm_rows,
        burst_rows=burst_rows,
        drain_epochs=40,
        max_epochs=300,
        # how deep the ladder stacks before relief lands is scale-
        # dependent; the gate requires the ladder to BITE (>=2 states,
        # runner-enforced) and to fully recover, not a fixed depth
        require_full_ladder=False,
    )
    try:
        got, want = runner.run()
    except RuntimeError as e:
        # the runner's own contract failed: OOM, wedge, or no recovery
        violations.append(f"overload: {e}")
        return violations, report
    rep = runner.report
    report.update(
        {
            "states_seen": rep.get("states_seen"),
            "storm_epochs": rep.get("epochs"),
            "drain_barriers": rep.get("drain_barriers"),
            "budget_bytes": rep.get("budget"),
            "ledger_high": rep.get("ledger_high"),
            "vetoes": rep.get("vetoes"),
            "spills": rep.get("spills"),
            "parked_polls": rep.get("parked_polls"),
            "flaps": rep.get("flaps"),
        }
    )
    if got != want:
        violations.append(
            "overload: governed MV diverged from the unthrottled twin "
            "— admission control broke exactly-once"
        )
    mx = ob.get("throttle_flaps_max")
    if mx is not None and rep.get("flaps", 0) > mx:
        violations.append(
            f"overload: ladder flapped {rep['flaps']}x > budget {mx} "
            "(thrashing between rungs — hysteresis regressed)"
        )
    mx = ob.get("recover_within_barriers_max")
    if mx is not None and rep.get("drain_barriers", 0) > mx:
        violations.append(
            f"overload: {rep['drain_barriers']} post-storm barriers to "
            f"reach NORMAL > budget {mx} (recovery stalled)"
        )

    # -- leg 2: steady overhead + ledger reconciliation ------------------
    obj = make(seed)
    gov = obj.runtime.memory_governor
    gov.budget_bytes = 1 << 30  # generous: governed but never pressed
    gov.enabled = True
    obj.sources.attach_admission(gov.admission, obj.fragment_of)
    obj.ingest(512)
    obj.barrier()
    obj.barrier()  # warm: compiles + gate attachment out of the window
    gov.host_ms = 0.0
    epochs = 24
    t0 = time.perf_counter()
    for _ in range(epochs):
        obj.ingest(256)
        obj.barrier()
    wall_ms = (time.perf_counter() - t0) * 1e3
    frac = gov.host_ms / wall_ms if wall_ms > 0 else 0.0
    walk = 0
    for ex in obj.runtime.executors():
        fn = getattr(ex, "state_nbytes", None)
        if fn is not None:
            try:
                walk += int(fn())
            except Exception:  # noqa: BLE001
                pass
    drift = (
        abs(gov.ledger_total - walk) / walk if walk > 0 else 0.0
    )
    report.update(
        {
            "steady_wall_ms": round(wall_ms, 2),
            "governor_host_ms": round(gov.host_ms, 4),
            "governor_overhead_frac": round(frac, 5),
            "ledger_bytes": gov.ledger_total,
            "ledger_walk_bytes": walk,
            "ledger_drift_frac": round(drift, 5),
        }
    )
    mx = ob.get("governor_overhead_frac_max")
    if mx is not None and frac > mx:
        violations.append(
            f"overload: governor host overhead {frac:.4f} of the "
            f"steady barrier > budget {mx} (the ledger walk must stay "
            "host-cheap)"
        )
    mx = ob.get("ledger_drift_frac_max")
    if mx is not None and drift > mx:
        violations.append(
            f"overload: ledger {gov.ledger_total}B vs independent "
            f"state_nbytes walk {walk}B — drift {drift:.4f} > budget "
            f"{mx} (a lying ledger un-guards the budget)"
        )
    return violations, report


def run_mesh_gate(budgets: dict):
    """The mesh-observability gate (ROADMAP multi-chip, ISSUE 18), run
    in a SUBPROCESS: the child pins ``JAX_PLATFORMS=cpu`` with
    ``--xla_force_host_platform_device_count=8`` so a REAL 8-virtual-
    device mesh drives the sharded q5/q8 fragments — while this
    parent's other gates never see the forced device count. Contracts
    (child-measured, parent-compared against ``budgets["mesh"]``):

    1. Attribution coverage: per-shard + exchange-phase attribution
       covers >= ``attribution_coverage_min`` of the measured sharded
       barrier wall on q5 AND q8.
    2. Bit-identity: the telemetry-armed q5 run's MV content equals an
       unarmed twin fed identical chunks (observability may never
       touch results).
    3. Overhead: MESHPROF's self-measured host_ms over the steady
       armed window < ``mesh_overhead_frac_max`` of the window wall
       (calibration probes are booked separately and excluded).
    4. Skew teeth: a seeded constant-key workload fires a hot-shard
       verdict naming exactly the shard the router sends the key to.
    5. Zero profiler errors.

    Returns (violations, report)."""
    import subprocess

    mb = budgets.get("mesh", {})
    violations, report = [], {}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--mesh-child",
    ]
    try:
        proc = subprocess.run(
            cmd,
            env=env,
            cwd=ROOT,
            capture_output=True,
            text=True,
            timeout=900,
        )
    except subprocess.TimeoutExpired:
        violations.append("mesh: child timed out (900s)")
        return violations, report
    tail = None
    for line in proc.stdout.splitlines():
        if line.startswith("MESH_CHILD_JSON: "):
            tail = line[len("MESH_CHILD_JSON: "):]
    if tail is None:
        violations.append(
            f"mesh: child produced no report (rc {proc.returncode}); "
            f"stderr tail: {proc.stderr[-500:]!r}"
        )
        return violations, report
    try:
        report = json.loads(tail)
    except json.JSONDecodeError as e:
        violations.append(f"mesh: unparseable child report: {e}")
        return violations, report
    if report.get("fatal"):
        violations.append(f"mesh: child failed: {report['fatal']}")
        return violations, report

    mn = mb.get("attribution_coverage_min")
    if mn is not None:
        for q in ("q5", "q8"):
            cov = report.get(f"{q}_coverage_frac")
            if cov is None:
                violations.append(f"mesh: no {q} coverage measured")
            elif cov < mn:
                violations.append(
                    f"mesh: {q} attribution covers {cov:.1%} of the "
                    f"sharded barrier wall < budget {mn:.0%} (per-"
                    "shard/exchange accounting lost track of the wall)"
                )
    if mb.get("require_bit_identical") and not report.get(
        "bit_identical"
    ):
        violations.append(
            "mesh: telemetry-armed q5 MV diverged from the unarmed "
            "twin — observability touched results"
        )
    mx = mb.get("mesh_overhead_frac_max")
    frac = report.get("overhead_frac")
    if mx is not None and frac is not None and frac > mx:
        violations.append(
            f"mesh: profiler host overhead {frac:.4f} of the steady "
            f"armed barrier > budget {mx} (per-shard accounting must "
            "stay host-cheap)"
        )
    if mb.get("require_skew_verdict"):
        if not report.get("skew_detected"):
            violations.append(
                "mesh: seeded constant-key workload fired NO hot-"
                "shard verdict (skew detection regressed)"
            )
        elif report.get("skew_shard") != report.get("expected_shard"):
            violations.append(
                f"mesh: skew verdict named shard "
                f"{report.get('skew_shard')} but the router sends the "
                f"seeded key to shard {report.get('expected_shard')}"
            )
    mx = mb.get("errors_max")
    if mx is not None and report.get("errors", 0) > mx:
        violations.append(
            f"mesh: {report['errors']} profiler errors > budget {mx}"
        )
    return violations, report


def run_mesh_child() -> int:
    """In-process body of the mesh gate (``--mesh-child``): assumes the
    parent exported the 8-virtual-device CPU env. Prints one
    ``MESH_CHILD_JSON:`` line; exit code 0 unless the workload itself
    crashed (budget comparison happens in the parent)."""
    import time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    report: dict = {}
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        jax.config.update("jax_platforms", "cpu")
        if jax.device_count() < 8:
            raise RuntimeError(
                f"need 8 virtual devices, got {jax.device_count()}"
            )
        from risingwave_tpu.connectors.nexmark import (
            AUCTION_SCHEMA,
            BID_SCHEMA,
            PERSON_SCHEMA,
            NexmarkConfig,
            NexmarkGenerator,
        )
        from risingwave_tpu.parallel.exchange import dest_shard
        from risingwave_tpu.parallel.meshprof import MESHPROF, _key_fn_for
        from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg
        from risingwave_tpu.runtime.fragmenter import sharded_planned_mv
        from risingwave_tpu.sql import Catalog, StreamPlanner

        q5_sql = (
            "CREATE MATERIALIZED VIEW q5 AS "
            "SELECT auction, window_start, count(*) AS num "
            "FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
            "INTERVAL '10' SECOND) GROUP BY auction, window_start"
        )
        q8_sql = (
            "CREATE MATERIALIZED VIEW q8 AS "
            "SELECT p.id, p.name, p.starttime FROM "
            "(SELECT id, name, window_start AS starttime "
            " FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
            " GROUP BY id, name, window_start) AS p "
            "JOIN "
            "(SELECT seller, window_start AS astarttime "
            " FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
            " GROUP BY seller, window_start) AS a "
            "ON p.id = a.seller AND p.starttime = a.astarttime"
        )
        catalog = Catalog(
            {
                "bid": BID_SCHEMA,
                "person": PERSON_SCHEMA,
                "auction": AUCTION_SCHEMA,
            }
        )

        def factory():
            return lambda: StreamPlanner(catalog, capacity=1 << 12)

        gen = NexmarkGenerator(NexmarkConfig())
        bids = []
        while len(bids) < 6:
            c = gen.next_chunks(1_500, 1 << 11)["bid"]
            if c is not None:
                bids.append(c)

        # -- leg 1: unarmed q5 twin (the bit-identity baseline) -------
        unarmed = sharded_planned_mv(factory(), q5_sql, n_shards=8)
        try:
            for c in bids:
                unarmed.pipeline.push(c)
                unarmed.pipeline.barrier()
            want = unarmed.mview.snapshot()
        finally:
            unarmed.pipeline.close()

        # -- leg 2: armed q5 — coverage + overhead + bit-identity -----
        MESHPROF.enable(probes=True)
        q5 = sharded_planned_mv(factory(), q5_sql, n_shards=8)
        MESHPROF.watch(q5.pipeline, name="q5")
        try:
            for c in bids[:2]:  # warm: compiles + probe calibration
                q5.pipeline.push(c)
                q5.pipeline.barrier()
            MESHPROF.host_ms = 0.0
            t0 = time.perf_counter()
            for c in bids[2:]:
                q5.pipeline.push(c)
                q5.pipeline.barrier()
            steady_ms = (time.perf_counter() - t0) * 1e3
            got = q5.mview.snapshot()
        finally:
            q5.pipeline.close()
        doc = MESHPROF.barriers[-1]
        report["q5_coverage_frac"] = doc["coverage_frac"]
        report["q5_wall_ms"] = doc["wall_ms"]
        report["q5_phases_ms"] = doc["phases_ms"]
        report["q5_shard_local_ms"] = doc["shard_local_ms"]
        report["q5_exchange_rows"] = doc["exchange"]["rows"]
        report["bit_identical"] = got == want
        report["steady_wall_ms"] = round(steady_ms, 2)
        report["mesh_host_ms"] = round(MESHPROF.host_ms, 3)
        report["calibration_ms"] = round(MESHPROF.calibration_ms, 2)
        report["overhead_frac"] = round(
            MESHPROF.host_ms / steady_ms if steady_ms > 0 else 0.0, 5
        )

        # -- leg 3: armed q8 (join shape) — coverage ------------------
        MESHPROF.reset_stats()
        MESHPROF.enable(probes=False)
        q8 = sharded_planned_mv(factory(), q8_sql, n_shards=8)
        MESHPROF.watch(q8.pipeline, name="q8")
        gen8 = NexmarkGenerator(NexmarkConfig())
        try:
            for _ in range(4):
                chunks = gen8.next_chunks(2_000, 2048)
                if chunks["person"] is not None:
                    q8.pipeline.push_left(chunks["person"])
                if chunks["auction"] is not None:
                    q8.pipeline.push_right(chunks["auction"])
                q8.pipeline.barrier()
        finally:
            q8.pipeline.close()
        doc8 = MESHPROF.barriers[-1]
        report["q8_coverage_frac"] = doc8["coverage_frac"]
        report["q8_wall_ms"] = doc8["wall_ms"]

        # -- leg 4: seeded skew — constant grouping key ---------------
        MESHPROF.reset_stats()
        hot_sql = (
            "CREATE MATERIALIZED VIEW hot AS "
            "SELECT auction, count(*) AS n FROM bid GROUP BY auction"
        )
        hot = sharded_planned_mv(factory(), hot_sql, n_shards=8)
        MESHPROF.watch(hot.pipeline, name="hot")
        agg = next(
            ex
            for ex in hot.pipeline.executors
            if isinstance(ex, ShardedHashAgg)
        )
        skew_key = 1007
        try:
            for c in bids[:3]:
                auc = np.asarray(c.col("auction"))
                c = c.with_columns(
                    auction=jnp.asarray(
                        np.full(auc.shape, skew_key, auc.dtype)
                    )
                )
                if "expected_shard" not in report:
                    kf = _key_fn_for(agg, "agg", None)
                    dest = np.asarray(dest_shard(kf(c), 8))
                    live = np.asarray(c.valid)
                    report["expected_shard"] = int(dest[live][0])
                hot.pipeline.push(c)
                hot.pipeline.barrier()
        finally:
            hot.pipeline.close()
        sk = MESHPROF.barriers[-1]["skew"]
        report["skew_detected"] = sk is not None
        report["skew_shard"] = sk["shard"] if sk else None
        report["skew_ratio"] = sk["ratio"] if sk else None
        report["errors"] = MESHPROF.errors
        MESHPROF.disable()
    except Exception as e:  # noqa: BLE001 — parent turns this into a violation
        report["fatal"] = repr(e)
    print(f"MESH_CHILD_JSON: {json.dumps(report)}")
    return 0


def _engine_generation() -> int:
    """Load provenance.py BY PATH: the pure-JSON gate mode must stay
    jax-free, and importing the package would pull jax in via
    __init__."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_rw_provenance",
        os.path.join(ROOT, "risingwave_tpu", "provenance.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.ENGINE_GENERATION


def generation_warnings(artifact: dict, label: str):
    """Provenance check: ratcheting against an artifact written by an
    OLDER engine generation is exactly the stale-artifact confusion
    that cost a re-anchor — warn loudly (not a violation: old
    artifacts stay comparable for the fields they carry)."""
    ENGINE_GENERATION = _engine_generation()
    # bench artifacts stamp at top level; fusion reports under the
    # "_"-prefixed key the ratchet loop skips
    prov = artifact.get("_provenance") or artifact
    gen = prov.get("engine_generation")
    if gen is None:
        return [
            f"{label}: no engine_generation stamp (predates PR 11 "
            "provenance) — treat its numbers as a DIFFERENT engine's"
        ]
    if int(gen) < ENGINE_GENERATION:
        return [
            f"{label}: written by engine generation {gen} < current "
            f"{ENGINE_GENERATION} (sha {prov.get('git_sha', '?')[:12]}"
            f", tag {prov.get('pr_tag', '?')}) — numbers may not "
            "be comparable"
        ]
    return []


# ---------------------------------------------------------------------------
# mode 2: steady-state smoke microbench (CPU, in-process)
# ---------------------------------------------------------------------------


def _smoke_leg(budgets: dict, fused: bool, epochs: int, events: int):
    """One q5 steady-state microbench leg (interpreted or fused) with
    the profiler armed. Returns (violations, report)."""
    from risingwave_tpu.metrics import REGISTRY
    from risingwave_tpu.profiler import PROFILER

    sb = budgets.get("smoke", {})
    leg = "fused" if fused else "smoke"
    _q5, wrappers, epoch, rows = _q5_steady_setup(events, fused)
    epoch()
    epoch()  # warm: compiles + first-flush paths
    PROFILER.reset()
    PROFILER.enable(fence=False)  # count + host-attribute, no fencing
    try:
        per_epoch = []
        for _ in range(epochs):
            base = PROFILER.total_dispatches()
            epoch()
            per_epoch.append(PROFILER.total_dispatches() - base)
        h = REGISTRY.histograms.get("executor_ms")
        host_ms = sum(h._sum.values()) if h is not None else 0.0
        fused_labels = [
            k for k in PROFILER.dispatch_counts() if k.startswith("fused:")
        ]
    finally:
        PROFILER.disable()
        PROFILER.reset()
    dpb = max(per_epoch) if per_epoch else 0.0
    ms_per_row = host_ms / max(rows * epochs, 1)
    report = {
        f"{leg}_dispatches_per_barrier": per_epoch,
        f"{leg}_python_ms_per_row": round(ms_per_row, 5),
        "rows_per_epoch": rows,
    }
    violations = []
    mx = sb.get(
        "fused_dispatches_per_barrier_max"
        if fused
        else "dispatches_per_barrier_max"
    )
    if mx is not None and dpb > mx:
        violations.append(
            f"{leg}: {dpb} device dispatches/barrier > budget {mx}"
        )
    mx = sb.get("python_ms_per_row_max")
    if mx is not None and ms_per_row > mx:
        violations.append(
            f"{leg}: {ms_per_row:.5f} host-python ms/row > budget {mx}"
        )
    if len(set(per_epoch)) > 1:
        violations.append(
            f"{leg}: steady-state dispatch count not stable: {per_epoch} "
            "(shape-unstable epoch — recompile hazard)"
        )
    if fused:
        # a silently de-fused fragment would fall back to interpretation
        # and only get SLOWER — fail CI loudly instead
        report["fused_fragments"] = len(wrappers)
        report["fused_whole_chain"] = bool(wrappers) and all(
            w.covers_whole_chain for w in wrappers
        )
        if not wrappers or not report["fused_whole_chain"]:
            violations.append(
                "fused: the q5 chain did not fuse whole "
                f"({len(wrappers)} wrappers) — fragment silently de-fused"
            )
        elif not fused_labels:
            violations.append(
                "fused: no fused:<fragment> dispatch attribution recorded "
                "— the fused program never ran (de-fused fallback?)"
            )
    return violations, report


def _two_input_leg(budgets: dict, query: str, epochs: int = 3):
    """One fused two-input steady-state leg (q7 or q8): the whole
    side-chains x join x MV barrier must cost at most
    ``two_input_dispatches_per_barrier_max`` device dispatches (the
    de-fusion tripwire: a silently-interpreted q7 costs ~31), with the
    ``fused:`` attribution present and the pipeline actually carrying
    the whole-fusion wrapper."""
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.profiler import PROFILER
    from risingwave_tpu.queries.nexmark_q import build_q7, build_q8
    from risingwave_tpu.runtime.fused_step import fuse_pipeline

    sb = budgets.get("smoke", {})
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    if query == "q7":
        q = build_q7(
            capacity=1 << 13,
            agg_capacity=1 << 11,
            filter_capacity=1 << 11,
            out_cap=1 << 11,
        )

        def epoch(measure):
            bid = None
            while bid is None:
                bid = gen.next_chunks(1000, 1024)["bid"]
            bid = bid.select(["auction", "bidder", "price", "date_time"])
            q.pipeline.push_left(bid)
            q.pipeline.push_right(bid)
            mx = int(bid.to_numpy()["date_time"].max())
            if measure is not None:
                base = PROFILER.total_dispatches()
                q.pipeline.barrier()
                measure.append(PROFILER.total_dispatches() - base)
            else:
                q.pipeline.barrier()
            q.pipeline.watermark("date_time", mx)
    else:
        q = build_q8(capacity=1 << 12, out_cap=1 << 11)

        def epoch(measure):
            ev = gen.next_chunks(2000, 4096)
            p, a = ev["person"], ev["auction"]
            if p is not None:
                q.pipeline.push_left(
                    p.select(["id", "name", "date_time"])
                )
            if a is not None:
                q.pipeline.push_right(a.select(["seller", "date_time"]))
            if measure is not None:
                base = PROFILER.total_dispatches()
                q.pipeline.barrier()
                measure.append(PROFILER.total_dispatches() - base)
            else:
                q.pipeline.barrier()

    wrappers = fuse_pipeline(q.pipeline, label=query)
    violations, report = [], {}
    fused_whole = (
        getattr(q.pipeline, "_fused", None) is not None
        and len(wrappers) == 1
        and wrappers[0].covers_whole_chain
    )
    report[f"{query}_fused_whole_chain"] = fused_whole
    if not fused_whole:
        violations.append(
            f"{query}: two-input pipeline did not fuse whole "
            "(silent de-fusion — see fusion_refusals())"
        )
        return violations, report
    for _ in range(4):
        epoch(None)  # warm: compiles + growth transitions
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        for _ in range(epochs):
            epoch(per)
        fused_labels = [
            k
            for k in PROFILER.dispatch_counts()
            if k.startswith("fused:")
        ]
    finally:
        PROFILER.disable()
        PROFILER.reset()
    report[f"{query}_dispatches_per_barrier"] = per
    mx = sb.get("two_input_dispatches_per_barrier_max")
    if mx is not None and per and max(per) > mx:
        violations.append(
            f"{query}: {max(per)} device dispatches/barrier > budget "
            f"{mx} (two-input de-fusion regression)"
        )
    if not fused_labels:
        violations.append(
            f"{query}: no fused:<fragment> dispatch attribution — the "
            "two-input program never ran"
        )
    return violations, report


def _pipelining_leg(budgets: dict):
    """K-barrier pipelining microbench (q8, K=1 vs K=2): mid-window
    barriers must defer the blocking staged-scalar read, so their
    host barrier-call latency sits WELL below the K=1 per-barrier
    latency (``k_midwindow_barrier_p50_frac_max``); the full host
    ms/row of both modes is reported for the PROFILE ledger."""
    import time

    import numpy as np

    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.queries.nexmark_q import build_q8
    from risingwave_tpu.runtime.fused_step import fuse_pipeline

    sb = budgets.get("smoke", {})

    def run(depth, nb=16, warm=8):
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
        q8 = build_q8(capacity=1 << 12, out_cap=1 << 11)
        (w,) = fuse_pipeline(
            q8.pipeline, label="q8", pipeline_depth=depth
        )
        lat = []
        rows = 0

        def epoch(measure):
            nonlocal rows
            ev = gen.next_chunks(2000, 4096)
            p, a = ev["person"], ev["auction"]
            if p is not None:
                c = p.select(["id", "name", "date_time"])
                q8.pipeline.push_left(c)
                if measure:
                    rows += int(c.to_numpy()["id"].shape[0])
            if a is not None:
                c = a.select(["seller", "date_time"])
                q8.pipeline.push_right(c)
                if measure:
                    rows += int(c.to_numpy()["seller"].shape[0])
            t0 = time.perf_counter()
            q8.pipeline.barrier()
            if measure:
                lat.append((time.perf_counter() - t0) * 1e3)

        for _ in range(warm):
            epoch(False)
        w.finish_barrier(force=True)
        t0 = time.perf_counter()
        for _ in range(nb - warm):
            epoch(True)
        w.finish_barrier(force=True)
        wall_ms = (time.perf_counter() - t0) * 1e3
        return lat, wall_ms / max(rows, 1), w.depth

    lat1, row_ms1, _ = run(1)
    lat2, row_ms2, depth = run(2)
    # non-boundary barriers only: under K=2 every other barrier defers
    midwindow = lat2[0::2]
    p50_k1 = float(np.percentile(lat1, 50))
    p50_mid = float(np.percentile(midwindow, 50))
    report = {
        "pipelining_depth": depth,
        "k1_barrier_p50_ms": round(p50_k1, 3),
        "k2_midwindow_barrier_p50_ms": round(p50_mid, 3),
        "k1_host_ms_per_row": round(row_ms1, 6),
        "k2_host_ms_per_row": round(row_ms2, 6),
    }
    violations = []
    frac = sb.get("k_midwindow_barrier_p50_frac_max")
    if frac is not None and p50_k1 > 0 and p50_mid > p50_k1 * frac:
        violations.append(
            f"pipelining: K=2 mid-window barrier p50 {p50_mid:.2f}ms "
            f"not below {frac} x K=1 p50 {p50_k1:.2f}ms — the deferred "
            "finish stopped deferring"
        )
    return violations, report


def run_smoke(budgets: dict, epochs: int = 4, events: int = 2_000):
    """Steady state with the profiler armed, FOUR legs: the q5
    interpreted per-executor walk (bounded device dispatches per
    barrier + host-python ms per row), the q5 fused per-barrier step
    (tighter budget + de-fusion tripwire), the fused TWO-INPUT legs
    (q7/q8: whole side-chains x join x MV barriers at <=
    ``two_input_dispatches_per_barrier_max`` dispatches — q7 costs ~31
    interpreted), and the K-barrier pipelining microbench (mid-window
    barriers must actually defer the blocking read). Returns
    (violations, report dict)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:  # runnable as a script from anywhere
        sys.path.insert(0, ROOT)
    import jax

    jax.config.update("jax_platforms", "cpu")
    violations, report = _smoke_leg(budgets, False, epochs, events)
    v2, r2 = _smoke_leg(budgets, True, epochs, events)
    violations += v2
    report.update(r2)
    for q in ("q7", "q8"):
        v3, r3 = _two_input_leg(budgets, q)
        violations += v3
        report.update(r3)
    v4, r4 = _pipelining_leg(budgets)
    violations += v4
    report.update(r4)
    return violations, report


def run_integrity_gate(budgets: dict, epochs: int = 4):
    """The end-to-end state-integrity gate, four legs:

    1. Dispatch neutrality: the device digest lanes are ALWAYS-ON in
       the fused programs; the steady fused q5 barrier must still cost
       at most ``q5_dispatches_per_barrier_max`` device dispatches,
       and the q7/q8 two-input barriers must hold the smoke tier's
       ``two_input_dispatches_per_barrier_max`` — the digests ride the
       existing staged scalar read or they don't ship.
    2. Host overhead: crc verification + host digests on the commit
       path (``RW_STATE_DIGEST=1``) must stay under
       ``host_overhead_frac_max`` of the steady barrier+commit wall.
    3. Scrub smoke: the committed fixture scrubs all-ok; ONE flipped
       byte at rest must be detected (corrupt + quarantined) by the
       next scrub.
    4. Verified recovery at CI scale: corrupt the NEWEST committed SST
       at rest; a fresh manager must walk back to the newest fully-
       verifying epoch, restore its exact row image, and emit a
       ``state_corruption`` event naming the quarantined artifact.

    Returns (violations, report)."""
    import time

    import numpy as np

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu import integrity
    from risingwave_tpu.event_log import EVENT_LOG
    from risingwave_tpu.profiler import PROFILER
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import (
        CheckpointManager,
        Checkpointable,
        StateDelta,
    )

    ib = budgets.get("integrity", {})
    violations, report = [], {}

    # -- legs 1+2: fused q5 steady window with per-epoch commits ----------
    prev = os.environ.get("RW_STATE_DIGEST")
    os.environ["RW_STATE_DIGEST"] = "1"
    try:
        q5, wrappers, epoch, _rows = _q5_steady_setup(2_000, fused=True)
        store = MemObjectStore()
        mgr = CheckpointManager(store)
        # the fused wrapper replaces pipeline.executors; the MEMBER
        # objects stay the checkpointing system of record
        members = wrappers[0].members if wrappers else q5.pipeline.executors

        def commit(ep):
            mgr.commit_staged(ep << 16, mgr.stage(members))

        epoch()
        commit(1)
        epoch()
        commit(2)  # warm: compiles + first-flush outside the window
        integrity.reset_host_ms()
        PROFILER.reset()
        PROFILER.enable(fence=False)
        per = []
        t0 = time.perf_counter()
        try:
            for i in range(epochs):
                base = PROFILER.total_dispatches()
                epoch()
                per.append(PROFILER.total_dispatches() - base)
                commit(3 + i)
            wall_ms = (time.perf_counter() - t0) * 1e3
        finally:
            PROFILER.disable()
            PROFILER.reset()
        host = integrity.host_ms()
        frac = host / wall_ms if wall_ms > 0 else 0.0
        digs = wrappers[0].last_digests if wrappers else {}
        report.update(
            {
                "q5_dispatches_per_barrier": per,
                "integrity_host_ms": round(host, 3),
                "steady_wall_ms": round(wall_ms, 2),
                "host_overhead_frac": round(frac, 5),
                "fused_digest_lanes": sorted(digs),
            }
        )
        mx = ib.get("q5_dispatches_per_barrier_max")
        if mx is not None and per and max(per) > mx:
            violations.append(
                f"integrity: digest lanes armed, steady fused q5 "
                f"barrier costs {max(per)} dispatches > budget {mx} — "
                "the digest fold added a dispatch"
            )
        mx = ib.get("host_overhead_frac_max")
        if mx is not None and frac > mx:
            violations.append(
                f"integrity: digest+checksum host overhead {frac:.4f} "
                f"of the steady barrier+commit wall > budget {mx}"
            )
        if not ("agg" in digs and "mv" in digs):
            violations.append(
                "integrity: fused q5 decoded no agg/mv digest "
                f"(got {sorted(digs)!r}) — the digest lane is dead"
            )

        # -- leg 3: scrub smoke over the fixture just committed ----------
        bad = [r for r in mgr.scrub() if r["status"] != "ok"]
        if bad:
            violations.append(
                f"integrity: clean fixture scrubbed dirty: {bad!r}"
            )
        sst = [p for p in store.list("hummock/sst/")][0]
        blob = bytearray(store.read(sst))
        blob[len(blob) // 2] ^= 0x10
        store.put(sst, bytes(blob))
        hits = [
            r
            for r in mgr.scrub()
            if r["status"] == "corrupt" and r["artifact"] == sst
        ]
        report["scrub_detected_flip"] = bool(hits)
        if not hits:
            violations.append(
                f"integrity: scrub missed a flipped byte in {sst}"
            )
    finally:
        if prev is None:
            os.environ.pop("RW_STATE_DIGEST", None)
        else:
            os.environ["RW_STATE_DIGEST"] = prev

    # -- leg 4: corrupted-newest-SST verified recovery --------------------
    os.environ["RW_STATE_DIGEST"] = "1"
    try:
        store2 = MemObjectStore()
        m2 = CheckpointManager(store2)
        for ep in (1, 2, 3):
            d = StateDelta(
                "t.gate",
                {"k": np.arange(6, dtype=np.int64)},
                {"v": np.arange(6, dtype=np.int64) * ep},
                np.zeros(6, bool),
                ("k",),
            )
            m2.commit_staged(ep << 16, [d])
        newest = max(store2.list("hummock/sst/"))
        blob = bytearray(store2.read(newest))
        blob[len(blob) // 2] ^= 0x10
        store2.put(newest, bytes(blob))

        class _Sink(Checkpointable):
            table_id = "t.gate"
            image = None

            def restore_state(self, table_id, keys, values):
                self.image = (keys, values)

        sink = _Sink()
        m3 = CheckpointManager(store2)
        m3.recover([sink])
        landed = m3.max_committed_epoch >> 16
        report["recovery_landed_epoch"] = landed
        if landed != 2:
            violations.append(
                f"integrity: recovery landed on epoch {landed}, "
                "expected walk-back to 2 (newest fully-verifying)"
            )
        want = np.arange(6, dtype=np.int64) * 2
        got = (
            np.asarray(sink.image[1]["v"])
            if sink.image is not None
            else None
        )
        if got is None or not np.array_equal(np.sort(got), want):
            violations.append(
                f"integrity: recovered row image wrong: {got!r} "
                f"(want permutation of {want!r})"
            )
        named = [
            e
            for e in EVENT_LOG.events(kind="state_corruption")
            if e.get("artifact") == newest
        ]
        report["corruption_event_named_artifact"] = bool(named)
        if not named:
            violations.append(
                "integrity: no state_corruption event names the "
                f"corrupted artifact {newest}"
            )
    finally:
        if prev is None:
            os.environ.pop("RW_STATE_DIGEST", None)
        else:
            os.environ["RW_STATE_DIGEST"] = prev

    # -- leg 1 (cont.): two-input dispatch neutrality ---------------------
    for q in ("q7", "q8"):
        v, r = _two_input_leg(budgets, q)
        violations += [f"integrity/{x}" for x in v]
        report.update({f"integrity_{k}": val for k, val in r.items()})
    return violations, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bench", default=None, help="BENCH JSON artifact")
    ap.add_argument("--budgets", default=DEFAULT_BUDGETS)
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="run the CPU steady-state microbench gate",
    )
    ap.add_argument(
        "--fusion",
        action="store_true",
        help="re-run the fusion analyzer and fail on fusible-prefix "
        "or host-sync-count regressions vs FUSION_REPORT.json",
    )
    ap.add_argument(
        "--fusion-baseline",
        default=None,
        help="baseline report (default: FUSION_REPORT.json)",
    )
    ap.add_argument(
        "--blackbox",
        action="store_true",
        help="gate the flight recorder: host ms/barrier + fsync-stall "
        "budgets, and the write-ring -> SIGKILL -> reader-CLI smoke",
    )
    ap.add_argument(
        "--roofline",
        action="store_true",
        help="gate the device-observability layer: fused telemetry "
        "host overhead < 1%% of the steady barrier, modeled bytes "
        "present, dispatches/barrier still 1 (plus the artifact "
        "padding/compile budgets, which always run with --bench)",
    )
    ap.add_argument(
        "--serving",
        action="store_true",
        help="gate the shared-arrangement serving tier: CI-scale "
        "registration storm (compile count O(families), flat barrier "
        "p99, ~1x shared device state) + concurrent pgwire readers "
        "(p99 + zero errors + registry overhead < 1%% of the barrier)",
    )
    ap.add_argument(
        "--freshness",
        action="store_true",
        help="gate end-to-end freshness SLOs: runtime-driven fused q5, "
        "p99 barrier-commit->visible under budget, event-time lag "
        "bounded with the watermark frontier threaded, dispatches/"
        "barrier unchanged with tracking armed, and tracking host "
        "overhead < 1%% of the steady barrier",
    )
    ap.add_argument(
        "--overload",
        action="store_true",
        help="gate overload protection: seeded chaos storm against the "
        "memory-governed runtime (zero OOM, zero wedge, MV bit-"
        "identical to the unthrottled twin, bounded flaps + recovery) "
        "plus the steady leg (governor host overhead < 1%% of the "
        "barrier, ledger reconciles against state_nbytes)",
    )
    ap.add_argument(
        "--integrity",
        action="store_true",
        help="gate the state-integrity layer: digest-lane dispatch "
        "neutrality on fused q5/q7/q8, digest+checksum host overhead "
        "< 1%% of the steady barrier+commit wall, scrub flip "
        "detection, and corrupted-newest-SST walk-back recovery with "
        "the state_corruption event naming the quarantined artifact",
    )
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="gate the mesh-observability layer on a real 8-virtual-"
        "device sim: per-shard attribution covers >=90%% of the "
        "sharded barrier wall on q5 and q8, armed-vs-unarmed MVs are "
        "bit-identical, a seeded skewed workload yields the correct "
        "skew_shard verdict, and mesh telemetry host overhead stays "
        "< 1%% of the steady barrier",
    )
    ap.add_argument(
        "--mesh-child",
        action="store_true",
        help=argparse.SUPPRESS,  # internal: 8-device subprocess leg
    )
    ap.add_argument(
        "--fusion-current",
        default=None,
        help="reuse an existing `lint --fusion-report --json` output "
        "as the current analysis instead of re-tracing (CI passes "
        "the stage-3 artifact here)",
    )
    ap.add_argument(
        "--mesh-static",
        action="store_true",
        help="re-run the mesh-readiness analyzer over the sharded "
        "corpus and fail on host-routed-edge growth, per-code E9xx "
        "blocker growth, or lost SPMD-fusibility proofs vs "
        "MESH_REPORT.json",
    )
    ap.add_argument(
        "--mesh-baseline",
        default=None,
        help="baseline report (default: MESH_REPORT.json)",
    )
    ap.add_argument(
        "--mesh-current",
        default=None,
        help="reuse an existing `lint --mesh-report --json` output as "
        "the current analysis instead of re-analyzing (CI passes the "
        "lint-stage artifact here)",
    )
    args = ap.parse_args(argv)
    if args.mesh_child:
        return run_mesh_child()
    try:
        budgets = _load(args.budgets)
    except (OSError, json.JSONDecodeError) as e:
        print(f"[perf_gate] cannot read budgets: {e}", file=sys.stderr)
        return 2
    violations = []
    if args.smoke:
        v, report = run_smoke(budgets)
        print(f"[perf_gate] smoke: {json.dumps(report)}")
        violations += v
    if args.blackbox:
        v, report = run_blackbox_gate(budgets)
        print(f"[perf_gate] blackbox: {json.dumps(report)}")
        violations += v
    if args.roofline:
        v, report = run_roofline_gate(budgets)
        print(f"[perf_gate] roofline: {json.dumps(report)}")
        violations += v
    if args.serving:
        v, report = run_serving_gate(budgets)
        print(f"[perf_gate] serving: {json.dumps(report)}")
        violations += v
    if args.freshness:
        v, report = run_freshness_gate(budgets)
        print(f"[perf_gate] freshness: {json.dumps(report)}")
        violations += v
    if args.overload:
        v, report = run_overload_gate(budgets)
        print(f"[perf_gate] overload: {json.dumps(report)}")
        violations += v
    if args.integrity:
        v, report = run_integrity_gate(budgets)
        print(f"[perf_gate] integrity: {json.dumps(report)}")
        violations += v
    if args.mesh:
        v, report = run_mesh_gate(budgets)
        print(f"[perf_gate] mesh: {json.dumps(report)}")
        violations += v
    if args.mesh_static or args.mesh_current:
        try:
            mbase = _load(args.mesh_baseline or DEFAULT_MESH_BASELINE)
            for w in generation_warnings(mbase, "mesh baseline"):
                print(f"[perf_gate] WARNING: {w}")
        except (OSError, json.JSONDecodeError):
            pass  # run_mesh_static_gate reports unreadable baselines
        v, skipped = run_mesh_static_gate(
            budgets, args.mesh_baseline, args.mesh_current
        )
        for s in skipped:
            print(f"[perf_gate] skip: {s}")
        violations += v
    if args.fusion or args.fusion_current:
        try:
            baseline = _load(args.fusion_baseline or DEFAULT_FUSION_BASELINE)
            for w in generation_warnings(baseline, "fusion baseline"):
                print(f"[perf_gate] WARNING: {w}")
        except (OSError, json.JSONDecodeError):
            pass  # run_fusion_gate reports unreadable baselines itself
        v, skipped = run_fusion_gate(
            budgets, args.fusion_baseline, args.fusion_current
        )
        for s in skipped:
            print(f"[perf_gate] skip: {s}")
        violations += v
    bench_path = args.bench or DEFAULT_BENCH
    # --smoke without an explicit artifact still gates the committed
    # baseline when one exists (CI runs both checks in one call)
    if args.bench or not args.smoke or os.path.exists(bench_path):
        try:
            bench = _load(bench_path)
        except (OSError, json.JSONDecodeError) as e:
            print(f"[perf_gate] cannot read bench: {e}", file=sys.stderr)
            return 2
        for w in generation_warnings(
            bench, os.path.basename(bench_path)
        ):
            print(f"[perf_gate] WARNING: {w}")
        v, skipped = check_bench(bench, budgets)
        for s in skipped:
            print(f"[perf_gate] skip: {s}")
        violations += v
    for v in violations:
        print(f"[perf_gate] REGRESSION: {v}", file=sys.stderr)
    print(f"[perf_gate] {'FAIL' if violations else 'ok'}")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
