"""Round-long TPU tunnel health monitor.

The single-client tunneled TPU (axon relay) can wedge for hours if any
client is SIGKILLed; four rounds have ended with zero driver-captured TPU
artifacts because the tunnel was dead whenever bench ran.  This monitor
probes the tunnel all round on a gentle cadence and leaves a forensic
trail either way:

  - TPU_PROBE_r05.log   — timestamped probe results for the whole round
  - TPU_PROBE_events.jsonl — ``device_state`` transition events (the
                          meta event-log spill; the same ALIVE/SLOW/
                          WEDGED vocabulary the in-process blackbox
                          sentinel uses, so an operator can splice both
                          timelines)
  - .tpu_healthy        — marker file (touched when the last probe passed,
                          removed when it failed) so the builder can react

Probe discipline (see bench.py:_device_alive): the child installs
signal.alarm and exits through normal teardown; the parent only ever
SIGTERMs — never SIGKILL, a murdered client wedges the tunnel for hours.

Usage: python scripts/tpu_probe_monitor.py [--interval 900] [--once]
"""

from __future__ import annotations

import argparse
import datetime
import os
import subprocess
import sys
import time

# this process never touches the device itself (probes are fresh
# subprocesses); pin its own jax to CPU so importing risingwave_tpu
# (for the shared blackbox classification + event log) cannot grab the
# single-client tunnel. The PROBE children must NOT inherit the pin —
# a CPU-pinned probe always "passes" and would green-light bench
# rounds against a dead tunnel — so remember the original value and
# restore it in their env (probe_once).
_ORIG_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_r05.log")
EVENTS = os.path.join(REPO, "TPU_PROBE_events.jsonl")
MARKER = os.path.join(REPO, ".tpu_healthy")
BUSY = os.path.join(REPO, ".bench_running")

# a completed probe slower than this is a congested (SLOW) tunnel —
# same threshold family as the in-process sentinel's slow_ms
SLOW_PROBE_S = 30.0


def probe_once(timeout_s: int = 90) -> tuple[bool, float, str]:
    """Fresh-process device acquisition probe; returns (ok, secs, detail)."""
    code = (
        "import signal, os\n"
        "signal.signal(signal.SIGALRM, lambda *a: os._exit(9))\n"
        f"signal.alarm({timeout_s})\n"
        "import jax\n"
        "d = jax.devices()\n"
        "print(len(d), d[0].platform)\n"
    )
    # the child probes the REAL platform: undo this process's CPU pin
    env = dict(os.environ)
    if _ORIG_JAX_PLATFORMS is None:
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = _ORIG_JAX_PLATFORMS
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=REPO,
        env=env,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s + 15)
        dt = time.monotonic() - t0
        if proc.returncode == 0:
            return True, dt, (out or "").strip()
        return False, dt, f"rc={proc.returncode}"
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM only — never SIGKILL a tunnel client
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            pass
        return False, time.monotonic() - t0, "hang (SIGTERMed)"


def classify(ok: bool, dt: float, timeout_s: int) -> str:
    """Map a probe result onto the sentinel's ALIVE/SLOW/WEDGED states
    (blackbox.classify_latency — ONE vocabulary for both observers)."""
    from risingwave_tpu.blackbox import classify_latency

    return classify_latency(
        dt * 1e3 if ok else None, SLOW_PROBE_S * 1e3, timeout_s * 1e3
    )


_LAST_STATE = ["UNKNOWN"]


def record_transition(state: str, dt: float, detail: str) -> None:
    """Emit a ``device_state`` event into the meta event log on every
    transition (ring + JSONL spill -> TPU_PROBE_events.jsonl; `/events`
    and the dashboard pick these up when the monitor shares a process
    with a served runtime)."""
    prev = _LAST_STATE[0]
    if state == prev:
        return
    _LAST_STATE[0] = state
    try:
        from risingwave_tpu.event_log import EVENT_LOG
        from risingwave_tpu.metrics import REGISTRY

        if EVENT_LOG.spill_path is None:
            EVENT_LOG.set_spill(os.environ.get("RW_EVENT_LOG_PATH", EVENTS))
        EVENT_LOG.record(
            "device_state",
            state=state,
            prev=prev,
            latency_ms=round(dt * 1e3, 1),
            detail=detail,
            source="probe_monitor",
        )
        from risingwave_tpu.blackbox import _STATE_GAUGE

        REGISTRY.gauge("device_state").set(_STATE_GAUGE.get(state, -1.0))
    except Exception:
        pass  # the probe log is the floor; events are best-effort


def dump_stalls(dt: float, detail: str) -> str:
    """Probe found the tunnel dead: leave a forensic JSON artifact
    (the monitor-side half of the stall-dump story — the in-process
    half is risingwave_tpu.epoch_trace.dump_stalls). Captures the probe
    result, the recent probe history, and whatever is known about the
    client that may be wedging the single-client tunnel."""
    import json

    doc = {
        "reason": f"device probe failed after {dt:.1f}s: {detail}",
        "ts": time.time(),
        "marker_present": os.path.exists(MARKER),
        "bench_running": None,
        "probe_log_tail": [],
    }
    if os.path.exists(BUSY):
        try:
            with open(BUSY) as f:
                pid = int(f.read().strip() or "0")
            info = {"pid": pid}
            try:  # is the bench client alive, and what is it running?
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    info["cmdline"] = (
                        f.read().replace(b"\0", b" ").decode().strip()
                    )
                info["alive"] = True
            except OSError:
                info["alive"] = False  # stale marker: client died
            doc["bench_running"] = info
        except (OSError, ValueError):
            pass
    try:
        with open(LOG) as f:
            doc["probe_log_tail"] = f.readlines()[-20:]
    except OSError:
        pass
    # the bench child's own black box (if the wedging client was ours):
    # point the reader at the freshest segment + any wedge bundles
    try:
        doc["blackbox_artifacts"] = sorted(
            p
            for p in os.listdir(REPO)
            if p.startswith("BLACKBOX_") or p.startswith("WEDGE_")
        )[-10:]
    except OSError:
        pass
    path = os.path.join(REPO, f"STALL_DUMP_probe_{int(time.time())}.json")
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        return ""
    return path


def log_line(state: str, dt: float, detail: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    line = f"{stamp} {state} {dt:.1f}s {detail}\n"
    with open(LOG, "a") as f:
        f.write(line)
    if state in ("ALIVE", "SLOW"):
        # the device answers (possibly slowly): bench can run
        with open(MARKER, "w") as f:
            f.write(stamp + "\n")
    elif os.path.exists(MARKER):
        os.remove(MARKER)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=900)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--timeout", type=int, default=90)
    args = ap.parse_args()
    while True:
        if os.path.exists(BUSY):
            # bench (or another legitimate client) holds the single-
            # client tunnel: probing now would both hang AND add a
            # competing client — skip, and don't touch the marker
            stamp = datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"
            )
            with open(LOG, "a") as f:
                f.write(f"{stamp} BUSY skipped (bench running)\n")
            print("probe: BUSY (bench running)", flush=True)
        else:
            ok, dt, detail = probe_once(args.timeout)
            state = classify(ok, dt, args.timeout)
            log_line(state, dt, detail)
            record_transition(state, dt, detail)
            if state == "WEDGED":
                path = dump_stalls(dt, detail)
                if path:
                    print(f"probe: stall dump -> {path}", flush=True)
            print(f"probe: {state} ({dt:.1f}s) {detail}", flush=True)
        if args.once:
            break
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
