#!/usr/bin/env python
"""bench_serving — the multi-tenant serving-tier bench (ROADMAP item 4).

Measures the two halves of the shared-arrangement story end to end and
writes ``BENCH_SERVING.json``:

1. **Registration storm** — N ``CREATE MATERIALIZED VIEW`` statements
   across F structurally-distinct families (identical within a
   family). With sharing ON, each family costs ONE writer fragment +
   one set of device state; every further CREATE attaches in O(1).
   Reported: create-latency p50/p99, fused compile count (must be
   O(shape families), not O(MVs) — constant lifting shares the
   programs across families too), arrangements/refs, barrier p99
   before vs after the storm (flat = the win), total device state and
   bytes-per-MV vs a sharing-disabled private-twin control.

2. **Concurrent serving** — R threaded pgwire readers issue SELECTs
   against subscriber MVs (served lock-free off published per-barrier
   versions) while a writer keeps streaming INSERT + barrier cycles.
   Reported: reader p50/p99, reads/s, barrier p99 under read load vs
   idle, registry publish overhead per barrier.

CPU-safe by default (the artifact is a serving-tier scaling proof, not
a TPU kernel number); run on device hardware via the usual bench
babysitter for HBM-scale numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _pctl(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return float(xs[i])


class _PgReader:
    """Minimal pgwire v3 client for the reader threads (startup +
    simple query), matching the server's subset."""

    def __init__(self, port: int):
        self.sock = socket.create_connection(
            ("127.0.0.1", port), timeout=30
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        body = struct.pack("!I", 196608) + b"user\0bench\0database\0dev\0\0"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self._drain()

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            got = self.sock.recv(n - len(buf))
            if not got:
                raise ConnectionError("server closed")
            buf += got
        return buf

    def _drain(self):
        rows, err = 0, None
        while True:
            head = self._recv(5)
            (length,) = struct.unpack("!I", head[1:])
            body = self._recv(length - 4)
            if head[:1] == b"D":
                rows += 1
            elif head[:1] == b"E":
                err = body
            elif head[:1] == b"Z":
                return rows, err

    def query(self, sql: str):
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        return self._drain()

    def close(self):
        try:
            self.sock.sendall(b"X" + struct.pack("!I", 4))
            self.sock.close()
        except OSError:
            pass


def _mk_session(exec_mode: str, capacity: int):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    return SqlSession(
        Catalog({}),
        capacity=capacity,
        exec_mode=exec_mode,
        parallelism=1,
    )


_FAMILY_THRESHOLDS = (10, 250, 500, 750, 900, 120, 380, 640)


def _family_sql(name: str, family: int) -> str:
    thr = _FAMILY_THRESHOLDS[family % len(_FAMILY_THRESHOLDS)]
    return (
        f"CREATE MATERIALIZED VIEW {name} AS SELECT k, count(*) AS c "
        f"FROM base WHERE v > {thr} GROUP BY k"
    )


def _seed(session, rows: int, seed: int = 7) -> None:
    import numpy as np

    rng = np.random.default_rng(seed)
    ks = rng.integers(0, 64, size=rows)
    vs = rng.integers(0, 1000, size=rows)
    vals = ", ".join(f"({k}, {v})" for k, v in zip(ks, vs))
    session.execute(f"INSERT INTO base VALUES {vals}")


def _barrier_p99(session, n: int = 12):
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        with session.runtime.lock:
            session.runtime.barrier()
        lat.append((time.perf_counter() - t0) * 1e3)
    return _pctl(lat, 0.5), _pctl(lat, 0.99)


def run_serving(
    mvs: int = 1000,
    families: int = 4,
    readers: int = 8,
    read_seconds: float = 4.0,
    exec_mode: str = "graph",
    capacity: int = 1 << 10,
    seed_rows: int = 512,
    private_twins: int = 8,
    use_pgwire: bool = True,
    verbose: bool = True,
) -> dict:
    from risingwave_tpu.runtime.fused_step import fused_cache_stats

    say = print if verbose else (lambda *a, **k: None)
    out: dict = {
        "mvs": mvs,
        "families": families,
        "readers": readers,
        "exec_mode": exec_mode,
    }

    # -- private-twin control (sharing OFF) ------------------------------
    prev = os.environ.get("RW_SHARED_ARRANGEMENTS")
    os.environ["RW_SHARED_ARRANGEMENTS"] = "0"
    try:
        ctl = _mk_session(exec_mode, capacity)
        ctl.execute("CREATE TABLE base (k BIGINT, v BIGINT)")
        _seed(ctl, seed_rows)
        base_bytes = ctl.runtime.state_nbytes()
        for i in range(private_twins):
            ctl.execute(_family_sql(f"priv{i}", 0))
        private_per_mv = (
            ctl.runtime.state_nbytes() - base_bytes
        ) / max(1, private_twins)
        for p in ctl.runtime.fragments.values():
            close = getattr(p, "close", None)
            if close is not None:
                close()
    finally:
        if prev is None:
            os.environ.pop("RW_SHARED_ARRANGEMENTS", None)
        else:
            os.environ["RW_SHARED_ARRANGEMENTS"] = prev
    out["bytes_per_mv_private"] = round(private_per_mv, 1)
    say(f"[serving] private twin: {private_per_mv / 1e3:.1f} KB/MV")

    # -- shared storm ----------------------------------------------------
    session = _mk_session(exec_mode, capacity)
    session.execute("CREATE TABLE base (k BIGINT, v BIGINT)")
    _seed(session, seed_rows)
    base_bytes = session.runtime.state_nbytes()
    cache0 = fused_cache_stats()["compiled_programs"]

    # warm phase: one MV per family (the writers + their compiles)
    create_ms = []
    t_storm = time.perf_counter()
    for i in range(families):
        t0 = time.perf_counter()
        session.execute(_family_sql(f"mv{i}", i))
        create_ms.append((time.perf_counter() - t0) * 1e3)
    session.execute("INSERT INTO base VALUES (1, 999), (2, 1)")
    pre_p50, pre_p99 = _barrier_p99(session)

    for i in range(families, mvs):
        t0 = time.perf_counter()
        session.execute(_family_sql(f"mv{i}", i % families))
        create_ms.append((time.perf_counter() - t0) * 1e3)
    storm_wall = time.perf_counter() - t_storm
    post_p50, post_p99 = _barrier_p99(session)

    stats = session.runtime.arrangements.stats()
    cache = fused_cache_stats()
    shared_bytes = session.runtime.state_nbytes() - base_bytes
    out.update(
        {
            "storm_wall_s": round(storm_wall, 3),
            "creates_per_s": round(mvs / storm_wall, 1),
            "create_p50_ms": round(_pctl(create_ms, 0.5), 3),
            "create_p99_ms": round(_pctl(create_ms, 0.99), 3),
            "arrangements": stats["arrangements"],
            "arrangement_refs": stats["refs"],
            # -1 = the jit cache size is unreadable (a jax-internal
            # surface): propagate the sentinel rather than a bogus
            # delta, so the gate can refuse instead of passing vacuously
            "compile_programs": (
                cache["compiled_programs"] - cache0
                if cache["compiled_programs"] >= 0 and cache0 >= 0
                else -1
            ),
            "plans_lifted": cache["plans_lifted"],
            "plans_lift_rejected": cache["plans_lift_rejected"],
            "barrier_p50_ms_pre_storm": round(pre_p50, 3),
            "barrier_p99_ms_pre_storm": round(pre_p99, 3),
            "barrier_p50_ms_post_storm": round(post_p50, 3),
            "barrier_p99_ms_post_storm": round(post_p99, 3),
            "state_bytes_shared_total": int(shared_bytes),
            "bytes_per_mv_shared": round(shared_bytes / mvs, 1),
            "bytes_per_mv_ratio": round(
                (shared_bytes / mvs) / max(private_per_mv, 1.0), 4
            ),
        }
    )
    say(
        f"[serving] storm: {mvs} MVs in {storm_wall:.2f}s, "
        f"{stats['arrangements']} arrangement(s), "
        f"{out['compile_programs']} compiled program(s), barrier p99 "
        f"{pre_p99:.1f} -> {post_p99:.1f} ms"
    )

    # -- registry publish overhead (the no-reader barrier cost) ----------
    reg = session.runtime.arrangements
    epoch = session.runtime.epoch
    t0 = time.perf_counter()
    rounds = 500
    with session.runtime.lock:
        for _ in range(rounds):
            reg.publish(epoch)
    publish_us = (time.perf_counter() - t0) / rounds * 1e6
    out["publish_us_per_barrier"] = round(publish_us, 2)
    out["registry_overhead_frac"] = round(
        publish_us / 1e3 / max(post_p99, 1e-9), 6
    )

    # -- concurrent serving ----------------------------------------------
    sub_names = [f"mv{i}" for i in range(families, min(mvs, families + 64))]
    lat_ms: list = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    errors: list = []
    reads = [0]

    pg = None
    port = None
    if use_pgwire:
        from risingwave_tpu.frontend.pgwire import PgServer

        pg = PgServer(session, port=0).start()
        port = pg.port

    def reader(idx: int):
        cli = _PgReader(port) if use_pgwire else None
        my = []
        n = 0
        try:
            while not stop.is_set():
                name = sub_names[(idx + n) % len(sub_names)]
                sql = f"SELECT k, c FROM {name} ORDER BY k"
                t0 = time.perf_counter()
                if cli is not None:
                    _rows, err = cli.query(sql)
                    if err:
                        errors.append(err.decode(errors="replace"))
                else:
                    session.execute(sql)
                my.append((time.perf_counter() - t0) * 1e3)
                n += 1
        except Exception as e:  # noqa: BLE001 — surfaced in the artifact
            errors.append(repr(e))
        finally:
            if cli is not None:
                cli.close()
        with lat_lock:
            lat_ms.extend(my)
            reads[0] += n

    # warmup: compile the serve-loop shapes OUTSIDE the timed window
    # (the 1-row insert chunk program, the facade read path, and the
    # eager publish's snapshot gather) — first-use compiles are a
    # compile-cache property, not a serving-tier latency
    session.execute("INSERT INTO base VALUES (0, 0)")
    for name in sub_names[:2]:
        session.execute(f"SELECT k, c FROM {name} ORDER BY k")
    session.execute("INSERT INTO base VALUES (0, 1)")
    session.execute(f"SELECT k, c FROM {sub_names[0]} ORDER BY k")
    session.execute("INSERT INTO base VALUES (0, 2)")

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(readers)
    ]
    n_barriers_before = len(session.runtime.barrier_latencies_ms)
    for t in threads:
        t.start()
    t_serve = time.perf_counter()
    deadline = t_serve + read_seconds
    wrote = 0
    while time.perf_counter() < deadline:
        session.execute(
            f"INSERT INTO base VALUES ({wrote % 64}, {wrote % 1000})"
        )
        wrote += 1
    stop.set()
    for t in threads:
        t.join(timeout=30)
    serve_wall = time.perf_counter() - t_serve
    if pg is not None:
        pg.shutdown()
    under_load = session.runtime.barrier_latencies_ms[n_barriers_before:]
    out.update(
        {
            "serve_wall_s": round(serve_wall, 3),
            "reads_total": reads[0],
            "reads_per_s": round(reads[0] / max(serve_wall, 1e-9), 1),
            "reader_p50_ms": round(_pctl(lat_ms, 0.5), 3),
            "reader_p99_ms": round(_pctl(lat_ms, 0.99), 3),
            "writes_during_serve": wrote,
            "barrier_p99_ms_under_read_load": round(
                _pctl(under_load, 0.99), 3
            ),
            "reader_errors": errors[:5],
            "reader_error_count": len(errors),
        }
    )
    say(
        f"[serving] {readers} readers: {out['reads_per_s']}/s, p50 "
        f"{out['reader_p50_ms']}ms p99 {out['reader_p99_ms']}ms; "
        f"barrier p99 under load {out['barrier_p99_ms_under_read_load']}"
        f"ms; {len(errors)} error(s)"
    )
    for p in session.runtime.fragments.values():
        close = getattr(p, "close", None)
        if close is not None:
            close()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mvs", type=int, default=1000)
    ap.add_argument("--families", type=int, default=4)
    ap.add_argument("--readers", type=int, default=8)
    ap.add_argument("--read-seconds", type=float, default=4.0)
    ap.add_argument("--exec-mode", default="graph")
    ap.add_argument("--out", default=os.path.join(ROOT, "BENCH_SERVING.json"))
    ap.add_argument("--device", choices=["auto", "cpu"], default="cpu")
    args = ap.parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.provenance import stamp

    out = run_serving(
        mvs=args.mvs,
        families=args.families,
        readers=args.readers,
        read_seconds=args.read_seconds,
        exec_mode=args.exec_mode,
    )
    out.update(stamp())
    out["device"] = args.device
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(f"[serving] artifact -> {args.out}")
    return 1 if out.get("reader_error_count") else 0


if __name__ == "__main__":
    sys.exit(main())
