#!/usr/bin/env python
"""Profile the hot kernels at bench shape on the real device.

Usage: python scripts/profile_kernels.py [--n 40960] [--cap 262144]
Each section warms up (compile) then times K repetitions with
block_until_ready. Prints one line per kernel.
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import risingwave_tpu  # noqa: F401  (enables x64)
from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert
from risingwave_tpu.ops.hashing import hash128
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.ops.agg import AggCall


def timeit(name, fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:40s} {dt*1e3:10.3f} ms")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=40960)
    ap.add_argument("--cap", type=int, default=1 << 18)
    args = ap.parse_args()
    n, cap = args.n, args.cap
    print(f"device={jax.devices()[0]} n={n} cap={cap}")

    rng = np.random.default_rng(0)
    auction = jnp.asarray(rng.integers(1000, 2000, n, dtype=np.int64))
    wstart = jnp.asarray(
        (rng.integers(0, 50, n, dtype=np.int64)) * 2000 + 1_600_000_000_000
    )
    valid = jnp.ones(n, jnp.bool_)
    keys = (auction, wstart)

    timeit("hash128(int64 x2)", jax.jit(lambda k: hash128(k)), keys)

    # single gather / scatter at shape
    big = jnp.zeros(cap, jnp.int64)
    idx = jnp.asarray(rng.integers(0, cap, n, dtype=np.int32))
    timeit("gather int64 [n from cap]", jax.jit(lambda b, i: b[i]), big, idx)
    big32 = jnp.zeros(cap, jnp.int32)
    timeit("gather int32 [n from cap]", jax.jit(lambda b, i: b[i]), big32, idx)
    vals = jnp.ones(n, jnp.int64)
    timeit(
        "scatter int64 [n into cap]",
        jax.jit(lambda b, i, v: b.at[i].set(v, mode="drop")),
        big, idx, vals,
    )

    # one full lookup_or_insert
    def mk_table():
        return HashTable.create(cap, (auction.dtype, wstart.dtype))

    t = mk_table()
    t, slots, _, _ = lookup_or_insert(t, keys, valid)
    jax.block_until_ready(t.fp1)
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        t2 = mk_table()
        t2, slots, _, _ = lookup_or_insert(t2, keys, valid)
    jax.block_until_ready(t2.fp1)
    print(f"{'lookup_or_insert (fresh table)':40s} {(time.perf_counter()-t0)/reps*1e3:10.3f} ms")

    # agg apply at shape
    calls = (AggCall(kind="count_star", input=None, output="cnt"),)
    dtypes = {"auction": jnp.int64, "window_start": jnp.int64}
    state = agg_ops.create_state(cap, calls, dtypes)
    signs = jnp.ones(n, jnp.int64)
    slots_c = jnp.asarray(rng.integers(0, cap, n, dtype=np.int32))
    f = jax.jit(lambda s, sl, sg: agg_ops.apply(s, calls, sl, sg, {}, {}))
    timeit("agg_ops.apply count", f, state, slots_c, signs)


if __name__ == "__main__":
    main()
