#!/usr/bin/env python
"""Consolidate the committed BENCH artifacts into ONE provenance-aware
performance trajectory.

The repo accumulates heterogeneous bench evidence: ``BENCH_r0*.json``
(device-run retry wrappers: ``{n, cmd, rc, tail, parsed}`` where
``parsed`` is the bench's own JSON — or null when the run crashed),
``BENCH_TPU_*.json`` (flat bench dicts from TPU sessions),
``BENCH_partial.json`` / ``BENCH.json`` (CPU smoke baselines),
``BENCH_SERVING.json`` (the PR 12 serving storm) and
``MULTICHIP_r0*.json`` / ``MULTICHIP.json`` (the sharded dryrun:
pre-PR 18 rounds are stdout-tail wrappers ``{n_devices, rc, ok,
skipped, tail}`` with no provenance or numbers, the current
``bench.py --multichip`` form carries per-query mesh blocks —
attribution coverage, exchange matrix, skew verdicts). Reading the
trajectory by hand means re-discovering every wrapper shape and —
worse — comparing numbers produced by DIFFERENT engine generations as
if they were one series (the stale-artifact confusion that forced a
ROADMAP re-anchor).

This tool flattens all of them into one table, one row per artifact:

- headline metric (value, unit, vs_baseline) + per-query
  ``{q}_vs_baseline`` / ``{q}_p99_barrier_ms`` where stamped;
- freshness evidence where stamped (``{q}_freshness`` commit->visible
  p99, PR 16);
- the artifact's ``engine_generation`` (from ``_provenance`` or the
  top level), with a LOUD warning column when it predates the current
  generation — those numbers are a different engine's.

Usage::

    python scripts/perf_trend.py            # table on stdout
    python scripts/perf_trend.py --json     # machine-readable rows
    python scripts/perf_trend.py A.json B.json   # explicit artifacts

Exit code is 0 even with warnings: this is a ledger, not a gate
(perf_gate owns pass/fail).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

QUERIES = ("q5", "q5u", "q7", "q8")


def _engine_generation() -> int:
    """Load provenance.py BY PATH (jax-free, same trick as perf_gate):
    the trend tool must run on artifact JSON alone."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_rw_provenance",
        os.path.join(ROOT, "risingwave_tpu", "provenance.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.ENGINE_GENERATION


def default_artifacts() -> list:
    """The committed trajectory, oldest-ish first: numbered retry
    wrappers, then numbered TPU sessions, then the CPU baselines."""

    def _numbered(pattern):
        def key(p):
            m = re.search(r"(\d+)", os.path.basename(p))
            return int(m.group(1)) if m else 0

        return sorted(glob.glob(os.path.join(ROOT, pattern)), key=key)

    paths = _numbered("BENCH_r[0-9]*.json")
    paths += _numbered("BENCH_TPU_*.json")
    paths += _numbered("MULTICHIP_r[0-9]*.json")
    for name in (
        "BENCH_partial.json",
        "BENCH.json",
        "BENCH_SERVING.json",
        "MULTICHIP.json",
    ):
        p = os.path.join(ROOT, name)
        if os.path.exists(p):
            paths.append(p)
    return paths


def load_artifact(path: str):
    """Read one artifact; unwrap retry wrappers. Returns
    ``(bench_dict_or_None, note)`` — a null/crashed wrapper yields
    (None, reason) instead of raising, so one bad file never hides the
    rest of the trajectory."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return None, f"unreadable ({e})"
    if not isinstance(doc, dict):
        return None, f"unexpected shape ({type(doc).__name__})"
    if set(doc) >= {"n", "cmd", "rc", "parsed"}:
        # retry wrapper: the bench's own JSON lives under "parsed"
        parsed = doc.get("parsed")
        note = f"retry wrapper n={doc.get('n')} rc={doc.get('rc')}"
        if not isinstance(parsed, dict):
            tail = (doc.get("tail") or "").strip().splitlines()
            last = tail[-1][:100] if tail else ""
            return None, f"{note}: no parsed bench output ({last!r})"
        return parsed, note
    if "n_devices" in doc and "tail" in doc and "queries" not in doc:
        # pre-PR 18 multichip stdout-tail wrapper: pass/fail only (the
        # structured form — bench.py --multichip — carries mesh blocks
        # and falls through as a plain dict)
        note = (
            f"multichip tail wrapper n_devices={doc.get('n_devices')} "
            f"rc={doc.get('rc')}"
        )
        if doc.get("skipped"):
            return None, f"{note}: dryrun skipped (no device window)"
        if not doc.get("ok"):
            tail = (doc.get("tail") or "").strip().splitlines()
            last = tail[-1][:100] if tail else ""
            return None, f"{note}: dryrun failed ({last!r})"
        return dict(doc, multichip=True), note
    return doc, ""


def _fresh_p99(bench: dict, q: str):
    blk = bench.get(f"{q}_freshness")
    if not isinstance(blk, dict):
        return None
    c2v = blk.get("commit_to_visible_ms") or {}
    return c2v.get("p99") if c2v.get("n") else None


def summarize(path: str, current_gen: int) -> dict:
    """One trajectory row for one artifact."""
    bench, note = load_artifact(path)
    row = {
        "artifact": os.path.basename(path),
        "note": note,
        "ok": bench is not None,
    }
    if bench is None:
        return row
    prov = bench.get("_provenance") or bench
    gen = prov.get("engine_generation")
    row["engine_generation"] = gen
    if gen is None:
        row["warning"] = "no engine_generation (predates PR 11)"
    elif int(gen) < current_gen:
        row["warning"] = (
            f"engine generation {gen} < current {current_gen} "
            f"(sha {str(prov.get('git_sha', '?'))[:12]}) — numbers "
            "may not be comparable"
        )
    if "metric" in bench:
        row["metric"] = bench.get("metric")
        row["value"] = bench.get("value")
        row["unit"] = bench.get("unit")
        row["vs_baseline"] = bench.get("vs_baseline")
        row["tier"] = bench.get("tier")
        if "p99_barrier_ms" in bench:
            row["p99_barrier_ms"] = bench.get("p99_barrier_ms")
    # multichip dryrun artifacts: MV-parity pass/fail + (structured
    # form only) per-query mesh evidence — attribution coverage and
    # the skew verdict shard
    if bench.get("multichip") or (
        "n_devices" in bench and isinstance(bench.get("queries"), dict)
    ):
        row["metric"] = "multichip_dryrun"
        row["value"] = bench.get("n_devices")
        row["unit"] = "devices"
        mq = {}
        for q, ent in (bench.get("queries") or {}).items():
            if not isinstance(ent, dict):
                continue
            sub = {"match": ent.get("match")}
            mesh = ent.get("mesh")
            if isinstance(mesh, dict):
                sub["mesh_coverage"] = mesh.get("coverage_frac")
                sk = mesh.get("skew")
                if isinstance(sk, dict):
                    sub["skew_shard"] = sk.get("shard")
            mq[q] = sub
        if mq:
            row["queries"] = mq
        return row
    # serving-storm artifacts carry their own vocabulary
    if "reads_per_s" in bench and "compile_programs" in bench:
        row["metric"] = row.get("metric") or "serving_storm"
        row["serving"] = {
            k: bench.get(k)
            for k in (
                "compile_programs",
                "reader_p99_ms",
                "reads_per_s",
                "bytes_per_mv_ratio",
            )
        }
    queries = {}
    for q in QUERIES:
        ent = {}
        for key, out in (
            (f"{q}_throughput", "throughput"),
            (f"{q}_vs_baseline", "vs_baseline"),
            (f"{q}_p99_barrier_ms", "p99_barrier_ms"),
        ):
            if key in bench:
                ent[out] = bench[key]
        fp = _fresh_p99(bench, q)
        if fp is not None:
            ent["freshness_p99_ms"] = fp
        if ent:
            queries[q] = ent
    if queries:
        row["queries"] = queries
    if bench.get("errors"):
        row["errors"] = bench["errors"]
    return row


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render(rows: list, current_gen: int) -> str:
    out = [
        f"perf trajectory ({len(rows)} artifacts, current engine "
        f"generation {current_gen})",
        "",
    ]
    hdr = (
        f"{'artifact':<22} {'gen':>4} {'metric':<28} {'value':>10} "
        f"{'vs_base':>8} {'p99 ms':>8}  queries"
    )
    out.append(hdr)
    out.append("-" * len(hdr))
    warnings = []
    for r in rows:
        if not r["ok"]:
            out.append(f"{r['artifact']:<22}  -- {r['note']}")
            continue
        qbits = []
        for q, ent in (r.get("queries") or {}).items():
            bits = []
            if "vs_baseline" in ent:
                bits.append(f"x{_fmt(ent['vs_baseline'])}")
            if "p99_barrier_ms" in ent:
                bits.append(f"p99={_fmt(ent['p99_barrier_ms'])}ms")
            if "freshness_p99_ms" in ent:
                bits.append(f"fresh={_fmt(ent['freshness_p99_ms'])}ms")
            if "mesh_coverage" in ent and ent["mesh_coverage"] is not None:
                bits.append(f"cov={_fmt(ent['mesh_coverage'])}")
            if ent.get("skew_shard") is not None:
                bits.append(f"skew@{ent['skew_shard']}")
            if ent.get("match") and not bits:
                bits.append("match")
            if bits:
                qbits.append(f"{q}({','.join(bits)})")
        if "serving" in r:
            s = r["serving"]
            qbits.append(
                f"serving(programs={_fmt(s.get('compile_programs'))},"
                f"reader_p99={_fmt(s.get('reader_p99_ms'))}ms)"
            )
        out.append(
            f"{r['artifact']:<22} {_fmt(r.get('engine_generation')):>4} "
            f"{_fmt(r.get('metric'))[:28]:<28} {_fmt(r.get('value')):>10} "
            f"{_fmt(r.get('vs_baseline')):>8} "
            f"{_fmt(r.get('p99_barrier_ms')):>8}  {' '.join(qbits)}"
        )
        if r.get("warning"):
            warnings.append(f"{r['artifact']}: {r['warning']}")
    if warnings:
        out.append("")
        out.append("provenance warnings (treat these rows as a DIFFERENT")
        out.append("engine's numbers — do not ratchet against them):")
        for w in warnings:
            out.append(f"  ! {w}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="explicit artifacts")
    ap.add_argument(
        "--json", action="store_true", help="emit rows as JSON"
    )
    args = ap.parse_args(argv)
    paths = args.paths or default_artifacts()
    current_gen = _engine_generation()
    rows = [summarize(p, current_gen) for p in paths]
    if args.json:
        print(
            json.dumps(
                {"engine_generation": current_gen, "rows": rows}, indent=2
            )
        )
    else:
        print(render(rows, current_gen))
    return 0


if __name__ == "__main__":
    sys.exit(main())
