"""Fire bench.py the moment the TPU tunnel probe reports healthy.

The tunnel wedges for hours and revives unpredictably (r05 log: two OK
probes at 01:03/01:18 between dead stretches); a human-paced check
misses those windows. This watcher polls the probe monitor's
``.tpu_healthy`` marker every 45s and launches ``python bench.py``
(which banks every success to BENCH_partial.json immediately and
maintains ``.bench_running`` so the prober stands down) as soon as the
marker appears. Results are left on disk for the builder to commit;
BENCH_WATCH.log records every attempt either way.

Usage: python scripts/bench_on_healthy.py  (backgrounded, SIGTERM-safe)
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = os.path.join(REPO, ".tpu_healthy")
BUSY = os.path.join(REPO, ".bench_running")
LOG = os.path.join(REPO, "BENCH_WATCH.log")
COOLDOWN_S = 1800  # after a bench attempt, let the prober re-establish


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(msg, flush=True)


def main() -> None:
    log("watcher up")
    while True:
        if os.path.exists(MARKER) and not os.path.exists(BUSY):
            log("tunnel healthy -> launching bench.py")
            t0 = time.monotonic()
            try:
                rc = subprocess.call(
                    [sys.executable, "bench.py"], cwd=REPO, timeout=5400
                )
            except subprocess.TimeoutExpired:
                # bench.py budgets itself; this is a backstop. SIGTERM
                # only (a SIGKILLed tunnel client wedges the relay).
                log("bench.py exceeded 90min backstop (SIGTERMed)")
                rc = -15
            log(
                f"bench.py exited rc={rc} after "
                f"{time.monotonic() - t0:.0f}s — check BENCH_partial.json"
            )
            time.sleep(COOLDOWN_S)
        time.sleep(45)


if __name__ == "__main__":
    main()
