"""Fire bench.py the moment the TPU tunnel probe reports healthy.

The tunnel wedges for hours and revives unpredictably (r05 log: two OK
probes at 01:03/01:18 between dead stretches); a human-paced check
misses those windows. This watcher polls the probe monitor's
``.tpu_healthy`` marker every 45s and launches ``python bench.py``
(which banks every success to BENCH_partial.json + per-query
BENCH_<q>.json immediately and maintains ``.bench_running`` so the
prober stands down) as soon as the marker appears.

While a bench runs, the watcher TAILS the child's wedge-sentinel
heartbeats (the SENTINEL_STATE.json status file bench children rewrite
every beat) into BENCH_WATCH.log — so the round log shows the device's
ALIVE/SLOW/WEDGED trajectory even when the child is later killed and
its stdout lost. Results are left on disk for the builder to commit;
BENCH_WATCH.log records every attempt either way.

Round resume: a bench attempt that dies mid-round (tunnel loss — r04
and r05 lost ALL artifacts this way) no longer abandons the round. The
watcher keeps a ``.bench_round.json`` marker (round start time +
attempt count); the next healthy window relaunches bench.py with
``RW_BENCH_RESUME=1`` + ``RW_BENCH_ROUND_START`` so it re-probes the
device, SKIPS the queries already banked to ``BENCH_<q>.json`` since
the round began, measures only what is missing, and stamps the merged
artifact with a ``resumed_from`` marker. A clean exit closes the
round; the next launch starts fresh (everything re-measured).

Usage: python scripts/bench_on_healthy.py  (backgrounded, SIGTERM-safe)
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = os.path.join(REPO, ".tpu_healthy")
BUSY = os.path.join(REPO, ".bench_running")
LOG = os.path.join(REPO, "BENCH_WATCH.log")
SENTINEL_STATE = os.path.join(REPO, "SENTINEL_STATE.json")
ROUND_STATE = os.path.join(REPO, ".bench_round.json")
COOLDOWN_S = 1800  # after a bench attempt, let the prober re-establish
HEARTBEAT_POLL_S = 15
MAX_RESUME_ATTEMPTS = 4  # then the round is abandoned and starts fresh


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(msg, flush=True)


def tail_sentinel(last: dict) -> dict:
    """One poll of the bench child's sentinel status file; logs state
    transitions (always) and a periodic pulse (every ~60s) so the
    round log carries the heartbeat trajectory — and, when the memory
    governor is armed in the child, every ``overload_state`` ladder
    transition (NORMAL/THROTTLED/SHEDDING/DEGRADED), so a bench round
    that ran under overload protection says so in BENCH_WATCH.log.
    When the child runs sharded with MESHPROF armed the sentinel also
    carries ``shard_skew_frac`` / ``mesh_coverage_frac`` /
    ``exchange_rows_total``: hot-shard onset and clearance (the skew
    gauge leaving / returning to 0) are logged as transitions, and the
    periodic pulse carries the exchange-row flow so a bench round's
    mesh pressure survives in BENCH_WATCH.log even when the child's
    stdout is lost. Returns updated bookkeeping. Never raises — the
    watcher outlives a torn file."""
    try:
        with open(SENTINEL_STATE) as f:
            st = json.load(f)
    except (OSError, ValueError):
        return last
    if st.get("ts") == last.get("ts"):
        return last  # stale: child not beating (compiling, or gone)
    ov = st.get("overload_state")
    if ov is not None and ov != last.get("overload_state"):
        log(
            f"overload: {last.get('overload_state') or 'NORMAL'} -> {ov} "
            "[ladder transition]"
        )
    skew = st.get("shard_skew_frac")
    if skew is not None:
        was_hot = (last.get("shard_skew_frac") or 0.0) > 0.0
        if (skew > 0.0) != was_hot:
            log(
                f"mesh skew: {'cleared' if was_hot else 'HOT shard'} "
                f"(shard_skew_frac {last.get('shard_skew_frac') or 0.0} "
                f"-> {skew}) [skew transition]"
            )
    xr = st.get("exchange_rows_total")
    state = st.get("state", "?")
    changed = state != last.get("state")
    pulse = time.monotonic() - last.get("logged_at", 0.0) >= 60
    if changed or pulse:
        mesh_bits = ""
        if skew is not None or xr is not None:
            mesh_bits = (
                f" skew={skew} cover={st.get('mesh_coverage_frac')}"
                f" xrows={xr}"
                + (
                    f" (+{xr - last['exchange_rows_total']})"
                    if xr is not None
                    and last.get("exchange_rows_total") is not None
                    and xr >= last["exchange_rows_total"]
                    else ""
                )
            )
        log(
            f"sentinel: {state} latency={st.get('latency_ms')}ms "
            f"beats={st.get('beats')} wedges={st.get('wedges')}"
            + (f" overload={ov}" if ov is not None else "")
            + mesh_bits
            + (" [transition]" if changed else "")
        )
        last = dict(st, logged_at=time.monotonic())
    else:
        last = dict(
            last,
            ts=st.get("ts"),
            overload_state=ov,
            shard_skew_frac=skew,
            exchange_rows_total=xr,
        )
    return last


def load_round() -> dict:
    """The in-flight round marker, or {} (no round open / torn file)."""
    try:
        with open(ROUND_STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_round(state: dict) -> None:
    try:
        tmp = ROUND_STATE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, ROUND_STATE)
    except OSError:
        pass  # round tracking is best-effort; a fresh round still works


def close_round() -> None:
    try:
        os.remove(ROUND_STATE)
    except OSError:
        pass


def run_bench(resume: bool, round_start: float) -> int:
    """Launch bench.py and babysit it: poll + tail the sentinel status
    while it runs; SIGTERM (never SIGKILL — a murdered client wedges
    the relay) at the 90min backstop. ``resume`` re-enters the current
    round: bench.py re-probes and skips queries banked since
    ``round_start``."""
    t0 = time.monotonic()
    env = dict(os.environ)
    if resume:
        env["RW_BENCH_RESUME"] = "1"
    env["RW_BENCH_ROUND_START"] = repr(round_start)
    proc = subprocess.Popen([sys.executable, "bench.py"], cwd=REPO, env=env)
    last: dict = {}
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc
        if time.monotonic() - t0 > 5400:
            log("bench.py exceeded 90min backstop (SIGTERMed)")
            proc.terminate()
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                pass  # an orphan that eventually exits beats a SIGKILL
            return -15
        last = tail_sentinel(last)
        time.sleep(HEARTBEAT_POLL_S)


def main() -> None:
    log("watcher up")
    while True:
        if os.path.exists(MARKER) and not os.path.exists(BUSY):
            rnd = load_round()
            resume = bool(rnd)
            if resume and rnd.get("attempts", 0) >= MAX_RESUME_ATTEMPTS:
                log(
                    f"round abandoned after {rnd['attempts']} attempts; "
                    "starting fresh"
                )
                close_round()
                rnd, resume = {}, False
            if not resume:
                rnd = {"started": time.time(), "attempts": 0}
            rnd["attempts"] = rnd.get("attempts", 0) + 1
            save_round(rnd)
            log(
                "tunnel healthy -> launching bench.py"
                + (
                    f" (RESUMING round started {rnd['started']:.0f}, "
                    f"attempt {rnd['attempts']}: banked BENCH_<q>.json "
                    "queries will be skipped)"
                    if resume
                    else ""
                )
            )
            t0 = time.monotonic()
            rc = run_bench(resume, float(rnd.get("started", 0.0)))
            log(
                f"bench.py exited rc={rc} after "
                f"{time.monotonic() - t0:.0f}s — check BENCH_partial.json"
            )
            if rc == 0:
                close_round()
                log("round complete")
            else:
                log(
                    "round INCOMPLETE — will resume (skipping banked "
                    "queries) on the next healthy window"
                )
            time.sleep(COOLDOWN_S)
        time.sleep(45)


if __name__ == "__main__":
    main()
