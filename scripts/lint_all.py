#!/usr/bin/env python
"""The one-liner CI gate: host-language lint + rwlint over every
built-in query.

    python scripts/lint_all.py

Stages (all must pass; exit code is the OR of their failures):

1. ruff (pyflakes+bugbear, ruff.toml) over risingwave_tpu/, tests/,
   scripts/, bench.py — or, when ruff is not installed (the bench
   image does not ship it), a built-in AST unused-import scan (the
   F401 class) + byte-compilation of every file (syntax errors).
2. ``python -m risingwave_tpu lint --all-nexmark --deep`` — the static
   plan verifier + jaxpr sanitizer over q5/q7/q8.
3. ``python -m risingwave_tpu lint --all-nexmark --fusion-report`` —
   the fusion-feasibility analyzer: per-fragment fusible prefixes +
   RW-E8xx blockers with provenance.
3b. ``python -m risingwave_tpu lint --mesh-report`` — the mesh-
   readiness analyzer over the sharded q5/q7/q8 corpus (fresh
   subprocess owning the 8-virtual-device sim mesh): per-fragment
   SPMD-fusibility proofs + RW-E9xx blockers with provenance.
4. ``python scripts/perf_gate.py --smoke --blackbox --roofline
   --serving --freshness --overload --mesh --fusion
   --mesh-static`` — the
   dispatch-cost regression gate: committed BENCH artifacts vs
   scripts/perf_budgets.json, the CPU q5 steady-state microbench
   (bounded device dispatches/barrier + host-python ms/row), the
   black-box recorder gate (host ms/barrier + fsync-stall budgets, and
   the write-ring -> SIGKILL -> reader-CLI crash-survival smoke), the
   shared-arrangement serving gate (CI-scale registration storm with
   O(families) compile count + concurrent pgwire readers under
   budget), the overload-protection gate (seeded chaos storm against
   the memory-governed runtime: zero OOM/wedge, twin bit-identity,
   bounded flaps + recovery, governor overhead < 1%), the mesh-
   observability gate (8-virtual-device child: per-shard attribution
   covers >=90% of the sharded q5/q8 barrier wall, armed-vs-unarmed
   bit-identity, seeded hot-shard skew verdict names the right shard,
   mesh telemetry host overhead < 1%), the fusion ratchet vs
   FUSION_REPORT.json (fusible prefixes must not shrink, host-sync
   counts must not grow), and the mesh-static ratchet vs
   MESH_REPORT.json (host-routed exchange edges and per-code E9xx
   blocker counts must not grow, SPMD proofs must not shrink).
"""

from __future__ import annotations

import ast
import os
import py_compile
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ["risingwave_tpu", "tests", "scripts", "bench.py"]


def _py_files():
    for t in TARGETS:
        p = os.path.join(ROOT, t)
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in filenames:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _unused_imports(path: str) -> list:
    """F401-class scan: imported names never referenced. Conservative:
    __init__.py re-exports, `_` names, and __all__-listed names pass."""
    if os.path.basename(path) == "__init__.py":
        return []
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    imported = {}  # name -> lineno
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # feature declarations, not names
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    if not imported:
        return []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # handled via the root Name
    # names echoed in strings count (doctests, __all__, noqa-ish use)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.update(node.value.replace(".", " ").split())
    out = []
    for name, lineno in sorted(imported.items(), key=lambda kv: kv[1]):
        if name.startswith("_") or name in used:
            continue
        line = src.splitlines()[lineno - 1]
        if "noqa" in line:
            continue
        out.append(f"{path}:{lineno}: unused import {name!r}")
    return out


def stage_host_lint() -> int:
    ruff = shutil.which("ruff")
    if ruff is not None:
        print(f"[lint_all] ruff ({ruff})")
        return subprocess.call(
            [ruff, "check", *TARGETS], cwd=ROOT
        )
    print("[lint_all] ruff not installed — built-in fallback "
          "(unused-import scan + byte-compile)")
    import tempfile

    rc = 0
    findings = []
    with tempfile.TemporaryDirectory() as tmp:
        for path in _py_files():
            try:
                py_compile.compile(
                    path, doraise=True,
                    cfile=os.path.join(tmp, "out.pyc"),
                )
            except py_compile.PyCompileError as e:
                findings.append(str(e))
                rc = 1
            findings.extend(_unused_imports(path))
    for f in findings:
        print(f)
    if findings:
        rc = 1
    return rc


def stage_rwlint() -> int:
    print("[lint_all] rwlint --all-nexmark --deep")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.call(
        [sys.executable, "-m", "risingwave_tpu", "lint",
         "--all-nexmark", "--deep"],
        cwd=ROOT,
        env=env,
    )


def stage_fusion_report(out_path: str) -> int:
    """Produce the fusion analysis ONCE (JSON to ``out_path``); stage
    4's perf_gate consumes it via --fusion-current instead of paying
    for a second corpus build + jaxpr trace."""
    print("[lint_all] rwlint --fusion-report (fusion feasibility)")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        with open(out_path, "w") as f:
            rc = subprocess.call(
                [sys.executable, "-m", "risingwave_tpu", "lint",
                 "--all-nexmark", "--fusion-report", "--json"],
                cwd=ROOT,
                env=env,
                stdout=f,
            )
    except OSError as e:
        print(f"[lint_all] cannot write {out_path}: {e}")
        return 1
    if rc == 0:
        try:
            import json

            with open(out_path) as f:
                fus = json.load(f).get("__fusion__", {})
            for q in sorted(fus):
                if q.startswith("_"):
                    continue  # _provenance and friends: not a query
                s = fus[q]["summary"]
                print(
                    f"[lint_all]   {q}: "
                    f"{s['fusible_fragments']}/{s['fragments']} "
                    f"fragments fusible, "
                    f"{s['host_sync_points']} host-sync point(s), "
                    f"blockers {s['blockers_by_code']}"
                )
        except (OSError, ValueError, KeyError):
            pass
    return rc


def stage_mesh_report(out_path: str) -> int:
    """Produce the mesh-readiness analysis ONCE (JSON to ``out_path``)
    in a fresh subprocess — ``lint --mesh-report`` claims its own
    8-virtual-device mesh, which cannot be conjured in a process that
    already initialized jax. Stage 4's perf_gate consumes it via
    --mesh-current (the --mesh-static ratchet vs MESH_REPORT.json)."""
    print("[lint_all] rwlint --mesh-report (SPMD mesh readiness)")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # the child claims its own mesh
    try:
        with open(out_path, "w") as f:
            rc = subprocess.call(
                [sys.executable, "-m", "risingwave_tpu", "lint",
                 "--mesh-report", "--json"],
                cwd=ROOT,
                env=env,
                stdout=f,
            )
    except OSError as e:
        print(f"[lint_all] cannot write {out_path}: {e}")
        return 1
    if rc == 0:
        try:
            import json

            with open(out_path) as f:
                rep = json.load(f)
            for q in sorted(rep):
                if q.startswith("_") or q in ("ranking", "top_cost"):
                    continue
                s = rep[q]["summary"]
                print(
                    f"[lint_all]   {q}: "
                    f"{s['spmd_fusible_fragments']}/{s['fragments']} "
                    f"fragments SPMD-fusible, "
                    f"{s['host_routed_edges']} host-routed edge(s), "
                    f"blockers {s['blockers_by_code']}"
                )
            top = rep.get("top_cost") or {}
            print(
                f"[lint_all]   top cost: phase={top.get('phase')} "
                f"est_ms={top.get('est_ms')}"
            )
        except (OSError, ValueError, KeyError):
            pass
    return rc


def stage_perf_gate(
    fusion_current: str = None, mesh_current: str = None
) -> int:
    print("[lint_all] perf_gate --smoke --blackbox --roofline --serving "
          "--freshness --overload --mesh --integrity + fusion ratchet + "
          "mesh-static ratchet (dispatch-cost + recorder/fsync + device-"
          "roofline + shared-arrangement serving + freshness SLO + "
          "overload-protection + mesh-observability + state-integrity + "
          "fusion-regression + mesh-readiness budgets)")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "perf_gate.py"),
           "--smoke", "--blackbox", "--roofline", "--serving",
           "--freshness", "--overload", "--mesh", "--integrity"]
    if fusion_current and os.path.exists(fusion_current):
        cmd += ["--fusion-current", fusion_current]
    else:
        cmd += ["--fusion"]
    if mesh_current and os.path.exists(mesh_current):
        cmd += ["--mesh-current", mesh_current]
    else:
        cmd += ["--mesh-static"]
    return subprocess.call(cmd, cwd=ROOT, env=env)


def main() -> int:
    import tempfile

    rc = stage_host_lint()
    rc |= stage_rwlint()
    with tempfile.TemporaryDirectory() as tmp:
        fusion_json = os.path.join(tmp, "fusion_report.json")
        frc = stage_fusion_report(fusion_json)
        rc |= frc
        mesh_json = os.path.join(tmp, "mesh_report.json")
        mrc = stage_mesh_report(mesh_json)
        rc |= mrc
        rc |= stage_perf_gate(
            fusion_json if frc == 0 else None,
            mesh_json if mrc == 0 else None,
        )
    print(f"[lint_all] {'FAIL' if rc else 'ok'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
