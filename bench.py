#!/usr/bin/env python
"""Nexmark q5-lite throughput benchmark (the BASELINE.md headline path).

Measures the streaming HashAgg pipeline — bids -> hop window (10s/2s)
-> COUNT(*) per (auction, window_start) -> per-barrier delta flush ->
MV — in events/sec on the default JAX device (the TPU under the
driver; ``--smoke`` forces CPU), against a vectorized single-core
numpy "CPU actor" baseline doing identical work (our stand-in for the
reference's per-actor CPU throughput; the reference publishes no
absolute numbers, BASELINE.md).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def cpu_actor_baseline(host_chunks, window_ms, slide_ms):
    """Single-threaded numpy actor: hop-expand + dict groupby-count per
    chunk, barrier no-op (state already materialized). Vectorized with
    np.unique — a strong CPU actor, not a per-row straw man."""
    import numpy as np

    factor = window_ms // slide_ms
    counts = {}
    t0 = time.perf_counter()
    n_rows = 0
    for cols in host_chunks:
        auction = cols["auction"]
        ts = cols["date_time"]
        n_rows += len(ts)
        first = ((ts - window_ms) // slide_ms + 1) * slide_ms
        for k in range(factor):
            ws = first + k * slide_ms
            ok = ws <= ts
            pairs = np.stack([auction[ok], ws[ok]], axis=1)
            uniq, cnt = np.unique(pairs, axis=0, return_counts=True)
            for (a, w), c in zip(uniq, cnt):
                counts[(a, w)] = counts.get((a, w), 0) + int(c)
    dt = time.perf_counter() - t0
    return n_rows / dt, counts


def cpu_actor_q8(stream, window_ms):
    """Single-threaded q8 actor: per-side tumble + dedup dicts + probe
    of the other side's seen-set — the row-loop shape of a reference
    CPU actor. ``stream`` is [(side, cols_dict), ...] in arrival order."""
    pseen, aseen, out = {}, set(), {}
    t0 = time.perf_counter()
    n_rows = 0
    for side, cols in stream:
        ws = (cols["date_time"] // window_ms) * window_ms
        if side == "p":
            n_rows += len(ws)
            for i, w, nm in zip(
                cols["id"].tolist(), ws.tolist(), cols["name"].tolist()
            ):
                k = (i, w)
                if k not in pseen:
                    pseen[k] = nm
                    if k in aseen:
                        out[k] = nm
        else:
            n_rows += len(ws)
            for s, w in zip(cols["seller"].tolist(), ws.tolist()):
                k = (s, w)
                if k not in aseen:
                    aseen.add(k)
                    if k in pseen:
                        out[k] = pseen[k]
    dt = time.perf_counter() - t0
    return n_rows / dt, out


def _rwlint_gate(query: str):
    """Static plan verification BEFORE the bench runs (strict): a
    provably-broken plan fails the child with RW-E### diagnostics
    instead of burning a tier on wrong numbers. Lints the same
    small-capacity twin `lint --all-nexmark` verifies (the verifier is
    static, so plan shape is all that matters — analysis/).

    Also runs the fusion-feasibility analyzer over the same twin and
    returns its summary, so every BENCH JSON carries static blocker
    evidence (``{q}_fusion``) next to the dynamic profiler evidence —
    a TPU round's artifact shows WHAT was measured and WHY the
    dispatch wall is still there, in one file."""
    from risingwave_tpu.analysis.lint import (
        NEXMARK_SOURCE_SCHEMAS,
        build_nexmark_corpus,
        lint_pipeline,
    )

    built = build_nexmark_corpus(only=query)
    if query not in built:
        return None
    lint_pipeline(
        built[query].pipeline,
        NEXMARK_SOURCE_SCHEMAS[query],
        name=query,
        strict=True,
    )
    try:
        from risingwave_tpu.analysis.fusion_analyzer import (
            analyze_pipeline,
            report_to_json,
        )

        rep = report_to_json(
            analyze_pipeline(
                built[query].pipeline,
                NEXMARK_SOURCE_SCHEMAS[query],
                query,
                deep=True,
            )
        )
    except Exception:  # noqa: BLE001 — evidence, not a gate
        return None
    return {
        "summary": rep["summary"],
        "fragments": [
            {
                "fragment": f["fragment"],
                "fusible_prefix": f["fusible_prefix"],
                "chain_len": f["chain_len"],
                "whole_chain_fusible": f["whole_chain_fusible"],
                "host_sync_points": f["host_sync_points"],
                "blocker_codes": sorted(
                    {b["code"] for b in f["blockers"]}
                ),
            }
            for f in rep["fragments"]
        ],
    }


def _recompile_watch():
    """Armed AFTER the warmup pass: steady-state kernel cache deltas
    land in the BENCH JSON (``*_recompiles``) and in
    ``recompiles_total{fn=...}`` — nonzero means the run was re-tracing
    fused steps mid-measurement."""
    from risingwave_tpu.analysis.jax_sanitizer import RecompileWatch

    w = RecompileWatch()
    w.snapshot()
    return w


_BENCH_GOV = None


def _shape_watch_begin():
    """Arm SignatureWatch BEFORE warmup (the warmup pass registers the
    legitimate signature set; only post-warmup novelty is a hazard)
    plus a fresh ShapeGovernor for this query. RW_BENCH_SHAPEWATCH=0
    opts out."""
    global _BENCH_GOV
    import os

    if os.environ.get("RW_BENCH_SHAPEWATCH", "1") == "0":
        _BENCH_GOV = None
        return
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.runtime.bucketing import ShapeGovernor

    SIGNATURES.start()
    _BENCH_GOV = ShapeGovernor()


def _shape_watch_stable():
    """End of warmup: every later novel abstract input signature is a
    recompile hazard (the governor may pin on it)."""
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES

    if SIGNATURES.enabled:
        SIGNATURES.mark_stable()


def _shape_fields(prefix, executors):
    """Steady-state shape evidence for the BENCH JSON: post-warmup
    recompile-hazard count (perf_gate budget: zero), governor actions,
    and the padding overhead of the bucketed state buffers
    (wasted-lane fraction — the price paid for shape stability)."""
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.runtime.bucketing import padding_stats

    out = {f"{prefix}_padding": padding_stats(executors)}
    if SIGNATURES.enabled:
        if _BENCH_GOV is not None:
            # final sweep so trailing-barrier hazards still pin + count
            _BENCH_GOV.observe_barrier(list(executors))
            out[f"{prefix}_shape_governor"] = _BENCH_GOV.snapshot()
        out[f"{prefix}_recompile_hazards"] = SIGNATURES.hazard_total()
        SIGNATURES.stop()
    return out


def _governor_tick(executors):
    """Per-barrier governor hook for the raw-pipeline bench paths (the
    unified q5u path rides StreamingRuntime's built-in hook)."""
    if _BENCH_GOV is not None:
        _BENCH_GOV.observe_barrier(executors)


def _arm_fusion(pipeline, label):
    """Arm the fused per-barrier step (runtime/fused_step) on a bench
    pipeline — serial pipelines fuse here; the unified q5u path fuses
    inside the graph runtime automatically. RW_FUSED_STEP=0 opts out
    (the interpreted-twin baseline runs)."""
    from risingwave_tpu.runtime.fused_step import fuse_pipeline, fused_enabled

    if not fused_enabled():
        return []
    return fuse_pipeline(pipeline, label=label)


def _fused_fields(prefix, pipeline):
    """Every BENCH JSON carries ``{q}_fused_fragments`` (count +
    whole-chain flag + fragment labels): the artifact says how much of
    the measured pipeline ran as one donated device program."""
    from risingwave_tpu.runtime.fused_step import fused_fragments

    return {f"{prefix}_fused_fragments": fused_fragments(pipeline)}


def _freshness_fields(prefix, pipeline):
    """Every BENCH JSON carries ``{q}_freshness``: p50/p99/n per lane
    (commit->visible, source->visible, event-time lag) summarized from
    the pipeline's own per-barrier FreshnessSurface samples — the
    artifact records how fresh the MV actually was while the bench ran,
    and perf_gate holds the commit->visible p99 to the SLO budget
    (``bench_commit_to_visible_p99_ms_max``)."""
    samples = list(getattr(pipeline, "freshness_samples", ()) or ())
    out = {}
    for lane in (
        "commit_to_visible_ms",
        "source_to_visible_ms",
        "event_time_lag_ms",
    ):
        vals = sorted(
            s[lane]
            for s in samples
            if isinstance(s.get(lane), (int, float))
        )
        if vals:
            out[lane] = {
                "n": len(vals),
                "p50": round(vals[len(vals) // 2], 3),
                "p99": round(
                    vals[min(len(vals) - 1, int(0.99 * len(vals)))], 3
                ),
            }
        else:
            out[lane] = {"n": 0}
    return {f"{prefix}_freshness": out}


def _expand(executors):
    """Fused wrappers hide their members from plain executor lists;
    padding/governor surfaces need the members themselves."""
    from risingwave_tpu.runtime.fused_step import expand_fused

    return expand_fused(executors)


def _arm_deviceprof():
    """Arm the compiled-artifact roofline (deviceprof): every fused
    program bucket the measured run dispatches gets introspected ONCE
    via AOT lower+compile — FLOPs, bytes accessed, HBM footprint,
    compile ms, executable size — so the artifact's byte accounting
    comes from the executable, not host guesses. Armed BEFORE warmup
    so steady-state buckets analyze during warmup, not mid-measurement
    (a cache miss there costs one extra compile). RW_BENCH_DEVICEPROF=0
    opts out."""
    import os

    if os.environ.get("RW_BENCH_DEVICEPROF", "1") == "0":
        return None
    from risingwave_tpu.deviceprof import DEVICEPROF

    DEVICEPROF.reset()
    return DEVICEPROF.arm()


def _roofline_fields(prefix, n_barriers, seconds):
    """The ``{q}_roofline`` BENCH block: modeled bytes per barrier
    from the compiled executable, decomposed into useful vs padding
    traffic via the telemetry lanes — the explanation half of
    ``achieved_bw_frac``."""
    from risingwave_tpu.deviceprof import DEVICEPROF

    if not DEVICEPROF.enabled:
        return {}
    return DEVICEPROF.roofline_fields(prefix, n_barriers, seconds)


def _provenance_fields():
    """git_sha / pr_tag / engine_generation for every artifact —
    perf_gate warns when ratcheting against an older generation."""
    from risingwave_tpu.provenance import stamp

    return stamp()


def _profile_begin():
    """Arm the dispatch-wall profiler for the measured run: every BENCH
    JSON carries the per-executor decomposition of the dispatch stage
    (executor_ms + device-wait), dispatches-per-barrier/row, and
    host<->device transfer counts — the ranked fusion worklist for
    ROADMAP open item 1. Fencing (per-call block_until_ready — the
    host/device split) is OFF by default on every backend: it
    serializes the async dispatch the fused step exists to exploit,
    re-attributing device compute into the walk and poisoning the
    ``barrier_stage_ms`` dispatch/device_step split the perf gate
    ratchets. Force it with RW_BENCH_PROFILE_FENCE=1 when the per-
    executor device-wait decomposition matters more than honest stage
    attribution; opt out of profiling entirely with
    RW_BENCH_PROFILE=0."""
    import os

    if os.environ.get("RW_BENCH_PROFILE", "1") == "0":
        return None
    from risingwave_tpu.profiler import PROFILER

    fence = os.environ.get("RW_BENCH_PROFILE_FENCE") == "1"
    PROFILER.reset()
    return PROFILER.enable(fence=fence)


def _profile_fields(prefix, prof, n_barriers, rows):
    """Collect the profiler's surfaces into BENCH-JSON fields, print
    the operator-readable top-5 dispatch-cost executors, and disarm."""
    if prof is None:
        return {}
    total = prof.total_dispatches()
    top = prof.top_executors()
    fields = {
        f"{prefix}_executor_ms": prof.executor_summary(),
        f"{prefix}_device_dispatches": prof.dispatch_counts(),
        f"{prefix}_dispatches_per_barrier": round(
            total / max(n_barriers, 1), 2
        ),
        f"{prefix}_dispatches_per_row": round(total / max(rows, 1), 6),
        f"{prefix}_transfers": prof.transfer_counts(),
        f"{prefix}_top_executors": top,
    }
    print(f"[{prefix}] top dispatch-cost executors:", file=sys.stderr)
    for d in top:
        print(
            f"  {d['executor']:<28} host {d.get('host_ms', 0.0):>9.1f}ms  "
            f"device-wait {d.get('device_wait_ms', 0.0):>7.1f}ms  "
            f"dispatches {d.get('dispatches', 0.0):>6.0f}",
            file=sys.stderr,
        )
    prof.disable()
    return fields


def _arm_blackbox(smoke: bool) -> None:
    """Child-mode black box: the flight recorder persists every barrier
    to an append-only BLACKBOX_*.jsonl (so a SIGKILLed/wedged child
    still leaves a per-barrier timeline on disk), and — on a real
    device — the wedge sentinel heartbeats the device and converts a
    wedge into a prompt structured ``DeviceWedged`` (via the existing
    SIGALRM unwind) instead of sitting out the full child alarm.
    Smoke/CPU runs keep the in-memory ring only (no repo litter)."""
    import os
    import signal

    from risingwave_tpu import blackbox

    if os.environ.get("RW_BENCH_BLACKBOX", "1") == "0":
        return
    if not smoke:
        blackbox.RECORDER.configure(
            dir=os.environ.get("RW_BLACKBOX_DIR", "."),
            fsync_interval_s=2.0,
        )

        def on_wedge(err):
            # the main thread may be blocked inside a device call no
            # Python raise can reach: ride the child's SIGALRM handler
            # (see _expire — it surfaces the sentinel's DeviceWedged)
            signal.alarm(5)

        blackbox.SENTINEL.start(
            interval_s=float(os.environ.get("RW_BLACKBOX_HEARTBEAT_S", 10)),
            slow_ms=float(os.environ.get("RW_BLACKBOX_SLOW_MS", 2000)),
            deadline_s=float(os.environ.get("RW_BLACKBOX_DEADLINE_S", 60)),
            on_wedge=on_wedge,
            dir=os.environ.get("RW_BLACKBOX_DIR", "."),
        )


def _state_cap(expected_rows: int, floor: int) -> int:
    """Table capacity whose growth margin covers the expected volume:
    growth REBUILDS tables at new capacities, and every new capacity
    recompiles the fused step programs (~30s each on TPU) — size state
    up front so a bench run never grows mid-flight."""
    cap = floor
    while expected_rows * 2.5 > cap:
        cap *= 2
    return cap


def bench_q8(gen_cfg, epochs, events_per_epoch, chunk_events):
    """Returns the q8 result dict (device run + CPU actor baseline)."""
    import jax
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
    from risingwave_tpu.queries.nexmark_q import Q8_WINDOW_MS, build_q8

    fusion = _rwlint_gate("q8")  # static: fail BEFORE the event stream
    _shape_watch_begin()  # dynamic: warmup registers the legal shapes
    gen = NexmarkGenerator(NexmarkConfig(**gen_cfg))
    host_stream = []  # [(side, cols)] in arrival order, per epoch
    epochs_stream = []
    total_rows = 0
    for _ in range(epochs):
        # one person + one auction chunk per epoch: persons/auctions are
        # 2%/6% of the event stream, so per-generator-call chunks would
        # be tens of rows — all dispatch overhead. Batching per epoch is
        # result-identical (append-only dedup + inner join is
        # order-insensitive at barrier granularity).
        p_parts, a_parts = [], []
        done = 0
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            ev = gen.next_events(n)
            p, a = ev["person"], ev["auction"]
            if p and len(p["id"]):
                p_parts.append(p)
            if a and len(a["seller"]):
                a_parts.append(a)
        per_epoch = []
        if p_parts:
            cols = {
                k: np.concatenate([p[k] for p in p_parts])
                for k in ("id", "name", "date_time")
            }
            per_epoch.append(("p", cols))
            total_rows += len(cols["id"])
        if a_parts:
            cols = {
                k: np.concatenate([a[k] for a in a_parts])
                for k in ("seller", "date_time")
            }
            per_epoch.append(("a", cols))
            total_rows += len(cols["seller"])
        epochs_stream.append(per_epoch)
        host_stream.extend(per_epoch)

    def _cap(side):
        mx = max(
            (len(c["id" if s == "p" else "seller"]) for ep in epochs_stream
             for s, c in ep if s == side),
            default=64,
        )
        return 1 << (mx - 1).bit_length()

    p_cap, a_cap = _cap("p"), _cap("a")

    cpu_rows_s, cpu_out = cpu_actor_q8(host_stream, Q8_WINDOW_MS)

    def dev_chunks():
        return [
            [
                (
                    side,
                    StreamChunk.from_numpy(
                        cols, p_cap if side == "p" else a_cap
                    ),
                )
                for side, cols in ep
            ]
            for ep in epochs_stream
        ]

    chunks = dev_chunks()
    # q8 state accumulates across the run (no watermarks driven here):
    # persons+auctions ~8%% of events, all retained
    c8 = _state_cap(int(epochs * events_per_epoch * 0.09), 1 << 16)
    _arm_deviceprof()  # roofline: analyze buckets from warmup on
    q8 = build_q8(capacity=c8, fanout=8, out_cap=1 << 14)
    _arm_fusion(q8.pipeline, "q8")
    # warmup epoch compiles every kernel, then fresh state + warm caches
    # warm over ALL epochs' chunk layouts (the fused two-input program
    # compiles per batch-count family — see the q7 warmup note)
    for ep in chunks:
        for side, c in ep:
            (q8.pipeline.push_left if side == "p" else q8.pipeline.push_right)(c)
        q8.pipeline.barrier()
    q8 = build_q8(capacity=c8, fanout=8, out_cap=1 << 14)
    _arm_fusion(q8.pipeline, "q8")
    recompiles = _recompile_watch()
    _shape_watch_stable()  # post-warmup novelty = recompile hazard
    from risingwave_tpu.metrics import REGISTRY

    REGISTRY.histograms.pop("barrier_stage_ms", None)  # drop warmup obs
    prof = _profile_begin()

    barrier_times = []
    t0 = time.perf_counter()
    for ep in chunks:
        for side, c in ep:
            (q8.pipeline.push_left if side == "p" else q8.pipeline.push_right)(c)
        tb = time.perf_counter()
        q8.pipeline.barrier()
        barrier_times.append(time.perf_counter() - tb)
        _governor_tick(
            _expand(list(q8.pipeline.left) + list(q8.pipeline.right))
            + [q8.join]
        )
    jax.block_until_ready(q8.join.left.row_valid)
    dt = time.perf_counter() - t0

    got = {k: v[0] for k, v in q8.mview.snapshot().items()}
    ok = got == cpu_out
    if not ok:
        print(
            f"Q8 MISMATCH: device {len(got)} rows vs cpu {len(cpu_out)}",
            file=sys.stderr,
        )
    from risingwave_tpu.epoch_trace import stage_breakdown

    return {
        "q8_throughput": round(total_rows / dt, 1),
        "q8_unit": "persons+auctions/sec",
        "q8_vs_baseline": round((total_rows / dt) / cpu_rows_s, 3),
        "q8_cpu_actor_rows_per_sec": round(cpu_rows_s, 1),
        "q8_p99_barrier_ms": round(
            float(np.percentile(np.asarray(barrier_times) * 1e3, 99)), 2
        ),
        "q8_correct": ok,
        "q8_recompiles": recompiles.deltas(),
        "q8_fusion": fusion,
        "q8_barrier_stage_ms": stage_breakdown(),
        **_profile_fields("q8", prof, len(barrier_times), total_rows),
        **_fused_fields("q8", q8.pipeline),
        **_freshness_fields("q8", q8.pipeline),
        **_roofline_fields("q8", len(barrier_times), dt),
        **_shape_fields(
            "q8",
            _expand(
                list(q8.pipeline.left)
                + list(q8.pipeline.right)
                + [q8.join]
                + list(q8.pipeline.tail)
            ),
        ),
    }


def cpu_actor_q7(chunks, window_ms):
    """Single-threaded q7 actor with the same dynamic-filter smarts the
    device plan uses (rows below their window's running max drop)."""
    wmax, bids_at = {}, {}
    t0 = time.perf_counter()
    n_rows = 0
    for cols in chunks:
        ws = (cols["date_time"] // window_ms) * window_ms
        n_rows += len(ws)
        for a, b, p, w in zip(
            cols["auction"].tolist(),
            cols["bidder"].tolist(),
            cols["price"].tolist(),
            ws.tolist(),
        ):
            cur = wmax.get(w, -1)
            if p >= cur:
                bids_at.setdefault((w, p), []).append((a, b))
                if p > cur:
                    wmax[w] = p
    out = {
        (w, a, b): (p,)
        for w, p in wmax.items()
        for (a, b) in bids_at.get((w, p), ())
    }
    dt = time.perf_counter() - t0
    return n_rows / dt, out


def bench_q7(gen_cfg, epochs, events_per_epoch, chunk_events):
    import jax
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
    from risingwave_tpu.queries.nexmark_q import build_q7

    fusion = _rwlint_gate("q7")  # static: fail BEFORE the event stream
    _shape_watch_begin()  # dynamic: warmup registers the legal shapes
    window_ms = 10_000
    gen = NexmarkGenerator(NexmarkConfig(**gen_cfg))
    host_epochs = []
    total_bids = 0
    for _ in range(epochs):
        per_epoch = []
        done = 0
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            b = gen.next_events(n)["bid"]
            if b and len(b["auction"]):
                per_epoch.append(
                    {k: b[k] for k in ("auction", "bidder", "price", "date_time")}
                )
                total_bids += len(b["auction"])
        host_epochs.append(per_epoch)

    flat = [c for ep in host_epochs for c in ep]
    cpu_rows_s, cpu_out = cpu_actor_q7(flat, window_ms)

    cap = chunk_events
    mk = lambda: [
        [StreamChunk.from_numpy(c, cap) for c in ep] for ep in host_epochs
    ]

    def run(q7, chunks):
        execs = _expand(
            list(q7.pipeline.left)
            + list(q7.pipeline.right)
            + [q7.join]
            + list(q7.pipeline.tail)
        )
        barrier_times = []
        max_ts = 0
        t0 = time.perf_counter()
        for ep_i, ep in enumerate(chunks):
            for c in ep:
                q7.pipeline.push_left(c)
                q7.pipeline.push_right(c)
            max_ts = max(
                max_ts, int(host_epochs[ep_i][-1]["date_time"].max())
            )
            tb = time.perf_counter()
            q7.pipeline.barrier()
            barrier_times.append(time.perf_counter() - tb)
            # recompile-storm governor: hazard deltas per barrier; over
            # budget (or SLOW sentinel) pins the offender's buckets
            _governor_tick(execs)
            q7.pipeline.watermark("date_time", max_ts)
        jax.block_until_ready(q7.join.left.row_valid)
        return time.perf_counter() - t0, barrier_times

    # watermarks bound q7 state to open windows, but the growth
    # heuristic is volume-driven: margin must cover one epoch's pushes
    c7 = _state_cap(events_per_epoch, 1 << 16)
    _arm_deviceprof()  # roofline: analyze buckets from warmup on

    def mk_q7():
        q7 = build_q7(
            capacity=c7,
            fanout=16,
            out_cap=1 << 14,
            agg_capacity=c7,
            filter_capacity=c7,
        )
        _arm_fusion(q7.pipeline, "q7")
        return q7

    q7 = mk_q7()
    # warm over ALL epochs' chunk layouts: the fused two-input program
    # compiles per (batch count, chunk signature) family, and a
    # 2-epoch smoke tier would otherwise pay a fresh compile INSIDE
    # the measured window whenever epoch 2's chunk count differs
    run(q7, mk())

    recompiles = _recompile_watch()
    _shape_watch_stable()  # post-warmup novelty = recompile hazard
    # build + host->device conversion BEFORE arming the profiler: the
    # measured dispatch/transfer counts describe steady-state barriers,
    # not one-time construction (same protocol as q5/q8)
    q7 = mk_q7()
    chunks7 = mk()
    from risingwave_tpu.metrics import REGISTRY

    REGISTRY.histograms.pop("barrier_stage_ms", None)  # drop warmup obs
    prof = _profile_begin()
    dt, barrier_times = run(q7, chunks7)

    got = q7.mview.snapshot()
    ok = got == cpu_out
    if not ok:
        print(
            f"Q7 MISMATCH: device {len(got)} rows vs cpu {len(cpu_out)}",
            file=sys.stderr,
        )
    from risingwave_tpu.epoch_trace import stage_breakdown

    return {
        "q7_throughput": round(total_bids / dt, 1),
        "q7_unit": "bids/sec",
        "q7_vs_baseline": round((total_bids / dt) / cpu_rows_s, 3),
        "q7_cpu_actor_rows_per_sec": round(cpu_rows_s, 1),
        "q7_p99_barrier_ms": round(
            float(np.percentile(np.asarray(barrier_times) * 1e3, 99)), 2
        ),
        "q7_correct": ok,
        "q7_recompiles": recompiles.deltas(),
        "q7_fusion": fusion,
        "q7_barrier_stage_ms": stage_breakdown(),
        **_profile_fields("q7", prof, len(barrier_times), total_bids),
        **_fused_fields("q7", q7.pipeline),
        **_freshness_fields("q7", q7.pipeline),
        **_roofline_fields("q7", len(barrier_times), dt),
        # AFTER profiler disarm: padding stats read device occupancy
        # counters and must not pollute the steady-state transfer counts
        **_shape_fields(
            "q7",
            _expand(
                list(q7.pipeline.left)
                + list(q7.pipeline.right)
                + [q7.join]
                + list(q7.pipeline.tail)
            ),
        ),
    }


Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)


def bench_q5_unified(epochs, events_per_epoch, chunk_events, smoke):
    """The SAME q5 as SQL through the UNIFIED path: planner -> actor
    graph (dispatchers, permit channels, FragmentActor threads) — the
    one-path-from-SQL-to-execution evidence, measured."""
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.connectors.nexmark import (
        BID_SCHEMA,
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.queries.nexmark_q import Q5_SLIDE_MS, Q5_WINDOW_MS
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv
    from risingwave_tpu.sql import Catalog, StreamPlanner

    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    host_chunks = []
    for _ in range(epochs):
        per_epoch, done = [], 0
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            bid = gen.next_events(n)["bid"]
            if bid and len(bid["auction"]):
                per_epoch.append(
                    {"auction": bid["auction"], "date_time": bid["date_time"]}
                )
        host_chunks.append(per_epoch)
    flat = [c for ep in host_chunks for c in ep]
    total_bids = sum(len(c["auction"]) for c in flat)
    cpu_rows_s, cpu_counts = cpu_actor_baseline(
        flat, Q5_WINDOW_MS, Q5_SLIDE_MS
    )

    _shape_watch_begin()  # warmup registers the legal shape set
    _arm_deviceprof()  # roofline: analyze buckets from warmup on
    c5 = _state_cap(2 * events_per_epoch, 1 << 16)
    catalog = Catalog({"bid": BID_SCHEMA})
    factory = lambda: StreamPlanner(catalog, capacity=c5)
    mv = graph_planned_mv(factory, Q5_SQL, parallelism=1)
    cap = chunk_events
    mk = lambda: [
        [StreamChunk.from_numpy(c, cap) for c in ep] for ep in host_chunks
    ]
    # warmup epoch compiles, then a fresh graph + warm caches
    for c in (StreamChunk.from_numpy(x, cap) for x in host_chunks[0]):
        mv.pipeline.push(c)
    mv.pipeline.barrier()
    mv.pipeline.close()
    mv = graph_planned_mv(factory, Q5_SQL, parallelism=1)
    _shape_watch_stable()  # post-warmup novelty = recompile hazard
    # drop warmup-epoch observations (first-epoch compile would
    # dominate the reported per-stage p99 and defeat the breakdown)
    from risingwave_tpu.metrics import REGISTRY

    REGISTRY.histograms.pop("barrier_stage_ms", None)

    dev_epochs = mk()  # host->device conversion OUTSIDE the timer
    prof = _profile_begin()  # armed after build+conversion (steady state)
    barrier_times = []
    t0 = time.perf_counter()
    for ep in dev_epochs:
        for c in ep:
            mv.pipeline.push(c)
        tb = time.perf_counter()
        mv.pipeline.barrier()
        barrier_times.append(time.perf_counter() - tb)
        _governor_tick(_expand(list(mv.pipeline.executors)))
    dt = time.perf_counter() - t0
    # measured roofline (PROFILE.md "measured vs modeled"): HBM bytes
    # actually moved this run = chunks pushed + live executor state
    from risingwave_tpu.epoch_trace import chunk_nbytes, roofline

    moved = sum(chunk_nbytes(c) for ep in dev_epochs for c in ep) + sum(
        ex.state_nbytes()
        for ex in mv.pipeline.executors
        if hasattr(ex, "state_nbytes")
    )
    rf = roofline(moved, dt)
    # snapshot the per-stage breakdown NOW: it must describe the sync
    # run next to whose p99 it is reported, not blend in the pipelined
    # phase's admission-mode observations below
    from risingwave_tpu.epoch_trace import stage_breakdown

    stages_sync = stage_breakdown()
    # per-executor decomposition of the sync run's dispatch stage (the
    # pipelined phase below runs unprofiled — the breakdown must
    # describe the same run as stages_sync)
    prof_fields = _profile_fields("q5u", prof, len(barrier_times), total_bids)
    # before close(): fused evidence scans live actors, padding stats
    # read live executor occupancy
    fused_fields = _fused_fields("q5u", mv.pipeline)
    fresh_fields = _freshness_fields("q5u", mv.pipeline)
    shape_fields = _shape_fields("q5u", _expand(list(mv.pipeline.executors)))
    roofline_fields = _roofline_fields("q5u", len(barrier_times), dt)
    snap = mv.mview.snapshot()  # {(auction, window_start): (num,)}
    ok = snap == {k: (v,) for k, v in cpu_counts.items()}
    mv.pipeline.close()

    # pipelined barriers: admit every epoch without draining (the
    # reference's in-flight barriers, barrier/mod.rs:538) — epoch N+1's
    # pushes overlap epoch N's flush inside the actors. A failure here
    # must not zero the banked sync number: fall back to sync-only.
    dtp = float("inf")
    mvp = None
    try:
        mvp = graph_planned_mv(factory, Q5_SQL, parallelism=1)
        dev_epochs = mk()
        tp0 = time.perf_counter()
        pending = []
        for ep in dev_epochs:
            for c in ep:
                mvp.pipeline.push(c)
            pending.append(mvp.pipeline.barrier_nowait())
        for e in pending:
            mvp.pipeline.wait_barrier(e)
        dtp = time.perf_counter() - tp0
        snap_p = mvp.mview.snapshot()
        ok = ok and snap_p == {k: (v,) for k, v in cpu_counts.items()}
    except Exception as e:
        # a crashed pipelined phase never validated: drop its time so
        # the reported best is the (validated) sync run only
        dtp = float("inf")
        print(f"Q5U pipelined phase failed ({e}); sync-only", file=sys.stderr)
    finally:
        if mvp is not None:
            try:
                mvp.pipeline.close()  # actor threads must release the chip
            except Exception:
                pass
    if not ok:
        print(
            f"Q5U MISMATCH: {len(snap)} groups vs {len(cpu_counts)}",
            file=sys.stderr,
        )
    best = max(total_bids / dt, total_bids / dtp)
    return {
        "q5u_throughput": round(best, 1),
        "q5u_unit": "bids/sec",
        "q5u_vs_baseline": round(best / cpu_rows_s, 3),
        "q5u_sync_throughput": round(total_bids / dt, 1),
        "q5u_pipelined_throughput": round(total_bids / dtp, 1),
        "q5u_p99_barrier_ms": round(
            float(np.percentile(np.asarray(barrier_times) * 1e3, 99)), 2
        ),
        "q5u_correct": ok,
        "q5u_cpu_actor_rows_per_sec": round(cpu_rows_s, 1),
        "q5u_total_bids": total_bids,
        # barrier-lifecycle observability: where each barrier's time
        # went (per stage, sync run only) + the measured roofline
        "barrier_stage_ms": stages_sync,
        "achieved_bw_frac": rf["achieved_bw_frac"],
        "achieved_bw_gbps": rf["achieved_bw_gbps"],
        "hbm_peak_gbps": rf["hbm_peak_gbps"],
        "hbm_bytes_touched": rf["hbm_bytes_touched"],
        **prof_fields,
        **fused_fields,
        **fresh_fields,
        **shape_fields,
        **roofline_fields,
    }


def bench_q5(args_epochs, events_per_epoch, chunk_events, smoke, agg_mode):
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")

    fusion = _rwlint_gate("q5")  # static: fail BEFORE the event stream
    _shape_watch_begin()  # dynamic: warmup registers the legal shapes
    _arm_deviceprof()  # roofline: analyze buckets from warmup on

    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
    from risingwave_tpu.queries.nexmark_q import (
        Q5_SLIDE_MS,
        Q5_WINDOW_MS,
        build_q5_lite,
    )

    epochs = args_epochs
    device = jax.devices()[0]
    platform = device.platform

    # -- pre-generate the workload (host) --------------------------------
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    host_chunks = []  # numpy column dicts, one per push
    for _ in range(epochs):
        done = 0
        per_epoch = []
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            ev = gen.next_events(n)
            bid = ev["bid"]
            if bid and len(bid["auction"]):
                per_epoch.append(
                    {
                        "auction": bid["auction"],
                        "date_time": bid["date_time"],
                    }
                )
        host_chunks.append(per_epoch)
    flat_host = [c for ep in host_chunks for c in ep]
    total_bids = sum(len(c["auction"]) for c in flat_host)

    # -- CPU actor baseline ----------------------------------------------
    cpu_rows_s, cpu_counts = cpu_actor_baseline(
        flat_host, Q5_WINDOW_MS, Q5_SLIDE_MS
    )

    # -- device pipeline --------------------------------------------------
    import functools

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.hop_window import hop_step_fn
    from risingwave_tpu.parallel.sharded_agg import stack_chunks

    cap = chunk_events  # bids per chunk <= events per chunk
    # one fused lax.scan per epoch: hop + agg over every chunk in ONE
    # device dispatch (per-chunk Python dispatch dominates on TPU)
    pre = functools.partial(
        hop_step_fn,
        ts_col="date_time",
        size_ms=Q5_WINDOW_MS,
        slide_ms=Q5_SLIDE_MS,
        out_start="window_start",
    )

    c5 = _state_cap(2 * events_per_epoch, 1 << 18)

    def run_q5(epochs_chunks, q5=None):
        from risingwave_tpu.profiler import PROFILER

        if q5 is None:
            q5 = build_q5_lite(capacity=c5, state_cleaning=False)
            _arm_fusion(q5.pipeline, "q5")
        barrier_times = []
        t0 = time.perf_counter()
        for stacked in epochs_chunks:
            if PROFILER.enabled:
                # apply_stacked bypasses the chain walk — attribute its
                # host time to the agg executor explicitly
                PROFILER.run(
                    q5.agg, "apply", q5.agg.apply_stacked,
                    stacked, pre=pre, mode=agg_mode,
                )
            else:
                q5.agg.apply_stacked(stacked, pre=pre, mode=agg_mode)
            tb = time.perf_counter()
            q5.pipeline.barrier()
            barrier_times.append(time.perf_counter() - tb)
        jax.block_until_ready(q5.agg.state.row_count)
        return q5, time.perf_counter() - t0, barrier_times

    def mk_stacked():
        return [
            stack_chunks([StreamChunk.from_numpy(c, cap) for c in ep])
            for ep in host_chunks
        ]

    run_q5(mk_stacked()[:1])  # warmup: compile epoch step + flush
    from risingwave_tpu.metrics import REGISTRY

    REGISTRY.histograms.pop("barrier_stage_ms", None)  # drop warmup obs
    recompiles = _recompile_watch()
    _shape_watch_stable()  # post-warmup novelty = recompile hazard
    # build + conversion outside the profiled window (steady-state
    # dispatch counts, not construction)
    stacked = mk_stacked()
    q5_fresh = build_q5_lite(capacity=c5, state_cleaning=False)
    _arm_fusion(q5_fresh.pipeline, "q5")
    prof = _profile_begin()
    q5, dt, barrier_times = run_q5(stacked, q5_fresh)

    rows_s = total_bids / dt
    p99_barrier_ms = float(np.percentile(np.asarray(barrier_times) * 1e3, 99))

    # measured roofline: bytes this run moved through HBM (epoch-stacked
    # input chunks + the live agg/MV state) over the measured wall time
    from risingwave_tpu.epoch_trace import chunk_nbytes, roofline, stage_breakdown

    moved = sum(chunk_nbytes(s) for s in stacked) + sum(
        ex.state_nbytes()
        for ex in q5.pipeline.executors
        if hasattr(ex, "state_nbytes")
    )
    rf = roofline(moved, dt)

    # -- correctness cross-check vs the CPU actor ------------------------
    mv = {k: v[0] for k, v in q5.mview.snapshot().items()}
    ok = mv == {k: v for k, v in cpu_counts.items()}
    if not ok:
        print(
            f"MISMATCH: device MV {len(mv)} groups vs cpu {len(cpu_counts)}",
            file=sys.stderr,
        )

    return {
        "metric": "nexmark_q5_lite_throughput",
        "value": round(rows_s, 1),
        "unit": "bids/sec",
        "vs_baseline": round(rows_s / cpu_rows_s, 3),
        "platform": platform,
        "cpu_actor_rows_per_sec": round(cpu_rows_s, 1),
        "p99_barrier_ms": round(p99_barrier_ms, 2),
        "total_bids": total_bids,
        "epochs": epochs,
        "agg_mode": agg_mode,
        "correct": ok,
        "q5_achieved_bw_frac": rf["achieved_bw_frac"],
        "q5_achieved_bw_gbps": rf["achieved_bw_gbps"],
        "q5_hbm_peak_gbps": rf["hbm_peak_gbps"],
        "q5_barrier_stage_ms": stage_breakdown(),
        "q5_recompiles": recompiles.deltas(),
        "q5_fusion": fusion,
        **_profile_fields("q5", prof, len(barrier_times), total_bids),
        **_fused_fields("q5", q5.pipeline),
        **_freshness_fields("q5", q5.pipeline),
        **_shape_fields("q5", _expand(list(q5.pipeline.executors))),
        **_roofline_fields("q5", len(barrier_times), dt),
    }


# ---------------------------------------------------------------------------
# Orchestration: each query benches in an isolated SUBPROCESS, so one
# kernel fault / hang cannot zero out the whole benchmark (VERDICT r2
# #1). r4 discipline (VERDICT r3 #1):
#   - BREADTH-FIRST tiers: every query lands a smoke_dev number before
#     anything escalates — the first few minutes bank a full result set.
#   - Every success is written to BENCH_partial.json IMMEDIATELY; the
#     driver's artifact can never be empty because the run was cut off.
#   - Children time THEMSELVES out via signal.alarm and exit through
#     normal teardown; the parent NEVER SIGKILLs a TPU client (a killed
#     client wedges the single-client tunnel for a long time).
#   - A global wall-clock budget (BENCH_BUDGET_S, default 2100s) gates
#     every child launch; remaining-time is always enough for the child
#     plus finalize, so the ONE JSON line always prints.
# ---------------------------------------------------------------------------

TIERS = {
    # (epochs, events_per_epoch, chunk_events, timeout_s)
    "full": (10, 200_000, 8_192, 600),
    "mid": (5, 50_000, 4_096, 360),
    "smoke_dev": (2, 10_000, 2_048, 240),
}
TIER_ORDER = ["smoke_dev", "mid", "full"]  # breadth-first escalation
PARTIAL_PATH = "BENCH_partial.json"
_FINALIZE_RESERVE_S = 20  # budget held back for merge+print


def _device_alive(timeout_s: int = 60) -> bool:
    """Fresh-process probe: can a client still acquire the device? A
    killed bench child can wedge the single-client TPU tunnel; when
    that happens every later jax.devices() hangs, so detect it cheaply
    instead of burning each tier's full timeout. The probe itself
    self-terminates via signal.alarm (never leaves a hung client)."""
    import subprocess

    code = (
        "import signal, os\n"
        "signal.signal(signal.SIGALRM, lambda *a: os._exit(9))\n"
        f"signal.alarm({timeout_s})\n"
        "import jax; jax.devices()\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout_s + 15) == 0
    except subprocess.TimeoutExpired:
        # alarm never fired (blocked inside a C call): SIGTERM and move
        # on — never SIGKILL a process that may hold the tunnel
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            pass
        return False


def _dump_bench_stall(query: str, tier: str, err) -> str:
    """A child wedged the device: leave a parent-side stall artifact
    naming the query (the child's own runtime-side STALL_DUMP_*.json —
    graph.wait_barrier timeout — complements this with per-actor
    detail). Never raises."""
    import os

    path = f"BENCH_STALL_{query}_{tier}.json"
    try:
        with open(path, "w") as f:
            json.dump(
                {
                    "query": query,
                    "tier": tier,
                    "error": str(err),
                    "ts": time.time(),
                    **_provenance_fields(),
                    "child_stall_dumps": sorted(
                        p for p in os.listdir(".")
                        if p.startswith("STALL_DUMP_")
                        or p.startswith("WEDGE_")
                        or p.startswith("BLACKBOX_")
                    ),
                },
                f,
                indent=1,
            )
    except OSError:
        return ""
    return path


def _bank_partial(merged: dict) -> None:
    """Persist the merged results NOW — a cut-off run must still leave
    the numbers on disk (r3 lost everything to an rc=124)."""
    import os

    merged.update(_provenance_fields())
    tmp = PARTIAL_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, PARTIAL_PATH)


def _bank_query(query: str, tier: str, sub: dict) -> None:
    """Per-query summary artifact, flushed the moment the query's
    child returns (probe-early, SNIPPETS.md [1]): a mid-round tunnel
    loss like r04/r05 still leaves every completed query's numbers in
    its own ``BENCH_<q>.json``, not only the merged partial."""
    import os

    path = f"BENCH_{query}.json"
    try:
        doc = {"query": query, "tier": tier, "ts": time.time()}
        doc.update(_provenance_fields())
        doc.update(sub)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        pass  # banking is forensic, never fatal


def _child_timeout(query: str, tier: str) -> int:
    """Per-(query, tier) child alarm. q7's compile stack (grouped-max
    DynamicFilter + retracting join) is the deepest; it has blown tier
    alarms at smoke_dev AND mid and wedged the tunnel each time — it
    runs DEAD-last now, so generous headroom costs only its own tier.
    q5u compiles one program per executor (vs q5's single fused
    program) and measures the run TWICE (sync + pipelined)."""
    base = TIERS[tier][3]
    mult = {"q7": 2.5, "q5u": 2.0}.get(query, 1.0)
    return int(base * mult)


def _run_child(query: str, tier: str, smoke: bool, agg_mode: str):
    """Run one (query, tier) in a subprocess. The child installs
    signal.alarm(timeout) and exits through normal JAX teardown on
    expiry; the parent waits timeout+grace and then SIGTERMs (still
    catchable) — it never SIGKILLs."""
    import subprocess

    import os

    epochs, events, chunk, _ = TIERS[tier]
    timeout_s = _child_timeout(query, tier)
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--only",
        query,
        "--epochs",
        str(epochs),
        "--events-per-epoch",
        str(events),
        "--chunk-events",
        str(chunk),
        "--agg-mode",
        agg_mode,
        "--alarm-s",
        str(timeout_s),
    ]
    if smoke:
        cmd.append("--smoke")
    env = dict(os.environ)
    # the barrier deadman must outlast first-epoch XLA compiles over the
    # TPU tunnel (minutes); the child's own signal.alarm stays the real
    # backstop, so give the deadman everything up to 30s before it
    env.setdefault("RW_BARRIER_TIMEOUT_S", str(max(timeout_s - 30, 120)))
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s + 45)
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM: python unwinds, client detaches
        try:
            out, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            # do NOT escalate to SIGKILL: a murdered client wedges the
            # tunnel; an orphan that eventually exits does less damage
            return None, f"{query}/{tier}: unresponsive after SIGTERM"
        return None, f"{query}/{tier}: timeout after {timeout_s}s"
    if proc.returncode != 0:
        tail = (err or "")[-400:]
        return None, f"{query}/{tier}: rc={proc.returncode}: {tail}"
    for line in reversed((out or "").strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, f"{query}/{tier}: no JSON in output"


def _bench_one(query: str, epochs, events, chunk, smoke, agg_mode):
    _enable_compile_cache()
    gen_cfg = {"first_event_rate": 10_000}
    if query == "q5":
        return bench_q5(epochs, events, chunk, smoke, agg_mode)
    if query == "q5u":
        return bench_q5_unified(epochs, events, chunk, smoke)
    if query == "q8":
        return bench_q8(gen_cfg, epochs, events, chunk)
    if query == "q7":
        return bench_q7(gen_cfg, epochs, events, chunk)
    raise ValueError(query)


def _enable_compile_cache():
    """Persistent XLA compilation cache shared across bench children
    and watcher re-runs: first-epoch compiles dominate every TPU tier
    (q7's stack alone has blown multiple tier alarms and wedged the
    tunnel), and identical HLO recompiles from scratch in each fresh
    subprocess without this. Safe no-op if the backend refuses."""
    from risingwave_tpu.config import enable_compile_cache

    enable_compile_cache()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small run on CPU")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--events-per-epoch", type=int, default=None)
    ap.add_argument("--chunk-events", type=int, default=None)
    ap.add_argument(
        "--only", choices=["q5", "q7", "q8", "q5u"], default=None
    )
    ap.add_argument(
        "--agg-mode",
        choices=["reduce", "scan"],
        default="reduce",
        help="epoch pre-reduction (fast) vs per-chunk lax.scan",
    )
    ap.add_argument(
        "--no-subprocess",
        action="store_true",
        help="run all queries in-process (debug aid)",
    )
    ap.add_argument(
        "--alarm-s",
        type=int,
        default=None,
        help="child self-timeout: exit via normal teardown (never "
        "leaves a wedged TPU client behind)",
    )
    ap.add_argument(
        "--multichip",
        type=int,
        nargs="?",
        const=8,
        default=None,
        metavar="N",
        help="run the N-virtual-device sharded dryrun (q5/q8/q7 MV "
        "parity vs serial + mid-stream kill/recover) with MESHPROF "
        "armed and stamp the structured MULTICHIP.json artifact: "
        "provenance + per-query per-shard attribution, exchange "
        "matrix, and skew verdicts",
    )
    args = ap.parse_args()

    if args.alarm_s:
        import signal

        alarm_deadline = time.monotonic() + args.alarm_s

        def _expire(signum, frame):
            # a sentinel-detected wedge surfaces as the STRUCTURED
            # DeviceWedged (forensic bundle already on disk) rather
            # than a generic timeout; either way python unwinds, the
            # JAX client detaches cleanly, parent reads rc != 0
            from risingwave_tpu import blackbox

            wedged = blackbox.SENTINEL.wedged_error()
            if wedged is not None:
                raise wedged
            remaining = alarm_deadline - time.monotonic()
            if remaining > 1:
                # the sentinel's on_wedge pulled the alarm forward but
                # the wedge HEALED before it fired (a completed beat
                # disarms): restore the original budget, don't kill a
                # healthy run with a misleading timeout
                signal.alarm(int(remaining) + 1)
                return
            raise TimeoutError(f"self-timeout after {args.alarm_s}s")

        signal.signal(signal.SIGALRM, _expire)
        signal.alarm(args.alarm_s)

    if args.smoke:
        import os

        # the axon sitecustomize force-registers the TPU plugin and
        # overrides JAX_PLATFORMS; both the env var AND the in-process
        # config update are required to actually get CPU
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.multichip:
        # the sharded dryrun is self-contained (forces virtual CPU
        # devices + arms MESHPROF internally); the artifact carries
        # the structured mesh doc so perf_trend can chart per-shard
        # attribution and skew across rounds, replacing the old
        # stdout-tail wrapper (MULTICHIP_r0*.json)
        import os

        import __graft_entry__ as graft

        doc = {"multichip": True, "ts": time.time()}
        doc.update(_provenance_fields())
        try:
            doc.update(graft.dryrun_multichip(args.multichip))
            doc["ok"] = True
        except Exception as e:  # noqa: BLE001 — artifact carries the failure
            doc["ok"] = False
            doc["error"] = repr(e)
        finally:
            from risingwave_tpu.parallel.meshprof import MESHPROF

            MESHPROF.disable()
        tmp = "MULTICHIP.json.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, "MULTICHIP.json")
        print(json.dumps(doc))
        return 0 if doc["ok"] else 1

    if args.only:
        # child mode: one query, one shape, in-process — with the
        # black box armed so even a SIGKILL/wedge leaves per-barrier
        # telemetry and a forensic bundle behind
        _arm_blackbox(args.smoke)
        epochs = args.epochs or 3
        events = args.events_per_epoch or 20_000
        chunk = args.chunk_events or 2_048
        result = _bench_one(
            args.only, epochs, events, chunk, args.smoke, args.agg_mode
        )
        from risingwave_tpu import blackbox

        if blackbox.RECORDER.segment_path:
            result[f"{args.only}_blackbox_segment"] = (
                blackbox.RECORDER.segment_path
            )
        blackbox.SENTINEL.stop()
        blackbox.RECORDER.close()
        result.update(_provenance_fields())
        print(json.dumps(result))
        return

    if (
        args.no_subprocess
        or args.epochs
        or args.events_per_epoch
        or args.chunk_events
    ):
        epochs = args.epochs or (3 if args.smoke else 10)
        events = args.events_per_epoch or (
            20_000 if args.smoke else 200_000
        )
        chunk = args.chunk_events or (2_048 if args.smoke else 8_192)
        result = _bench_one("q5", epochs, events, chunk, args.smoke, args.agg_mode)
        for q in ("q8", "q7"):
            result.update(
                _bench_one(q, epochs, events, chunk, args.smoke, args.agg_mode)
            )
        result.setdefault(
            "achieved_bw_frac", result.get("q5_achieved_bw_frac", 0.0)
        )
        result.setdefault(
            "barrier_stage_ms", result.get("q5_barrier_stage_ms", {})
        )
        result.update(_provenance_fields())
        print(json.dumps(result))
        return

    # orchestrator: breadth-first tiers, banked incrementally, budgeted
    import os

    budget_s = float(os.environ.get("BENCH_BUDGET_S", "2100"))
    t_start = time.perf_counter()

    def remaining() -> float:
        return budget_s - (time.perf_counter() - t_start)

    tiers = ["smoke_dev"] if args.smoke else TIER_ORDER
    merged = {}
    errors = []
    dead = False
    # -- round resume (tunnel-loss recovery; r04/r05 lost everything) --
    # RW_BENCH_RESUME=1 (set by bench_on_healthy after a failed attempt
    # of the SAME round): seed `merged` from the queries already banked
    # to BENCH_<q>.json since the round started, skip their completed
    # tiers in the schedule, and stamp the final artifact with a
    # `resumed_from` marker naming what was reused.
    resume = os.environ.get("RW_BENCH_RESUME", "0") not in ("", "0")
    try:
        round_start = float(os.environ.get("RW_BENCH_ROUND_START", "0"))
    except ValueError:
        round_start = 0.0
    if resume and round_start <= 0:
        # without a round anchor every banked artifact would pass the
        # freshness check — arbitrarily stale numbers must never be
        # stamped into today's round; re-measure everything instead
        print(
            "RW_BENCH_RESUME set without a valid RW_BENCH_ROUND_START: "
            "refusing to reuse banked artifacts (re-measuring all)",
            file=sys.stderr,
        )
        resume = False
    banked: dict = {}
    if resume:
        for q in ("q5u", "q5", "q8", "q7"):
            try:
                with open(f"BENCH_{q}.json") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            tier_b = doc.get("tier")
            if tier_b not in TIER_ORDER:
                continue
            if round_start and float(doc.get("ts", 0)) < round_start:
                continue  # a PREVIOUS round's artifact: re-measure
            banked[q] = tier_b
            merged.update(
                {
                    k: v
                    for k, v in doc.items()
                    if k not in ("query", "tier", "ts")
                }
            )
        if banked:
            merged["resumed_from"] = {
                "queries": dict(banked),
                "round_start": round_start,
            }
            print(
                f"resuming round: banked {banked} reused, re-measuring "
                "the rest",
                file=sys.stderr,
            )
    if not args.smoke:
        # tell the round's tunnel-health monitor we legitimately hold
        # the single-client device (it skips probing while this exists)
        try:
            with open(".bench_running", "w") as f:
                f.write(str(os.getpid()))
            import atexit

            atexit.register(
                lambda: os.path.exists(".bench_running")
                and os.remove(".bench_running")
            )
        except OSError:
            pass
        # the tunnel admits one client and a previously killed process
        # can wedge it for a long time; wait briefly for recovery — but
        # cap at ~5 min total (r3 burned 33 min here and still lost)
        for attempt in range(3):
            if _device_alive(60):
                break
            print(
                f"device unavailable (attempt {attempt + 1}/3); waiting",
                file=sys.stderr,
            )
            if attempt < 2:
                time.sleep(60)
        else:
            merged = {
                "metric": "nexmark_q5_unified_throughput",
                "value": 0,
                "unit": "bids/sec",
                "vs_baseline": 0,
                "errors": ["TPU tunnel unavailable (~5 min of probes)"],
            }
            _bank_partial(merged)
            print(json.dumps(merged))
            return
    failed: set = set()  # (query) that failed — don't escalate those
    # q5u FIRST: the unified SQL->actor path is the headline system
    # (VERDICT r4 weak #1 — the benched system must be the built
    # system); q5 (apply_stacked direct) stays as the fusion oracle.
    # q7 runs DEAD-LAST across all tiers: it has wedged the tunnel on
    # every r05 attempt (smoke_dev AND mid), and a wedge stops the
    # whole run — it must never cost the other queries their
    # escalation to mid/full.
    schedule = [(t, q) for t in tiers for q in ("q5u", "q5", "q8")]
    schedule += [(t, "q7") for t in tiers]
    for tier, query in schedule:
        if dead or query in failed:
            continue
        if query in banked and TIER_ORDER.index(tier) <= TIER_ORDER.index(
            banked[query]
        ):
            continue  # this round already banked the query at >= tier
        # worst case this child costs: its (per-query multiplied)
        # timeout + 45s communicate grace + 30s SIGTERM drain + a 75s
        # post-failure device probe — all before the finalize reserve
        child_budget = (
            _child_timeout(query, tier) + 45 + 30 + 75 + _FINALIZE_RESERVE_S
        )
        if remaining() < child_budget:
            errors.append(
                f"{query}/{tier}: skipped (budget: {remaining():.0f}s "
                f"left, need {child_budget}s)"
            )
            continue
        sub, err = _run_child(query, tier, args.smoke, args.agg_mode)
        if sub is not None:
            sub[f"{query}_tier" if query != "q5" else "tier"] = tier
            merged.update(sub)  # larger tier overwrites smaller
            _bank_query(query, tier, sub)  # per-query artifact, NOW
        else:
            errors.append(err)
            failed.add(query)
        snapshot = dict(merged)
        if errors:
            snapshot["errors"] = list(errors)
        _bank_partial(snapshot)  # success AND failure: bank now
        if sub is None and not args.smoke:
            # per-query device health re-probe (VERDICT r6 #2): one
            # wedged query must not cost the remaining queries their
            # runs. Record the forensic artifact, then give the tunnel
            # a bounded chance to recover before the next child.
            healthy = _device_alive()
            if not healthy:
                _dump_bench_stall(query, tier, err)
                for _attempt in range(2):
                    if remaining() < 120 + _FINALIZE_RESERVE_S:
                        break
                    time.sleep(60)
                    if _device_alive():
                        healthy = True
                        errors.append(
                            f"{query}/{tier}: tunnel recovered after wedge"
                        )
                        break
            if not healthy:
                # still wedged after the grace window: stop risking the
                # banked results; report what we have
                errors.append(f"{query}/{tier}: device wedged; stopping")
                dead = True
    if "value" in merged:
        # keep the apply_stacked (fusion-oracle) number visible next to
        # the headline before q5u overwrites the driver fields
        merged["q5_stacked_throughput"] = merged["value"]
    if "q5u_throughput" in merged:
        # HEADLINE = the unified SQL->planner->actor-graph path: the
        # number the driver records measures the actual system
        merged["metric"] = "nexmark_q5_unified_throughput"
        merged["value"] = merged["q5u_throughput"]
        merged["unit"] = "bids/sec"
        merged["vs_baseline"] = merged["q5u_vs_baseline"]
    if "achieved_bw_frac" not in merged and "q5_achieved_bw_frac" in merged:
        # q5u failed but the stacked oracle landed: its measured
        # roofline keeps the headline fields populated
        merged["achieved_bw_frac"] = merged["q5_achieved_bw_frac"]
        merged.setdefault(
            "barrier_stage_ms", merged.get("q5_barrier_stage_ms", {})
        )
    if "metric" not in merged:
        # every headline candidate failed even if q8/q7 landed: keep
        # the one-JSON-line contract parseable for the driver
        merged.update(
            {
                "metric": "nexmark_q5_unified_throughput",
                "value": 0,
                "unit": "bids/sec",
                "vs_baseline": 0,
            }
        )
    if errors:
        merged["errors"] = errors
    merged["bench_wall_s"] = round(time.perf_counter() - t_start, 1)
    _bank_partial(merged)
    print(json.dumps(merged))


if __name__ == "__main__":
    sys.exit(main())
