#!/usr/bin/env python
"""Nexmark q5-lite throughput benchmark (the BASELINE.md headline path).

Measures the streaming HashAgg pipeline — bids -> hop window (10s/2s)
-> COUNT(*) per (auction, window_start) -> per-barrier delta flush ->
MV — in events/sec on the default JAX device (the TPU under the
driver; ``--smoke`` forces CPU), against a vectorized single-core
numpy "CPU actor" baseline doing identical work (our stand-in for the
reference's per-actor CPU throughput; the reference publishes no
absolute numbers, BASELINE.md).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def cpu_actor_baseline(host_chunks, window_ms, slide_ms):
    """Single-threaded numpy actor: hop-expand + dict groupby-count per
    chunk, barrier no-op (state already materialized). Vectorized with
    np.unique — a strong CPU actor, not a per-row straw man."""
    import numpy as np

    factor = window_ms // slide_ms
    counts = {}
    t0 = time.perf_counter()
    n_rows = 0
    for cols in host_chunks:
        auction = cols["auction"]
        ts = cols["date_time"]
        n_rows += len(ts)
        first = ((ts - window_ms) // slide_ms + 1) * slide_ms
        for k in range(factor):
            ws = first + k * slide_ms
            ok = ws <= ts
            pairs = np.stack([auction[ok], ws[ok]], axis=1)
            uniq, cnt = np.unique(pairs, axis=0, return_counts=True)
            for (a, w), c in zip(uniq, cnt):
                counts[(a, w)] = counts.get((a, w), 0) + int(c)
    dt = time.perf_counter() - t0
    return n_rows / dt, counts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small run on CPU")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--events-per-epoch", type=int, default=None)
    ap.add_argument("--chunk-events", type=int, default=None)
    args = ap.parse_args()

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
    from risingwave_tpu.queries.nexmark_q import (
        Q5_SLIDE_MS,
        Q5_WINDOW_MS,
        build_q5_lite,
    )

    epochs = args.epochs or (3 if args.smoke else 10)
    events_per_epoch = args.events_per_epoch or (20_000 if args.smoke else 200_000)
    chunk_events = args.chunk_events or (2_048 if args.smoke else 8_192)

    device = jax.devices()[0]
    platform = device.platform

    # -- pre-generate the workload (host) --------------------------------
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    host_chunks = []  # numpy column dicts, one per push
    for _ in range(epochs):
        done = 0
        per_epoch = []
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            ev = gen.next_events(n)
            bid = ev["bid"]
            if bid and len(bid["auction"]):
                per_epoch.append(
                    {
                        "auction": bid["auction"],
                        "date_time": bid["date_time"],
                    }
                )
        host_chunks.append(per_epoch)
    flat_host = [c for ep in host_chunks for c in ep]
    total_bids = sum(len(c["auction"]) for c in flat_host)

    # -- CPU actor baseline ----------------------------------------------
    cpu_rows_s, cpu_counts = cpu_actor_baseline(
        flat_host, Q5_WINDOW_MS, Q5_SLIDE_MS
    )

    # -- device pipeline --------------------------------------------------
    from risingwave_tpu.array.chunk import StreamChunk

    cap = chunk_events  # bids per chunk <= events per chunk
    q5 = build_q5_lite(capacity=1 << 18, state_cleaning=False)
    dev_chunks = [
        [StreamChunk.from_numpy(c, cap) for c in ep] for ep in host_chunks
    ]

    # warmup: compile every kernel in the chain
    q5.pipeline.push(dev_chunks[0][0])
    q5.pipeline.barrier()
    warm = build_q5_lite(capacity=1 << 18, state_cleaning=False)
    q5 = warm  # fresh state, warm jit caches

    barrier_times = []
    t0 = time.perf_counter()
    for ep in dev_chunks:
        for c in ep:
            q5.pipeline.push(c)
        tb = time.perf_counter()
        q5.pipeline.barrier()
        barrier_times.append(time.perf_counter() - tb)
    jax.block_until_ready(q5.agg.state.row_count)
    dt = time.perf_counter() - t0

    rows_s = total_bids / dt
    p99_barrier_ms = float(np.percentile(np.asarray(barrier_times) * 1e3, 99))

    # -- correctness cross-check vs the CPU actor ------------------------
    mv = {k: v[0] for k, v in q5.mview.snapshot().items()}
    ok = mv == {k: v for k, v in cpu_counts.items()}
    if not ok:
        print(
            f"MISMATCH: device MV {len(mv)} groups vs cpu {len(cpu_counts)}",
            file=sys.stderr,
        )

    print(
        json.dumps(
            {
                "metric": "nexmark_q5_lite_throughput",
                "value": round(rows_s, 1),
                "unit": "bids/sec",
                "vs_baseline": round(rows_s / cpu_rows_s, 3),
                "platform": platform,
                "cpu_actor_rows_per_sec": round(cpu_rows_s, 1),
                "p99_barrier_ms": round(p99_barrier_ms, 2),
                "total_bids": total_bids,
                "epochs": epochs,
                "correct": ok,
            }
        )
    )


if __name__ == "__main__":
    main()
