"""Expand executor (GROUPING SETS): per-subset row copies with
out-of-subset NULLs + flag; end-to-end with HashAgg on (key, flag).
Reference: src/stream/src/executor/expand.rs."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.expand import ExpandExecutor
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall


def _chunk(ks, cities, xs, cap=8):
    return StreamChunk.from_numpy(
        {"k": np.asarray(ks), "city": np.asarray(cities),
         "x": np.asarray(xs)}, cap,
    )


def test_expand_nulls_and_flags():
    ex = ExpandExecutor([("k", "city"), ("k",), ()])
    (out,) = ex.apply(_chunk([1, 2], [10, 20], [5, 6]))
    d = out.to_numpy()
    rows = sorted(
        zip(
            d["flag"].tolist(),
            [None if m else v for v, m in zip(d["k"], d.get("k__null", [False] * 6))],
            [None if m else v for v, m in zip(d["city"], d.get("city__null", [False] * 6))],
            d["x"].tolist(),
        )
    )
    assert rows == [
        (0, 1, 10, 5), (0, 2, 20, 6),       # full set
        (1, 1, None, 5), (1, 2, None, 6),   # k only
        (2, None, None, 5), (2, None, None, 6),  # grand total
    ]


def test_expand_feeds_grouping_sets_agg():
    """expand -> HashAgg on (k, city, flag) computes sum(x) for
    GROUPING SETS ((k, city), (k,), ()) in one pass."""
    expand = ExpandExecutor([("k", "city"), ("k",), ()])
    agg = HashAggExecutor(
        group_keys=("k", "city", "flag"),
        calls=(AggCall("sum", "x", "sx"),),
        schema_dtypes={"k": jnp.int64, "city": jnp.int64, "flag": jnp.int64, "x": jnp.int64},
        capacity=1 << 8,
        nullable_keys=("k", "city"),
    )
    for c in expand.apply(_chunk([1, 1, 2], [10, 11, 10], [5, 6, 7])):
        agg.apply(c)
    outs = agg.on_barrier(None)
    agg.finish_barrier()
    snap = {}
    for c in outs:
        d = c.to_numpy()
        for i in range(len(d["sx"])):
            key = (
                None if d.get("k__null", np.zeros(len(d["sx"]), bool))[i] else int(d["k"][i]),
                None if d.get("city__null", np.zeros(len(d["sx"]), bool))[i] else int(d["city"][i]),
                int(d["flag"][i]),
            )
            snap[key] = int(d["sx"][i])
    assert snap == {
        (1, 10, 0): 5, (1, 11, 0): 6, (2, 10, 0): 7,
        (1, None, 1): 11, (2, None, 1): 7,
        (None, None, 2): 18,
    }
