"""Shared arrangements + serving tier (runtime/arrangements.py, PR 12).

Covers the registry lifecycle end to end: attach/refcount/free at the
DDL boundary, the device-state census returning to baseline after
DROP (the leak regression this PR fixed — which is also the
refcount-zero free proof), snapshot-consistent versioned reads under
a concurrent writer (never torn: every labeled read is bit-identical
to the quiesced state at that barrier), owner-fragment recovery with
live subscribers, kill-9 + restore staging shared state once, the
seeded concurrent CREATE/DROP/query stress, multi-tenant compile
sharing via lifted constants, and the rwlint sharing report.
"""

import gc
import threading
import time

import jax
import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog


def _mk(exec_mode="serial", runtime=None, capacity=1 << 10):
    return SqlSession(
        Catalog({}),
        runtime,
        capacity=capacity,
        exec_mode=exec_mode,
        parallelism=1,
    )


MV_SQL = (
    "CREATE MATERIALIZED VIEW {name} AS "
    "SELECT k, count(*) AS c FROM t WHERE v > {thr} GROUP BY k"
)


def _base(s, rows=((1, 100), (2, 20), (1, 300), (3, 50))):
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    vals = ", ".join(f"({k}, {v})" for k, v in rows)
    s.execute(f"INSERT INTO t VALUES {vals}")


def _cols(out):
    return {k: list(map(int, v)) for k, v in out.items()}


# ---------------------------------------------------------------------------
# attach / refcount / versioned reads
# ---------------------------------------------------------------------------


def test_identical_mvs_share_one_arrangement():
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    frags_after_owner = set(s.runtime.fragments)
    for name in ("b", "c", "d"):
        s.execute(MV_SQL.format(name=name, thr=10))
    # subscribers register NO fragments, NO executors, NO device state
    assert set(s.runtime.fragments) == frags_after_owner
    st = s.runtime.arrangements.stats()
    assert st["arrangements"] == 1 and st["refs"] == 4
    # all four names answer identically, and track new data together
    s.execute("INSERT INTO t VALUES (2, 500)")
    outs = [
        _cols(s.execute(f"SELECT k, c FROM {n} ORDER BY k")[0])
        for n in ("a", "b", "c", "d")
    ]
    assert all(o == outs[0] for o in outs)
    assert outs[0] == {"k": [1, 2, 3], "c": [2, 2, 1]}


def test_different_literals_do_not_share_state():
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    s.execute(MV_SQL.format(name="b", thr=250))
    st = s.runtime.arrangements.stats()
    assert st["arrangements"] == 2 and st["refs"] == 2
    a = _cols(s.execute("SELECT k, c FROM a ORDER BY k")[0])
    b = _cols(s.execute("SELECT k, c FROM b ORDER BY k")[0])
    assert a == {"k": [1, 2, 3], "c": [2, 1, 1]}
    assert b == {"k": [1], "c": [1]}


def test_share_fingerprint_components():
    from risingwave_tpu.runtime.arrangements import plan_share_fingerprint
    from risingwave_tpu.sql import parser as P

    s = _mk()
    _base(s)
    kw = dict(capacity=1 << 10, exec_mode="serial", parallelism=1)
    fp = lambda sql: plan_share_fingerprint(P.parse(sql), s.catalog, **kw)
    same = "CREATE MATERIALIZED VIEW x AS SELECT k, count(*) AS c FROM t WHERE v > 5 GROUP BY k"
    twin = "CREATE MATERIALIZED VIEW y AS SELECT k, count(*) AS c FROM t WHERE v > 5 GROUP BY k"
    other = "CREATE MATERIALIZED VIEW z AS SELECT k, count(*) AS c FROM t WHERE v > 6 GROUP BY k"
    assert fp(same) == fp(twin)  # the NAME is not part of the key
    assert fp(same) != fp(other)  # literal values ARE
    # unknown relation / UNION: conservatively unshareable
    assert fp("CREATE MATERIALIZED VIEW u AS SELECT q FROM nosuch") is None
    # capacity/exec knobs split the key (different lattice/plan shape)
    alt = plan_share_fingerprint(
        P.parse(same), s.catalog,
        capacity=1 << 12, exec_mode="serial", parallelism=1,
    )
    assert alt != fp(same)


def test_owner_drop_hands_off_then_refcount_zero_frees():
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    s.execute(MV_SQL.format(name="b", thr=10))
    s.execute("DROP MATERIALIZED VIEW a")
    # the writer keeps streaming under an internal alias
    assert "a" not in s.runtime.fragments
    assert any(f.startswith("__arr") for f in s.runtime.fragments)
    s.execute("INSERT INTO t VALUES (7, 700)")
    b = _cols(s.execute("SELECT k, c FROM b ORDER BY k")[0])
    assert b["k"] == [1, 2, 3, 7]
    assert s.runtime.arrangements.refcount("b") == 1
    # last reference: everything frees, the names become reusable
    s.execute("DROP MATERIALIZED VIEW b")
    assert s.runtime.arrangements.stats()["arrangements"] == 0
    assert set(s.runtime.fragments) == {"t"}
    s.execute(MV_SQL.format(name="a", thr=10))
    a = _cols(s.execute("SELECT k, c FROM a ORDER BY k")[0])
    assert a["k"] == [1, 2, 3, 7]


def test_mv_on_attached_mv_routes_to_writer_fragment():
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    s.execute(MV_SQL.format(name="b", thr=10))  # attached
    # an MV OVER the attached name subscribes to the writer fragment
    s.execute(
        "CREATE MATERIALIZED VIEW over_b AS "
        "SELECT k, c FROM b WHERE c > 1"
    )
    s.execute("INSERT INTO t VALUES (3, 500), (3, 600)")
    out = _cols(s.execute("SELECT k, c FROM over_b ORDER BY k")[0])
    assert out == {"k": [1, 3], "c": [2, 3]}
    # dropping the attached name over_b reads from must be refused
    # even while the arrangement has OTHER references (_subs never
    # carries the attached name — the alias-dependency map does)
    with pytest.raises(ValueError, match="depend"):
        s.execute("DROP MATERIALIZED VIEW b")
    # freeing the last arrangement reference would tear down the
    # writer fragment over_b rides: the drop must be refused — even
    # through a handoff rename — until the dependent MV is gone
    s.execute("DROP MATERIALIZED VIEW a")  # handoff (b still attached)
    with pytest.raises(ValueError, match="depend"):
        s.execute("DROP MATERIALIZED VIEW b")
    s.execute("DROP MATERIALIZED VIEW over_b")
    s.execute("DROP MATERIALIZED VIEW b")
    assert s.runtime.arrangements.stats()["arrangements"] == 0


# ---------------------------------------------------------------------------
# DROP leak audit (the refcount-zero free check)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("exec_mode", ["serial", "graph"])
def test_drop_mv_returns_live_array_census_to_baseline(exec_mode):
    """After DROP MATERIALIZED VIEW the device-state census must return
    to baseline: no executor (or actor thread, in graph mode) may keep
    HBM slabs reachable. The first create/drop cycle warms jit caches
    (compiled programs legitimately retain constants); later cycles
    must be leak-free."""
    s = _mk(exec_mode=exec_mode)
    _base(s)
    mk = lambda n: s.execute(MV_SQL.format(name=n, thr=10))
    drop = lambda n: s.execute(f"DROP MATERIALIZED VIEW {n}")
    mk("warm")
    s.execute("INSERT INTO t VALUES (5, 50)")
    drop("warm")
    gc.collect()
    baseline_arrays = len(jax.live_arrays())
    baseline_threads = threading.active_count()
    for cycle in range(2):
        mk("leakcheck")
        s.execute("INSERT INTO t VALUES (6, 60)")
        drop("leakcheck")
        gc.collect()
        assert len(jax.live_arrays()) <= baseline_arrays, (
            f"cycle {cycle}: live arrays grew past baseline "
            f"({len(jax.live_arrays())} > {baseline_arrays})"
        )
        # graph mode: actor threads must be reaped, not leaked
        assert threading.active_count() <= baseline_threads


def test_shared_drop_frees_exactly_at_zero_refs():
    """The census proof for arrangements: N attached MVs add ZERO
    device state, and dropping all of them frees the writer's state."""
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="warm", thr=10))
    s.execute("DROP MATERIALIZED VIEW warm")
    gc.collect()
    baseline = len(jax.live_arrays())
    base_bytes = s.runtime.state_nbytes()
    s.execute(MV_SQL.format(name="a", thr=10))
    gc.collect()
    owner_arrays = len(jax.live_arrays())
    owner_bytes = s.runtime.state_nbytes()
    for n in ("b", "c", "d", "e"):
        s.execute(MV_SQL.format(name=n, thr=10))
    gc.collect()
    # N structurally-identical MVs over one shared index hold ~1x the
    # device state of a single private MV (<=: the idle barriers run
    # by each CREATE let the bucket allocator's lazy shrink kick in)
    assert s.runtime.state_nbytes() <= owner_bytes
    # small slack: the attach-time idle barriers may shrink-rebuild
    # tables, and each fresh compiled program retains a few cached
    # constants — the accounted STATE equality above is the real claim
    assert len(jax.live_arrays()) <= owner_arrays + 6
    for n in ("a", "b", "c", "d", "e"):
        s.execute(f"DROP MATERIALIZED VIEW {n}")
    gc.collect()
    assert s.runtime.state_nbytes() <= base_bytes
    assert len(jax.live_arrays()) <= baseline


# ---------------------------------------------------------------------------
# snapshot consistency (never torn) + concurrency stress
# ---------------------------------------------------------------------------


def test_chaos_readers_never_observe_torn_snapshot():
    """Reader threads hammer versioned reads while a writer streams
    INSERT+barrier cycles: every read labeled with epoch E must be
    BIT-IDENTICAL to the owner MV quiesced at barrier E (the ground
    truth recorded under the runtime lock right after each barrier)."""
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="owner", thr=0))
    s.execute(MV_SQL.format(name="sub", thr=0))
    reader = s.runtime.arrangements.reader("sub")
    truth = {}  # epoch -> canonical rows
    truth_lock = threading.Lock()

    def canon(cols):
        ks = np.asarray(cols["k"])
        cs = np.asarray(cols["c"])
        return tuple(sorted(zip(ks.tolist(), cs.tolist())))

    owner_mv = s.runtime.arrangements._by_name["owner"].mview
    with s.runtime.lock:
        with truth_lock:
            truth[s.runtime.epoch] = canon(owner_mv.to_numpy())

    stop = threading.Event()
    failures = []
    checked = [0]

    def read_loop(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            epoch, cols = reader.read_versioned()
            if epoch is None:
                continue  # interim (pre-barrier-aligned) snapshot
            got = canon(cols)
            with truth_lock:
                want = truth.get(epoch)
            if want is None:
                continue  # a barrier the writer has not recorded yet
            checked[0] += 1
            if got != want:
                failures.append((epoch, got, want))
                return
            if rng.random() < 0.05:
                time.sleep(0.001)

    threads = [
        threading.Thread(target=read_loop, args=(i,), daemon=True)
        for i in range(8)
    ]
    for t in threads:
        t.start()
    rng = np.random.default_rng(42)
    for i in range(30):
        k, v = int(rng.integers(0, 9)), int(rng.integers(1, 1000))
        with s.runtime.lock:
            s._execute_locked(f"INSERT INTO t VALUES ({k}, {v})")
            # ground truth AT this barrier, before the lock releases
            with truth_lock:
                truth[s.runtime.epoch] = canon(owner_mv.to_numpy())
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not failures, f"torn/stale read: {failures[0]}"
    assert checked[0] > 0, "readers never validated a labeled snapshot"


def test_concurrent_create_drop_query_stress():
    """Seeded catalog/registry mutation under concurrent readers: DDL
    churn (CREATE/DROP of shared + private MVs) races pgwire-style
    readers and never corrupts the catalog, wedges a reader, or loses
    a refcount."""
    s = _mk()
    _base(s)
    s.execute(MV_SQL.format(name="stable0", thr=10))
    s.execute(MV_SQL.format(name="stable1", thr=10))  # shared reader
    stop = threading.Event()
    errors = []

    def read_loop(seed):
        rng = np.random.default_rng(seed)
        names = ["stable0", "stable1"]
        while not stop.is_set():
            name = names[int(rng.integers(0, len(names)))]
            try:
                out, tag = s.execute(f"SELECT k, c FROM {name} ORDER BY k")
                assert tag.startswith("SELECT")
                assert list(out) == ["k", "c"]
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                return

    readers = [
        threading.Thread(target=read_loop, args=(i,), daemon=True)
        for i in range(4)
    ]
    for t in readers:
        t.start()
    rng = np.random.default_rng(7)
    for i in range(12):
        thr = int(rng.integers(0, 3)) * 100
        s.execute(MV_SQL.format(name=f"churn{i}", thr=thr))
        s.execute(f"INSERT INTO t VALUES ({i % 5}, {thr + 1})")
        if i % 2:
            s.execute(f"DROP MATERIALIZED VIEW churn{i}")
            s.execute(f"DROP MATERIALIZED VIEW churn{i - 1}")
    stop.set()
    for t in readers:
        t.join(timeout=30)
    assert not errors, errors[0]
    assert s.runtime.arrangements.refcount("stable1") == 2
    # every churn MV dropped -> only the stable arrangement remains
    st = s.runtime.arrangements.stats()
    assert st["refs"] == 2


# ---------------------------------------------------------------------------
# recovery lifecycle
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_owner_crash_partial_recovery_keeps_subscribers(tmp_path):
    """Owner-fragment crash with live subscribers: the blast radius IS
    the shared write path, partial recovery restores + replays it, the
    subscribers re-serve off the recovered state, refcounts exact."""
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    rt = StreamingRuntime(
        LocalFsObjectStore(str(tmp_path)), auto_recover=True
    )
    s = _mk(exec_mode="graph", runtime=rt)
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    s.execute(MV_SQL.format(name="b", thr=10))
    before = _cols(s.execute("SELECT k, c FROM b ORDER BY k")[0])

    # poison the owner's actor chain: next chunk kills the actor
    pipeline = rt.fragments["a"]
    victim = pipeline.graph.executors[0]
    real_apply = victim.apply
    fired = []

    def poison(chunk):
        if not fired:
            fired.append(1)
            raise RuntimeError("injected owner-fragment crash")
        return real_apply(chunk)

    victim.apply = poison
    s.execute("INSERT INTO t VALUES (8, 800)")  # dies mid-epoch
    # the barrier inside INSERT auto-recovered FRAGMENT-SCOPED: only
    # the owner's blast radius restored + replayed, and the replayed
    # epoch closes at the NEXT barrier (partial recovery's rejoin
    # boundary) — run one so the replayed row becomes visible
    assert rt.auto_recoveries >= 1
    assert rt.partial_recoveries >= 1, "recovery was not fragment-scoped"
    with rt.lock:
        rt.barrier()
    after = _cols(s.execute("SELECT k, c FROM b ORDER BY k")[0])
    assert after["k"] == before["k"] + [8]
    assert s.runtime.arrangements.refcount("b") == 2
    a = _cols(s.execute("SELECT k, c FROM a ORDER BY k")[0])
    assert a == after


def test_restore_after_kill9_stages_shared_state_once(tmp_path):
    """kill-9 + restore: the DDL log replays CREATE a; CREATE b (the
    attach), recovery restores the ONE copy of shared state, both
    names serve, refcounts exact. Staging never wrote a twin: every
    staged table_id is unique (the owner-tagged single copy)."""
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    store = LocalFsObjectStore(str(tmp_path))
    rt = StreamingRuntime(store)
    s = _mk(runtime=rt)
    _base(s)
    s.execute(MV_SQL.format(name="a", thr=10))
    s.execute(MV_SQL.format(name="b", thr=10))
    s.execute("INSERT INTO t VALUES (9, 900)")
    rt.wait_checkpoints()
    want = _cols(s.execute("SELECT k, c FROM b ORDER BY k")[0])
    # staging covered the shared arrangement exactly once
    staged = rt.mgr.stage(rt.executors())
    tids = [d.table_id for d in staged]
    assert len(tids) == len(set(tids))
    del s  # no clean shutdown — the kill-9 analogue

    rt2 = StreamingRuntime(LocalFsObjectStore(str(tmp_path)))
    s2 = SqlSession.restore(rt2, capacity=1 << 10)
    st = rt2.arrangements.stats()
    assert st["arrangements"] == 1 and st["refs"] == 2
    for n in ("a", "b"):
        out = _cols(s2.execute(f"SELECT k, c FROM {n} ORDER BY k")[0])
        assert out == want


# ---------------------------------------------------------------------------
# multi-tenant compile sharing (lifted constants)
# ---------------------------------------------------------------------------


def test_parameter_variants_share_fused_programs():
    """Structurally-identical fused plans with different literals share
    one compiled program: after the shape-combo set compiles, further
    parameter variants add ZERO jit cache entries."""
    from risingwave_tpu.runtime.fused_step import fused_cache_stats

    s = _mk(exec_mode="graph")
    _base(s)
    sizes = []
    for i, thr in enumerate((11, 23, 37, 41, 53)):
        s.execute(MV_SQL.format(name=f"p{i}", thr=thr))
        s.execute(f"INSERT INTO t VALUES (1, {thr + 1}), (2, 3)")
        stats = fused_cache_stats()
        sizes.append(stats["compiled_programs"])
    assert stats["plans_lifted"] >= 5
    # the last two parameter variants hit the shared executables
    assert sizes[4] == sizes[3] == sizes[2], sizes
    # and the results stay exact per variant: v > 53 keeps the base
    # rows (1,100) and (1,300) plus the final insert (1,54)
    out = _cols(s.execute("SELECT k, c FROM p4 ORDER BY k")[0])
    assert out == {"k": [1], "c": [3]}


def test_lift_rejected_plans_fall_back_to_baked_literals():
    """RW_FUSED_LIFT=0 keeps the baked-literal behavior (the kill
    switch contract) — results identical, no lifted plans."""
    import os

    from risingwave_tpu.runtime.fused_step import fused_cache_stats

    prev = os.environ.get("RW_FUSED_LIFT")
    os.environ["RW_FUSED_LIFT"] = "0"
    try:
        s = _mk(exec_mode="graph")
        _base(s)
        lifted0 = fused_cache_stats()["plans_lifted"]
        s.execute(MV_SQL.format(name="nolift", thr=10))
        s.execute("INSERT INTO t VALUES (1, 999)")
        assert fused_cache_stats()["plans_lifted"] == lifted0
        out = _cols(s.execute("SELECT k, c FROM nolift ORDER BY k")[0])
        assert out == {"k": [1, 2, 3], "c": [3, 1, 1]}
    finally:
        if prev is None:
            os.environ.pop("RW_FUSED_LIFT", None)
        else:
            os.environ["RW_FUSED_LIFT"] = prev


# ---------------------------------------------------------------------------
# rwlint sharing report
# ---------------------------------------------------------------------------


def test_sharing_report_finds_q5_q5u_window_agg_index():
    from risingwave_tpu.analysis.sharing import run_sharing_report

    rep = run_sharing_report()
    assert rep["summary"]["plans"] >= 4
    agg_opps = [
        o
        for o in rep["opportunities"]
        if o["keys"] == ["auction", "window_start"]
        and any("agg" in t for t in o["tables"])
    ]
    assert agg_opps, "q5/q5u shared window-agg index not reported"
    assert {"q5", "q5u"} <= set(agg_opps[0]["plans"])
    # the would-share-but-for-lattice diagnostic class
    assert any(
        d["code"] == "RW-E703" for d in rep["diagnostics"]
    ), "lattice-mismatch diagnostic missing"
    assert all(
        d["severity"] == "warning"
        for d in rep["diagnostics"]
        if d["code"] == "RW-E703"
    )


def test_sharing_disabled_kill_switch():
    import os

    prev = os.environ.get("RW_SHARED_ARRANGEMENTS")
    os.environ["RW_SHARED_ARRANGEMENTS"] = "0"
    try:
        s = _mk()
        _base(s)
        s.execute(MV_SQL.format(name="a", thr=10))
        s.execute(MV_SQL.format(name="b", thr=10))
        # both built private pipelines: two fragments, no arrangements
        assert "a" in s.runtime.fragments and "b" in s.runtime.fragments
        assert s.runtime.arrangements.stats()["arrangements"] == 0
    finally:
        if prev is None:
            os.environ.pop("RW_SHARED_ARRANGEMENTS", None)
        else:
            os.environ["RW_SHARED_ARRANGEMENTS"] = prev
