"""DML breadth: DELETE FROM / UPDATE ... SET + pk-upsert retraction.

Reference: handler/dml.rs (batch insert/delete/update executors feed the
table's DML channel) and mview/materialize.rs:192-230 (Overwrite
conflict behavior emits UpdateDelete(stored) + UpdateInsert(new), so
downstream MVs stay consistent with the table).
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _sess():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_delete_from_rowid_table_updates_mv():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, sum(v) AS sv, count(*) AS n FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
    out, _ = s.execute("SELECT k, sv FROM m ORDER BY k")
    assert list(out["sv"]) == [30, 5]
    _, tag = s.execute("DELETE FROM t WHERE v = 20")
    assert tag == "DELETE 1"
    out, _ = s.execute("SELECT k, sv, n FROM m ORDER BY k")
    assert list(out["sv"]) == [10, 5]
    assert list(out["n"]) == [1, 1]
    # the table itself shrank too
    out, _ = s.execute("SELECT k, v FROM t ORDER BY v")
    assert list(out["v"]) == [5, 10]


def test_delete_whole_group_removes_mv_row():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, count(*) AS n FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 1), (2, 2)")
    s.execute("DELETE FROM t WHERE k = 2")
    out, _ = s.execute("SELECT k, n FROM m ORDER BY k")
    assert list(out["k"]) == [1]


def test_delete_without_where_empties_table():
    s = _sess()
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (1), (2), (3)")
    _, tag = s.execute("DELETE FROM t")
    assert tag == "DELETE 3"
    out, _ = s.execute("SELECT v FROM t")
    assert len(out.get("v", [])) == 0


def test_update_set_updates_table_and_mv():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, sum(v) AS sv, avg(v) AS a FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
    _, tag = s.execute("UPDATE t SET v = v + 100 WHERE k = 1")
    assert tag == "UPDATE 2"
    out, _ = s.execute("SELECT k, sv, a FROM m ORDER BY k")
    assert list(out["sv"]) == [230, 5]
    assert list(out["a"]) == pytest.approx([115.0, 5.0])
    out, _ = s.execute("SELECT v FROM t ORDER BY v")
    assert list(out["v"]) == [5, 110, 120]


def test_pk_upsert_emits_retraction_to_mv():
    """INSERT on an existing pk = Overwrite: downstream aggregates see
    UpdateDelete(old) + UpdateInsert(new), not a phantom extra row."""
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT g, sum(v) AS sv, count(*) AS n, avg(v) AS a "
        "FROM t GROUP BY g"
    )
    s.execute("INSERT INTO t VALUES (0, 0, 10), (2, 0, 30), (1, 1, 100)")
    out, _ = s.execute("SELECT g, a FROM m ORDER BY g")
    assert list(out["a"]) == pytest.approx([20.0, 100.0])
    s.execute("INSERT INTO t VALUES (0, 0, 50)")  # pk upsert: 10 -> 50
    out, _ = s.execute("SELECT g, sv, n, a FROM m ORDER BY g")
    assert list(out["n"]) == [2, 1]  # still two rows in group 0
    assert list(out["sv"]) == [80, 100]
    assert list(out["a"]) == pytest.approx([40.0, 100.0])


def test_pk_table_delete_and_update():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT sum(v) AS sv FROM t"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    s.execute("DELETE FROM t WHERE k = 2")
    out, _ = s.execute("SELECT sv FROM m")
    assert out["sv"][0] == 40
    s.execute("UPDATE t SET v = 99 WHERE k = 3")
    out, _ = s.execute("SELECT sv FROM m")
    assert out["sv"][0] == 109
    out, _ = s.execute("SELECT k, v FROM t ORDER BY k")
    assert list(out["k"]) == [1, 3]
    assert list(out["v"]) == [10, 99]


def test_update_pk_column_rejected():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10)")
    with pytest.raises(ValueError, match="primary-key"):
        s.execute("UPDATE t SET k = 2 WHERE v = 10")


def test_delete_on_mv_rejected():
    s = _sess()
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
    with pytest.raises(ValueError, match="not a DML-writable"):
        s.execute("DELETE FROM m")


def test_delete_varchar_predicate():
    s = _sess()
    s.execute("CREATE TABLE t (name VARCHAR, v BIGINT)")
    s.execute("INSERT INTO t VALUES ('a', 1), ('b', 2), ('a', 3)")
    _, tag = s.execute("DELETE FROM t WHERE name = 'a'")
    assert tag == "DELETE 2"
    out, _ = s.execute("SELECT name, v FROM t")
    assert list(out["name"]) == ["b"]


def test_pk_conflict_resolution_survives_recovery():
    """After a cold restart the restored pk table must KEEP resolving
    conflicts (restore_state may not flip it onto the int-matrix
    backend, which cannot emit UpdateDelete(stored))."""
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = MemObjectStore()
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT g, sum(v) AS sv, count(*) AS n FROM t GROUP BY g"
    )
    s.execute("INSERT INTO t VALUES (1, 0, 10), (2, 0, 30)")
    rt.wait_checkpoints()

    rt2 = StreamingRuntime(store)
    s2 = SqlSession.restore(rt2)
    s2.execute("INSERT INTO t VALUES (1, 0, 99)")  # upsert post-restore
    out, _ = s2.execute("SELECT g, sv, n FROM m")
    assert list(out["n"]) == [2]  # NOT 3: the upsert retracted
    assert list(out["sv"]) == [129]


def test_update_set_null_demotes_native_backend():
    """UPDATE ... SET c = NULL on an all-int (native-mapped) table:
    the table must store a real NULL (not 0) and survive checkpoint."""
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = MemObjectStore()
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    s.execute("UPDATE t SET v = NULL WHERE k = 2")
    out, _ = s.execute("SELECT k, v FROM t ORDER BY k")
    assert out["v"][1] is None or (
        not isinstance(out["v"][1], str) and np.isnan(float(out["v"][1]))
    )
    rt.wait_checkpoints()  # NULL value persistence (vn lanes)
    rt2 = StreamingRuntime(store)
    s2 = SqlSession.restore(rt2)
    out, _ = s2.execute("SELECT k, v FROM t ORDER BY k")
    v1 = out["v"][1]
    assert v1 is None or (not isinstance(v1, str) and np.isnan(float(v1)))
