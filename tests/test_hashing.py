"""Hashing / vnode tests (reference: vnode.rs, hash/key.rs)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.ops.hashing import VNODE_COUNT, hash128, hash_columns, vnode_of


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def test_vnode_range_and_determinism(rng):
    keys = jnp.asarray(rng.integers(0, 1 << 30, size=1000, dtype=np.int32))
    v1 = np.asarray(vnode_of([keys]))
    v2 = np.asarray(vnode_of([keys]))
    assert v1.min() >= 0 and v1.max() < VNODE_COUNT
    np.testing.assert_array_equal(v1, v2)
    # rough uniformity: every byte bucket of 1000 keys, chi-square-ish bound
    counts = np.bincount(v1, minlength=VNODE_COUNT)
    assert counts.max() < 25


def test_hash_distinguishes_columns_order():
    a = jnp.asarray(np.array([1, 2, 3], np.int32))
    b = jnp.asarray(np.array([3, 2, 1], np.int32))
    h_ab = np.asarray(hash_columns([a, b]))
    h_ba = np.asarray(hash_columns([b, a]))
    assert not np.array_equal(h_ab, h_ba)


def test_hash128_independent():
    k = jnp.asarray(np.arange(4096, dtype=np.int32))
    h1, h2 = hash128([k])
    # no trivial correlation between the two 32-bit mixes
    assert not np.array_equal(np.asarray(h1), np.asarray(h2))
    assert len(np.unique(np.asarray(h1))) > 4000


def test_float_negative_zero():
    x = jnp.asarray(np.array([0.0, -0.0], np.float32))
    h = np.asarray(hash_columns([x]))
    assert h[0] == h[1]


def test_int64_lanes():
    big = jnp.asarray(np.array([2**40, 2**40 + 1, 5], np.int64))
    # guard against silent truncation (ADVICE r1 high): values above bit
    # 31 must survive the device round-trip with their dtype intact
    assert big.dtype == jnp.int64
    np.testing.assert_array_equal(
        np.asarray(big), np.array([2**40, 2**40 + 1, 5], np.int64)
    )
    h = np.asarray(hash_columns([big]))
    assert len(np.unique(h)) == 3


def test_int64_high_bits_reach_both_fingerprints():
    # keys differing ONLY above bit 31 must differ in BOTH hash128 mixes;
    # the r1 folding scheme collapsed them to one folded u32, weakening
    # the pair to <64 bits for BIGINT ids
    a = jnp.asarray(np.array([5, 2**33 + 5, 2**34 + 5], np.int64))
    h1, h2 = hash128([a])
    assert len(np.unique(np.asarray(h1))) == 3
    assert len(np.unique(np.asarray(h2))) == 3


def test_float64_hash_precision():
    # doubles differing only below f32 precision must hash differently
    x = jnp.asarray(np.array([1.0, 1.0 + 1e-12], np.float64))
    assert x.dtype == jnp.float64
    h = np.asarray(hash_columns([x]))
    assert h[0] != h[1]
    z = jnp.asarray(np.array([0.0, -0.0], np.float64))
    hz = np.asarray(hash_columns([z]))
    assert hz[0] == hz[1]
