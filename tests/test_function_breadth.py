"""Scalar function breadth (VERDICT r4 missing #9) + Debezium CDC
parsing (missing #6): the new math/bit/string functions evaluate with
SQL NULL conventions, and a Debezium-envelope source drives a
retracting MV end to end (op r = the CDC backfill lane)."""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_math_and_bit_functions_from_sql():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO t VALUES (12, 8), (7, 3)")
    out, _ = s.execute(
        "SELECT gcd(a, b) AS g, lcm(a, b) AS l, bit_and(a, b) AS ba, "
        "bit_or(a, b) AS bo, bit_xor(a, b) AS bx, "
        "bit_shift_left(a, 2) AS sl, trunc(a / 2) AS tr FROM t "
        "ORDER BY g"
    )
    assert list(out["g"]) == [1, 4]
    assert list(out["l"]) == [21, 24]
    assert list(out["ba"]) == [3, 8]
    assert list(out["bo"]) == [7, 12]
    assert list(out["bx"]) == [4, 4]
    assert list(out["sl"]) == [28, 48]


def test_trig_log_null_domains():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT)")
    s.execute("INSERT INTO t VALUES (1), (0)")
    out, _ = s.execute(
        "SELECT a, log2(a) AS l2, asin(a) AS asn FROM t ORDER BY a"
    )
    # log2(0) -> NULL (domain), asin in [-1,1] both fine
    l2 = list(out["l2"])
    assert l2[0] is None or (
        isinstance(l2[0], float) and np.isnan(l2[0])
    ), l2
    assert float(l2[1]) == 0.0


def test_string_function_breadth():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (w VARCHAR)")
    s.execute("INSERT INTO t VALUES ('hello world')")
    out, _ = s.execute(
        "SELECT split_part(w, ' ', 2) AS p, initcap(w) AS ic, "
        "lpad(w, 13, '*') AS lp, strpos(w, 'world') AS sp, "
        "repeat('ab', 2) AS rp, md5(w) AS h FROM t"
    )
    assert list(out["p"]) == ["world"]
    assert list(out["ic"]) == ["Hello World"]
    assert list(out["lp"]) == ["**hello world"]
    assert list(out["sp"]) == [7]
    assert list(out["rp"]) == ["abab"]
    import hashlib

    assert list(out["h"]) == [hashlib.md5(b"hello world").hexdigest()]


def test_debezium_cdc_source_to_retracting_mv(tmp_path):
    """Debezium envelope lines (snapshot reads + create/update/delete)
    through the connector framework: the downstream agg MV converges to
    the upstream table's state — the CDC backfill contract."""
    import jax.numpy as jnp

    from risingwave_tpu.connectors.framework import (
        DebeziumJsonParser,
        FileLogSource,
        GenericSourceExecutor,
    )
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.ops.agg import AggCall
    from risingwave_tpu.runtime.pipeline import Pipeline
    from risingwave_tpu.types import DataType, Field, Schema

    d = str(tmp_path)
    schema = Schema([Field("id", DataType.INT64), Field("v", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), DebeziumJsonParser(schema), table_id="cdc"
    )
    agg = HashAggExecutor(
        ("id",),
        (AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
        {"id": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id="cdc.agg",
    )
    mv = MaterializeExecutor(pk=("id",), columns=("s", "c"), table_id="cdc.mv")
    pipe = Pipeline([agg, mv])

    lines = [
        # snapshot (backfill) reads
        '{"op": "r", "after": {"id": 1, "v": 10}}',
        '{"op": "r", "after": {"id": 2, "v": 20}}',
        # streaming changes
        '{"op": "c", "after": {"id": 3, "v": 30}}',
        '{"op": "u", "before": {"id": 1, "v": 10}, "after": {"id": 1, "v": 15}}',
        '{"op": "d", "before": {"id": 2, "v": 20}}',
        '{"schema": {}, "payload": {"op": "c", "after": {"id": 4, "v": 40}}}',
        'garbage not json',
    ]
    FileLogSource.append(d, 0, lines)
    src.discover()
    for c in src.poll(64, 16):
        pipe.push(c)
    pipe.barrier()
    snap = {k[0]: v for k, v in mv.snapshot().items()}
    assert snap == {1: (15, 1), 3: (30, 1), 4: (40, 1)}


def test_upsert_json_parser(tmp_path):
    """Upsert-keyed JSON: NULL value deletes the key (kafka upsert
    model)."""
    from risingwave_tpu.connectors.framework import (
        FileLogSource,
        GenericSourceExecutor,
        UpsertJsonParser,
    )
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.runtime.pipeline import Pipeline
    from risingwave_tpu.types import DataType, Field, Schema

    d = str(tmp_path)
    schema = Schema([Field("id", DataType.INT64), Field("v", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), UpsertJsonParser(schema), table_id="up"
    )
    mv = MaterializeExecutor(pk=("id",), columns=("v",), table_id="up.mv")
    pipe = Pipeline([mv])
    FileLogSource.append(d, 0, [
        '{"key": {"id": 1}, "value": {"v": 5}}',
        '{"key": {"id": 2}, "value": {"v": 9}}',
        '{"key": {"id": 1}, "value": {"v": 7}}',   # upsert
        '{"key": {"id": 2}, "value": null}',        # delete
        '{"key": {"id": 3}, "value": {"v": 1}}',
        '{"key": {"id": 3}}',                       # null-omitting tombstone
    ])
    src.discover()
    for c in src.poll(64, 16):
        pipe.push(c)
    pipe.barrier()
    assert mv.snapshot() == {(1,): (7,)}


def test_protobuf_parser(tmp_path):
    """Protobuf-encoded source messages decode through a compiled
    message class (parser/protobuf analogue)."""
    import shutil
    import subprocess
    import sys

    if shutil.which("protoc") is None:
        pytest.skip("protoc not installed")
    pytest.importorskip("google.protobuf")
    proto_dir = str(tmp_path / "p")
    import os

    os.makedirs(proto_dir)
    with open(f"{proto_dir}/ev.proto", "w") as f:
        f.write(
            'syntax = "proto3";\n'
            "message Ev { int64 id = 1; int64 v = 2; }\n"
        )
    subprocess.check_call(
        ["protoc", f"--python_out={proto_dir}", f"-I{proto_dir}",
         "ev.proto"]
    )
    sys.path.insert(0, proto_dir)
    try:
        import ev_pb2
    finally:
        sys.path.remove(proto_dir)

    from risingwave_tpu.connectors.framework import ProtobufParser
    from risingwave_tpu.types import DataType, Field, Schema

    schema = Schema([Field("id", DataType.INT64), Field("v", DataType.INT64)])
    p = ProtobufParser(schema, ev_pb2.Ev)
    blob = ev_pb2.Ev(id=7, v=42).SerializeToString()
    assert p.parse(blob) == (7, 42)
    assert p.parse(blob.hex()) == (7, 42)  # text-carried form
    # proto3: zero-valued scalars are VALUES, not NULL
    assert p.parse(ev_pb2.Ev(id=0, v=0).SerializeToString()) == (0, 0)
    assert p.parse(b"\xff\xff garbage") is None


def test_round5_math_additions():
    import math

    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    s = SqlSession(Catalog({}), capacity=1 << 8)
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (5)")
    out, _ = s.execute(
        "SELECT factorial(v) AS f, asinh(v) AS a, hypot(v, v) AS h "
        "FROM t"
    )
    assert out["f"][0] == 120
    assert out["a"][0] == pytest.approx(math.asinh(5))
    assert out["h"][0] == pytest.approx(math.hypot(5, 5))
    # domain errors -> NULL, never a trap
    s.execute("INSERT INTO t VALUES (-3)")
    out, _ = s.execute("SELECT v, factorial(v) AS f FROM t ORDER BY v")
    assert out["f"][0] is None or bool(
        __import__("numpy").asarray(out.get("f__null", [0, 0]))[0]
    )
