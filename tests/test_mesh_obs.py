"""Mesh observability (ISSUE 18): per-shard barrier attribution,
exchange-cost matrix, hot-shard skew verdicts and the rw_ mesh tables,
on a REAL 8-virtual-device mesh (conftest forces the device count).

The contract under test: MESHPROF's per-shard accounting must cover
the sharded barrier wall it claims to explain, the (src, dst) routed-
row matrix must reconcile with the rows actually pushed, a seeded
constant-key workload must fire exactly one skew verdict naming the
shard the router hashes the key to, arming the profiler must never
change MV content (the counts ride the executors' own compiled step),
the rw_shards / rw_exchange relations must be SELECTable over pgwire
while a sharded pipeline streams, and a mid-stream kill must surface
as orphaned lanes exactly once — then leave the maps clean.
"""

import gc
import socket
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.frontend import PgServer, SqlSession
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.parallel.exchange import dest_shard
from risingwave_tpu.parallel.meshprof import MESHPROF, _key_fn_for
from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg
from risingwave_tpu.runtime.fragmenter import sharded_planned_mv
from risingwave_tpu.sql import Catalog, StreamPlanner

N_SHARDS = 8

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)

# a plain keyed agg: a constant auction routes EVERY row to one shard
# (q5's HOP would spread the constant over window_start shards)
HOT_SQL = (
    "CREATE MATERIALIZED VIEW hot AS "
    "SELECT auction, count(*) AS n FROM bid GROUP BY auction"
)


@pytest.fixture(autouse=True)
def _clean_meshprof():
    MESHPROF.disable()
    MESHPROF.reset_stats()
    yield
    MESHPROF.disable()
    MESHPROF.reset_stats()


@pytest.fixture(scope="module")
def catalog():
    assert len(jax.devices()) >= N_SHARDS
    return Catalog({"bid": BID_SCHEMA})


def _factory(catalog):
    return lambda: StreamPlanner(catalog, capacity=1 << 11)


def _bid_chunks(n=2, events=900, cap=1 << 10):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def _run_sharded(catalog, sql, chunks, name):
    mv = sharded_planned_mv(_factory(catalog), sql, N_SHARDS)
    MESHPROF.watch(mv.pipeline, name=name)
    try:
        for c in chunks:
            mv.pipeline.push(c)
            mv.pipeline.barrier()
        return mv.mview.snapshot()
    finally:
        mv.pipeline.close()


# ---------------------------------------------------------------------------
# attribution covers the wall
# ---------------------------------------------------------------------------


def test_attribution_covers_barrier_wall(catalog):
    MESHPROF.enable(probes=False)
    snap = _run_sharded(catalog, Q5_SQL, _bid_chunks(2), "q5")
    assert len(snap) > 0
    assert MESHPROF.errors == 0
    doc = MESHPROF.barriers[-1]
    # the phase split exists and sums to (almost exactly) the wall the
    # coverage fraction claims to explain
    phases = doc["phases_ms"]
    for key in ("pack", "route", "unpack", "shard_local"):
        assert key in phases, f"missing phase {key}"
    assert doc["wall_ms"] > 0
    attributed = sum(phases.values())
    assert attributed <= doc["wall_ms"] * 1.05
    assert 0.5 < doc["coverage_frac"] <= 1.05
    # one shard_local lane per shard, every one clocked
    assert len(doc["shard_local_ms"]) == N_SHARDS
    assert all(v >= 0 for v in doc["shard_local_ms"])


def test_exchange_matrix_reconciles_with_rows_pushed(catalog):
    MESHPROF.enable(probes=False)
    chunks = _bid_chunks(2)
    pushed = sum(int(np.asarray(c.valid).sum()) for c in chunks)
    _run_sharded(catalog, HOT_SQL, chunks, "hot")
    snap = MESHPROF.table_snapshot()
    ex = snap["exchange"]
    rows = np.asarray(ex["rows"], np.int64)
    assert rows.shape == (N_SHARDS, N_SHARDS)
    assert rows.min() >= 0
    # the keyed agg routes every valid row exactly once: its per-shard
    # rows_in_total reconciles with the chunks we pushed (the global
    # matrix is strictly larger — the sharded MV re-exchanges the agg's
    # output deltas)
    agg_tables = {
        tid: t for tid, t in snap["tables"].items() if "agg" in tid
    }
    assert agg_tables, f"no sharded agg table in {list(snap['tables'])}"
    agg_total = sum(
        sum(t["rows_in_total"]) for t in agg_tables.values()
    )
    assert agg_total == pushed
    assert int(rows.sum()) >= pushed
    # the cumulative prometheus counters carry the same total
    total = REGISTRY.counter("exchange_rows_total").total()
    assert int(total) >= pushed


# ---------------------------------------------------------------------------
# seeded skew -> one verdict naming the router's shard
# ---------------------------------------------------------------------------


def test_seeded_skew_fires_correct_verdict(catalog):
    MESHPROF.enable(probes=False)
    mv = sharded_planned_mv(_factory(catalog), HOT_SQL, N_SHARDS)
    MESHPROF.watch(mv.pipeline, name="hot")
    agg = next(
        ex for ex in mv.pipeline.executors if isinstance(ex, ShardedHashAgg)
    )
    skew_key = 1007
    expected = None
    n_skew_events = len(EVENT_LOG.events(kind="skew"))
    try:
        for c in _bid_chunks(2):
            auc = np.asarray(c.col("auction"))
            c = c.with_columns(
                auction=jnp.asarray(np.full(auc.shape, skew_key, auc.dtype))
            )
            if expected is None:
                kf = _key_fn_for(agg, "agg", None)
                dest = np.asarray(dest_shard(kf(c), N_SHARDS))
                expected = int(dest[np.asarray(c.valid)][0])
            mv.pipeline.push(c)
            mv.pipeline.barrier()
    finally:
        mv.pipeline.close()
    doc = MESHPROF.barriers[-1]
    sk = doc["skew"]
    assert sk is not None, "constant-key workload fired no skew verdict"
    assert sk["shard"] == expected
    assert sk["ratio"] >= 2.0
    # at most ONE verdict per barrier (the worst offender), surfaced on
    # the gauge and as a structured event
    assert isinstance(sk, dict)
    assert REGISTRY.gauge("shard_skew_frac").get() > 0
    events = EVENT_LOG.events(kind="skew")
    assert len(events) > n_skew_events
    assert events[-1]["shard"] == expected


# ---------------------------------------------------------------------------
# arming never changes results
# ---------------------------------------------------------------------------


def test_armed_vs_unarmed_bit_identity(catalog):
    chunks = _bid_chunks(2)
    # unarmed twin first (MESHPROF off: watch() is a no-op)
    unarmed = sharded_planned_mv(_factory(catalog), Q5_SQL, N_SHARDS)
    try:
        for c in chunks:
            unarmed.pipeline.push(c)
            unarmed.pipeline.barrier()
        want = unarmed.mview.snapshot()
    finally:
        unarmed.pipeline.close()
    MESHPROF.enable(probes=False)
    got = _run_sharded(catalog, Q5_SQL, chunks, "q5")
    assert got == want
    assert MESHPROF.errors == 0


# ---------------------------------------------------------------------------
# rw_shards / rw_exchange over pgwire, while streaming
# ---------------------------------------------------------------------------


class _PgClient:
    """Minimal protocol-v3 simple-query client (test_pgwire.py's,
    trimmed to what this test needs)."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        params = b"user\0test\0database\0dev\0\0"
        body = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self._drain_until_ready()

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            got = self.sock.recv(n - len(buf))
            assert got, "server closed"
            buf += got
        return buf

    def _drain_until_ready(self):
        msgs = []
        while True:
            head = self._recv_exact(5)
            (length,) = struct.unpack("!I", head[1:])
            msgs.append((head[:1], self._recv_exact(length - 4)))
            if head[:1] == b"Z":
                return msgs

    def query(self, sql):
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, err = [], None
        for tag, body in self._drain_until_ready():
            if tag == b"D":
                (ncols,) = struct.unpack("!h", body[:2])
                at, row = 2, []
                for _ in range(ncols):
                    (ln,) = struct.unpack("!i", body[at : at + 4])
                    at += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[at : at + ln].decode())
                        at += ln
                rows.append(tuple(row))
            elif tag == b"E":
                err = body
        return rows, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


def test_rw_mesh_tables_over_pgwire_during_streaming(catalog):
    MESHPROF.enable(probes=False)
    mv = sharded_planned_mv(_factory(catalog), HOT_SQL, N_SHARDS)
    MESHPROF.watch(mv.pipeline, name="hot")
    srv = PgServer(SqlSession(Catalog({}), capacity=1 << 8)).start()
    stop = threading.Event()
    failures = []

    def stream():
        try:
            gen = NexmarkGenerator(NexmarkConfig())
            deadline = time.monotonic() + 30
            while not stop.is_set() and time.monotonic() < deadline:
                c = gen.next_chunks(600, 1 << 10)["bid"]
                if c is None:
                    continue
                mv.pipeline.push(c)
                mv.pipeline.barrier()
        except Exception as e:  # noqa: BLE001 — surfaced via failures
            failures.append(repr(e))

    t = threading.Thread(target=stream, daemon=True)
    t.start()
    client = _PgClient(srv.port)
    try:
        shard_rows, ex_rows = [], []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            shard_rows, err = client.query("SELECT * FROM rw_shards")
            assert err is None, err
            ex_rows, err = client.query(
                "SELECT src, dst, rows_total FROM rw_exchange"
            )
            assert err is None, err
            if shard_rows and len(ex_rows) == N_SHARDS * N_SHARDS:
                break
            time.sleep(0.3)
        assert shard_rows, "rw_shards never materialized rows"
        assert len(ex_rows) == N_SHARDS * N_SHARDS
        # one row per (table, shard); shard ids dense 0..7
        shards = sorted({int(r[3]) for r in shard_rows})
        assert shards == list(range(N_SHARDS))
        assert sum(int(r[2]) for r in ex_rows) > 0
    finally:
        stop.set()
        t.join(timeout=60)
        client.close()
        srv.shutdown()
        mv.pipeline.close()
    assert not failures, failures
    assert MESHPROF.errors == 0


# ---------------------------------------------------------------------------
# kill + recover: orphaned lanes surface once, then the maps are clean
# ---------------------------------------------------------------------------


def test_kill_and_recover_leaves_no_orphaned_lanes(catalog):
    MESHPROF.enable(probes=False)
    mv = sharded_planned_mv(_factory(catalog), HOT_SQL, N_SHARDS)
    MESHPROF.watch(mv.pipeline, name="hot")
    chunks = _bid_chunks(2)
    mv.pipeline.push(chunks[0])
    mv.pipeline.barrier()
    # open a window, then kill WITHOUT a barrier: the lane is orphaned
    mv.pipeline.push(chunks[1])
    mv.pipeline.close()
    del mv
    gc.collect()
    stale = MESHPROF.orphans()
    assert stale, "mid-stream kill left no orphan evidence"
    # the audit prunes: a second sweep is clean
    assert MESHPROF.orphans() == []
    # "recover": a fresh watched pipeline runs clean on the same maps
    got = _run_sharded(catalog, HOT_SQL, chunks, "hot2")
    assert len(got) > 0
    assert MESHPROF.orphans() == []
    assert MESHPROF.errors == 0
