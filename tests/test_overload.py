"""Overload chaos + recovery composition (PR 17 acceptance): the
seeded OverloadChaosRunner must drive the degradation ladder through
its FULL arc and back with zero OOM and zero wedge, the device-state
ledger must never exceed the HBM budget, and the governed run's final
MV must be BIT-IDENTICAL to an unthrottled fault-free twin — lag,
never loss. Composition: a process kill + store outage landing while
the ladder is raised must recover exactly-once with credits re-derived
on the rebuilt runtime.

Replay a failing schedule: every failure message carries the seed;
rerun with ``RW_CHAOS_SEED=<seed>``.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import SourceManager, StreamingRuntime
from risingwave_tpu.runtime.memory_governor import (
    DEGRADED,
    NORMAL,
    SHEDDING,
    THROTTLED,
    OverloadLadder,
)
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.sim import OverloadChaosRunner, chaos_seed
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    CheckpointManager,
    StateDelta,
)

CAP = 1 << 9


class _Split:
    def __init__(self, split_id):
        self.split_id = split_id


class _StormSource(Checkpointable):
    """Deterministic skewed key storm, offset-addressed: event i draws
    its key from a cardinality that RAMPS with the offset — riding
    successive pow2 capacities of the agg's bucket lattice — mixed
    with a small hot set that keeps re-touching (and so re-faulting)
    cold-evicted groups. Both passes see the identical event prefix
    regardless of how admission chunks the polls (lag, never loss);
    offsets checkpoint like any connector's."""

    table_id = "storm.src"

    def __init__(self, seed, hot=48):
        self.seed = seed
        self.hot = hot
        self.offset = 0
        self._committed = 0
        self.splits = [_Split("storm-0")]

    def discover(self):
        pass

    def _key(self, i):
        h = (i * 2654435761 + self.seed * 40503) & 0xFFFFFFFF
        if h % 3 == 0:
            return h % self.hot
        card = 256 + i // 3
        return self.hot + (h % card)

    def poll(self, max_rows_per_split, capacity, only=None):
        n = int(max_rows_per_split)
        chunks = []
        while n > 0:
            take = min(n, capacity)
            idx = np.arange(self.offset, self.offset + take, dtype=np.int64)
            keys = np.asarray(
                [self._key(int(i)) for i in idx], np.int64
            )
            chunks.append(
                StreamChunk.from_numpy(
                    {"k": keys, "v": (idx % 97).astype(np.int64)},
                    capacity,
                )
            )
            self.offset += take
            n -= take
        return chunks

    # -- exactly-once: offsets travel with the checkpoint ---------------
    def checkpoint_delta(self):
        if self.offset == self._committed:
            return []
        self._committed = self.offset
        return [
            StateDelta(
                "storm.src",
                {"k": np.zeros(1, np.int64)},
                {"offset": np.asarray([self.offset], np.int64)},
                np.zeros(1, bool),
                ("k",),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols):
        # an empty committed table means NOTHING is durable: rewind to
        # zero, not "keep the live offset" (the rows behind it rolled
        # back with the failed commit and must replay)
        off = value_cols.get("offset") if value_cols else None
        self.offset = int(off[0]) if off is not None and len(off) else 0
        self._committed = self.offset


class _GovernedAgg:
    """The chaos workload: storm source -> HashAgg(count, sum) ->
    host-map MV, on a real StreamingRuntime (so the governor rides the
    barrier clock) with the agg wired to the cold tier (so relief can
    actually spill) and a commit lane that lands every K barriers (so
    durability LAGS the storm — the honest overload physics: dirty
    groups cannot spill until the commit catches up)."""

    K_COMMIT = 8

    def __init__(self, seed, store=None):
        self.agg = HashAggExecutor(
            group_keys=("k",),
            calls=(
                AggCall("count_star", None, "cnt"),
                AggCall("sum", "v", "s"),
            ),
            schema_dtypes={"k": jnp.int64, "v": jnp.int64},
            capacity=CAP,
            out_cap=1 << 11,
            table_id="storm.agg",
        )
        self.mview = MaterializeExecutor(
            pk=("k",), columns=("cnt", "s"), table_id="storm.mv"
        )
        self.runtime = StreamingRuntime(store=None)
        self.runtime.register("storm", Pipeline([self.agg, self.mview]))
        self.sources = SourceManager()
        self.src = _StormSource(seed)
        self.sources.register("bids", self.src)
        self.fragment_of = {"bids": "storm"}
        self.mgr = CheckpointManager(store if store is not None else MemObjectStore())
        self.agg.cold_reader = lambda keys: self.mgr.get_rows(
            "storm.agg", keys
        )
        self._epoch = 0

    @property
    def executors(self):
        return [self.agg, self.mview, self.src]

    def ingest(self, max_rows):
        if max_rows <= 0:
            return 0
        before = self.src.offset
        for ch in self.sources.poll(
            "bids", max_rows_per_split=max_rows, capacity=CAP
        ):
            self.runtime.push("storm", ch)
        return self.src.offset - before

    def barrier(self):
        self.runtime.barrier()
        self._epoch += 1
        if self._epoch % self.K_COMMIT == 0:
            self.mgr.commit_epoch(self._epoch << 16, self.executors)

    def drain(self):
        # flush the commit lane NOW: every group turns durable, so the
        # next relief pass can spill the whole working set
        self._epoch += 1
        self.mgr.commit_epoch(self._epoch << 16, self.executors)

    def mv(self):
        return self.mview.snapshot()


def test_overload_chaos_full_ladder_and_bit_identity():
    seed = chaos_seed(11)
    runner = OverloadChaosRunner(
        make=lambda: _GovernedAgg(seed),
        seed=seed,
        storm_rows=9_000,
        burst_rows=2_000,
    )
    got, want = runner.run()
    # the runner already asserted: every rung visited, back to NORMAL,
    # ledger <= budget on every governed barrier, no wedge
    assert got == want, (
        f"governed run diverged from the unthrottled twin "
        f"(seed={seed}; report={runner.report})"
    )
    assert len(want) > 200
    # admission actually bit: the governed pass lagged (more barriers
    # than the twin's storm epochs) and DEGRADED parked the source
    assert runner.report["parked_polls"] > 0, runner.report
    assert runner.report["spills"] > 0, runner.report


def test_overload_chaos_deterministic_replay():
    """Same seed -> same ladder walk and same report shape (the replay
    contract RW_CHAOS_SEED rests on)."""
    seed = chaos_seed(13)

    def once():
        r = OverloadChaosRunner(
            make=lambda: _GovernedAgg(seed),
            seed=seed,
            storm_rows=9_000,
            burst_rows=2_000,
            require_full_ladder=False,  # replay contract, not depth
        )
        got, want = r.run()
        assert got == want
        return r.report

    a, b = once(), once()
    assert a["states_seen"] == b["states_seen"]
    assert a["epochs"] == b["epochs"]
    assert a["budget"] == b["budget"]


# ---------------------------------------------------------------------------
# recovery x overload composition
# ---------------------------------------------------------------------------


def _arm(obj, budget, cooldown=2):
    gov = obj.runtime.memory_governor
    gov.budget_bytes = budget
    gov.enabled = True
    gov.ladder = OverloadLadder(
        throttle_at=0.30, shed_at=0.55, degrade_at=0.90, cooldown=cooldown
    )
    gov.spill_at = 0.5  # relieve aggressively: DEGRADED must not freeze
    obj.sources.attach_admission(gov.admission, obj.fragment_of)
    return gov


def test_recovery_during_throttle_keeps_exactly_once():
    """A process kill landing while the ladder is RAISED: rebuild from
    the store, re-arm the governor (fresh instance — the ladder is
    control state, not data state), and the run must still converge to
    the undisturbed twin's MV with credits re-derived on the rebuilt
    runtime."""
    seed = chaos_seed(17)
    rows_per_epoch, epochs = 1_200, 9

    def feed_all(obj, n_epochs, barrier_budget=300):
        barriers = 0
        for _ in range(n_epochs):
            want = rows_per_epoch
            while want > 0:
                got = obj.ingest(want)
                obj.barrier()  # parked barriers still run the commit
                want -= got    # lane, so relief eventually unfreezes
                barriers += 1
                if barriers > barrier_budget:
                    pytest.fail(
                        f"wedged: ingest stalled (seed={seed}, "
                        f"state={obj.runtime.memory_governor.ladder.state})"
                    )

    # undisturbed, unthrottled twin
    twin = _GovernedAgg(seed)
    feed_all(twin, epochs)
    twin.drain()
    twin.barrier()
    want = twin.mv()

    # governed run with a mid-run kill: everything live is abandoned,
    # the store's committed bytes are the only survivors
    disk = MemObjectStore()
    obj = _GovernedAgg(seed, store=disk)
    # budget ~ the twin's final footprint: tight enough to raise the
    # ladder well before the run completes
    peak = OverloadChaosRunner._footprint(twin.runtime)
    gov = _arm(obj, int(peak * 1.1))
    feed_all(obj, 4)
    assert gov.ladder.state != NORMAL, (
        f"ladder never raised before the kill (seed={seed}, "
        f"state={gov.ladder.state}, score={gov.ladder.last_score})"
    )
    raised_state = gov.ladder.state
    assert raised_state in (THROTTLED, SHEDDING, DEGRADED)

    # KILL: drop the object mid-window (uncommitted epochs vanish),
    # rebuild from the store, recover offsets + state, re-arm
    obj2 = _GovernedAgg(seed, store=disk)
    obj2.mgr.recover(obj2.executors)
    obj2._epoch = obj2.mgr.max_committed_epoch >> 16
    committed_offset = obj2.src.offset
    assert committed_offset < rows_per_epoch * 4, "kill landed too late"
    gov2 = _arm(obj2, int(peak * 1.1))
    # the epochs the kill rolled back replay from the anchored offset
    # (exactly-once: offsets travel with the commit)
    remaining = rows_per_epoch * epochs - committed_offset
    while remaining > 0:
        got = obj2.ingest(min(remaining, rows_per_epoch))
        obj2.barrier()
        remaining -= got
    obj2.drain()
    for _ in range(30):
        obj2.barrier()
        if gov2.ladder.state == NORMAL:
            break
    assert obj2.mv() == want, (
        f"recovery during {raised_state} diverged (seed={seed}; "
        f"rerun with RW_CHAOS_SEED={seed})"
    )
    # credits re-derived on the REBUILT runtime (fresh controller)
    assert gov2.admission.rederives > 0
    assert "storm" in gov2.admission.credits


def test_store_outage_during_shed_parks_then_recovers():
    """Store down while the ladder is raised: commits fail, relief
    cannot spill (nothing new turns durable), the ladder holds its
    rung — and once the store returns, the commit lands, spill frees
    the working set and the ladder descends. Exactly-once holds
    because each failed commit follows the manager's contract (mark
    flips are eager — a commit failure REQUIRES recover(), never a
    retry against live state): state rolls back to the last good
    manifest and the source offset rewinds with it (lag, never
    loss)."""
    seed = chaos_seed(19)
    twin = _GovernedAgg(seed)
    for _ in range(6):
        twin.ingest(1_000)
        twin.barrier()
    twin.drain()
    twin.barrier()
    want = twin.mv()
    peak = OverloadChaosRunner._footprint(twin.runtime)

    disk = MemObjectStore()
    obj = _GovernedAgg(seed, store=disk)
    down = {"on": False}

    class _Gate(MemObjectStore):
        def put(self, path, data):
            if down["on"]:
                raise RuntimeError("store down")
            return disk.put(path, data)

        def read(self, path):
            return disk.read(path)

        def read_range(self, path, off, length):
            return disk.read_range(path, off, length)

        def exists(self, path):
            return disk.exists(path)

        def list(self, prefix):
            return disk.list(prefix)

        def delete(self, path):
            return disk.delete(path)

    obj.mgr = CheckpointManager(_Gate())
    obj.agg.cold_reader = lambda keys: obj.mgr.get_rows("storm.agg", keys)
    gov = _arm(obj, int(peak * 1.1))

    target = 6_000
    down["on"] = True  # outage from the start: nothing turns durable
    barriers = 0
    failed_commits = 0
    while obj.src.offset < target:
        obj.ingest(min(1_000, target - obj.src.offset))
        try:
            obj.barrier()
        except RuntimeError:
            # the commit failed mid-outage. Contract (CheckpointManager
            # docstring): mark flips are eager, so live state is now
            # invalid — recover from the last good manifest. The source
            # offset rewinds with the commit, so the rolled-back rows
            # replay from their anchored offsets.
            failed_commits += 1
            obj.mgr.recover(obj.executors)
            obj._epoch = obj.mgr.max_committed_epoch >> 16
        barriers += 1
        if barriers == 12:
            down["on"] = False  # store returns mid-run
        if barriers > 200:
            pytest.fail(
                f"wedged under store outage (seed={seed}, "
                f"offset={obj.src.offset}, state={gov.ladder.state})"
            )
    assert failed_commits > 0, "outage never hit a commit"
    obj.drain()
    for _ in range(40):
        obj.barrier()
        if gov.ladder.state == NORMAL:
            break
    assert obj.mv() == want, f"store outage diverged (seed={seed})"
    assert gov.ladder.state == NORMAL
