"""WATERMARK FOR DDL + EMIT ON WINDOW CLOSE: the EOWC SQL surface.

Reference: watermark definitions on sources/tables + EmitOnWindowClose
plans. The planner inserts a self-driving WatermarkFilterExecutor at
every scan of a watermark-declared relation (late rows drop, the
generated watermark walks downstream each barrier) and windowed
grouped aggs keyed on the TVF window column get window_key state
cleaning — closed windows finalize (state freed) while the MV keeps
their final rows. Divergence (documented): intermediate updates are
visible before the close.
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke

W = 10_000  # tumble-ish window: size == slide


def _windowed_mv(s, eowc: bool):
    suffix = " EMIT ON WINDOW CLOSE" if eowc else ""
    s.execute(
        "CREATE MATERIALIZED VIEW w AS SELECT window_start, "
        "count(*) AS n FROM HOP(bids, ts, INTERVAL '10' SECONDS, "
        f"INTERVAL '10' SECONDS) GROUP BY window_start{suffix}"
    )


def test_watermark_cleans_closed_windows():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE TABLE bids (ts TIMESTAMP, v BIGINT, "
        "WATERMARK FOR ts AS ts - INTERVAL '0' SECONDS)"
    )
    _windowed_mv(s, eowc=False)
    from risingwave_tpu.executors.hash_agg import HashAggExecutor

    agg = next(
        ex
        for ex in s.runtime.fragments["w"].executors
        if isinstance(ex, HashAggExecutor)
    )
    assert agg.window_key == ("window_start", 0, False)
    # epoch 1: two windows; epoch 2 advances event time far ahead —
    # earlier windows CLOSE (state frees) but the MV keeps finals
    s.execute(f"INSERT INTO bids VALUES (1000, 1), ({W + 1000}, 1)")
    s.execute(f"INSERT INTO bids VALUES ({5 * W + 1}, 1)")
    out, _ = s.execute("SELECT window_start, n FROM w ORDER BY window_start")
    assert list(out["n"]) == [1, 1, 1]
    live = int(np.asarray(agg.table.live).sum())
    assert live <= 1, f"closed windows still hold state ({live} groups)"
    # LATE row for a closed window: dropped by the watermark filter
    s.execute("INSERT INTO bids VALUES (1001, 1)")
    out, _ = s.execute("SELECT window_start, n FROM w ORDER BY window_start")
    assert list(out["n"]) == [1, 1, 1]  # unchanged


def test_emit_on_window_close_suffix():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE TABLE bids (ts TIMESTAMP, v BIGINT, "
        "WATERMARK FOR ts AS ts - INTERVAL '2' SECONDS)"
    )
    _windowed_mv(s, eowc=True)
    s.execute(f"INSERT INTO bids VALUES (1000, 1), (2000, 1)")
    s.execute(f"INSERT INTO bids VALUES ({9 * W}, 1)")
    out, _ = s.execute("SELECT window_start, n FROM w ORDER BY window_start")
    assert list(out["n"]) == [2, 1]


def test_eowc_without_watermark_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE bids (ts TIMESTAMP, v BIGINT)")
    with pytest.raises(ValueError, match="WATERMARK"):
        _windowed_mv(s, eowc=True)


def test_source_watermark_ddl(tmp_path):
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE ev (ts TIMESTAMP, v BIGINT, "
        f"WATERMARK FOR ts AS ts - INTERVAL '1' SECOND) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    assert s.catalog.watermarks["ev"] == ("ts", 1000)
    _ = s.execute(
        "CREATE MATERIALIZED VIEW c AS SELECT window_start, count(*) "
        "AS n FROM HOP(ev, ts, INTERVAL '10' SECONDS, "
        "INTERVAL '10' SECONDS) GROUP BY window_start"
    )
    FileLogSource.append(d, 0, [
        '{"ts": 1000, "v": 1}', '{"ts": 50000, "v": 1}',
    ])
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute(
        "SELECT window_start, n FROM c ORDER BY window_start"
    )
    assert list(out["n"]) == [1, 1]
    s.execute("DROP MATERIALIZED VIEW c")
    s.execute("DROP SOURCE ev")
    assert "ev" not in s.catalog.watermarks


def test_watermark_survives_ddl_replay():
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = MemObjectStore()
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute(
        "CREATE TABLE bids (ts TIMESTAMP, v BIGINT, "
        "WATERMARK FOR ts AS ts - INTERVAL '3' SECONDS)"
    )
    rt.wait_checkpoints()
    s2 = SqlSession.restore(StreamingRuntime(store))
    assert s2.catalog.watermarks["bids"] == ("ts", 3000)


def test_retractions_pass_the_watermark_filter():
    """DELETE/UPDATE below the watermark must still reach downstream
    state (review finding r5: dropping them desynced MVs from DML'd
    tables); its no-op against already-cleaned state is fine."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE TABLE t (ts TIMESTAMP, v BIGINT, "
        "WATERMARK FOR ts AS ts - INTERVAL '0' SECONDS)"
    )
    s.execute("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
    s.execute("INSERT INTO t VALUES (1000, 1)")
    s.execute("INSERT INTO t VALUES (100000, 2)")  # wm -> 100000
    out, _ = s.execute("SELECT n FROM m")
    assert out["n"][0] == 2
    s.execute("DELETE FROM t WHERE ts = 1000")  # below the watermark
    out, _ = s.execute("SELECT n FROM m")
    assert out["n"][0] == 1  # the retraction arrived


def test_source_watermark_unit_inside_quotes(tmp_path):
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE ev (ts TIMESTAMP, "
        f"WATERMARK FOR ts AS TS - INTERVAL '1 second') "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    assert s.catalog.watermarks["ev"] == ("ts", 1000)
