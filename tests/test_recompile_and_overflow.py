"""jit-cache stability + hash-table overflow behavior (VERDICT r1
next-step 8): the fixed-capacity chunk design exists so a pipeline
compiles once and replays every epoch with ZERO recompiles; overflow
past MAX_PROBE must signal -1 (host rehash), never corrupt."""

import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors import hash_agg as hash_agg_mod
from risingwave_tpu.executors import hop_window as hop_mod
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.ops.hash_table import (
    MAX_PROBE,
    HashTable,
    lookup,
    lookup_or_insert,
)
from risingwave_tpu.queries.nexmark_q import build_q5_lite


def test_zero_recompiles_across_epochs():
    """After a warmup epoch, further epochs must not grow any jit
    cache (chunk.py's 'compile once, run every epoch' premise).
    Steady-state misses are asserted through the shared RecompileWatch
    (analysis/) — the same counter bench.py surfaces per query — and
    the executors' abstract input signatures must stay stable
    (SignatureWatch: the recompile-HAZARD detector)."""
    from risingwave_tpu.analysis.jax_sanitizer import (
        RecompileWatch,
        SignatureWatch,
    )
    from risingwave_tpu.metrics import REGISTRY

    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    gen = NexmarkGenerator(NexmarkConfig())
    watch = SignatureWatch().start()
    import risingwave_tpu.runtime.pipeline as pipeline_mod

    orig = pipeline_mod.SIGNATURES
    pipeline_mod.SIGNATURES = watch  # route walk_chain observations

    # STEADY state: the same key set every epoch (counts grow, state
    # capacity does not). Fresh keys per epoch would legitimately grow
    # the MV table past its load factor — a rebuild+recompile by
    # design, not the regression this guards against.
    bid = gen.next_chunks(1000, 1024)["bid"].select(
        ["auction", "date_time"]
    )

    def push_epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    try:
        push_epoch()  # warmup: compiles everything
        push_epoch()  # flush path warm too (first flush may add an entry)
        recompiles = RecompileWatch()
        recompiles.snapshot()
        watch.mark_stable()
        before = REGISTRY.counter("recompiles_total")._values.copy()
        for _ in range(4):
            push_epoch()
        # steady-state epochs trigger ZERO recompiles across every
        # registered step kernel...
        assert recompiles.deltas() == {}
        assert REGISTRY.counter("recompiles_total")._values == before
        # ...and zero shape instability (no recompile hazards)
        assert watch.report() == []
        # the original per-kernel checks stay as a cross-check
        assert hash_agg_mod._agg_step._cache_size() > 0
        assert hop_mod._hop_step._cache_size() > 0
    finally:
        watch.stop()
        pipeline_mod.SIGNATURES = orig


def test_overflow_past_max_probe_signals_minus_one():
    """Drive a table far past 50% load: rows must either resolve to a
    verified slot or return -1 — never a wrong slot."""
    cap = 256
    table = HashTable.create(cap, (jnp.dtype(jnp.int64),))
    rng = np.random.default_rng(3)
    all_keys = []
    got_minus_one = False
    for _ in range(4):
        keys = rng.integers(0, 1 << 40, 120).astype(np.int64)
        all_keys.append(keys)
        table, slots, found, inserted = lookup_or_insert(
            table, (jnp.asarray(keys),), jnp.ones(120, jnp.bool_)
        )
        slots = np.asarray(slots)
        got_minus_one |= bool((slots < 0).any())
        # every resolved slot stores EXACTLY the row's key
        stored = np.asarray(table.keys[0])
        ok = slots >= 0
        assert (stored[slots[ok]] == keys[ok]).all()
    # 480 inserts into 256 slots: overflow must have fired
    assert got_minus_one
    # and the table never "finds" a key it doesn't hold
    probe = rng.integers(1 << 41, 1 << 42, 64).astype(np.int64)
    _, found = lookup(table, (jnp.asarray(probe),), jnp.ones(64, jnp.bool_))
    assert not bool(np.asarray(found).any())


def test_agg_executor_grows_past_initial_capacity():
    """Executor-level: sustained distinct keys trigger host rehash; the
    final state matches a fresh big-table run exactly."""
    from risingwave_tpu.executors import Barrier, HashAggExecutor
    from risingwave_tpu.executors.base import Epoch

    calls = (AggCall("count_star", None, "cnt"),)
    small = HashAggExecutor(
        ("k",), calls, {"k": jnp.int64}, capacity=1 << 6, out_cap=1 << 10
    )
    big = HashAggExecutor(
        ("k",), calls, {"k": jnp.int64}, capacity=1 << 12, out_cap=1 << 10
    )
    rng = np.random.default_rng(5)
    for _ in range(6):
        keys = rng.integers(0, 500, 100).astype(np.int64)
        chunk = StreamChunk.from_numpy({"k": keys}, 128)
        small.apply(chunk)
        big.apply(chunk)

    def snap(ex):
        outs = ex.on_barrier(Barrier(Epoch(0, 1)))
        d = {}
        for out in outs:
            o = out.to_numpy(with_ops=True)
            for i in range(len(o["__op__"])):
                d[int(o["k"][i])] = int(o["cnt"][i])
        return d

    assert small.table.capacity > (1 << 6)
    assert snap(small) == snap(big)


def test_float64_sum_precision():
    """FLOAT64 must really be f64 on device (r1 ADVICE): summing 10^6
    doubles stays within f64 tolerance of the numpy oracle."""
    from risingwave_tpu.executors import Barrier, HashAggExecutor
    from risingwave_tpu.executors.base import Epoch

    rng = np.random.default_rng(7)
    calls = (AggCall("sum", "x", "total"),)
    ex = HashAggExecutor(
        ("g",), calls, {"g": jnp.int64, "x": jnp.float64}, capacity=1 << 4
    )
    total = 0.0
    vals_all = []
    for _ in range(100):
        x = rng.uniform(0.1, 1e9, 10_000)
        vals_all.append(x)
        chunk = StreamChunk.from_numpy(
            {"g": np.zeros(10_000, np.int64), "x": x}, 1 << 14
        )
        ex.apply(chunk)
    outs = ex.on_barrier(Barrier(Epoch(0, 1)))
    got = None
    for out in outs:
        d = out.to_numpy(with_ops=True)
        if len(d["__op__"]):
            got = float(d["total"][-1])
    want = float(np.sum(np.concatenate(vals_all)))
    assert got == pytest.approx(want, rel=1e-12)


def test_int64_fingerprints_distinguish_high_bits():
    """int64 keys differing only above bit 31 must hash apart (r1
    weak #6: folded 32-bit lanes weakened fingerprints)."""
    from risingwave_tpu.ops.hashing import hash128

    base = np.int64(5)
    variants = np.array(
        [base + (np.int64(1) << s) for s in range(32, 63)], np.int64
    )
    keys = np.concatenate([[base], variants])
    h1, h2 = hash128((jnp.asarray(keys),))
    pairs = set(zip(np.asarray(h1).tolist(), np.asarray(h2).tolist()))
    assert len(pairs) == len(keys)  # no collisions among 32 variants


def test_bench_shape_stacked_scan_at_bench_capacity():
    """The BENCH's exact device shapes in the suite (VERDICT r2/r3: the
    r02 kernel-fault class only ever fired at bench scale): capacity
    2^16 agg state fed by stacked per-epoch scans in both agg modes."""
    import functools

    from risingwave_tpu.executors.hop_window import hop_step_fn
    from risingwave_tpu.parallel.sharded_agg import stack_chunks

    q5 = build_q5_lite(capacity=1 << 16, state_cleaning=False)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    pre = functools.partial(
        hop_step_fn,
        ts_col="date_time",
        size_ms=10_000,
        slide_ms=2_000,
        out_start="window_start",
    )
    total = 0
    for mode in ("reduce", "scan"):  # both bench agg modes
        for _ in range(2):
            chunks = []
            done = 0
            while done < 6_000:
                ev = gen.next_events(2048)
                done += 2048
                bid = ev["bid"]
                if bid and len(bid["auction"]):
                    chunks.append(
                        StreamChunk.from_numpy(
                            {
                                "auction": bid["auction"],
                                "date_time": bid["date_time"],
                            },
                            2048,
                        )
                    )
                    total += len(bid["auction"])
            q5.agg.apply_stacked(stack_chunks(chunks), pre=pre, mode=mode)
            q5.pipeline.barrier()
    assert total > 5_000
    assert q5.mview.snapshot()
