"""Fragment-scoped partial recovery (the blast-radius contract).

Reference contrast: the reference's failed-barrier recovery
(barrier/recovery.rs:353) restarts the WHOLE dataflow from
max_committed_epoch. Here an actor death is attributed to its fragment
by the graph supervisor (runtime/graph.py), only the downstream-closure
blast radius is fenced/rebuilt/restored/replayed, and every un-faulted
MV keeps its live state and keeps answering query() through the
recovery window. The escalation ladder (partial x3 -> full -> raise)
and the degraded-mode composition (store down => recovery DEFERS, never
wedges) are asserted here too.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.resilience import (
    CircuitBreaker,
    RetryPolicy,
    TransientStoreError,
)
from risingwave_tpu.runtime.fragmenter import (
    GraphPipeline,
    PartitionedStateView,
)
from risingwave_tpu.runtime.graph import (
    FragmentSpec,
    GraphRuntime,
    _default_barrier_timeout,
)
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sim import CrashingExecutor
from risingwave_tpu.storage.object_store import MemObjectStore, ObjectStore

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# workload builders
# ---------------------------------------------------------------------------


def _mk_agg(tid):
    return HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
        schema_dtypes={"k": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id=tid,
    )


def _mk_mview(tid):
    return MaterializeExecutor(pk=("k",), columns=("s", "c"), table_id=tid)


def build_singleton_mv(name, crash=None):
    """One-fragment graph MV (blast radius == whole graph: any partial
    recovery of it is a full-graph rebuild, scoped at the MV level)."""
    agg, mv = _mk_agg(f"{name}.agg"), _mk_mview(f"{name}.mview")
    chain = ([crash] if crash is not None else []) + [agg, mv]
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec(
            "work", lambda i, c=tuple(chain): list(c), inputs=[("src", 0)]
        ),
    ]
    gp = GraphPipeline(
        specs, {"single": "src"}, "work", chain,
        ckpt_fragments=["work"] * len(chain),
    )
    return gp, mv


def build_parallel_mv(name, crash):
    """src --hash(k)--> par x2 --> mat, with the crash executor inside
    par#0's chain: the blast radius is {par, mat}, the src actors stay
    alive — the scoped INTRA-graph rebuild path."""
    aggs = [_mk_agg(f"{name}.agg") for _ in range(2)]
    mv = _mk_mview(f"{name}.mview")
    chains = [[crash, aggs[0]], [aggs[1]]]
    specs = [
        FragmentSpec("src", lambda i: [], dispatch=("hash", ["k"])),
        FragmentSpec(
            "par", lambda i: list(chains[i]), inputs=[("src", 0)],
            parallelism=2,
        ),
        FragmentSpec("mat", lambda i: [mv], inputs=[("par", 0)]),
    ]
    view = PartitionedStateView(aggs, {f"{name}.agg": (0,)})
    gp = GraphPipeline(
        specs, {"single": "src"}, "mat", [view, mv],
        ckpt_fragments=["par", "mat"],
    )
    return gp, mv


def _chunks(seed, n_epochs):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_epochs):
        n = int(rng.integers(4, 12))
        ks = rng.integers(0, 8, n).astype(np.int64)
        vs = rng.integers(0, 50, n).astype(np.int64)
        out.append(StreamChunk.from_numpy({"k": ks, "v": vs}, 16))
    return out


def _fault_free(chunks):
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    gpa, mva = build_singleton_mv("mv_a")
    gpb, mvb = build_parallel_mv("mv_b", CrashingExecutor("idle"))
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    for c in chunks:
        rt.push("mv_a", c)
        rt.push("mv_b", c)
        rt.barrier()
    rt.wait_checkpoints()
    want = dict(mva.snapshot()), dict(mvb.snapshot())
    gpa.close()
    gpb.close()
    return want


# ---------------------------------------------------------------------------
# headline: scoped failover keeps the healthy MV hot
# ---------------------------------------------------------------------------


def test_partial_recovery_scopes_to_failed_fragment():
    """A seeded actor crash in mv_b's parallel fragment recovers ONLY
    mv_b's subtree (partial event, recovery_scope_fragments < total),
    while mv_a answers query() INSIDE the recovery window with no
    barrier gap anywhere near RW_BARRIER_TIMEOUT_S; post-recovery both
    MVs are bit-identical to a fault-free run."""
    chunks = _chunks(11, 6)
    want_a, want_b = _fault_free(chunks)

    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    crash = CrashingExecutor("mv_b")
    gpa, mva = build_singleton_mv("mv_a")
    gpb, mvb = build_parallel_mv("mv_b", crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)

    graph_b0 = gpb.graph
    src_actors0 = [a for a in gpb.graph.actors if a.actor_name.startswith("src#")]
    seq0 = max((e["seq"] for e in EVENT_LOG.events()), default=0)
    scope_hist0 = REGISTRY.histogram("recovery_downtime_ms").count(
        fragment="mv_a"
    )

    # mid-recovery probe: fires inside the recovery window, right after
    # mv_b's subtree restored and before it rejoins — the healthy MV
    # must answer query() NOW
    window_queries = []
    expect_a_keys = set()

    def _query_healthy():
        snap = mva.snapshot()
        window_queries.append(len(snap))
        assert set(snap) == expect_a_keys  # mv_a state is LIVE, not rolled back

    sync_point.activate("partial_recovery:mv_b", _query_healthy)
    barrier_gaps = []
    try:
        t_last = time.monotonic()
        for i, c in enumerate(chunks):
            if i == 3:
                crash.arm("apply", after=1)  # mid-epoch murder
            rt.push("mv_a", c)
            rt.push("mv_b", c)
            for k in np.asarray(c.col("k"))[np.asarray(c.valid)].tolist():
                expect_a_keys.add((int(k),))
            before = rt.mgr.max_committed_epoch
            rt.barrier()
            if rt.mgr.max_committed_epoch == before:  # recovered, not committed
                assert rt.last_recovery_mode == "partial"
                rt.barrier()  # replayed window commits at the next boundary
                assert rt.mgr.max_committed_epoch > before
            barrier_gaps.append(time.monotonic() - t_last)
            t_last = time.monotonic()
        rt.wait_checkpoints()
    finally:
        sync_point.deactivate("partial_recovery:mv_b")

    # the crash fired exactly once and recovery was PARTIAL, not full
    assert crash.kills == 1
    assert rt.auto_recoveries == 1 and rt.partial_recoveries == 1
    evs = [e for e in EVENT_LOG.events("recovery") if e["seq"] > seq0]
    modes = [e["mode"] for e in evs]
    assert "partial" in modes and "partial_done" in modes
    assert "auto" not in modes and "restore" not in modes  # never full
    partial = next(e for e in evs if e["mode"] == "partial")
    assert partial["fragments"] == ["mv_b"]
    assert partial["scope"] == 1 < partial["total"] == 2
    assert REGISTRY.gauge("recovery_scope_fragments").get() == 1.0

    # the healthy MV answered query() inside the window...
    assert window_queries and window_queries[0] > 0
    # ...and never saw a barrier gap approaching the deadman
    assert max(barrier_gaps) < _default_barrier_timeout()
    # recovery downtime is attributed per affected MV only
    assert REGISTRY.histogram("recovery_downtime_ms").count(fragment="mv_b") >= 1
    assert (
        REGISTRY.histogram("recovery_downtime_ms").count(fragment="mv_a")
        == scope_hist0
    )

    # the rebuild was SCOPED: same graph object, src actors survived
    assert gpb.graph is graph_b0
    assert all(a.is_alive() for a in src_actors0)
    # the healthy MV's graph was never touched
    assert all(a.is_alive() for a in gpa.graph.actors)

    # bit-identical convergence for BOTH MVs
    assert dict(mva.snapshot()) == want_a
    assert dict(mvb.snapshot()) == want_b
    gpa.close()
    gpb.close()


def test_manual_scoped_recover_fragments_kwarg():
    """recover(fragments=...) restores + replays ONLY the named
    fragments; the other MV's live (uncommitted) state is untouched."""
    chunks = _chunks(23, 3)
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=False
    )
    gpa, mva = build_singleton_mv("mv_a")
    gpb, mvb = build_parallel_mv("mv_b", CrashingExecutor("idle"))
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    for c in chunks[:2]:
        rt.push("mv_a", c)
        rt.push("mv_b", c)
        rt.barrier()
    # push an UNCOMMITTED chunk, then scoped-recover mv_b only
    rt.push("mv_a", chunks[2])
    rt.push("mv_b", chunks[2])
    rt.recover(fragments=["mv_b"])
    rt.barrier()
    rt.wait_checkpoints()
    want_a, want_b = _fault_free(chunks)
    assert dict(mvb.snapshot()) == want_b  # replayed from the buffer
    assert dict(mva.snapshot()) == want_a  # live state never rolled back
    with pytest.raises(KeyError):
        rt.recover(fragments=["nope"])
    gpa.close()
    gpb.close()


# ---------------------------------------------------------------------------
# escalation ladder: partial x3 -> full -> deterministic-fault raise
# ---------------------------------------------------------------------------


def test_escalation_partial_to_full_to_raise():
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    crash = CrashingExecutor("boom")
    gpa, _mva = build_singleton_mv("mv_a")
    gpb, _mvb = build_singleton_mv("mv_b", crash=crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    rng = np.random.default_rng(7)

    def chunk():
        n = int(rng.integers(4, 10))
        return StreamChunk.from_numpy(
            {"k": rng.integers(0, 4, n).astype(np.int64),
             "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
        )

    for _ in range(2):
        c = chunk()
        rt.push("mv_a", c)
        rt.push("mv_b", c)
        rt.barrier()
    seq0 = max((e["seq"] for e in EVENT_LOG.events()), default=0)
    crash.always = True  # DETERMINISTIC fault: every barrier kills
    with pytest.raises(RuntimeError, match="deterministic"):
        for _ in range(10):
            c = chunk()
            rt.push("mv_a", c)
            rt.push("mv_b", c)
            rt.barrier()
    modes = [
        e["mode"]
        for e in EVENT_LOG.events("recovery")
        if e["seq"] > seq0
    ]
    # three consecutive partial attempts, then full recoveries, then
    # the raise (the full path's consecutive budget)
    assert modes.count("partial") == 3
    assert modes.count("auto") == 3
    assert modes.index("auto") > modes.index("partial")
    gpa.close()
    gpb.close()


# ---------------------------------------------------------------------------
# degraded-mode composition: store down => partial recovery DEFERS
# ---------------------------------------------------------------------------


class _DownableStore(ObjectStore):
    """Store with a hard-down switch (transient classification, so the
    resilience layer absorbs it until the budget/ breaker trips)."""

    def __init__(self, inner):
        self.inner = inner
        self.down = False

    def _gate(self):
        if self.down:
            raise TransientStoreError("store down (injected)")

    def put(self, p, d):
        self._gate()
        self.inner.put(p, d)

    def read(self, p):
        self._gate()
        return self.inner.read(p)

    def read_range(self, p, o, ln):
        self._gate()
        return self.inner.read_range(p, o, ln)

    def exists(self, p):
        self._gate()
        return self.inner.exists(p)

    def list(self, p):
        self._gate()
        return self.inner.list(p)

    def delete(self, p):
        self._gate()
        self.inner.delete(p)


def test_partial_recovery_defers_while_store_unavailable():
    """Actor crash while the store is DOWN: the restore cannot read the
    checkpoint, so partial recovery defers — the blast radius stays
    fenced (inputs park in the replay buffer), healthy fragments keep
    committing (degraded spill) and answering query(), and the barrier
    clock completes the recovery once the store heals. Nothing wedges,
    nothing double-applies."""
    down = _DownableStore(MemObjectStore())
    rt = StreamingRuntime(
        down,
        async_checkpoint=False,
        auto_recover=True,
        retry_policy=RetryPolicy(
            max_attempts=2, base_backoff_s=1e-4, max_backoff_s=1e-3,
            deadline_s=0.2,
        ),
        breaker=CircuitBreaker(
            "object_store", failure_threshold=1, cooldown_s=0.05
        ),
    )
    crash = CrashingExecutor("boom")
    gpa, mva = build_singleton_mv("mv_a")
    gpb, mvb = build_singleton_mv("mv_b", crash=crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    fed = []
    rng = np.random.default_rng(5)

    def feed():
        n = int(rng.integers(4, 10))
        c = StreamChunk.from_numpy(
            {"k": rng.integers(0, 4, n).astype(np.int64),
             "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
        )
        fed.append(c)
        rt.push("mv_a", c)
        rt.push("mv_b", c)

    for _ in range(2):
        feed()
        rt.barrier()
    down.down = True
    crash.arm("apply", after=1)
    feed()
    rt.barrier()
    assert rt._pending_partial is not None  # deferred, not wedged
    assert rt.last_recovery_mode == "partial"
    # healthy MV keeps flowing and answering while deferred
    before_keys = len(mva.snapshot())
    feed()
    rt.barrier()
    assert len(mva.snapshot()) >= before_keys > 0
    # heal -> the barrier clock resumes and completes the recovery
    down.down = False
    deadline = time.time() + 20
    while rt._pending_partial is not None and time.time() < deadline:
        time.sleep(0.06)  # past the breaker cooldown
        rt.barrier()
    assert rt._pending_partial is None, "deferred recovery never resumed"
    rt.barrier()
    rt.wait_checkpoints()
    # convergence against a fault-free twin over the same feed
    rt2 = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    gpa2, mva2 = build_singleton_mv("mv_a")
    gpb2, mvb2 = build_singleton_mv("mv_b")
    rt2.register("mv_a", gpa2)
    rt2.register("mv_b", gpb2)
    for c in fed:
        rt2.push("mv_a", c)
        rt2.push("mv_b", c)
        rt2.barrier()
    assert dict(mvb.snapshot()) == dict(mvb2.snapshot())
    assert dict(mva.snapshot()) == dict(mva2.snapshot())
    for gp in (gpa, gpb, gpa2, gpb2):
        gp.close()


def test_deferred_resume_respects_per_fragment_durable_coverage():
    """checkpoint_frequency > 1: a fenced fragment's non-checkpoint
    barrier markers are NOT durably covered, and healthy-only commits
    during the deferral advance the global epoch past them. The resume
    must replay from the FRAGMENT's durable coverage, not the global
    committed epoch — otherwise the non-checkpoint window is silently
    lost."""
    down = _DownableStore(MemObjectStore())
    rt = StreamingRuntime(
        down,
        async_checkpoint=False,
        auto_recover=True,
        checkpoint_frequency=2,
        retry_policy=RetryPolicy(
            max_attempts=2, base_backoff_s=1e-4, max_backoff_s=1e-3,
            deadline_s=0.2,
        ),
        breaker=CircuitBreaker(
            "object_store", failure_threshold=1, cooldown_s=0.05
        ),
    )
    crash = CrashingExecutor("boom")
    gpa, mva = build_singleton_mv("mv_a")
    gpb, mvb = build_singleton_mv("mv_b", crash=crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    fed = []
    rng = np.random.default_rng(29)

    def feed():
        n = int(rng.integers(4, 10))
        c = StreamChunk.from_numpy(
            {"k": rng.integers(0, 4, n).astype(np.int64),
             "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
        )
        fed.append(c)
        rt.push("mv_a", c)
        rt.push("mv_b", c)

    for _ in range(3):  # barriers 1(n) 2(ckpt) 3(n): marker 3 un-covered
        feed()
        rt.barrier()
    down.down = True
    crash.arm("apply", after=1)
    feed()
    rt.barrier()  # crash -> partial defers (store down)
    assert rt._pending_partial is not None
    # healthy-only barriers while deferred (commits degrade -> spill)
    for _ in range(2):
        feed()
        rt.barrier()
    down.down = False
    deadline = time.time() + 20
    while rt._pending_partial is not None and time.time() < deadline:
        time.sleep(0.06)
        rt.barrier()  # spill replays durably FIRST, then the resume
    assert rt._pending_partial is None
    rt.barrier()
    rt.wait_checkpoints()
    rt2 = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, checkpoint_frequency=2
    )
    gpa2, mva2 = build_singleton_mv("mv_a")
    gpb2, mvb2 = build_singleton_mv("mv_b")
    rt2.register("mv_a", gpa2)
    rt2.register("mv_b", gpb2)
    for c in fed:
        rt2.push("mv_a", c)
        rt2.push("mv_b", c)
        rt2.barrier()
    rt2.wait_checkpoints()
    assert dict(mvb.snapshot()) == dict(mvb2.snapshot())
    assert dict(mva.snapshot()) == dict(mva2.snapshot())
    for gp in (gpa, gpb, gpa2, gpb2):
        gp.close()


def test_manual_scoped_recover_refuses_lost_replay_window():
    """recover(fragments=...) must enforce the same replay-window guard
    as the auto path: a fragment whose buffer overflowed cannot be
    scope-recovered (that would silently drop its un-durable window)."""
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    gpa, _ = build_singleton_mv("mv_a")
    gpb, _ = build_singleton_mv("mv_b")
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    c = StreamChunk.from_numpy(
        {"k": np.array([1], np.int64), "v": np.array([2], np.int64)}, 16
    )
    rt.push("mv_a", c)
    rt.push("mv_b", c)
    rt.barrier()
    rt.wait_checkpoints()
    # simulate the overflow: window lost until re-anchored durably
    with rt._replay_lock:
        rt._replay["mv_b"] = []
        rt._replay_floor["mv_b"] = None
    with pytest.raises(RuntimeError, match="replay window lost"):
        rt.recover(fragments=["mv_b"])
    gpa.close()
    gpb.close()


# ---------------------------------------------------------------------------
# satellite: the graph supervisor's attribution + fencing, unit-level
# ---------------------------------------------------------------------------


def test_supervisor_blast_radius_and_stall_provenance():
    """Fragment attribution + downstream-closure blast radius land in
    the supervisor state AND the stall snapshot (debuggable from the
    artifact alone); fragments outside the blast keep their actors."""

    class Boom:
        def apply(self, chunk):
            return [chunk]

        def on_barrier(self, b):
            raise ValueError("kaboom")

        def on_watermark(self, wm):
            return wm, []

        def emit_watermark(self):
            return None

        def pure_step(self):
            return None

        def finish_barrier(self):
            pass

        def lint_info(self):
            return None

    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec("mid", lambda i: [Boom()], inputs=[("src", 0)]),
            FragmentSpec("leaf", lambda i: [], inputs=[("mid", 0)]),
            FragmentSpec("other", lambda i: [], inputs=[("src", 0)]),
        ],
        epoch_batch=False,
    ).start()
    assert g.blast_radius("mid") == {"mid", "leaf"}
    assert g.downstream_closure("src") == {"mid", "leaf", "other"}
    with pytest.raises(RuntimeError):
        g.inject_barrier(timeout=30)
    snap = g.stall_snapshot()
    assert snap["failed_fragments"] == ["mid"]
    assert snap["blast_radius"] == ["leaf", "mid"]
    assert any("kaboom" in v for v in snap["actor_errors"].values())
    by_name = {a["actor"]: a for a in snap["actors"]}
    assert by_name["mid#0"]["fragment"] == "mid"
    assert by_name["leaf#0"]["fenced"] and by_name["mid#0"]["fenced"]
    assert not by_name["other#0"]["fenced"]
    # fragments OUTSIDE the blast radius keep their actors running
    deadline = time.time() + 5
    while time.time() < deadline and by_name["leaf#0"]["alive"]:
        time.sleep(0.02)
        by_name = {a["actor"]: a for a in g.stall_snapshot()["actors"]}
    assert not by_name["leaf#0"]["alive"]  # fenced subtree exited
    assert by_name["other#0"]["alive"] and by_name["src#0"]["alive"]
    g.stop()


def test_scoped_rebuild_rejects_unsound_scopes():
    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec("a", lambda i: [], inputs=[("src", 0)]),
            FragmentSpec("b", lambda i: [], inputs=[("a", 0)]),
        ],
        epoch_batch=False,
    ).start()
    with pytest.raises(ValueError, match="source"):
        g.rebuild_scoped({"src", "a", "b"})
    with pytest.raises(ValueError, match="downstream-closed"):
        g.rebuild_scoped({"a"})  # leaves b consuming a dead edge
    with pytest.raises(KeyError):
        g.rebuild_scoped({"ghost"})
    g.stop()


# ---------------------------------------------------------------------------
# satellite: stall-watchdog timers never orphan across recoveries
# ---------------------------------------------------------------------------


def _watchdog_threads():
    return [
        t
        for t in threading.enumerate()
        if t.name == "rw-stall-watchdog" and t.is_alive()
    ]


def _sentinel_threads():
    return [
        t
        for t in threading.enumerate()
        if t.name.startswith("rw-sentinel") and t.is_alive()
    ]


def test_no_orphan_stall_watchdog_timers_across_recoveries():
    """Every barrier arms a stall-watchdog Timer; success, partial
    recovery, full recovery, AND the escalation raise must all cancel
    it — repeated recoveries may not pile up live timers. Same audit
    for profiler capture windows and (PR 8) the blackbox sentinel: a
    capture open when the fault fires must be closed by recovery, the
    sentinel's wedge-capture window must never survive a recovery, and
    stopping the sentinel must leave no rw-sentinel threads."""
    from risingwave_tpu import blackbox
    from risingwave_tpu.profiler import PROFILER

    # a healthy sentinel rides across every recovery below — a FRESH
    # instance swapped in for the singleton, so the tuned heartbeat/
    # interval never leak into later tests (restored in the finally)
    saved_sentinel = blackbox.SENTINEL
    blackbox.SENTINEL = blackbox.DeviceSentinel()
    blackbox.SENTINEL.start(
        interval_s=0.05, slow_ms=1e6, deadline_s=5.0,
        heartbeat_fn=lambda: None,
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.stall_dump_after_s = 30.0  # real timers, armed per barrier
    PROFILER.enable(fence=False)
    PROFILER.start_capture(tag="orphan-audit")  # open across the faults
    crash = CrashingExecutor("boom")
    gpa, _ = build_singleton_mv("mv_a")
    gpb, _ = build_singleton_mv("mv_b", crash=crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    rng = np.random.default_rng(9)
    try:
        for i in range(6):
            n = int(rng.integers(4, 10))
            c = StreamChunk.from_numpy(
                {"k": rng.integers(0, 4, n).astype(np.int64),
                 "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
            )
            if i in (2, 4):
                crash.arm("apply", after=1)
            rt.push("mv_a", c)
            rt.push("mv_b", c)
            rt.barrier()
        # drive the raise path too (its finally must also cancel)
        crash.always = True
        with pytest.raises(RuntimeError):
            for _ in range(10):
                rt.push("mv_b", c)
                rt.barrier()
        assert rt.auto_recoveries >= 3
        deadline = time.time() + 5
        while time.time() < deadline and _watchdog_threads():
            time.sleep(0.05)  # canceled Timers exit, not at expiry
        assert _watchdog_threads() == []
        # no orphaned profiler capture windows either: the first
        # recovery closed the pre-fault window, none re-opened
        assert PROFILER.active_captures == []
        # blackbox sentinel audit: recoveries never left a wedge-
        # capture window open, no spurious wedge was armed, and the
        # sentinel kept beating across every recovery
        assert blackbox.SENTINEL.abort_capture() == 0
        assert blackbox.SENTINEL.wedged_error() is None
        assert blackbox.SENTINEL.beats > 0
    finally:
        PROFILER.disable()
        PROFILER.reset()
        gpa.close()
        gpb.close()
        blackbox.SENTINEL.stop()
        blackbox.SENTINEL = saved_sentinel
    deadline = time.time() + 5
    while time.time() < deadline and _sentinel_threads():
        time.sleep(0.05)
    assert _sentinel_threads() == []  # stop() reaps sentinel threads
