"""Batch spill-to-disk aggregation (VERDICT r4 weak #8 depth item;
reference: src/batch/src/spill/): over-threshold GROUP BY inputs
hash-partition to disk and aggregate partition-by-partition, exactly."""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_spilled_group_by_matches_in_memory():
    s = SqlSession(Catalog({}), capacity=1 << 12)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    rng = np.random.default_rng(2)
    ks = rng.integers(0, 300, 6000).tolist()
    vs = rng.integers(-50, 50, 6000).tolist()
    for at in range(0, 6000, 500):
        vals = ", ".join(
            f"({k}, {v})"
            for k, v in zip(ks[at : at + 500], vs[at : at + 500])
        )
        s.execute(f"INSERT INTO t VALUES {vals}")

    sql = (
        "SELECT k, count(*) AS c, sum(v) AS sv, min(v) AS mn "
        "FROM t GROUP BY k ORDER BY k"
    )
    want, _ = s.execute(sql)
    s.execute("SET batch_spill_threshold = 1000")
    got, _ = s.execute(sql)
    assert s.batch.last_spill_partitions > 1, "never spilled"
    for nm in ("k", "c", "sv", "mn"):
        assert list(got[nm]) == list(want[nm]), nm
    # NULL agg outputs survive the spill path (all-NULL group)
    s.execute("CREATE TABLE t2 (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO t2 VALUES (1, NULL), (1, NULL), (2, 5)")
    s.execute("SET batch_spill_threshold = 1")
    got, _ = s.execute(
        "SELECT k, sum(v) AS sv FROM t2 GROUP BY k ORDER BY k"
    )
    assert list(got["k"]) == [1, 2]
    assert list(got["sv"]) == [None, 5]
