"""Per-column NULL semantics + VARCHAR dictionary tests.

Reference semantics being matched:
- every array carries a null Bitmap independent of chunk visibility
  (src/common/src/array/data_chunk.rs);
- GROUP BY: all NULLs form one group, distinct from any value
  (src/common/src/hash/key.rs serializes a null tag per datum);
- VARCHAR group-by equality (utf8_array.rs) via host dictionary codes.
"""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu import DataChunk, DataType, Schema, StreamChunk, StringDictionary
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops.hashing import group_key_lanes, hash_columns


def test_null_lane_roundtrip():
    c = StreamChunk.from_numpy(
        {"a": np.array([1, 2, 3], np.int64)},
        capacity=8,
        nulls={"a": np.array([False, True, False])},
    )
    out = c.to_numpy()
    np.testing.assert_array_equal(out["a__null"], [False, True, False])
    # visibility and nullability are independent: mask away row 0,
    # row 1 stays visible-and-NULL
    c2 = c.mask(jnp.asarray(np.array([0, 1, 1, 1, 1, 1, 1, 1], np.bool_)))
    out2 = c2.to_numpy()
    np.testing.assert_array_equal(out2["a"], [2, 3])
    np.testing.assert_array_equal(out2["a__null"], [True, False])


def test_null_group_key_semantics():
    # NULL must hash apart from literal 0 but all NULLs must agree
    c = DataChunk.from_numpy(
        {"k": np.array([0, 7, 0, 5], np.int64)},
        capacity=4,
        nulls={"k": np.array([False, True, True, False])},
    )
    lanes = group_key_lanes(c, ["k"])
    h = np.asarray(hash_columns(lanes))
    assert h[1] == h[2], "all NULLs are one group"
    assert h[0] != h[1], "NULL group != value-0 group"

    # and through the hash table: 3 distinct groups (0, NULL, 5)
    table = ht.HashTable.create(64, tuple(l.dtype for l in lanes))
    table, slots, _, _ = ht.lookup_or_insert(table, lanes, c.valid)
    slots = np.asarray(slots)
    assert slots[1] == slots[2]
    assert len({slots[0], slots[1], slots[3]}) == 3


def test_chunk_ops_required():
    import pytest

    with pytest.raises(TypeError):
        StreamChunk(
            columns={"a": jnp.zeros(4, jnp.int32)}, valid=jnp.ones(4, jnp.bool_)
        )


def test_int64_overflow_guard():
    import pytest

    sch = Schema([("a", DataType.INT32)])
    with pytest.raises(ValueError):
        DataChunk.from_numpy(
            {"a": np.array([2**40], np.int64)}, capacity=4, schema=sch
        )


def test_string_dictionary_roundtrip():
    d = StringDictionary()
    vals = ["apple", "pear", "apple", "fig", "pear"]
    codes = d.encode(vals)
    assert codes.dtype == np.int32
    assert codes[0] == codes[2] and codes[1] == codes[4]
    assert len(d) == 3
    np.testing.assert_array_equal(d.decode(codes), np.asarray(vals, object))
    # codes are stable across later growth
    d.encode(["guava"])
    np.testing.assert_array_equal(d.decode(codes), np.asarray(vals, object))
    # dump/restore preserves codes (checkpoint path)
    d2 = StringDictionary(d.dump())
    np.testing.assert_array_equal(d2.encode(vals), codes)


def test_string_group_by_via_codes(rng):
    d = StringDictionary()
    strings = np.asarray(["a", "bb", "ccc", "bb", "a", "dddd"], object)
    codes = d.encode(strings)
    sch = Schema([("name", DataType.VARCHAR)])
    c = DataChunk.from_numpy({"name": codes}, capacity=8, schema=sch)
    lanes = group_key_lanes(c, ["name"])
    table = ht.HashTable.create(64, tuple(l.dtype for l in lanes))
    table, slots, _, _ = ht.lookup_or_insert(table, lanes, c.valid)
    slots = np.asarray(slots)[:6]
    # same string -> same slot; distinct -> distinct
    groups = {}
    for s, slot in zip(strings, slots):
        groups.setdefault(s, slot)
        assert groups[s] == slot
    assert len(set(groups.values())) == 4


def test_with_columns_clears_replaced_null_lane():
    c = DataChunk.from_numpy(
        {"a": np.array([1, 2], np.int64)},
        capacity=4,
        nulls={"a": np.array([True, False])},
    )
    c2 = c.with_columns(a=c.col("a") * 2)
    assert not c2.is_nullable("a"), "computed columns are non-null"
    c3 = c2.with_nulls(a=c.null_of("a"))
    assert c3.is_nullable("a")


def test_concat_heterogeneous_nullability():
    from risingwave_tpu.array.chunk import concat_chunks

    a = StreamChunk.from_numpy(
        {"x": np.array([1], np.int64)}, 2, nulls={"x": np.array([True])}
    )
    b = StreamChunk.from_numpy({"x": np.array([2], np.int64)}, 2)
    out = concat_chunks([a, b]).to_numpy()
    np.testing.assert_array_equal(out["x__null"], [True, False])
    out2 = concat_chunks([b, a]).to_numpy()
    np.testing.assert_array_equal(out2["x__null"], [False, True])


def test_pytree_roundtrip_with_nulls():
    c = StreamChunk.from_numpy(
        {"a": np.array([1, 2], np.int64), "b": np.array([1.5, 2.5], np.float64)},
        capacity=4,
        nulls={"b": np.array([True, False])},
    )
    leaves, treedef = __import__("jax").tree_util.tree_flatten(c)
    c2 = __import__("jax").tree_util.tree_unflatten(treedef, leaves)
    out, out2 = c.to_numpy(), c2.to_numpy()
    for k in out:
        np.testing.assert_array_equal(out[k], out2[k])
