"""HAVING / DISTINCT inside derived tables + left-deep multi-way BATCH
joins (VERDICT r4 weak #9 + layer-7 depth)."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_having_inside_derived_table():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW hot AS SELECT k2, c FROM "
        "(SELECT k AS k2, count(*) AS c FROM t GROUP BY k HAVING "
        "c > 1) AS g"
    )
    s.execute("INSERT INTO t VALUES (1, 0), (1, 0), (2, 0)")
    out, _ = s.execute("SELECT k2, c FROM hot ORDER BY k2")
    assert list(out["k2"]) == [1] and list(out["c"]) == [2]
    s.execute("INSERT INTO t VALUES (2, 0)")
    out, _ = s.execute("SELECT k2, c FROM hot ORDER BY k2")
    assert list(out["k2"]) == [1, 2]


def test_distinct_inside_derived_table():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW dk AS SELECT k2, count(*) AS n FROM "
        "(SELECT DISTINCT k AS k2 FROM t) AS d GROUP BY k2"
    )
    s.execute("INSERT INTO t VALUES (5, 1), (5, 2), (6, 3)")
    out, _ = s.execute("SELECT k2, n FROM dk ORDER BY k2")
    assert list(out["k2"]) == [5, 6]
    assert list(out["n"]) == [1, 1]  # dedup before the count


def test_batch_three_way_join():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (ak BIGINT, av BIGINT)")
    s.execute("CREATE TABLE b (bk BIGINT, bv BIGINT)")
    s.execute("CREATE TABLE c (ck BIGINT, cv BIGINT)")
    s.execute("INSERT INTO a VALUES (1, 10), (2, 20)")
    s.execute("INSERT INTO b VALUES (1, 100), (2, 200)")
    s.execute("INSERT INTO c VALUES (1, 1000), (3, 3000)")
    out, _ = s.execute(
        "SELECT av, bv, cv FROM a JOIN b ON a.ak = b.bk "
        "JOIN c ON b.bk = c.ck ORDER BY av"
    )
    assert list(out["av"]) == [10]
    assert list(out["bv"]) == [100]
    assert list(out["cv"]) == [1000]
