"""Connector framework: enumerator/reader/parser triples, datagen,
file-log (kafka-shaped) source, offset checkpoint/recovery.

Reference: src/connector/src/source/base.rs traits, parser/ crate,
datagen + kafka connectors; exactly-once resume discipline of
source_executor.rs offsets.
"""


import numpy as np

from risingwave_tpu.connectors.framework import (
    CsvParser,
    DatagenSource,
    FileLogSource,
    GenericSourceExecutor,
    JsonParser,
)
from risingwave_tpu.types import DataType, Schema


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def test_datagen_splits_partition_sequence_space():
    schema = Schema([("id", DataType.INT64), ("v", DataType.INT64)])
    src = GenericSourceExecutor(
        DatagenSource(schema, split_num=2),
        JsonParser(schema),
        table_id="dg",
    )
    # datagen emits dict rows directly (no text round-trip needed)
    chunks = src.poll(4, 16)
    ids = np.concatenate([c.to_numpy()["id"] for c in chunks])
    assert len(ids) == 8
    assert len(set(ids.tolist())) == 8  # splits never collide
    # second poll continues, no repeats
    ids2 = np.concatenate([c.to_numpy()["id"] for c in src.poll(4, 16)])
    assert not set(ids.tolist()) & set(ids2.tolist())


def test_file_log_source_with_json_parser(tmp_path):
    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"k": 1, "v": 10}', '{"k": 2, "v": 20}'])
    FileLogSource.append(d, 1, ['{"k": 3}', "not json", '{"k": 4, "v": 40}'])
    schema = Schema([("k", DataType.INT64), ("v", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="fl"
    )
    assert [s.split_id for s in src.splits] == ["0", "1"]
    chunks = src.poll(10, 16)
    rows = {}
    for c in chunks:
        data = c.to_numpy()
        for i in range(len(data["k"])):
            v = data["v"][i]
            isnull = data.get("v__null")
            rows[int(data["k"][i])] = (
                None if isnull is not None and isnull[i] else int(v)
            )
    assert rows == {1: 10, 2: 20, 3: None, 4: 40}  # bad line dropped

    # producer appends; a later poll picks up ONLY the new messages
    FileLogSource.append(d, 0, ['{"k": 5, "v": 50}'])
    chunks = src.poll(10, 16)
    assert len(chunks) == 1
    assert int(chunks[0].to_numpy()["k"][0]) == 5


def test_json_parser_type_mismatch_becomes_null(tmp_path):
    """A wrong-typed cell ({"k": "oops"} for BIGINT) must become NULL
    at parse time — not blow up encode_column after offsets advanced,
    which would permanently lose the whole poll batch (advisor r3)."""
    d = str(tmp_path)
    FileLogSource.append(
        d,
        0,
        [
            '{"k": 1, "v": 10}',
            '{"k": "oops", "v": [1, 2]}',
            '{"k": 3, "v": "30"}',
        ],
    )
    schema = Schema([("k", DataType.INT64), ("v", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="fl"
    )
    chunks = src.poll(10, 16)
    data = chunks[0].to_numpy()
    knull = data.get("k__null")
    got = [
        None if knull is not None and knull[i] else int(data["k"][i])
        for i in range(len(data["k"]))
    ]
    assert got == [1, None, 3]
    # offsets advanced past ALL three rows: the poll consumed them
    assert src.offsets["0"] > 0
    assert not src.poll(10, 16)  # nothing re-read
    # numeric strings coerce ("30" -> 30)
    vnull = data.get("v__null")
    assert int(data["v"][2]) == 30
    assert vnull is None or not vnull[2]


def test_offsets_checkpoint_and_restore(tmp_path):
    d = str(tmp_path)
    FileLogSource.append(d, 0, [f'{{"k": {i}}}' for i in range(6)])
    schema = Schema([("k", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="fl"
    )
    src.poll(4, 8)
    deltas = src.checkpoint_delta()
    assert len(deltas) == 1

    # a fresh executor restores and resumes at row 4, no dup/loss
    src2 = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="fl"
    )
    src2.restore_state("fl", deltas[0].key_cols, deltas[0].value_cols)
    chunks = src2.poll(10, 8)
    ks = chunks[0].to_numpy()["k"].tolist()
    assert ks == [4, 5]


def test_csv_parser_types(tmp_path):
    d = str(tmp_path)
    FileLogSource.append(
        d, 0, ["1,alice,2.50,true", "2,,0.10,false", "3,bob,,true"]
    )
    schema = Schema(
        [
            ("id", DataType.INT64),
            ("name", DataType.VARCHAR),
            ("amt", DataType.DECIMAL),
            ("ok", DataType.BOOLEAN),
        ]
    )
    # DECIMAL default scale is 6
    src = GenericSourceExecutor(
        FileLogSource(d), CsvParser(schema), table_id="csv"
    )
    c = src.poll(10, 8)[0]
    data = c.to_numpy()
    assert data["id"].tolist() == [1, 2, 3]
    assert src.strings.decode(data["name"]).tolist()[0] == "alice"
    assert data["name__null"].tolist() == [False, True, False]
    assert data["amt"].tolist()[0] == 2_500_000  # 2.50 at scale 6
    assert data["amt__null"].tolist() == [False, False, True]
    assert data["ok"].tolist() == [True, False, True]


def test_discovery_picks_up_new_partitions(tmp_path):
    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"k": 1}'])
    schema = Schema([("k", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="fl"
    )
    assert len(src.splits) == 1
    src.poll(10, 8)
    FileLogSource.append(d, 1, ['{"k": 2}'])
    src.discover()
    assert len(src.splits) == 2
    chunks = src.poll(10, 8)
    assert [int(c.to_numpy()["k"][0]) for c in chunks] == [2]


def test_create_source_sql_end_to_end(tmp_path):
    """CREATE SOURCE (filelog/json) -> MV -> pump -> SELECT, with late
    appends picked up by later pumps."""
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    d = str(tmp_path)
    FileLogSource.append(
        d, 0, ['{"uid": 1, "amt": 10}', '{"uid": 2, "amt": 20}']
    )
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE pay (uid BIGINT, amt BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW spend AS "
        "SELECT uid, sum(amt) AS total FROM pay GROUP BY uid"
    )
    assert s.pump_sources() == 2
    s.runtime.barrier()
    out, _ = s.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [10, 20]

    FileLogSource.append(d, 0, ['{"uid": 1, "amt": 5}'])
    FileLogSource.append(d, 1, ['{"uid": 3, "amt": 30}'])  # new partition
    assert s.pump_sources() == 2
    s.runtime.barrier()
    out, _ = s.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [15, 20, 30]


def test_create_source_datagen_sql(tmp_path):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE SOURCE g (id BIGINT, v BIGINT) "
        "WITH (connector='datagen', split_num='2')"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW c AS SELECT count(*) AS n FROM g"
    )
    s.pump_sources(max_rows_per_split=8)
    s.runtime.barrier()
    out, _ = s.execute("SELECT n FROM c")
    assert list(out["n"]) == [16]


def test_json_parser_fractional_int_cell_becomes_null():
    """A non-integral JSON number landing in an int lane follows the
    bad-cell-becomes-NULL convention — never silent truncation
    (advisor r4: int(3.7) -> 3 altered producer data)."""
    schema = Schema([("id", DataType.INT64), ("v", DataType.INT64)])
    p = JsonParser(schema)
    assert p.parse('{"id": 1, "v": 3.7}') == (1, None)
    assert p.parse('{"id": 2, "v": 4.0}') == (2, 4)  # integral float ok
    assert p.parse('{"id": 3, "v": 5}') == (3, 5)
