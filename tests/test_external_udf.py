"""Out-of-process UDF server + client (reference: udf/external.rs —
the external UDF flight service; here a dependency-free framed-JSON
TCP protocol with the same batch + row-error->NULL semantics)."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog
from risingwave_tpu.udf_server import UdfServer, call_external

pytestmark = pytest.mark.smoke


@pytest.fixture
def server():
    def double(x):
        return x * 2

    def risky(x):
        if x == 13:
            raise ValueError("unlucky")
        return x + 1

    def shout(s):
        return s.upper() + "!"

    srv = UdfServer(
        {"double": double, "risky": risky, "shout": shout}
    ).start()
    yield srv
    srv.stop()


def test_protocol_batch_and_row_errors(server):
    vals, nulls = call_external(server.address, "double", [[1, 2, 3]])
    assert vals == [2, 4, 6] and nulls == [False] * 3
    vals, nulls = call_external(server.address, "risky", [[12, 13, 14]])
    assert vals == [13, None, 15]
    assert nulls == [False, True, False]  # row error -> NULL
    with pytest.raises(RuntimeError, match="unknown function"):
        call_external(server.address, "nope", [[1]])


def test_unreachable_server_raises():
    with pytest.raises(RuntimeError, match="unreachable"):
        call_external("127.0.0.1:1", "f", [[1]], timeout=0.3, retries=1)


def test_sql_external_udf_end_to_end(server):
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE FUNCTION double(x BIGINT) RETURNS BIGINT "
        f"LANGUAGE external AS '{server.address}'"
    )
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT v, double(v) AS d FROM t"
    )
    s.execute("INSERT INTO t VALUES (3), (5)")
    out, _ = s.execute("SELECT v, d FROM m ORDER BY v")
    assert list(out["d"]) == [6, 10]
    # batch SELECT path too
    out, _ = s.execute("SELECT double(v) AS d2 FROM t ORDER BY d2")
    assert list(out["d2"]) == [6, 10]


def test_sql_external_varchar_udf(server):
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE FUNCTION shout(s VARCHAR) RETURNS VARCHAR "
        f"LANGUAGE external AS '{server.address}'"
    )
    s.execute("CREATE TABLE t (name VARCHAR)")
    s.execute("INSERT INTO t VALUES ('hi'), ('yo')")
    out, _ = s.execute("SELECT shout(name) AS x FROM t")
    assert sorted(out["x"]) == ["HI!", "YO!"]


def test_subprocess_server_cli(tmp_path):
    """The shipped __main__ entry hosts functions from a user file."""
    import socket
    import subprocess
    import sys
    import time

    fns = tmp_path / "fns.py"
    fns.write_text("def triple(x):\n    return x * 3\n")
    # pick a free port
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "risingwave_tpu.udf_server",
            "--port",
            str(port),
            "--file",
            str(fns),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                vals, _ = call_external(
                    f"127.0.0.1:{port}", "triple", [[7]],
                    timeout=1.0, retries=0,
                )
                assert vals == [21]
                break
            except RuntimeError:
                time.sleep(0.2)
        else:
            raise AssertionError("server never came up")
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_external_decimal_udf(server):
    """DECIMAL crosses the wire as str both ways (review finding r5:
    repr(str) used to corrupt the Decimal parse into all-NULL rows)."""
    srv = __import__(
        "risingwave_tpu.udf_server", fromlist=["UdfServer"]
    ).UdfServer({"with_tax": lambda amt: str(round(float(amt) * 1.1, 2))})
    srv.start()
    try:
        s = SqlSession(Catalog({}), capacity=1 << 10)
        s.execute(
            f"CREATE FUNCTION with_tax(a DECIMAL(10,2)) RETURNS "
            f"DECIMAL(10,2) LANGUAGE external AS '{srv.address}'"
        )
        s.execute("CREATE TABLE t (amt DECIMAL(10, 2))")
        s.execute("INSERT INTO t VALUES (100.00), (250.50)")
        out, _ = s.execute("SELECT with_tax(amt) AS x FROM t")
        vals = sorted(float(v) for v in out["x"])
        assert vals == pytest.approx([110.0, 275.55])
    finally:
        srv.stop()


def test_pump_rotates_workers_under_throttle(tmp_path):
    """parallelism=2 + rate limit: both workers' splits make progress
    across pumps (review finding r5: fixed worker order starved w1)."""
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    FileLogSource.append(d, 0, [f'{{"v": {i}}}' for i in range(500)])
    FileLogSource.append(d, 1, [f'{{"v": {1000 + i}}}' for i in range(5)])
    s = SqlSession(Catalog({}), capacity=1 << 10, parallelism=2)
    s.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW mx AS SELECT max(v) AS m FROM g"
    )
    s.execute("ALTER SOURCE g SET rate_limit = 5")
    src = s.sources["g"]
    for _ in range(8):
        s.pump_sources()
        s.runtime.barrier()
        if src._bucket_t is not None:
            src._bucket_t -= 1.0  # deterministic refill
    out, _ = s.execute("SELECT m FROM mx")
    assert out["m"][0] >= 1000, "worker 1's split starved"


def test_numpy_and_unserializable_results():
    """A numpy-scalar result serializes via .item(); a genuinely
    unserializable one becomes an error FRAME, not a dead socket
    (review finding r5)."""
    import numpy as _np

    from risingwave_tpu.udf_server import UdfServer

    srv = UdfServer({
        "npy": lambda x: _np.int64(x) * 2,
        "bad": lambda x: object(),
    }).start()
    try:
        vals, nulls = call_external(srv.address, "npy", [[4]])
        assert vals == [8] and nulls == [False]
        vals, nulls = call_external(srv.address, "bad", [[1]])
        # object() stringifies via the fallback: delivered as str, or
        # an error frame — either way the CONNECTION survives
        vals2, _ = call_external(srv.address, "npy", [[5]])
        assert vals2 == [10]
    finally:
        srv.stop()
