"""GroupTopN oracle tests — emitted deltas replay to exactly each
group's top-k (reference: top_n executor tests, top_n_cache.rs)."""

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import Barrier, GroupTopNExecutor, Watermark
from risingwave_tpu.executors.base import Epoch
from risingwave_tpu.types import Op

import jax.numpy as jnp


def _replay(outs, snap, names=("g", "v", "p")):
    for out in outs:
        d = out.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            row = tuple(int(d[n][i]) for n in names)
            delta = 1 if d["__op__"][i] == Op.INSERT else -1
            snap[row] = snap.get(row, 0) + delta
            if snap[row] == 0:
                del snap[row]
    return snap


def _chunk(g, v, p, cap=64, ops=None):
    return StreamChunk.from_numpy(
        {
            "g": np.asarray(g, np.int64),
            "v": np.asarray(v, np.int64),
            "p": np.asarray(p, np.int64),
        },
        cap,
        ops=ops,
    )


def _oracle(rows, k, desc=True):
    """rows: list of (g, v, p) -> expected multiset of top-k rows."""
    from collections import defaultdict

    groups = defaultdict(list)
    for i, (g, v, p) in enumerate(rows):
        groups[g].append((v, i, p))
    want = {}
    for g, items in groups.items():
        items.sort(key=lambda t: (-t[0], t[1]) if desc else (t[0], t[1]))
        for v, _, p in items[:k]:
            key = (g, v, p)
            want[key] = want.get(key, 0) + 1
    return want


def test_topn_basic_and_eviction():
    ex = GroupTopNExecutor(
        ("g",), "v", k=2,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=True, capacity=1 << 8, out_cap=1 << 8,
    )
    snap = {}
    _replay(ex.apply(_chunk([1, 1, 1], [10, 30, 20], [100, 101, 102])), snap)
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == {(1, 30, 101): 1, (1, 20, 102): 1}

    # a higher row evicts the current #2
    _replay(ex.apply(_chunk([1], [25], [103])), snap)
    assert snap == {(1, 30, 101): 1, (1, 25, 103): 1}
    # a lower row changes nothing
    _replay(ex.apply(_chunk([1], [5], [104])), snap)
    assert snap == {(1, 30, 101): 1, (1, 25, 103): 1}


def test_topn_random_vs_oracle(rng):
    k = 4
    ex = GroupTopNExecutor(
        ("g",), "v", k=k,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=True, capacity=1 << 6,  # force regrows
        out_cap=1 << 10,
    )
    snap, rows = {}, []
    for _ in range(12):
        n = int(rng.integers(5, 60))
        g = rng.integers(0, 30, n).astype(np.int64)
        v = rng.integers(0, 10_000, n).astype(np.int64)  # ~unique orders
        p = rng.integers(0, 1000, n).astype(np.int64)
        rows += list(zip(g.tolist(), v.tolist(), p.tolist()))
        _replay(ex.apply(_chunk(g, v, p)), snap)
        ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == _oracle(rows, k)
    assert len(snap) > 50


def test_topn_asc_order(rng):
    k = 3
    ex = GroupTopNExecutor(
        ("g",), "v", k=k,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=False, capacity=1 << 8, out_cap=1 << 10,
    )
    snap, rows = {}, []
    for _ in range(5):
        n = 40
        g = rng.integers(0, 10, n).astype(np.int64)
        v = rng.integers(-5000, 5000, n).astype(np.int64)
        p = rng.integers(0, 100, n).astype(np.int64)
        rows += list(zip(g.tolist(), v.tolist(), p.tolist()))
        _replay(ex.apply(_chunk(g, v, p)), snap)
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == _oracle(rows, k, desc=False)


def test_topn_checkpoint_recovery(rng):
    from risingwave_tpu.storage import CheckpointManager, MemObjectStore

    store = MemObjectStore()
    mgr = CheckpointManager(store)

    def mk():
        return GroupTopNExecutor(
            ("g",), "v", k=3,
            schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
            payload=("p",), capacity=1 << 8, out_cap=1 << 10,
            table_id="topn",
        )

    ex = mk()
    snap = {}
    epoch = 0
    for _ in range(4):
        n = 50
        g = rng.integers(0, 20, n).astype(np.int64)
        v = rng.integers(0, 100_000, n).astype(np.int64)
        p = rng.integers(0, 100, n).astype(np.int64)
        _replay(ex.apply(_chunk(g, v, p)), snap)
        ex.on_barrier(Barrier(Epoch(epoch, epoch + 1)))
        epoch += 1
        mgr.commit_epoch(epoch, [ex])

    ex2 = mk()
    CheckpointManager(store).recover([ex2])
    # both see identical emissions for identical future input
    g = rng.integers(0, 20, 30).astype(np.int64)
    v = rng.integers(0, 100_000, 30).astype(np.int64)
    p = rng.integers(0, 100, 30).astype(np.int64)
    out_a = {}
    out_b = {}
    _replay(ex.apply(_chunk(g, v, p)), out_a)
    _replay(ex2.apply(_chunk(g, v, p)), out_b)
    assert out_a == out_b
    assert np.array_equal(
        np.sort(np.asarray(ex.state["order"])[np.asarray(ex.table.live)], axis=None),
        np.sort(np.asarray(ex2.state["order"])[np.asarray(ex2.table.live)], axis=None),
    )
