"""GroupTopN oracle tests — emitted deltas replay to exactly each
group's top-k (reference: top_n executor tests, top_n_cache.rs)."""

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import Barrier, GroupTopNExecutor
from risingwave_tpu.executors.base import Epoch
from risingwave_tpu.types import Op

import jax.numpy as jnp


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def _replay(outs, snap, names=("g", "v", "p")):
    for out in outs:
        d = out.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            row = tuple(int(d[n][i]) for n in names)
            delta = 1 if d["__op__"][i] == Op.INSERT else -1
            snap[row] = snap.get(row, 0) + delta
            if snap[row] == 0:
                del snap[row]
    return snap


def _chunk(g, v, p, cap=64, ops=None):
    return StreamChunk.from_numpy(
        {
            "g": np.asarray(g, np.int64),
            "v": np.asarray(v, np.int64),
            "p": np.asarray(p, np.int64),
        },
        cap,
        ops=ops,
    )


def _oracle(rows, k, desc=True):
    """rows: list of (g, v, p) -> expected multiset of top-k rows."""
    from collections import defaultdict

    groups = defaultdict(list)
    for i, (g, v, p) in enumerate(rows):
        groups[g].append((v, i, p))
    want = {}
    for g, items in groups.items():
        items.sort(key=lambda t: (-t[0], t[1]) if desc else (t[0], t[1]))
        for v, _, p in items[:k]:
            key = (g, v, p)
            want[key] = want.get(key, 0) + 1
    return want


def test_topn_basic_and_eviction():
    ex = GroupTopNExecutor(
        ("g",), "v", k=2,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=True, capacity=1 << 8, out_cap=1 << 8,
    )
    snap = {}
    _replay(ex.apply(_chunk([1, 1, 1], [10, 30, 20], [100, 101, 102])), snap)
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == {(1, 30, 101): 1, (1, 20, 102): 1}

    # a higher row evicts the current #2
    _replay(ex.apply(_chunk([1], [25], [103])), snap)
    assert snap == {(1, 30, 101): 1, (1, 25, 103): 1}
    # a lower row changes nothing
    _replay(ex.apply(_chunk([1], [5], [104])), snap)
    assert snap == {(1, 30, 101): 1, (1, 25, 103): 1}


def test_topn_random_vs_oracle(rng):
    k = 4
    ex = GroupTopNExecutor(
        ("g",), "v", k=k,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=True, capacity=1 << 6,  # force regrows
        out_cap=1 << 10,
    )
    snap, rows = {}, []
    for _ in range(12):
        n = int(rng.integers(5, 60))
        g = rng.integers(0, 30, n).astype(np.int64)
        v = rng.integers(0, 10_000, n).astype(np.int64)  # ~unique orders
        p = rng.integers(0, 1000, n).astype(np.int64)
        rows += list(zip(g.tolist(), v.tolist(), p.tolist()))
        _replay(ex.apply(_chunk(g, v, p)), snap)
        ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == _oracle(rows, k)
    assert len(snap) > 50


def test_topn_asc_order(rng):
    k = 3
    ex = GroupTopNExecutor(
        ("g",), "v", k=k,
        schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
        payload=("p",), desc=False, capacity=1 << 8, out_cap=1 << 10,
    )
    snap, rows = {}, []
    for _ in range(5):
        n = 40
        g = rng.integers(0, 10, n).astype(np.int64)
        v = rng.integers(-5000, 5000, n).astype(np.int64)
        p = rng.integers(0, 100, n).astype(np.int64)
        rows += list(zip(g.tolist(), v.tolist(), p.tolist()))
        _replay(ex.apply(_chunk(g, v, p)), snap)
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert snap == _oracle(rows, k, desc=False)


def test_topn_checkpoint_recovery(rng):
    from risingwave_tpu.storage import CheckpointManager, MemObjectStore

    store = MemObjectStore()
    mgr = CheckpointManager(store)

    def mk():
        return GroupTopNExecutor(
            ("g",), "v", k=3,
            schema_dtypes={"g": jnp.int64, "v": jnp.int64, "p": jnp.int64},
            payload=("p",), capacity=1 << 8, out_cap=1 << 10,
            table_id="topn",
        )

    ex = mk()
    snap = {}
    epoch = 0
    for _ in range(4):
        n = 50
        g = rng.integers(0, 20, n).astype(np.int64)
        v = rng.integers(0, 100_000, n).astype(np.int64)
        p = rng.integers(0, 100, n).astype(np.int64)
        _replay(ex.apply(_chunk(g, v, p)), snap)
        ex.on_barrier(Barrier(Epoch(epoch, epoch + 1)))
        epoch += 1
        mgr.commit_epoch(epoch, [ex])

    ex2 = mk()
    CheckpointManager(store).recover([ex2])
    # both see identical emissions for identical future input
    g = rng.integers(0, 20, 30).astype(np.int64)
    v = rng.integers(0, 100_000, 30).astype(np.int64)
    p = rng.integers(0, 100, 30).astype(np.int64)
    out_a = {}
    out_b = {}
    _replay(ex.apply(_chunk(g, v, p)), out_a)
    _replay(ex2.apply(_chunk(g, v, p)), out_b)
    assert out_a == out_b
    assert np.array_equal(
        np.sort(np.asarray(ex.state["order"])[np.asarray(ex.table.live)], axis=None),
        np.sort(np.asarray(ex2.state["order"])[np.asarray(ex2.table.live)], axis=None),
    )


def test_retractable_group_topn_randomized_oracle():
    """Random inserts/deletes/updates crossing each group's top-k
    boundary: replaying the executor's delta stream must always equal
    the per-group SQL top-k (group_top_n.rs:63 semantics)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )
    from risingwave_tpu.types import Op

    K = 3
    ex = RetractableGroupTopNExecutor(
        group_by=("g",),
        order_col="v",
        limit=K,
        pk=("id",),
        schema_dtypes={"g": jnp.int64, "id": jnp.int64, "v": jnp.int64},
        desc=True,
        capacity=1 << 9,
        table_id="gtn",
    )
    rng = np.random.default_rng(17)
    live = {}  # id -> (g, v): the true current relation
    replay = {}  # replayed downstream state: row tuple -> count
    next_id = 0

    def oracle_topk():
        from collections import defaultdict

        per_g = defaultdict(list)
        for id_, (g, v) in live.items():
            per_g[g].append((v, -id_, id_))
        out = set()
        for g, rows in per_g.items():
            rows.sort(reverse=True)  # desc by v, id tiebreak
            for v, _nid, id_ in rows[:K]:
                out.add((g, id_, v))
        return out

    for epoch in range(12):
        n = int(rng.integers(3, 18))
        ops, gs, ids, vs = [], [], [], []
        for _ in range(n):
            if live and rng.random() < 0.4:
                id_ = int(rng.choice(list(live)))
                g, v = live[id_]
                if rng.random() < 0.5:  # delete
                    ops.append(int(Op.DELETE))
                    gs.append(g); ids.append(id_); vs.append(v)
                    del live[id_]
                else:  # update value (upsert same pk)
                    nv = int(rng.integers(0, 100))
                    ops.append(int(Op.INSERT))
                    gs.append(g); ids.append(id_); vs.append(nv)
                    live[id_] = (g, nv)
            else:
                g = int(rng.integers(0, 4))
                v = int(rng.integers(0, 100))
                ops.append(int(Op.INSERT))
                gs.append(g); ids.append(next_id); vs.append(v)
                live[next_id] = (g, v)
                next_id += 1
        chunk = StreamChunk.from_numpy(
            {
                "g": np.asarray(gs, np.int64),
                "id": np.asarray(ids, np.int64),
                "v": np.asarray(vs, np.int64),
            },
            32,
            ops=np.asarray(ops, np.int32),
        )
        ex.apply(chunk)
        for out in ex.on_barrier(None):
            d = out.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = (int(d["g"][i]), int(d["id"][i]), int(d["v"][i]))
                if d["__op__"][i] in (int(Op.DELETE), int(Op.UPDATE_DELETE)):
                    replay[row] = replay.get(row, 0) - 1
                    if not replay[row]:
                        del replay[row]
                else:
                    replay[row] = replay.get(row, 0) + 1
        got = {r for r, c in replay.items() if c}
        assert all(c == 1 for c in replay.values())
        assert got == oracle_topk(), f"epoch {epoch}"


def test_retractable_group_topn_checkpoint_restore():
    """Kill+recover mid-stream: the delta stream after restore matches
    an uninterrupted run (incl. the rebuilt emitted mirror)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager
    from risingwave_tpu.types import Op

    DT = {"g": jnp.int64, "id": jnp.int64, "v": jnp.int64}

    def mk():
        return RetractableGroupTopNExecutor(
            ("g",), "v", 2, ("id",), DT, desc=True,
            capacity=1 << 8, table_id="gtn2",
        )

    rng = np.random.default_rng(5)
    epochs = []
    for _ in range(6):
        n = int(rng.integers(4, 16))
        epochs.append(
            StreamChunk.from_numpy(
                {
                    "g": rng.integers(0, 3, n).astype(np.int64),
                    "id": rng.integers(0, 40, n).astype(np.int64),
                    "v": rng.integers(0, 100, n).astype(np.int64),
                },
                32,
            )
        )

    def replay_into(state, outs):
        for out in outs:
            d = out.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = (int(d["g"][i]), int(d["id"][i]), int(d["v"][i]))
                if d["__op__"][i] in (int(Op.DELETE), int(Op.UPDATE_DELETE)):
                    state.discard(row)
                else:
                    state.add(row)

    want = set()
    oracle = mk()
    for c in epochs:
        oracle.apply(c)
        replay_into(want, oracle.on_barrier(None))

    got = set()
    mgr = CheckpointManager(MemObjectStore())
    ex1 = mk()
    for c in epochs[:3]:
        ex1.apply(c)
        replay_into(got, ex1.on_barrier(None))
    mgr.commit_staged(1, mgr.stage([ex1]))
    del ex1

    ex2 = mk()
    mgr.recover([ex2])
    for c in epochs[3:]:
        ex2.apply(c)
        replay_into(got, ex2.on_barrier(None))
    assert got == want and want


def test_retractable_group_topn_group_change_and_extreme_values():
    """A row 'moving' groups (DELETE old + INSERT new) retracts from
    the old group; INT64-extreme order values never lose to dead
    slots (review findings r4)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )
    from risingwave_tpu.types import Op

    ex = RetractableGroupTopNExecutor(
        ("g",), "v", 2, ("id",),
        {"g": jnp.int64, "id": jnp.int64, "v": jnp.int64},
        desc=False, capacity=1 << 7, table_id="gtn3",
    )
    state = set()

    def replay(outs):
        for c in outs:
            d = c.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = (int(d["g"][i]), int(d["id"][i]), int(d["v"][i]))
                if d["__op__"][i] in (
                    int(Op.DELETE), int(Op.UPDATE_DELETE)
                ):
                    state.discard(row)
                else:
                    state.add(row)

    IMAX = np.iinfo(np.int64).max
    ex.apply(
        StreamChunk.from_numpy(
            {
                "g": np.asarray([0, 0, 1], np.int64),
                "id": np.asarray([1, 2, 3], np.int64),
                # ascending top-2 with an INT64_MAX order value: must
                # not be displaced by dead/unclaimed slots
                "v": np.asarray([5, IMAX, 9], np.int64),
            },
            8,
        )
    )
    replay(ex.on_barrier(None))
    assert state == {(0, 1, 5), (0, 2, IMAX), (1, 3, 9)}

    # move id=2 from group 0 to group 1: old group must retract
    ex.apply(
        StreamChunk.from_numpy(
            {
                "g": np.asarray([0, 1], np.int64),
                "id": np.asarray([2, 2], np.int64),
                "v": np.asarray([IMAX, 4], np.int64),
            },
            8,
            ops=np.asarray([int(Op.DELETE), int(Op.INSERT)], np.int32),
        )
    )
    replay(ex.on_barrier(None))
    assert state == {(0, 1, 5), (1, 2, 4), (1, 3, 9)}
