"""Test config: force a virtual 8-device CPU platform.

Mirrors the reference's approach of testing multi-node behavior without a
cluster (madsim simulation, src/tests/simulation/): we test multi-chip
sharding on a virtual CPU mesh; the real-TPU path is exercised by
bench.py / __graft_entry__.py on hardware.

NOTE: the environment ships a sitecustomize that registers the `axon`
TPU plugin and *forces* JAX_PLATFORMS=axon via an in-process hook, so
setting the env var alone is not enough — we must also flip jax's
config after import. Tests must never touch the real TPU: the tunnel
admits one client and a killed test run can wedge it.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# arm jax.transfer_guard("disallow") around the per-barrier device step
# (runtime/pipeline.py + runtime/graph.py wrap it via
# analysis.jax_sanitizer.transfer_guard): an implicit host<->device
# transfer on the hot path raises AT the offending executor. Opt out
# with RW_TRANSFER_GUARD=0.
os.environ.setdefault("RW_TRANSFER_GUARD", "1")

# persistent XLA compilation cache (VERDICT r4 weak #10): identical
# test compiles re-load across runs instead of re-tracing XLA — pays
# for itself on both dev and judge boxes. Safe no-op on refusal.
from risingwave_tpu.config import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "smoke: fast CI-signal subset — `pytest -m smoke` runs <2 min "
        "(VERDICT r3 #10)",
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-process / long-haul tests (subprocess spawns pay "
        "a cold jax import each)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules: hundreds of
    accumulated CPU executables have produced in-compile segfaults deep
    into the full suite (observed in jax backend_compile during a late
    module); modules are self-contained, so bounding the live cache
    costs only per-module recompiles."""
    yield
    jax.clear_caches()
