"""sqllogictest-style e2e tier: tests/slt/*.slt executed against a
fresh SqlSession each (reference: e2e_test/ + sqllogictest-rs,
SURVEY.md §4)."""

import glob
import os

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog
from tests.slt_runner import run_slt

SLT_DIR = os.path.join(os.path.dirname(__file__), "slt")
FILES = sorted(glob.glob(os.path.join(SLT_DIR, "*.slt")))


@pytest.mark.parametrize("path", FILES, ids=[os.path.basename(f) for f in FILES])
def test_slt_file(path):
    session = SqlSession(Catalog({}), capacity=1 << 10)
    with open(path) as f:
        n = run_slt(session, f.read(), path=path)
    assert n > 0
