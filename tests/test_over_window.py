

def test_running_min_max_and_lag():
    """min/max/lag window kinds vs a pandas-style oracle across chunks
    (state crosses chunk boundaries)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )

    ex = OverWindowExecutor(
        partition_by=("p",),
        calls=(
            WindowCall("min", "x", "rmin"),
            WindowCall("max", "x", "rmax"),
            WindowCall("lag", "x", "prev"),
        ),
        schema_dtypes={"p": jnp.int64, "x": jnp.int64},
        capacity=1 << 8,
    )
    rng = np.random.default_rng(7)
    hist = {}
    got = []
    for _ in range(6):
        n = int(rng.integers(3, 30))
        ps = rng.integers(0, 4, n)
        xs = rng.integers(-50, 50, n)
        chunk = StreamChunk.from_numpy({"p": ps, "x": xs}, 32)
        (out,) = ex.apply(chunk)
        d = out.to_numpy()
        pn = d.get("prev__null", np.zeros(len(d["p"]), bool))
        for i in range(len(d["p"])):
            got.append(
                (int(d["p"][i]), int(d["rmin"][i]), int(d["rmax"][i]),
                 None if pn[i] else int(d["prev"][i]))
            )
    want = []
    hist = {}
    # rebuild the oracle from the SAME arrival order
    rng = np.random.default_rng(7)
    for _ in range(6):
        n = int(rng.integers(3, 30))
        ps = rng.integers(0, 4, n)
        xs = rng.integers(-50, 50, n)
        for p, x in zip(ps.tolist(), xs.tolist()):
            seen = hist.setdefault(p, [])
            prev = seen[-1] if seen else None
            seen.append(x)
            want.append((p, min(seen), max(seen), prev))
    assert got == want
