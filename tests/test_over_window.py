"""OverWindow executor: running window kinds vs pandas-style oracles,
rank/dense_rank over ordered arrivals, checkpoint/restore.

Reference: src/stream/src/executor/over_window/general.rs:49 (the
append-only arrival-ordered specialization)."""


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def test_running_min_max_and_lag():
    """min/max/lag window kinds vs a pandas-style oracle across chunks
    (state crosses chunk boundaries)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )

    ex = OverWindowExecutor(
        partition_by=("p",),
        calls=(
            WindowCall("min", "x", "rmin"),
            WindowCall("max", "x", "rmax"),
            WindowCall("lag", "x", "prev"),
        ),
        schema_dtypes={"p": jnp.int64, "x": jnp.int64},
        capacity=1 << 8,
    )
    rng = np.random.default_rng(7)
    hist = {}
    got = []
    for _ in range(6):
        n = int(rng.integers(3, 30))
        ps = rng.integers(0, 4, n)
        xs = rng.integers(-50, 50, n)
        chunk = StreamChunk.from_numpy({"p": ps, "x": xs}, 32)
        (out,) = ex.apply(chunk)
        d = out.to_numpy()
        pn = d.get("prev__null", np.zeros(len(d["p"]), bool))
        for i in range(len(d["p"])):
            got.append(
                (int(d["p"][i]), int(d["rmin"][i]), int(d["rmax"][i]),
                 None if pn[i] else int(d["prev"][i]))
            )
    want = []
    hist = {}
    # rebuild the oracle from the SAME arrival order
    rng = np.random.default_rng(7)
    for _ in range(6):
        n = int(rng.integers(3, 30))
        ps = rng.integers(0, 4, n)
        xs = rng.integers(-50, 50, n)
        for p, x in zip(ps.tolist(), xs.tolist()):
            seen = hist.setdefault(p, [])
            prev = seen[-1] if seen else None
            seen.append(x)
            want.append((p, min(seen), max(seen), prev))
    assert got == want


def test_rank_dense_rank_ordered_arrivals():
    """rank/dense_rank over per-partition non-decreasing order values,
    with ties, crossing chunk boundaries."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )

    ex = OverWindowExecutor(
        partition_by=("p",),
        calls=(
            WindowCall("rank", "x", "rk"),
            WindowCall("dense_rank", "x", "drk"),
            WindowCall("row_number", None, "rn"),
        ),
        schema_dtypes={"p": jnp.int64, "x": jnp.int64},
        capacity=1 << 8,
    )
    rng = np.random.default_rng(3)
    # per-partition monotone order values WITH ties: random increments
    # of 0/0/1/2 so ties occur both inside a chunk and across chunks
    cur = {p: 0 for p in range(4)}
    arrivals = []
    for _ in range(5):
        n = int(rng.integers(4, 24))
        ps = rng.integers(0, 4, n)
        xs = []
        for p in ps.tolist():
            cur[p] += int(rng.choice([0, 0, 1, 2]))
            xs.append(cur[p])
        arrivals.append((ps, np.asarray(xs, np.int64)))

    got = []
    for ps, xs in arrivals:
        chunk = StreamChunk.from_numpy({"p": ps, "x": xs}, 32)
        (out,) = ex.apply(chunk)
        d = out.to_numpy()
        for i in range(len(d["p"])):
            got.append(
                (int(d["p"][i]), int(d["rk"][i]), int(d["drk"][i]),
                 int(d["rn"][i]))
            )
    ex.on_barrier(None)  # ooo latch must NOT fire

    # oracle: SQL rank()/dense_rank() over (partition by p order by x)
    hist = {}
    want = []
    for ps, xs in arrivals:
        for p, x in zip(ps.tolist(), xs.tolist()):
            seen = hist.setdefault(p, [])
            seen.append(x)
            rank = 1 + sum(1 for v in seen if v < x)
            dense = len({v for v in seen if v < x}) + 1
            want.append((p, rank, dense, len(seen)))
    assert got == want


def test_rank_out_of_order_raises():
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )

    ex = OverWindowExecutor(
        partition_by=("p",),
        calls=(WindowCall("rank", "x", "rk"),),
        schema_dtypes={"p": jnp.int64, "x": jnp.int64},
        capacity=1 << 6,
    )
    ex.apply(
        StreamChunk.from_numpy(
            {"p": np.zeros(2, np.int64), "x": np.asarray([5, 3], np.int64)},
            8,
        )
    )
    with pytest.raises(RuntimeError, match="out-of-order"):
        ex.on_barrier(None)


def test_over_window_checkpoint_restore():
    """A window MV's state survives kill+recover bit-exactly: outputs
    after restore equal an uninterrupted run (VERDICT r3 #5 — before
    this, recovery silently produced wrong results)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    CALLS = (
        WindowCall("row_number", None, "rn"),
        WindowCall("sum", "x", "rs"),
        WindowCall("min", "x", "rmin"),
        WindowCall("lag", "x", "prev"),
        WindowCall("rank", "o", "rk"),
    )
    DT = {"p": jnp.int64, "x": jnp.int64, "o": jnp.int64}

    def chunks():
        rng = np.random.default_rng(9)
        cur = {p: 0 for p in range(5)}
        out = []
        for _ in range(6):
            n = int(rng.integers(4, 20))
            ps = rng.integers(0, 5, n)
            xs = rng.integers(-40, 40, n).astype(np.int64)
            os_ = []
            for p in ps.tolist():
                cur[p] += int(rng.choice([0, 1, 3]))
                os_.append(cur[p])
            out.append(
                StreamChunk.from_numpy(
                    {"p": ps, "x": xs, "o": np.asarray(os_, np.int64)}, 32
                )
            )
        return out

    def outputs(ex, cs):
        rows = []
        for c in cs:
            (out,) = ex.apply(c)
            d = out.to_numpy()
            pn = d.get("prev__null", np.zeros(len(d["p"]), bool))
            for i in range(len(d["p"])):
                rows.append(
                    (int(d["p"][i]), int(d["rn"][i]), int(d["rs"][i]),
                     int(d["rmin"][i]),
                     None if pn[i] else int(d["prev"][i]),
                     int(d["rk"][i]))
                )
        return rows

    cs = chunks()
    oracle = OverWindowExecutor(("p",), CALLS, DT, capacity=1 << 7,
                                table_id="ow")
    uninterrupted = outputs(oracle, cs)

    mgr = CheckpointManager(MemObjectStore())
    ex1 = OverWindowExecutor(("p",), CALLS, DT, capacity=1 << 7,
                             table_id="ow")
    first = outputs(ex1, cs[:3])
    staged = mgr.stage([ex1])
    assert staged and staged[0].table_id == "ow"
    mgr.commit_staged(1, staged)
    del ex1  # the kill

    ex2 = OverWindowExecutor(("p",), CALLS, DT, capacity=1 << 7,
                             table_id="ow")
    mgr.recover([ex2])
    rest = outputs(ex2, cs[3:])
    ex2.on_barrier(None)
    assert first + rest == uninterrupted


def _eowc_oracle(rows, calls_spec):
    """Oracle: SQL window functions over complete (p, w) partitions
    ordered by (o, arrival)."""
    from collections import defaultdict

    parts = defaultdict(list)
    for i, r in enumerate(rows):
        parts[(r["p"], r["w"])].append((r["o"], i, r))
    out = []
    for key in parts:
        seq = sorted(parts[key], key=lambda t: (t[0], t[1]))
        vals = [r["x"] for _o, _i, r in seq]
        orders = [o for o, _i, _r in seq]
        n = len(seq)
        for i, (_o, _idx, r) in enumerate(seq):
            row = dict(r)
            row["rn"] = i + 1
            row["rk"] = 1 + sum(1 for o2 in orders if o2 < orders[i])
            row["drk"] = len({o2 for o2 in orders if o2 < orders[i]}) + 1
            row["ld"] = vals[i + 1] if i + 1 < n else None
            row["lg"] = vals[i - 1] if i >= 1 else None
            lo, hi = max(0, i - 2), min(n - 1, i + 1)
            w = vals[lo : hi + 1]
            row["fsum"] = sum(w)
            row["fmin"] = min(w)
            out.append(row)
    return out


def test_eowc_over_window_lead_and_frames():
    """Lead, lag, rank and a ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING
    frame, computed when the watermark closes each window partition —
    vs a complete-partition SQL oracle. Checkpoint/restore mid-stream."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.over_window import (
        EowcOverWindowExecutor,
        WindowCall,
    )
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    CALLS = (
        WindowCall("row_number", None, "rn"),
        WindowCall("rank", "o", "rk"),
        WindowCall("dense_rank", "o", "drk"),
        WindowCall("lead", "x", "ld"),
        WindowCall("lag", "x", "lg"),
        WindowCall("sum", "x", "fsum", frame=(-2, 1)),
        WindowCall("min", "x", "fmin", frame=(-2, 1)),
    )
    DT = {
        "p": jnp.int64, "w": jnp.int64, "o": jnp.int64, "x": jnp.int64
    }

    def mk(capacity=1 << 9, table_id="eow"):
        return EowcOverWindowExecutor(
            partition_by=("w", "p"),
            order_col="o",
            calls=CALLS,
            schema_dtypes=DT,
            win_col="w",
            capacity=capacity,
            table_id=table_id,
        )

    rng = np.random.default_rng(21)
    all_rows = []
    epochs = []
    for e in range(4):
        n = int(rng.integers(6, 28))
        rows = [
            {
                "p": int(rng.integers(0, 3)),
                "w": int(e // 2),  # two epochs per window
                "o": int(rng.integers(0, 6)),
                "x": int(rng.integers(-20, 20)),
            }
            for _ in range(n)
        ]
        all_rows.extend(rows)
        epochs.append(
            StreamChunk.from_numpy(
                {
                    k: np.asarray([r[k] for r in rows], np.int64)
                    for k in ("p", "w", "o", "x")
                },
                32,
            )
        )

    def run(ex, chunks, wms):
        """Apply chunks, then each watermark; collect emitted rows."""
        got = []
        for c in chunks:
            ex.apply(c)
        for wm_v in wms:
            from risingwave_tpu.executors.base import Watermark

            _, outs = ex.on_watermark(Watermark("w", wm_v))
            for out in outs:
                d = out.to_numpy()
                nl = {
                    k: d.get(k + "__null")
                    for k in ("ld", "lg", "fmin")
                }
                for i in range(len(d["p"])):
                    got.append(
                        {
                            "p": int(d["p"][i]), "w": int(d["w"][i]),
                            "o": int(d["o"][i]), "x": int(d["x"][i]),
                            "rn": int(d["rn"][i]), "rk": int(d["rk"][i]),
                            "drk": int(d["drk"][i]),
                            "ld": None
                            if nl["ld"] is not None and nl["ld"][i]
                            else int(d["ld"][i]),
                            "lg": None
                            if nl["lg"] is not None and nl["lg"][i]
                            else int(d["lg"][i]),
                            "fsum": int(d["fsum"][i]),
                            "fmin": int(d["fmin"][i]),
                        }
                    )
        return got

    # uninterrupted run: close window 0, then window 1
    ex = mk()
    got = run(ex, epochs, [1, 2])
    ex.on_barrier(None)

    want = _eowc_oracle(all_rows, CALLS)
    key = lambda r: (r["w"], r["p"], r["o"], r["rn"])
    assert sorted(got, key=key) == sorted(want, key=key)

    # kill+recover between the two windows: same final output set
    mgr = CheckpointManager(MemObjectStore())
    ex1 = mk()
    got1 = run(ex1, epochs[:2], [1])  # window 0 closed
    mgr.commit_staged(1, mgr.stage([ex1]))
    del ex1

    ex2 = mk()
    mgr.recover([ex2])
    got2 = run(ex2, epochs[2:], [2])
    assert sorted(got1 + got2, key=key) == sorted(want, key=key)
