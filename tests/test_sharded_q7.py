"""The q7 shape on the 8-device mesh (VERDICT r4 #6).

The SQL-planned Nexmark q7 — bids self-joined against their per-window
MAX — runs as sharded fragments: the MAX side is a ShardedHashAgg whose
barrier flush stays STACKED on device and feeds the ShardedHashJoin
directly (the retracting change stream crosses ICI, not the host), and
the MV is a ShardedMaterialize partitioned by pk vnode. Parity is
checked against the serial plan of the same SQL, and the whole sharded
plane (agg + join sides + MV) survives a mid-stream kill + recover.

Reference: every fragment parallelizes
(src/meta/src/stream/stream_graph/actor.rs:648); q7 plan shape
e2e_test/nexmark/.
"""

import pytest

# ~2 min of virtual-mesh compile+replay: deeper-tier only (the tier-1
# budget keeps the cheap sharded parity tests; q7's coverage here is
# the kill/recover + parity pair, still run by plain `pytest`)
pytestmark = pytest.mark.slow

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg
from risingwave_tpu.parallel.sharded_join import ShardedHashJoin
from risingwave_tpu.parallel.sharded_mv import ShardedMaterialize
from risingwave_tpu.runtime.fragmenter import sharded_planned_mv
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.storage.object_store import MemObjectStore

N = 8

Q7_SQL = (
    "CREATE MATERIALIZED VIEW q7 AS "
    "SELECT b.auction, b.bidder, b.price, b.wstart FROM "
    "(SELECT auction, bidder, price, window_start AS wstart "
    " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)) AS b "
    "JOIN "
    "(SELECT max(price) AS maxprice, window_start AS mwstart "
    " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    " GROUP BY window_start) AS m "
    "ON b.wstart = m.mwstart AND b.price = m.maxprice"
)


def _factory():
    cat = Catalog({"bid": BID_SCHEMA})
    return lambda: StreamPlanner(cat, capacity=1 << 14)


def _bid_chunks(n, events=1500, cap=2048, rate=1000):
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=rate))
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def _feed(pipe, chunk):
    pipe.push_left(chunk)
    pipe.push_right(chunk)


def test_sharded_q7_parity():
    """Sharded q7 == serial q7, with the expected sharded executors in
    the plan (agg flush rides ICI into the join; MV pk-partitioned)."""
    serial = _factory()().plan(Q7_SQL)
    sharded = sharded_planned_mv(_factory(), Q7_SQL, N)
    kinds = [type(e).__name__ for e in sharded.pipeline.executors]
    assert any(isinstance(e, ShardedHashAgg) for e in sharded.pipeline.executors), kinds
    assert any(isinstance(e, ShardedHashJoin) for e in sharded.pipeline.executors), kinds
    assert isinstance(sharded.mview, ShardedMaterialize), kinds
    agg = next(
        e for e in sharded.pipeline.executors if isinstance(e, ShardedHashAgg)
    )
    assert agg.stacked_out, "join-side agg must flush stacked chunks"
    for c in _bid_chunks(8):
        _feed(serial.pipeline, c)
        _feed(sharded.pipeline, c)
        serial.pipeline.barrier()
        sharded.pipeline.barrier()
    want = serial.mview.snapshot()
    got = sharded.mview.snapshot()
    sharded.pipeline.close()
    assert len(want) >= 2  # multiple windows closed
    assert got == want


@pytest.mark.smoke
def test_sharded_q7_kill_recover():
    """Mid-stream kill of the whole sharded q7 plane; a fresh plan
    restores agg + both join sides + the sharded MV from the
    checkpoint store and converges to the uninterrupted result."""
    chunks = _bid_chunks(8)
    serial = _factory()().plan(Q7_SQL)
    for c in chunks:
        _feed(serial.pipeline, c)
        serial.pipeline.barrier()
    want = serial.mview.snapshot()

    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=False)
    sharded = sharded_planned_mv(_factory(), Q7_SQL, N)
    rt.register("q7", sharded.pipeline)
    for c in chunks[:4]:
        _feed(sharded.pipeline, c)
        rt.barrier()
    sharded.pipeline.close()  # the kill

    rt2 = StreamingRuntime(store, async_checkpoint=False)
    sharded2 = sharded_planned_mv(_factory(), Q7_SQL, N)
    rt2.register("q7", sharded2.pipeline)
    rt2.recover()
    for c in chunks[4:]:
        _feed(sharded2.pipeline, c)
        rt2.barrier()
    got = sharded2.mview.snapshot()
    sharded2.pipeline.close()
    assert len(want) >= 2
    assert got == want
