"""Pipelined barriers: more than one epoch in flight.

``StreamingRuntime(in_flight_barriers=N)`` returns from ``barrier()``
at ADMISSION (inject only); a closer thread waits for collection,
stages the deltas the actors SEALED at the barrier
(``capture_checkpoint``), and feeds the async commit lane. Epoch N+1's
pushes and compute overlap epoch N's flush/stage/commit.

Reference: up to ``in_flight_barrier_nums`` concurrent epochs
(/root/reference/src/meta/src/barrier/mod.rs:538-541); shared-buffer
seal + async upload (event_handler/uploader.rs:548).
"""

import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.runtime.fragmenter import graph_planned_mv
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)


@pytest.fixture
def catalog():
    return Catalog({"bid": BID_SCHEMA})


def _factory(catalog):
    return lambda: StreamPlanner(catalog, capacity=1 << 12)


def _bid_chunks(n, events=800, cap=1 << 10):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def test_pipelined_matches_sync_and_checkpoints(catalog):
    """N epochs with 4 barriers in flight: identical MV and identical
    recoverable checkpoint as the synchronous runtime."""
    chunks = _bid_chunks(8)

    sync_store = MemObjectStore()
    rt_s = StreamingRuntime(sync_store, async_checkpoint=False)
    mv_s = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt_s.register("q5", mv_s.pipeline)

    pipe_store = MemObjectStore()
    rt_p = StreamingRuntime(pipe_store, in_flight_barriers=4)
    mv_p = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt_p.register("q5", mv_p.pipeline)

    try:
        for i in range(0, 8, 2):
            for c in chunks[i : i + 2]:
                rt_s.push("q5", c)
                rt_p.push("q5", c)
            rt_s.barrier()
            rt_p.barrier()
        rt_p.wait_checkpoints()
        want = mv_s.mview.snapshot()
        assert want
        assert mv_p.mview.snapshot() == want

        # the pipelined run's checkpoint is fully recoverable
        rt_r = StreamingRuntime(pipe_store, async_checkpoint=False)
        mv_r = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
        rt_r.register("q5", mv_r.pipeline)
        rt_r.recover()
        try:
            assert mv_r.mview.snapshot() == want
        finally:
            mv_r.pipeline.close()
    finally:
        mv_s.pipeline.close()
        mv_p.pipeline.close()


def test_admission_overlaps_close(catalog):
    """barrier() returns at admission: admission latency must be far
    below the epoch close latency (the whole point of in-flight
    barriers — barrier-interval < single-barrier latency)."""
    chunks = _bid_chunks(12, events=1200, cap=1 << 11)
    rt = StreamingRuntime(MemObjectStore(), in_flight_barriers=6)
    mv = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=1)
    rt.register("q5", mv.pipeline)
    try:
        # warm compiles outside the measurement
        rt.push("q5", chunks[0])
        rt.barrier()
        rt.wait_epochs()
        rt.barrier_latencies_ms.clear()
        rt.epoch_close_ms.clear()

        for c in chunks[1:]:
            rt.push("q5", c)
            rt.barrier()
        rt.wait_checkpoints()
        adm = float(np.mean(rt.barrier_latencies_ms))
        close = float(np.mean(rt.epoch_close_ms))
        assert len(rt.epoch_close_ms) == 11
        # admission is inject-only: at least 2x faster than full close
        assert adm < close / 2, (adm, close)
    finally:
        mv.pipeline.close()


def test_pipelined_rejects_subscriptions(catalog):
    rt = StreamingRuntime(MemObjectStore(), in_flight_barriers=2)
    up = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=1)
    rt.register("q5", up.pipeline)
    down = graph_planned_mv(
        _factory(Catalog({"bid": BID_SCHEMA})),
        Q5_SQL.replace("q5", "q5b"),
        parallelism=1,
    )
    try:
        rt.register("q5b", down.pipeline, upstream="q5")
        with pytest.raises(ValueError, match="subscription"):
            rt.barrier()
    finally:
        up.pipeline.close()
        down.pipeline.close()


def test_pipelined_recovery_in_flight(catalog):
    """Kill the graph with epochs still in flight; a fresh runtime
    recovers to a committed epoch and replaying the remaining chunks
    converges on the serial oracle."""
    chunks = _bid_chunks(8)
    store = MemObjectStore()
    rt = StreamingRuntime(store, in_flight_barriers=4)
    mv = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt.register("q5", mv.pipeline)
    for i, c in enumerate(chunks[:6]):
        rt.push("q5", c)
        rt.barrier()
    # ensure at least the early epochs are durable, then kill without
    # waiting for the tail to close
    rt.wait_checkpoints()
    committed = rt.mgr.max_committed_epoch
    assert committed > 0
    mv.pipeline.close()

    rt2 = StreamingRuntime(store, async_checkpoint=False)
    mv2 = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt2.register("q5", mv2.pipeline)
    rt2.recover()
    try:
        # recovered state equals a serial run of the first 6 chunks
        oracle = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
        for c in chunks[:6]:
            oracle.pipeline.push(c)
        oracle.pipeline.barrier()
        assert mv2.mview.snapshot() == oracle.mview.snapshot()
        # and the stream continues
        for c in chunks[6:]:
            rt2.push("q5", c)
            rt2.barrier()
        for c in chunks[6:]:
            oracle.pipeline.push(c)
        oracle.pipeline.barrier()
        assert mv2.mview.snapshot() == oracle.mview.snapshot()
    finally:
        mv2.pipeline.close()
