"""UNION schema check at DDL time (VERDICT r4 weak #7) + heap
profiling surface (missing component: heap profiling)."""

import urllib.request

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_union_schema_mismatch_raises_at_ddl():
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.runtime import Pipeline, StreamingRuntime

    rt = StreamingRuntime()
    rt.register(
        "u1", Pipeline([MaterializeExecutor(pk=("a",), columns=("b",),
                                            table_id="u1.mv")])
    )
    rt.register(
        "u2", Pipeline([MaterializeExecutor(pk=("a",), columns=("c",),
                                            table_id="u2.mv")])
    )
    rt.register(
        "sink", Pipeline([MaterializeExecutor(pk=("a",), columns=("b",),
                                              table_id="sink.mv")])
    )
    rt.subscribe("u1", "sink", backfill=False)
    with pytest.raises(ValueError, match="UNION inputs disagree"):
        rt.subscribe("u2", "sink", backfill=False)
    # same-schema second input is fine
    rt.register(
        "u3", Pipeline([MaterializeExecutor(pk=("a",), columns=("b",),
                                            table_id="u3.mv")])
    )
    rt.subscribe("u3", "sink", backfill=False)


def test_heap_endpoint_reports_device_state():
    from risingwave_tpu import utils_heap
    from risingwave_tpu.metrics import REGISTRY

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW hm AS SELECT k, count(*) AS c FROM t "
        "GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 2), (3, 4)")
    utils_heap.start()
    try:
        blob = utils_heap.render()
        assert "TOTAL device state" in blob
        assert "HashAggExecutor" in blob
        assert "host allocations" in blob
        port = REGISTRY.serve(0)
        try:
            got = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/heap", timeout=10
            ).read().decode()
            assert "TOTAL device state" in got
        finally:
            REGISTRY.shutdown()
    finally:
        utils_heap.stop()


def test_dashboard_page_renders():
    from risingwave_tpu.metrics import REGISTRY

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE d (k BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW dm AS SELECT k, count(*) AS c FROM d "
        "GROUP BY k"
    )
    s.execute("INSERT INTO d VALUES (1), (2)")
    port = REGISTRY.serve(0)
    try:
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10
        ).read().decode()
        assert "risingwave_tpu dashboard" in page
        assert "dm" in page  # the fragment appears
        assert "committed epoch" in page
        # /metrics still serves prometheus text
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "# TYPE" in body
    finally:
        REGISTRY.shutdown()


def test_set_system_params_mutates_runtime():
    """SET barrier_interval_ms / checkpoint_frequency are the cluster-
    mutable system params (ALTER SYSTEM surface, system_param/mod.rs)."""
    s = SqlSession(Catalog({}), capacity=1 << 8)
    s.execute("SET barrier_interval_ms = 250")
    s.execute("SET checkpoint_frequency = 4")
    assert s.runtime.barrier_interval_ms == 250
    assert s.runtime.checkpoint_frequency == 4
    with pytest.raises(ValueError):
        s.execute("SET barrier_interval_ms = nope")
