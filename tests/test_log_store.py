"""KV log store sink decoupling: durable batches, at-least-once
delivery, rolled-back epochs never delivered.
Reference: common/log_store_impl/kv_log_store/."""

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.log_store import (
    KvLogStore,
    LogSinker,
    LogStoreSinkExecutor,
)
from risingwave_tpu.connectors.sink import BlackholeSink
from risingwave_tpu.executors.base import Barrier, Epoch
from risingwave_tpu.storage.object_store import MemObjectStore


def _chunk(ks, vs, ops=None, cap=8):
    return StreamChunk.from_numpy(
        {"k": np.asarray(ks), "v": np.asarray(vs)}, cap,
        ops=np.asarray(ops) if ops is not None else None,
    )


class RecordingSink(BlackholeSink):
    def __init__(self):
        super().__init__()
        self.batches = []

    def write_batch(self, rows, epoch):
        super().write_batch(rows, epoch)
        self.batches.append((epoch, rows))


def test_log_store_appends_and_delivers_in_order():
    store = MemObjectStore()
    log = KvLogStore(store, "s1")
    ex = LogStoreSinkExecutor(log, pk=("k",), columns=("v",))
    ex.apply(_chunk([1, 2], [10, 20]))
    ex.on_barrier(Barrier(Epoch(0, 1)))
    ex.finish_barrier()
    ex.apply(_chunk([1], [11]))
    ex.on_barrier(Barrier(Epoch(1, 2)))
    ex.finish_barrier()

    sink = RecordingSink()
    delivered = LogSinker(log, sink).run_once()
    assert delivered == 2
    assert [e for e, _ in sink.batches] == [1, 2]
    assert sink.batches[1][1] == [((1,), (11,), 0)]
    # delivered epochs truncate; nothing pending
    assert log.pending_epochs() == []
    assert LogSinker(log, sink).run_once() == 0  # idempotent


def test_crash_between_delivery_and_offset_redelivers():
    """At-least-once: if the consumer crashed after the sink write but
    before the offset commit, the epoch is delivered again."""
    store = MemObjectStore()
    log = KvLogStore(store, "s1")
    ex = LogStoreSinkExecutor(log, pk=("k",), columns=("v",))
    ex.apply(_chunk([5], [50]))
    ex.on_barrier(Barrier(Epoch(0, 1)))
    ex.finish_barrier()

    sink = RecordingSink()
    # simulate the crash window: write happened, offset did not commit
    sink.write_batch(log.read(1), 1)
    fresh = LogSinker(log, sink)
    assert fresh.run_once() == 1  # redelivered (no lost batch)
    assert len(sink.batches) == 2


def test_rolled_back_epochs_discarded_on_recovery():
    store = MemObjectStore()
    log = KvLogStore(store, "s1")
    ex = LogStoreSinkExecutor(log, pk=("k",), columns=("v",))
    ex.apply(_chunk([1], [10]))
    ex.on_barrier(Barrier(Epoch(0, 1)))
    ex.finish_barrier()
    ex.apply(_chunk([2], [20]))
    ex.on_barrier(Barrier(Epoch(1, 2)))  # this epoch will roll back
    ex.finish_barrier()

    ex.on_recover(1)  # recovery landed on epoch 1
    sink = RecordingSink()
    assert LogSinker(log, sink).run_once() == 1
    assert [e for e, _ in sink.batches] == [1]  # epoch-2 output gone


def test_up_to_respects_durable_frontier():
    store = MemObjectStore()
    log = KvLogStore(store, "s1")
    for e in (1, 2, 3):
        log.append(e, [((e,), (e,), 0)])
    sink = RecordingSink()
    assert LogSinker(log, sink).run_once(up_to=2) == 2
    assert log.pending_epochs() == [3]
