"""Fusion-feasibility analyzer (analysis/fusion_analyzer.py +
analysis/shape_domain.py): seeded chains must classify exactly —
device-fusible proofs for pure chains, RW-E801 host-sync blockers with
file:line provenance, RW-E803 for the unbucketed-window q7 wedge class
— and the CLI / perf-gate / DDL / bench surfaces must carry the
reports. CPU-only, tier-1."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.analysis.fusion_analyzer import (
    analyze_chain,
    analyze_nexmark,
    analyze_pipeline,
    classify_executor,
    report_to_json,
    scan_host_syncs,
)
from risingwave_tpu.analysis.shape_domain import (
    ChunkSpec,
    capacity_bucket,
    trace_signature,
)
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.executors.filter import FilterExecutor
from risingwave_tpu.executors.hop_window import HopWindowExecutor
from risingwave_tpu.executors.project import ProjectExecutor
from risingwave_tpu.expr import expr as E

pytestmark = pytest.mark.smoke

BID_SCHEMA = {"auction": "int64", "date_time": "int64", "price": "int64"}


def _spec(**over):
    schema = dict(BID_SCHEMA)
    schema.update(over)
    return ChunkSpec.from_schema(schema, capacity=256)


# ---------------------------------------------------------------------------
# shape domain
# ---------------------------------------------------------------------------


def test_chunk_spec_abstract_traces():
    spec = _spec()
    sig = trace_signature(lambda c: c.mask(c.col("price") > 0), spec)
    assert sig.in_avals and sig.out_avals
    assert not sig.host_calls
    # unknown dtypes refuse to guess
    assert ChunkSpec.from_schema({"a": None}) is None


def test_capacity_bucket_pow2():
    assert capacity_bucket(1) == 1
    assert capacity_bucket(5) == 8
    assert capacity_bucket(1024) == 1024


# ---------------------------------------------------------------------------
# seeded chains
# ---------------------------------------------------------------------------


class HostSyncingExecutor(Executor):
    """Deliberately host-syncing: reads a device scalar per chunk."""

    def apply(self, chunk):
        n = int(jnp.sum(chunk.valid))  # the blocker under test
        if n > 0:
            return [chunk]
        return [chunk]

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: c,
            "state": None,
            "donate": True,
            "emission": "passthrough",
        }


class UndonatedStatefulExecutor(Executor):
    def __init__(self):
        self.state = jnp.zeros(8, jnp.int64)

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: c,
            "state": self.state,
            "donate": False,
            "emission": "passthrough",
        }


def test_e801_host_sync_with_exact_provenance():
    chain = [HopWindowExecutor("date_time", 10_000, 2_000),
             HostSyncingExecutor()]
    rep = analyze_chain(chain, _spec(), "seeded")
    assert rep.fusible_prefix == 1  # hop proves; the syncer stops it
    assert not rep.whole_chain_fusible
    e801 = [d for d in rep.diagnostics if d.code == "RW-E801"]
    assert e801, rep.diagnostics
    # exact executor + file:line provenance
    assert all(d.executor == "1:HostSyncingExecutor" for d in e801)
    assert any(
        "test_fusion_analyzer" in d.message and ":" in d.message
        for d in e801
    ), [d.message for d in e801]
    # the scanner names the sync site inside apply
    syncs = scan_host_syncs(HostSyncingExecutor())
    assert any(s.method.endswith(".apply") for s in syncs)


def test_e804_undonated_state():
    ec = classify_executor(UndonatedStatefulExecutor(), _spec(), "f", 0)
    assert any(d.code == "RW-E804" for d in ec.blockers)
    assert not ec.fusible


def test_fully_fusible_chain_whole_fragment_proof():
    chain = [
        HopWindowExecutor("date_time", 10_000, 2_000),
        FilterExecutor(E.col("price") > E.lit(10)),
        ProjectExecutor({"auction": E.col("auction")}),
    ]
    rep = analyze_chain(chain, _spec(), "pure")
    assert rep.whole_chain_fusible, [
        (e.name, e.kind, [d.code for d in e.blockers])
        for e in rep.executors
    ]
    assert rep.fusible_prefix == 3
    assert rep.host_sync_points == 0
    # the proof is positive: every executor traced over the lattice
    assert all(e.signatures >= 1 for e in rep.executors)


def test_e803_q7_window_path():
    """The q7 wedge class statically: the deliberately-UNBUCKETED twin
    (``build_q7(bucketed=False)`` — the legacy unbounded-rehash path)
    must yield RW-E803 with exact executor provenance on both the
    dynamic max filter and the join; the SHIPPED bucketed q7 (the lint
    corpus) must be clean — its executors declare the allocator's pow2
    lattice (runtime/bucketing.py)."""
    from risingwave_tpu.analysis.lint import (
        NEXMARK_SOURCE_SCHEMAS,
        build_nexmark_corpus,
    )
    from risingwave_tpu.queries.nexmark_q import build_q7

    twin = build_q7(
        capacity=1 << 8, agg_capacity=1 << 8, filter_capacity=1 << 8,
        out_cap=1 << 8, bucketed=False,
    )
    reports = analyze_pipeline(
        twin.pipeline, NEXMARK_SOURCE_SCHEMAS["q7"], "q7twin"
    )
    e803 = [
        d
        for r in reports
        for d in r.diagnostics
        if d.code == "RW-E803"
    ]
    assert e803
    provs = {d.executor for d in e803}
    assert any("DynamicMaxFilterExecutor" in p for p in provs), provs
    assert any("HashJoinExecutor" in p for p in provs), provs
    # the shipped (bucketed) corpus q7 walks free of the wedge class —
    # the PR-9 acceptance bar: zero RW-E803/E806 on q7's fragments
    q7 = build_nexmark_corpus(only="q7")["q7"]
    q7_reports = analyze_pipeline(
        q7.pipeline, NEXMARK_SOURCE_SCHEMAS["q7"], "q7"
    )
    assert not [
        d
        for r in q7_reports
        for d in r.diagnostics
        if d.code in ("RW-E803", "RW-E806")
    ]
    # q5's windowed agg declares its two-capacity flush lattice: the
    # SAME window machinery, bucketed, must NOT flag
    q5 = build_nexmark_corpus(only="q5")["q5"]
    q5_reports = analyze_pipeline(
        q5.pipeline, NEXMARK_SOURCE_SCHEMAS["q5"], "q5"
    )
    assert not [
        d
        for r in q5_reports
        for d in r.diagnostics
        if d.code == "RW-E803"
    ]


def test_every_nexmark_fragment_classified():
    """Acceptance shape: every fragment carries a whole-chain fusible
    proof or >=1 named RW-E8xx blocker with executor provenance."""
    out = analyze_nexmark(deep=True)
    # provenance rides every regenerated report (stale-artifact
    # detection, PR 11) under a "_"-prefixed key the ratchet skips
    prov = out.pop("_provenance")
    assert prov["engine_generation"] >= 11
    assert set(out) == {"q5", "q7", "q8"}
    for q, rep in out.items():
        assert rep["fragments"], q
        for fr in rep["fragments"]:
            assert fr["whole_chain_fusible"] or any(
                b["code"].startswith("RW-E8") and b["executor"]
                for b in fr["blockers"]
            ), (q, fr)
    # the fused-step PRs burned the corpus down: q5's hop->agg->MV
    # fragment AND every q7/q8 fragment (filter/dedup sides, the
    # join_tail) carry whole-chain fusible proofs with ZERO host syncs
    # (PR 13: note-based growth planning + cold-tier hooks + the
    # join's declared input schema re-anchoring the join_tail trace)
    for q in ("q5", "q7", "q8"):
        for fr in out[q]["fragments"]:
            assert fr["whole_chain_fusible"], (q, fr)
            assert fr["host_sync_points"] == 0, (q, fr)


def test_opaque_executor_stops_prefix():
    class NoContract(Executor):
        def trace_contract(self):
            return None

    chain = [
        HopWindowExecutor("date_time", 10_000, 2_000),
        NoContract(),
        ProjectExecutor({"auction": E.col("auction")}),
    ]
    rep = analyze_chain(chain, _spec(), "opaque")
    assert rep.fusible_prefix == 1
    assert rep.executors[1].kind == "opaque"


# ---------------------------------------------------------------------------
# surfaces: report JSON, perf gate, DDL, bench, SignatureWatch buckets
# ---------------------------------------------------------------------------


def test_report_json_shape_and_summary():
    chain = [ProjectExecutor({"auction": E.col("auction")})]
    rep = report_to_json([analyze_chain(chain, _spec(), "one")])
    assert rep["summary"]["fragments"] == 1
    assert rep["summary"]["fusible_fragments"] == 1
    fr = rep["fragments"][0]
    assert fr["executors"][0]["executor"] == "ProjectExecutor"
    json.dumps(rep)  # JSON-serializable end to end


def test_perf_gate_fusion_clean_and_regression(tmp_path):
    import sys

    sys.path.insert(0, "scripts")
    try:
        from perf_gate import _load, run_fusion_gate
    finally:
        sys.path.pop(0)

    budgets = _load("scripts/perf_budgets.json")
    v, skipped = run_fusion_gate(budgets, "FUSION_REPORT.json")
    assert v == [], v  # committed baseline is green
    # injected regression: baseline claims a longer fusible prefix
    # (q5, already whole-chain) and fewer fallback sync points than
    # reality (the q7 agg side's interpreted-path flush read) -> the
    # ratchet trips on both axes. Host-sync counts are ZERO corpus-
    # wide since PR 13, so the sync ratchet is exercised through the
    # fallback ledger.
    base = _load("FUSION_REPORT.json")
    frag = base["q5"]["fragments"][0]
    frag["fusible_prefix"] += 1
    synced = next(
        f
        for f in base["q7"]["fragments"]
        if f.get("fallback_sync_points", 0) > 0
    )
    synced["fallback_sync_points"] = 0
    p = tmp_path / "base.json"
    p.write_text(json.dumps(base))
    v, _ = run_fusion_gate(budgets, str(p))
    assert any("fusible prefix regressed" in x for x in v), v
    assert any("fallback-sync points grew" in x for x in v), v
    # unreadable baseline skips, never crashes CI
    v, skipped = run_fusion_gate(budgets, str(tmp_path / "nope.json"))
    assert v == [] and skipped


def test_ddl_fusion_findings_and_strict_gate(monkeypatch):
    """Strict-fusion is ON BY DEFAULT now that the bucketing layer
    exists: an unbucketed (E803) window-keyed plan is refused at
    CREATE MV; the shipped bucketed q7 sails through; and
    RW_STRICT_FUSION=0 restores report-only mode."""
    from risingwave_tpu.analysis.diagnostics import PlanLintError
    from risingwave_tpu.analysis.lint import fusion_findings_for_ddl
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.queries.nexmark_q import build_q7
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog

    twin = build_q7(capacity=1 << 8, agg_capacity=1 << 8,
                    filter_capacity=1 << 8, out_cap=1 << 8,
                    bucketed=False)

    class Shim:
        name = "q7"
        pipeline = twin.pipeline

    diags = fusion_findings_for_ddl(Shim())
    assert diags and all(d.code == "RW-E803" for d in diags)

    q7 = build_q7(capacity=1 << 8, agg_capacity=1 << 8,
                  filter_capacity=1 << 8, out_cap=1 << 8)

    class CleanShim:
        name = "q7clean"
        pipeline = q7.pipeline

    assert fusion_findings_for_ddl(CleanShim()) == []

    session = SqlSession(Catalog({}), StreamingRuntime(store=None))
    monkeypatch.delenv("RW_STRICT_FUSION", raising=False)
    # strict by default: the wedge class is refused at CREATE MV
    with pytest.raises(PlanLintError):
        session._fusion_lint(Shim(), strict=True)
    # ... but the bucketed plan is not
    session._fusion_lint(CleanShim(), strict=True)
    # RW_STRICT_FUSION=0: report-only (records, never raises)
    monkeypatch.setenv("RW_STRICT_FUSION", "0")
    session._fusion_lint(Shim(), strict=True)
    assert any(
        d.code == "RW-E803" for _n, d in session.lint_findings
    )
    monkeypatch.setenv("RW_STRICT_FUSION", "1")
    with pytest.raises(PlanLintError):
        session._fusion_lint(Shim(), strict=True)
    # strict_lint=False (e.g. DDL replay) still never refuses
    session._fusion_lint(Shim(), strict=False)


def test_bench_gate_returns_fusion_summary():
    import bench

    fusion = bench._rwlint_gate("q5")
    assert fusion is not None
    assert fusion["summary"]["chain_len_total"] == 3
    assert fusion["fragments"][0]["fusible_prefix"] >= 1
    assert all("blocker_codes" in f for f in fusion["fragments"])


def test_signature_watch_records_shape_bucket():
    from risingwave_tpu.analysis.jax_sanitizer import SignatureWatch
    from risingwave_tpu.metrics import REGISTRY

    watch = SignatureWatch().start()
    ex = ProjectExecutor({"x": E.col("a")})
    watch.observe(ex, StreamChunk.from_numpy({"a": np.arange(4)}, 4))
    watch.mark_stable()
    before = REGISTRY.counter("recompile_hazard_bucket_total").get(
        executor="ProjectExecutor", bucket="32"
    )
    watch.observe(ex, StreamChunk.from_numpy({"a": np.arange(8)}, 32))
    diags = watch.report()
    assert [d.code for d in diags] == ["RW-E403"]
    # the hazard names the capacity bucket and cross-references the
    # static finding class
    assert "bucket" in diags[0].message and "RW-E803" in diags[0].message
    assert (
        REGISTRY.counter("recompile_hazard_bucket_total").get(
            executor="ProjectExecutor", bucket="32"
        )
        == before + 1
    )
    watch.stop()


def test_lint_cli_fusion_report_json(capsys):
    """python -m risingwave_tpu lint --fusion-report --all-nexmark
    --json: classifies every fragment; the bucketed corpus carries
    ZERO RW-E803/E806 (the PR-9 acceptance bar) AND zero RW-E801
    (the PR-13 two-input burn-down: the whole corpus is host-sync
    free on its hot paths)."""
    import argparse

    from risingwave_tpu.analysis.lint import run_cli

    args = argparse.Namespace(
        paths=[],
        all_nexmark=True,
        deep=False,
        json=True,
        fusion_report=True,
    )
    rc = run_cli(args)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    fus = out["__fusion__"]
    assert "_provenance" in fus  # stamped for stale-artifact detection
    assert set(fus) - {"_provenance"} == {"q5", "q7", "q8"}
    for q in list(fus):
        if q.startswith("_"):
            continue
        assert not any(
            b["code"] in ("RW-E801", "RW-E803", "RW-E806")
            for fr in fus[q]["fragments"]
            for b in fr["blockers"]
        ), q


# ---------------------------------------------------------------------------
# satellite: lint_info coverage on previously-opaque executors
# ---------------------------------------------------------------------------


def test_new_lint_info_coverage_visible_to_verifier():
    """The satellite executors expose real metadata now: a seeded
    missing-column plan is caught (no more silent opacity)."""
    from risingwave_tpu.analysis.diagnostics import LintReport
    from risingwave_tpu.analysis.plan_verifier import _walk_chain, _TableIds
    from risingwave_tpu.executors.simple_agg import SimpleAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    agg = SimpleAggExecutor(
        (AggCall("sum", "missing_col", "s"),),
        {"missing_col": jnp.int64},
        table_id="t.simple",
    )
    rep = LintReport()
    _walk_chain(
        [agg], {"a": jnp.dtype("int64")}, {"a"}, "f", rep, _TableIds(rep)
    )
    assert any(d.code == "RW-E101" for d in rep.diagnostics)


def test_new_lint_info_smoke_all_satellites():
    """Every satellite executor returns a dict (not None, not raising)
    so the verifier and the fusion analyzer both see it."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )
    from risingwave_tpu.executors.expand import ExpandExecutor
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.executors.lookup import (
        DeltaJoinExecutor,
        IndexArrangement,
    )
    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )
    from risingwave_tpu.executors.project_set import ProjectSetExecutor
    from risingwave_tpu.executors.simple_agg import SimpleAggExecutor
    from risingwave_tpu.executors.sort import SortExecutor
    from risingwave_tpu.executors.temporal_join import (
        TemporalJoinExecutor,
    )
    from risingwave_tpu.ops.agg import AggCall

    dt = {"a": jnp.int64, "t": jnp.int64}
    left = IndexArrangement(("a",), ("t",), ("a", "t"), "t.l")
    right = IndexArrangement(("a",), ("t",), ("a", "t"), "t.r")
    agg = HashAggExecutor(
        group_keys=("a",),
        calls=(AggCall("count_star", None, "n"),),
        schema_dtypes=dt,
        capacity=64,
        table_id="t.agg",
    )
    execs = [
        SimpleAggExecutor(
            (AggCall("count_star", None, "n"),), dt, table_id="t.sa"
        ),
        SortExecutor("t", dt, capacity=64, table_id="t.sort"),
        TemporalJoinExecutor(left, ("a",), ("a",)),
        DeltaJoinExecutor(
            left, right, ("a",), ("a",),
            (("a", "a"),), (("t2", "t"),),
        ),
        OverWindowExecutor(
            ("a",), (WindowCall("count", None, "n"),), dt,
            capacity=64, table_id="t.ow",
        ),
        ExpandExecutor((("a",), ("t",))),
        ProjectSetExecutor(
            "generate_series", out="v", start_col="a", stop_col="t"
        ),
        EpochBatchedAggExecutor([], agg),
    ]
    for ex in execs:
        info = ex.lint_info()
        assert isinstance(info, dict), type(ex).__name__
        # and a trace contract (or an honest host classification)
        contract = ex.trace_contract()
        assert contract is None or contract["kind"] in (
            "device",
            "host",
        ), type(ex).__name__


def test_epoch_batch_lint_info_composes():
    """The wrapper's metadata equals walking its members: requires
    trace back through the prefix, the agg's emits surface, and
    opacity propagates when a member is opaque."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    hop = HopWindowExecutor("date_time", 10_000, 2_000)
    agg = HashAggExecutor(
        group_keys=("auction", "window_start"),
        calls=(AggCall("count_star", None, "num"),),
        schema_dtypes={
            "auction": jnp.int64,
            "window_start": jnp.int64,
        },
        capacity=64,
        table_id="t.q5agg",
    )
    wrapper = EpochBatchedAggExecutor([hop], agg)
    info = wrapper.lint_info()
    # window_start is hop-computed: the wrapper requires only true
    # input columns
    assert set(info["requires"]) == {"auction", "date_time"}
    assert "num" in info["emits"]
    assert info["table_ids"] == ("t.q5agg",)
    assert info["watermark_map"] == {"date_time": "window_start"}

    class Opaque(Executor):
        def pure_step(self):
            return None

    agg2 = HashAggExecutor(
        group_keys=("auction",),
        calls=(AggCall("count_star", None, "num"),),
        schema_dtypes={"auction": jnp.int64},
        capacity=64,
        table_id="t.q5agg2",
    )
    try:
        w2 = EpochBatchedAggExecutor([Opaque()], agg2)
    except ValueError:
        return  # wrapper refuses opaque prefixes outright: also fine
    assert w2.lint_info() is None
