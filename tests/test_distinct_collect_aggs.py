"""DISTINCT aggregates (count(DISTINCT x) / approx_count_distinct) in
streaming and batch, plus the batch collect aggregates string_agg /
array_agg.

Reference: executor/aggregation/distinct.rs (distinct dedup tables),
impl/src/aggregate/approx_count_distinct.rs, string_agg.rs.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _sess():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_streaming_count_distinct_incremental():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, u BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, count(DISTINCT u) AS d FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 7), (1, 7), (1, 8), (2, 7)")
    out, _ = s.execute("SELECT k, d FROM m ORDER BY k")
    assert list(out["d"]) == [2, 1]
    # duplicates never re-count; new values do
    s.execute("INSERT INTO t VALUES (1, 7), (1, 9)")
    out, _ = s.execute("SELECT k, d FROM m ORDER BY k")
    assert list(out["d"]) == [3, 1]


def test_streaming_approx_count_distinct():
    s = _sess()
    s.execute("CREATE TABLE t (u BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT approx_count_distinct(u) AS d FROM t GROUP BY u"
    )
    # grouped by u itself: every group has exactly 1 distinct value
    s.execute("INSERT INTO t VALUES (5), (5), (6)")
    out, _ = s.execute("SELECT d FROM m")
    assert list(out["d"]) == [1, 1]


def test_streaming_mixed_distinct_plain_rejected():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, u BIGINT)")
    with pytest.raises(NotImplementedError, match="mixing"):
        s.execute(
            "CREATE MATERIALIZED VIEW m AS "
            "SELECT k, count(DISTINCT u) AS d, sum(u) AS s "
            "FROM t GROUP BY k"
        )


def test_batch_count_distinct_and_approx():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, u BIGINT)")
    s.execute(
        "INSERT INTO t VALUES (1, 7), (1, 7), (1, 8), (2, 9), (2, 9)"
    )
    out, _ = s.execute(
        "SELECT k, count(DISTINCT u) AS d FROM t GROUP BY k ORDER BY k"
    )
    assert list(out["d"]) == [2, 1]
    out, _ = s.execute("SELECT approx_count_distinct(u) AS d FROM t")
    assert out["d"][0] == 3


def test_batch_string_agg():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, name VARCHAR)")
    s.execute(
        "INSERT INTO t VALUES (1, 'a'), (1, 'b'), (2, 'c')"
    )
    out, _ = s.execute(
        "SELECT k, string_agg(name, ',') AS names FROM t "
        "GROUP BY k ORDER BY k"
    )
    # without ORDER BY the concatenation order is unspecified (PG)
    assert sorted(out["names"][0].split(",")) == ["a", "b"]
    assert out["names"][1] == "c"
    out, _ = s.execute("SELECT string_agg(name, '-') AS n FROM t")
    assert sorted(out["n"][0].split("-")) == ["a", "b", "c"]


def test_batch_array_agg():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
    out, _ = s.execute(
        "SELECT k, array_agg(v) AS vs FROM t GROUP BY k ORDER BY k"
    )
    assert [sorted(x) for x in out["vs"]] == [[10, 20], [5]]
    out, _ = s.execute("SELECT array_agg(v) AS vs FROM t")
    assert sorted(out["vs"][0]) == [5, 10, 20]


def test_streaming_sum_distinct():
    """sum(DISTINCT x) lowers to sum over the dedup stage, NOT count
    (review finding r5)."""
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, sum(DISTINCT x) AS sd FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (1, 10), (1, 20)")
    out, _ = s.execute("SELECT sd FROM m")
    assert list(out["sd"]) == [30]  # not 2 (count) and not 40 (plain)


def test_streaming_global_count_distinct():
    s = _sess()
    s.execute("CREATE TABLE t (u BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT count(DISTINCT u) AS d FROM t"
    )
    s.execute("INSERT INTO t VALUES (7), (7), (8)")
    out, _ = s.execute("SELECT d FROM m")
    assert out["d"][0] == 2


def test_avg_distinct_rejected_not_silent():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    with pytest.raises(NotImplementedError, match="DISTINCT"):
        s.execute(
            "CREATE MATERIALIZED VIEW m AS "
            "SELECT k, avg(DISTINCT x) AS a FROM t GROUP BY k"
        )


def test_collect_aggs_null_semantics():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, name VARCHAR, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 'a', 1), (1, NULL, NULL)")
    # string_agg over zero surviving rows -> NULL
    out, _ = s.execute(
        "SELECT string_agg(name, ',') AS sa FROM t WHERE k = 99"
    )
    assert out["sa"][0] is None
    # array_agg preserves NULL elements
    out, _ = s.execute("SELECT array_agg(v) AS vs FROM t")
    assert sorted(out["vs"][0], key=lambda x: (x is None, x)) == [1, None]


def test_streaming_count_distinct_ignores_nulls():
    """NULL distinct-column rows filter out before the dedup stage
    (PG: count(DISTINCT u) ignores NULLs; review finding r5: they used
    to crash the dedup executor)."""
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, u BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, count(DISTINCT u) AS d FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 7), (1, NULL), (1, 7)")
    out, _ = s.execute("SELECT k, d FROM m")
    assert list(out["d"]) == [1]


def test_array_agg_decodes_varchar_elements():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, name VARCHAR)")
    s.execute("INSERT INTO t VALUES (1, 'alpha'), (1, 'beta')")
    out, _ = s.execute("SELECT array_agg(name) AS ns FROM t")
    assert sorted(out["ns"][0]) == ["alpha", "beta"]
    out, _ = s.execute(
        "SELECT k, array_agg(name) AS ns FROM t GROUP BY k"
    )
    assert sorted(out["ns"][0]) == ["alpha", "beta"]


def test_distinct_on_scalar_function_rejected():
    s = _sess()
    s.execute("CREATE TABLE t (name VARCHAR)")
    s.execute("INSERT INTO t VALUES ('a')")
    with pytest.raises(Exception, match="DISTINCT"):
        s.execute("SELECT upper(DISTINCT name) AS u FROM t")
