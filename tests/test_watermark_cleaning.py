"""Skip-watermark state cleaning in storage compaction.

Reference: StateTable::update_watermark (state_table.rs:1133) ->
Hummock table watermarks -> compaction dropping expired keys
(iterator/skip_watermark.rs). Closed-window state that was never
tombstoned (the EOWC path frees device state silently) reclaims its
DURABLE footprint here.
"""

import numpy as np
import pytest

from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import (
    CheckpointManager,
    StateDelta,
)

pytestmark = pytest.mark.smoke


def _commit(mgr, epoch, tid, ks, vs, tomb=None):
    n = len(ks)
    mgr.commit_staged(
        epoch,
        [
            StateDelta(
                tid,
                {"k0": np.asarray(ks, np.int64)},
                {"v": np.asarray(vs, np.int64)},
                np.zeros(n, bool) if tomb is None else np.asarray(tomb),
                ("k0",),
            )
        ],
    )


def test_compaction_drops_expired_keys():
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    for e in range(1, 5):
        _commit(mgr, e, "t", [e * 10, e * 10 + 1], [e, e])
    mgr.update_table_watermark("t", "k0", 30)
    assert mgr.compact_once("t", 10)
    keys, _ = mgr.read_table("t")
    ks = sorted(np.asarray(keys["k0"]).tolist())
    assert ks == [30, 31, 40, 41]  # 10/11/20/21 expired
    # watermark is monotonic: an older value cannot regress it
    mgr.update_table_watermark("t", "k0", 5)
    assert mgr.table_watermark("t") == ("k0", 30)


def test_watermark_survives_manifest_reload():
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    _commit(mgr, 1, "t", [1, 100], [0, 0])
    mgr.update_table_watermark("t", "k0", 50)
    mgr2 = CheckpointManager(store, compact_at=2)
    assert mgr2.table_watermark("t") == ("k0", 50)
    _commit(mgr2, 2, "t", [2, 200], [0, 0])
    assert mgr2.compact_once("t", 10)
    keys, _ = mgr2.read_table("t")
    assert sorted(np.asarray(keys["k0"]).tolist()) == [100, 200]


def test_eowc_agg_forwards_cleaning_watermark():
    """An EOWC-style HashAgg (window_key, emit_deletes=False) frees
    device state silently; its cleaning watermark must reach the
    manager at stage() so compaction reclaims the durable rows."""
    import jax.numpy as jnp

    from risingwave_tpu.executors.base import Watermark
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    agg = HashAggExecutor(
        ("ws",),
        (AggCall("count_star", None, "n"),),
        {"ws": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id="q.agg",
        window_key=("ws", 0, False),  # EOWC: no delete emission
    )
    from risingwave_tpu.array.chunk import StreamChunk

    for e, ws in enumerate(((1000, 2000), (2000, 3000)), start=1):
        agg.apply(
            StreamChunk.from_numpy(
                {
                    "ws": np.asarray(ws, np.int64),
                    "v": np.asarray([1, 1], np.int64),
                },
                4,
            )
        )
        mgr.commit_epoch(e, [agg])
    # watermark closes windows < 2500
    agg.on_watermark(Watermark("ws", 2500))
    assert agg.cleaning_watermarks() == [("q.agg", "k0", 2500)]
    mgr.commit_epoch(3, [agg])  # stage() forwards the watermark
    assert mgr.table_watermark("q.agg") == ("k0", 2500)
    # two fresh L0 deltas re-arm the compaction threshold
    agg.apply(
        StreamChunk.from_numpy(
            {
                "ws": np.asarray([3000, 4000], np.int64),
                "v": np.asarray([1, 1], np.int64),
            },
            4,
        )
    )
    mgr.commit_epoch(4, [agg])
    # threshold compaction (inline or manual) applies the watermark
    mgr.compact_once("q.agg", 10)
    keys, _ = mgr.read_table("q.agg")
    ks = sorted(np.asarray(keys["k0"]).tolist())
    assert all(k >= 2500 for k in ks), ks
    assert 3000 in ks


def test_watermark_durability_rides_epoch_commit():
    """A staged-but-uncommitted epoch must NOT have persisted its
    cleaning watermark: compaction acting on an early watermark could
    destroy state whose downstream emissions were never durable
    (review finding r5)."""
    import jax.numpy as jnp

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.base import Watermark
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=99)
    agg = HashAggExecutor(
        ("ws",), (AggCall("count_star", None, "n"),),
        {"ws": jnp.int64}, capacity=1 << 8, table_id="w.agg",
        window_key=("ws", 0, False),
    )
    agg.apply(
        StreamChunk.from_numpy({"ws": np.asarray([1000], np.int64)}, 2)
    )
    mgr.commit_epoch(1, [agg])
    agg.on_watermark(Watermark("ws", 5000))
    staged = mgr.stage([agg])  # buffers the watermark, does NOT persist
    assert mgr.table_watermark("w.agg") is None
    # a fresh manager over the same store sees no watermark either
    assert CheckpointManager(store).table_watermark("w.agg") is None
    mgr.commit_staged(2, staged)  # durable together with the epoch
    assert mgr.table_watermark("w.agg") == ("k0", 5000)
    assert CheckpointManager(store).table_watermark("w.agg") == (
        "k0", 5000,
    )
