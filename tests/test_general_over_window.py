"""General (retractable) OverWindow: randomized insert/delete/update
streams vs a full-recompute oracle, maintained through the executor's
retract/re-emit diffs; checkpoint/restore parity mid-stream.

Reference: src/stream/src/executor/over_window/general.rs:49 (any
change retracts and re-emits the affected frames)."""

import pytest as _pytest

pytestmark = _pytest.mark.smoke

CAP = 64  # chunk capacity


def _mk_exec(jnp, calls, capacity=1 << 9):
    from risingwave_tpu.executors.over_window import (
        GeneralOverWindowExecutor,
    )

    return GeneralOverWindowExecutor(
        partition_by=("p",),
        order_col="o",
        pk=("id",),
        calls=calls,
        schema_dtypes={
            "id": jnp.int64,
            "p": jnp.int64,
            "o": jnp.int64,
            "x": jnp.int64,
        },
        capacity=capacity,
        nullable=("x",),
    )


def _oracle(rows, calls):
    """Full recompute: rows = {id: (p, o, x_or_None, seq)} -> set of
    emitted tuples (id, p, o, x, out1, out2, ...) with None for NULL."""
    by_part = {}
    for rid, (p, o, x, seq) in rows.items():
        by_part.setdefault(p, []).append((o, seq, rid, x))
    out = set()
    for p, items in by_part.items():
        items.sort()
        n = len(items)
        for i, (o, seq, rid, x) in enumerate(items):
            vals = []
            for c in calls:
                if c.kind == "row_number":
                    vals.append(i + 1)
                elif c.kind == "rank":
                    vals.append(
                        1 + sum(1 for it in items if it[0] < o)
                    )
                elif c.kind == "dense_rank":
                    vals.append(
                        1 + len({it[0] for it in items if it[0] < o})
                    )
                elif c.kind == "sum" and c.frame is None:
                    window = [
                        it[3]
                        for it in items[: i + 1]
                        if it[3] is not None
                    ]
                    vals.append(sum(window))
                elif c.kind == "min" and c.frame is None:
                    window = [
                        it[3]
                        for it in items[: i + 1]
                        if it[3] is not None
                    ]
                    vals.append(min(window) if window else None)
                elif c.kind == "sum" and c.frame is not None:
                    lo, hi = c.frame
                    window = [
                        items[j][3]
                        for j in range(max(0, i + lo), min(n, i + hi + 1))
                        if items[j][3] is not None
                    ]
                    # frame sum is NULL when no non-NULL row is in frame
                    vals.append(sum(window) if window else None)
                elif c.kind == "lead":
                    j = i + c.offset
                    vals.append(items[j][3] if j < n else None)
                elif c.kind == "lag":
                    j = i - c.offset
                    vals.append(items[j][3] if j >= 0 else None)
                else:
                    raise AssertionError(c.kind)
            out.add((rid, p, o, x) + tuple(vals))
    return out


def _drive(ex, chunks_ops, calls, mv=None, np=None):
    """Push op lists through the executor, maintaining the downstream
    MV from its retract/insert emissions. Returns the MV set."""
    from risingwave_tpu.array.chunk import StreamChunk

    mv = set() if mv is None else mv
    out_names = [c.output for c in calls]
    for ops_rows in chunks_ops:
        cols = {
            "id": np.array([r[1] for r in ops_rows], np.int64),
            "p": np.array([r[2] for r in ops_rows], np.int64),
            "o": np.array([r[3] for r in ops_rows], np.int64),
            "x": np.array(
                [0 if r[4] is None else r[4] for r in ops_rows], np.int64
            ),
        }
        nulls = {"x": np.array([r[4] is None for r in ops_rows], bool)}
        opcodes = np.array(
            [0 if r[0] == "+" else 1 for r in ops_rows], np.int32
        )
        chunk = StreamChunk.from_numpy(
            cols, CAP, ops=opcodes, nulls=nulls
        )
        for out in ex.apply(chunk):
            d = out.to_numpy()
            for i in range(len(d["id"])):
                x = (
                    None
                    if d.get("x__null", np.zeros(len(d["id"]), bool))[i]
                    else int(d["x"][i])
                )
                vals = tuple(
                    None
                    if d.get(f"{nm}__null", np.zeros(len(d["id"]), bool))[
                        i
                    ]
                    else int(d[nm][i])
                    for nm in out_names
                )
                row = (
                    int(d["id"][i]),
                    int(d["p"][i]),
                    int(d["o"][i]),
                    x,
                ) + vals
                if int(d["__op__"][i]) == 1:  # DELETE
                    assert row in mv, f"retracting absent row {row}"
                    mv.remove(row)
                else:
                    assert row not in mv, f"double insert {row}"
                    mv.add(row)
        ex.on_barrier(None)
    return mv


def _random_stream(rng, n_chunks, rows, next_id):
    """Generate chunks of mixed +/- ops; returns (chunks, rows, next_id)
    where rows tracks the live {id: (p, o, x, seq)} set."""
    chunks = []
    seq = [0]
    for _ in range(n_chunks):
        ops_rows = []
        n = int(rng.integers(3, 20))
        for _ in range(n):
            r = rng.random()
            if r < 0.55 or not rows:
                rid = next_id
                next_id += 1
                p = int(rng.integers(0, 3))
                o = int(rng.integers(0, 40))
                x = (
                    None
                    if rng.random() < 0.15
                    else int(rng.integers(-50, 50))
                )
                ops_rows.append(("+", rid, p, o, x))
                rows[rid] = (p, o, x, seq[0])
                seq[0] += 1
            elif r < 0.85:
                rid = int(rng.choice(list(rows)))
                p, o, x, _ = rows.pop(rid)
                ops_rows.append(("-", rid, p, o, x))
            else:  # update: -old +new, same pk
                rid = int(rng.choice(list(rows)))
                p, o, x, _ = rows.pop(rid)
                ops_rows.append(("-", rid, p, o, x))
                o2 = int(rng.integers(0, 40))
                x2 = (
                    None
                    if rng.random() < 0.15
                    else int(rng.integers(-50, 50))
                )
                ops_rows.append(("+", rid, p, o2, x2))
                rows[rid] = (p, o2, x2, seq[0])
                seq[0] += 1
        chunks.append(ops_rows)
    return chunks, rows, next_id


def test_retractable_rank_and_frames_oracle():
    """Inserts/deletes/updates anywhere in the order shift ranks, sums
    and frames; the maintained MV must equal a full recompute."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.executors.over_window import WindowCall

    calls = (
        WindowCall("row_number", None, "rn"),
        WindowCall("rank", "o", "rk"),
        WindowCall("dense_rank", "o", "dr"),
        WindowCall("sum", "x", "sx"),
        WindowCall("min", "x", "mn"),
        WindowCall("sum", "x", "fs", frame=(-1, 0)),
        WindowCall("lead", "x", "ld"),
        WindowCall("lag", "x", "lg"),
    )
    ex = _mk_exec(jnp, calls)
    rng = np.random.default_rng(11)
    rows = {}
    chunks, rows, _ = _random_stream(rng, 8, rows, 0)
    mv = _drive(ex, chunks, calls, np=np)
    assert mv == _oracle(rows, calls)


def test_rank_ties_and_ooo_arrivals():
    """Ties in the order column and out-of-order arrivals (forbidden in
    the append-only executor) are exactly handled here."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.executors.over_window import WindowCall

    calls = (
        WindowCall("rank", "o", "rk"),
        WindowCall("dense_rank", "o", "dr"),
        WindowCall("row_number", None, "rn"),
    )
    ex = _mk_exec(jnp, calls)
    # descending arrival order + ties
    chunks = [
        [("+", 0, 1, 30, 5), ("+", 1, 1, 20, 6), ("+", 2, 1, 30, 7)],
        [("+", 3, 1, 10, 8), ("+", 4, 1, 20, 9)],
        [("-", 1, 1, 20, 6)],
    ]
    rows = {
        0: (1, 30, 5, 0),
        2: (1, 30, 7, 2),
        3: (1, 10, 8, 3),
        4: (1, 20, 9, 4),
    }
    mv = _drive(ex, chunks, calls, np=np)
    assert mv == _oracle(rows, calls)


def test_same_chunk_partition_move_dirties_old_partition():
    """-old/+new in ONE chunk moving a row between partitions must
    re-emit the remaining rows of the OLD partition (their row_numbers
    shift)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.executors.over_window import WindowCall

    calls = (
        WindowCall("row_number", None, "rn"),
        WindowCall("sum", "x", "sx"),
    )
    ex = _mk_exec(jnp, calls)
    chunks = [
        [
            ("+", 0, 1, 10, 5),
            ("+", 1, 1, 20, 6),
            ("+", 2, 1, 30, 7),
        ],
        # move id=1 from partition 1 to partition 2 in one fused chunk
        [("-", 1, 1, 20, 6), ("+", 1, 2, 20, 6)],
    ]
    rows = {
        0: (1, 10, 5, 0),
        1: (2, 20, 6, 3),
        2: (1, 30, 7, 2),
    }
    mv = _drive(ex, chunks, calls, np=np)
    assert mv == _oracle(rows, calls)


def test_churn_keeps_capacity_bounded():
    """Insert+delete with ever-fresh pks must compact at rehash, not
    double capacity forever (dead slots are reclaimed)."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.executors.over_window import WindowCall

    calls = (WindowCall("row_number", None, "rn"),)
    ex = _mk_exec(jnp, calls, capacity=1 << 7)
    rid = 0
    mv = set()
    for _ in range(40):
        # insert 8 fresh rows, then delete them next chunk
        ins = [("+", rid + i, 0, i, i) for i in range(8)]
        dels = [("-", rid + i, 0, i, i) for i in range(8)]
        rid += 8
        mv = _drive(ex, [ins, dels], calls, mv=mv, np=np)
        ex.checkpoint_delta()  # flush sdirty so slots become reclaimable
    assert mv == set()
    assert ex.capacity <= 1 << 9, (
        f"arena grew to {ex.capacity} despite zero live rows"
    )


def test_checkpoint_restore_mid_stream():
    """Kill after k chunks, restore from accumulated deltas, continue:
    the MV matches an uninterrupted run AND the oracle."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.executors.over_window import WindowCall

    calls = (
        WindowCall("row_number", None, "rn"),
        WindowCall("rank", "o", "rk"),
        WindowCall("sum", "x", "sx"),
        WindowCall("lead", "x", "ld"),
    )
    rng = np.random.default_rng(23)
    rows = {}
    chunks, rows, _ = _random_stream(rng, 10, rows, 0)

    ex = _mk_exec(jnp, calls)
    store = {}  # durable KV: key tuple -> value dict

    def commit(deltas):
        for d in deltas:
            n = len(next(iter(d.key_cols.values()))) if d.key_cols else 0
            for i in range(n):
                k = tuple(int(d.key_cols[kn][i]) for kn in d.key_order)
                if d.tombstone[i]:
                    store.pop(k, None)
                else:
                    store[k] = {
                        vn: v[i] for vn, v in d.value_cols.items()
                    }

    mv = _drive(ex, chunks[:6], calls, np=np)
    commit(ex.checkpoint_delta())

    # restore into a fresh executor from the durable store
    ex2 = _mk_exec(jnp, calls)
    if store:
        keys = sorted(store)
        key_cols = {
            "k0": np.array([k[0] for k in keys], np.int64),
        }
        value_cols = {
            vn: np.array([store[k][vn] for k in keys])
            for vn in next(iter(store.values()))
        }
        ex2.restore_state("general_over", key_cols, value_cols)
    mv2 = _drive(ex2, chunks[6:], calls, mv=set(mv), np=np)
    assert mv2 == _oracle(rows, calls)
