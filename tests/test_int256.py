"""INT256 composite type: 4 little-endian int64 limb lanes.

Reference: src/common/src/types/ int256 (a 4-limb wide integer used
where int64 sums would overflow). TPU re-design: fixed-width limb
lanes keep the device layout static; arithmetic happens at the host
edges (the reference's int256 is host-side too — no SIMD kernels).
"""

import numpy as np
import pytest

from risingwave_tpu.array.composite import (
    _int256_to_limbs,
    _limbs_to_int256,
    decode_column,
    encode_column,
    expand_field,
)
from risingwave_tpu.types import DataType, Field

pytestmark = pytest.mark.smoke


def test_limb_round_trip_extremes():
    cases = [
        0, 1, -1, (1 << 255) - 1, -(1 << 255), 1 << 200, -(1 << 200),
        123456789, -987654321, (1 << 64), (1 << 128) + 7,
    ]
    for v in cases:
        assert _limbs_to_int256(_int256_to_limbs(v)) == v
    with pytest.raises(OverflowError):
        _int256_to_limbs(1 << 255)
    with pytest.raises(OverflowError):
        _int256_to_limbs(-(1 << 255) - 1)


def test_expand_encode_decode_with_nulls():
    f = Field("x", DataType.INT256)
    lanes_spec = expand_field(f)
    assert [n for n, _ in lanes_spec] == ["x.l0", "x.l1", "x.l2", "x.l3"]
    assert all(d == np.dtype(np.int64) for _, d in lanes_spec)
    vals = [1 << 100, None, -(1 << 200), 42]
    lanes, nulls = encode_column(f, vals)
    assert set(lanes) == {"x.l0", "x.l1", "x.l2", "x.l3"}
    assert nulls is not None and list(nulls["x.l0"]) == [
        False, True, False, False,
    ]
    got = decode_column(
        f, lanes, lambda n: nulls.get(n) if nulls else None
    )
    assert got == [1 << 100, None, -(1 << 200), 42]


def test_int256_sum_via_host():
    """The int64-overflow use case: limb decode -> python bigint sum."""
    f = Field("x", DataType.INT256)
    big = (1 << 80) + 5
    vals = [big, big, big]
    lanes, nulls = encode_column(f, vals)
    decoded = decode_column(f, lanes, lambda n: None)
    assert sum(decoded) == 3 * big


def test_ddl_gated_like_other_composites():
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    s = SqlSession(Catalog({}), capacity=1 << 10)
    with pytest.raises(NotImplementedError, match="INT256"):
        s.execute("CREATE TABLE t (x INT256)")
