"""ProjectSet: unnest over LIST lanes + generate_series expansion.
Reference: src/stream/src/executor/project_set.rs."""

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.composite import encode_column
from risingwave_tpu.executors.project_set import ProjectSetExecutor
from risingwave_tpu.types import DataType, Field


def test_unnest_expands_list_rows():
    f = Field("xs", DataType.LIST, elem=DataType.INT64, list_cap=4)
    lanes, nulls = encode_column(f, [[10, 11], [], None, [7]])
    lanes["k"] = np.asarray([1, 2, 3, 4])
    chunk = StreamChunk.from_numpy(lanes, 4, nulls=nulls)
    ex = ProjectSetExecutor("unnest", out="x", list_col="xs", list_cap=4)
    (out,) = ex.apply(chunk)
    d = out.to_numpy()
    rows = sorted(zip(d["k"].tolist(), d["x"].tolist(), d["projected_row_id"].tolist()))
    assert rows == [(1, 10, 0), (1, 11, 1), (4, 7, 0)]
    assert "xs.0" not in d  # element lanes consumed


def test_generate_series_expansion_and_cap():
    chunk = StreamChunk.from_numpy(
        {"k": np.asarray([1, 2]), "lo": np.asarray([5, 0]),
         "hi": np.asarray([7, -1])}, 2,
    )
    ex = ProjectSetExecutor(
        "generate_series", out="s", start_col="lo", stop_col="hi",
        max_steps=8,
    )
    (out,) = ex.apply(chunk)
    d = out.to_numpy()
    rows = sorted(zip(d["k"].tolist(), d["s"].tolist()))
    assert rows == [(1, 5), (1, 6), (1, 7)]  # empty series for k=2
    ex.on_barrier(None)  # no truncation

    big = StreamChunk.from_numpy(
        {"k": np.asarray([9]), "lo": np.asarray([0]), "hi": np.asarray([100])}, 2,
    )
    ex.apply(big)
    with pytest.raises(RuntimeError, match="max_steps"):
        ex.on_barrier(None)
