"""Memory governor unit surface (PR 17): the degradation ladder's
hysteresis, credit-based admission, the BucketAllocator grow-gate veto
contract (hysteresis ticks ONCE across a veto/release cycle — the
regression the PR fixes), dormancy by default, and the zero-row poll
anchoring exactly-once rests on.
"""

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.runtime import SourceManager
from risingwave_tpu.runtime.bucketing import BucketAllocator, BucketPolicy
from risingwave_tpu.runtime.memory_governor import (
    DEGRADED,
    LADDER,
    NORMAL,
    SHEDDING,
    THROTTLED,
    AdmissionController,
    MemoryGovernor,
    OverloadLadder,
)

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------


def _ladder(cooldown=3):
    return OverloadLadder(
        throttle_at=0.75, shed_at=0.90, degrade_at=0.98, cooldown=cooldown
    )


def test_ladder_escalates_immediately_possibly_multiple_rungs():
    lad = _ladder()
    assert lad.step(0.5) == NORMAL
    # a single spike jumps straight to the matching rung
    assert lad.step(0.99) == DEGRADED
    assert [t["to"] for t in lad.transitions] == [DEGRADED]


def test_ladder_descends_one_rung_per_cooldown_of_calm():
    lad = _ladder(cooldown=3)
    lad.step(0.99)
    assert lad.state == DEGRADED
    # calm must be CONSECUTIVE: an interleaved hot barrier resets it
    lad.step(0.1)
    lad.step(0.1)
    lad.step(0.97)  # below degrade_at*0.85? no: 0.97 > 0.833 -> resets
    assert lad.state == DEGRADED
    for _ in range(3):
        lad.step(0.1)
    assert lad.state == SHEDDING  # ONE rung, not straight to NORMAL
    for _ in range(3):
        lad.step(0.1)
    assert lad.state == THROTTLED
    for _ in range(3):
        lad.step(0.1)
    assert lad.state == NORMAL


def test_ladder_flap_is_reescalation_within_cooldown_of_descent():
    lad = _ladder(cooldown=2)
    lad.step(0.80)  # THROTTLED
    lad.step(0.1)
    lad.step(0.1)  # descends to NORMAL
    assert lad.state == NORMAL and lad.flaps == 0
    lad.step(0.80)  # right back up: a flap
    assert lad.state == THROTTLED
    assert lad.flaps == 1


def test_ladder_exit_threshold_is_sticky():
    """Scores in the (exit, enter) hysteresis band hold the rung
    forever — boundary-riding load cannot flap the ladder."""
    lad = _ladder(cooldown=2)
    lad.step(0.80)
    for _ in range(20):
        lad.step(0.70)  # above exit 0.75*0.85=0.6375, below enter
    assert lad.state == THROTTLED
    assert lad.flaps == 0


# ---------------------------------------------------------------------------
# credits
# ---------------------------------------------------------------------------


def test_degraded_parks_immediately_and_recovers_stepwise():
    adm = AdmissionController(recover_step=0.25)
    adm.rederive(DEGRADED, 1.0, fragments=("q5",))
    assert adm.credits["q5"] == 0.0  # parked NOW, no trickle
    assert adm.admit_rows("q5", 1_000) == 0
    assert adm.parked_polls == 1
    # recovery is bounded per barrier: 0 -> .25 -> .5 -> ...
    adm.rederive(NORMAL, 0.0, fragments=("q5",))
    assert adm.credits["q5"] == 0.25
    adm.rederive(NORMAL, 0.0, fragments=("q5",))
    assert adm.credits["q5"] == 0.5
    # a nonzero credit always admits at least one row
    assert adm.admit_rows("q5", 1) == 1


def test_bottleneck_fragment_clamped_one_extra_halving():
    adm = AdmissionController()
    adm.rederive(THROTTLED, 0.8, bottleneck="hot", fragments=("hot", "ok"))
    # movement is damped to one halving per barrier; the bottleneck's
    # LOWER target (base 0.5 halved again) lands on the next rederive
    assert adm.credits["ok"] == 0.5
    assert adm.credits["hot"] == 0.5
    adm.rederive(THROTTLED, 0.8, bottleneck="hot", fragments=("hot", "ok"))
    assert adm.credits["ok"] == 0.5
    assert adm.credits["hot"] == 0.25


def test_unmapped_source_gets_the_tightest_window():
    adm = AdmissionController()
    adm.rederive(SHEDDING, 0.9, fragments=("a", "b"))
    adm.credits["a"] = 0.75
    assert adm.credit("unknown") == min(adm.credits.values())
    assert adm.credit(None) == min(adm.credits.values())
    # with no credits derived at all, admission is wide open
    assert AdmissionController().credit("anything") == 1.0


# ---------------------------------------------------------------------------
# the grow-gate veto contract (the PR's bug fix, at a lattice boundary)
# ---------------------------------------------------------------------------


def _alloc():
    return BucketAllocator(BucketPolicy(min_cap=64, max_cap=1024))


def test_vetoed_grow_leaves_hysteresis_untouched_then_ticks_once():
    """A vetoed grow that later succeeds must apply its pending-shrink
    and streak resets exactly once — at the grow that actually runs.
    Regression: the veto path used to reset them on refusal too, so a
    veto/release cycle double-ticked the hysteresis and a buffer
    sitting at a lattice boundary lost its earned shrink."""
    alloc = _alloc()
    # earn a pending shrink: calm barriers at low occupancy on a big cap
    for _ in range(alloc.policy.patience):
        alloc.note_barrier(512, 8)
    assert alloc._pending_shrink is not None
    streak = alloc._streak

    denies = {"on": True}
    alloc.grow_gate = lambda cap, new_cap: not denies["on"]

    # boundary-riding load asks to grow 512 -> 1024; the gate refuses
    assert alloc.plan(512, incoming=300, claimed=300, survivors=300) is None
    assert alloc.vetoes == 1
    assert alloc._veto_hold is True
    # hysteresis state UNTOUCHED by the refusal
    assert alloc._pending_shrink is not None
    assert alloc._streak == streak

    # barrier: hold clears (occupancy high -> shrink state resets here,
    # by the normal note_barrier rules, not by the veto)
    alloc.note_barrier(512, 300)
    assert alloc._veto_hold is False

    # released: the SAME grow now succeeds and ticks the resets once
    denies["on"] = False
    assert alloc.plan(512, incoming=300, claimed=300, survivors=300) == 1024
    assert alloc._pending_shrink is None
    assert alloc._streak == 0
    assert alloc.vetoes == 1  # no further veto counted


def test_veto_hold_stops_per_chunk_reasking_until_the_barrier():
    alloc = _alloc()
    alloc.grow_gate = lambda cap, new_cap: False
    assert alloc.plan(512, incoming=300, claimed=300, survivors=300) is None
    assert alloc._veto_hold is True
    # the apply path's pre-check goes quiet for the rest of the epoch
    assert not alloc.should_plan(512, bound=300, incoming=300)
    alloc.note_barrier(512, 300)  # re-probe on the barrier clock
    assert alloc.should_plan(512, bound=300, incoming=300)


def test_same_cap_compaction_is_never_vetoed():
    """A tombstone compaction (new_cap == cap) frees memory — the gate
    must only see GENUINE growth."""
    alloc = _alloc()
    calls = []
    alloc.grow_gate = lambda cap, new_cap: calls.append((cap, new_cap)) or False
    # claimed rides above grow_at but survivors fit the same bucket
    out = alloc.plan(512, incoming=0, claimed=400, survivors=100)
    assert out == 512  # pure compaction planned
    assert calls == []  # gate never consulted
    assert alloc.vetoes == 0


def test_bump_stays_ungated():
    """The mid-epoch overflow guard must never be vetoed: it exists to
    prevent data loss NOW; the governor reconciles next barrier."""
    alloc = _alloc()
    alloc.grow_gate = lambda cap, new_cap: False
    assert alloc.bump(512) == 1024
    assert alloc.vetoes == 0


def test_broken_gate_never_wedges_growth():
    alloc = _alloc()

    def boom(cap, new_cap):
        raise RuntimeError("gate crashed")

    alloc.grow_gate = boom
    assert alloc.plan(512, incoming=300, claimed=300, survivors=300) == 1024


# ---------------------------------------------------------------------------
# the governor
# ---------------------------------------------------------------------------


def test_governor_dormant_by_default(monkeypatch):
    monkeypatch.delenv("RW_HBM_BUDGET_BYTES", raising=False)
    monkeypatch.delenv("RW_HBM_BUDGET_FRAC", raising=False)
    monkeypatch.delenv("RW_OVERLOAD_LADDER", raising=False)
    gov = MemoryGovernor()
    assert gov.enabled is False
    # observe_barrier is a no-op: no ledger walk, no gating
    gov.observe_barrier(runtime=None, tr=None)
    assert gov._barriers == 0
    assert gov.authorize_grow("t", 64, 128, 8.0) is True


def test_authorize_grow_vetoes_at_budget_and_charges_optimistically():
    gov = MemoryGovernor(budget_bytes=10_000)
    gov.ledger_total = 9_000
    # projected 9_000 + 128*16 = 11_048 > budget -> veto + relief flag
    assert gov.authorize_grow("t", 128, 256, 16.0) is False
    assert gov.vetoes == 1
    assert gov._relief_wanted is True
    assert gov.ledger_total == 9_000  # refusal charges nothing
    # within budget: allowed, and the headroom is claimed immediately
    # so a second same-barrier grow cannot double-spend it
    assert gov.authorize_grow("t", 64, 128, 8.0) is True
    assert gov.ledger_total == 9_000 + 64 * 8
    assert gov.authorize_grow("u", 128, 256, 8.0) is False


def test_pressure_score_combines_memory_and_queue_age():
    gov = MemoryGovernor(budget_bytes=1_000)
    gov.queue_ms_budget = 1_000.0
    gov.ledger_total = 500

    class _Tr:
        backpressure = {"f": {"oldest_age_ms": 1_000.0}}

    # queue at budget lands ON the degrade threshold (same scale)
    assert gov._pressure_score(_Tr()) == pytest.approx(
        gov.ladder.degrade_at
    )
    _Tr.backpressure = {"f": {"oldest_age_ms": 0.0}}
    assert gov._pressure_score(_Tr()) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# zero-row poll anchoring (exactly-once under parking)
# ---------------------------------------------------------------------------


class _CountingSource:
    def __init__(self):
        self.offset = 0
        self.splits = [type("S", (), {"split_id": "s0"})()]

    def discover(self):
        pass

    def poll(self, max_rows_per_split, capacity, only=None):
        n = int(max_rows_per_split)
        chunks = []
        while n > 0:
            take = min(n, capacity)
            cols = {
                "k": np.arange(
                    self.offset, self.offset + take, dtype=np.int64
                )
            }
            chunks.append(StreamChunk.from_numpy(cols, capacity))
            self.offset += take
            n -= take
        return chunks


def test_parked_source_polls_zero_rows_and_offsets_anchor():
    mgr = SourceManager()
    src = _CountingSource()
    mgr.register("bids", src)
    adm = AdmissionController()
    mgr.attach_admission(adm, {"bids": "frag"})

    adm.rederive(DEGRADED, 1.0, fragments=("frag",))
    assert mgr.poll("bids", max_rows_per_split=500, capacity=64) == []
    assert src.offset == 0  # anchored: the parked poll moved nothing
    assert adm.parked_polls == 1

    # credit recovers -> the SAME rows flow from the anchored offset
    for _ in range(4):
        adm.rederive(NORMAL, 0.0, fragments=("frag",))
    chunks = mgr.poll("bids", max_rows_per_split=500, capacity=64)
    assert chunks and src.offset == 500


def test_throttled_credit_scales_the_poll_window():
    mgr = SourceManager()
    src = _CountingSource()
    mgr.register("bids", src)
    adm = AdmissionController()
    mgr.attach_admission(adm, {"bids": "frag"})
    adm.rederive(THROTTLED, 0.8, fragments=("frag",))
    mgr.poll("bids", max_rows_per_split=1_000, capacity=64)
    assert src.offset == 500  # credit 0.5 halves the window


def test_ladder_constants_are_the_public_contract():
    assert LADDER == (NORMAL, THROTTLED, SHEDDING, DEGRADED)
