"""Differential read store (VerifyStateStore analogue): the optimized
pruned read paths agree with a full-materialization oracle on every
read — and a deliberately corrupted bloom/bound is CAUGHT."""

import numpy as np
import pytest

from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import (
    CheckpointManager,
    StateDelta,
)
from risingwave_tpu.storage.verify_store import VerifyReadStore

pytestmark = pytest.mark.smoke


def _commit(mgr, epoch, ks, vs, tomb=None):
    n = len(ks)
    mgr.commit_staged(epoch, [
        StateDelta(
            "vt", {"k": np.asarray(ks, np.int64)},
            {"v": np.asarray(vs, np.int64)},
            np.zeros(n, bool) if tomb is None else np.asarray(tomb),
            ("k",),
        )
    ])


def test_reads_verified_against_oracle():
    mgr = CheckpointManager(MemObjectStore(), compact_at=2)
    vs = VerifyReadStore(mgr)
    rng = np.random.default_rng(7)
    epoch = 0
    for _ in range(6):
        epoch += 1 << 16
        ks = rng.integers(0, 5000, 400)
        _commit(mgr, epoch, ks, ks * 3)
        mgr._maybe_compact(epoch)

    found, vals = vs.get_rows(
        "vt", {"k": np.asarray([1, 2, 999999], np.int64)}
    )
    keys, _ = vs.scan_range("vt", range_col="k", lo=100, hi=200)
    assert vs.verified_reads == 2
    # pass-through of non-read surface
    assert vs.max_committed_epoch == epoch


def test_divergence_is_caught():
    mgr = CheckpointManager(MemObjectStore(), compact_at=100)
    vs = VerifyReadStore(mgr)
    _commit(mgr, 1 << 16, [1, 2, 3], [10, 20, 30])

    # corrupt the fast path: poison the cached SST's bloom so a real
    # key gets pruned — the differential read must catch it
    readers = mgr._readers_newest_first("vt")
    readers[0].bloom = np.zeros_like(readers[0].bloom)
    with pytest.raises(AssertionError, match="differential store"):
        vs.get_rows("vt", {"k": np.asarray([2], np.int64)})
