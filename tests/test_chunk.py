"""Chunk model unit tests (reference behavior: data_chunk.rs / stream_chunk.rs)."""

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import DataChunk, StreamChunk
from risingwave_tpu.types import DataType, Op, Schema


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def test_roundtrip_padding():
    c = DataChunk.from_numpy({"a": np.arange(5), "b": np.ones(5) * 0.5}, capacity=8)
    assert c.capacity == 8
    assert int(c.num_rows()) == 5
    out = c.to_numpy()
    np.testing.assert_array_equal(out["a"], np.arange(5))
    assert out["b"].shape == (5,)


def test_stream_chunk_signs():
    ops = np.array([Op.INSERT, Op.DELETE, Op.UPDATE_DELETE, Op.UPDATE_INSERT])
    c = StreamChunk.from_numpy({"x": np.arange(4)}, capacity=6, ops=ops)
    np.testing.assert_array_equal(
        np.asarray(c.effective_signs()), [1, -1, -1, 1, 0, 0]
    )


def test_mask_filter():
    c = StreamChunk.from_numpy({"x": np.arange(6)}, capacity=8)
    filtered = c.mask(c.col("x") % 2 == 0)
    out = filtered.to_numpy()
    np.testing.assert_array_equal(out["x"], [0, 2, 4])


def test_chunk_is_pytree():
    c = StreamChunk.from_numpy({"x": np.arange(4), "y": np.arange(4)}, capacity=4)

    @jax.jit
    def double(ch):
        return ch.with_columns(x=ch.col("x") * 2)

    out = double(c)
    np.testing.assert_array_equal(out.to_numpy()["x"], [0, 2, 4, 6])
    # ops and valid survive the pytree roundtrip
    assert out.ops.shape == (4,)


def test_schema_types():
    s = Schema([("id", DataType.INT64), ("price", DataType.FLOAT32)])
    assert s.field("price").dtype.device_dtype == np.float32
    assert s.index("id") == 0
    c = DataChunk.from_numpy({"id": np.arange(3), "price": np.arange(3)}, 4, schema=s)
    assert c.col("price").dtype == jnp.float32
