"""Vnode-sharded agg on a virtual 8-device mesh vs the single-chip
executor — must be exactly equal (reference: hash dispatch semantics,
dispatch.rs:683; multi-node testing via simulation, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.parallel import ShardedHashAgg, make_mesh
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.types import Op


def _mv_replay(snapshot, chunk, n_keys=1):
    d = chunk.to_numpy(with_ops=True)
    names = [n for n in d if n != "__op__" and not n.endswith("__null")]
    for i in range(len(d["__op__"])):
        key = tuple(d[n][i] for n in names[:n_keys])
        if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
            snapshot.pop(key, None)
        else:
            snapshot[key] = tuple(d[n][i] for n in names[n_keys:])
    return snapshot


N_SHARDS = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_SHARDS
    return make_mesh(N_SHARDS)


def test_sharded_agg_matches_single_chip(mesh):
    calls = (
        AggCall("count_star", None, "cnt"),
        AggCall("sum", "price", "total"),
    )
    dtypes = {"auction": jnp.int64, "price": jnp.int64}
    sharded = ShardedHashAgg(
        mesh,
        ("auction",),
        calls,
        dtypes,
        capacity=1 << 12,
        out_cap=1 << 10,
    )
    single = HashAggExecutor(
        ("auction",), calls, dtypes, capacity=1 << 14, out_cap=1 << 12
    )

    # per-shard Nexmark splits, exactly the reference's multi-split setup
    dicts = NexmarkGenerator.make_dictionaries()
    gens = [
        NexmarkGenerator(
            NexmarkConfig(), split_index=i, split_num=N_SHARDS, dictionaries=dicts
        )
        for i in range(N_SHARDS)
    ]

    snap_sharded, snap_single = {}, {}
    for epoch in range(3):
        per_shard = []
        for g in gens:
            chunks = g.next_chunks(500, 512)
            bid = chunks["bid"]
            assert bid is not None
            bid = bid.select(["auction", "price"])
            per_shard.append(bid)
            single.apply(bid)
        sharded.apply(stack_chunks(per_shard))

        for out in sharded.on_barrier(None):
            snap_sharded = _mv_replay(snap_sharded, out)
        for out in single.on_barrier(None):
            snap_single = _mv_replay(snap_single, out)

    assert len(snap_single) > 100
    assert snap_sharded == snap_single


def test_sharded_agg_state_is_actually_sharded(mesh):
    calls = (AggCall("count_star", None, "cnt"),)
    sharded = ShardedHashAgg(
        mesh, ("k",), calls, {"k": jnp.int64}, capacity=1 << 10
    )
    # each group must live on exactly ONE shard: feed the same keys from
    # every shard; per-shard live counts must sum to the global count
    keys = np.arange(64, dtype=np.int64)
    per_shard = [
        StreamChunk.from_numpy({"k": keys}, 64) for _ in range(N_SHARDS)
    ]
    sharded.apply(stack_chunks(per_shard))
    live_per_shard = np.asarray(
        jnp.sum(sharded.table.live.astype(jnp.int32), axis=1)
    )
    assert live_per_shard.sum() == 64  # no duplication across shards
    assert (live_per_shard > 0).sum() > 1  # and actually distributed

    outs = sharded.on_barrier(None)
    snap = {}
    for out in outs:
        snap = _mv_replay(snap, out)
    assert {k[0] for k in snap} == set(range(64))
    assert all(v == (N_SHARDS,) for v in snap.values())  # 8 rows per key

def test_sharded_agg_null_inputs_match_single_chip(mesh):
    """NULL lanes must ride the exchange: SUM/COUNT skip NULL inputs
    identically on the sharded and single-chip paths (hash_agg.rs:326
    apply_chunk NULL semantics)."""
    calls = (
        AggCall("count", "price", "cnt"),
        AggCall("sum", "price", "total"),
    )
    dtypes = {"k": jnp.int64, "price": jnp.int64}
    sharded = ShardedHashAgg(
        mesh, ("k",), calls, dtypes, capacity=1 << 10, out_cap=1 << 9
    )
    single = HashAggExecutor(
        ("k",), calls, dtypes, capacity=1 << 12, out_cap=1 << 10
    )

    rng = np.random.default_rng(7)
    per_shard = []
    for s in range(N_SHARDS):
        k = rng.integers(0, 40, 128).astype(np.int64)
        price = rng.integers(1, 1000, 128).astype(np.int64)
        isnull = rng.random(128) < 0.3
        chunk = StreamChunk.from_numpy(
            {"k": k, "price": price}, 128, nulls={"price": isnull}
        )
        per_shard.append(chunk)
        single.apply(chunk)
    sharded.apply(stack_chunks(per_shard))

    snap_sharded, snap_single = {}, {}
    for out in sharded.on_barrier(None):
        snap_sharded = _mv_replay(snap_sharded, out)
    for out in single.on_barrier(None):
        snap_single = _mv_replay(snap_single, out)
    assert len(snap_single) > 0
    assert snap_sharded == snap_single

def test_sharded_agg_nullable_group_key(mesh):
    """NULL group keys form their own group across the exchange,
    identically to the single-chip executor."""
    calls = (AggCall("count_star", None, "cnt"),)
    dtypes = {"k": jnp.int64}
    sharded = ShardedHashAgg(
        mesh, ("k",), calls, dtypes, capacity=1 << 10, out_cap=1 << 9,
        nullable_keys=("k",),
    )
    single = HashAggExecutor(
        ("k",), calls, dtypes, capacity=1 << 12, out_cap=1 << 10,
        nullable_keys=("k",),
    )

    rng = np.random.default_rng(11)
    per_shard = []
    for s in range(N_SHARDS):
        k = rng.integers(0, 10, 64).astype(np.int64)
        isnull = rng.random(64) < 0.25
        # NULL rows carry k=0 values: must NOT merge with the real 0 group
        k[isnull] = 0
        chunk = StreamChunk.from_numpy({"k": k}, 64, nulls={"k": isnull})
        per_shard.append(chunk)
        single.apply(chunk)
    sharded.apply(stack_chunks(per_shard))

    def replay_nullkey(outs):
        snap = {}
        for out in outs:
            d = out.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                key = None if d["k__null"][i] else d["k"][i]
                if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                    snap.pop(key, None)
                else:
                    snap[key] = d["cnt"][i]
        return snap

    got = replay_nullkey(sharded.on_barrier(None))
    want = replay_nullkey(single.on_barrier(None))
    assert None in want  # the NULL group exists and is separate
    assert got == want


@pytest.mark.slow
def test_sharded_agg_checkpoint_restore_across_mesh_sizes(mesh):
    """Kill-recover the sharded agg, restoring onto a DIFFERENT mesh
    size (vnode remap; VERDICT r2 #6) — continued output matches an
    unkilled single-chip twin."""
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    calls = (AggCall("count_star", None, "cnt"), AggCall("sum", "price", "total"))
    dtypes = {"auction": jnp.int64, "price": jnp.int64}

    def mk_sharded(m, n):
        return ShardedHashAgg(
            m, ("auction",), calls, dtypes,
            capacity=1 << 10, out_cap=1 << 9, table_id="sagg",
        )

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    sharded = mk_sharded(mesh, N_SHARDS)
    single = HashAggExecutor(
        ("auction",), calls, dtypes, capacity=1 << 12, out_cap=1 << 11
    )

    dicts = NexmarkGenerator.make_dictionaries()

    def gens(n):
        return [
            NexmarkGenerator(
                NexmarkConfig(), split_index=i, split_num=n, dictionaries=dicts
            )
            for i in range(n)
        ]

    g8 = gens(N_SHARDS)
    snap_sharded, snap_single = {}, {}
    for epoch in range(2):
        per_shard = []
        for g in g8:
            bid = g.next_chunks(400, 512)["bid"].select(["auction", "price"])
            per_shard.append(bid)
            single.apply(bid)
        sharded.apply(stack_chunks(per_shard))
        for out in sharded.on_barrier(None):
            snap_sharded = _mv_replay(snap_sharded, out)
        for out in single.on_barrier(None):
            snap_single = _mv_replay(snap_single, out)
        mgr.commit_epoch((epoch + 1) << 16, [sharded])
    assert snap_sharded == snap_single

    # restore onto a 4-device mesh
    mesh4 = make_mesh(4)
    restored = mk_sharded(mesh4, 4)
    CheckpointManager(store).recover([restored])

    # continue feeding: same global rows re-split 8 -> re-stacked as 4
    for _ in range(2):
        per8 = [
            g.next_chunks(400, 512)["bid"].select(["auction", "price"])
            for g in g8
        ]
        for bid in per8:
            single.apply(bid)
        # merge 8 splits into 4 shard inputs (2 splits each, stacked
        # along capacity: concat the raw numpy then rebuild chunks)
        per4 = []
        for k in range(4):
            a, b = per8[2 * k].to_numpy(False), per8[2 * k + 1].to_numpy(False)
            cols = {
                n: np.concatenate([a[n], b[n]]) for n in ("auction", "price")
            }
            per4.append(StreamChunk.from_numpy(cols, 1024))
        restored.apply(stack_chunks(per4))
        for out in restored.on_barrier(None):
            snap_sharded = _mv_replay(snap_sharded, out)
        for out in single.on_barrier(None):
            snap_single = _mv_replay(snap_single, out)
    assert snap_sharded == snap_single


@pytest.mark.slow
def test_sharded_agg_grows(mesh):
    """Per-shard rehash: tiny initial capacity must grow instead of
    latching dropped."""
    calls = (AggCall("count_star", None, "cnt"),)
    dtypes = {"k": jnp.int64}
    sharded = ShardedHashAgg(
        mesh, ("k",), calls, dtypes, capacity=64, out_cap=1 << 12,
        bucket_cap=512,
    )
    single = HashAggExecutor(("k",), calls, dtypes, capacity=1 << 12, out_cap=1 << 12)
    rng = np.random.default_rng(5)
    snap_s, snap_1 = {}, {}
    for _ in range(4):
        per_shard = []
        for i in range(N_SHARDS):
            k = rng.integers(0, 3000, 256).astype(np.int64)
            c = StreamChunk.from_numpy({"k": k}, 256)
            per_shard.append(c)
            single.apply(c)
        sharded.apply(stack_chunks(per_shard))
        for out in sharded.on_barrier(None):
            snap_s = _mv_replay(snap_s, out)
        for out in single.on_barrier(None):
            snap_1 = _mv_replay(snap_1, out)
    assert sharded.capacity > 64
    assert snap_s == snap_1
