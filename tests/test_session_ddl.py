"""Session DDL/DML consistency: duplicate relations, MVs joining two
tables (two-sided subscriptions), no double-delivery of INSERTs.

Regressions for the r3 code-review findings on frontend/session.py.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog


@pytest.fixture
def session():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_duplicate_create_table_rejected(session):
    session.execute("CREATE TABLE t (k BIGINT)")
    with pytest.raises(ValueError, match="already exists"):
        session.execute("CREATE TABLE t (k BIGINT)")
    # the duplicate did not double the DML targets
    session.execute("INSERT INTO t VALUES (1)")
    out, tag = session.execute("SELECT k FROM t")
    assert tag == "SELECT 1"
    assert list(out["k"]) == [1]


def test_duplicate_mv_rejected(session):
    session.execute("CREATE TABLE t (k BIGINT)")
    session.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, count(*) AS n FROM t GROUP BY k"
    )
    with pytest.raises(ValueError, match="already exists"):
        session.execute(
            "CREATE MATERIALIZED VIEW m AS "
            "SELECT k, count(*) AS n FROM t GROUP BY k"
        )
    # graph stayed consistent: barriers and inserts still work
    session.execute("INSERT INTO t VALUES (3)")
    out, _ = session.execute("SELECT k, n FROM m")
    assert list(out["k"]) == [3] and list(out["n"]) == [1]


def test_mv_joining_two_tables(session):
    """A join MV over two CREATE TABLEs: both sides must subscribe to
    their table's delta edge (left/right), and later INSERTs into
    either table must update the join."""
    session.execute("CREATE TABLE a (k BIGINT, x BIGINT)")
    session.execute("CREATE TABLE b (kk BIGINT, y BIGINT)")
    session.execute("INSERT INTO a VALUES (1, 10), (2, 20)")
    session.execute("INSERT INTO b VALUES (1, 7)")
    session.execute(
        "CREATE MATERIALIZED VIEW j AS "
        "SELECT l.k, l.xs, r.ys FROM "
        "(SELECT k, sum(x) AS xs FROM a GROUP BY k) AS l "
        "JOIN "
        "(SELECT kk, sum(y) AS ys FROM b GROUP BY kk) AS r "
        "ON l.k = r.kk"
    )
    out, _ = session.execute("SELECT k, xs, ys FROM j")
    assert list(out["k"]) == [1]
    assert list(out["xs"]) == [10] and list(out["ys"]) == [7]

    # delta on the LEFT side: sum retracts 10, inserts 15
    session.execute("INSERT INTO a VALUES (1, 5)")
    out, _ = session.execute("SELECT k, xs, ys FROM j")
    assert list(out["k"]) == [1] and list(out["xs"]) == [15]

    # delta on the RIGHT side: new key joins existing left row
    session.execute("INSERT INTO b VALUES (2, 3)")
    out, _ = session.execute("SELECT k, xs, ys FROM j ORDER BY k")
    assert list(out["k"]) == [1, 2]
    assert list(out["xs"]) == [15, 20]
    assert list(out["ys"]) == [7, 3]
