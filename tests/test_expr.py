"""Expression framework: SQL semantics vs hand-computed oracles
(reference: src/expr/core vectorized eval + non-strict NULL handling)."""

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import FilterExecutor, ProjectExecutor
from risingwave_tpu.expr import Case, IsNull, TumbleStart, col, lit
from risingwave_tpu.types import Op


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def make_chunk(**kw):
    nulls = kw.pop("nulls", None)
    n = len(next(iter(kw.values())))
    return StreamChunk.from_numpy(
        {k: np.asarray(v) for k, v in kw.items()}, capacity=8, nulls=nulls
    )


def test_arith_and_compare():
    c = make_chunk(a=[1, 2, 3, 4], b=[10, 20, 30, 40])
    v, n = ((col("a") + col("b")) * lit(2)).eval(c)
    assert n is None
    np.testing.assert_array_equal(np.asarray(v)[:4], [22, 44, 66, 88])
    v, _ = (col("a") >= lit(3)).eval(c)
    np.testing.assert_array_equal(np.asarray(v)[:4], [False, False, True, True])


def test_null_strict_arith_and_3vl():
    c = make_chunk(
        a=[1, 2, 3, 4], b=[5, 6, 7, 8], nulls={"a": [False, True, False, True]}
    )
    _, n = (col("a") + col("b")).eval(c)
    np.testing.assert_array_equal(np.asarray(n)[:4], [False, True, False, True])

    # (a > 0) OR (b > 100): NULL OR FALSE = NULL; NULL OR TRUE = TRUE
    pred = (col("a") > lit(0)) | (col("b") > lit(100))
    v, n = pred.eval(c)
    np.testing.assert_array_equal(np.asarray(n)[:4], [False, True, False, True])
    # (a > 0) AND (b > 0): NULL AND TRUE = NULL
    pred = (col("a") > lit(0)) & (col("b") > lit(0))
    v, n = pred.eval(c)
    np.testing.assert_array_equal(np.asarray(n)[:4], [False, True, False, True])
    # FALSE AND NULL = FALSE (definite)
    pred = (col("b") > lit(100)) & (col("a") > lit(0))
    v, n = pred.eval(c)
    assert not bool(n[1])
    assert not bool(v[1])


def test_div_by_zero_is_null_not_trap():
    c = make_chunk(a=[10, 20], b=[2, 0])
    v, n = (col("a") // col("b")).eval(c)
    assert int(v[0]) == 5
    assert bool(n[1])


def test_case_and_is_null():
    c = make_chunk(a=[1, 2, 3, 4], nulls={"a": [False, False, True, False]})
    e = Case(
        branches=((col("a") > lit(2), lit(100)), (col("a") > lit(1), lit(50))),
        default=lit(0),
    )
    v, n = e.eval(c)
    np.testing.assert_array_equal(np.asarray(v)[:4], [0, 50, 0, 100])
    v, n = IsNull(col("a")).eval(c)
    assert n is None
    np.testing.assert_array_equal(np.asarray(v)[:4], [False, False, True, False])


def test_tumble_start():
    c = make_chunk(ts=[0, 999, 10_000, 25_500])
    v, _ = TumbleStart(col("ts"), 10_000).eval(c)
    np.testing.assert_array_equal(np.asarray(v)[:4], [0, 0, 10_000, 20_000])


def test_filter_executor_drops_null_and_false():
    c = make_chunk(a=[1, 5, 3, 7], nulls={"a": [False, False, True, False]})
    (out,) = FilterExecutor(col("a") > lit(2)).apply(c)
    data = out.to_numpy()
    np.testing.assert_array_equal(data["a"], [5, 7])


def test_filter_fixes_torn_update_pairs():
    c = StreamChunk.from_numpy(
        {"a": np.asarray([1, 10, 2, 20])},
        capacity=4,
        ops=np.asarray(
            [Op.UPDATE_DELETE, Op.UPDATE_INSERT, Op.UPDATE_DELETE, Op.UPDATE_INSERT]
        ),
    )
    # keeps rows > 5: first pair loses its U- half, second keeps only U-
    (out,) = FilterExecutor(col("a") > lit(5)).apply(c)
    data = out.to_numpy(with_ops=True)
    np.testing.assert_array_equal(data["a"], [10, 20])
    np.testing.assert_array_equal(data["__op__"], [Op.INSERT, Op.INSERT])


def test_project_executor():
    c = make_chunk(price=[100, 200], qty=[2, 3])
    (out,) = ProjectExecutor(
        {"total": col("price") * col("qty"), "price": col("price")}
    ).apply(c)
    data = out.to_numpy()
    np.testing.assert_array_equal(data["total"], [200, 600])
    np.testing.assert_array_equal(data["price"], [100, 200])
