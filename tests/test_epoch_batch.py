"""Per-epoch chunk batching in the actor graph (VERDICT r4 weak #1:
make the benched path the built path).

A fragment whose chain ends [stateless*, HashAgg] accumulates the
epoch's chunks and applies them in ONE fused device program
(HashAggExecutor.apply_stacked with the stateless prefix traced in via
``pre``) — emission stays barrier-granular, so results are
byte-identical to the per-chunk walk.

Reference: the reference benches its production executor directly
(src/stream/src/executor/hash_agg.rs:62, src/stream/benches/).
"""

import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.executors.epoch_batch import (
    EpochBatchedAggExecutor,
    fuse_epoch_batch,
)
from risingwave_tpu.runtime.fragmenter import graph_planned_mv
from risingwave_tpu.sql import Catalog, StreamPlanner

pytestmark = pytest.mark.smoke

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)


@pytest.fixture
def catalog():
    return Catalog({"bid": BID_SCHEMA})


def _factory(catalog):
    return lambda: StreamPlanner(catalog, capacity=1 << 12)


def _bid_chunks(n, events=800, cap=1 << 10):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def test_fuse_rewrites_stateless_agg_runs(catalog):
    chain = list(
        StreamPlanner(catalog, capacity=1 << 10)
        .plan(Q5_SQL)
        .pipeline.executors
    )
    fused = fuse_epoch_batch(chain)
    wrappers = [
        e for e in fused if isinstance(e, EpochBatchedAggExecutor)
    ]
    assert len(wrappers) == 1
    # the wrapper holds the ORIGINAL agg object (checkpoint registry
    # keeps referencing it) and the stateless prefix was absorbed
    from risingwave_tpu.executors.hash_agg import HashAggExecutor

    orig_aggs = [e for e in chain if type(e) is HashAggExecutor]
    assert wrappers[0].agg is orig_aggs[0]
    assert len(fused) < len(chain)
    # everything downstream of the agg is untouched, in order
    tail = chain[chain.index(orig_aggs[0]) + 1 :]
    assert fused[fused.index(wrappers[0]) + 1 :] == tail


def test_actor_chain_is_batched(catalog):
    """Actors batch the epoch by default: the fused per-barrier step
    (runtime/fused_step) when enabled, else the epoch-batch wrapper."""
    from risingwave_tpu.runtime.fused_step import FusedChainExecutor

    mv = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=1)
    try:
        chains = [a.chain for a in mv.pipeline.graph.actors]
        assert any(
            isinstance(e, (EpochBatchedAggExecutor, FusedChainExecutor))
            for ch in chains
            for e in ch
        )
    finally:
        mv.pipeline.close()


def test_actor_chain_falls_back_to_epoch_batch(catalog, monkeypatch):
    """RW_FUSED_STEP=0 is the kill switch: actors keep the per-epoch
    batched interpreted path."""
    monkeypatch.setenv("RW_FUSED_STEP", "0")
    mv = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=1)
    try:
        chains = [a.chain for a in mv.pipeline.graph.actors]
        assert any(
            isinstance(e, EpochBatchedAggExecutor)
            for ch in chains
            for e in ch
        )
    finally:
        mv.pipeline.close()


@pytest.mark.parametrize("parallelism", [1, 2])
def test_batched_graph_matches_serial_varying_epoch_sizes(
    catalog, parallelism
):
    """Epochs of 1, 3, 5 and 2 chunks (pow2 padding exercises 1/4/8/2
    stack shapes) produce the exact serial-pipeline MV."""
    chunks = _bid_chunks(11)
    epochs = [chunks[0:1], chunks[1:4], chunks[4:9], chunks[9:11]]

    serial = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
    graph = graph_planned_mv(
        _factory(catalog), Q5_SQL, parallelism=parallelism
    )
    try:
        for ep in epochs:
            for c in ep:
                serial.pipeline.push(c)
                graph.pipeline.push(c)
            serial.pipeline.barrier()
            graph.pipeline.barrier()
        want = serial.mview.snapshot()
        assert want
        assert graph.mview.snapshot() == want
    finally:
        graph.pipeline.close()


def test_batched_graph_off_switch_matches(catalog):
    """epoch_batch=False is the per-chunk walk; both graph modes agree
    (the differential guard for the fused path)."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor as EB,
    )

    chunks = _bid_chunks(6)
    on = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=1)
    off = graph_planned_mv(
        _factory(catalog), Q5_SQL, parallelism=1, epoch_batch=False
    )
    try:
        off_chains = [e for a in off.pipeline.graph.actors for e in a.chain]
        assert not any(isinstance(e, EB) for e in off_chains)
        for i in range(0, 6, 3):
            for c in chunks[i : i + 3]:
                on.pipeline.push(c)
                off.pipeline.push(c)
            on.pipeline.barrier()
            off.pipeline.barrier()
        want = off.mview.snapshot()
        assert want
        assert on.mview.snapshot() == want
    finally:
        on.pipeline.close()
        off.pipeline.close()
