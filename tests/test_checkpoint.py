"""Checkpoint/recovery tests — SST round-trips, merge-on-read, and the
kill-and-recover contract (VERDICT r1 next-step 3; reference:
state_table.rs commit + recovery from max_committed_epoch)."""

import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.storage import (
    CheckpointManager,
    LocalFsObjectStore,
    MemObjectStore,
)
from risingwave_tpu.storage.sstable import build_sst, merge_ssts, read_sst


def test_sst_round_trip():
    keys = {"k0": np.array([3, 1, 2], np.int64)}
    vals = {"v": np.array([30, 10, 20], np.int64)}
    blob = build_sst("t", 7, keys, vals, np.array([False, True, False]), ("k0",))
    sst = read_sst(blob)
    assert sst.meta.table_id == "t" and sst.meta.epoch == 7
    # sorted by memcomparable key order
    assert sst.keys["k0"].tolist() == [1, 2, 3]
    assert sst.values["v"].tolist() == [10, 20, 30]
    assert sst.tombstone.tolist() == [True, False, False]
    # bloom admits present keys (no false negatives)
    assert sst.may_contain([np.array([1, 2, 3], np.int64)]).all()


def test_sst_negative_keys_sort_correctly():
    keys = {"k0": np.array([5, -3, 0, -7], np.int64)}
    vals = {"v": np.arange(4)}
    sst = read_sst(build_sst("t", 1, keys, vals, np.zeros(4, bool), ("k0",)))
    assert sst.keys["k0"].tolist() == [-7, -3, 0, 5]


def test_merge_newest_wins_and_tombstones():
    mk = lambda ep, ks, vs, tomb: read_sst(
        build_sst(
            "t",
            ep,
            {"k0": np.asarray(ks, np.int64)},
            {"v": np.asarray(vs, np.int64)},
            np.asarray(tomb, bool),
            ("k0",),
        )
    )
    s1 = mk(1, [1, 2, 3], [10, 20, 30], [False] * 3)
    s2 = mk(2, [2, 4], [21, 40], [False, False])
    s3 = mk(3, [3, 1], [0, 11], [True, False])  # delete 3, update 1
    keys, vals = merge_ssts([s3, s1, s2], ("k0",))
    got = dict(zip(keys["k0"].tolist(), vals["v"].tolist()))
    assert got == {1: 11, 2: 21, 4: 40}


def test_local_fs_object_store(tmp_path):
    store = LocalFsObjectStore(str(tmp_path))
    store.put("a/b/c.sst", b"hello")
    assert store.read("a/b/c.sst") == b"hello"
    assert store.list("a/") == ["a/b/c.sst"]
    store.put("a/b/c.sst", b"world")  # overwrite is atomic
    assert store.read("a/b/c.sst") == b"world"
    store.delete("a/b/c.sst")
    assert not store.exists("a/b/c.sst")
    with pytest.raises(ValueError):
        store.put("../escape", b"x")


def _run_epochs(q5, mgr, gen, n_epochs, events=1500, cap=2048):
    """Drive q5 n epochs, committing a checkpoint per barrier."""
    for _ in range(n_epochs):
        bid = gen.next_chunks(events, cap)["bid"]
        q5.pipeline.push(bid.select(["auction", "date_time"]))
        q5.pipeline.barrier()
        mgr.commit_epoch(q5.pipeline.epoch, q5.pipeline.executors)


def test_kill_and_recover_q5(tmp_path):
    store = LocalFsObjectStore(str(tmp_path))
    mgr = CheckpointManager(store)
    gen = NexmarkGenerator(NexmarkConfig())

    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    _run_epochs(q5, mgr, gen, 5)
    snap_before = q5.mview.snapshot()
    committed = mgr.max_committed_epoch
    assert len(snap_before) > 100

    # "kill": drop every object; rebuild from the store alone
    del q5
    mgr2 = CheckpointManager(LocalFsObjectStore(str(tmp_path)))
    assert mgr2.max_committed_epoch == committed
    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    mgr2.recover(q5b.pipeline.executors)
    assert q5b.mview.snapshot() == snap_before

    # the recovered pipeline must CONTINUE identically to an unkilled
    # twin fed the same post-kill chunks
    dicts = NexmarkGenerator.make_dictionaries()
    gen_a = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    gen_b = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    # rebuild the unkilled twin by replaying from scratch (same events)
    q5a = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    g0 = NexmarkGenerator(NexmarkConfig())
    for _ in range(5):
        bid = g0.next_chunks(1500, 2048)["bid"]
        q5a.pipeline.push(bid.select(["auction", "date_time"]))
        q5a.pipeline.barrier()
    # advance both generators to the same stream position
    for g in (gen_a, gen_b):
        for _ in range(5):
            g.next_chunks(1500, 2048)
    for _ in range(3):
        ba = gen_a.next_chunks(1500, 2048)["bid"]
        bb = gen_b.next_chunks(1500, 2048)["bid"]
        q5a.pipeline.push(ba.select(["auction", "date_time"]))
        q5a.pipeline.barrier()
        q5b.pipeline.push(bb.select(["auction", "date_time"]))
        q5b.pipeline.barrier()
    assert q5b.mview.snapshot() == q5a.mview.snapshot()


def test_recover_after_state_cleaning_tombstones(tmp_path):
    """EOWC expiry frees agg groups -> tombstones; recovery must not
    resurrect them into operator state (but the MV keeps final rows)."""
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    # 500 ev/s so the 4 epochs span several hop windows and some close
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=500))

    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=True)
    max_ts = 0
    for _ in range(4):
        bid = gen.next_chunks(1500, 2048)["bid"]
        max_ts = max(max_ts, int(bid.to_numpy(False)["date_time"].max()))
        q5.pipeline.push(bid.select(["auction", "date_time"]))
        q5.pipeline.barrier()
        q5.pipeline.watermark("date_time", max_ts)
        mgr.commit_epoch(q5.pipeline.epoch, q5.pipeline.executors)

    live_before = int(q5.agg.table.num_live())
    mv_before = q5.mview.snapshot()
    assert live_before < len(mv_before)  # cleaning actually freed groups

    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=True)
    mgr2 = CheckpointManager(store)
    mgr2.recover(q5b.pipeline.executors)
    assert int(q5b.agg.table.num_live()) == live_before
    assert q5b.mview.snapshot() == mv_before


def test_compaction_bounds_sst_count():
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    gen = NexmarkGenerator(NexmarkConfig())
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    _run_epochs(q5, mgr, gen, 10, events=800)
    for table_id, entries in mgr.version["tables"].items():
        assert len(entries) <= 8, table_id
    # recovery still exact after compaction replaced the L0 run
    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    CheckpointManager(store).recover(q5b.pipeline.executors)
    assert q5b.mview.snapshot() == q5.mview.snapshot()


def test_kill_and_recover_q8():
    """Two-input join pipeline: kill after N epochs, recover, continue —
    outputs identical to an unkilled twin."""
    from risingwave_tpu.queries.nexmark_q import build_q8

    store = MemObjectStore()
    mgr = CheckpointManager(store)

    def feed(q8, g, n):
        for _ in range(n):
            chunks = g.next_chunks(2000, 2048)
            if chunks["person"] is not None:
                q8.pipeline.push_left(
                    chunks["person"].select(["id", "name", "date_time"])
                )
            if chunks["auction"] is not None:
                q8.pipeline.push_right(
                    chunks["auction"].select(["seller", "date_time"])
                )
            q8.pipeline.barrier()

    dicts = NexmarkGenerator.make_dictionaries()
    gen = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    q8 = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    for _ in range(4):
        feed(q8, gen, 1)
        mgr.commit_epoch(q8.pipeline.epoch, q8.pipeline.executors)
    snap = q8.mview.snapshot()
    assert len(snap) > 30

    # recover into a fresh pipeline
    q8b = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    CheckpointManager(store).recover(q8b.pipeline.executors)
    assert q8b.mview.snapshot() == snap

    # continue both with identical post-kill traffic
    gen_b = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    for _ in range(4):
        gen_b.next_chunks(2000, 2048)
    feed(q8, gen, 2)
    feed(q8b, gen_b, 2)
    assert q8b.mview.snapshot() == q8.mview.snapshot()
    assert len(q8b.mview.snapshot()) > len(snap)


def test_kill_and_recover_q7():
    """q7 recovery must preserve the retraction machinery: a post-
    recovery higher bid still retracts the pre-kill max's pairs."""
    from risingwave_tpu.queries.nexmark_q import build_q7

    store = MemObjectStore()
    mgr = CheckpointManager(store)

    def feed(q7, g, n):
        for _ in range(n):
            bid = g.next_chunks(1500, 2048)["bid"]
            c = bid.select(["auction", "bidder", "price", "date_time"])
            q7.pipeline.push_left(c)
            q7.pipeline.push_right(c)
            q7.pipeline.barrier()

    dicts = NexmarkGenerator.make_dictionaries()
    gen = NexmarkGenerator(
        NexmarkConfig(first_event_rate=500), dictionaries=dicts
    )
    q7 = build_q7(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    for _ in range(3):
        feed(q7, gen, 1)
        mgr.commit_epoch(q7.pipeline.epoch, q7.pipeline.executors)
    snap = q7.mview.snapshot()
    assert len(snap) > 0

    q7b = build_q7(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    CheckpointManager(store).recover(q7b.pipeline.executors)
    assert q7b.mview.snapshot() == snap

    gen_b = NexmarkGenerator(
        NexmarkConfig(first_event_rate=500), dictionaries=dicts
    )
    for _ in range(3):
        gen_b.next_chunks(1500, 2048)
    feed(q7, gen, 3)
    feed(q7b, gen_b, 3)
    assert q7b.mview.snapshot() == q7.mview.snapshot()
