"""MV-on-MV backfill (VERDICT r2 #8; no_shuffle_backfill.rs:66):
create an MV over a live MV — snapshot + live deltas must equal a
from-scratch computation, and both MVs must survive kill-recover."""

import pandas as pd

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.storage.object_store import MemObjectStore

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)
MV2_SQL = (
    "CREATE MATERIALIZED VIEW hot AS "
    "SELECT auction, window_start, num FROM q5 WHERE num >= 3"
)


def _oracle(rows):
    df = pd.DataFrame(rows)
    parts = []
    for k in range(5):
        ws = ((df.date_time - 10_000) // 2000 + 1) * 2000 + k * 2000
        sub = df[ws <= df.date_time].copy()
        sub["window_start"] = ws[ws <= df.date_time]
        parts.append(sub)
    allw = pd.concat(parts)
    counts = allw.groupby(["auction", "window_start"]).size()
    return {
        (int(a), int(w)): (int(c),) for (a, w), c in counts.items()
    }


def _run(runtime, catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    q5 = planner.plan(Q5_SQL)
    runtime.register("q5", q5.pipeline)
    catalog.add_mv(q5)

    gen = NexmarkGenerator(NexmarkConfig())
    rows = {"auction": [], "date_time": []}

    def feed(n_epochs):
        for _ in range(n_epochs):
            bid = gen.next_chunks(1200, 2048)["bid"]
            d = bid.to_numpy(False)
            rows["auction"].extend(d["auction"].tolist())
            rows["date_time"].extend(d["date_time"].tolist())
            runtime.push("q5", bid)
            runtime.barrier()

    feed(2)
    # DDL mid-stream: the new MV backfills q5's current rows, then
    # rides its live change stream
    mv2 = planner.plan(MV2_SQL)
    assert mv2.inputs == {"q5": "single"}
    runtime.register("hot", mv2.pipeline, upstream="q5")
    catalog.add_mv(mv2)
    feed(3)
    runtime.wait_checkpoints()
    return q5, mv2, rows


def test_backfill_matches_from_scratch():
    catalog = Catalog({"bid": BID_SCHEMA})
    runtime = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    q5, mv2, rows = _run(runtime, catalog)

    want_q5 = _oracle(rows)
    assert q5.mview.snapshot() == want_q5
    want_hot = {k: v for k, v in want_q5.items() if v[0] >= 3}
    got_hot = mv2.mview.snapshot()
    assert len(want_hot) > 10
    assert got_hot == want_hot


def test_backfill_survives_recovery():
    store = MemObjectStore()
    catalog = Catalog({"bid": BID_SCHEMA})
    runtime = StreamingRuntime(store, async_checkpoint=False)
    q5, mv2, rows = _run(runtime, catalog)
    want_q5 = _oracle(rows)
    want_hot = {k: v for k, v in want_q5.items() if v[0] >= 3}

    # cold start: fresh pipelines, register WITHOUT backfill (state is
    # checkpointed), recover device state from the store
    catalog2 = Catalog({"bid": BID_SCHEMA})
    planner2 = StreamPlanner(catalog2, capacity=1 << 12)
    rt2 = StreamingRuntime(store, async_checkpoint=False)
    q5b = planner2.plan(Q5_SQL)
    rt2.register("q5", q5b.pipeline)
    catalog2.add_mv(q5b)
    hotb = planner2.plan(MV2_SQL)
    rt2.register("hot", hotb.pipeline, upstream="q5", backfill=False)
    rt2.recover()
    assert q5b.mview.snapshot() == want_q5
    assert hotb.mview.snapshot() == want_hot
