"""Unified runtime path: the SAME SQL runs on the serial pipeline and
on the planner-built actor graph (dispatchers, permit channels,
parallel fragments) with identical MV results, and graph-mode state
checkpoints/restores through the shared StreamingRuntime machinery.

Reference: one path from SQL to actors — stream_fragmenter/mod.rs ->
stream_graph/actor.rs:648 -> dispatch.rs; recovery.rs:353 restores the
same actors from committed state.
"""

import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (
    AUCTION_SCHEMA,
    BID_SCHEMA,
    PERSON_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.runtime.fragmenter import (
    GraphPipeline,
    PartitionedStateView,
    graph_planned_mv,
)
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.storage.object_store import MemObjectStore

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)

Q8_SQL = (
    "CREATE MATERIALIZED VIEW q8 AS "
    "SELECT p.id, p.name, p.starttime FROM "
    "(SELECT id, name, window_start AS starttime "
    " FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
    " GROUP BY id, name, window_start) AS p "
    "JOIN "
    "(SELECT seller, window_start AS astarttime "
    " FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
    " GROUP BY seller, window_start) AS a "
    "ON p.id = a.seller AND p.starttime = a.astarttime"
)


@pytest.fixture
def catalog():
    return Catalog(
        {"bid": BID_SCHEMA, "person": PERSON_SCHEMA, "auction": AUCTION_SCHEMA}
    )


def _factory(catalog):
    return lambda: StreamPlanner(catalog, capacity=1 << 12)


def _bid_chunks(n=4, events=1500, cap=1 << 11):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def test_graph_single_input_matches_serial(catalog):
    serial = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
    graph = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    assert isinstance(graph.pipeline, GraphPipeline)
    try:
        for c in _bid_chunks():
            serial.pipeline.push(c)
            graph.pipeline.push(c)
            serial.pipeline.barrier()
            graph.pipeline.barrier()
        want = serial.mview.snapshot()
        assert want
        assert graph.mview.snapshot() == want
        # the work actually partitioned: a PartitionedStateView exists
        # and neither instance owns every group
        views = [
            v
            for v in graph.pipeline.executors
            if isinstance(v, PartitionedStateView)
        ]
        assert views
        counts = [
            int(np.asarray(inst.table.live).sum())
            for inst in views[0]._instances
        ]
        assert all(0 < c < len(want) for c in counts)
    finally:
        graph.pipeline.close()


def test_graph_join_matches_serial(catalog):
    serial = StreamPlanner(catalog, capacity=1 << 12).plan(Q8_SQL)
    graph = graph_planned_mv(_factory(catalog), Q8_SQL, parallelism=2)
    gen = NexmarkGenerator(NexmarkConfig())
    try:
        for _ in range(6):
            chunks = gen.next_chunks(2000, 2048)
            if chunks["person"] is not None:
                serial.pipeline.push_left(chunks["person"])
                graph.pipeline.push_left(chunks["person"])
            if chunks["auction"] is not None:
                serial.pipeline.push_right(chunks["auction"])
                graph.pipeline.push_right(chunks["auction"])
            serial.pipeline.barrier()
            graph.pipeline.barrier()
        want = serial.mview.snapshot()
        assert want
        assert graph.mview.snapshot() == want
    finally:
        graph.pipeline.close()


def test_graph_mode_checkpoint_restore(catalog):
    store = MemObjectStore()
    chunks = _bid_chunks(n=6)

    rt = StreamingRuntime(store, async_checkpoint=False)
    graph = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt.register("q5", graph.pipeline)
    for c in chunks[:3]:
        rt.push("q5", c)
        rt.barrier()
    mid_snapshot = graph.mview.snapshot()
    assert mid_snapshot
    graph.pipeline.close()

    # fresh process: rebuild the SAME graph shape, recover from store,
    # then continue the stream — must equal a serial run of ALL chunks
    rt2 = StreamingRuntime(store, async_checkpoint=False)
    graph2 = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt2.register("q5", graph2.pipeline)
    rt2.recover()
    try:
        assert graph2.mview.snapshot() == mid_snapshot
        for c in chunks[3:]:
            rt2.push("q5", c)
            rt2.barrier()

        oracle = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
        for c in chunks:
            oracle.pipeline.push(c)
        oracle.pipeline.barrier()
        assert graph2.mview.snapshot() == oracle.mview.snapshot()
    finally:
        graph2.pipeline.close()


def test_graph_restore_across_parallelism(catalog):
    """Restore routes rows by the dispatcher's own hash, so state
    written at parallelism 2 restores correctly at parallelism 3 (the
    ScaleController's re-partitioning contract, scale.rs:453)."""
    store = MemObjectStore()
    chunks = _bid_chunks(n=6)

    rt = StreamingRuntime(store, async_checkpoint=False)
    graph = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=2)
    rt.register("q5", graph.pipeline)
    for c in chunks[:3]:
        rt.push("q5", c)
        rt.barrier()
    graph.pipeline.close()

    rt2 = StreamingRuntime(store, async_checkpoint=False)
    graph2 = graph_planned_mv(_factory(catalog), Q5_SQL, parallelism=3)
    rt2.register("q5", graph2.pipeline)
    rt2.recover()
    try:
        for c in chunks[3:]:
            rt2.push("q5", c)
            rt2.barrier()
        oracle = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
        for c in chunks:
            oracle.pipeline.push(c)
        oracle.pipeline.barrier()
        assert graph2.mview.snapshot() == oracle.mview.snapshot()
    finally:
        graph2.pipeline.close()


@pytest.mark.slow
def test_sharded_mode_single_input_matches_serial(catalog):
    """The SAME q5 SQL on the sharded (multi-chip) fragment mode: one
    actor, state stacked over an 8-device mesh, on-device vnode
    exchange — identical MV to the serial plan."""
    from risingwave_tpu.runtime.fragmenter import sharded_planned_mv

    serial = StreamPlanner(catalog, capacity=1 << 12).plan(Q5_SQL)
    sharded = sharded_planned_mv(_factory(catalog), Q5_SQL, n_shards=8)
    try:
        for c in _bid_chunks():
            serial.pipeline.push(c)
            sharded.pipeline.push(c)
            serial.pipeline.barrier()
            sharded.pipeline.barrier()
        want = serial.mview.snapshot()
        assert want
        assert sharded.mview.snapshot() == want
    finally:
        sharded.pipeline.close()


@pytest.mark.slow
def test_sharded_mode_join_matches_serial(catalog):
    """q8 SQL in sharded mode: sharded dedups feed a sharded join
    on-device (stacked chunks end to end), flattened only at the MV."""
    from risingwave_tpu.parallel.sharded_join import ShardedHashJoin
    from risingwave_tpu.runtime.fragmenter import sharded_planned_mv

    serial = StreamPlanner(catalog, capacity=1 << 12).plan(Q8_SQL)
    sharded = sharded_planned_mv(_factory(catalog), Q8_SQL, n_shards=8)
    assert any(
        isinstance(ex, ShardedHashJoin) for ex in sharded.pipeline.executors
    ), "q8 shape must actually shard"
    gen = NexmarkGenerator(NexmarkConfig())
    try:
        for _ in range(5):
            chunks = gen.next_chunks(2000, 2048)
            if chunks["person"] is not None:
                serial.pipeline.push_left(chunks["person"])
                sharded.pipeline.push_left(chunks["person"])
            if chunks["auction"] is not None:
                serial.pipeline.push_right(chunks["auction"])
                sharded.pipeline.push_right(chunks["auction"])
            serial.pipeline.barrier()
            sharded.pipeline.barrier()
        want = serial.mview.snapshot()
        assert want
        assert sharded.mview.snapshot() == want
    finally:
        sharded.pipeline.close()


def test_session_graph_mode_end_to_end():
    """SqlSession(exec_mode='graph'): CREATE TABLE + INSERT + MV with
    GROUP BY runs on the actor graph; SELECT over the MV matches the
    serial session byte for byte."""
    from risingwave_tpu.frontend.session import SqlSession

    def run(mode):
        s = SqlSession(
            Catalog({}), capacity=1 << 10, exec_mode=mode, parallelism=2
        )
        s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
        s.execute(
            "INSERT INTO t VALUES (1, 10), (2, 20), (1, 30), (3, 5), (2, 1)"
        )
        s.execute(
            "CREATE MATERIALIZED VIEW agg AS "
            "SELECT k, sum(v) AS s, count(*) AS c FROM t GROUP BY k"
        )
        s.execute("INSERT INTO t VALUES (1, 100), (4, 7)")
        out, _ = s.execute("SELECT k, s, c FROM agg ORDER BY k")
        return {
            k: list(map(int, v)) for k, v in out.items() if k != "_row_id"
        }

    assert run("graph") == run("serial")
