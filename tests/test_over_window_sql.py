"""OVER() window functions from SQL (VERDICT r4 missing #3): the
parser's window grammar lowers to GeneralOverWindowExecutor — incl.
DESC ordering (hidden negated lane), frames, and retracting inputs
(MV-on-MV: upstream agg updates shift ranks downstream).

Reference: binder window_function.rs; e2e nexmark q9 shape."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _session():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE TABLE bid (auction BIGINT, bidder BIGINT, price BIGINT, "
        "date_time BIGINT)"
    )
    return s


def test_row_number_rank_sum_over_partition():
    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW w AS SELECT auction, price, "
        "row_number() OVER (PARTITION BY auction ORDER BY price) AS rn, "
        "rank() OVER (PARTITION BY auction ORDER BY price) AS rk, "
        "sum(price) OVER (PARTITION BY auction ORDER BY price) AS rs "
        "FROM bid"
    )
    s.execute(
        "INSERT INTO bid VALUES (1, 0, 30, 0), (1, 0, 10, 0), "
        "(1, 0, 20, 0), (2, 0, 5, 0), (1, 0, 20, 0)"
    )
    out, _ = s.execute("SELECT auction, price, rn, rk, rs FROM w ORDER BY auction")
    rows = sorted(zip(*(list(out[c]) for c in ("auction", "price", "rn", "rk", "rs"))))
    assert rows == [
        (1, 10, 1, 1, 10),
        (1, 20, 2, 2, 30),
        (1, 20, 3, 2, 50),
        (1, 30, 4, 4, 80),
        (2, 5, 1, 1, 5),
    ]


def test_desc_order_and_frame():
    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW w2 AS SELECT auction, price, "
        "row_number() OVER (PARTITION BY auction ORDER BY price DESC) AS rn, "
        "sum(price) OVER (PARTITION BY auction ORDER BY price "
        "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS fs "
        "FROM bid"
    )
    s.execute(
        "INSERT INTO bid VALUES (1, 0, 10, 0), (1, 0, 30, 0), (1, 0, 20, 0)"
    )
    out, _ = s.execute("SELECT price, rn, fs FROM w2")
    rows = sorted(zip(*(list(out[c]) for c in ("price", "rn", "fs"))))
    # DESC row_number: 30->1, 20->2, 10->3; ASC frame sums: 10, 10+20, 20+30
    assert rows == [(10, 3, 10), (20, 2, 30), (30, 1, 50)]


def test_retracting_input_shifts_ranks():
    """Window over an MV: upstream count changes retract through the
    window executor and re-rank downstream rows."""
    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW cnts AS SELECT auction, count(*) AS c "
        "FROM bid GROUP BY auction"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW ranked AS SELECT auction, c, "
        "rank() OVER (ORDER BY c) AS rk FROM cnts"
    )
    s.execute("INSERT INTO bid VALUES (1, 0, 0, 0), (2, 0, 0, 0), (2, 0, 0, 0)")
    out, _ = s.execute("SELECT auction, c, rk FROM ranked ORDER BY auction")
    assert sorted(zip(list(out["auction"]), list(out["c"]), list(out["rk"]))) == [
        (1, 1, 1),
        (2, 2, 2),
    ]
    # auction 1 overtakes: 1 -> 3 bids; ranks flip via retract/re-emit
    s.execute("INSERT INTO bid VALUES (1, 0, 0, 0), (1, 0, 0, 0)")
    out, _ = s.execute("SELECT auction, c, rk FROM ranked ORDER BY auction")
    assert sorted(zip(list(out["auction"]), list(out["c"]), list(out["rk"]))) == [
        (1, 3, 2),
        (2, 2, 1),
    ]


def test_non_partition_predicate_stays_above_window():
    """WHERE on a non-PARTITION column must NOT push below the window:
    rn ranks the FULL row set, then the filter applies."""
    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW g AS SELECT auction, price FROM "
        "(SELECT auction, price, row_number() OVER "
        "(ORDER BY price DESC) AS rn FROM bid) AS t "
        "WHERE rn = 1 AND auction = 2"
    )
    # global top row is auction 1: the MV must be EMPTY (pushing
    # auction = 2 below the window would wrongly return (2, 80))
    s.execute("INSERT INTO bid VALUES (1, 0, 100, 0), (2, 0, 80, 0)")
    out, _ = s.execute("SELECT auction, price FROM g")
    assert len(out["auction"]) == 0
    # auction 2 takes the global top: exactly one row appears
    s.execute("INSERT INTO bid VALUES (2, 0, 150, 0)")
    out, _ = s.execute("SELECT auction, price FROM g")
    assert list(out["auction"]) == [2] and list(out["price"]) == [150]


def test_q9_shape_top1_per_partition():
    """The Nexmark q9 shape: highest bid per auction via row_number()
    OVER (... ORDER BY price DESC) filtered to 1 in an outer select."""
    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW q9 AS SELECT auction, price, bidder FROM "
        "(SELECT auction, price, bidder, row_number() OVER "
        "(PARTITION BY auction ORDER BY price DESC) AS rn FROM bid) AS t "
        "WHERE rn = 1"
    )
    s.execute(
        "INSERT INTO bid VALUES (1, 7, 100, 0), (1, 8, 300, 0), "
        "(2, 9, 50, 0), (1, 10, 200, 0)"
    )
    out, _ = s.execute("SELECT auction, price, bidder FROM q9 ORDER BY auction")
    assert list(out["auction"]) == [1, 2]
    assert list(out["price"]) == [300, 50]
    assert list(out["bidder"]) == [8, 9]
    # a new global max for auction 2 replaces its top row
    s.execute("INSERT INTO bid VALUES (2, 11, 500, 0)")
    out, _ = s.execute("SELECT auction, price, bidder FROM q9 ORDER BY auction")
    assert list(out["price"]) == [300, 500]
    assert list(out["bidder"]) == [8, 11]


def test_over_window_to_topn_rule():
    """row_number() ... WHERE rn <= k plans onto GroupTopN (the
    reference's over_window_to_topn_rule), not the general window
    executor — per-group maintenance instead of partition recompute."""
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )

    s = _session()
    s.execute(
        "CREATE MATERIALIZED VIEW t2 AS SELECT auction, price FROM "
        "(SELECT auction, price, row_number() OVER "
        "(PARTITION BY auction ORDER BY price DESC) AS rn FROM bid) AS x "
        "WHERE rn <= 2"
    )
    planned = s.catalog.mvs["t2"]
    assert any(
        isinstance(e, RetractableGroupTopNExecutor)
        for e in planned.pipeline.executors
    ), [type(e).__name__ for e in planned.pipeline.executors]
    s.execute(
        "INSERT INTO bid VALUES (1, 0, 10, 0), (1, 0, 30, 0), "
        "(1, 0, 20, 0), (2, 0, 5, 0)"
    )
    out, _ = s.execute("SELECT auction, price FROM t2 ORDER BY price")
    assert sorted(zip(out["auction"], out["price"])) == [
        (1, 20), (1, 30), (2, 5),
    ]
    # a new maximum displaces the group's k-th row
    s.execute("INSERT INTO bid VALUES (1, 0, 40, 0)")
    out, _ = s.execute("SELECT auction, price FROM t2 ORDER BY price")
    assert sorted(zip(out["auction"], out["price"])) == [
        (1, 30), (1, 40), (2, 5),
    ]
