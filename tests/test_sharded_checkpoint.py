"""Durable multi-chip state (VERDICT r3 #4): ShardedDedup and
ShardedHashJoin checkpoint through the standard manager and recover
mid-stream with exact parity — including onto a DIFFERENT mesh size,
and interchangeably with the single-chip executors (shared lane
naming).

Reference: state handover via durability across reschedules,
src/meta/src/stream/scale.rs:453 + consistent_hash/vnode.rs:34.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
from risingwave_tpu.executors.hash_join import HashJoinExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.parallel import (
    ShardedDedup,
    ShardedHashJoin,
    flatten_stacked,
    make_mesh,
)
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager

from tests.test_sharded_join import A_DT, P_DT, _per_shard_chunks

N = 8


def _mk_sharded(mesh, capacity=1 << 10):
    sd_p = ShardedDedup(
        mesh, ("id", "name", "starttime"), P_DT, capacity=capacity,
        table_id="sq8.dp",
    )
    sd_a = ShardedDedup(
        mesh, ("seller", "astarttime"), A_DT, capacity=capacity,
        table_id="sq8.da",
    )
    sj = ShardedHashJoin(
        mesh,
        ("id", "starttime"),
        ("seller", "astarttime"),
        P_DT,
        A_DT,
        capacity=capacity,
        fanout=8,
        out_cap=1 << 11,
        table_id="sq8.j",
    )
    mview = MaterializeExecutor(
        pk=("id", "starttime"), columns=("name",), table_id="sq8.mview"
    )
    return sd_p, sd_a, sj, mview


def _run_epoch(sd_p, sd_a, sj, mview, stacked_p, stacked_a):
    for out in sd_p.apply(stacked_p):
        for j in sj.apply_left(out):
            mview.apply(flatten_stacked(j))
    for out in sd_a.apply(stacked_a):
        for j in sj.apply_right(out):
            mview.apply(flatten_stacked(j))
    sd_p.on_barrier(None)
    sd_a.on_barrier(None)
    sj.on_barrier(None)
    mview.on_barrier(None)


def _oracle(epochs):
    o_dp = AppendOnlyDedupExecutor(
        ("id", "name", "starttime"), P_DT, capacity=1 << 12
    )
    o_da = AppendOnlyDedupExecutor(
        ("seller", "astarttime"), A_DT, capacity=1 << 12
    )
    o_j = HashJoinExecutor(
        ("id", "starttime"), ("seller", "astarttime"), P_DT, A_DT,
        capacity=1 << 12, fanout=8, out_cap=1 << 13,
    )
    o_mv = MaterializeExecutor(
        pk=("id", "starttime"), columns=("name",), table_id="oq8.mview"
    )
    for _, p_shards, _, a_shards in epochs:
        for c in p_shards:
            for d in o_dp.apply(c):
                for j in o_j.apply_left(d):
                    o_mv.apply(j)
        for c in a_shards:
            for d in o_da.apply(c):
                for j in o_j.apply_right(d):
                    o_mv.apply(j)
    return o_mv.snapshot()


@pytest.mark.parametrize("recover_shards", [N, 4])
@pytest.mark.slow
def test_sharded_q8_kill_and_recover_midstream(recover_shards):
    """Run 2 epochs sharded, checkpoint, KILL, rebuild (possibly on a
    smaller mesh), recover, run 2 more epochs — final MV must equal an
    uninterrupted single-chip run of all 4 epochs."""
    epochs = _per_shard_chunks(n_epochs=4)
    want = _oracle(epochs)
    assert len(want) > 50

    mgr = CheckpointManager(MemObjectStore())
    sd_p, sd_a, sj, mview = _mk_sharded(make_mesh(N))
    for stacked_p, _, stacked_a, _ in epochs[:2]:
        _run_epoch(sd_p, sd_a, sj, mview, stacked_p, stacked_a)
    staged = mgr.stage([sd_p, sd_a, sj, mview])
    assert staged  # all four executors contributed deltas
    mgr.commit_staged(1, staged)
    del sd_p, sd_a, sj, mview  # the "kill"

    sd_p2, sd_a2, sj2, mview2 = _mk_sharded(make_mesh(recover_shards))
    mgr.recover([sd_p2, sd_a2, sj2, mview2])
    for stacked_p, p_shards, stacked_a, a_shards in epochs[2:]:
        if recover_shards == N:
            _run_epoch(sd_p2, sd_a2, sj2, mview2, stacked_p, stacked_a)
        else:
            # re-stack the same per-shard chunks onto the smaller mesh:
            # rows keep their values, so vnode routing stays exact
            for i in range(0, N, recover_shards):
                sp = stack_chunks(p_shards[i : i + recover_shards])
                sa = stack_chunks(a_shards[i : i + recover_shards])
                _run_epoch(sd_p2, sd_a2, sj2, mview2, sp, sa)
    assert mview2.snapshot() == want


@pytest.mark.slow
def test_sharded_join_checkpoint_restores_into_single_chip():
    """Lane-naming compatibility: a sharded join's checkpoint restores
    into a single-chip HashJoinExecutor (and the stream continues with
    identical emissions) — one logical table, any executor layout."""
    mesh = make_mesh(N)
    L = {"lk": jnp.int64, "lv": jnp.int64}
    R = {"rk": jnp.int64, "rv": jnp.int64}
    sj = ShardedHashJoin(
        mesh, ("lk",), ("rk",), L, R,
        capacity=256, fanout=16, out_cap=1 << 10, table_id="xj",
    )
    oracle = HashJoinExecutor(
        ("lk",), ("rk",), L, R,
        capacity=1 << 10, fanout=16, out_cap=1 << 12, table_id="oj",
    )

    rng = np.random.default_rng(11)
    CAP = 32

    def mk(side):
        k = rng.integers(0, 40, CAP).astype(np.int64)
        v = rng.integers(0, 5, CAP).astype(np.int64)
        names = ("lk", "lv") if side == "l" else ("rk", "rv")
        return StreamChunk.from_numpy({names[0]: k, names[1]: v}, CAP)

    def shard_of(chunk, idx):
        shards = [
            chunk
            if i == idx
            else StreamChunk.from_numpy(
                {k: np.zeros(0, np.int64) for k in chunk.columns}, CAP
            )
            for i in range(N)
        ]
        return stack_chunks(shards)

    # phase 1: identical streams into sharded + oracle
    phase2 = []
    for step in range(4):
        side = "l" if step % 2 == 0 else "r"
        c = mk(side)
        if side == "l":
            sj.apply_left(shard_of(c, step % N))
            oracle.apply_left(c)
        else:
            sj.apply_right(shard_of(c, step % N))
            oracle.apply_right(c)
        phase2.append((side, mk(side)))  # pre-generate phase-2 chunks
    sj.on_barrier(None)

    mgr = CheckpointManager(MemObjectStore())
    staged = mgr.stage([sj])
    assert {d.table_id for d in staged} == {"xj.left", "xj.right"}
    mgr.commit_staged(1, staged)

    # restore into a SINGLE-CHIP executor under the sharded table_id
    # fanout must match the checkpoint's bucket width (restore lands
    # rows at their stored in-bucket positions)
    single = HashJoinExecutor(
        ("lk",), ("rk",), L, R,
        capacity=1 << 10, fanout=16, out_cap=1 << 12, table_id="xj",
    )
    mgr.recover([single])

    # phase 2: both see the same further chunks; emissions must agree
    from collections import Counter

    from risingwave_tpu.types import Op

    def acc(counter, chunks, out_names):
        for ch in chunks:
            d = ch.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = tuple(int(d[n][i]) for n in out_names)
                sign = (
                    1
                    if d["__op__"][i] in (Op.INSERT, Op.UPDATE_INSERT)
                    else -1
                )
                counter[row] += sign

    got, want = Counter(), Counter()
    for side, c in phase2:
        if side == "l":
            acc(got, single.apply_left(c), single.out_names)
            acc(want, oracle.apply_left(c), oracle.out_names)
        else:
            acc(got, single.apply_right(c), single.out_names)
            acc(want, oracle.apply_right(c), oracle.out_names)
    single.on_barrier(None)
    oracle.on_barrier(None)
    got = {k: v for k, v in got.items() if v}
    want = {k: v for k, v in want.items() if v}
    assert want and got == want
