"""Unified commit path + thread-safety + sink durability (VERDICT r2 #3,
ADVICE r2). Reference contracts:
- one commit implementation for sync and async lanes
  (src/storage/src/hummock/event_handler/uploader.rs:548,
  src/meta/src/hummock/manager/commit_epoch.rs:93);
- compaction off the commit path (compactor_runner.rs:62);
- sink commits never ahead of durability (executor/sink.rs:40).
"""

import threading

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.sink import BlackholeSink, SinkExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager, StateDelta


def _chunk(ids, vals, cap=8):
    return StreamChunk.from_numpy(
        {"id": np.asarray(ids, np.int64), "v": np.asarray(vals, np.int64)},
        cap,
    )


def _mk_runtime(**kw):
    store = MemObjectStore()
    rt = StreamingRuntime(store, checkpoint_frequency=1, **kw)
    mv = MaterializeExecutor(pk=["id"], columns=["v"], table_id="mv1")
    sink = BlackholeSink()
    se = SinkExecutor(sink, pk=["id"], columns=["v"])
    rt.register("f", Pipeline([mv, se]))
    return rt, store, mv, sink, se


def test_async_and_sync_commits_share_validation():
    """The async lane must enforce the same duplicate-table_id check as
    the sync path (it previously skipped it)."""
    store = MemObjectStore()
    rt = StreamingRuntime(store, checkpoint_frequency=1)
    a = MaterializeExecutor(pk=["id"], columns=["v"], table_id="dup")
    b = MaterializeExecutor(pk=["id"], columns=["v"], table_id="dup")
    rt.register("f", Pipeline([a]))
    rt.register("g", Pipeline([b]))
    for ex in (a, b):
        ex.apply(_chunk([1], [10]))
    with pytest.raises(ValueError, match="duplicate table_id"):
        rt.barrier()


def test_async_commit_epoch_monotonicity_enforced():
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    d = StateDelta(
        "t", {"k": np.array([1])}, {"v": np.array([2])},
        np.array([False]), ("k",),
    )
    mgr.commit_staged(100, [d])
    with pytest.raises(ValueError, match="<= committed"):
        mgr.commit_staged(100, [d])
    with pytest.raises(ValueError, match="<= committed"):
        mgr.commit_staged(50, [d])


def test_concurrent_barriers_flush_compaction_stress():
    """Barriers racing FLUSH racing compaction: drive many epochs with a
    tiny compact_at so compaction constantly rewrites runs while the
    async lane commits; every row must survive recovery."""
    rt, store, mv, sink, se = _mk_runtime(compact_at=2)
    stop = threading.Event()
    flush_err = []

    def flusher():
        while not stop.is_set():
            try:
                rt.wait_checkpoints()
            except Exception as e:  # pragma: no cover
                flush_err.append(e)
                return

    t = threading.Thread(target=flusher)
    t.start()
    n = 30
    for i in range(n):
        mv.apply(_chunk([i, i + 1000], [i, -i]))
        se.apply(_chunk([i], [i]))
        rt.barrier()
    rt.wait_checkpoints()
    stop.set()
    t.join()
    assert not flush_err
    rt.wait_compaction()

    # recover into a twin and compare the full MV
    mv2 = MaterializeExecutor(pk=["id"], columns=["v"], table_id="mv1")
    mgr2 = CheckpointManager(store)
    mgr2.recover([mv2])
    assert mv2.snapshot() == mv.snapshot()
    assert len(mv2.snapshot()) == 2 * n


def test_sink_commit_deferred_until_durable():
    """With a checkpoint store, sink delivery happens only after the
    epoch's manifest persisted — on_barrier alone delivers nothing."""
    rt, store, mv, sink, se = _mk_runtime(async_checkpoint=False)
    assert se.deliver_on_durable
    se.apply(_chunk([1], [10]))
    rt.barrier()  # sync path: durable inside barrier -> delivered after
    assert sink.rows_written == 1
    assert sink.commits == 1


def test_sink_deferred_async_delivers_after_wait():
    rt, store, mv, sink, se = _mk_runtime()
    se.apply(_chunk([1], [10]))
    se.apply(_chunk([2], [20]))
    rt.barrier()
    rt.wait_checkpoints()
    assert sink.rows_written == 2
    assert sink.commits >= 1


def test_sink_standalone_immediate():
    """No runtime/store: old behavior — write at barrier, commit at
    checkpoint barrier (documented at-least-once standalone mode)."""
    sink = BlackholeSink()
    se = SinkExecutor(sink, pk=["id"], columns=["v"])
    p = Pipeline([se])
    se.apply(_chunk([1], [10]))
    p.barrier()
    assert sink.rows_written == 1
    assert sink.commits == 1


def test_materialize_pending_bounded_without_checkpoint():
    """Native-path _pending must not grow with stream length when no
    checkpoint manager drains it (barrier compacts to net effect)."""
    mv = MaterializeExecutor(pk=["id"], columns=["v"], table_id="m")
    p = Pipeline([mv])
    for i in range(50):
        mv.apply(_chunk([1, 2], [i, i]))
        p.barrier()
    assert mv._backend == "native"
    assert len(mv._pending) <= 2  # one net batch + at most one new chunk
    total_rows = sum(len(k) for k, _, _ in mv._pending)
    assert total_rows <= 4


def test_compaction_cas_preserves_racing_commit():
    """compact_once must not drop SSTs committed while it merged."""
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)

    def delta(i):
        return StateDelta(
            "t",
            {"k": np.array([i], np.int64)},
            {"v": np.array([i * 10], np.int64)},
            np.array([False]),
            ("k",),
        )

    mgr.commit_staged(1 << 16, [delta(1)])
    mgr.commit_staged(2 << 16, [delta(2)])
    # simulate a commit landing between compaction's read and its swap:
    orig_read = mgr.store.read
    raced = {"done": False}

    def racing_read(path):
        blob = orig_read(path)
        if not raced["done"]:
            raced["done"] = True
            mgr.commit_staged(3 << 16, [delta(3)])
        return blob

    mgr.store.read = racing_read
    assert mgr.compact_once("t", 2 << 16)
    mgr.store.read = orig_read
    keys, vals = mgr.read_table("t")
    assert sorted(keys["k"].tolist()) == [1, 2, 3]
