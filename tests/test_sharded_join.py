"""Sharded q8 end-to-end on the virtual 8-device mesh (VERDICT r2 #2):
vnode-exchanged dedup + join fragments must match the single-chip
pipeline exactly. Plus join-type parity for the sharded join.

Reference model: every fragment runs N actors fed by a hash dispatcher
(dispatch.rs:683); here each fragment is one shard_map program (see
parallel/sharded_join.py)."""

from collections import Counter

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors.hash_join import HashJoinExecutor
from risingwave_tpu.executors.hop_window import _hop_step
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.parallel import (
    ShardedDedup,
    ShardedHashJoin,
    flatten_stacked,
    make_mesh,
)
from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.types import Op

N = 8
WINDOW_MS = 10_000


def _per_shard_chunks(n_epochs=3, events=800, cap=1024):
    """Per-shard person/auction chunk streams (one Nexmark split each),
    tumbled on the host (stateless pre-op, same as the q5 dryrun)."""
    dicts = NexmarkGenerator.make_dictionaries()
    gens = [
        NexmarkGenerator(
            NexmarkConfig(), split_index=i, split_num=N, dictionaries=dicts
        )
        for i in range(N)
    ]
    epochs = []
    for _ in range(n_epochs):
        p_shards, a_shards = [], []
        for g in gens:
            ch = g.next_chunks(events, cap)
            p = ch["person"]
            if p is None:
                p = StreamChunk.from_numpy(
                    {
                        "id": np.zeros(0, np.int64),
                        "name": np.zeros(0, np.int32),
                        "date_time": np.zeros(0, np.int64),
                    },
                    cap,
                )
            else:
                p = p.select(["id", "name", "date_time"])
            a = ch["auction"]
            if a is None:
                a = StreamChunk.from_numpy(
                    {
                        "seller": np.zeros(0, np.int64),
                        "date_time": np.zeros(0, np.int64),
                    },
                    cap,
                )
            else:
                a = a.select(["seller", "date_time"])
            p_shards.append(
                _hop_step(p, "date_time", WINDOW_MS, WINDOW_MS, "starttime")
                .select(["id", "name", "starttime"])
            )
            a_shards.append(
                _hop_step(a, "date_time", WINDOW_MS, WINDOW_MS, "astarttime")
                .select(["seller", "astarttime"])
            )
        epochs.append((stack_chunks(p_shards), p_shards, stack_chunks(a_shards), a_shards))
    return epochs


P_DT = {"id": jnp.int64, "name": jnp.int32, "starttime": jnp.int64}
A_DT = {"seller": jnp.int64, "astarttime": jnp.int64}


@pytest.mark.slow
def test_sharded_q8_matches_single_chip():
    mesh = make_mesh(N)
    sd_p = ShardedDedup(
        mesh, ("id", "name", "starttime"), P_DT, capacity=1 << 10
    )
    sd_a = ShardedDedup(mesh, ("seller", "astarttime"), A_DT, capacity=1 << 10)
    sj = ShardedHashJoin(
        mesh,
        ("id", "starttime"),
        ("seller", "astarttime"),
        P_DT,
        A_DT,
        capacity=1 << 10,
        fanout=8,
        out_cap=1 << 11,
    )
    mview = MaterializeExecutor(
        pk=("id", "starttime"), columns=("name",), table_id="sq8.mview"
    )

    # single-chip oracle: same dedup -> join -> MV chain, fed serially
    o_dp = AppendOnlyDedupExecutor(
        ("id", "name", "starttime"), P_DT, capacity=1 << 12
    )
    o_da = AppendOnlyDedupExecutor(
        ("seller", "astarttime"), A_DT, capacity=1 << 12
    )
    o_j = HashJoinExecutor(
        ("id", "starttime"), ("seller", "astarttime"), P_DT, A_DT,
        capacity=1 << 12, fanout=8, out_cap=1 << 13,
    )
    o_mv = MaterializeExecutor(
        pk=("id", "starttime"), columns=("name",), table_id="oq8.mview"
    )

    for stacked_p, p_shards, stacked_a, a_shards in _per_shard_chunks():
        for c in p_shards:
            for d in o_dp.apply(c):
                for j in o_j.apply_left(d):
                    o_mv.apply(j)
        for c in a_shards:
            for d in o_da.apply(c):
                for j in o_j.apply_right(d):
                    o_mv.apply(j)

        for out in sd_p.apply(stacked_p):
            for j in sj.apply_left(out):
                mview.apply(flatten_stacked(j))
        for out in sd_a.apply(stacked_a):
            for j in sj.apply_right(out):
                mview.apply(flatten_stacked(j))
        sd_p.on_barrier(None)
        sd_a.on_barrier(None)
        sj.on_barrier(None)

    got = mview.snapshot()
    want = o_mv.snapshot()
    assert len(want) > 50
    assert got == want


@pytest.mark.parametrize("join_type", ["left", "full", "left_semi", "left_anti"])
def test_sharded_join_types_match_single(join_type):
    """Random insert streams through sharded vs single-chip join emit
    the same net multiset for every join type."""
    mesh = make_mesh(N)
    L = {"lk": jnp.int64, "lv": jnp.int64}
    R = {"rk": jnp.int64, "rv": jnp.int64}
    sj = ShardedHashJoin(
        mesh, ("lk",), ("rk",), L, R,
        capacity=256, fanout=16, out_cap=1 << 10, join_type=join_type,
    )
    single = HashJoinExecutor(
        ("lk",), ("rk",), L, R,
        capacity=1 << 10, fanout=32, out_cap=1 << 12, join_type=join_type,
    )

    rng = np.random.default_rng(7)
    CAP = 32

    def mk(side):
        k = rng.integers(0, 48, CAP).astype(np.int64)
        v = rng.integers(0, 5, CAP).astype(np.int64)
        names = ("lk", "lv") if side == "l" else ("rk", "rv")
        return StreamChunk.from_numpy({names[0]: k, names[1]: v}, CAP)

    def acc_into(acc, chunks, out_names):
        for c in chunks:
            d = c.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = tuple(
                    None
                    if (d.get(n + "__null") is not None and d[n + "__null"][i])
                    else int(d[n][i])
                    for n in out_names
                )
                sign = (
                    1
                    if d["__op__"][i] in (Op.INSERT, Op.UPDATE_INSERT)
                    else -1
                )
                acc[row] += sign

    got, want = Counter(), Counter()
    for step in range(6):
        side = "l" if step % 2 == 0 else "r"
        chunk = mk(side)
        shards = [
            chunk if i == step % N else StreamChunk.from_numpy(
                {k: np.zeros(0, np.int64) for k in chunk.columns}, CAP
            )
            for i in range(N)
        ]
        stacked = stack_chunks(shards)
        if side == "l":
            outs = sj.apply_left(stacked)
            souts = single.apply_left(chunk)
        else:
            outs = sj.apply_right(stacked)
            souts = single.apply_right(chunk)
        acc_into(got, [flatten_stacked(o) for o in outs], sj.out_names)
        acc_into(want, souts, single.out_names)
    sj.on_barrier(None)
    single.on_barrier(None)
    got = {k: v for k, v in got.items() if v}
    want = {k: v for k, v in want.items() if v}
    assert want and got == want
