"""Sync-point-driven deterministic crash tests (reference:
src/utils/sync-point + storage failpoint tests)."""

import pytest

from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore


@pytest.fixture(autouse=True)
def _clean():
    yield
    sync_point.reset()


class Boom(Exception):
    pass


def _push_epoch(rt, q5, gen):
    c = gen.next_chunks(2_000, 1 << 11)["bid"]
    if c is not None:
        rt.push("q5", c.select(["auction", "date_time"]))


def test_crash_between_sst_upload_and_manifest_commit():
    """SSTs uploaded but manifest unwritten is the classic torn-commit
    window: recovery must land on the PREVIOUS epoch exactly."""
    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=False)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    _push_epoch(rt, q5, gen)
    rt.barrier()
    want = q5.mview.snapshot()  # state at the durable epoch

    sync_point.activate(
        "before_manifest_commit", lambda: (_ for _ in ()).throw(Boom())
    )
    _push_epoch(rt, q5, gen)
    with pytest.raises(Boom):
        rt.barrier()
    sync_point.deactivate("before_manifest_commit")

    rt2 = StreamingRuntime(store)
    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt2.register("q5b", q5b.pipeline)
    rt2.recover()
    assert q5b.mview.snapshot() == want  # previous epoch, not the torn one


def test_sync_point_ordering_record():
    """hit() is observable and zero-cost when inactive."""
    seen = []
    sync_point.hit("before_manifest_commit")  # inactive: no-op
    sync_point.activate("after_manifest_commit", lambda: seen.append("c"))
    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=False)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    _push_epoch(rt, q5, gen)
    rt.barrier()
    assert seen == ["c"]
