"""End-to-end Nexmark q8: person ⋈ auction per tumble window, MV
snapshot checked against a pandas oracle over the same events
(reference: e2e_test/nexmark/ q8 + simulation Nexmark tests)."""

import numpy as np
import pandas as pd

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import Q8_WINDOW_MS, build_q8


def _oracle(persons, auctions, window_ms):
    pdf = pd.DataFrame(persons).drop_duplicates()
    adf = pd.DataFrame(auctions).drop_duplicates()
    pdf["starttime"] = (pdf["date_time"] // window_ms) * window_ms
    adf["astarttime"] = (adf["date_time"] // window_ms) * window_ms
    p = pdf[["id", "name", "starttime"]].drop_duplicates()
    a = adf[["seller", "astarttime"]].drop_duplicates()
    m = p.merge(a, left_on=["id", "starttime"], right_on=["seller", "astarttime"])
    return {
        (int(r.id), int(r.starttime)): (int(r.name),)
        for r in m.itertuples()
    }


def test_q8_pipeline_matches_pandas():
    q8 = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    gen = NexmarkGenerator(NexmarkConfig())

    all_p = {"id": [], "name": [], "date_time": []}
    all_a = {"seller": [], "date_time": []}
    for epoch in range(4):
        for _ in range(3):
            chunks = gen.next_chunks(2000, 2048)
            person = chunks["person"]
            auction = chunks["auction"]
            if person is not None:
                d = person.to_numpy(with_ops=False)
                for k in all_p:
                    all_p[k].extend(d[k].tolist())
                q8.pipeline.push_left(
                    person.select(["id", "name", "date_time"])
                )
            if auction is not None:
                d = auction.to_numpy(with_ops=False)
                for k in all_a:
                    all_a[k].extend(d[k].tolist())
                q8.pipeline.push_right(
                    auction.select(["seller", "date_time"])
                )
        q8.pipeline.barrier()

    want = _oracle(all_p, all_a, Q8_WINDOW_MS)
    got = q8.mview.snapshot()
    assert len(want) > 50
    assert got == want


def test_q8_watermark_state_cleaning():
    q8 = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    gen = NexmarkGenerator(NexmarkConfig())

    max_ts = 0
    for epoch in range(3):
        chunks = gen.next_chunks(2000, 2048)
        person, auction = chunks["person"], chunks["auction"]
        if person is not None:
            max_ts = max(max_ts, int(person.to_numpy(False)["date_time"].max()))
            q8.pipeline.push_left(person.select(["id", "name", "date_time"]))
        if auction is not None:
            q8.pipeline.push_right(auction.select(["seller", "date_time"]))
        q8.pipeline.barrier()
        q8.pipeline.watermark("date_time", max_ts)

    # all windows strictly below the watermark's window are closed:
    # join state for them is gone
    mv_rows = len(q8.mview.snapshot())
    live = int(q8.join.left.table.num_live())
    assert mv_rows > 0
    # only the watermark's own (possibly still-open) window survives
    closed_cutoff = (max_ts // Q8_WINDOW_MS) * Q8_WINDOW_MS
    lane = np.asarray(q8.join.left.table.keys[1])
    live_mask = np.asarray(q8.join.left.table.live)
    assert (lane[live_mask] >= closed_cutoff).all()
    assert live > 0  # the open window's persons are still joinable
