"""Config layering, metrics registry, and the sink framework
(reference: config.rs:138, guarded_metrics.rs, sink/mod.rs:337,
compact_chunk.rs)."""

import json

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.config import RwConfig, load_config
from risingwave_tpu.connectors.sink import (
    BlackholeSink,
    FileSink,
    SinkExecutor,
    compact_rows,
)
from risingwave_tpu.executors import Barrier
from risingwave_tpu.executors.base import Epoch
from risingwave_tpu.metrics import MetricsRegistry
from risingwave_tpu.types import Op


def test_config_layering(tmp_path):
    toml = tmp_path / "rw.toml"
    toml.write_text(
        """
[system]
barrier_interval_ms = 250

[streaming]
chunk_capacity = 8192
future_knob = 7

[brand_new_section]
x = 1
"""
    )
    cfg = load_config(str(toml), overrides={"system.checkpoint_frequency": 5})
    assert cfg.system.barrier_interval_ms == 250
    assert cfg.system.checkpoint_frequency == 5
    assert cfg.streaming.chunk_capacity == 8192
    assert cfg.storage.compact_at == 8  # untouched default
    assert cfg.unrecognized["streaming.future_knob"] == 7
    assert "brand_new_section" in cfg.unrecognized


def test_runtime_from_config(tmp_path):
    from risingwave_tpu.runtime import StreamingRuntime

    cfg = RwConfig()
    cfg.storage.object_store_root = str(tmp_path / "state")
    cfg.system.barrier_interval_ms = 123
    cfg.storage.compact_at = 3
    rt = StreamingRuntime.from_config(cfg)
    assert rt.barrier_interval_ms == 123
    assert rt.mgr.compact_at == 3


def test_metrics_registry():
    reg = MetricsRegistry()
    reg.counter("rows_total").inc(5, fragment="q5")
    reg.counter("rows_total").inc(2, fragment="q5")
    reg.histogram("lat_ms").observe(10.0)
    reg.histogram("lat_ms").observe(30.0)
    assert reg.counter("rows_total").get(fragment="q5") == 7
    assert reg.histogram("lat_ms").percentile(50) == 20.0
    text = reg.render()
    assert 'rows_total{fragment="q5"} 7' in text
    assert "lat_ms_count 2" in text


def test_compact_rows_net_effect():
    rows = [
        ((1,), (10,), Op.INSERT),
        ((1,), (10,), Op.UPDATE_DELETE),
        ((1,), (11,), Op.UPDATE_INSERT),   # 1: insert then update -> (11,)
        ((2,), (20,), Op.DELETE),          # 2: pre-existing delete
        ((3,), (30,), Op.INSERT),
        ((3,), (30,), Op.DELETE),          # 3: appeared+vanished -> nothing
    ]
    out = compact_rows(rows)
    assert out == [((1,), (11,), Op.INSERT), ((2,), None, Op.DELETE)]


def test_sink_executor_file_and_blackhole(tmp_path):
    bh = BlackholeSink()
    ex = SinkExecutor(bh, pk=("k",), columns=("k", "v"))
    chunk = StreamChunk.from_numpy(
        {"k": np.array([1, 2, 1], np.int64), "v": np.array([5, 6, 7], np.int64)},
        8,
        ops=np.array([Op.INSERT, Op.INSERT, Op.UPDATE_DELETE], np.int32),
    )
    ex.apply(chunk)
    ex.on_barrier(Barrier(Epoch(0, 1)))
    ex.finish_barrier()
    # pk 1: insert then update-delete -> vanished within epoch; pk 2 stays
    assert bh.rows_written == 1 and bh.commits == 1

    path = str(tmp_path / "out.jsonl")
    fs = FileSink(path, columns=("k", "v"))
    ex2 = SinkExecutor(fs, pk=("k",), columns=("k", "v"))
    ex2.apply(
        StreamChunk.from_numpy(
            {"k": np.array([9], np.int64), "v": np.array([90], np.int64)}, 4
        )
    )
    ex2.on_barrier(Barrier(Epoch(1, 2)))
    ex2.finish_barrier()
    lines = [json.loads(l) for l in open(path)]
    assert lines[0] == {"op": "insert", "pk": [9], "row": [9, 90]}
    assert lines[1]["op"] == "commit"


def test_metrics_gauge_and_http_exposition():
    import urllib.request

    from risingwave_tpu.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("rows_total").inc(5, fragment="q5")
    reg.gauge("state_bytes").set(1234.0)
    reg.histogram("lat_ms").observe(2.0)
    reg.histogram("lat_ms").observe(4.0)
    text = reg.render()
    assert '# TYPE rows_total counter' in text
    assert 'rows_total{fragment="q5"} 5.0' in text
    assert '# TYPE state_bytes gauge' in text
    assert 'lat_ms_count 2' in text and 'quantile="0.5"' in text

    port = reg.serve(0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert body == text
    finally:
        reg.shutdown()


def test_events_endpoint_and_bounded_histogram():
    import urllib.request

    from risingwave_tpu.event_log import EVENT_LOG
    from risingwave_tpu.metrics import REGISTRY

    EVENT_LOG.clear()
    EVENT_LOG.record("ddl", tag="CREATE_TABLE", sql="CREATE TABLE t (...)")
    EVENT_LOG.record("recovery", mode="auto", cause="Boom()")
    port = REGISTRY.serve(0)
    try:
        doc = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/events", timeout=5
            ).read().decode()
        )
        kinds = [e["kind"] for e in doc["events"]]
        assert kinds[-2:] == ["ddl", "recovery"]
        assert doc["events"][-2]["tag"] == "CREATE_TABLE"
        # the dashboard renders the same history
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/dashboard", timeout=5
        ).read().decode()
        assert "/events" in html and "recovery" in html
    finally:
        REGISTRY.shutdown()

    # Histogram memory is bounded: quantiles window, totals stay exact
    reg = MetricsRegistry()
    h = reg.histogram("long_run_ms")
    for i in range(3 * h.window):
        h.observe(float(i), stage="upload")
    key = (("stage", "upload"),)
    assert len(h._obs[key]) == h.window
    assert h.count(stage="upload") == 3 * h.window
    assert f'long_run_ms{{stage="upload"}}_count {3 * h.window}' in reg.render()
    # the window sees only the newest observations
    assert h.percentile(0, stage="upload") >= float(2 * h.window)


def test_roofline_fields_and_stage_breakdown():
    """The bench JSON contract: achieved_bw_frac is a measured
    fraction of a configured chip peak, and barrier_stage_ms carries a
    per-stage breakdown once barriers ran."""
    import os

    from risingwave_tpu.epoch_trace import (
        hbm_peak_gbps,
        record_stage,
        roofline,
        stage_breakdown,
    )

    rf = roofline(10 * 10**9, 1.0, platform="cpu")
    assert rf["achieved_bw_gbps"] == 10.0
    assert 0.0 < rf["achieved_bw_frac"] <= 1.0
    assert rf["achieved_bw_frac"] == round(10.0 / rf["hbm_peak_gbps"], 6)
    assert roofline(0, 0.0)["achieved_bw_frac"] == 0.0
    os.environ["RW_HBM_PEAK_GBPS"] = "123.0"
    try:
        assert hbm_peak_gbps("tpu") == 123.0
    finally:
        del os.environ["RW_HBM_PEAK_GBPS"]

    record_stage("manifest_commit", 2.0)
    bd = stage_breakdown()
    assert any("stage=manifest_commit" in k for k in bd)
    row = next(v for k, v in bd.items() if "stage=manifest_commit" in k)
    assert {"p50", "p99", "count", "sum"} <= set(row)


def test_tracer_spans_and_chrome_export(tmp_path):
    import json

    from risingwave_tpu.trace import TRACER

    TRACER.clear()
    with TRACER.span("unit.outer", k=1):
        with TRACER.span("unit.inner"):
            pass
    doc = json.loads(TRACER.chrome_trace())
    names = [e["name"] for e in doc["traceEvents"]]
    assert "unit.outer" in names and "unit.inner" in names
    path = tmp_path / "trace.json"
    TRACER.dump(str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_arrow_roundtrip():
    import numpy as np
    import pyarrow as pa  # noqa: F401 — availability gate

    from risingwave_tpu.array.arrow import chunk_from_arrow, chunk_to_arrow
    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.array.dictionary import StringDictionary

    d = StringDictionary()
    codes = d.encode(["alpha", "beta", "alpha"])
    chunk = StreamChunk.from_numpy(
        {
            "k": np.asarray([1, 2, 3], np.int64),
            "s": codes.astype(np.int32),
            "v": np.asarray([1.5, 0.0, -2.25], np.float64),
        },
        8,
        nulls={"v": np.asarray([False, True, False])},
    )
    batch = chunk_to_arrow(chunk, dictionaries={"s": d})
    assert batch.num_rows == 3
    assert batch.column("s").to_pylist() == ["alpha", "beta", "alpha"]
    assert batch.column("v").to_pylist()[1] is None

    dicts = {}
    back = chunk_from_arrow(batch, dictionaries=dicts)
    got = back.to_numpy(False)
    assert got["k"].tolist() == [1, 2, 3]
    assert [dicts["s"].decode_one(c) for c in got["s"].tolist()] == [
        "alpha", "beta", "alpha",
    ]
    assert got["v__null"].tolist() == [False, True, False]
