"""Storage depth (VERDICT r4 next #10): block-granular SSTs, ordered
range/backward iteration, and the two-level compaction picker.

Reference: src/storage/src/hummock/sstable/builder.rs:95 (block
layout), iterator/ (forward/backward merge iterators),
compaction/picker/ (leveled picker bounding write amplification)."""

import numpy as np
import pytest

from risingwave_tpu.storage.block_sst import (
    BlockSst,
    build_block_sst,
    order_tuple,
)
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import (
    CheckpointManager,
    StateDelta,
)

pytestmark = pytest.mark.smoke


def _commit(mgr, epoch, tid, ks, vs, tomb=None):
    n = len(ks)
    mgr.commit_staged(
        epoch,
        [
            StateDelta(
                tid,
                {"k": np.asarray(ks, np.int64)},
                {"v": np.asarray(vs, np.int64)},
                np.zeros(n, bool) if tomb is None else np.asarray(tomb),
                ("k",),
            )
        ],
    )


def test_block_sst_point_and_range_reads():
    store = MemObjectStore()
    n = 20_000
    ks = np.arange(n, dtype=np.int64)
    blob = build_block_sst(
        "t", 1, {"k": ks}, {"v": ks * 7}, np.zeros(n, bool), ("k",),
        block_rows=1024,
    )
    store.put("t.sst", blob)
    r = BlockSst(store, "t.sst")
    assert r.meta.n_rows == n and len(r.blocks) == (n + 1023) // 1024

    # point read touches header + one block, not the whole file
    store.bytes_read = 0
    hit, tomb, vals = r.point_read(
        [np.asarray([5000, 19999, 123456], np.int64)],
        np.ones(3, bool),
    )
    assert list(hit) == [True, True, False]
    assert vals["v"][0] == 35000 and vals["v"][1] == 19999 * 7
    assert store.bytes_read < len(blob) // 4

    # range scan loads only overlapping blocks
    store.bytes_read = 0
    got = []
    blo = order_tuple((7000,), [np.dtype(np.int64)])
    bhi = order_tuple((7100,), [np.dtype(np.int64)])
    for blk in r.scan_blocks(blo, bhi):
        m = (blk["k_k"] >= 7000) & (blk["k_k"] <= 7100)
        got.extend(blk["k_k"][m].tolist())
    assert got == list(range(7000, 7101))
    assert store.bytes_read < len(blob) // 8

    # backward iteration yields blocks in reverse key order
    firsts = [blk["k_k"][0] for blk in r.scan_blocks(reverse=True)]
    assert firsts == sorted(firsts, reverse=True)


def test_leveled_compaction_bounds_rewrites_and_stays_exact():
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=4)
    rng = np.random.default_rng(3)
    oracle = {}
    epoch = 0
    # many epochs over a WIDE key space: compactions must go leveled
    for round_ in range(16):
        epoch += 1 << 16
        ks = rng.integers(0, 200_000, 500)
        vs = rng.integers(0, 1 << 30, 500)
        for k, v in zip(ks.tolist(), vs.tolist()):
            oracle[k] = v
        _commit(mgr, epoch, "lt", ks, vs)
        mgr._maybe_compact(epoch)
    entries = mgr.version["tables"]["lt"]
    l1 = [e for e in entries if e.get("level", 0) == 1]
    assert l1, "no leveled files were ever produced"
    # L1 files are non-overlapping and sorted
    spans = sorted((tuple(e["first"]), tuple(e["last"])) for e in l1)
    for (f1, l1_), (f2, _) in zip(spans, spans[1:]):
        assert l1_ < f2, "L1 files overlap"

    # point reads agree with the oracle
    probe = rng.choice(list(oracle), 300, replace=False)
    found, vals = mgr.get_rows(
        "lt", {"k": np.asarray(probe, np.int64)}
    )
    assert found.all()
    assert [oracle[k] for k in probe.tolist()] == vals["v"][found].tolist()

    # full recovery read agrees
    keys, vals = mgr.read_table("lt")
    assert dict(zip(keys["k"].tolist(), vals["v"].tolist())) == oracle


def test_leveled_point_reads_are_sublinear():
    """A narrow probe over a big leveled store must read a small
    fraction of the stored bytes (block index + bloom + few blocks)."""
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    epoch = 0
    n_per = 30_000
    for r in range(4):
        epoch += 1 << 16
        ks = np.arange(r * n_per, (r + 1) * n_per, dtype=np.int64)
        _commit(mgr, epoch, "big", ks, ks * 3)
        mgr._maybe_compact(epoch)
    total = sum(len(b) for p, b in store._blobs.items() if "/sst/" in p)
    # fresh manager: cold cache, every byte accounted
    mgr2 = CheckpointManager(store, compact_at=2)
    store.bytes_read = 0
    found, vals = mgr2.get_rows(
        "big", {"k": np.asarray([7, 50_000, 119_999], np.int64)}
    )
    assert found.all() and vals["v"].tolist() == [21, 150_000, 359_997]
    assert store.bytes_read < total // 5, (store.bytes_read, total)


def test_scan_range_ordered_and_backward():
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    # two-lane key: (g, k); updates + tombstones across epochs
    def commit2(epoch, gs, ks, vs, tomb=None):
        n = len(gs)
        mgr.commit_staged(
            epoch,
            [
                StateDelta(
                    "r2",
                    {
                        "g": np.asarray(gs, np.int64),
                        "k": np.asarray(ks, np.int64),
                    },
                    {"v": np.asarray(vs, np.int64)},
                    np.zeros(n, bool)
                    if tomb is None
                    else np.asarray(tomb),
                    ("g", "k"),
                )
            ],
        )

    commit2(1 << 16, [1] * 5 + [2] * 5, list(range(5)) * 2,
            [10, 11, 12, 13, 14, 20, 21, 22, 23, 24])
    commit2(2 << 16, [1, 1], [2, 4], [99, 0], tomb=[False, True])
    mgr._maybe_compact(2 << 16)
    commit2(3 << 16, [1], [9], [77])

    keys, vals = mgr.scan_range(
        "r2", prefix_cols={"g": 1}, range_col="k", lo=1, hi=9
    )
    assert keys["k"].tolist() == [1, 2, 3, 9]  # k=4 tombstoned
    assert vals["v"].tolist() == [11, 99, 13, 77]  # k=2 updated

    keys, vals = mgr.scan_range(
        "r2", prefix_cols={"g": 1}, range_col="k", lo=1, hi=9,
        reverse=True,
    )
    assert keys["k"].tolist() == [9, 3, 2, 1]

    # full prefix scan of g=2 untouched by g=1 churn
    keys, vals = mgr.scan_prefix("r2", {"g": 2})
    assert keys["k"].tolist() == [0, 1, 2, 3, 4]
    assert vals["v"].tolist() == [20, 21, 22, 23, 24]


def test_epoch_pinned_mvcc_reads():
    """get_rows/scan_range accept an MVCC snapshot pin: the read sees
    exactly the state committed at that epoch (StateStore epoch-pinned
    read options)."""
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=100)  # keep history
    _commit(mgr, 1 << 16, "mv", [1, 2], [10, 20])
    _commit(mgr, 2 << 16, "mv", [1], [11])           # update k=1
    _commit(mgr, 3 << 16, "mv", [2], [0], tomb=[True])  # delete k=2

    def at(epoch):
        found, vals = mgr.get_rows(
            "mv", {"k": np.asarray([1, 2], np.int64)}, at_epoch=epoch
        )
        return {
            k: int(vals["v"][i])
            for i, k in enumerate((1, 2))
            if found[i]
        }

    assert at(1 << 16) == {1: 10, 2: 20}
    assert at(2 << 16) == {1: 11, 2: 20}
    assert at(3 << 16) == {1: 11}
    assert at(None) == {1: 11}

    keys, vals = mgr.scan_range("mv", at_epoch=1 << 16)
    assert keys["k"].tolist() == [1, 2] and vals["v"].tolist() == [10, 20]


def test_mvcc_pin_below_compaction_floor_raises():
    store = MemObjectStore()
    mgr = CheckpointManager(store, compact_at=2)
    _commit(mgr, 1 << 16, "f", [1], [10])
    _commit(mgr, 2 << 16, "f", [1], [11])
    mgr._maybe_compact(2 << 16)  # folds e1+e2 into L1(epoch = e2)
    _commit(mgr, 3 << 16, "f", [1], [12])
    # pins at/above the floor work
    found, vals = mgr.get_rows(
        "f", {"k": np.asarray([1], np.int64)}, at_epoch=2 << 16
    )
    assert found[0] and vals["v"][0] == 11
    # a pin below the folded history refuses instead of reading empty
    from risingwave_tpu.storage.state_table import EpochFloorError

    with pytest.raises(EpochFloorError, match="compaction floor"):
        mgr.get_rows(
            "f", {"k": np.asarray([1], np.int64)}, at_epoch=1 << 16
        )
