"""Batch local-mode queries over MV snapshots (reference: batch
executors + local execution mode)."""

import numpy as np

from risingwave_tpu.batch import BatchQueryEngine
from risingwave_tpu.connectors.nexmark import BID_SCHEMA, NexmarkConfig, NexmarkGenerator
from risingwave_tpu.sql import Catalog, StreamPlanner


def _mv_with_data():
    planner = StreamPlanner(Catalog({"bid": BID_SCHEMA}), capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW counts AS "
        "SELECT auction, window_start, count(*) AS num "
        "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        "GROUP BY auction, window_start"
    )
    gen = NexmarkGenerator(NexmarkConfig())
    for _ in range(3):
        mv.pipeline.push(gen.next_chunks(1500, 2048)["bid"])
        mv.pipeline.barrier()
    return mv


def test_batch_scan_filter_order_limit():
    mv = _mv_with_data()
    eng = BatchQueryEngine({"counts": mv.mview})
    out = eng.query(
        "SELECT auction, num FROM counts WHERE num >= 3 "
        "ORDER BY num DESC LIMIT 5"
    )
    snap = mv.mview.snapshot()
    want = sorted((v[0] for v in snap.values() if v[0] >= 3), reverse=True)[:5]
    assert out["num"].tolist() == want
    assert len(out["auction"]) == len(want)


def test_batch_scalar_and_group_agg():
    mv = _mv_with_data()
    eng = BatchQueryEngine({"counts": mv.mview})
    snap = mv.mview.snapshot()

    total = eng.query("SELECT sum(num) AS s, count(*) AS c FROM counts")
    assert total["s"][0] == sum(v[0] for v in snap.values())
    assert total["c"][0] == len(snap)

    per_auction = eng.query(
        "SELECT auction, sum(num) AS s FROM counts GROUP BY auction"
    )
    want = {}
    for (a, w), (num,) in snap.items():
        want[a] = want.get(a, 0) + num
    got = dict(zip(per_auction["auction"].tolist(), per_auction["s"].tolist()))
    assert got == want
