"""Batch local-mode queries over MV snapshots (reference: batch
executors + local execution mode)."""

import numpy as np

from risingwave_tpu.batch import BatchQueryEngine
from risingwave_tpu.connectors.nexmark import BID_SCHEMA, NexmarkConfig, NexmarkGenerator
from risingwave_tpu.sql import Catalog, StreamPlanner


def _mv_with_data():
    planner = StreamPlanner(Catalog({"bid": BID_SCHEMA}), capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW counts AS "
        "SELECT auction, window_start, count(*) AS num "
        "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        "GROUP BY auction, window_start"
    )
    gen = NexmarkGenerator(NexmarkConfig())
    for _ in range(3):
        mv.pipeline.push(gen.next_chunks(1500, 2048)["bid"])
        mv.pipeline.barrier()
    return mv


def test_batch_scan_filter_order_limit():
    mv = _mv_with_data()
    eng = BatchQueryEngine({"counts": mv.mview})
    out = eng.query(
        "SELECT auction, num FROM counts WHERE num >= 3 "
        "ORDER BY num DESC LIMIT 5"
    )
    snap = mv.mview.snapshot()
    want = sorted((v[0] for v in snap.values() if v[0] >= 3), reverse=True)[:5]
    assert out["num"].tolist() == want
    assert len(out["auction"]) == len(want)


def test_batch_scalar_and_group_agg():
    mv = _mv_with_data()
    eng = BatchQueryEngine({"counts": mv.mview})
    snap = mv.mview.snapshot()

    total = eng.query("SELECT sum(num) AS s, count(*) AS c FROM counts")
    assert total["s"][0] == sum(v[0] for v in snap.values())
    assert total["c"][0] == len(snap)

    per_auction = eng.query(
        "SELECT auction, sum(num) AS s FROM counts GROUP BY auction"
    )
    want = {}
    for (a, w), (num,) in snap.items():
        want[a] = want.get(a, 0) + num
    got = dict(zip(per_auction["auction"].tolist(), per_auction["s"].tolist()))
    assert got == want


def test_batch_join_and_agg_over_join():
    import numpy as np

    from risingwave_tpu.batch.engine import BatchQueryEngine
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.array.chunk import StreamChunk

    a = MaterializeExecutor(pk=("ak",), columns=("av",))
    b = MaterializeExecutor(pk=("bk", "bv"), columns=())
    a.apply(StreamChunk.from_numpy(
        {"ak": np.asarray([1, 2, 3], np.int64),
         "av": np.asarray([10, 20, 30], np.int64)}, 8))
    b.apply(StreamChunk.from_numpy(
        {"bk": np.asarray([2, 3, 3, 5], np.int64),
         "bv": np.asarray([7, 8, 9, 99], np.int64)}, 8))
    eng = BatchQueryEngine({"a": a, "b": b})

    out = eng.query(
        "SELECT ak, av, bv FROM a JOIN b ON ak = bk ORDER BY bv"
    )
    assert out["ak"].tolist() == [2, 3, 3]
    assert out["bv"].tolist() == [7, 8, 9]

    out = eng.query(
        "SELECT ak, count(*) AS n FROM a LEFT JOIN b ON ak = bk "
        "GROUP BY ak ORDER BY ak"
    )
    assert out["ak"].tolist() == [1, 2, 3]
    assert out["n"].tolist() == [1, 1, 2]

    out = eng.query("SELECT ak FROM a LEFT ANTI JOIN b ON ak = bk")
    assert out["ak"].tolist() == [1]

    out = eng.query(
        "SELECT bk, bv FROM a RIGHT SEMI JOIN b ON ak = bk ORDER BY bv"
    )
    assert out["bv"].tolist() == [7, 8, 9]
