"""jit-static Expr identity (code-review r5 catch): Expr.__eq__ is
operator sugar (builds a truthy BinOp), so bare Exprs as jit statics
collided different predicates in the compilation cache — two MVs with
different WHERE clauses returned identical rows. Statics now ride
StaticTree (structural eq/hash)."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_same_shape_filters_do_not_share_kernels():
    s = SqlSession(Catalog({}), capacity=1 << 8)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("CREATE MATERIALIZED VIEW a AS SELECT k FROM t WHERE v > 0")
    s.execute("CREATE MATERIALIZED VIEW b AS SELECT k FROM t WHERE v > 150")
    s.execute("INSERT INTO t VALUES (1, 100), (2, 200)")
    oa, _ = s.execute("SELECT k FROM a ORDER BY k")
    ob, _ = s.execute("SELECT k FROM b ORDER BY k")
    assert list(oa["k"]) == [1, 2]
    assert list(ob["k"]) == [2]


def test_same_name_projects_do_not_share_kernels():
    s = SqlSession(Catalog({}), capacity=1 << 8)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("CREATE MATERIALIZED VIEW p1 AS SELECT k, v + 1 AS x FROM t")
    s.execute("CREATE MATERIALIZED VIEW p2 AS SELECT k, v * 2 AS x FROM t")
    s.execute("INSERT INTO t VALUES (1, 100), (2, 200)")
    p1, _ = s.execute("SELECT k, x FROM p1 ORDER BY k")
    p2, _ = s.execute("SELECT k, x FROM p2 ORDER BY k")
    assert list(p1["x"]) == [101, 201]
    assert list(p2["x"]) == [200, 400]


def test_structural_key_distinguishes_and_unifies():
    from risingwave_tpu.expr import expr as E
    from risingwave_tpu.expr.expr import StaticTree, structural_key

    a = E.col("v") > E.lit(0)
    b = E.col("v") > E.lit(150)
    c = E.col("v") > E.lit(0)  # structurally identical to a
    assert structural_key(a) != structural_key(b)
    assert structural_key(a) == structural_key(c)
    assert StaticTree(a) == StaticTree(c) and hash(StaticTree(a)) == hash(
        StaticTree(c)
    )
    assert StaticTree(a) != StaticTree(b)
