"""Fragment-graph runtime: dispatchers, permit channels, n-way barrier
alignment, parallel stateful fragments.

Reference test model: executor-chain and exchange tests
(src/stream/src/executor/integration_tests.rs, exchange/permit.rs
tests, dispatch.rs tests) — here validated against the single-pipeline
result as oracle.
"""

import threading
import time

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.runtime.graph import (
    FragmentSpec,
    GraphRuntime,
    PermitChannel,
)


def _bid_chunks(n_chunks=6, events=2_000, cap=1 << 11):
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    out = []
    while len(out) < n_chunks:
        chunks = gen.next_chunks(events, cap)
        if chunks["bid"] is not None:
            out.append(chunks["bid"])
    return out


def test_parallel_hash_agg_matches_single_pipeline():
    """source -> hash-dispatch(auction) -> 2x [q5 agg chain] == 1x chain."""
    chunks = _bid_chunks()

    oracle = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    for c in chunks:
        oracle.pipeline.push(c)
    oracle.pipeline.barrier()
    want = oracle.mview.snapshot()
    assert want

    built = {}

    def build_agg(inst):
        q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
        built[inst] = q5
        return list(q5.pipeline.executors)

    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: [], dispatch=("hash", ["auction"])),
            FragmentSpec(
                "agg", build_agg, inputs=[("src", 0)], parallelism=2
            ),
        ]
    ).start()
    for c in chunks:
        g.inject_chunk("src", c)
    g.inject_barrier()
    g.stop()

    got = {}
    overlap = 0
    for q5 in built.values():
        snap = q5.mview.snapshot()
        overlap += sum(1 for k in snap if k in got)
        got.update(snap)
    assert overlap == 0  # disjoint vnode ownership
    assert got == want
    # the work actually split: neither instance owns everything
    assert all(len(q5.mview.snapshot()) < len(want) for q5 in built.values())


def test_two_source_join_graph_matches_two_input_pipeline():
    """p-source + a-source -> join fragment == TwoInputPipeline on the
    same arrival order (barrier alignment across two sources)."""
    gen = NexmarkConfig(first_event_rate=25_000)
    chunks = NexmarkGenerator(gen).next_chunks(20_000, 1 << 15)
    p, a = chunks["person"], chunks["auction"]
    assert p is not None and a is not None

    oracle = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 12)
    oracle.pipeline.push_left(p)
    oracle.pipeline.push_right(a)
    oracle.pipeline.barrier()
    want = oracle.mview.snapshot()
    assert want

    q8 = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 12)
    tip = q8.pipeline

    g = GraphRuntime(
        [
            FragmentSpec("p", lambda i: []),
            FragmentSpec("a", lambda i: []),
            FragmentSpec(
                "join",
                lambda i: {
                    "left": tip.left,
                    "right": tip.right,
                    "join": tip.join,
                    "tail": tip.tail,
                },
                inputs=[("p", 0), ("a", 1)],
            ),
        ]
    ).start()
    g.inject_chunk("p", p)
    g.inject_chunk("a", a)
    g.inject_barrier()
    g.stop()
    assert q8.mview.snapshot() == want


def test_broadcast_and_round_robin_dispatch():
    chunks = _bid_chunks(n_chunks=4)

    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: [], dispatch="broadcast"),
            FragmentSpec("down", lambda i: [], inputs=[("src", 0)],
                         parallelism=2),
        ]
    ).start()
    for c in chunks:
        g.inject_chunk("src", c)
    g.inject_barrier()
    g.stop()
    got = g.drain("down")
    assert len(got) == 2 * len(chunks)  # every instance sees every chunk

    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: [], dispatch="round_robin"),
            FragmentSpec("down", lambda i: [], inputs=[("src", 0)],
                         parallelism=2),
        ]
    ).start()
    for c in chunks:
        g.inject_chunk("src", c)
    g.inject_barrier()
    g.stop()
    assert len(g.drain("down")) == len(chunks)  # chunks split, not copied


def test_union_merge_preserves_rows_and_aligns_barriers():
    """Two sources union-merged into one chain: row totals add up and
    the downstream barrier fires exactly once per inject_barrier."""
    chunks = _bid_chunks(n_chunks=4)

    class CountBarriers(Executor):
        def __init__(self):
            self.barriers = 0
            self.rows = 0

        def apply(self, chunk):
            self.rows += int(np.asarray(chunk.valid).sum())
            return [chunk]

        def on_barrier(self, b):
            self.barriers += 1
            return []

    rec = CountBarriers()
    g = GraphRuntime(
        [
            FragmentSpec("s1", lambda i: []),
            FragmentSpec("s2", lambda i: []),
            FragmentSpec(
                "u", lambda i: [rec], inputs=[("s1", 0), ("s2", 0)]
            ),
        ]
    ).start()
    g.inject_chunk("s1", chunks[0])
    g.inject_chunk("s2", chunks[1])
    g.inject_barrier()
    g.inject_chunk("s2", chunks[2])
    g.inject_chunk("s1", chunks[3])
    g.inject_barrier()
    g.stop()
    want_rows = sum(int(np.asarray(c.valid).sum()) for c in chunks)
    assert rec.rows == want_rows
    assert rec.barriers == 2


def test_watermark_min_alignment_across_sources():
    class RecordWM(Executor):
        def __init__(self):
            self.seen = []

        def on_watermark(self, wm):
            self.seen.append((wm.column, wm.value))
            return wm, []

    rec = RecordWM()
    g = GraphRuntime(
        [
            FragmentSpec("s1", lambda i: []),
            FragmentSpec("s2", lambda i: []),
            FragmentSpec(
                "m", lambda i: [rec], inputs=[("s1", 0), ("s2", 0)]
            ),
        ]
    ).start()
    g.inject_watermark("ts", 100, source="s1")
    g.inject_barrier()
    assert rec.seen == []  # s2 has no frontier yet: nothing aligned
    g.inject_watermark("ts", 50, source="s2")
    g.inject_barrier()
    assert rec.seen == [("ts", 50)]  # min(100, 50)
    g.inject_watermark("ts", 120, source="s2")
    g.inject_barrier()
    assert rec.seen == [("ts", 50), ("ts", 100)]  # min(100, 120)
    g.stop()


def test_watermark_aligns_after_source_stop():
    """A stopped upstream must drop out of min-alignment: the live
    input's watermarks keep flowing instead of stalling EOWC forever
    (advisor r3, graph.py watermark alignment)."""

    class RecordWM(Executor):
        def __init__(self):
            self.seen = []

        def on_watermark(self, wm):
            self.seen.append((wm.column, wm.value))
            return wm, []

    rec = RecordWM()
    g = GraphRuntime(
        [
            FragmentSpec("s1", lambda i: []),
            FragmentSpec("s2", lambda i: []),
            FragmentSpec(
                "m", lambda i: [rec], inputs=[("s1", 0), ("s2", 0)]
            ),
        ]
    ).start()
    g.inject_watermark("ts", 100, source="s1")
    g.inject_barrier()
    assert rec.seen == []  # s2 has no frontier: aligned on nothing
    for ch in g._source_channels["s2"]:
        ch.send_control("stop")
    deadline = time.time() + 5.0
    while time.time() < deadline and rec.seen != [("ts", 100)]:
        time.sleep(0.01)
    assert rec.seen == [("ts", 100)]  # realigned across live inputs
    g.inject_watermark("ts", 200, source="s1")
    deadline = time.time() + 5.0
    while time.time() < deadline and len(rec.seen) < 2:
        time.sleep(0.01)
    assert rec.seen == [("ts", 100), ("ts", 200)]
    g.stop()


def test_permit_channel_backpressure():
    ch = PermitChannel(record_permits=8)
    c = StreamChunk.from_numpy({"x": np.arange(8)}, 8)
    ch.send_chunk(c)  # consumes all 8 permits

    done = threading.Event()

    def sender():
        ch.send_chunk(c)  # must block until a recv returns permits
        done.set()

    t = threading.Thread(target=sender, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not done.is_set()  # blocked on permits
    kind, got = ch.recv()
    assert kind == "chunk"
    assert done.wait(timeout=5.0)  # permits returned -> send completed
    # control bypasses permits even while data budget is exhausted
    ch.send_control("barrier", None)
    assert len(ch) == 2


def test_actor_failure_surfaces_on_inject_barrier():
    class Boom(Executor):
        def on_barrier(self, b):
            raise ValueError("kaboom")

    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec("f", lambda i: [Boom()], inputs=[("src", 0)]),
        ]
    ).start()
    with pytest.raises(RuntimeError):
        g.inject_barrier(timeout=30)
    g.stop()
