"""Chaos tier (VERDICT r2 #10; madsim recovery suites analogue):
random kill-and-recover at arbitrary commit writes — including between
SST uploads and the manifest commit — must converge to exactly the
undisturbed run's MV; with the FlakyStore storm layered on, transient
faults are absorbed by the resilience layer and convergence still
holds byte-for-byte.

Replay a failing schedule: every failure message carries the seed;
rerun with ``RW_CHAOS_SEED=<seed>`` to reproduce it deterministically.
"""

import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.connectors.source import NexmarkSourceExecutor
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.sim import ChaosRunner, chaos_seed
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager

EVENTS, CAP = 900, 1024


def _assert_converged(runner, got, want):
    """Convergence check that prints the fault-schedule seed on
    failure (satellite: replay with RW_CHAOS_SEED=<seed>)."""
    assert got == want, (
        f"chaos run diverged from the undisturbed twin "
        f"(seed={runner.seed}; rerun with RW_CHAOS_SEED={runner.seed} "
        f"to replay this schedule: crashes={runner.crashes} "
        f"giveups={runner.giveups} faults={runner.faults_injected})"
    )


class _Q5:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q5.pipeline.executors + [self.source]

    def feed(self):
        for bid in self.source.poll(EVENTS, CAP)["bid"]:
            self.q5.pipeline.push(bid.select(["auction", "date_time"]))
        self.q5.pipeline.barrier()


class _Q8:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q8 = build_q8(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q8.pipeline.executors + [self.source]

    def feed(self):
        polled = self.source.poll(EVENTS, CAP)
        for p in polled["person"]:
            self.q8.pipeline.push_left(p)
        for a in polled["auction"]:
            self.q8.pipeline.push_right(a)
        self.q8.pipeline.barrier()


def _undisturbed(cls, n_epochs):
    obj = cls()
    mgr = CheckpointManager(MemObjectStore())
    for i in range(n_epochs):
        obj.feed()
        mgr.commit_epoch((i + 1) << 16, obj.executors)
    return obj


@pytest.mark.parametrize("cls,snap,seed", [
    (_Q5, lambda o: o.q5.mview.snapshot(), 1),
    (_Q5, lambda o: o.q5.mview.snapshot(), 2),
    (_Q8, lambda o: o.q8.mview.snapshot(), 3),
    (_Q8, lambda o: o.q8.mview.snapshot(), 4),
])
def test_chaos_converges_to_undisturbed(cls, snap, seed):
    seed = chaos_seed(seed)
    n_epochs = 6
    want = snap(_undisturbed(cls, n_epochs))
    runner = ChaosRunner(
        make=cls, feed=lambda o: o.feed(), seed=seed, crash_prob=0.45
    )
    obj = runner.run(n_epochs)
    assert runner.crashes >= 1, "chaos run never crashed — raise crash_prob"
    _assert_converged(runner, snap(obj), want)
    assert len(want) > 50


def test_flaky_storm_converges_to_undisturbed():
    """The acceptance bar: a >=20% transient-error storm (seeded) over
    the full ingest->barrier->crash->recover loop converges to the
    byte-identical undisturbed result; every retry is deadline-bounded
    (the runner's policy), and the storm actually fired."""
    from risingwave_tpu.metrics import REGISTRY

    seed = chaos_seed(5)
    n_epochs = 5
    want = _undisturbed(_Q5, n_epochs).q5.mview.snapshot()
    retries0 = REGISTRY.counter("retries_total").get(op="store.put")
    runner = ChaosRunner(
        make=_Q5,
        feed=lambda o: o.feed(),
        seed=seed,
        crash_prob=0.3,
        flaky_rate=0.25,
    )
    obj = runner.run(n_epochs)
    assert runner.faults_injected > 0, "the flaky storm never fired"
    _assert_converged(runner, obj.q5.mview.snapshot(), want)
    # the storm was absorbed by BOUNDED retries (the runner's policy
    # carries a deadline; a giveup recovers like a crash, never spins)
    # and the retry pressure is visible in the metrics
    assert (
        REGISTRY.counter("retries_total").get(op="store.put") > retries0
    )
    assert len(want) > 50


def test_crash_lands_mid_retry_loop():
    """FlakyStore composes with CrashingStore: a transient fault on
    attempt 1 and the armed crash on attempt 2 means the process dies
    INSIDE the retry loop — and CrashPoint must pass straight through
    (a retry loop may never 'handle' a death)."""
    from risingwave_tpu.resilience import RetryingObjectStore, RetryPolicy
    from risingwave_tpu.sim import CrashingStore, CrashPoint, FlakyStore

    crashing = CrashingStore(MemObjectStore())
    crashing.arm(1)  # first write that REACHES the store crashes
    # seed 1's first two draws are 0.134, 0.847: at rate .5 attempt 1
    # faults before reaching the store, attempt 2 passes through
    flaky = FlakyStore(crashing, rate=0.5, seed=1)
    rs = RetryingObjectStore(
        flaky,
        RetryPolicy(max_attempts=5, base_backoff_s=1e-4, deadline_s=2.0),
    )
    with pytest.raises(CrashPoint):
        rs.put("a", b"x")
    assert flaky.faults == 1  # the retry actually happened first


@pytest.mark.slow
def test_flaky_fault_storm_heavy():
    """Fault storm at higher rate + injected latency over the join
    workload (q8), composed with crashes — long-haul convergence."""
    seed = chaos_seed(13)
    n_epochs = 6
    want = _undisturbed(_Q8, n_epochs).q8.mview.snapshot()
    runner = ChaosRunner(
        make=_Q8,
        feed=lambda o: o.feed(),
        seed=seed,
        crash_prob=0.4,
        flaky_rate=0.35,
    )
    obj = runner.run(n_epochs)
    assert runner.faults_injected > 0
    assert runner.crashes >= 1
    _assert_converged(runner, obj.q8.mview.snapshot(), want)


# ---------------------------------------------------------------------------
# actor-kill chaos (partial recovery's madsim analogue): murder random
# ACTORS mid-epoch — not the store — and converge bit-identically
# ---------------------------------------------------------------------------


class _ActorKillWorkload:
    """Two graph MVs over one deterministic chunk stream; CrashingExecutors
    planted in mv_b's parallel fragment are the runner's kill targets.
    A kill's blast radius is mv_b only — mv_a must stay hot."""

    def __init__(self, seed=101, n_epochs=8):
        import jax.numpy as jnp
        import numpy as np

        from risingwave_tpu.array.chunk import StreamChunk
        from risingwave_tpu.executors.hash_agg import HashAggExecutor
        from risingwave_tpu.executors.materialize import MaterializeExecutor
        from risingwave_tpu.ops.agg import AggCall
        from risingwave_tpu.runtime.fragmenter import (
            GraphPipeline,
            PartitionedStateView,
        )
        from risingwave_tpu.runtime.graph import FragmentSpec
        from risingwave_tpu.runtime.runtime import StreamingRuntime
        from risingwave_tpu.sim import CrashingExecutor

        def mk_agg(tid):
            return HashAggExecutor(
                group_keys=("k",),
                calls=(AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
                schema_dtypes={"k": jnp.int64, "v": jnp.int64},
                capacity=1 << 8,
                table_id=tid,
            )

        self.runtime = StreamingRuntime(
            MemObjectStore(), async_checkpoint=False, auto_recover=True
        )
        agg_a, self.mva = mk_agg("ka.agg"), MaterializeExecutor(
            pk=("k",), columns=("s", "c"), table_id="ka.mview"
        )
        chain_a = [agg_a, self.mva]
        gpa = GraphPipeline(
            [
                FragmentSpec("src", lambda i: []),
                FragmentSpec(
                    "work", lambda i, c=tuple(chain_a): list(c),
                    inputs=[("src", 0)],
                ),
            ],
            {"single": "src"}, "work", chain_a,
            ckpt_fragments=["work"] * len(chain_a),
        )
        self.crash_points = [CrashingExecutor("p0"), CrashingExecutor("p1")]
        aggs_b = [mk_agg("kb.agg") for _ in range(2)]
        self.mvb = MaterializeExecutor(
            pk=("k",), columns=("s", "c"), table_id="kb.mview"
        )
        chains = [
            [self.crash_points[0], aggs_b[0]],
            [self.crash_points[1], aggs_b[1]],
        ]
        gpb = GraphPipeline(
            [
                FragmentSpec("src", lambda i: [], dispatch=("hash", ["k"])),
                FragmentSpec(
                    "par", lambda i: list(chains[i]), inputs=[("src", 0)],
                    parallelism=2,
                ),
                FragmentSpec("mat", lambda i: [self.mvb], inputs=[("par", 0)]),
            ],
            {"single": "src"}, "mat",
            [PartitionedStateView(aggs_b, {"kb.agg": (0,)}), self.mvb],
            ckpt_fragments=["par", "mat"],
        )
        self.runtime.register("mv_a", gpa)
        self.runtime.register("mv_b", gpb)
        rng = np.random.default_rng(seed)
        self.chunks = []
        for _ in range(n_epochs):
            n = int(rng.integers(4, 12))
            self.chunks.append(
                StreamChunk.from_numpy(
                    {
                        "k": rng.integers(0, 8, n).astype("int64"),
                        "v": rng.integers(0, 50, n).astype("int64"),
                    },
                    16,
                )
            )

    def feed(self, i):
        c = self.chunks[i]
        self.runtime.push("mv_a", c)
        self.runtime.push("mv_b", c)
        self.runtime.barrier()

    def snapshots(self):
        return dict(self.mva.snapshot()), dict(self.mvb.snapshot())


def test_actor_kill_chaos_converges_to_undisturbed():
    """ChaosRunner's actor-kill mode at a tier-1-friendly rate: random
    actor murders mid-epoch (apply AND barrier sites), recovered by the
    fragment-scoped supervisor — both MVs bit-identical to the
    fault-free twin, with at least one PARTIAL recovery exercised."""
    from risingwave_tpu.sim import ActorChaosRunner

    from risingwave_tpu.profiler import PROFILER

    seed = chaos_seed(21)
    n_epochs = 6
    twin = _ActorKillWorkload()
    for i in range(n_epochs):
        twin.feed(i)
    want = twin.snapshots()

    # profiler armed with an open capture across the storm: partial
    # recovery must close it (orphan-window audit, extends the PR-5
    # watchdog audit to profiler capture sessions); the blackbox
    # sentinel rides the same storm — actor kills must neither arm a
    # spurious wedge nor orphan its capture window (PR 8 audit)
    from risingwave_tpu import blackbox

    PROFILER.enable(fence=False)
    PROFILER.start_capture(tag="chaos-audit")
    saved_sentinel = blackbox.SENTINEL  # fresh instance: no config leak
    blackbox.SENTINEL = blackbox.DeviceSentinel()
    blackbox.SENTINEL.start(
        interval_s=0.05, slow_ms=1e6, deadline_s=5.0,
        heartbeat_fn=lambda: None,
    )
    try:
        runner = ActorChaosRunner(
            _ActorKillWorkload, seed=seed, kill_prob=0.45, kill_site="mixed"
        )
        obj = runner.run(n_epochs)
        # no orphaned profiler capture windows survived the recoveries
        assert PROFILER.active_captures == []
        # actor faults are NOT device wedges: nothing armed, no window
        assert blackbox.SENTINEL.wedged_error() is None
        assert blackbox.SENTINEL.abort_capture() == 0
    finally:
        PROFILER.disable()
        PROFILER.reset()
        blackbox.SENTINEL.stop()
        blackbox.SENTINEL = saved_sentinel
    kills = sum(cp.kills for cp in obj.crash_points)
    assert kills >= 1, (
        f"no actor was ever killed — raise kill_prob (seed={seed})"
    )
    got = obj.snapshots()
    assert got == want, (
        f"actor-kill chaos diverged from the fault-free twin "
        f"(seed={seed}; rerun with RW_CHAOS_SEED={seed}: "
        f"kills={kills} armed={runner.kills_armed} "
        f"recoveries={obj.runtime.auto_recoveries} "
        f"partial={obj.runtime.partial_recoveries})"
    )
    assert obj.runtime.partial_recoveries >= 1  # the scoped path ran


@pytest.mark.slow
def test_actor_kill_storm_q8_heavy():
    """Heavy-kill storm over the q8 join graph: crash points in both
    join-side chains, high kill rate, mixed sites — the partial-recovery
    replay must keep join state exactly-once and converge."""
    from risingwave_tpu.connectors.nexmark import NexmarkGenerator
    from risingwave_tpu.queries.nexmark_q import build_q5_lite
    from risingwave_tpu.runtime.fragmenter import GraphPipeline
    from risingwave_tpu.runtime.graph import FragmentSpec
    from risingwave_tpu.runtime.runtime import StreamingRuntime
    from risingwave_tpu.sim import ActorChaosRunner, CrashingExecutor

    seed = chaos_seed(33)
    n_epochs = 6

    class _Q8Kill:
        def __init__(self):
            self.runtime = StreamingRuntime(
                MemObjectStore(), async_checkpoint=False, auto_recover=True
            )
            self.q8 = build_q8(capacity=1 << 12, state_cleaning=False)
            tp = self.q8.pipeline
            self.crash_points = [
                CrashingExecutor("q8l"), CrashingExecutor("q8r"),
            ]
            build = {
                "left": [self.crash_points[0]] + tp.left,
                "right": [self.crash_points[1]] + tp.right,
                "join": tp.join,
                "tail": tp.tail,
            }
            specs = [
                FragmentSpec("p", lambda i: []),
                FragmentSpec("a", lambda i: []),
                FragmentSpec(
                    "join", lambda i, b=build: dict(b),
                    inputs=[("p", 0), ("a", 1)],
                ),
            ]
            gp = GraphPipeline(
                specs, {"left": "p", "right": "a"}, "join", tp.executors,
                ckpt_fragments=["join"] * len(tp.executors),
            )
            # a second, independent MV keeps the runtime multi-fragment
            # so q8's blast radius stays a strict subset (partial path)
            self.q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
            c5 = list(self.q5.pipeline.executors)
            gp5 = GraphPipeline(
                [
                    FragmentSpec("src", lambda i: []),
                    FragmentSpec(
                        "work", lambda i, c=tuple(c5): list(c),
                        inputs=[("src", 0)],
                    ),
                ],
                {"single": "src"}, "work", c5,
                ckpt_fragments=["work"] * len(c5),
            )
            self.runtime.register("q8", gp)
            self.runtime.register("q5", gp5)
            gen = NexmarkGenerator(NexmarkConfig(first_event_rate=25_000))
            self.feeds = []
            while len(self.feeds) < n_epochs:
                ch = gen.next_chunks(6_000, 1 << 13)
                if ch["person"] is None or ch["auction"] is None or ch["bid"] is None:
                    continue
                self.feeds.append(ch)

        def feed(self, i):
            ch = self.feeds[i]
            self.runtime.push("q8", ch["person"], side="left")
            self.runtime.push("q8", ch["auction"], side="right")
            self.runtime.push(
                "q5", ch["bid"].select(["auction", "date_time"])
            )
            self.runtime.barrier()

        def snapshots(self):
            return (
                dict(self.q8.mview.snapshot()),
                dict(self.q5.mview.snapshot()),
            )

    twin = _Q8Kill()
    for i in range(n_epochs):
        twin.feed(i)
    want = twin.snapshots()
    assert len(want[0]) > 20

    runner = ActorChaosRunner(
        _Q8Kill, seed=seed, kill_prob=0.6, kill_site="mixed"
    )
    obj = runner.run(n_epochs, max_attempts=300)
    kills = sum(cp.kills for cp in obj.crash_points)
    assert kills >= 1
    got = obj.snapshots()
    assert got == want, (
        f"q8 heavy-kill storm diverged (seed={seed}; rerun with "
        f"RW_CHAOS_SEED={seed}: kills={kills} "
        f"recoveries={obj.runtime.auto_recoveries} "
        f"partial={obj.runtime.partial_recoveries})"
    )


def test_dead_store_serves_nothing():
    """CrashingStore sim fidelity: once dead, EVERY op raises — a
    killed process cannot answer reads/exists/list either."""
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    disk = MemObjectStore()
    disk.put("p", b"x")
    store = CrashingStore(disk)
    assert store.read("p") == b"x"  # alive: reads pass through
    store.arm(1)
    with pytest.raises(CrashPoint):
        store.put("q", b"y")
    for op in (
        lambda: store.read("p"),
        lambda: store.read_range("p", 0, 1),
        lambda: store.exists("p"),
        lambda: store.list(""),
        lambda: store.put("r", b"z"),
        lambda: store.delete("p"),
    ):
        with pytest.raises(CrashPoint):
            op()
    assert disk.read("p") == b"x"  # the durable bytes are untouched


def test_crash_exactly_between_sst_and_manifest():
    """Pin the crash to the torn-upload window: the SST is uploaded,
    the manifest is not — recovery must land on the PREVIOUS epoch and
    replay produces the undisturbed result."""
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    want = _undisturbed(_Q5, 3).q5.mview.snapshot()

    disk = MemObjectStore()
    obj = _Q5()
    store = CrashingStore(disk)
    mgr = CheckpointManager(store)
    obj.feed()
    mgr.commit_epoch(1 << 16, obj.executors)
    obj.feed()
    # next writes: 1 source-offset SST + agg/mv SSTs + manifest; arm so
    # the MANIFEST put dies (count the tables staged: offsets, agg, mv)
    n_tables = 3
    store.arm(n_tables + 1)
    with pytest.raises(CrashPoint):
        mgr.commit_epoch(2 << 16, obj.executors)

    obj2 = _Q5()
    mgr2 = CheckpointManager(CrashingStore(disk))
    mgr2.recover(obj2.executors)
    assert mgr2.max_committed_epoch == 1 << 16  # epoch 2 rolled back
    for i in (2, 3):
        obj2.feed()
        mgr2.commit_epoch(i << 16, obj2.executors)
    assert obj2.q5.mview.snapshot() == want
