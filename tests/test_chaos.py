"""Chaos tier (VERDICT r2 #10; madsim recovery suites analogue):
random kill-and-recover at arbitrary commit writes — including between
SST uploads and the manifest commit — must converge to exactly the
undisturbed run's MV; with the FlakyStore storm layered on, transient
faults are absorbed by the resilience layer and convergence still
holds byte-for-byte.

Replay a failing schedule: every failure message carries the seed;
rerun with ``RW_CHAOS_SEED=<seed>`` to reproduce it deterministically.
"""

import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.connectors.source import NexmarkSourceExecutor
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.sim import ChaosRunner, chaos_seed
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager

EVENTS, CAP = 900, 1024


def _assert_converged(runner, got, want):
    """Convergence check that prints the fault-schedule seed on
    failure (satellite: replay with RW_CHAOS_SEED=<seed>)."""
    assert got == want, (
        f"chaos run diverged from the undisturbed twin "
        f"(seed={runner.seed}; rerun with RW_CHAOS_SEED={runner.seed} "
        f"to replay this schedule: crashes={runner.crashes} "
        f"giveups={runner.giveups} faults={runner.faults_injected})"
    )


class _Q5:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q5.pipeline.executors + [self.source]

    def feed(self):
        for bid in self.source.poll(EVENTS, CAP)["bid"]:
            self.q5.pipeline.push(bid.select(["auction", "date_time"]))
        self.q5.pipeline.barrier()


class _Q8:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q8 = build_q8(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q8.pipeline.executors + [self.source]

    def feed(self):
        polled = self.source.poll(EVENTS, CAP)
        for p in polled["person"]:
            self.q8.pipeline.push_left(p)
        for a in polled["auction"]:
            self.q8.pipeline.push_right(a)
        self.q8.pipeline.barrier()


def _undisturbed(cls, n_epochs):
    obj = cls()
    mgr = CheckpointManager(MemObjectStore())
    for i in range(n_epochs):
        obj.feed()
        mgr.commit_epoch((i + 1) << 16, obj.executors)
    return obj


@pytest.mark.parametrize("cls,snap,seed", [
    (_Q5, lambda o: o.q5.mview.snapshot(), 1),
    (_Q5, lambda o: o.q5.mview.snapshot(), 2),
    (_Q8, lambda o: o.q8.mview.snapshot(), 3),
    (_Q8, lambda o: o.q8.mview.snapshot(), 4),
])
def test_chaos_converges_to_undisturbed(cls, snap, seed):
    seed = chaos_seed(seed)
    n_epochs = 6
    want = snap(_undisturbed(cls, n_epochs))
    runner = ChaosRunner(
        make=cls, feed=lambda o: o.feed(), seed=seed, crash_prob=0.45
    )
    obj = runner.run(n_epochs)
    assert runner.crashes >= 1, "chaos run never crashed — raise crash_prob"
    _assert_converged(runner, snap(obj), want)
    assert len(want) > 50


def test_flaky_storm_converges_to_undisturbed():
    """The acceptance bar: a >=20% transient-error storm (seeded) over
    the full ingest->barrier->crash->recover loop converges to the
    byte-identical undisturbed result; every retry is deadline-bounded
    (the runner's policy), and the storm actually fired."""
    from risingwave_tpu.metrics import REGISTRY

    seed = chaos_seed(5)
    n_epochs = 5
    want = _undisturbed(_Q5, n_epochs).q5.mview.snapshot()
    retries0 = REGISTRY.counter("retries_total").get(op="store.put")
    runner = ChaosRunner(
        make=_Q5,
        feed=lambda o: o.feed(),
        seed=seed,
        crash_prob=0.3,
        flaky_rate=0.25,
    )
    obj = runner.run(n_epochs)
    assert runner.faults_injected > 0, "the flaky storm never fired"
    _assert_converged(runner, obj.q5.mview.snapshot(), want)
    # the storm was absorbed by BOUNDED retries (the runner's policy
    # carries a deadline; a giveup recovers like a crash, never spins)
    # and the retry pressure is visible in the metrics
    assert (
        REGISTRY.counter("retries_total").get(op="store.put") > retries0
    )
    assert len(want) > 50


def test_crash_lands_mid_retry_loop():
    """FlakyStore composes with CrashingStore: a transient fault on
    attempt 1 and the armed crash on attempt 2 means the process dies
    INSIDE the retry loop — and CrashPoint must pass straight through
    (a retry loop may never 'handle' a death)."""
    from risingwave_tpu.resilience import RetryingObjectStore, RetryPolicy
    from risingwave_tpu.sim import CrashingStore, CrashPoint, FlakyStore

    crashing = CrashingStore(MemObjectStore())
    crashing.arm(1)  # first write that REACHES the store crashes
    # seed 1's first two draws are 0.134, 0.847: at rate .5 attempt 1
    # faults before reaching the store, attempt 2 passes through
    flaky = FlakyStore(crashing, rate=0.5, seed=1)
    rs = RetryingObjectStore(
        flaky,
        RetryPolicy(max_attempts=5, base_backoff_s=1e-4, deadline_s=2.0),
    )
    with pytest.raises(CrashPoint):
        rs.put("a", b"x")
    assert flaky.faults == 1  # the retry actually happened first


@pytest.mark.slow
def test_flaky_fault_storm_heavy():
    """Fault storm at higher rate + injected latency over the join
    workload (q8), composed with crashes — long-haul convergence."""
    seed = chaos_seed(13)
    n_epochs = 6
    want = _undisturbed(_Q8, n_epochs).q8.mview.snapshot()
    runner = ChaosRunner(
        make=_Q8,
        feed=lambda o: o.feed(),
        seed=seed,
        crash_prob=0.4,
        flaky_rate=0.35,
    )
    obj = runner.run(n_epochs)
    assert runner.faults_injected > 0
    assert runner.crashes >= 1
    _assert_converged(runner, obj.q8.mview.snapshot(), want)


def test_dead_store_serves_nothing():
    """CrashingStore sim fidelity: once dead, EVERY op raises — a
    killed process cannot answer reads/exists/list either."""
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    disk = MemObjectStore()
    disk.put("p", b"x")
    store = CrashingStore(disk)
    assert store.read("p") == b"x"  # alive: reads pass through
    store.arm(1)
    with pytest.raises(CrashPoint):
        store.put("q", b"y")
    for op in (
        lambda: store.read("p"),
        lambda: store.read_range("p", 0, 1),
        lambda: store.exists("p"),
        lambda: store.list(""),
        lambda: store.put("r", b"z"),
        lambda: store.delete("p"),
    ):
        with pytest.raises(CrashPoint):
            op()
    assert disk.read("p") == b"x"  # the durable bytes are untouched


def test_crash_exactly_between_sst_and_manifest():
    """Pin the crash to the torn-upload window: the SST is uploaded,
    the manifest is not — recovery must land on the PREVIOUS epoch and
    replay produces the undisturbed result."""
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    want = _undisturbed(_Q5, 3).q5.mview.snapshot()

    disk = MemObjectStore()
    obj = _Q5()
    store = CrashingStore(disk)
    mgr = CheckpointManager(store)
    obj.feed()
    mgr.commit_epoch(1 << 16, obj.executors)
    obj.feed()
    # next writes: 1 source-offset SST + agg/mv SSTs + manifest; arm so
    # the MANIFEST put dies (count the tables staged: offsets, agg, mv)
    n_tables = 3
    store.arm(n_tables + 1)
    with pytest.raises(CrashPoint):
        mgr.commit_epoch(2 << 16, obj.executors)

    obj2 = _Q5()
    mgr2 = CheckpointManager(CrashingStore(disk))
    mgr2.recover(obj2.executors)
    assert mgr2.max_committed_epoch == 1 << 16  # epoch 2 rolled back
    for i in (2, 3):
        obj2.feed()
        mgr2.commit_epoch(i << 16, obj2.executors)
    assert obj2.q5.mview.snapshot() == want
