"""Chaos tier (VERDICT r2 #10; madsim recovery suites analogue):
random kill-and-recover at arbitrary commit writes — including between
SST uploads and the manifest commit — must converge to exactly the
undisturbed run's MV."""

import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.connectors.source import NexmarkSourceExecutor
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.sim import ChaosRunner
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager

EVENTS, CAP = 900, 1024


class _Q5:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q5.pipeline.executors + [self.source]

    def feed(self):
        for bid in self.source.poll(EVENTS, CAP)["bid"]:
            self.q5.pipeline.push(bid.select(["auction", "date_time"]))
        self.q5.pipeline.barrier()


class _Q8:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q8 = build_q8(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q8.pipeline.executors + [self.source]

    def feed(self):
        polled = self.source.poll(EVENTS, CAP)
        for p in polled["person"]:
            self.q8.pipeline.push_left(p)
        for a in polled["auction"]:
            self.q8.pipeline.push_right(a)
        self.q8.pipeline.barrier()


def _undisturbed(cls, n_epochs):
    obj = cls()
    mgr = CheckpointManager(MemObjectStore())
    for i in range(n_epochs):
        obj.feed()
        mgr.commit_epoch((i + 1) << 16, obj.executors)
    return obj


@pytest.mark.parametrize("cls,snap,seed", [
    (_Q5, lambda o: o.q5.mview.snapshot(), 1),
    (_Q5, lambda o: o.q5.mview.snapshot(), 2),
    (_Q8, lambda o: o.q8.mview.snapshot(), 3),
    (_Q8, lambda o: o.q8.mview.snapshot(), 4),
])
def test_chaos_converges_to_undisturbed(cls, snap, seed):
    n_epochs = 6
    want = snap(_undisturbed(cls, n_epochs))
    runner = ChaosRunner(
        make=cls, feed=lambda o: o.feed(), seed=seed, crash_prob=0.45
    )
    obj = runner.run(n_epochs)
    assert runner.crashes >= 1, "chaos run never crashed — raise crash_prob"
    assert snap(obj) == want
    assert len(want) > 50


def test_crash_exactly_between_sst_and_manifest():
    """Pin the crash to the torn-upload window: the SST is uploaded,
    the manifest is not — recovery must land on the PREVIOUS epoch and
    replay produces the undisturbed result."""
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    want = _undisturbed(_Q5, 3).q5.mview.snapshot()

    disk = MemObjectStore()
    obj = _Q5()
    store = CrashingStore(disk)
    mgr = CheckpointManager(store)
    obj.feed()
    mgr.commit_epoch(1 << 16, obj.executors)
    obj.feed()
    # next writes: 1 source-offset SST + agg/mv SSTs + manifest; arm so
    # the MANIFEST put dies (count the tables staged: offsets, agg, mv)
    n_tables = 3
    store.arm(n_tables + 1)
    with pytest.raises(CrashPoint):
        mgr.commit_epoch(2 << 16, obj.executors)

    obj2 = _Q5()
    mgr2 = CheckpointManager(CrashingStore(disk))
    mgr2.recover(obj2.executors)
    assert mgr2.max_committed_epoch == 1 << 16  # epoch 2 rolled back
    for i in (2, 3):
        obj2.feed()
        mgr2.commit_epoch(i << 16, obj2.executors)
    assert obj2.q5.mview.snapshot() == want
