"""HashJoin oracle tests — emitted deltas replayed against a pandas
merge of the final input states (reference test discipline:
executor tests vs expected chunks, hash_join.rs:1351+)."""

import collections

import jax.numpy as jnp
import numpy as np
import pandas as pd

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import Barrier, HashJoinExecutor, Watermark
from risingwave_tpu.executors.base import Epoch
from risingwave_tpu.types import Op

CAP = 128


def _chunk(cols, ops=None, nulls=None, cap=CAP):
    return StreamChunk.from_numpy(
        {k: np.asarray(v) for k, v in cols.items()},
        cap,
        ops=None if ops is None else np.asarray(ops, np.int32),
        nulls=nulls,
    )


def _collect(outs, counter, names):
    """Fold emitted deltas into a multiset of output rows."""
    for out in outs:
        d = out.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            row = tuple(
                None
                if d.get(n + "__null") is not None and d[n + "__null"][i]
                else d[n][i]
                for n in names
            )
            sign = 1 if d["__op__"][i] in (Op.INSERT, Op.UPDATE_INSERT) else -1
            counter[row] += sign
            if counter[row] == 0:
                del counter[row]
    return counter


def _oracle(left_rows, right_rows, lkey, rkey, names):
    """pandas inner merge of the surviving input multisets."""
    ldf = pd.DataFrame(left_rows) if left_rows else None
    rdf = pd.DataFrame(right_rows) if right_rows else None
    out = collections.Counter()
    if ldf is None or rdf is None or ldf.empty or rdf.empty:
        return out
    merged = ldf.merge(rdf, left_on=list(lkey), right_on=list(rkey))
    for _, r in merged.iterrows():
        out[tuple(r[n] for n in names)] += 1
    return out


def test_join_basic_insert_probe():
    ex = HashJoinExecutor(
        ("seller",),
        ("pid",),
        {"seller": jnp.int64, "aid": jnp.int64},
        {"pid": jnp.int64, "pname": jnp.int64},
        capacity=1 << 10,
        fanout=8,
        out_cap=1 << 10,
    )
    got = collections.Counter()
    names = ("seller", "aid", "pid", "pname")

    # right rows first: persons 1..4
    _collect(
        ex.apply_right(
            _chunk({"pid": [1, 2, 3, 4], "pname": [10, 20, 30, 40]})
        ),
        got,
        names,
    )
    # left: auctions by sellers 2,2,3,9 (9 matches nothing)
    _collect(
        ex.apply_left(
            _chunk({"seller": [2, 2, 3, 9], "aid": [100, 101, 102, 103]})
        ),
        got,
        names,
    )
    ex.on_barrier(Barrier(Epoch(0, 1)))

    assert got == collections.Counter(
        {
            (2, 100, 2, 20): 1,
            (2, 101, 2, 20): 1,
            (3, 102, 3, 30): 1,
        }
    )


def test_join_retraction_both_sides():
    ex = HashJoinExecutor(
        ("lk",),
        ("rk",),
        {"lk": jnp.int64, "lv": jnp.int64},
        {"rk": jnp.int64, "rv": jnp.int64},
        capacity=1 << 10,
        fanout=8,
        out_cap=1 << 10,
    )
    got = collections.Counter()
    names = ("lk", "lv", "rk", "rv")

    _collect(ex.apply_left(_chunk({"lk": [1, 1], "lv": [5, 6]})), got, names)
    _collect(ex.apply_right(_chunk({"rk": [1], "rv": [7]})), got, names)
    # delete one left row -> retracts its pair
    _collect(
        ex.apply_left(
            _chunk({"lk": [1], "lv": [5]}, ops=[Op.DELETE])
        ),
        got,
        names,
    )
    # delete the right row -> retracts the remaining pair
    _collect(
        ex.apply_right(
            _chunk({"rk": [1], "rv": [7]}, ops=[Op.DELETE])
        ),
        got,
        names,
    )
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert got == collections.Counter()


def test_join_null_keys_never_match():
    ex = HashJoinExecutor(
        ("lk",),
        ("rk",),
        {"lk": jnp.int64, "lv": jnp.int64},
        {"rk": jnp.int64, "rv": jnp.int64},
        capacity=1 << 10,
        fanout=8,
        out_cap=1 << 10,
    )
    got = collections.Counter()
    names = ("lk", "lv", "rk", "rv")
    _collect(
        ex.apply_right(
            _chunk(
                {"rk": [0, 2], "rv": [70, 71]},
                nulls={"rk": [True, False]},
            )
        ),
        got,
        names,
    )
    # left NULL key must match neither the right NULL nor rk=0
    _collect(
        ex.apply_left(
            _chunk(
                {"lk": [0, 0, 2], "lv": [50, 51, 52]},
                nulls={"lk": [True, False, False]},
            )
        ),
        got,
        names,
    )
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert got == collections.Counter({(2, 52, 2, 71): 1})


def test_join_random_stream_vs_pandas(rng):
    """Random insert/delete traffic on both sides; emitted deltas must
    replay to exactly the pandas merge of the surviving rows."""
    ex = HashJoinExecutor(
        ("lk",),
        ("rk",),
        {"lk": jnp.int64, "lv": jnp.int64},
        {"rk": jnp.int64, "rv": jnp.int64},
        capacity=1 << 12,
        fanout=16,
        out_cap=1 << 12,
    )
    got = collections.Counter()
    names = ("lk", "lv", "rk", "rv")
    live = {"l": [], "r": []}

    for epoch in range(4):
        for _ in range(3):
            side = rng.choice(["l", "r"])
            n = int(rng.integers(8, 60))
            kcol, vcol = ("lk", "lv") if side == "l" else ("rk", "rv")
            keys, vals, ops = [], [], []
            for _ in range(n):
                if live[side] and rng.random() < 0.35:
                    k, v = live[side].pop(int(rng.integers(len(live[side]))))
                    keys.append(k)
                    vals.append(v)
                    ops.append(Op.DELETE)
                else:
                    k = int(rng.integers(0, 25))
                    v = int(rng.integers(0, 1000))
                    live[side].append((k, v))
                    keys.append(k)
                    vals.append(v)
                    ops.append(Op.INSERT)
            chunk = _chunk({kcol: keys, vcol: vals}, ops=ops)
            outs = (
                ex.apply_left(chunk) if side == "l" else ex.apply_right(chunk)
            )
            _collect(outs, got, names)
        ex.on_barrier(Barrier(Epoch(epoch, epoch + 1)))

    want = _oracle(
        [{"lk": k, "lv": v} for k, v in live["l"]],
        [{"rk": k, "rv": v} for k, v in live["r"]],
        ("lk",),
        ("rk",),
        names,
    )
    assert got == want
    assert len(want) > 10  # the test actually joined something


def test_join_duplicate_rows_same_chunk():
    """Identical rows inserted in ONE chunk must occupy distinct bucket
    entries (intra-chunk rank), and delete exactly one each."""
    ex = HashJoinExecutor(
        ("lk",),
        ("rk",),
        {"lk": jnp.int64, "lv": jnp.int64},
        {"rk": jnp.int64, "rv": jnp.int64},
        capacity=1 << 8,
        fanout=8,
        out_cap=1 << 10,
    )
    got = collections.Counter()
    names = ("lk", "lv", "rk", "rv")
    _collect(ex.apply_right(_chunk({"rk": [7], "rv": [1]})), got, names)
    # 3 identical + 1 distinct row into one bucket, one chunk
    _collect(
        ex.apply_left(_chunk({"lk": [7, 7, 7, 7], "lv": [5, 5, 5, 8]})),
        got,
        names,
    )
    assert got == collections.Counter({(7, 5, 7, 1): 3, (7, 8, 7, 1): 1})
    # delete two of the three twins in one chunk
    _collect(
        ex.apply_left(
            _chunk({"lk": [7, 7], "lv": [5, 5]}, ops=[Op.DELETE, Op.DELETE])
        ),
        got,
        names,
    )
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert got == collections.Counter({(7, 5, 7, 1): 1, (7, 8, 7, 1): 1})
    # state agrees: one more right row joins the remaining twins once each
    _collect(ex.apply_right(_chunk({"rk": [7], "rv": [2]})), got, names)
    assert got[(7, 5, 7, 2)] == 1
    assert got[(7, 8, 7, 2)] == 1


def test_join_growth_and_watermark_expiry():
    ex = HashJoinExecutor(
        ("lk", "lw"),
        ("rk", "rw"),
        {"lk": jnp.int64, "lw": jnp.int64, "lv": jnp.int64},
        {"rk": jnp.int64, "rw": jnp.int64, "rv": jnp.int64},
        capacity=1 << 6,  # forces several regrows
        fanout=4,
        out_cap=1 << 12,
        window_cols=("lw", "rw"),
    )
    got = collections.Counter()
    names = ("lk", "lw", "lv", "rk", "rw", "rv")
    n_keys = 300  # >> initial capacity
    for start in range(0, n_keys, 50):
        ks = np.arange(start, start + 50, dtype=np.int64)
        win = (ks % 4).astype(np.int64)
        _collect(
            ex.apply_left(
                _chunk({"lk": ks, "lw": win, "lv": ks * 2}, cap=64)
            ),
            got,
            names,
        )
        _collect(
            ex.apply_right(
                _chunk({"rk": ks, "rw": win, "rv": ks * 3}, cap=64)
            ),
            got,
            names,
        )
    ex.on_barrier(Barrier(Epoch(0, 1)))
    assert len(got) == n_keys  # every key joined exactly once
    assert ex.left.capacity >= n_keys

    # watermark closes windows < 2: those keys drop from state
    ex.on_watermark(Watermark("lw", 2))
    live_left = int(ex.left.table.num_live())
    assert live_left == len([k for k in range(n_keys) if k % 4 >= 2])
    # a late row for a closed window finds nothing to join
    outs = ex.apply_right(
        _chunk({"rk": [4], "rw": [0], "rv": [12]}, cap=64)
    )
    before = dict(got)
    _collect(outs, got, names)
    assert dict(got) == before
