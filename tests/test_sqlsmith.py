"""SqlSmith-lite: seeded random query generation + DIFFERENTIAL
checking of the streaming plan against the batch engine.

Reference: src/tests/sqlsmith/ — generated queries where the property
under test is agreement between two independent execution paths, not
hand-written expectations. Here every generated query runs twice:

  1. CREATE MATERIALIZED VIEW m AS <query>  (streaming executors,
     incremental over multiple INSERT epochs)
  2. <query> directly                        (batch engine over the
     base table snapshot)

and the row multisets must agree. Failures reproduce from the seed.
"""

import random

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

AGGS = ["count", "sum", "min", "max", "avg"]
CMPS = ["<", "<=", ">", ">=", "=", "<>"]


def _gen_query(rng: random.Random, i: int):
    """One random supported SELECT over t(k BIGINT, v BIGINT, w BIGINT)."""
    where = ""
    if rng.random() < 0.7:
        col = rng.choice(["k", "v", "w"])
        lit = rng.randint(-5, 15)
        op = rng.choice(CMPS)
        where = f" WHERE {col} {op} {lit}"
        if rng.random() < 0.3:
            col2 = rng.choice(["v", "w"])
            where += f" AND {col2} {rng.choice(CMPS)} {rng.randint(-5, 15)}"
    if rng.random() < 0.6:
        # grouped aggregates
        n_aggs = rng.randint(1, 3)
        items = ["k"]
        for j in range(n_aggs):
            fn = rng.choice(AGGS)
            arg = "*" if fn == "count" and rng.random() < 0.4 else rng.choice(["v", "w"])
            items.append(f"{fn}({arg}) AS a{j}")
        return f"SELECT {', '.join(items)} FROM t{where} GROUP BY k"
    # plain projection
    cols = rng.sample(["k", "v", "w"], rng.randint(1, 3))
    return f"SELECT {', '.join(cols)} FROM t{where}"


def _rows(out):
    """Column dict -> sorted list of normalized row tuples."""
    if not out:
        return []
    names = sorted(k for k in out if not k.endswith("__null"))
    cols = []
    for n in names:
        nl = out.get(n + "__null")
        vals = []
        for i, v in enumerate(np.asarray(out[n]).tolist()):
            if nl is not None and bool(np.asarray(nl)[i]):
                vals.append(None)
            elif isinstance(v, float):
                vals.append(None if np.isnan(v) else round(v, 9))
            else:
                vals.append(v)
        cols.append(vals)
    rows = list(zip(*cols))
    return sorted(rows, key=lambda r: tuple((x is None, x) for x in r))


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_streaming_batch_differential(seed):
    rng = random.Random(seed)
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT, w BIGINT)")
    # data in TWO epochs so streaming exercises incremental updates
    for _ in range(2):
        rows = ", ".join(
            f"({rng.randint(0, 4)}, {rng.randint(-5, 15)}, "
            f"{rng.randint(-5, 15)})"
            for _ in range(rng.randint(5, 20))
        )
        s.execute(f"INSERT INTO t VALUES {rows}")
    n_q = 8
    checked = 0
    for i in range(n_q):
        q = _gen_query(rng, i)
        mv = f"fz{seed}_{i}"
        try:
            s.execute(f"CREATE MATERIALIZED VIEW {mv} AS {q}")
        except (NotImplementedError, ValueError):
            continue  # outside the supported streaming surface: fine
        checked += 1
        got_stream, _ = s.execute(f"SELECT * FROM {mv}")
        got_batch, _ = s.execute(q)
        # streaming MV may expose hidden pk cols; compare the batch
        # query's column set
        keep = {
            k
            for k in got_batch
            if not k.endswith("__null") and not k.startswith("_")
        }
        gs = {
            k: v
            for k, v in got_stream.items()
            if k.split("__null")[0] in keep
        }
        gb = {
            k: v
            for k, v in got_batch.items()
            if k.split("__null")[0] in keep
        }
        assert _rows(gs) == _rows(gb), (
            f"seed={seed} query #{i}: {q}\n"
            f"stream={_rows(gs)}\nbatch={_rows(gb)}"
        )
    # a planner regression must not turn the whole seed into a no-op
    assert checked > 0, f"seed={seed}: every generated query was skipped"


def test_differential_with_updates_and_deletes():
    """The same property under RETRACTION: DML mutates the table and
    both paths must still agree."""
    rng = random.Random(7)
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT, w BIGINT)")
    rows = ", ".join(
        f"({rng.randint(0, 3)}, {rng.randint(-5, 15)}, {rng.randint(-5, 15)})"
        for _ in range(15)
    )
    s.execute(f"INSERT INTO t VALUES {rows}")
    q = "SELECT k, sum(v) AS sv, count(*) AS n, avg(w) AS aw FROM t GROUP BY k"
    s.execute(f"CREATE MATERIALIZED VIEW dm AS {q}")
    s.execute("UPDATE t SET v = v + 7 WHERE w > 5")
    s.execute("DELETE FROM t WHERE v < 0")
    got_stream, _ = s.execute("SELECT * FROM dm")
    got_batch, _ = s.execute(q)
    ks = {"k", "sv", "n", "aw"}
    gs = {k: v for k, v in got_stream.items() if k.split("__null")[0] in ks}
    gb = {k: v for k, v in got_batch.items() if k.split("__null")[0] in ks}
    assert _rows(gs) == _rows(gb)


def test_select_star():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 2), (3, 4)")
    out, _ = s.execute("SELECT * FROM t ORDER BY a")
    assert list(out["a"]) == [1, 3] and list(out["b"]) == [2, 4]
    s.execute("CREATE MATERIALIZED VIEW m AS SELECT * FROM t")
    out, _ = s.execute("SELECT * FROM m ORDER BY a")
    assert list(out["b"]) == [2, 4]
    # hidden planner columns stay hidden
    assert all(not c.startswith("_") for c in out)


def test_select_star_preserves_logical_types():
    """SELECT * MVs keep VARCHAR/DECIMAL logical types (review
    finding r5: the overlay used to skip Star items and serve codes)."""
    from decimal import Decimal

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (x VARCHAR, d DECIMAL(10, 2))")
    s.execute("INSERT INTO t VALUES ('hi', 1.25)")
    s.execute("CREATE MATERIALIZED VIEW m AS SELECT * FROM t")
    out, _ = s.execute("SELECT x, d FROM m")
    assert list(out["x"]) == ["hi"]
    assert out["d"][0] == Decimal("1.25")


def test_nested_select_star():
    """Star over a star-subquery expands level by level (streaming
    planner path; batch FROM-subqueries are a separate limitation)."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT * FROM (SELECT * FROM t) AS s2"
    )
    s.execute("INSERT INTO t VALUES (1, 2)")
    out, _ = s.execute("SELECT * FROM m")
    assert list(out["a"]) == [1] and list(out["b"]) == [2]


def test_select_star_with_extra_items():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT)")
    s.execute("INSERT INTO t VALUES (5)")
    out, _ = s.execute("SELECT *, a + 1 AS a1 FROM t")
    assert list(out["a"]) == [5] and list(out["a1"]) == [6]


def test_star_keeps_uninferrable_derived_columns():
    """* over a derived table includes expression columns whose TYPE
    is uninferrable (review finding r5: they used to vanish)."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT * FROM (SELECT k, v + 1 AS x FROM t) AS d"
    )
    s.execute("INSERT INTO t VALUES (1, 10)")
    out, _ = s.execute("SELECT k, x FROM m")
    assert list(out["x"]) == [11]


def test_star_in_any_item_position():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT)")
    s.execute("INSERT INTO t VALUES (5)")
    out, _ = s.execute("SELECT a - 1 AS a0, * FROM t")
    assert list(out["a0"]) == [4] and list(out["a"]) == [5]


def test_user_underscore_column_expands():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (_id BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 2)")
    out, _ = s.execute("SELECT * FROM t")
    assert "_id" in out and list(out["_id"]) == [1]
