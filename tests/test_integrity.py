"""End-to-end state integrity (device digests -> checksummed
checkpoints -> quarantine + verified recovery).

Three layers under test, matching the integrity spine:

1. The digest fold itself: bit-identical between the numpy twin and
   the jax fold, order-insensitive over slots, provably blind to
   padding (dead-slot bytes cannot move it).
2. The checksum envelope on every durable artifact: SSTs and the
   manifest verify on every read; a wrong byte raises StateCorruption
   (a RuntimeError — it must never ride the transient-retry loop),
   quarantines the evidence aside, and NEVER deletes the original.
3. Recovery: a corrupted newest checkpoint walks back to the newest
   fully-verifying epoch and replays to a result bit-identical to a
   fault-free twin — including under a seeded corruption storm
   composed with the crash + flaky storms.

Failing storm schedules print their seed; rerun with
``RW_CHAOS_SEED=<seed>`` to replay deterministically.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu import integrity
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.connectors.source import NexmarkSourceExecutor
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.integrity import (
    QUARANTINE_PREFIX,
    StateCorruption,
    decode_manifest,
    device_digest,
    digest_from_scalar,
    encode_manifest,
    host_digest,
)
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.resilience import (
    STORE_UNAVAILABLE,
    RetryingObjectStore,
    RetryPolicy,
)
from risingwave_tpu.runtime.fused_step import fuse_pipeline
from risingwave_tpu.sim import (
    CorruptingStore,
    CrashingStore,
    CrashPoint,
    FlakyStore,
    chaos_seed,
    corrupt_device_state,
)
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import (
    CheckpointManager,
    Checkpointable,
    StateDelta,
)


# ---------------------------------------------------------------------------
# layer 1: the fold
# ---------------------------------------------------------------------------


def _lanes(n=8):
    return {
        "a": np.arange(n, dtype=np.int64) * 3 - 5,
        "b": (np.arange(n) % 2 == 0),
        "c": np.linspace(-0.5, 2.5, n),
        "d": np.arange(n, dtype=np.int32) ^ 0x55,
    }


def test_fold_host_device_bit_identical():
    lanes = _lanes()
    live = np.arange(8) % 3 != 0
    want = host_digest(lanes, live)
    got = digest_from_scalar(
        device_digest(
            {k: jnp.asarray(v) for k, v in lanes.items()},
            jnp.asarray(live),
        )
    )
    assert got == want


def test_fold_is_slot_order_insensitive():
    lanes = _lanes()
    live = np.arange(8) % 3 != 0
    perm = np.random.default_rng(7).permutation(8)
    permuted = {k: v[perm] for k, v in lanes.items()}
    assert host_digest(permuted, live[perm]) == host_digest(lanes, live)


def test_fold_excludes_padding_and_sees_live_rows():
    lanes = _lanes()
    live = np.arange(8) % 3 != 0
    base = host_digest(lanes, live)
    # scribble over every DEAD slot: the digest must not move
    scribbled = {k: v.copy() for k, v in lanes.items()}
    dead = ~live
    scribbled["a"][dead] = -1
    scribbled["d"][dead] = 0x7FFF
    scribbled["c"][dead] = 1e9
    assert host_digest(scribbled, live) == base
    # flip ONE live value: the digest must move
    moved = {k: v.copy() for k, v in lanes.items()}
    moved["a"][np.flatnonzero(live)[0]] ^= 1
    assert host_digest(moved, live) != base


# ---------------------------------------------------------------------------
# layer 2: checksums, quarantine, manifest envelope
# ---------------------------------------------------------------------------


def _delta(ep, tid="t.x", n=5):
    return StateDelta(
        tid,
        {"k": np.arange(n, dtype=np.int64)},
        {"v": np.arange(n, dtype=np.int64) * ep},
        np.zeros(n, bool),
        ("k",),
    )


def _commit_fixture(store, epochs=(1,), tid="t.x"):
    mgr = CheckpointManager(store)
    for ep in epochs:
        mgr.commit_staged(ep << 16, [_delta(ep, tid)])
    return mgr


def test_corrupt_sst_read_quarantines_and_raises():
    store = MemObjectStore()
    _commit_fixture(store)
    (sst,) = store.list("hummock/sst/")
    good = store.read(sst)
    blob = bytearray(good)
    blob[len(blob) // 2] ^= 0x04
    store.put(sst, bytes(blob))
    n0 = integrity.corruption_count()
    m2 = CheckpointManager(store)
    with pytest.raises(StateCorruption) as ei:
        m2.read_table("t.x")
    assert ei.value.artifact == sst
    assert integrity.corruption_count() > n0
    # the corrupt original is still in place (recovery stops
    # REFERENCING it; nothing ever deletes the evidence) ...
    assert store.read(sst) == bytes(blob)
    # ... and a quarantine copy preserves the exact corrupt bytes
    qpath = f"{QUARANTINE_PREFIX}/{sst}"
    assert store.exists(qpath)
    assert store.read(qpath) == bytes(blob)


def test_manifest_envelope_roundtrip_and_faults():
    version = {"max_committed_epoch": 3 << 16, "tables": {"t": []}}
    raw = encode_manifest(version)
    assert decode_manifest(raw) == version
    # torn tail — the mid-write crash window
    with pytest.raises(StateCorruption) as ei:
        decode_manifest(raw[: len(raw) // 2])
    assert ei.value.kind == "torn-manifest"
    # wrong payload byte under a stale crc
    doc = raw.replace(b'"max_committed_epoch": ' + b"196608", b'"max_committed_epoch": 196609')
    assert doc != raw
    with pytest.raises(StateCorruption) as ei:
        decode_manifest(doc)
    assert ei.value.kind == "manifest-crc"
    # a flipped bit in the "format" field must NOT launder the blob
    # through the legacy path (the corruption storm found this one)
    with pytest.raises(StateCorruption) as ei:
        decode_manifest(raw.replace(b'"format": 2', b'"format": 3'))
    assert ei.value.kind == "manifest-format"
    # legacy format-1 (pre-envelope) decodes as-is: those bytes carry
    # no checksum to hold them to
    import json

    legacy = json.dumps(version).encode()
    assert decode_manifest(legacy) == version


def test_torn_manifest_write_walks_back_one_epoch():
    """Satellite regression: a crash mid-pointer-write. The commit
    order is MANIFEST first, then the history copy — so the torn
    window leaves a truncated pointer and NO newest history entry.
    A fresh manager must land on the previous epoch and read its
    exact image; a third manager must load cleanly (pointer healed)."""
    store = MemObjectStore()
    mgr = _commit_fixture(store, epochs=(1, 2))
    raw = store.read(mgr._manifest_path())
    store.put(mgr._manifest_path(), raw[: len(raw) - 7])
    store.delete(mgr._history_path(2 << 16))
    m2 = CheckpointManager(store)
    assert m2.max_committed_epoch == 1 << 16
    _k, v = m2.read_table("t.x")
    np.testing.assert_array_equal(
        np.sort(np.asarray(v["v"])), np.arange(5, dtype=np.int64)
    )
    # the walk-back HEALED the pointer: a later manager loads clean
    assert CheckpointManager(store).max_committed_epoch == 1 << 16


def test_corrupted_newest_checkpoint_verified_recovery(monkeypatch):
    """The acceptance bar: corrupt the newest checkpoint at rest ->
    recovery lands on the newest fully-verifying epoch, emits a
    ``state_corruption`` event naming the quarantined artifact, and a
    replay of the lost epoch is bit-identical to a fault-free twin."""
    monkeypatch.setenv("RW_STATE_DIGEST", "1")
    # fault-free twin
    tw = CheckpointManager(MemObjectStore())
    for ep in (1, 2, 3):
        tw.commit_staged(ep << 16, [_delta(ep)])
    want_k, want_v = tw.read_table("t.x")

    store = MemObjectStore()
    _commit_fixture(store, epochs=(1, 2, 3))
    newest = max(store.list("hummock/sst/"))
    blob = bytearray(store.read(newest))
    blob[len(blob) // 2] ^= 0x10
    store.put(newest, bytes(blob))

    class _Sink(Checkpointable):
        table_id = "t.x"
        image = None

        def restore_state(self, table_id, keys, values):
            self.image = (keys, values)

    sink = _Sink()
    m2 = CheckpointManager(store)
    m2.recover([sink])
    assert m2.max_committed_epoch >> 16 == 2
    assert sink.image is not None
    np.testing.assert_array_equal(
        np.sort(np.asarray(sink.image[1]["v"])),
        np.arange(5, dtype=np.int64) * 2,
    )
    named = [
        e
        for e in EVENT_LOG.events(kind="state_corruption")
        if e.get("artifact") == newest
    ]
    assert named, "no state_corruption event names the corrupt artifact"
    assert named[-1]["quarantined"] == f"{QUARANTINE_PREFIX}/{newest}"
    # replay the lost epoch exactly-once: bit-identical to the twin
    m2.commit_staged(3 << 16, [_delta(3)])
    got_k, got_v = m2.read_table("t.x")
    order_w = np.argsort(np.asarray(want_k["k"]))
    order_g = np.argsort(np.asarray(got_k["k"]))
    np.testing.assert_array_equal(
        np.asarray(got_k["k"])[order_g], np.asarray(want_k["k"])[order_w]
    )
    np.testing.assert_array_equal(
        np.asarray(got_v["v"])[order_g], np.asarray(want_v["v"])[order_w]
    )


def test_meta_backup_refuses_corrupt_sst():
    """Satellite 1: the backup tool VERIFIES checksums on the copy
    read — a faithfully copied corrupt SST would make the backup
    worthless, so it fails loudly instead."""
    from risingwave_tpu.storage.meta_backup import create_backup

    store = MemObjectStore()
    _commit_fixture(store, epochs=(1, 2))
    create_backup(store, "clean")  # a healthy store backs up fine
    sst = max(store.list("hummock/sst/"))
    blob = bytearray(store.read(sst))
    blob[-3] ^= 0x40
    store.put(sst, bytes(blob))
    with pytest.raises(StateCorruption) as ei:
        create_backup(store, "dirty")
    assert ei.value.artifact == sst


# ---------------------------------------------------------------------------
# layer 1 <-> layer 2 cross-checks: fused lanes vs interpreted twins
# ---------------------------------------------------------------------------


def test_fused_q5_digest_matches_interpreted_twin():
    """The fused one-dispatch barrier folds the same digest on-device
    (staged scalar lane) that the interpreted path computes on host —
    agg and MV must agree bit-for-bit every barrier."""
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    (w,) = fuse_pipeline(q5.pipeline, label="q5")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=5_000))
    for _ in range(2):
        for _ in range(2):
            c = gen.next_chunks(600, 1024)["bid"]
            if c is not None:
                q5.pipeline.push(c)
        q5.pipeline.barrier()
        assert w.last_digests["agg"] == w.agg.state_digest()
        assert w.last_digests["mv"] == w.mv.state_digest()
        assert "state_digests" in w._telemetry


def test_fused_q8_two_input_digest_matches_interpreted_twin():
    """Two-input path: per-side stateful digests plus the join's two
    side lanes; the join's host twin is the XOR of the packed side
    digests (XOR has no carries, so it commutes with the packing)."""
    q8 = build_q8(capacity=1 << 12, out_cap=1 << 11)
    (w,) = fuse_pipeline(q8.pipeline, label="q8")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    for _ in range(2):
        for _ in range(2):
            got = gen.next_chunks(1_000, 2048)
            p, a = got.get("person"), got.get("auction")
            if p is not None:
                q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
            if a is not None:
                q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()
        digs = w.last_digests
        if w.l_stateful is not None:
            assert digs["left"] == w.l_stateful.state_digest()
        if w.r_stateful is not None:
            assert digs["right"] == w.r_stateful.state_digest()
        if w.mv is not None:
            assert digs["mv"] == w.mv.state_digest()
        assert (
            digs["join_left"] ^ digs["join_right"]
            == w.join.state_digest()
        )


def test_device_state_corruption_moves_the_digest():
    """The sim hook flips one value in a LIVE, digest-covered slot —
    the executor's own state_digest() must move, which is exactly the
    signal the fused-vs-interpreted cross-check trips on."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=5_000))
    for _ in range(3):
        c = gen.next_chunks(600, 1024)["bid"]
        if c is not None:
            q5.pipeline.push(c)
    q5.pipeline.barrier()
    agg = q5.agg
    before = agg.state_digest()
    leaf, slot = corrupt_device_state(agg, seed=3)
    assert agg.state_digest() != before, (
        f"flip at leaf={leaf} slot={slot} did not move the digest"
    )


# ---------------------------------------------------------------------------
# rwlint RW-E709: digest coverage is part of the DDL contract
# ---------------------------------------------------------------------------


def _e709_env(monkeypatch, strict):
    if strict:
        monkeypatch.setenv("RW_STRICT_LINT", "1")
    else:
        monkeypatch.delenv("RW_STRICT_LINT", raising=False)


def _e709_chain():
    from risingwave_tpu.executors import HashAggExecutor
    from risingwave_tpu.executors.base import Executor
    from risingwave_tpu.ops.agg import AggCall

    class _NoDigest(Executor):
        """Ledger-visible (state_nbytes answers) but WITHOUT the
        state_digest contract — the RW-E709 target, isolated from
        RW-E708."""

        def apply(self, chunk):
            return [chunk]

        def state_nbytes(self):
            return 0

        def lint_info(self):
            return {"table_ids": ("nodigest.t",)}

    agg = HashAggExecutor(
        group_keys=("a",),
        calls=(AggCall("count_star", None, "n"),),
        schema_dtypes={"a": jnp.int64},
        capacity=64,
        out_cap=64,
        table_id="t.agg",
    )
    return [_NoDigest(), agg]


def _e709_session():
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime import Pipeline, StreamingRuntime
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.sql.planner import PlannedMV
    from risingwave_tpu.types import DataType, Field, Schema

    catalog = Catalog({"src": Schema([Field("a", DataType.INT64)])})
    session = SqlSession(
        catalog, StreamingRuntime(store=None), strict_lint=True
    )
    planned = PlannedMV(
        "bad",
        Pipeline(_e709_chain()),
        None,
        {"src": "single"},
        schema={"a": jnp.int64},
    )
    session.planner.plan = lambda sql: planned
    return session


def test_e709_reports_only_by_default(monkeypatch):
    _e709_env(monkeypatch, strict=False)
    session = _e709_session()
    session.execute("CREATE MATERIALIZED VIEW bad AS SELECT a FROM src")
    assert "bad" in session.runtime.fragments  # DDL accepted
    found = [d for _n, d in session.lint_findings if d.code == "RW-E709"]
    assert found and found[0].severity == "warning"
    assert "nodigest.t" in found[0].message


def test_e709_refused_under_explicit_strict_lint(monkeypatch):
    from risingwave_tpu.analysis import PlanLintError

    _e709_env(monkeypatch, strict=True)
    session = _e709_session()
    with pytest.raises(PlanLintError) as ei:
        session.execute("CREATE MATERIALIZED VIEW bad AS SELECT a FROM src")
    assert "RW-E709" in str(ei.value)
    assert "nodigest.t" in str(ei.value)


def test_builtin_stateful_executors_carry_digests():
    """Every shipped stateful executor overrides state_digest — the
    Nexmark corpus walks free of RW-E709 (covered by the rwlint suite's
    all-builders test); here the canonical state-holders answer the
    contract directly."""
    from risingwave_tpu.executors import HashAggExecutor
    from risingwave_tpu.executors.materialize import (
        DeviceMaterializeExecutor,
        MaterializeExecutor,
    )

    base = Checkpointable.state_digest
    for cls in (
        HashAggExecutor,
        MaterializeExecutor,
        DeviceMaterializeExecutor,
        NexmarkSourceExecutor,
    ):
        fn = getattr(cls, "state_digest", None)
        assert fn is not None and fn is not base, cls.__name__


# ---------------------------------------------------------------------------
# layer 3: the corruption storm (satellite 4)
# ---------------------------------------------------------------------------

EVENTS, CAP = 900, 1024


class _Q5:
    def __init__(self):
        self.source = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
        self.q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)

    @property
    def executors(self):
        return self.q5.pipeline.executors + [self.source]

    def feed(self):
        for bid in self.source.poll(EVENTS, CAP)["bid"]:
            self.q5.pipeline.push(bid.select(["auction", "date_time"]))
        self.q5.pipeline.barrier()


def _undisturbed(n_epochs):
    obj = _Q5()
    mgr = CheckpointManager(MemObjectStore())
    for i in range(n_epochs):
        obj.feed()
        mgr.commit_epoch((i + 1) << 16, obj.executors)
    return obj


def _run_corruption_storm(seed, n_epochs, corrupt_rate, flaky_rate):
    """ChaosRunner's kill-and-recover loop, extended with a seeded
    CorruptingStore under the crash + flaky layers — and with
    StateCorruption as a RESPAWN trigger (it escapes both the
    transient-retry classifier and ChaosRunner's own handlers by
    design: a wrong byte is never store weather)."""
    disk = MemObjectStore()
    corrupting = CorruptingStore(
        disk, rate=corrupt_rate, seed=seed, ops=("read", "read_range")
    )
    rng = random.Random(seed)
    policy = RetryPolicy(
        max_attempts=8,
        base_backoff_s=1e-3,
        max_backoff_s=0.02,
        deadline_s=10.0,
        seed=seed,
    )
    flaky_rng = random.Random(seed ^ 0x5EED)

    def spawn():
        # recovery reads ride the same corrupting store: a detected
        # wrong byte (or an exhausted retry budget) during restore is
        # just another death — die and come back, bounded
        for _ in range(40):
            obj = _Q5()
            crashing = CrashingStore(corrupting)
            flaky = FlakyStore(crashing, rate=flaky_rate, rng=flaky_rng)
            try:
                mgr = CheckpointManager(
                    RetryingObjectStore(flaky, policy), read_retry=policy
                )
                mgr.recover(obj.executors)
                return obj, crashing, mgr
            except (StateCorruption,) + STORE_UNAVAILABLE:
                continue
        raise AssertionError(
            f"respawn never survived recovery (seed={seed})"
        )

    obj, crashing, mgr = spawn()
    done = mgr.max_committed_epoch >> 16
    stats = {"crashes": 0, "corruption_respawns": 0, "attempts": 0}
    while done < n_epochs:
        stats["attempts"] += 1
        assert stats["attempts"] < 400, (
            f"corruption storm did not converge (seed={seed}, "
            f"stats={stats})"
        )
        if rng.random() < 0.30:
            crashing.arm(rng.randint(1, 3))
        try:
            obj.feed()
            mgr.commit_epoch((done + 1) << 16, obj.executors)
            done = mgr.max_committed_epoch >> 16
        except CrashPoint:
            stats["crashes"] += 1
            obj, crashing, mgr = spawn()
            done = mgr.max_committed_epoch >> 16
        except StateCorruption:
            stats["corruption_respawns"] += 1
            obj, crashing, mgr = spawn()
            done = mgr.max_committed_epoch >> 16
        except STORE_UNAVAILABLE:
            obj, crashing, mgr = spawn()
            done = mgr.max_committed_epoch >> 16
    return obj, corrupting, stats


def test_corruption_storm_zero_undetected(monkeypatch):
    """Satellite 4 acceptance: a seeded ~10% on-read corruption storm
    composed with the crash + flaky storms. Zero undetected
    corruptions — proven the strong way: the final MV is bit-identical
    to the fault-free twin's (a single laundered wrong byte would
    diverge it), and every detection was counted on the way."""
    monkeypatch.setenv("RW_STATE_DIGEST", "1")
    seed = chaos_seed(11)
    n_epochs = 4
    want = _undisturbed(n_epochs).q5.mview.snapshot()
    n0 = integrity.corruption_count()
    obj, corrupting, stats = _run_corruption_storm(
        seed, n_epochs, corrupt_rate=0.10, flaky_rate=0.15
    )
    assert corrupting.injected, (
        f"the corruption storm never fired (seed={seed})"
    )
    assert integrity.corruption_count() > n0, (
        f"injected corruption was never DETECTED (seed={seed}, "
        f"injected={len(corrupting.injected)})"
    )
    got = obj.q5.mview.snapshot()
    assert got == want, (
        f"corruption storm diverged from the fault-free twin "
        f"(seed={seed}; rerun with RW_CHAOS_SEED={seed}; stats={stats}, "
        f"injected={len(corrupting.injected)})"
    )
    assert len(want) > 50


@pytest.mark.slow
def test_corruption_storm_heavy(monkeypatch):
    """Longer storm at a higher corruption rate (nightly tier)."""
    monkeypatch.setenv("RW_STATE_DIGEST", "1")
    seed = chaos_seed(13)
    n_epochs = 6
    want = _undisturbed(n_epochs).q5.mview.snapshot()
    # rate is bounded by progress: every epoch needs ONE fully-clean
    # read window to commit, so past ~15% the storm starves rather
    # than exercises (detection, not availability, is under test)
    obj, corrupting, stats = _run_corruption_storm(
        seed, n_epochs, corrupt_rate=0.12, flaky_rate=0.20
    )
    assert corrupting.injected
    got = obj.q5.mview.snapshot()
    assert got == want, (
        f"heavy corruption storm diverged (seed={seed}, stats={stats})"
    )


# ---------------------------------------------------------------------------
# surfaces: rw_integrity system table + the scrub CLI
# ---------------------------------------------------------------------------


def test_rw_integrity_rows_and_scrub():
    from types import SimpleNamespace

    from risingwave_tpu.frontend.sys_tables import _rows_integrity

    store = MemObjectStore()
    mgr = _commit_fixture(store, epochs=(1, 2))
    shim = SimpleNamespace(runtime=SimpleNamespace(mgr=mgr))
    rows = _rows_integrity(shim)
    assert rows and all(r["status"] == "ok" for r in rows)
    assert {r["artifact"] for r in rows} >= set(store.list("hummock/sst/"))
    # no store at all reads empty, not an error
    none_shim = SimpleNamespace(runtime=SimpleNamespace(mgr=None))
    assert _rows_integrity(none_shim) == []
    # one flipped byte at rest: the next scrub names the artifact
    sst = max(store.list("hummock/sst/"))
    blob = bytearray(store.read(sst))
    blob[len(blob) // 2] ^= 0x08
    store.put(sst, bytes(blob))
    bad = [r for r in _rows_integrity(shim) if r["status"] == "corrupt"]
    assert bad and bad[0]["artifact"] == sst


def test_ctl_scrub_cli(tmp_path, monkeypatch, capsys):
    import sys

    from risingwave_tpu.__main__ import main
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    store = LocalFsObjectStore(str(tmp_path))
    _commit_fixture(store, epochs=(1,))
    argv = [
        "risingwave_tpu", "ctl", "scrub", "--state-dir", str(tmp_path)
    ]
    monkeypatch.setattr(sys, "argv", argv)
    main()  # clean store: exit 0 (no SystemExit)
    out = capsys.readouterr().out
    assert "0 corrupt" in out
    (sst,) = store.list("hummock/sst/")
    blob = bytearray(store.read(sst))
    blob[len(blob) // 2] ^= 0x20
    store.put(sst, bytes(blob))
    with pytest.raises(SystemExit) as ei:
        main()
    assert ei.value.code == 1
    out = capsys.readouterr().out
    assert "corrupt" in out and sst in out
