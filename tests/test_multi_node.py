"""N-compute-node cluster: vnode-sharded fragments across node
PROCESSES with meta-driven recovery (the multi-CN deployment shape —
cross-node hash exchange at the meta role + barrier broadcast)."""

import numpy as np
import pytest

from risingwave_tpu.cluster.multi_node import ShardedClusterClient

pytestmark = pytest.mark.slow


def _push_bids(cc, rng, n):
    cc.push_chunk(
        "bid",
        {
            "auction": rng.integers(0, 40, n).astype(np.int64),
            "price": rng.integers(1, 100, n).astype(np.int64),
        },
        1 << 9,
    )


def test_two_node_sharded_mv_with_kill9(tmp_path):
    cc = ShardedClusterClient.spawn(
        2, [str(tmp_path / "n0"), str(tmp_path / "n1")]
    )
    try:
        cc.ddl(
            "CREATE TABLE bid (auction BIGINT, price BIGINT)",
            distributed_by="auction",
        )
        cc.ddl(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, count(*) AS c, "
            "sum(price) AS s FROM bid GROUP BY auction"
        )
        rng = np.random.default_rng(3)
        oracle: dict = {}

        def feed(n):
            state = rng.bit_generator.state
            _push_bids(cc, rng, n)
            rng.bit_generator.state = state
            a = rng.integers(0, 40, n).astype(np.int64)
            p = rng.integers(1, 100, n).astype(np.int64)
            for k, v in zip(a.tolist(), p.tolist()):
                c, s = oracle.get(k, (0, 0))
                oracle[k] = (c + 1, s + v)

        feed(300)
        cc.barrier()
        # every node holds only ITS shard (state is actually split)
        per_node = [len(n.query("SELECT auction FROM m")["auction"])
                    for n in cc.nodes]
        assert all(c > 0 for c in per_node)
        assert sum(per_node) == len(oracle)

        # kill -9 node 1 mid-stream; meta recovery replays its chunks
        feed(200)
        cc.kill9(1)
        cc.barrier()  # recovers node 1 in place, then commits
        feed(100)
        cc.barrier()

        out = cc.query(
            "SELECT auction, c, s FROM m", order_by="auction"
        )
        got = {
            int(a): (int(c), int(s))
            for a, c, s in zip(out["auction"], out["c"], out["s"])
        }
        assert got == oracle
    finally:
        cc.close()


def test_kill9_racing_barrier_single_recovery_event(tmp_path):
    """Satellite: kill -9 racing the barrier broadcast must surface
    EXACTLY ONE ``recovery`` event for the dead node (one death = one
    event, however many bounded retry attempts recovery takes inside)
    and converge to the undisturbed result."""
    from risingwave_tpu.event_log import EVENT_LOG

    cc = ShardedClusterClient.spawn(
        2, [str(tmp_path / "n0"), str(tmp_path / "n1")]
    )
    try:
        cc.ddl(
            "CREATE TABLE bid (auction BIGINT, price BIGINT)",
            distributed_by="auction",
        )
        cc.ddl(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, count(*) AS c, "
            "sum(price) AS s FROM bid GROUP BY auction"
        )
        rng = np.random.default_rng(11)
        oracle: dict = {}

        def feed(n):
            state = rng.bit_generator.state
            _push_bids(cc, rng, n)
            rng.bit_generator.state = state
            a = rng.integers(0, 40, n).astype(np.int64)
            p = rng.integers(1, 100, n).astype(np.int64)
            for k, v in zip(a.tolist(), p.tolist()):
                c, s = oracle.get(k, (0, 0))
                oracle[k] = (c + 1, s + v)

        feed(250)
        cc.barrier()
        # the kill lands between the data and the barrier broadcast:
        # the barrier must recover the node in place and commit
        feed(150)
        before = len(EVENT_LOG.events(kind="recovery"))
        cc.kill9(1)
        cc.barrier()
        recoveries = [
            e
            for e in EVENT_LOG.events(kind="recovery")[before:]
            if e.get("mode") == "node"
        ]
        assert len(recoveries) == 1, recoveries
        assert recoveries[0]["node"] == 1
        assert cc.node_breakers[1].state == "closed"  # healthy again

        feed(100)
        cc.barrier()
        out = cc.query("SELECT auction, c, s FROM m", order_by="auction")
        got = {
            int(a): (int(c), int(s))
            for a, c, s in zip(out["auction"], out["c"], out["s"])
        }
        assert got == oracle  # converged to the undisturbed result
    finally:
        cc.close()
