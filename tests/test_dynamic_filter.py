"""General DynamicFilter: comparator against a moving 1-row right
value with re-emission/retraction from state in BOTH directions.

Reference: src/stream/src/executor/dynamic_filter.rs:40 (1,295 LoC) —
the `WHERE price > (SELECT max(...) ...)` plan shape.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.dynamic_filter import DynamicFilterExecutor
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager
from risingwave_tpu.types import Op

DT = {"id": jnp.int64, "v": jnp.int64}


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def _replay(state, chunks):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            row = (int(d["id"][i]), int(d["v"][i]))
            if d["__op__"][i] in (int(Op.DELETE), int(Op.UPDATE_DELETE)):
                assert row in state, f"retract of unemitted {row}"
                state.discard(row)
            else:
                assert row not in state, f"duplicate emit {row}"
                state.add(row)


def _right(ex, val=None, delete=False):
    if delete:
        ex.apply_right(
            StreamChunk.from_numpy(
                {"v": np.asarray([0], np.int64)},
                4,
                ops=np.asarray([int(Op.DELETE)], np.int32),
            )
        )
    else:
        ex.apply_right(
            StreamChunk.from_numpy({"v": np.asarray([val], np.int64)}, 4)
        )


@pytest.mark.parametrize("op", [">", ">=", "<", "<="])
def test_dynamic_filter_randomized_oracle(op):
    """Random left inserts/deletes interleaved with right-value moves
    in both directions; replaying the deltas always equals the SQL
    filter over the live relation."""
    ex = DynamicFilterExecutor(
        "v", op, ("id",), DT, capacity=1 << 9, table_id=f"df_{op}"
    )
    cmp = {
        ">": lambda a, b: a > b,
        ">=": lambda a, b: a >= b,
        "<": lambda a, b: a < b,
        "<=": lambda a, b: a <= b,
    }[op]
    rng = np.random.default_rng(23)
    live = {}
    state = set()
    rv = None
    next_id = 0
    for _ in range(15):
        n = int(rng.integers(2, 12))
        ids, vs, ops = [], [], []
        for _ in range(n):
            if live and rng.random() < 0.35:
                i = int(rng.choice(list(live)))
                ids.append(i)
                vs.append(live.pop(i))
                ops.append(int(Op.DELETE))
            else:
                v = int(rng.integers(0, 100))
                ids.append(next_id)
                vs.append(v)
                ops.append(int(Op.INSERT))
                live[next_id] = v
                next_id += 1
        _replay(
            state,
            ex.apply_left(
                StreamChunk.from_numpy(
                    {
                        "id": np.asarray(ids, np.int64),
                        "v": np.asarray(vs, np.int64),
                    },
                    16,
                    ops=np.asarray(ops, np.int32),
                )
            ),
        )
        r = rng.random()
        if r < 0.45:
            rv = int(rng.integers(0, 100))
            _right(ex, rv)
        elif r < 0.55 and rv is not None:
            rv = None
            _right(ex, delete=True)
        _replay(state, ex.on_barrier(None))
        want = (
            set()
            if rv is None
            else {(i, v) for i, v in live.items() if cmp(v, rv)}
        )
        assert state == want


def test_dynamic_filter_checkpoint_restore():
    """Kill+recover keeps the row store, pass flags AND the right
    value: post-restore moves retract/promote exactly."""

    def mk():
        return DynamicFilterExecutor(
            "v", ">", ("id",), DT, capacity=1 << 8, table_id="dfc"
        )

    ex = mk()
    state = set()
    _replay(
        state,
        ex.apply_left(
            StreamChunk.from_numpy(
                {
                    "id": np.arange(6, dtype=np.int64),
                    "v": np.asarray([5, 20, 35, 50, 65, 80], np.int64),
                },
                8,
            )
        ),
    )
    _right(ex, 40)
    _replay(state, ex.on_barrier(None))
    assert state == {(3, 50), (4, 65), (5, 80)}

    mgr = CheckpointManager(MemObjectStore())
    mgr.commit_staged(1, mgr.stage([ex]))
    del ex

    ex2 = mk()
    mgr.recover([ex2])
    # move DOWN: rows 20 and 35 must re-emerge from restored state
    _right(ex2, 10)
    _replay(state, ex2.on_barrier(None))
    assert state == {(1, 20), (2, 35), (3, 50), (4, 65), (5, 80)}
    # move UP: most retract
    _right(ex2, 70)
    _replay(state, ex2.on_barrier(None))
    assert state == {(5, 80)}


def test_right_chunk_insert_then_delete_nets_to_invalid():
    """Rows apply in order: an INSERT followed by its own DELETE in one
    right chunk leaves NO right value — everything retracts."""
    ex = DynamicFilterExecutor(
        "v", ">", ("id",), DT, capacity=1 << 6, table_id="dford"
    )
    state = set()
    _replay(
        state,
        ex.apply_left(
            StreamChunk.from_numpy(
                {
                    "id": np.asarray([1, 2], np.int64),
                    "v": np.asarray([60, 80], np.int64),
                },
                4,
            )
        ),
    )
    _right(ex, 50)
    _replay(state, ex.on_barrier(None))
    assert state == {(1, 60), (2, 80)}
    # one chunk: INSERT 10 then DELETE 10 -> net empty right side
    ex.apply_right(
        StreamChunk.from_numpy(
            {"v": np.asarray([10, 10], np.int64)},
            4,
            ops=np.asarray([int(Op.INSERT), int(Op.DELETE)], np.int32),
        )
    )
    _replay(state, ex.on_barrier(None))
    assert state == set()
