"""Fused device-resident barrier step (runtime/fused_step).

Twin discipline: the fused program must be BIT-IDENTICAL to the
interpreted per-executor walk — same seeds, same epochs, identical MV
snapshots at every barrier — across q5 (hop->agg->MV), q7 (two-input
join with a fusible hop->maxagg side and a fused MV tail) and q8
(dedup join with a fused MV tail). Plus the operational contracts:
one device dispatch per barrier attributed as ``fused:<fragment>``,
donation leaves no orphaned state buffers, rebuilt fragments re-fuse,
latch checks still raise at finish_barrier, and RW_FUSED_STEP=0 falls
back to the epoch-batched interpreted path.
"""

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.queries.nexmark_q import (
    build_q5_lite,
    build_q7,
    build_q8,
)
from risingwave_tpu.runtime.fused_step import (
    FusedChainExecutor,
    expand_fused,
    fuse_chain,
    fuse_pipeline,
    fused_fragments,
)

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)


# ---------------------------------------------------------------------------
# fused-vs-interpreted twins (bit-identity per barrier)
# ---------------------------------------------------------------------------


def _drive_q5(q5, *, fuse, watermarks, epochs=4, chunks_per_epoch=3):
    if fuse:
        wrappers = fuse_pipeline(q5.pipeline, label="q5")
        assert len(wrappers) == 1 and wrappers[0].covers_whole_chain
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=5_000))
    snaps, mx = [], 0
    for _ in range(epochs):
        for _ in range(chunks_per_epoch):
            c = gen.next_chunks(800, 1024)["bid"]
            if c is None:
                continue
            q5.pipeline.push(c)
            mx = max(mx, int(c.to_numpy()["date_time"].max()))
        q5.pipeline.barrier()
        if watermarks:
            q5.pipeline.watermark("date_time", mx)
        snaps.append(q5.mview.snapshot())
    return snaps


@pytest.mark.parametrize("watermarks", [False, True])
def test_q5_fused_bit_identical_to_interpreted_twin(watermarks):
    mk = lambda: build_q5_lite(
        capacity=1 << 12, state_cleaning=watermarks
    )
    interp = _drive_q5(mk(), fuse=False, watermarks=watermarks)
    fused = _drive_q5(mk(), fuse=True, watermarks=watermarks)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused MV diverged from interpreted"
    assert len(interp[-1]) > 0


def _drive_q7(q7, *, fuse, epochs=4):
    if fuse:
        from risingwave_tpu.executors.epoch_batch import (
            EpochBatchedAggExecutor,
        )

        wrappers = fuse_pipeline(q7.pipeline, label="q7")
        # nothing on q7 forms the agg->MV shape: the hop->maxagg side
        # feeds the INTERPRETED join so it epoch-batches (the fused
        # flush would hand the join bound-padded chunks), and the
        # join-fed MV tail stays interpreted (stacking a join's
        # heterogeneous emissions would compile-storm) — fusion armed
        # must still be bit-identical through all the fallbacks
        assert wrappers == []
        assert any(
            isinstance(e, EpochBatchedAggExecutor) for e in q7.pipeline.right
        )
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    snaps, mx = [], 0
    for _ in range(epochs):
        for _ in range(2):
            bid = gen.next_chunks(1200, 2048)["bid"]
            if bid is None:
                continue
            bid = bid.select(["auction", "bidder", "price", "date_time"])
            q7.pipeline.push_left(bid)
            q7.pipeline.push_right(bid)
            mx = max(mx, int(bid.to_numpy()["date_time"].max()))
        q7.pipeline.barrier()
        q7.pipeline.watermark("date_time", mx)
        snaps.append(q7.mview.snapshot())
    return snaps


def test_q7_fused_bit_identical_to_interpreted_twin():
    mk = lambda: build_q7(
        capacity=1 << 13,
        agg_capacity=1 << 11,
        filter_capacity=1 << 11,
        out_cap=1 << 11,
    )
    interp = _drive_q7(mk(), fuse=False)
    fused = _drive_q7(mk(), fuse=True)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused q7 MV diverged"


def _drive_q8(q8, *, fuse, epochs=4):
    if fuse:
        wrappers = fuse_pipeline(q8.pipeline, label="q8")
        assert wrappers == []  # dedup/join/mv-tail: all interpreted
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    snaps = []
    for _ in range(epochs):
        for _ in range(2):
            ev = gen.next_chunks(3000, 8192)
            p, a = ev["person"], ev["auction"]
            if p is not None:
                q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
            if a is not None:
                q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()
        snaps.append(q8.mview.snapshot())
    return snaps


def test_q8_fused_bit_identical_to_interpreted_twin():
    mk = lambda: build_q8(capacity=1 << 12, out_cap=1 << 11)
    interp = _drive_q8(mk(), fuse=False)
    fused = _drive_q8(mk(), fuse=True)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused q8 MV diverged"
    assert len(interp[-1]) > 0


# ---------------------------------------------------------------------------
# dispatch-wall evidence: ONE program per barrier, attributed
# ---------------------------------------------------------------------------


def test_fused_q5_one_dispatch_per_barrier_with_fused_label():
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    bid = gen.next_chunks(2000, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    epoch()
    epoch()  # warm: compiles + growth transitions
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        for _ in range(3):
            base = PROFILER.total_dispatches()
            epoch()
            per.append(PROFILER.total_dispatches() - base)
        counts = PROFILER.dispatch_counts()
    finally:
        PROFILER.disable()
        PROFILER.reset()
    # steady state: the whole hop->agg->flush->MV barrier is ONE
    # Python-level device dispatch, attributed to the fused fragment
    assert per == [1.0, 1.0, 1.0], per
    assert counts.get("fused:q5", 0) >= 3, counts


def test_fused_fragments_report_shapes():
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    rep = fused_fragments(q5.pipeline)
    assert rep["count"] == 1 and rep["whole_chain"] is True
    assert rep["fragments"] == ["q5[3]"]


def test_no_orphaned_state_buffers_across_fused_barriers():
    """Donation contract: steady-state fused barriers must not leak
    device buffers (the donated state is consumed, the returned state
    replaces it — live-array count stays flat)."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    bid = gen.next_chunks(500, 512)["bid"].select(["auction", "date_time"])

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    for _ in range(3):  # warm: compiles + capacity transitions
        epoch()
    counts = []
    for _ in range(4):
        epoch()
        counts.append(len(jax.live_arrays()))
    assert max(counts) - min(counts) <= 2, (
        f"live device arrays grew across fused barriers: {counts}"
    )


# ---------------------------------------------------------------------------
# wrapper mechanics
# ---------------------------------------------------------------------------


def _bid_chunk(gen, n=400, cap=512):
    c = None
    while c is None:
        c = gen.next_chunks(n, cap)["bid"]
    return c.select(["auction", "date_time"])


def test_fused_flush_rounds_cover_small_out_cap():
    """Regression (code-review finding): the fused flush-round count
    must be derived AFTER the buffered epoch lands in the dirty bound.
    With out_cap far below the epoch's distinct groups, an early round
    count silently dropped every group past the first round — the
    fused MV diverged from the interpreted twin permanently."""
    mk = lambda: build_q5_lite(capacity=1 << 10, state_cleaning=False)

    def drive(q5, fuse):
        q5.agg.out_cap = 128  # << distinct (auction, window) groups
        if fuse:  # fuse AFTER sizing: the plan captures out_cap
            fuse_pipeline(q5.pipeline)
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
        for _ in range(2):
            q5.pipeline.push(_bid_chunk(gen, 800, 1024))
            q5.pipeline.barrier()
        return q5.mview.snapshot()

    interp = drive(mk(), fuse=False)
    fused = drive(mk(), fuse=True)
    assert len(interp) > 128  # the workload actually exceeds out_cap
    assert fused == interp


def test_signature_change_mid_epoch_flushes_buffer():
    mk = lambda: build_q5_lite(capacity=1 << 10, state_cleaning=False)
    a, b = mk(), mk()
    fuse_pipeline(b.pipeline)
    gen1 = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    gen2 = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    for q5, gen in ((a, gen1), (b, gen2)):
        c1 = _bid_chunk(gen, 400, 512)
        c2 = _bid_chunk(gen, 900, 1024)  # different capacity: new sig
        q5.pipeline.push(c1)
        q5.pipeline.push(c2)
        q5.pipeline.push(_bid_chunk(gen, 400, 512))
        q5.pipeline.barrier()
    assert a.mview.snapshot() == b.mview.snapshot()


def test_overflow_latch_still_raises_at_finish_barrier():
    """The agg's MAX_PROBE overflow latch rides the fused program's
    packed scalars and raises at the wrapper's finish_barrier — same
    raise point as the interpreted path."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    (wrapper,) = fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    q5.pipeline.push(_bid_chunk(gen))
    q5.pipeline.barrier()
    # force the latch: a poisoned dropped flag must surface as the
    # hash-table overflow error when the staged scalars materialize
    q5.agg.dropped = jnp.ones((), jnp.bool_)
    with pytest.raises(RuntimeError, match="overflowed MAX_PROBE"):
        q5.pipeline.push(_bid_chunk(gen))
        q5.pipeline.barrier()
    assert wrapper.agg is q5.agg  # members stayed the system of record


def test_fuse_chain_falls_back_around_unfusible_ops():
    """Host-bound / opaque members break the run: interpretation is
    the automatic per-run fallback, not a process-wide switch — and an
    agg whose flush exits to an interpreted consumer epoch-batches
    instead of fusing (the exact-sliced flush stays)."""
    from risingwave_tpu.executors.base import Executor
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    class HostOp(Executor):  # no pure_step -> not fusible
        pass

    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("count_star", None, "n"),),
        schema_dtypes={"k": jnp.int64},
        capacity=64,
        out_cap=32,
    )
    host = HostOp()
    out = fuse_chain([host, agg], label="t")
    assert out[0] is host
    assert isinstance(out[1], EpochBatchedAggExecutor)
    assert out[1].agg is agg
    # pure-only runs stay interpreted unless defer_pure opts in
    from risingwave_tpu.executors.hop_window import HopWindowExecutor

    hop = HopWindowExecutor("t", 10, 10)
    assert fuse_chain([hop, host], label="t") == [hop, host]


def test_expand_fused_exposes_members_for_padding_and_governor():
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    exs = expand_fused(q5.pipeline.executors)
    names = [type(e).__name__ for e in exs]
    assert "HashAggExecutor" in names
    assert "DeviceMaterializeExecutor" in names
    assert all(not isinstance(e, FusedChainExecutor) for e in exs)


def test_governor_bucket_pin_holds_fused_shapes_steady():
    """After a governor pin, steady fused barriers mint ZERO new
    compiled programs (exactly the recompile-storm throttle the fused
    step needs: pinned buckets = closed shape set)."""
    from risingwave_tpu.analysis.jax_sanitizer import RecompileWatch

    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    bid = _bid_chunk(gen)

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    epoch()
    epoch()
    pin_agg = q5.agg.pin_max_bucket()
    pin_mv = q5.mview.pin_max_bucket()
    assert pin_agg["pinned_cap"] == q5.agg.table.capacity
    assert pin_mv["pinned_cap"] == q5.mview.table.capacity
    watch = RecompileWatch()
    watch.snapshot()
    for _ in range(3):
        epoch()
    assert watch.deltas() == {}, watch.deltas()


# ---------------------------------------------------------------------------
# graph runtime: auto-fusion, rebuild re-fuses, recovery with fusion armed
# ---------------------------------------------------------------------------


def _catalog_factory(capacity=1 << 11):
    from risingwave_tpu.sql import Catalog, StreamPlanner

    catalog = Catalog({"bid": BID_SCHEMA})
    return lambda: StreamPlanner(catalog, capacity=capacity)


def _graph_mv(parallelism=1):
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv

    return graph_planned_mv(
        _catalog_factory(), Q5_SQL, parallelism=parallelism
    )


def _fused_in_actors(gp):
    return [
        e
        for a in gp.graph.actors
        for e in a.executors
        if isinstance(e, FusedChainExecutor)
    ]


def test_graph_actors_fuse_by_default_and_rebuild_refuses():
    mv = _graph_mv()
    try:
        assert _fused_in_actors(mv.pipeline), "graph chain did not fuse"
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
        bid = _bid_chunk(gen, 600, 1024)
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        before = mv.mview.snapshot()
        assert before
        # rebuild (the recovery path's actor replacement): fresh actors
        # around the SAME executor objects must RE-FUSE automatically
        mv.pipeline.rebuild()
        assert _fused_in_actors(mv.pipeline), "rebuilt actors lost fusion"
        assert mv.mview.snapshot() == before  # state survived the rebuild
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        after = mv.mview.snapshot()
        assert set(after) == set(before)
        assert all(after[k][0] == 2 * before[k][0] for k in before)
    finally:
        mv.pipeline.close()


class _PoisonOnce:
    """Raises at the first armed barrier, then behaves forever after
    (the transient-fault model of the recovery suites)."""

    def __init__(self):
        self.armed = False
        self.fired = 0

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self, b):
        if self.armed:
            self.armed = False
            self.fired += 1
            raise RuntimeError("poisoned epoch (injected)")
        return []

    def on_watermark(self, wm):
        return wm, []

    def emit_watermark(self):
        return None

    def finish_barrier(self):
        return None

    def pure_step(self):
        return None


def test_actor_kill_recovery_with_fusion_armed():
    """Actor-kill chaos with the fused step armed: the poisoned
    barrier kills the actor thread, the watchdog rebuilds the graph,
    the rebuilt fragment RE-FUSES around the restored state, and the
    stream continues exact (the serial interpreted twin is the
    oracle)."""
    from risingwave_tpu.runtime.fragmenter import GraphPipeline
    from risingwave_tpu.runtime.graph import FragmentSpec
    from risingwave_tpu.runtime.runtime import StreamingRuntime
    from risingwave_tpu.storage.object_store import MemObjectStore

    poison = _PoisonOnce()
    q5 = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    chain = [poison] + list(q5.pipeline.executors)
    gp = GraphPipeline(
        [FragmentSpec("gq5", lambda i, ch=tuple(chain): list(ch))],
        {"single": "gq5"},
        "gq5",
        [q5.agg, q5.mview],
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("gq5", gp)
    twin = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    first_graph = gp.graph
    try:
        assert _fused_in_actors(gp), "poisoned chain's fusible run lost"
        for epoch in range(5):
            chunk = _bid_chunk(gen, 500, 1024)
            if epoch == 2:
                poison.armed = True
            for _attempt in range(4):
                rt.push("gq5", chunk)
                before = rt.mgr.max_committed_epoch
                rt.barrier()
                if rt.mgr.max_committed_epoch > before:
                    break
            else:
                raise AssertionError("epoch never committed")
            twin.pipeline.push(chunk)
            twin.pipeline.barrier()
        assert rt.auto_recoveries == 1 and poison.fired == 1
        assert gp.graph is not first_graph  # actors were rebuilt
        assert _fused_in_actors(gp), "recovered graph lost fusion"
        assert q5.mview.snapshot() == twin.mview.snapshot()
    finally:
        gp.close()
