"""Fused device-resident barrier step (runtime/fused_step).

Twin discipline: the fused program must be BIT-IDENTICAL to the
interpreted per-executor walk — same seeds, same epochs, identical MV
snapshots at every barrier — across q5 (hop->agg->MV), q7 (two-input
join with a fusible hop->maxagg side and a fused MV tail) and q8
(dedup join with a fused MV tail). Plus the operational contracts:
one device dispatch per barrier attributed as ``fused:<fragment>``,
donation leaves no orphaned state buffers, rebuilt fragments re-fuse,
latch checks still raise at finish_barrier, and RW_FUSED_STEP=0 falls
back to the epoch-batched interpreted path.
"""

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.queries.nexmark_q import (
    build_q5_lite,
    build_q7,
    build_q8,
)
from risingwave_tpu.runtime.fused_step import (
    FusedChainExecutor,
    FusedTwoInputExecutor,
    expand_fused,
    fuse_chain,
    fuse_pipeline,
    fused_fragments,
    fusion_refusals,
)

Q5_SQL = (
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start"
)


# ---------------------------------------------------------------------------
# fused-vs-interpreted twins (bit-identity per barrier)
# ---------------------------------------------------------------------------


def _drive_q5(q5, *, fuse, watermarks, epochs=4, chunks_per_epoch=3):
    if fuse:
        wrappers = fuse_pipeline(q5.pipeline, label="q5")
        assert len(wrappers) == 1 and wrappers[0].covers_whole_chain
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=5_000))
    snaps, mx = [], 0
    for _ in range(epochs):
        for _ in range(chunks_per_epoch):
            c = gen.next_chunks(800, 1024)["bid"]
            if c is None:
                continue
            q5.pipeline.push(c)
            mx = max(mx, int(c.to_numpy()["date_time"].max()))
        q5.pipeline.barrier()
        if watermarks:
            q5.pipeline.watermark("date_time", mx)
        snaps.append(q5.mview.snapshot())
    return snaps


@pytest.mark.parametrize("watermarks", [False, True])
def test_q5_fused_bit_identical_to_interpreted_twin(watermarks):
    mk = lambda: build_q5_lite(
        capacity=1 << 12, state_cleaning=watermarks
    )
    interp = _drive_q5(mk(), fuse=False, watermarks=watermarks)
    fused = _drive_q5(mk(), fuse=True, watermarks=watermarks)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused MV diverged from interpreted"
    assert len(interp[-1]) > 0


def _drive_q7(q7, *, fuse, epochs=4, depth=None):
    if fuse:
        wrappers = fuse_pipeline(
            q7.pipeline, label="q7", pipeline_depth=depth
        )
        # the WHOLE two-input pipeline fuses: hop -> maxagg ->
        # [bucket-masked flush] -> DynamicMaxFilter x HashJoin -> MV
        # is ONE donated program per barrier (PR 13); the old
        # epoch-batch + interpreted-join fallback is now the
        # RW_FUSED_TWO_INPUT=0 twin
        assert len(wrappers) == 1
        assert isinstance(wrappers[0], FusedTwoInputExecutor)
        assert q7.pipeline._fused is wrappers[0]
        assert wrappers[0].covers_whole_chain
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    snaps, mx = [], 0
    for _ in range(epochs):
        for _ in range(2):
            bid = gen.next_chunks(1200, 2048)["bid"]
            if bid is None:
                continue
            bid = bid.select(["auction", "bidder", "price", "date_time"])
            q7.pipeline.push_left(bid)
            q7.pipeline.push_right(bid)
            mx = max(mx, int(bid.to_numpy()["date_time"].max()))
        q7.pipeline.barrier()
        q7.pipeline.watermark("date_time", mx)
        snaps.append(q7.mview.snapshot())
    return snaps


def test_q7_fused_bit_identical_to_interpreted_twin():
    mk = lambda: build_q7(
        capacity=1 << 13,
        agg_capacity=1 << 11,
        filter_capacity=1 << 11,
        out_cap=1 << 11,
    )
    interp = _drive_q7(mk(), fuse=False)
    fused = _drive_q7(mk(), fuse=True)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused q7 MV diverged"


def _drive_q8(q8, *, fuse, epochs=4, depth=None):
    if fuse:
        wrappers = fuse_pipeline(
            q8.pipeline, label="q8", pipeline_depth=depth
        )
        # dedup x join -> MV: one donated two-input program per barrier
        assert len(wrappers) == 1
        assert isinstance(wrappers[0], FusedTwoInputExecutor)
        assert wrappers[0].covers_whole_chain
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    snaps = []
    for _ in range(epochs):
        for _ in range(2):
            ev = gen.next_chunks(3000, 8192)
            p, a = ev["person"], ev["auction"]
            if p is not None:
                q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
            if a is not None:
                q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()
        snaps.append(q8.mview.snapshot())
    return snaps


def test_q8_fused_bit_identical_to_interpreted_twin():
    mk = lambda: build_q8(capacity=1 << 12, out_cap=1 << 11)
    interp = _drive_q8(mk(), fuse=False)
    fused = _drive_q8(mk(), fuse=True)
    for e, (a, b) in enumerate(zip(interp, fused)):
        assert a == b, f"epoch {e}: fused q8 MV diverged"
    assert len(interp[-1]) > 0


# ---------------------------------------------------------------------------
# dispatch-wall evidence: ONE program per barrier, attributed
# ---------------------------------------------------------------------------


def test_fused_q5_one_dispatch_per_barrier_with_fused_label():
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    bid = gen.next_chunks(2000, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    epoch()
    epoch()  # warm: compiles + growth transitions
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        for _ in range(3):
            base = PROFILER.total_dispatches()
            epoch()
            per.append(PROFILER.total_dispatches() - base)
        counts = PROFILER.dispatch_counts()
    finally:
        PROFILER.disable()
        PROFILER.reset()
    # steady state: the whole hop->agg->flush->MV barrier is ONE
    # Python-level device dispatch, attributed to the fused fragment
    assert per == [1.0, 1.0, 1.0], per
    assert counts.get("fused:q5", 0) >= 3, counts


def test_fused_fragments_report_shapes():
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    rep = fused_fragments(q5.pipeline)
    assert rep["count"] == 1 and rep["whole_chain"] is True
    assert rep["fragments"] == ["q5[3]"]


def test_no_orphaned_state_buffers_across_fused_barriers():
    """Donation contract: steady-state fused barriers must not leak
    device buffers (the donated state is consumed, the returned state
    replaces it — live-array count stays flat)."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    bid = gen.next_chunks(500, 512)["bid"].select(["auction", "date_time"])

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    for _ in range(3):  # warm: compiles + capacity transitions
        epoch()
    counts = []
    for _ in range(4):
        epoch()
        counts.append(len(jax.live_arrays()))
    assert max(counts) - min(counts) <= 2, (
        f"live device arrays grew across fused barriers: {counts}"
    )


# ---------------------------------------------------------------------------
# wrapper mechanics
# ---------------------------------------------------------------------------


def _bid_chunk(gen, n=400, cap=512):
    c = None
    while c is None:
        c = gen.next_chunks(n, cap)["bid"]
    return c.select(["auction", "date_time"])


def test_fused_flush_rounds_cover_small_out_cap():
    """Regression (code-review finding): the fused flush-round count
    must be derived AFTER the buffered epoch lands in the dirty bound.
    With out_cap far below the epoch's distinct groups, an early round
    count silently dropped every group past the first round — the
    fused MV diverged from the interpreted twin permanently."""
    mk = lambda: build_q5_lite(capacity=1 << 10, state_cleaning=False)

    def drive(q5, fuse):
        q5.agg.out_cap = 128  # << distinct (auction, window) groups
        if fuse:  # fuse AFTER sizing: the plan captures out_cap
            fuse_pipeline(q5.pipeline)
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
        for _ in range(2):
            q5.pipeline.push(_bid_chunk(gen, 800, 1024))
            q5.pipeline.barrier()
        return q5.mview.snapshot()

    interp = drive(mk(), fuse=False)
    fused = drive(mk(), fuse=True)
    assert len(interp) > 128  # the workload actually exceeds out_cap
    assert fused == interp


def test_signature_change_mid_epoch_flushes_buffer():
    mk = lambda: build_q5_lite(capacity=1 << 10, state_cleaning=False)
    a, b = mk(), mk()
    fuse_pipeline(b.pipeline)
    gen1 = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    gen2 = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    for q5, gen in ((a, gen1), (b, gen2)):
        c1 = _bid_chunk(gen, 400, 512)
        c2 = _bid_chunk(gen, 900, 1024)  # different capacity: new sig
        q5.pipeline.push(c1)
        q5.pipeline.push(c2)
        q5.pipeline.push(_bid_chunk(gen, 400, 512))
        q5.pipeline.barrier()
    assert a.mview.snapshot() == b.mview.snapshot()


def test_overflow_latch_still_raises_at_finish_barrier():
    """The agg's MAX_PROBE overflow latch rides the fused program's
    packed scalars and raises at the wrapper's finish_barrier — same
    raise point as the interpreted path."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    (wrapper,) = fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    q5.pipeline.push(_bid_chunk(gen))
    q5.pipeline.barrier()
    # force the latch: a poisoned dropped flag must surface as the
    # hash-table overflow error when the staged scalars materialize
    q5.agg.dropped = jnp.ones((), jnp.bool_)
    with pytest.raises(RuntimeError, match="overflowed MAX_PROBE"):
        q5.pipeline.push(_bid_chunk(gen))
        q5.pipeline.barrier()
    assert wrapper.agg is q5.agg  # members stayed the system of record


def test_fuse_chain_falls_back_around_unfusible_ops():
    """Host-bound / opaque members break the run: interpretation is
    the automatic per-run fallback, not a process-wide switch — and an
    agg whose flush exits to an interpreted consumer epoch-batches
    instead of fusing (the exact-sliced flush stays)."""
    from risingwave_tpu.executors.base import Executor
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.ops.agg import AggCall

    class HostOp(Executor):  # no pure_step -> not fusible
        pass

    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("count_star", None, "n"),),
        schema_dtypes={"k": jnp.int64},
        capacity=64,
        out_cap=32,
    )
    host = HostOp()
    out = fuse_chain([host, agg], label="t")
    assert out[0] is host
    assert isinstance(out[1], EpochBatchedAggExecutor)
    assert out[1].agg is agg
    # pure-only runs stay interpreted unless defer_pure opts in
    from risingwave_tpu.executors.hop_window import HopWindowExecutor

    hop = HopWindowExecutor("t", 10, 10)
    assert fuse_chain([hop, host], label="t") == [hop, host]


def test_expand_fused_exposes_members_for_padding_and_governor():
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    exs = expand_fused(q5.pipeline.executors)
    names = [type(e).__name__ for e in exs]
    assert "HashAggExecutor" in names
    assert "DeviceMaterializeExecutor" in names
    assert all(not isinstance(e, FusedChainExecutor) for e in exs)


def test_governor_bucket_pin_holds_fused_shapes_steady():
    """After a governor pin, steady fused barriers mint ZERO new
    compiled programs (exactly the recompile-storm throttle the fused
    step needs: pinned buckets = closed shape set)."""
    from risingwave_tpu.analysis.jax_sanitizer import RecompileWatch

    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    bid = _bid_chunk(gen)

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    epoch()
    epoch()
    pin_agg = q5.agg.pin_max_bucket()
    pin_mv = q5.mview.pin_max_bucket()
    assert pin_agg["pinned_cap"] == q5.agg.table.capacity
    assert pin_mv["pinned_cap"] == q5.mview.table.capacity
    watch = RecompileWatch()
    watch.snapshot()
    for _ in range(3):
        epoch()
    assert watch.deltas() == {}, watch.deltas()


# ---------------------------------------------------------------------------
# graph runtime: auto-fusion, rebuild re-fuses, recovery with fusion armed
# ---------------------------------------------------------------------------


def _catalog_factory(capacity=1 << 11):
    from risingwave_tpu.sql import Catalog, StreamPlanner

    catalog = Catalog({"bid": BID_SCHEMA})
    return lambda: StreamPlanner(catalog, capacity=capacity)


def _graph_mv(parallelism=1):
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv

    return graph_planned_mv(
        _catalog_factory(), Q5_SQL, parallelism=parallelism
    )


def _fused_in_actors(gp):
    return [
        e
        for a in gp.graph.actors
        for e in a.executors
        if isinstance(e, FusedChainExecutor)
    ]


def test_graph_actors_fuse_by_default_and_rebuild_refuses():
    mv = _graph_mv()
    try:
        assert _fused_in_actors(mv.pipeline), "graph chain did not fuse"
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
        bid = _bid_chunk(gen, 600, 1024)
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        before = mv.mview.snapshot()
        assert before
        # rebuild (the recovery path's actor replacement): fresh actors
        # around the SAME executor objects must RE-FUSE automatically
        mv.pipeline.rebuild()
        assert _fused_in_actors(mv.pipeline), "rebuilt actors lost fusion"
        assert mv.mview.snapshot() == before  # state survived the rebuild
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        after = mv.mview.snapshot()
        assert set(after) == set(before)
        assert all(after[k][0] == 2 * before[k][0] for k in before)
    finally:
        mv.pipeline.close()


class _PoisonOnce:
    """Raises at the first armed barrier, then behaves forever after
    (the transient-fault model of the recovery suites)."""

    def __init__(self):
        self.armed = False
        self.fired = 0

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self, b):
        if self.armed:
            self.armed = False
            self.fired += 1
            raise RuntimeError("poisoned epoch (injected)")
        return []

    def on_watermark(self, wm):
        return wm, []

    def emit_watermark(self):
        return None

    def finish_barrier(self):
        return None

    def pure_step(self):
        return None


def test_actor_kill_recovery_with_fusion_armed():
    """Actor-kill chaos with the fused step armed: the poisoned
    barrier kills the actor thread, the watchdog rebuilds the graph,
    the rebuilt fragment RE-FUSES around the restored state, and the
    stream continues exact (the serial interpreted twin is the
    oracle)."""
    from risingwave_tpu.runtime.fragmenter import GraphPipeline
    from risingwave_tpu.runtime.graph import FragmentSpec
    from risingwave_tpu.runtime.runtime import StreamingRuntime
    from risingwave_tpu.storage.object_store import MemObjectStore

    poison = _PoisonOnce()
    q5 = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    chain = [poison] + list(q5.pipeline.executors)
    gp = GraphPipeline(
        [FragmentSpec("gq5", lambda i, ch=tuple(chain): list(ch))],
        {"single": "gq5"},
        "gq5",
        [q5.agg, q5.mview],
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("gq5", gp)
    twin = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    first_graph = gp.graph
    try:
        assert _fused_in_actors(gp), "poisoned chain's fusible run lost"
        for epoch in range(5):
            chunk = _bid_chunk(gen, 500, 1024)
            if epoch == 2:
                poison.armed = True
            for _attempt in range(4):
                rt.push("gq5", chunk)
                before = rt.mgr.max_committed_epoch
                rt.barrier()
                if rt.mgr.max_committed_epoch > before:
                    break
            else:
                raise AssertionError("epoch never committed")
            twin.pipeline.push(chunk)
            twin.pipeline.barrier()
        assert rt.auto_recoveries == 1 and poison.fired == 1
        assert gp.graph is not first_graph  # actors were rebuilt
        assert _fused_in_actors(gp), "recovered graph lost fusion"
        assert q5.mview.snapshot() == twin.mview.snapshot()
    finally:
        gp.close()


# ---------------------------------------------------------------------------
# two-input fusion (PR 13): q7/q8 whole-pipeline programs, masked-lane
# padding proofs, K-barrier pipelining, recovery, refusal provenance
# ---------------------------------------------------------------------------

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk


def test_two_input_fallback_twin_bit_identical(monkeypatch):
    """RW_FUSED_TWO_INPUT=0: the pre-PR-13 per-chain fallback
    (epoch-batched agg side, interpreted join) armed on q7 must stay
    bit-identical — and the join-fed MV tail now FUSES under the
    lattice-compatibility rule (the old hard carve-out is gone: the
    join's fixed out_cap emission is a closed shape family)."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )

    mk = lambda: build_q7(
        capacity=1 << 13,
        agg_capacity=1 << 11,
        filter_capacity=1 << 11,
        out_cap=1 << 11,
    )
    interp = _drive_q7(mk(), fuse=False)
    monkeypatch.setenv("RW_FUSED_TWO_INPUT", "0")
    q7 = mk()
    wrappers = fuse_pipeline(q7.pipeline, label="q7")
    assert q7.pipeline._fused is None
    assert any(
        isinstance(e, EpochBatchedAggExecutor) for e in q7.pipeline.right
    )
    # the satellite bugfix: the MV tail fed by the (fixed-emission)
    # join fuses instead of staying interpreted
    assert len(wrappers) == 1 and isinstance(
        wrappers[0], FusedChainExecutor
    )
    assert wrappers[0].members == [q7.mview]
    monkeypatch.delenv("RW_FUSED_TWO_INPUT")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    snaps, mx = [], 0
    for _ in range(4):
        for _ in range(2):
            bid = gen.next_chunks(1200, 2048)["bid"]
            if bid is None:
                continue
            bid = bid.select(["auction", "bidder", "price", "date_time"])
            q7.pipeline.push_left(bid)
            q7.pipeline.push_right(bid)
            mx = max(mx, int(bid.to_numpy()["date_time"].max()))
        q7.pipeline.barrier()
        q7.pipeline.watermark("date_time", mx)
        snaps.append(q7.mview.snapshot())
    for e, (a, b) in enumerate(zip(interp, snaps)):
        assert a == b, f"epoch {e}: fallback q7 MV diverged"


def test_two_input_refusal_records_provenance():
    """An unbucketed join (the RW-E803 wedge twin) must be REFUSED
    with RW-E807 provenance — never a silent interpret fallback."""
    fusion_refusals(clear=True)
    q7 = build_q7(capacity=1 << 10, bucketed=False)
    fuse_pipeline(q7.pipeline, label="q7twin")
    assert q7.pipeline._fused is None
    recs = fusion_refusals()
    assert any(
        r["code"] == "RW-E807"
        and r["fragment"] == "q7twin"
        and "lattice" in r["message"]
        for r in recs
    ), recs


def test_masked_lane_padding_inert():
    """The join's probe/build kernels must treat padded (invalid)
    lanes as provably inert: the same logical rows arriving at an
    exact-full 2^k capacity and padded into the one-over 2^(k+1)
    bucket produce identical emissions and identical downstream MVs —
    the proof that lattice-padded flush lanes cost one masked device
    op, not wrong answers (the pre-bucketing '80x slower exact-slice'
    contract is retired)."""
    from risingwave_tpu.executors.hash_join import HashJoinExecutor
    import jax.numpy as jnp

    def mk_join():
        return HashJoinExecutor(
            left_keys=("w", "p"),
            right_keys=("mw", "mp"),
            left_dtypes={"w": jnp.int64, "p": jnp.int64, "b": jnp.int64},
            right_dtypes={"mw": jnp.int64, "mp": jnp.int64},
            capacity=1 << 8,
            fanout=4,
            out_cap=1 << 6,
        )

    k = 3  # 2^3 = 8 rows
    n = 1 << k
    left = {
        "w": np.arange(n, dtype=np.int64),
        "p": np.full(n, 7, np.int64),
        "b": np.arange(n, dtype=np.int64) + 100,
    }
    right = {
        "mw": np.arange(n, dtype=np.int64),
        "mp": np.full(n, 7, np.int64),
    }

    def rows_of(chunks):
        out = []
        for c in chunks:
            d = c.to_numpy(with_ops=True)
            sel = np.flatnonzero(np.asarray(c.valid))
            out.extend(
                tuple(int(d[nm][i]) for nm in sorted(d))
                for i in sel
            )
        return sorted(out)

    emitted = {}
    for cap in (n, 2 * n):  # exact-full 2^k vs one-over bucket 2^k+1
        j = mk_join()
        j.apply_left(StreamChunk.from_numpy(left, n))
        outs = j.apply_right(StreamChunk.from_numpy(right, cap))
        j.on_barrier(None)
        emitted[cap] = rows_of(outs)
    assert emitted[n] == emitted[2 * n]
    assert len(emitted[n]) == n  # every pair matched exactly once


def test_two_input_flush_rounds_exact_and_one_over():
    """Fused q7 flush lanes: an epoch with dirty groups exactly filling
    one flush round (2^k) and one with a single group over (2^k + 1,
    padded into a second, mostly-masked round) must both be
    bit-identical to the interpreted twin — masked trailing rounds are
    no-ops, never data."""

    def bid_chunk(rows, cap=64):
        cols = {
            "auction": np.array([r[0] for r in rows], np.int64),
            "bidder": np.array([r[1] for r in rows], np.int64),
            "price": np.array([r[2] for r in rows], np.int64),
            "date_time": np.array([r[3] for r in rows], np.int64),
        }
        return StreamChunk.from_numpy(cols, cap)

    def drive(fuse, n_windows):
        q7 = build_q7(capacity=1 << 10, fanout=8, out_cap=1 << 10)
        q7.agg.out_cap = 8  # flush drains 8 dirty groups per round
        if fuse:
            (w,) = fuse_pipeline(q7.pipeline, label="q7small")
            assert w.plan.right.agg.out_cap == 8
        snaps = []
        for epoch in range(2):
            rows = [
                (w_, 10 + w_, 100 + w_ + epoch, w_ * 10_000 + 5)
                for w_ in range(n_windows)
            ]
            c = bid_chunk(rows)
            q7.pipeline.push_left(c)
            q7.pipeline.push_right(c)
            q7.pipeline.barrier()
            snaps.append(q7.mview.snapshot())
        return snaps

    for n_windows in (8, 9):  # 2^k exact-full, 2^k + 1 one-over
        a = drive(False, n_windows)
        b = drive(True, n_windows)
        assert a == b, f"{n_windows} windows: fused flush diverged"
        assert len(a[-1]) == n_windows


def test_fused_two_input_one_dispatch_per_barrier():
    """Steady state: the whole q8 barrier — dedup x join x MV — is ONE
    device dispatch, attributed ``fused:<fragment>`` (q7's twin check
    lives in perf_gate --smoke; 31 -> 1 on this image)."""
    q8 = build_q8(capacity=1 << 12, out_cap=1 << 11)
    fuse_pipeline(q8.pipeline, label="q8")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))

    def epoch():
        ev = gen.next_chunks(2000, 4096)
        p, a = ev["person"], ev["auction"]
        if p is not None:
            q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
        if a is not None:
            q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()

    for _ in range(4):
        epoch()  # warm: compiles + growth transitions
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        for _ in range(3):
            base = PROFILER.total_dispatches()
            epoch()
            per.append(PROFILER.total_dispatches() - base)
        counts = PROFILER.dispatch_counts()
    finally:
        PROFILER.disable()
        PROFILER.reset()
    assert per == [1.0, 1.0, 1.0], per
    assert counts.get("fused:q8", 0) >= 3, counts


def test_two_input_donation_census_flat():
    """Donation contract: steady fused two-input barriers must not
    leak device buffers (donated state consumed, returned state
    replaces it)."""
    q8 = build_q8(capacity=1 << 10, out_cap=1 << 9)
    fuse_pipeline(q8.pipeline, label="q8")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))

    def epoch():
        ev = gen.next_chunks(800, 1024)
        p, a = ev["person"], ev["auction"]
        if p is not None:
            q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
        if a is not None:
            q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()

    for _ in range(4):
        epoch()
    counts = []
    for _ in range(4):
        epoch()
        counts.append(len(jax.live_arrays()))
    assert max(counts) - min(counts) <= 4, (
        f"live device arrays grew across fused two-input barriers: "
        f"{counts}"
    )


def _feed_q8(q8, gen, n):
    for _ in range(n):
        chunks = gen.next_chunks(2000, 2048)
        if chunks["person"] is not None:
            q8.pipeline.push_left(
                chunks["person"].select(["id", "name", "date_time"])
            )
        if chunks["auction"] is not None:
            q8.pipeline.push_right(
                chunks["auction"].select(["seller", "date_time"])
            )
        q8.pipeline.barrier()


def test_two_input_recovery_refuses():
    """Kill-and-recover with the two-input program armed: members stay
    the system of record, so checkpoint/restore work unchanged and a
    FRESH build re-fuses into the same compiled program (value-hashable
    plan statics)."""
    from risingwave_tpu.connectors.nexmark import NexmarkGenerator
    from risingwave_tpu.storage import CheckpointManager, MemObjectStore

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    dicts = NexmarkGenerator.make_dictionaries()
    gen = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)

    mk = lambda: build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    q8 = mk()
    fuse_pipeline(q8.pipeline, label="q8")
    for _ in range(3):
        _feed_q8(q8, gen, 1)
        mgr.commit_epoch(q8.pipeline.epoch, q8.pipeline.executors)
    snap = q8.mview.snapshot()
    assert len(snap) > 20

    q8b = mk()
    CheckpointManager(store).recover(q8b.pipeline.executors)
    wrappers = fuse_pipeline(q8b.pipeline, label="q8")
    assert len(wrappers) == 1  # restored members re-fuse
    assert q8b.mview.snapshot() == snap

    gen_b = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    for _ in range(3):
        gen_b.next_chunks(2000, 2048)
    _feed_q8(q8, gen, 2)
    _feed_q8(q8b, gen_b, 2)
    assert q8b.mview.snapshot() == q8.mview.snapshot()


@pytest.mark.parametrize("depth", [2, 4])
def test_pipeline_depth_twins_and_checkpoint_boundary(depth):
    """K-barrier pipelining: K in {1, K} produce bit-identical MVs at
    EVERY barrier; mid-window barriers defer the blocking scalar read
    (the host leaves the steady state), the K-boundary drains; and a
    checkpoint taken at the K-boundary recovers exactly."""
    from risingwave_tpu.connectors.nexmark import NexmarkGenerator
    from risingwave_tpu.storage import CheckpointManager, MemObjectStore

    dicts = NexmarkGenerator.make_dictionaries()

    def drive(d, nb=8):
        gen = NexmarkGenerator(
            NexmarkConfig(first_event_rate=10_000), dictionaries=dicts
        )
        q8 = build_q8(capacity=1 << 12, out_cap=1 << 11)
        (w,) = fuse_pipeline(
            q8.pipeline, label="q8", pipeline_depth=d
        )
        assert w.depth == d
        snaps = []
        for i in range(nb):
            _feed_q8(q8, gen, 1)
            # mid-window barriers hold their staged pack (no blocking
            # read); the K-boundary drains them all
            expect = 0 if (i + 1) % d == 0 else (i + 1) % d
            assert len(w._pending) == expect, (i, d, len(w._pending))
            snaps.append(q8.mview.snapshot())
        return q8, w, snaps

    _q1, _w1, s1 = drive(1)
    q8k, wk, sk = drive(depth)
    for e in range(8):
        assert s1[e] == sk[e], f"K={depth} diverged at barrier {e}"

    # checkpoint at the K-boundary (pending drained), then recover
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    assert wk._pending == []  # 8 % depth == 0: boundary just drained
    mgr.commit_epoch(q8k.pipeline.epoch, q8k.pipeline.executors)
    q8r = build_q8(capacity=1 << 12, out_cap=1 << 11)
    CheckpointManager(store).recover(q8r.pipeline.executors)
    assert q8r.mview.snapshot() == sk[-1]


def test_two_input_governor_pin_holds_shapes_steady():
    """After a governor pin on every two-input member, steady fused
    barriers mint ZERO new compiled programs."""
    from risingwave_tpu.analysis.jax_sanitizer import RecompileWatch

    q8 = build_q8(capacity=1 << 10, out_cap=1 << 9)
    fuse_pipeline(q8.pipeline, label="q8")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))

    def epoch():
        ev = gen.next_chunks(800, 1024)
        p, a = ev["person"], ev["auction"]
        if p is not None:
            q8.pipeline.push_left(p.select(["id", "name", "date_time"]))
        if a is not None:
            q8.pipeline.push_right(a.select(["seller", "date_time"]))
        q8.pipeline.barrier()

    epoch()
    epoch()
    for ex in expand_fused([q8.pipeline._fused]):
        pin = getattr(ex, "pin_max_bucket", None)
        if pin is not None:
            pin()
    watch = RecompileWatch()
    watch.snapshot()
    for _ in range(3):
        epoch()
    assert watch.deltas() == {}, watch.deltas()


def test_two_input_overflow_latch_raises_at_finish():
    """A poisoned member latch must surface at finish_barrier through
    the packed scalar lane — same raise point as interpreted."""
    import jax.numpy as jnp

    q8 = build_q8(capacity=1 << 10, out_cap=1 << 9)
    (w,) = fuse_pipeline(q8.pipeline, label="q8")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    ev = gen.next_chunks(800, 1024)
    q8.pipeline.push_left(ev["person"].select(["id", "name", "date_time"]))
    q8.pipeline.barrier()
    dedup = q8.pipeline.left[1]
    dedup._dropped = jnp.ones((), jnp.bool_)
    with pytest.raises(RuntimeError, match="dedup table overflowed"):
        q8.pipeline.push_left(
            ev["person"].select(["id", "name", "date_time"])
        )
        q8.pipeline.barrier()
    assert w.l_stateful is dedup  # members stayed the system of record
