"""Point/range reads over the committed SST set (VERDICT r2 #7;
StateStore::get/iter, store.rs:218,298): bloom-pruned per-key newest-
wins resolution without full-table materialization."""

import numpy as np

from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager, StateDelta


def _delta(table, keys, vals, tomb, epoch=None):
    return StateDelta(
        table,
        {"k0": np.asarray(keys[0], np.int64), "k1": np.asarray(keys[1], np.int64)},
        {"v": np.asarray(vals, np.int64)},
        np.asarray(tomb, bool),
        ("k0", "k1"),
    )


def _mgr():
    mgr = CheckpointManager(MemObjectStore(), compact_at=100)
    e = 1 << 16
    # epoch 1: keys (0..9, 0) -> v=k*10
    mgr.commit_staged(
        e,
        [_delta("t", (np.arange(10), np.zeros(10)), np.arange(10) * 10,
                np.zeros(10))],
    )
    # epoch 2: overwrite k=3 -> 999; tombstone k=5; new key (100, 7)
    mgr.commit_staged(
        2 * e,
        [_delta("t", ([3, 5, 100], [0, 0, 7]), [999, 0, 777],
                [False, True, False])],
    )
    return mgr


def test_point_reads_newest_wins_and_tombstones():
    mgr = _mgr()
    found, vals = mgr.get_rows(
        "t",
        {
            "k0": np.asarray([0, 3, 5, 100, 42], np.int64),
            "k1": np.asarray([0, 0, 0, 7, 0], np.int64),
        },
    )
    assert found.tolist() == [True, True, False, True, False]
    assert vals["v"][[0, 1, 3]].tolist() == [0, 999, 777]


def test_point_reads_match_full_merge():
    mgr = _mgr()
    keys, vals = mgr.read_table("t")  # the full-merge oracle
    found, got = mgr.get_rows("t", keys)
    assert found.all()
    assert got["v"].tolist() == vals["v"].tolist()


def test_scan_prefix():
    mgr = _mgr()
    keys, vals = mgr.scan_prefix("t", {"k1": 0})
    got = dict(zip(keys["k0"].tolist(), vals["v"].tolist()))
    # k=5 tombstoned, k=3 overwritten, (100,7) excluded by prefix
    want = {k: k * 10 for k in range(10) if k != 5}
    want[3] = 999
    assert got == want

    keys, vals = mgr.scan_prefix("t", {"k1": 7})
    assert keys["k0"].tolist() == [100] and vals["v"].tolist() == [777]


def test_reads_survive_compaction():
    mgr = _mgr()
    assert mgr.compact_at == 100
    mgr.compact_at = 2
    assert mgr.compact_once("t", 3 << 16)
    found, vals = mgr.get_rows(
        "t", {"k0": np.asarray([3, 5], np.int64),
              "k1": np.asarray([0, 0], np.int64)}
    )
    assert found.tolist() == [True, False]
    assert vals["v"][0] == 999
