"""State >> HBM: evict durable groups, fold them back on next touch
(VERDICT r2 missing #6; reference: LRU state-table caches over Hummock,
hash_agg.rs:49 + compute memory controller)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager
from risingwave_tpu.types import Op

DT = {"k": jnp.int64, "v": jnp.int64}
CAP = 64


def _chunk(rows):
    return StreamChunk.from_numpy(
        {
            "k": np.asarray([r[0] for r in rows], np.int64),
            "v": np.asarray([r[1] for r in rows], np.int64),
        },
        CAP,
        ops=np.asarray([r[2] for r in rows], np.int32),
    )


def _mk(cap=1 << 12):
    return HashAggExecutor(
        group_keys=("k",),
        calls=(
            AggCall("count_star", None, "cnt"),
            AggCall("sum", "v", "s"),
        ),
        schema_dtypes=DT,
        capacity=cap,
        out_cap=1 << 10,
        table_id="cold1",
    )


def _replay(snap, chunks):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            key = (int(d["k"][i]),)
            if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                snap.pop(key, None)
            else:
                row = []
                for n in ("cnt", "s"):
                    nl = d.get(n + "__null")
                    row.append(None if nl is not None and nl[i] else int(d[n][i]))
                snap[key] = tuple(row)
    return snap


def test_evict_then_merge_on_return():
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = _mk()
    ex.cold_reader = lambda keys: mgr.get_rows("cold1", keys)
    snap = {}

    # 500 groups, checkpoint -> all durable
    rows = [(k, k * 3, Op.INSERT) for k in range(500)]
    for at in range(0, len(rows), CAP):
        _replay(snap, ex.apply(_chunk(rows[at : at + CAP])))
    _replay(snap, ex.on_barrier(None))
    mgr.commit_epoch(1 << 16, [ex])

    before = ex.state_nbytes()
    evicted = ex.evict_cold()
    assert evicted == 500
    assert ex.state_nbytes() < before  # capacity shrank: HBM freed
    assert int(ex.table.occupancy()) == 0

    # touch 40 evicted groups (+ some deletes) and 10 brand-new ones:
    # merged results must continue exactly from the durable state
    upd = [(k, 1, Op.INSERT) for k in range(40)]
    upd += [(k, k * 3, Op.DELETE) for k in range(5)]  # retract cold rows
    upd += [(k, 7, Op.INSERT) for k in range(1000, 1010)]
    _replay(snap, ex.apply(_chunk(upd[:CAP])))
    _replay(snap, ex.apply(_chunk(upd[CAP:])))
    _replay(snap, ex.on_barrier(None))

    want = {}
    for k in range(500):
        cnt, s = 1, k * 3
        if k < 40:
            cnt, s = cnt + 1, s + 1
        if k < 5:
            cnt, s = cnt - 1, s - k * 3
        want[(k,)] = (cnt, s)
    for k in range(1000, 1010):
        want[(k,)] = (1, 7)
    assert snap == want

    # checkpoint again, kill, recover: merged state must round-trip
    mgr.commit_epoch(2 << 16, [ex])
    ex2 = _mk()
    CheckpointManager(store).recover([ex2])
    snap2 = {}
    _replay(snap2, ex2.on_barrier(None))  # nothing dirty -> no emissions
    assert snap2 == {}
    _replay(snap2, ex2.apply(_chunk([(3, 100, Op.INSERT)])))
    _replay(snap2, ex2.on_barrier(None))
    assert snap2[(3,)][0] == want[(3,)][0] + 1


def test_runtime_memory_budget_triggers_eviction():
    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=False,
                          memory_budget_bytes=1)  # absurdly small
    agg = _mk()
    mv = MaterializeExecutor(pk=("k",), columns=("cnt", "s"),
                             table_id="cold1.mv")
    rt.register("f", Pipeline([agg, mv]))
    rt.push("f", _chunk([(k, k, Op.INSERT) for k in range(50)]))
    rt.barrier()  # checkpoint -> durable -> budget forces eviction
    assert int(agg.table.occupancy()) == 0  # everything evicted
    rt.push("f", _chunk([(7, 5, Op.INSERT)]))
    rt.barrier()
    assert mv.snapshot()[(7,)] == (2, 12)  # merged back exactly


def test_cold_min_max_merge_append_only():
    """Extremes merge in the order-key domain on return from cold."""
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("min", "v", "mn"), AggCall("max", "v", "mx")),
        schema_dtypes=DT, capacity=1 << 10, out_cap=1 << 9,
        table_id="cold1",
    )
    ex.cold_reader = lambda keys: mgr.get_rows("cold1", keys)
    snap = {}

    def rep(chunks):
        for c in chunks:
            d = c.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                key = (int(d["k"][i]),)
                if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                    snap.pop(key, None)
                else:
                    snap[key] = (int(d["mn"][i]), int(d["mx"][i]))

    rep(ex.apply(_chunk([(1, 50, Op.INSERT), (1, 10, Op.INSERT)])))
    rep(ex.on_barrier(None))
    mgr.commit_epoch(1 << 16, [ex])
    assert ex.evict_cold() == 1

    rep(ex.apply(_chunk([(1, 30, Op.INSERT), (1, 99, Op.INSERT)])))
    rep(ex.on_barrier(None))
    assert snap[(1,)] == (10, 99)  # cold min=10 survives, new max=99
