"""State >> HBM: evict durable groups, fold them back on next touch
(VERDICT r2 missing #6; reference: LRU state-table caches over Hummock,
hash_agg.rs:49 + compute memory controller)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager
from risingwave_tpu.types import Op

DT = {"k": jnp.int64, "v": jnp.int64}
CAP = 64


def _chunk(rows):
    return StreamChunk.from_numpy(
        {
            "k": np.asarray([r[0] for r in rows], np.int64),
            "v": np.asarray([r[1] for r in rows], np.int64),
        },
        CAP,
        ops=np.asarray([r[2] for r in rows], np.int32),
    )


def _mk(cap=1 << 12):
    return HashAggExecutor(
        group_keys=("k",),
        calls=(
            AggCall("count_star", None, "cnt"),
            AggCall("sum", "v", "s"),
        ),
        schema_dtypes=DT,
        capacity=cap,
        out_cap=1 << 10,
        table_id="cold1",
    )


def _replay(snap, chunks):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            key = (int(d["k"][i]),)
            if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                snap.pop(key, None)
            else:
                row = []
                for n in ("cnt", "s"):
                    nl = d.get(n + "__null")
                    row.append(None if nl is not None and nl[i] else int(d[n][i]))
                snap[key] = tuple(row)
    return snap


def test_evict_then_merge_on_return():
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = _mk()
    ex.cold_reader = lambda keys: mgr.get_rows("cold1", keys)
    snap = {}

    # 500 groups, checkpoint -> all durable
    rows = [(k, k * 3, Op.INSERT) for k in range(500)]
    for at in range(0, len(rows), CAP):
        _replay(snap, ex.apply(_chunk(rows[at : at + CAP])))
    _replay(snap, ex.on_barrier(None))
    mgr.commit_epoch(1 << 16, [ex])

    before = ex.state_nbytes()
    evicted = ex.evict_cold()
    assert evicted == 500
    assert ex.state_nbytes() < before  # capacity shrank: HBM freed
    assert int(ex.table.occupancy()) == 0

    # touch 40 evicted groups (+ some deletes) and 10 brand-new ones:
    # merged results must continue exactly from the durable state
    upd = [(k, 1, Op.INSERT) for k in range(40)]
    upd += [(k, k * 3, Op.DELETE) for k in range(5)]  # retract cold rows
    upd += [(k, 7, Op.INSERT) for k in range(1000, 1010)]
    _replay(snap, ex.apply(_chunk(upd[:CAP])))
    _replay(snap, ex.apply(_chunk(upd[CAP:])))
    _replay(snap, ex.on_barrier(None))

    want = {}
    for k in range(500):
        cnt, s = 1, k * 3
        if k < 40:
            cnt, s = cnt + 1, s + 1
        if k < 5:
            cnt, s = cnt - 1, s - k * 3
        want[(k,)] = (cnt, s)
    for k in range(1000, 1010):
        want[(k,)] = (1, 7)
    assert snap == want

    # checkpoint again, kill, recover: merged state must round-trip
    mgr.commit_epoch(2 << 16, [ex])
    ex2 = _mk()
    CheckpointManager(store).recover([ex2])
    snap2 = {}
    _replay(snap2, ex2.on_barrier(None))  # nothing dirty -> no emissions
    assert snap2 == {}
    _replay(snap2, ex2.apply(_chunk([(3, 100, Op.INSERT)])))
    _replay(snap2, ex2.on_barrier(None))
    assert snap2[(3,)][0] == want[(3,)][0] + 1


def test_runtime_memory_budget_triggers_eviction():
    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=False,
                          memory_budget_bytes=1)  # absurdly small
    agg = _mk()
    mv = MaterializeExecutor(pk=("k",), columns=("cnt", "s"),
                             table_id="cold1.mv")
    rt.register("f", Pipeline([agg, mv]))
    rt.push("f", _chunk([(k, k, Op.INSERT) for k in range(50)]))
    rt.barrier()  # checkpoint -> durable -> budget forces eviction
    assert int(agg.table.occupancy()) == 0  # everything evicted
    rt.push("f", _chunk([(7, 5, Op.INSERT)]))
    rt.barrier()
    assert mv.snapshot()[(7,)] == (2, 12)  # merged back exactly


def test_cold_min_max_merge_append_only():
    """Extremes merge in the order-key domain on return from cold."""
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("min", "v", "mn"), AggCall("max", "v", "mx")),
        schema_dtypes=DT, capacity=1 << 10, out_cap=1 << 9,
        table_id="cold1",
    )
    ex.cold_reader = lambda keys: mgr.get_rows("cold1", keys)
    snap = {}

    def rep(chunks):
        for c in chunks:
            d = c.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                key = (int(d["k"][i]),)
                if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                    snap.pop(key, None)
                else:
                    snap[key] = (int(d["mn"][i]), int(d["mx"][i]))

    rep(ex.apply(_chunk([(1, 50, Op.INSERT), (1, 10, Op.INSERT)])))
    rep(ex.on_barrier(None))
    mgr.commit_epoch(1 << 16, [ex])
    assert ex.evict_cold() == 1

    rep(ex.apply(_chunk([(1, 30, Op.INSERT), (1, 99, Op.INSERT)])))
    rep(ex.on_barrier(None))
    assert snap[(1,)] == (10, 99)  # cold min=10 survives, new max=99


def test_join_cold_tier_eviction_and_fault_in():
    """Join state >> HBM (VERDICT r3 #8): durable buckets evict under a
    memory budget and fault back in when their key is touched again —
    emissions stay exact vs an unbudgeted twin, including recovery."""
    from risingwave_tpu.executors.hash_join import HashJoinExecutor

    L = {"lk": jnp.int64, "lv": jnp.int64}
    R = {"rk": jnp.int64, "rv": jnp.int64}

    def mk(tid):
        return HashJoinExecutor(
            ("lk",), ("rk",), L, R,
            capacity=1 << 10, fanout=8, out_cap=1 << 12, table_id=tid,
        )

    store = MemObjectStore()
    rt = StreamingRuntime(
        store, async_checkpoint=False, memory_budget_bytes=1
    )
    j = mk("cj")
    mv = MaterializeExecutor(
        pk=("lk", "lv", "rk", "rv"), columns=(), table_id="cj.mv"
    )
    from risingwave_tpu.runtime.pipeline import TwoInputPipeline

    rt.register("j", TwoInputPipeline([], [], j, [mv]))

    twin = mk("cj_twin")
    twin_mv = MaterializeExecutor(
        pk=("lk", "lv", "rk", "rv"), columns=(), table_id="twin.mv"
    )

    rng = np.random.default_rng(41)

    def lchunk(ks, vs):
        return StreamChunk.from_numpy(
            {"lk": np.asarray(ks, np.int64), "lv": np.asarray(vs, np.int64)},
            32,
        )

    def rchunk(ks, vs):
        return StreamChunk.from_numpy(
            {"rk": np.asarray(ks, np.int64), "rv": np.asarray(vs, np.int64)},
            32,
        )

    seen_keys = []
    for epoch in range(8):
        # revisit OLD keys often: the whole point is faulting evicted
        # buckets back in before probing/appending
        ks = [
            int(rng.choice(seen_keys))
            if seen_keys and rng.random() < 0.5
            else int(rng.integers(0, 64)) + 100 * epoch
            for _ in range(6)
        ]
        seen_keys.extend(ks)
        lvs = rng.integers(0, 9, 6).tolist()
        rvs = rng.integers(0, 9, 6).tolist()
        lc, rc = lchunk(ks, lvs), rchunk(ks, rvs)
        rt.push("j", lc, side="left")
        rt.push("j", rc, side="right")
        rt.barrier()  # budget=1 byte: evicts EVERYTHING durable
        for out in twin.apply_left(lc):
            twin_mv.apply(out)
        for out in twin.apply_right(rc):
            twin_mv.apply(out)
        twin.on_barrier(None)
        twin_mv.on_barrier(None)
        assert j._evicted["left"] or j._evicted["right"] or epoch == 0

    assert mv.snapshot() == twin_mv.snapshot()
    assert len(mv.snapshot()) > 20

    # kill + recover: evicted state lives in the store; a fresh join
    # restores EVERYTHING and continues exactly. Quiesce the old
    # node's compactor first (a killed node's compactor is dead too).
    rt.wait_compaction()
    rt2 = StreamingRuntime(store, async_checkpoint=False)
    j2 = mk("cj")
    mv2 = MaterializeExecutor(
        pk=("lk", "lv", "rk", "rv"), columns=(), table_id="cj.mv"
    )
    rt2.register("j", TwoInputPipeline([], [], j2, [mv2]), backfill=False)
    rt2.recover()
    assert mv2.snapshot() == twin_mv.snapshot()
    ks = seen_keys[:5]
    lc = lchunk(ks, [7] * 5)
    rt2.push("j", lc, side="left")
    rt2.barrier()
    for out in twin.apply_left(lc):
        twin_mv.apply(out)
    twin.on_barrier(None)
    assert mv2.snapshot() == twin_mv.snapshot()


def test_join_evicted_keys_expire_under_watermark():
    """A watermark closing a window must close EVICTED buckets too:
    they never fault back in, and recovery does not resurrect them
    (review r4: expire_keys reaches only resident slots)."""
    from risingwave_tpu.executors.hash_join import HashJoinExecutor

    L = {"lw": jnp.int64, "lv": jnp.int64}
    R = {"rw": jnp.int64, "rv": jnp.int64}

    def mk():
        return HashJoinExecutor(
            ("lw",), ("rw",), L, R,
            capacity=1 << 8, fanout=4, out_cap=1 << 9,
            window_cols=("lw", "rw"), table_id="wj",
        )

    from risingwave_tpu.executors.base import Watermark

    mgr = CheckpointManager(MemObjectStore())
    j = mk()
    j.cold_get_rows = mgr.get_rows
    j.apply_left(
        StreamChunk.from_numpy(
            {"lw": np.asarray([10, 20], np.int64),
             "lv": np.asarray([1, 2], np.int64)}, 8,
        )
    )
    j.on_barrier(None)
    mgr.commit_staged(1, mgr.stage([j]))
    assert j.evict_cold() == 2
    # watermark closes window 10 on BOTH sides
    j.on_watermark(Watermark("lw", 15))
    j.on_watermark(Watermark("rw", 15))
    assert j._evicted["left"] == {(20,)}
    # a late probe of the closed window matches NOTHING
    outs = j.apply_right(
        StreamChunk.from_numpy(
            {"rw": np.asarray([10], np.int64),
             "rv": np.asarray([9], np.int64)}, 8,
        )
    )
    d = outs[0].to_numpy(with_ops=True)
    assert len(d["__op__"]) == 0
    j.on_barrier(None)
    mgr.commit_staged(2, mgr.stage([j]))  # cold tombstones land here

    # recovery: the closed window's bucket must NOT come back
    j2 = mk()
    mgr.recover([j2])
    outs = j2.apply_right(
        StreamChunk.from_numpy(
            {"rw": np.asarray([10, 20], np.int64),
             "rv": np.asarray([9, 9], np.int64)}, 8,
        )
    )
    d = outs[0].to_numpy(with_ops=True)
    rows = {(int(d["lw"][i]), int(d["lv"][i])) for i in range(len(d["lw"]))}
    assert rows == {(20, 2)}  # window 10 gone, window 20 restored


def test_cold_tombstone_dropped_when_key_recreated_late():
    """A late arrival re-creates a key AFTER its window closed while
    evicted: the staged cold tombstone must yield to the resident
    upsert — point reads and merge reads must agree post-recovery."""
    from risingwave_tpu.executors.base import Watermark
    from risingwave_tpu.executors.hash_join import HashJoinExecutor

    L = {"lw": jnp.int64, "lv": jnp.int64}
    R = {"rw": jnp.int64, "rv": jnp.int64}

    def mk():
        return HashJoinExecutor(
            ("lw",), ("rw",), L, R,
            capacity=1 << 8, fanout=4, out_cap=1 << 9,
            window_cols=("lw", "rw"), table_id="lj",
        )

    mgr = CheckpointManager(MemObjectStore())
    j = mk()
    j.cold_get_rows = mgr.get_rows
    j.apply_left(
        StreamChunk.from_numpy(
            {"lw": np.asarray([10], np.int64),
             "lv": np.asarray([1], np.int64)}, 8,
        )
    )
    j.on_barrier(None)
    mgr.commit_staged(1, mgr.stage([j]))
    assert j.evict_cold() == 1
    j.on_watermark(Watermark("lw", 15))  # closes window 10 (evicted)
    j.on_watermark(Watermark("rw", 15))
    # LATE left row for window 10 arrives BEFORE the next checkpoint:
    # the key is resident again
    j.apply_left(
        StreamChunk.from_numpy(
            {"lw": np.asarray([10], np.int64),
             "lv": np.asarray([5], np.int64)}, 8,
        )
    )
    j.on_barrier(None)
    mgr.commit_staged(2, mgr.stage([j]))

    # point read and full recovery must BOTH see exactly the late row
    found, vals = mgr.get_rows(
        "lj.left", {"k0": np.asarray([10], np.int64)}
    )
    assert found[0]
    j2 = mk()
    mgr.recover([j2])
    outs = j2.apply_right(
        StreamChunk.from_numpy(
            {"rw": np.asarray([10], np.int64),
             "rv": np.asarray([9], np.int64)}, 8,
        )
    )
    d = outs[0].to_numpy(with_ops=True)
    rows = [(int(d["lw"][i]), int(d["lv"][i])) for i in range(len(d["lw"]))]
    assert rows == [(10, 5)]  # the late row, not the pre-expiry one


def _replay_cols(snap, chunks, cols):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            key = (int(d["k"][i]),)
            if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                snap.pop(key, None)
            else:
                row = []
                for n in cols:
                    nl = d.get(n + "__null")
                    row.append(
                        None if nl is not None and nl[i] else int(d[n][i])
                    )
                snap[key] = tuple(row)
    return snap


def _mk_mi(table_id):
    return HashAggExecutor(
        group_keys=("k",),
        calls=(
            AggCall("min", "v", "mn", materialized=True),
            AggCall("max", "v", "mx", materialized=True),
            AggCall("count_star", None, "cnt"),
        ),
        schema_dtypes=DT,
        capacity=1 << 10,
        out_cap=1 << 10,
        table_id=table_id,
    )


def test_minput_min_max_evicts_and_faults_in_on_touch():
    """VERDICT r4 #9: MIN/MAX-bearing (materialized-input) state now
    participates in the cold tier. Evicted multisets fault back in ON
    TOUCH — so a delete of a pre-eviction value, arriving right after
    eviction, retracts exactly (merge-at-barrier could not do this)."""
    MI = ("mn", "mx", "cnt")
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = _mk_mi("coldmi")
    ex.cold_reader = lambda keys: mgr.get_rows("coldmi", keys)
    snap = {}

    # 100 groups x 3 values each; checkpoint -> durable
    rows = [
        (k, v, Op.INSERT) for k in range(100) for v in (k, k + 50, k + 90)
    ]
    for at in range(0, len(rows), CAP):
        _replay_cols(snap, ex.apply(_chunk(rows[at : at + CAP])), MI)
    _replay_cols(snap, ex.on_barrier(None), MI)
    mgr.commit_epoch(1 << 16, [ex])

    assert ex.evict_cold() == 100
    assert int(ex.table.occupancy()) == 0
    assert len(ex._evicted) == 100

    # delete each group's MINIMUM (a pre-eviction value) -> the min
    # must fall back to the next multiset value, exactly
    dels = [(k, k, Op.DELETE) for k in range(30)]
    _replay_cols(snap, ex.apply(_chunk(dels)), MI)
    _replay_cols(snap, ex.on_barrier(None), MI)
    for k in range(30):
        assert snap[(k,)] == (k + 50, k + 90, 2), (k, snap[(k,)])
    for k in range(30, 100):
        assert snap[(k,)] == (k, k + 90, 3)
    assert len(ex._evicted) == 70  # untouched groups stay cold

    # checkpoint + recover: round-trips (evicted set resets, durable
    # rows restore resident)
    mgr.commit_epoch(2 << 16, [ex])
    ex2 = _mk_mi("coldmi")
    CheckpointManager(store).recover([ex2])
    assert ex2._evicted == set()
    snap2 = dict(snap)
    _replay_cols(snap2, ex2.apply(_chunk([(5, 55, Op.DELETE)])), MI)
    _replay_cols(snap2, ex2.on_barrier(None), MI)
    assert snap2[(5,)] == (95, 95, 1)


def test_runtime_budget_evicts_minput_state():
    """The runtime no longer skips MIN/MAX-bearing executors when
    enforcing the memory budget."""
    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("min", "v", "mn", materialized=True),),
        schema_dtypes=DT,
        capacity=1 << 10,
        table_id="coldmib",
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, memory_budget_bytes=1
    )
    rt.register("mi", Pipeline([agg]))
    rows = [(k, k, Op.INSERT) for k in range(50)]
    rt.push("mi", _chunk(rows))
    rt.barrier()  # checkpoint -> durable -> budget forces eviction
    assert int(agg.table.occupancy()) == 0
    assert len(agg._evicted) == 50
    # touch one back; its min continues exactly
    snap = {}
    _replay_cols(snap, agg.apply(_chunk([(7, 3, Op.INSERT)])), ("mn",))
    _replay_cols(snap, agg.on_barrier(None), ("mn",))
    assert snap[(7,)] == (3,)


def test_float_keyed_join_cold_tier():
    """VERDICT r4 #9: non-integer join keys ride the cold tier as exact
    bit patterns (host_key_view) instead of silently disabling
    eviction."""
    from risingwave_tpu.executors.hash_join import HashJoinExecutor

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ldt = {"fk": jnp.float64, "a": jnp.int64}
    rdt = {"fk2": jnp.float64, "b": jnp.int64}
    j = HashJoinExecutor(
        ("fk",), ("fk2",), ldt, rdt,
        capacity=1 << 8, fanout=4, out_cap=1 << 8, table_id="coldf.j",
    )
    j.cold_get_rows = mgr.get_rows

    def lchunk(pairs):
        return StreamChunk.from_numpy(
            {"fk": np.asarray([p[0] for p in pairs], np.float64),
             "a": np.asarray([p[1] for p in pairs], np.int64)}, 32)

    def rchunk(pairs):
        return StreamChunk.from_numpy(
            {"fk2": np.asarray([p[0] for p in pairs], np.float64),
             "b": np.asarray([p[1] for p in pairs], np.int64)}, 32)

    j.apply_left(lchunk([(0.5, 1), (1.25, 2), (2.75, 3)]))
    j.on_barrier(None)
    mgr.commit_epoch(1 << 16, [j])

    assert j.evict_cold() == 3
    assert len(j._evicted["left"]) == 3

    # probe from the right: the evicted left rows must fault in and
    # match by exact float key
    outs = j.apply_right(rchunk([(1.25, 9)]))
    d = outs[0].to_numpy()
    assert len(d["b"]) == 1 and int(d["a"][0]) == 2
    assert float(d["fk"][0]) == 1.25

    # watermark expiry of evicted float keys compares in the NUMERIC
    # domain (bit patterns are identity only): cutoff 1.0 closes 0.5
    assert len(j._evicted["left"]) == 2  # 1.25 faulted back in
    j._expire_evicted("left", 0, 1.0)
    assert len(j._evicted["left"]) == 1  # only 0.5 closed


def test_evicted_minput_groups_expire_under_watermark():
    """A cold-evicted group past the watermark cutoff still closes:
    it faults back in and the normal expiry path retracts it (the
    join's _expire_evicted analogue for aggs)."""
    from risingwave_tpu.executors.base import Watermark

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("min", "v", "mn", materialized=True),),
        schema_dtypes=DT,
        capacity=1 << 8,
        table_id="coldexp",
        window_key=("k", 0, True),  # k doubles as the window column
    )
    ex.cold_reader = lambda keys: mgr.get_rows("coldexp", keys)
    snap = {}
    _replay_cols(
        snap,
        ex.apply(_chunk([(1000, 5, Op.INSERT), (2000, 7, Op.INSERT)])),
        ("mn",),
    )
    _replay_cols(snap, ex.on_barrier(None), ("mn",))
    mgr.commit_epoch(1 << 16, [ex])
    assert ex.evict_cold() == 2 and len(ex._evicted) == 2

    wm, outs = ex.on_watermark(Watermark("k", 1500))
    _replay_cols(snap, outs, ("mn",))
    _replay_cols(snap, ex.on_barrier(None), ("mn",))
    assert (1000,) not in snap, "closed window row was not retracted"
    assert snap[(2000,)] == (7,)
    assert all(t[0] >= 1500 for t in ex._evicted)
