"""EOWC SortExecutor: watermark-ordered emission, buffering,
checkpoint/restore. Reference: executor/sort.rs:20 + sort_buffer.rs."""

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Watermark
from risingwave_tpu.executors.sort import SortExecutor

import jax.numpy as jnp

DT = {"ts": jnp.int64, "v": jnp.int64}


def _chunk(ts, v, cap=8):
    return StreamChunk.from_numpy(
        {"ts": np.asarray(ts), "v": np.asarray(v)}, cap
    )


def _rows(chunks):
    out = []
    for c in chunks:
        d = c.to_numpy()
        out.extend(zip(d["ts"].tolist(), d["v"].tolist()))
    return out


def test_sort_emits_closed_rows_in_order():
    s = SortExecutor("ts", DT, capacity=32)
    s.apply(_chunk([30, 10, 20], [1, 2, 3]))
    s.apply(_chunk([5, 40, 10], [4, 5, 6]))
    assert s.apply(_chunk([], [])) == []  # nothing emits on data

    _, outs = s.on_watermark(Watermark("ts", 25))
    got = _rows(outs)
    # rows below 25 in (ts, arrival) order; ties (10) by arrival
    assert got == [(5, 4), (10, 2), (10, 6), (20, 3)]

    # open rows stay; the rest closes later
    _, outs = s.on_watermark(Watermark("ts", 100))
    assert _rows(outs) == [(30, 1), (40, 5)]
    _, outs = s.on_watermark(Watermark("ts", 200))
    assert _rows(outs) == []


def test_sort_overflow_and_delete_raise():
    s = SortExecutor("ts", DT, capacity=4)
    s.apply(_chunk([1, 2, 3], [0, 0, 0]))
    s.apply(_chunk([4, 5, 6], [0, 0, 0]))  # exceeds capacity
    with pytest.raises(RuntimeError, match="overflow"):
        s.on_barrier(None)
        s.finish_barrier()

    s2 = SortExecutor("ts", DT, capacity=8)
    c = StreamChunk.from_numpy(
        {"ts": np.asarray([1]), "v": np.asarray([2])}, 4,
        ops=np.asarray([1]),
    )
    s2.apply(c)
    with pytest.raises(RuntimeError, match="append-only"):
        s2.on_barrier(None)
        s2.finish_barrier()


def test_sort_checkpoint_restore_roundtrip():
    s = SortExecutor("ts", DT, capacity=32, table_id="srt")
    s.apply(_chunk([30, 10, 20], [1, 2, 3]))
    deltas = s.checkpoint_delta()
    assert len(deltas) == 1

    s2 = SortExecutor("ts", DT, capacity=32, table_id="srt")
    s2.restore_state("srt", deltas[0].key_cols, deltas[0].value_cols)
    _, outs = s2.on_watermark(Watermark("ts", 100))
    assert _rows(outs) == [(10, 2), (20, 3), (30, 1)]

    # post-restore appends continue the seq ordering (ties by arrival)
    s2.apply(_chunk([10], [9]))
    _, outs = s2.on_watermark(Watermark("ts", 200))
    assert _rows(outs) == [(10, 9)]


def test_sort_checkpoint_tombstones_emitted_rows():
    s = SortExecutor("ts", DT, capacity=32, table_id="srt")
    s.apply(_chunk([10, 30], [1, 2]))
    d1 = s.checkpoint_delta()
    s.on_watermark(Watermark("ts", 20))  # emits ts=10
    d2 = s.checkpoint_delta()
    assert len(d2) == 1
    # the second delta tombstones the emitted row's seq
    assert d2[0].tombstone.any()
