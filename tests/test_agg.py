"""Grouped-agg kernel tests vs a pandas/numpy oracle.

Mirrors the reference's executor-test discipline (hash_agg tests,
src/stream/src/executor/hash_agg.rs tests + test_utils.rs): feed chunks,
flush at barriers, and check the emitted delta stream reconstructs the
oracle's groupby result.
"""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.ops import agg as agg_mod
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.types import Op

CALLS = (
    AggCall("count_star", None, "cnt"),
    AggCall("sum", "v", "total"),
    AggCall("min", "v", "lo"),
    AggCall("max", "v", "hi"),
)


def _setup(cap=256):
    table = ht.HashTable.create(cap, (jnp.int64,))
    state = agg_mod.create_state(cap, CALLS, {"v": jnp.int64})
    return table, state


def _apply(table, state, keys, vals, signs=None, nulls=None):
    n = len(keys)
    valid = jnp.ones(n, jnp.bool_)
    table, slots, _, _ = ht.lookup_or_insert(
        table, (jnp.asarray(keys, jnp.int64),), valid
    )
    table = ht.set_live(table, slots, jnp.ones(n, jnp.bool_))
    s = jnp.asarray(signs if signs is not None else np.ones(n), jnp.int32)
    nu = {"v": jnp.asarray(nulls, jnp.bool_)} if nulls is not None else {}
    state = agg_mod.apply(
        state, CALLS, slots, s, {"v": jnp.asarray(vals, jnp.int64)}, nu
    )
    return table, state


def _flush_to_host(state, table, out_cap=64):
    state, delta = agg_mod.flush(state, table.keys, out_cap)
    assert not bool(delta["overflow"])
    v = np.asarray(delta["valid"])
    rows = {
        "op": np.asarray(delta["ops"])[v],
        "key": np.asarray(delta["key0"])[v],
    }
    for name in ("cnt", "total", "lo", "hi"):
        rows[name] = np.asarray(delta[name])[v]
    return state, rows


def _replay(snapshot, rows):
    """Apply a delta to a dict snapshot {key: (cnt,total,lo,hi)}."""
    for i in range(len(rows["op"])):
        op, k = rows["op"][i], rows["key"][i]
        vals = tuple(rows[n][i] for n in ("cnt", "total", "lo", "hi"))
        if op in (Op.INSERT, Op.UPDATE_INSERT):
            snapshot[k] = vals
        else:
            assert k in snapshot, "retraction for unknown group"
            del snapshot[k]
    return snapshot


def test_basic_groupby_oracle(rng):
    table, state = _setup()
    keys = rng.integers(0, 20, 300).astype(np.int64)
    vals = rng.integers(-50, 50, 300).astype(np.int64)
    table, state = _apply(table, state, keys, vals)
    state, rows = _flush_to_host(state, table)
    snap = _replay({}, rows)

    import pandas as pd

    df = pd.DataFrame({"k": keys, "v": vals})
    oracle = df.groupby("k")["v"].agg(["count", "sum", "min", "max"])
    assert set(snap) == set(oracle.index)
    for k, (cnt, total, lo, hi) in snap.items():
        row = oracle.loc[k]
        assert cnt == row["count"] and total == row["sum"]
        assert lo == row["min"] and hi == row["max"]


def test_incremental_updates_across_barriers(rng):
    table, state = _setup()
    snap = {}
    all_k, all_v = [], []
    for epoch in range(5):
        keys = rng.integers(0, 10, 50).astype(np.int64)
        vals = rng.integers(0, 100, 50).astype(np.int64)
        all_k.append(keys)
        all_v.append(vals)
        table, state = _apply(table, state, keys, vals)
        state, rows = _flush_to_host(state, table)
        snap = _replay(snap, rows)

    import pandas as pd

    df = pd.DataFrame({"k": np.concatenate(all_k), "v": np.concatenate(all_v)})
    oracle = df.groupby("k")["v"].agg(["count", "sum", "min", "max"])
    assert set(snap) == set(oracle.index)
    for k, (cnt, total, lo, hi) in snap.items():
        row = oracle.loc[k]
        assert (cnt, total, lo, hi) == (
            row["count"],
            row["sum"],
            row["min"],
            row["max"],
        )


def test_retraction_sum_count():
    table, state = _setup()
    # insert 3 rows for key 7, then retract one
    table, state = _apply(table, state, [7, 7, 7], [10, 20, 30])
    state, rows = _flush_to_host(state, table)
    snap = _replay({}, rows)
    assert snap[7][:2] == (3, 60)
    calls_noext = (AggCall("count_star", None, "cnt"), AggCall("sum", "v", "total"))
    # retraction with only sum/count calls (min/max would flag)
    table2 = ht.HashTable.create(256, (jnp.int64,))
    state2 = agg_mod.create_state(256, calls_noext, {"v": jnp.int64})
    v = jnp.ones(3, jnp.bool_)
    table2, slots, _, _ = ht.lookup_or_insert(
        table2, (jnp.asarray([7, 7, 7], jnp.int64),), v
    )
    state2 = agg_mod.apply(
        state2, calls_noext, slots, jnp.asarray([1, 1, 1], jnp.int32),
        {"v": jnp.asarray([10, 20, 30], jnp.int64)}, {},
    )
    state2 = agg_mod.apply(
        state2, calls_noext, slots[:1], jnp.asarray([-1], jnp.int32),
        {"v": jnp.asarray([10], jnp.int64)}, {},
    )
    state2, delta = agg_mod.flush(state2, table2.keys, 8)
    val = np.asarray(delta["valid"])
    assert np.asarray(delta["cnt"])[val][-1] == 2
    assert np.asarray(delta["total"])[val][-1] == 50
    assert not bool(state2.minmax_retracted)


def test_minmax_retraction_flagged():
    table, state = _setup()
    table, state = _apply(table, state, [5], [10])
    table, state = _apply(table, state, [5], [10], signs=[-1])
    assert bool(state.minmax_retracted)


def test_group_death_emits_delete():
    calls = (AggCall("count_star", None, "cnt"),)
    table = ht.HashTable.create(64, (jnp.int64,))
    state = agg_mod.create_state(64, calls, {})
    v = jnp.ones(2, jnp.bool_)
    table, slots, _, _ = ht.lookup_or_insert(
        table, (jnp.asarray([1, 2], jnp.int64),), v
    )
    state = agg_mod.apply(state, calls, slots, jnp.asarray([1, 1], jnp.int32), {}, {})
    state, delta = agg_mod.flush(state, table.keys, 8)
    ops = np.asarray(delta["ops"])[np.asarray(delta["valid"])]
    assert (ops == Op.INSERT).all()
    # retract key 1 entirely -> Delete on next flush
    state = agg_mod.apply(
        state, calls, slots[:1], jnp.asarray([-1], jnp.int32), {}, {}
    )
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    ops = np.asarray(delta["ops"])[val]
    keys = np.asarray(delta["key0"])[val]
    assert list(ops) == [Op.DELETE] and list(keys) == [1]


def test_null_inputs_skipped():
    calls = (
        AggCall("count_star", None, "star"),
        AggCall("count", "v", "cnt"),
        AggCall("sum", "v", "total"),
    )
    table = ht.HashTable.create(64, (jnp.int64,))
    state = agg_mod.create_state(64, calls, {"v": jnp.int64})
    keys = jnp.asarray([1, 1, 1], jnp.int64)
    table, slots, _, _ = ht.lookup_or_insert(table, (keys,), jnp.ones(3, bool))
    state = agg_mod.apply(
        state, calls, slots, jnp.ones(3, jnp.int32),
        {"v": jnp.asarray([10, 99, 20], jnp.int64)},
        {"v": jnp.asarray([False, True, False])},
    )
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    assert np.asarray(delta["star"])[val][-1] == 3  # COUNT(*) counts NULLs
    assert np.asarray(delta["cnt"])[val][-1] == 2  # COUNT(v) skips
    assert np.asarray(delta["total"])[val][-1] == 30  # SUM skips


def test_all_null_inputs_emit_sql_null_outputs():
    """SUM/MIN/MAX over a group with only NULL inputs is SQL NULL, not
    0 / the sentinel (code-review r2 finding #2); COUNT stays 0."""
    calls = (
        AggCall("count", "v", "cnt"),
        AggCall("sum", "v", "total"),
        AggCall("min", "v", "lo"),
    )
    table = ht.HashTable.create(64, (jnp.int64,))
    state = agg_mod.create_state(64, calls, {"v": jnp.int64})
    keys = jnp.asarray([1, 1, 2], jnp.int64)
    table, slots, _, _ = ht.lookup_or_insert(table, (keys,), jnp.ones(3, bool))
    state = agg_mod.apply(
        state, calls, slots, jnp.ones(3, jnp.int32),
        {"v": jnp.asarray([10, 99, 20], jnp.int64)},
        {"v": jnp.asarray([True, True, False])},  # key 1: all-NULL inputs
    )
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    k = np.asarray(delta["key0"])[val]
    res = {
        kk: (c, t, tn, lo, ln)
        for kk, c, t, tn, lo, ln in zip(
            k,
            np.asarray(delta["cnt"])[val],
            np.asarray(delta["total"])[val],
            np.asarray(delta["total__isnull"])[val],
            np.asarray(delta["lo"])[val],
            np.asarray(delta["lo__isnull"])[val],
        )
    }
    assert res[1][0] == 0  # COUNT(v) = 0, not NULL
    assert res[1][2] and res[1][4]  # SUM / MIN are NULL
    assert res[2] == (1, 20, False, 20, False)
    # retraction of the only non-null input turns SUM back to NULL
    state = agg_mod.apply(
        state, calls, slots[2:], jnp.asarray([-1], jnp.int32),
        {"v": jnp.asarray([20], jnp.int64)},
        {"v": jnp.asarray([False])},
    )
    # group 2 still live? row_count 0 -> dead; add a NULL row to keep it
    state = agg_mod.apply(
        state, calls, slots[2:], jnp.asarray([1], jnp.int32),
        {"v": jnp.asarray([0], jnp.int64)},
        {"v": jnp.asarray([True])},
    )
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    k = np.asarray(delta["key0"])[val]
    ops = np.asarray(delta["ops"])[val]
    keep = ops != Op.UPDATE_DELETE
    res2 = dict(zip(k[keep], np.asarray(delta["total__isnull"])[val][keep]))
    assert res2[2]  # SUM(v) for key 2 is NULL again


def test_delete_groups_resets_extremes():
    table, state = _setup()
    table, state = _apply(table, state, [3], [42])
    state, _ = agg_mod.flush(state, table.keys, 8)
    slots, _ = ht.lookup(table, (jnp.asarray([3], jnp.int64),), jnp.ones(1, bool))
    state = agg_mod.delete_groups(state, CALLS, slots)
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    assert list(np.asarray(delta["ops"])[val]) == [Op.DELETE]
    # re-insert into the same slot: min must restart from the sentinel
    table, state = _apply(table, state, [3], [100])
    state, delta = agg_mod.flush(state, table.keys, 8)
    val = np.asarray(delta["valid"])
    assert np.asarray(delta["lo"])[val][-1] == 100
    assert np.asarray(delta["hi"])[val][-1] == 100


def test_float_minmax_nan_total_order():
    # ordered-float totality: NaN is the single LARGEST value, so
    # MIN([NaN, 1.0]) == 1.0 and MAX([NaN, 1.0]) is NaN; an all-NaN
    # group yields NaN for both. (Raw float scatter-min would let NaN
    # poison MIN forever.)
    calls = (AggCall("min", "v", "lo"), AggCall("max", "v", "hi"))
    meta = agg_mod.float_extreme_meta(calls, {"v": jnp.float64})
    table = ht.HashTable.create(64, (jnp.int64,))
    state = agg_mod.create_state(64, calls, {"v": jnp.float64})
    keys = jnp.asarray([1, 1, 2, 2, 3], jnp.int64)
    vals = jnp.asarray([np.nan, 1.0, -0.0, 2.5, np.nan], jnp.float64)
    table, slots, _, _ = ht.lookup_or_insert(table, (keys,), jnp.ones(5, bool))
    state = agg_mod.apply(
        state, calls, slots, jnp.ones(5, jnp.int32), {"v": vals}, {}
    )
    state, delta = agg_mod.flush(state, table.keys, 8, float_extremes=meta)
    v = np.asarray(delta["valid"])
    k = np.asarray(delta["key0"])[v]
    lo = np.asarray(delta["lo"])[v]
    hi = np.asarray(delta["hi"])[v]
    res = {kk: (l, h) for kk, l, h in zip(k, lo, hi)}
    assert res[1][0] == 1.0 and np.isnan(res[1][1])
    assert res[2] == (0.0, 2.5)
    assert np.isnan(res[3][0]) and np.isnan(res[3][1])


def test_flush_overflow_loops():
    calls = (AggCall("count_star", None, "cnt"),)
    table = ht.HashTable.create(256, (jnp.int64,))
    state = agg_mod.create_state(256, calls, {})
    keys = jnp.asarray(np.arange(40, dtype=np.int64))
    table, slots, _, _ = ht.lookup_or_insert(table, (keys,), jnp.ones(40, bool))
    state = agg_mod.apply(state, calls, slots, jnp.ones(40, jnp.int32), {}, {})
    seen = set()
    for _ in range(10):
        state, delta = agg_mod.flush(state, table.keys, 16)
        val = np.asarray(delta["valid"])
        seen |= set(np.asarray(delta["key0"])[val].tolist())
        if not bool(delta["overflow"]):
            break
    assert seen == set(range(40))


def test_apply_stacked_matches_per_chunk(rng):
    """lax.scan batch path must produce bit-identical state to the
    per-chunk path (same kernels, one dispatch)."""
    import functools

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors import HashAggExecutor
    from risingwave_tpu.executors.hop_window import hop_step_fn
    from risingwave_tpu.parallel.sharded_agg import stack_chunks

    calls = (AggCall("count_star", None, "num"),)
    dt = {"auction": jnp.int64, "window_start": jnp.int64, "date_time": jnp.int64}
    a = HashAggExecutor(("auction", "window_start"), calls, dt, capacity=1 << 12)
    b = HashAggExecutor(("auction", "window_start"), calls, dt, capacity=1 << 12)
    pre = functools.partial(
        hop_step_fn,
        ts_col="date_time",
        size_ms=10_000,
        slide_ms=2_000,
        out_start="window_start",
    )

    chunks = []
    for _ in range(6):
        cols = {
            "auction": rng.integers(0, 50, 256).astype(np.int64),
            "date_time": rng.integers(0, 40_000, 256).astype(np.int64),
        }
        chunks.append(StreamChunk.from_numpy(cols, 256))
    for c in chunks:
        a.apply(pre(c))
    b.apply_stacked(stack_chunks(chunks), pre=pre)

    def snap(ex):
        out = {}
        for ch in ex.on_barrier(None):
            d = ch.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                out[(int(d["auction"][i]), int(d["window_start"][i]))] = int(
                    d["num"][i]
                )
        return out

    assert snap(a) == snap(b)
