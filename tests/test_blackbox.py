"""Black-box flight recorder + device-wedge sentinel (blackbox.py).

The two failure modes the subsystem exists for, reproduced in the sim
tier: a SIGKILLed pipeline must leave a parseable, monotonic black box
on disk, and a wedged (fake) device must convert today's indefinite
hang into a structured ``DeviceWedged`` within the watchdog budget,
leaving a well-formed ``WEDGE_*.json`` forensic bundle — with the
recorder's steady-state overhead held under 1% of a barrier (the
perf_gate --blackbox contract)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu import blackbox
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.blackbox import (
    DeviceSentinel,
    DeviceWedged,
    FlightRecorder,
    classify_latency,
    read_segment,
)
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sim import BlockingKernelExecutor, WedgeableDevice
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace(i, ckpt=False, wall=10.0):
    return SimpleNamespace(
        epoch=i,
        seq=i,
        checkpoint=ckpt,
        wall_ms=wall,
        stages_ms={"ingest": 1.0, "dispatch": wall - 1.0},
        achieved_bw_frac=0.01,
        chunk_bytes=1 << 16,
        state_bytes=1 << 20,
    )


def _mk_pipeline(tid):
    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("sum", "v", "s"),),
        schema_dtypes={"k": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id=f"{tid}.agg",
    )
    mv = MaterializeExecutor(pk=("k",), columns=("s",), table_id=f"{tid}.mv")
    return Pipeline([agg, mv]), mv


def _chunk(rng, n=8):
    return StreamChunk.from_numpy(
        {
            "k": rng.integers(0, 4, n).astype(np.int64),
            "v": rng.integers(0, 40, n).astype(np.int64),
        },
        16,
    )


# ---------------------------------------------------------------------------
# flight recorder: ring + segment + reader
# ---------------------------------------------------------------------------


def test_recorder_segment_roundtrip_rotation_and_torn_tail(tmp_path):
    """Records round-trip through the JSONL segment; rotation keeps the
    readable window bounded-but-merged; a torn final line (SIGKILL
    mid-write) is tolerated, not fatal."""
    rec = FlightRecorder()
    rec.configure(
        dir=str(tmp_path), fsync_interval_s=0.0, segment_max_bytes=66_000
    )
    for i in range(600):
        rec.record_barrier(_trace(i + 1, ckpt=i % 4 == 0))
    path = rec.segment_path
    rec.close()
    assert os.path.exists(path + ".old")  # rotation happened
    # torn tail: a record cut mid-write by a SIGKILL
    with open(path, "a") as f:
        f.write('{"k":"b","ep":9999,"se')
    doc = read_segment(str(tmp_path))
    assert doc["torn_lines"] == 1
    assert doc["monotonic"]
    recs = doc["records"]
    # the merged (.old + current) window holds a contiguous tail
    assert len(recs) >= 100
    assert recs[-1]["epoch"] == 600
    epochs = [r["epoch"] for r in recs]
    assert epochs == sorted(epochs)
    assert recs[-1]["stages_ms"]["dispatch"] == 9.0
    assert doc["header"]["pid"] == os.getpid()


def test_recorder_unwritable_dir_degrades_to_ring_only(tmp_path):
    """An unwritable blackbox dir must not poison barriers: the
    recorder drops persistence (counted) and the ring keeps going."""
    rec = FlightRecorder()
    rec.configure(dir=str(tmp_path / "nope" / "\0bad"), fsync_interval_s=0)
    for i in range(5):
        rec.record_barrier(_trace(i + 1))
    assert len(rec.snapshot_tail(10)) == 5  # ring survived
    assert rec.dir is None  # persistence dropped, not retried per record


def test_runtime_barriers_feed_ring_and_pipeline_records_dedupe():
    """A runtime-driven barrier records exactly ONE ring record (the
    EpochTrace), not one per fragment pipeline — and epochs are
    monotonic across commits."""
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    p, _mv = _mk_pipeline("bb.dedupe")
    rt.register("mv", p)
    rng = np.random.default_rng(3)
    before = blackbox.RECORDER.snapshot()["records"]
    for _ in range(3):
        rt.push("mv", _chunk(rng))
        rt.barrier()
    after = blackbox.RECORDER.snapshot()["records"]
    assert after - before == 3  # one record per barrier, no doubles
    tail = blackbox.RECORDER.snapshot_tail(3)
    assert [r["seq"] for r in tail] == [1, 2, 3]
    assert all("dispatch" in r["st"] for r in tail), tail


def test_sigkill_mid_run_leaves_parseable_blackbox(tmp_path):
    """The r04/r05 failure mode: a pipeline murdered with SIGKILL mid-
    run still leaves a black box that replays a complete, monotonic
    epoch timeline up to the kill — via the in-process reader AND the
    ``python -m risingwave_tpu blackbox`` CLI (with a Perfetto trace)."""
    child = tmp_path / "child.py"
    child.write_text(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from risingwave_tpu.array.chunk import StreamChunk\n"
        "from risingwave_tpu.executors.hash_agg import HashAggExecutor\n"
        "from risingwave_tpu.executors.materialize import "
        "MaterializeExecutor\n"
        "from risingwave_tpu.ops.agg import AggCall\n"
        "from risingwave_tpu.runtime.pipeline import Pipeline\n"
        "from risingwave_tpu.runtime.runtime import StreamingRuntime\n"
        "from risingwave_tpu.storage.object_store import MemObjectStore\n"
        "# RW_BLACKBOX_DIR env arms persistence on construction\n"
        "rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)\n"
        "agg = HashAggExecutor(group_keys=('k',),\n"
        "    calls=(AggCall('sum', 'v', 's'),),\n"
        "    schema_dtypes={'k': jnp.int64, 'v': jnp.int64},\n"
        "    capacity=1 << 8, table_id='kill.agg')\n"
        "mv = MaterializeExecutor(pk=('k',), columns=('s',),\n"
        "    table_id='kill.mv')\n"
        "rt.register('mv', Pipeline([agg, mv]))\n"
        "rng = np.random.default_rng(7)\n"
        "i = 0\n"
        "while True:\n"
        "    i += 1\n"
        "    c = StreamChunk.from_numpy(\n"
        "        {'k': rng.integers(0, 4, 8).astype(np.int64),\n"
        "         'v': rng.integers(0, 40, 8).astype(np.int64)}, 16)\n"
        "    rt.push('mv', c)\n"
        "    rt.barrier()\n"
        "    print(f'B {i}', flush=True)\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        RW_BLACKBOX_DIR=str(tmp_path),
        RW_BLACKBOX_FSYNC_S="0",
    )
    proc = subprocess.Popen(
        [sys.executable, str(child)],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
    )
    barriers = 0
    try:
        deadline = time.time() + 120
        while barriers < 6 and time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("B "):
                barriers = int(line.split()[1])
        assert barriers >= 6, f"child made no progress ({barriers})"
    finally:
        # SIGKILL mid-barrier-loop: safe — a CPU-pinned child, not a
        # TPU tunnel client
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    doc = read_segment(str(tmp_path))
    recs = doc["records"]
    assert doc["monotonic"]
    # complete timeline up to the kill: every barrier the child
    # reported is in the box (the kill may race ONE in-flight record)
    assert len(recs) >= barriers - 1
    seqs = [r["seq"] for r in recs]
    assert seqs == list(range(1, len(recs) + 1))  # no holes
    epochs = [r["epoch"] for r in recs]
    assert epochs == sorted(epochs) and len(set(epochs)) == len(epochs)
    # reader CLI on the same dead segment (+ Perfetto trace)
    trace_out = tmp_path / "trace.json"
    cli = subprocess.run(
        [
            sys.executable,
            "-m",
            "risingwave_tpu",
            "blackbox",
            str(tmp_path),
            "--trace",
            str(trace_out),
        ],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=REPO,
    )
    assert cli.returncode == 0, cli.stderr
    assert f"{len(recs)} barrier(s)" in cli.stdout
    tr = json.loads(trace_out.read_text())
    assert any(e.get("ph") == "X" for e in tr["traceEvents"])
    assert any(e.get("cat") == "epoch" for e in tr["traceEvents"])


# ---------------------------------------------------------------------------
# device-wedge sentinel
# ---------------------------------------------------------------------------


def test_classify_latency_vocabulary():
    assert classify_latency(10, 100, 1000) == "ALIVE"
    assert classify_latency(200, 100, 1000) == "SLOW"
    assert classify_latency(1000, 100, 1000) == "WEDGED"
    assert classify_latency(None, 100, 1000) == "WEDGED"


def _sentinel_threads():
    return [
        t
        for t in threading.enumerate()
        if t.name.startswith("rw-sentinel") and t.is_alive()
    ]


def test_wedge_sentinel_fires_within_budget_with_forensic_bundle(tmp_path):
    """A wedged fake device flips the sentinel to WEDGED within a few
    deadlines, arms a structured DeviceWedged, and leaves a well-formed
    WEDGE_*.json (thread stacks, device forensics, recorder tail);
    unwedging heals back to ALIVE and disarms; stop() leaves no orphan
    sentinel threads."""
    dev = WedgeableDevice()
    sen = DeviceSentinel()
    sen.start(
        interval_s=0.05,
        slow_ms=50,
        deadline_s=0.2,
        heartbeat_fn=dev.heartbeat,
        dir=str(tmp_path),
    )
    try:
        deadline = time.time() + 5
        while sen.state != "ALIVE" and time.time() < deadline:
            time.sleep(0.02)
        assert sen.state == "ALIVE", sen.snapshot()
        dev.wedge()
        t0 = time.time()
        while sen.wedged_error() is None and time.time() - t0 < 5:
            time.sleep(0.02)
        detect_s = time.time() - t0
        w = sen.wedged_error()
        assert w is not None, sen.snapshot()
        assert isinstance(w, DeviceWedged)
        # within the watchdog budget: a handful of deadline windows,
        # nothing near the old 360s hang
        assert detect_s < 3.0, detect_s
        # the error ARMS before the bundle capture completes (fail-fast
        # first; forensics may touch the wedged device): poll briefly
        deadline = time.time() + 5
        while not w.bundle_path and time.time() < deadline:
            time.sleep(0.02)
        assert w.bundle_path
        bundle = json.load(open(w.bundle_path))
        assert bundle["state"] == "WEDGED"
        assert "threads" in bundle and "device" in bundle
        assert "recorder_tail" in bundle
        assert any("rw-sentinel" in k for k in bundle["threads"])
        # the heartbeat status file tracks the wedge (the surface
        # bench_on_healthy tails into BENCH_WATCH.log); written after
        # the capture, so poll briefly
        deadline = time.time() + 5
        st = {}
        while st.get("state") != "WEDGED" and time.time() < deadline:
            st = json.load(open(tmp_path / "SENTINEL_STATE.json"))
            time.sleep(0.02)
        assert st["state"] == "WEDGED" and st["wedges"] == 1
        assert REGISTRY.gauge("device_state").get() == 2.0
        # device_state transition landed in the meta event log
        from risingwave_tpu.event_log import EVENT_LOG

        trans = [
            e
            for e in EVENT_LOG.events(kind="device_state")
            if e.get("source") == "sentinel" and e.get("state") == "WEDGED"
        ]
        assert trans, "no device_state WEDGED event recorded"
        dev.unwedge()
        deadline = time.time() + 5
        while sen.state != "ALIVE" and time.time() < deadline:
            time.sleep(0.02)
        assert sen.state == "ALIVE"
        assert sen.wedged_error() is None  # healed => disarmed
    finally:
        dev.unwedge()
        sen.stop()
    deadline = time.time() + 5
    while _sentinel_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert _sentinel_threads() == []  # no orphaned sentinel threads


def test_runtime_barrier_raises_device_wedged_and_recovery_clears(
    tmp_path,
):
    """The runtime contract: an armed wedge surfaces at the next
    barrier as DeviceWedged (not a hang); with auto_recover it is
    routed like an actor fault — recovered, capture window aborted,
    wedge cleared — and once the device heals the stream commits
    again."""
    dev = WedgeableDevice()
    saved_sentinel = blackbox.SENTINEL  # fresh instance: no config leak
    blackbox.SENTINEL = blackbox.DeviceSentinel()
    blackbox.SENTINEL.start(
        interval_s=0.05,
        slow_ms=50,
        deadline_s=0.2,
        heartbeat_fn=dev.heartbeat,
        dir=str(tmp_path),
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    p, mv = _mk_pipeline("bb.wedge")
    rt.register("mv", p)
    rng = np.random.default_rng(11)
    try:
        rt.push("mv", _chunk(rng))
        rt.barrier()  # healthy commit
        dev.wedge()
        t0 = time.time()
        while blackbox.SENTINEL.wedged_error() is None and time.time() - t0 < 5:
            time.sleep(0.02)
        assert blackbox.SENTINEL.wedged_error() is not None
        # auto_recover: the wedge is treated like an actor fault — the
        # barrier recovers in place (returns {}) instead of crashing
        before = rt.auto_recoveries
        outs = rt.barrier()
        assert outs == {}
        assert rt.auto_recoveries == before + 1
        assert rt.last_recovery_mode == "full"
        assert isinstance(rt.last_failure, DeviceWedged)
        # recovery hygiene: no open capture window survived (the wedge
        # itself legitimately RE-ARMS while the device stays down —
        # the consecutive-recovery ladder owns that case)
        assert blackbox.SENTINEL.abort_capture() == 0
        # device heals -> the stream is live again
        dev.unwedge()
        deadline = time.time() + 5
        while blackbox.SENTINEL.state != "ALIVE" and time.time() < deadline:
            time.sleep(0.02)
        rt.push("mv", _chunk(rng))
        rt.barrier()
        assert rt.mgr.max_committed_epoch > 0
    finally:
        dev.unwedge()
        blackbox.SENTINEL.stop()
        blackbox.SENTINEL = saved_sentinel


def test_wait_barrier_converts_hang_into_device_wedged(tmp_path):
    """The q7 wedge shape: an actor stuck inside a blocking fake
    kernel would previously hang wait_barrier for the full timeout;
    with the sentinel wedged, wait_barrier raises the structured
    DeviceWedged within ~a slice — and dumps a stall artifact naming
    the stuck actors first."""
    from risingwave_tpu.runtime.graph import FragmentSpec, GraphRuntime

    dev = WedgeableDevice()
    blocker = BlockingKernelExecutor(dev, block_on="barrier")
    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec("work", lambda i: [blocker], inputs=[("src", 0)]),
        ]
    ).start()
    saved_sentinel = blackbox.SENTINEL  # fresh instance: no config leak
    blackbox.SENTINEL = blackbox.DeviceSentinel()
    blackbox.SENTINEL.start(
        interval_s=0.05,
        slow_ms=50,
        deadline_s=0.2,
        heartbeat_fn=dev.heartbeat,
        dir=str(tmp_path),
    )
    stall_dir = os.environ.get("RW_STALL_DIR")
    os.environ["RW_STALL_DIR"] = str(tmp_path)
    try:
        dev.wedge()  # kernel AND heartbeats block: the real wedge shape
        t0 = time.time()
        while blackbox.SENTINEL.wedged_error() is None and time.time() - t0 < 5:
            time.sleep(0.02)
        assert blackbox.SENTINEL.wedged_error() is not None
        b = g.inject_barrier_nowait()
        t0 = time.perf_counter()
        with pytest.raises(DeviceWedged):
            g.wait_barrier(b.epoch.curr, timeout=30.0)
        waited = time.perf_counter() - t0
        # structured failure in ~a wait slice, nowhere near the 30s
        # deadman (let alone the 360s the real wedge burned)
        assert waited < 10.0, waited
        # the stall dump is captured on a side thread (fail-fast first,
        # forensics best-effort): poll briefly for the artifact
        deadline = time.time() + 10
        dumps = []
        while not dumps and time.time() < deadline:
            dumps = [
                f
                for f in os.listdir(tmp_path)
                if f.startswith("STALL_DUMP_")
            ]
            time.sleep(0.05)
        assert dumps, "wedge left no stall artifact"
        doc = json.load(open(tmp_path / dumps[0]))
        assert "device wedged" in doc["reason"]
        assert "blackbox" in doc  # recorder tail + sentinel snapshot
    finally:
        if stall_dir is None:
            os.environ.pop("RW_STALL_DIR", None)
        else:
            os.environ["RW_STALL_DIR"] = stall_dir
        dev.unwedge()
        blackbox.SENTINEL.stop()
        blackbox.SENTINEL = saved_sentinel
        g.stop()


# ---------------------------------------------------------------------------
# overhead + config
# ---------------------------------------------------------------------------


def test_recorder_overhead_under_1pct_of_steady_barrier(tmp_path):
    """The always-on contract: one record_barrier per barrier — ring
    AND segment persistence — must cost <1% of a steady-state barrier
    wall (PROFILE.md round 10; enforced in CI by perf_gate --blackbox)."""
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    p, _mv = _mk_pipeline("bb.overhead")
    rt.register("mv", p)
    rng = np.random.default_rng(5)
    c = _chunk(rng, n=8)
    rt.push("mv", c)
    rt.barrier()  # warm compiles
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        rt.push("mv", c)
        rt.barrier()
    steady_ms = (time.perf_counter() - t0) / n * 1e3
    # the ALWAYS-ON half (ring only — what every barrier in every
    # process pays): one record per barrier must be <1% of the wall
    rec = FlightRecorder()
    loops = 500
    t0 = time.perf_counter()
    for i in range(loops):
        rec.record_barrier(_trace(i + 1), runtime=rt)
    ring_ms = (time.perf_counter() - t0) / loops * 1e3
    assert ring_ms < 0.01 * steady_ms, (ring_ms, steady_ms)
    # the PERSISTED half (armed during benches, fsync cadence bounded):
    # the full build+append+fsync worst case must stay under the
    # committed perf_gate budget (scripts/perf_budgets.json), which is
    # <1% of the ~100ms steady-state bench barrier it rides
    budgets = json.load(
        open(os.path.join(REPO, "scripts", "perf_budgets.json"))
    )
    rec.configure(dir=str(tmp_path), fsync_interval_s=0.0)
    loops = 200
    t0 = time.perf_counter()
    for i in range(loops):
        rec.record_barrier(_trace(i + 1001), runtime=rt)
    per_record_ms = (time.perf_counter() - t0) / loops * 1e3
    rec.close()
    assert per_record_ms < budgets["blackbox"]["host_ms_per_barrier_max"], (
        per_record_ms
    )


def test_blackbox_config_section_and_env_precedence(tmp_path, monkeypatch):
    """[blackbox] TOML parses into the config dataclass; RW_BLACKBOX=0
    (the env escape hatch) wins over an enabled config."""
    from risingwave_tpu.config import load_config

    cfg_path = tmp_path / "rw.toml"
    cfg_path.write_text(
        "[blackbox]\n"
        "ring_barriers = 64\n"
        "fsync_interval_s = 0.5\n"
        "sentinel_deadline_s = 7.5\n"
    )
    cfg = load_config(str(cfg_path))
    assert cfg.blackbox.ring_barriers == 64
    assert cfg.blackbox.fsync_interval_s == 0.5
    assert cfg.blackbox.sentinel_deadline_s == 7.5
    assert cfg.blackbox.enabled and not cfg.blackbox.sentinel
    rec = FlightRecorder()
    saved_recorder = blackbox.RECORDER
    blackbox.RECORDER = rec
    try:
        monkeypatch.setenv("RW_BLACKBOX", "0")
        blackbox.configure(cfg.blackbox)
        assert rec.enabled is False  # env beat the config's enabled=True
        assert rec.ring.maxlen == 64
        monkeypatch.setenv("RW_BLACKBOX", "1")
        monkeypatch.setenv("RW_BLACKBOX_DIR", str(tmp_path))
        monkeypatch.setenv("RW_BLACKBOX_RING", "32")
        blackbox.from_env()
        assert rec.enabled is True
        assert rec.dir == str(tmp_path)
        assert rec.ring.maxlen == 32
    finally:
        rec.close()
        blackbox.RECORDER = saved_recorder
