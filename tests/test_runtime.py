"""StreamingRuntime tests: shared barrier clock over multiple
fragments, async checkpoint lane, interval tick, recovery (reference:
GlobalBarrierManager loop + CheckpointControl, barrier/mod.rs:532)."""

import time


from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import build_q5_lite, build_q8
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.storage import MemObjectStore


def _feed(q5, q8, gen, n_epochs, rt):
    for _ in range(n_epochs):
        chunks = gen.next_chunks(1500, 2048)
        if chunks["bid"] is not None:
            q5.pipeline.push(chunks["bid"].select(["auction", "date_time"]))
        if chunks["person"] is not None:
            q8.pipeline.push_left(
                chunks["person"].select(["id", "name", "date_time"])
            )
        if chunks["auction"] is not None:
            q8.pipeline.push_right(
                chunks["auction"].select(["seller", "date_time"])
            )
        rt.barrier()


def test_runtime_two_fragments_async_checkpoint_and_recovery():
    store = MemObjectStore()
    rt = StreamingRuntime(store, async_checkpoint=True)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    q8 = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    rt.register("q5", q5.pipeline)
    rt.register("q8", q8.pipeline)

    dicts = NexmarkGenerator.make_dictionaries()
    gen = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    _feed(q5, q8, gen, 5, rt)
    rt.wait_checkpoints()
    snap5, snap8 = q5.mview.snapshot(), q8.mview.snapshot()
    assert len(snap5) > 100 and len(snap8) > 10
    assert rt.p99_barrier_ms() > 0

    # recover into a fresh runtime + fresh fragments, on a FORKED copy
    # of the store (two live clusters must not share one store: each
    # compacts/GCs SSTs the other's manifest still references)
    store2 = MemObjectStore()
    store2._blobs = dict(store._blobs)
    rt2 = StreamingRuntime(store2, async_checkpoint=True)
    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    q8b = build_q8(capacity=1 << 12, fanout=8, out_cap=1 << 14)
    rt2.register("q5", q5b.pipeline)
    rt2.register("q8", q8b.pipeline)
    rt2.recover()
    assert q5b.mview.snapshot() == snap5
    assert q8b.mview.snapshot() == snap8
    assert rt2.epoch == rt.mgr.max_committed_epoch

    # both runtimes continue identically on identical traffic
    gen_b = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    for _ in range(5):
        gen_b.next_chunks(1500, 2048)
    _feed(q5, q8, gen, 3, rt)
    _feed(q5b, q8b, gen_b, 3, rt2)
    rt.wait_checkpoints()
    rt2.wait_checkpoints()
    assert q5b.mview.snapshot() == q5.mview.snapshot()
    assert q8b.mview.snapshot() == q8.mview.snapshot()


def test_runtime_checkpoint_frequency():
    store = MemObjectStore()
    rt = StreamingRuntime(store, checkpoint_frequency=3, async_checkpoint=False)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig())
    committed = []
    for _ in range(6):
        bid = gen.next_chunks(800, 1024)["bid"]
        q5.pipeline.push(bid.select(["auction", "date_time"]))
        rt.barrier()
        committed.append(rt.mgr.max_committed_epoch)
    # only barriers 3 and 6 commit
    assert committed[0] == committed[1] == 0
    assert committed[2] > 0
    assert committed[3] == committed[4] == committed[2]
    assert committed[5] > committed[2]


def test_runtime_tick_paces_barriers():
    rt = StreamingRuntime(None, barrier_interval_ms=50)
    # sized so the table never grows inside the timed window: a growth
    # rebuild legitimately recompiles the agg programs (~seconds) and
    # this test is about tick pacing, not compile latency
    q5 = build_q5_lite(capacity=1 << 14, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    gen = NexmarkGenerator(NexmarkConfig())
    # warm the jit caches so compile time doesn't eat the tick window
    bid = gen.next_chunks(200, 256)["bid"]
    q5.pipeline.push(bid.select(["auction", "date_time"]))
    # three warm barriers: flush + device-MV + packed-latch programs
    # compile across the first couple of barriers, not just the first
    for _ in range(3):
        bid = gen.next_chunks(200, 256)["bid"]
        if bid is not None:
            q5.pipeline.push(bid.select(["auction", "date_time"]))
        rt.barrier()
    fired = 0
    t_end = time.time() + 0.55
    while time.time() < t_end:
        bid = gen.next_chunks(200, 256)["bid"]
        if bid is not None:
            q5.pipeline.push(bid.select(["auction", "date_time"]))
        fired += rt.tick()
        time.sleep(0.005)
    assert 4 <= fired <= 12  # ~0.55s / 50ms, with scheduling slop
