"""Online re-partitioning: ScaleController reschedules a running
sharded fragment onto a different mesh size with exact state handover.

Reference: src/meta/src/stream/scale.rs:453 (Reschedule), recovery-based
rescale (barrier/recovery.rs:415), auto-parallelism policy.
"""

import jax.numpy as jnp
import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.parallel import ShardedHashAgg, make_mesh
from risingwave_tpu.parallel.scale import ScaleController
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.runtime import Pipeline, StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore

CALLS = (AggCall("count_star", None, "cnt"), AggCall("sum", "price", "total"))
DTYPES = {"auction": jnp.int64, "price": jnp.int64}


def _mk_sharded(n_shards, capacity=1 << 10):
    return ShardedHashAgg(
        make_mesh(n_shards),
        ("auction",),
        CALLS,
        DTYPES,
        capacity=capacity,
        out_cap=1 << 9,
        table_id="sagg",
    )


def _replay(snap, chunk):
    d = chunk.to_numpy(with_ops=True)
    for i in range(len(d["__op__"])):
        key = int(d["auction"][i])
        if d["__op__"][i] in (1, 2):
            snap.pop(key, None)
        else:
            snap[key] = (int(d["cnt"][i]), int(d["total"][i]))
    return snap


def _gens(n):
    dicts = NexmarkGenerator.make_dictionaries()
    return [
        NexmarkGenerator(
            NexmarkConfig(), split_index=i, split_num=n, dictionaries=dicts
        )
        for i in range(n)
    ]


@pytest.mark.slow
def test_reschedule_4_to_8_shards_exact():
    """Epochs at 4 shards -> online reschedule to 8 -> more epochs:
    output matches an unrescheduled single-chip twin throughout."""
    rt = StreamingRuntime(MemObjectStore())
    sharded = _mk_sharded(4)
    rt.register("agg", Pipeline([sharded]))
    ctl = ScaleController(rt)

    single = HashAggExecutor(
        ("auction",), CALLS, DTYPES, capacity=1 << 12, out_cap=1 << 11
    )
    snap_s, snap_1 = {}, {}

    def run_epoch(n_feed, sharded_now):
        per_shard = []
        for g in gens[:n_feed]:
            bid = g.next_chunks(300, 512)["bid"].select(["auction", "price"])
            per_shard.append(bid)
            single.apply(bid)
        sharded_now.apply(stack_chunks(per_shard))
        for out in rt.barrier()["agg"]:
            _replay(snap_s, out)
        for out in single.on_barrier(None):
            _replay(snap_1, out)

    gens = _gens(8)
    run_epoch(4, sharded)
    run_epoch(4, sharded)
    assert snap_s == snap_1 and snap_s

    new = ctl.reschedule("agg", lambda old: Pipeline([_mk_sharded(8)]))
    sharded8 = new.executors[0]
    assert sharded8.n_shards == 8
    assert ctl.reschedules == 1

    run_epoch(8, sharded8)
    run_epoch(8, sharded8)
    assert snap_s == snap_1
    # groups really did spread over all 8 shards
    occ = sharded8.shard_occupancy()
    assert (occ > 0).sum() == 8


def test_autoscale_doubles_on_hot_shard():
    rt = StreamingRuntime(MemObjectStore())
    sharded = _mk_sharded(2, capacity=1 << 8)
    rt.register("agg", Pipeline([sharded]))
    ctl = ScaleController(rt)

    gens = _gens(2)
    per_shard = [
        g.next_chunks(300, 512)["bid"].select(["auction", "price"])
        for g in gens
    ]
    sharded.apply(stack_chunks(per_shard))
    rt.barrier()

    new = ctl.autoscale(
        "agg",
        rebuild_at=lambda n: Pipeline([_mk_sharded(n, capacity=1 << 8)]),
        max_shard_load=0.004,  # force the policy to trip (the table
        # may have auto-grown, shrinking relative load)
    )
    assert new is not None
    assert new.executors[0].n_shards == 4

    # and a fragment under the threshold does nothing
    assert (
        ctl.autoscale(
            "agg",
            rebuild_at=lambda n: Pipeline([_mk_sharded(n)]),
            max_shard_load=0.99,
        )
        is None
    )
