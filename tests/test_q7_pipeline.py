"""End-to-end Nexmark q7 (highest bid per tumble window): MV snapshot
vs a pandas oracle; exercises the join's retraction path (every new
window max retracts the old max's pairs)."""

import numpy as np
import pandas as pd

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import build_q7

WINDOW_MS = 10_000


def _oracle(bids):
    df = pd.DataFrame(bids)
    df["wstart"] = (df["date_time"] // WINDOW_MS) * WINDOW_MS
    mx = df.groupby("wstart")["price"].max().rename("maxprice").reset_index()
    m = df.merge(mx, left_on=["wstart", "price"], right_on=["wstart", "maxprice"])
    return {
        (int(r.wstart), int(r.auction), int(r.bidder)): (int(r.price),)
        for r in m.itertuples()
    }


def _push_bid(q7, chunk):
    q7.pipeline.push_left(chunk)
    q7.pipeline.push_right(chunk)


def test_q7_pipeline_matches_pandas():
    q7 = build_q7(capacity=1 << 14, fanout=8, out_cap=1 << 14)
    # 500 events/s so 18k events span several 10s windows
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=500))

    all_bids = {"auction": [], "bidder": [], "price": [], "date_time": []}
    for epoch in range(4):
        for _ in range(3):
            bid = gen.next_chunks(1500, 2048)["bid"]
            if bid is None:
                continue
            d = bid.to_numpy(with_ops=False)
            for k in all_bids:
                all_bids[k].extend(d[k].tolist())
            _push_bid(q7, bid.select(["auction", "bidder", "price", "date_time"]))
        q7.pipeline.barrier()

    want = _oracle(all_bids)
    got = q7.mview.snapshot()
    assert len({k[0] for k in want}) >= 3  # several windows covered
    assert got == want


def test_q7_cross_epoch_max_retraction():
    """A higher bid in a later epoch must retract the earlier epoch's
    emitted max pairs for that window."""
    from risingwave_tpu.array.chunk import StreamChunk

    q7 = build_q7(capacity=1 << 10, fanout=8, out_cap=1 << 10)

    def bid_chunk(rows):
        cols = {
            "auction": np.array([r[0] for r in rows], np.int64),
            "bidder": np.array([r[1] for r in rows], np.int64),
            "price": np.array([r[2] for r in rows], np.int64),
            "date_time": np.array([r[3] for r in rows], np.int64),
        }
        return StreamChunk.from_numpy(cols, 64)

    # epoch 1: window 0 max is 100 (auction 1, bidder 10)
    _push_bid(q7, bid_chunk([(1, 10, 100, 1000), (2, 20, 50, 2000)]))
    q7.pipeline.barrier()
    assert q7.mview.snapshot() == {(0, 1, 10): (100,)}

    # epoch 2: bidder 30 outbids in the same window; old pair retracts
    _push_bid(q7, bid_chunk([(3, 30, 120, 3000)]))
    q7.pipeline.barrier()
    assert q7.mview.snapshot() == {(0, 3, 30): (120,)}

    # epoch 3: tie at the max in the same window -> both pairs present
    _push_bid(q7, bid_chunk([(4, 40, 120, 4000)]))
    q7.pipeline.barrier()
    assert q7.mview.snapshot() == {
        (0, 3, 30): (120,),
        (0, 4, 40): (120,),
    }


def test_q7_watermark_keeps_state_bounded():
    q7 = build_q7(capacity=1 << 14, fanout=8, out_cap=1 << 14)
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=500))

    max_ts = 0
    for epoch in range(6):
        bid = gen.next_chunks(1500, 2048)["bid"]
        d = bid.to_numpy(with_ops=False)
        max_ts = max(max_ts, int(d["date_time"].max()))
        _push_bid(q7, bid.select(["auction", "bidder", "price", "date_time"]))
        q7.pipeline.barrier()
        q7.pipeline.watermark("date_time", max_ts)

    # closed windows' bid state is gone from the join's left side
    cutoff = (max_ts - WINDOW_MS) // WINDOW_MS * WINDOW_MS
    lane = np.asarray(q7.join.left.table.keys[0])
    live = np.asarray(q7.join.left.table.live)
    assert live.sum() > 0
    assert (lane[live] >= cutoff).all()
    # MV still holds every closed window's answer
    assert len({k[0] for k in q7.mview.snapshot()}) >= 2
