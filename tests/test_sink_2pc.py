"""Coordinated 2PC sinks: exactly-once external delivery across every
crash window (VERDICT r4 missing #10; reference:
src/meta/src/manager/sink_coordination/)."""

import pytest

from risingwave_tpu.connectors.log_store import KvLogStore
from risingwave_tpu.connectors.sink2pc import (
    FileTwoPhaseSink,
    SinkCoordinator,
)
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke


def _mk(tmp_path):
    log = KvLogStore(MemObjectStore(), "s1")
    sink = FileTwoPhaseSink(str(tmp_path))
    return log, sink, SinkCoordinator(log, sink)


def _batch(epoch):
    return [((epoch,), (epoch * 10,), 0)]


def test_exactly_once_across_crash_windows(tmp_path):
    log, sink, coord = _mk(tmp_path)
    for e in (1, 2, 3):
        log.append(e << 16, _batch(e))

    # window A: crash AFTER prepare, BEFORE commit (epoch 1)
    rows = log.read(1 << 16)
    sink.prepare(rows, 1 << 16)
    # "crash" -> recovery aborts staged epochs, replay re-runs
    coord.recover()
    coord.run_once(up_to=3 << 16)
    assert sink.committed_epochs() == [1 << 16, 2 << 16, 3 << 16]
    assert sink.read_committed(1 << 16) == [((1,), (10,), 0)]

    # window B: crash AFTER commit, BEFORE offset advance (epoch 4)
    log.append(4 << 16, _batch(4))
    rows = log.read(4 << 16)
    sink.prepare(rows, 4 << 16)
    sink.commit_prepared(4 << 16)
    # offset NOT advanced: a rerun must not duplicate the publish
    coord.recover()
    n = coord.run_once(up_to=4 << 16)
    assert n == 1  # the replayed epoch delivers once
    assert sink.committed_epochs().count(4 << 16) == 1
    assert log.committed_offset() == 4 << 16

    # idempotent rerun: nothing pending, nothing re-published
    assert coord.run_once(up_to=4 << 16) == 0
    assert sink.committed_epochs() == [
        1 << 16, 2 << 16, 3 << 16, 4 << 16,
    ]


def test_rolled_back_epoch_never_published(tmp_path):
    log, sink, coord = _mk(tmp_path)
    log.append(1 << 16, _batch(1))
    log.append(2 << 16, _batch(2))  # NOT durable yet
    coord.run_once(up_to=1 << 16)  # durable frontier = epoch 1
    assert sink.committed_epochs() == [1 << 16]
    # epoch 2 rolls back; replay regenerates it with different content
    log.discard_above(1 << 16)
    log.append(2 << 16, [((9,), (99,), 0)])
    coord.run_once(up_to=2 << 16)
    assert sink.read_committed(2 << 16) == [((9,), (99,), 0)]
    assert sink.committed_epochs() == [1 << 16, 2 << 16]


class FlakyTwoPhaseSink(FileTwoPhaseSink):
    """A flaky external coordinator: the first ``fail_prepares`` /
    ``fail_commits`` calls of each phase raise a transient fault."""

    def __init__(self, root, fail_prepares=0, fail_commits=0):
        super().__init__(root)
        self.fail_prepares = fail_prepares
        self.fail_commits = fail_commits
        self.faults = 0

    def prepare(self, rows, epoch):
        if self.fail_prepares > 0:
            self.fail_prepares -= 1
            self.faults += 1
            raise TransientStoreError("flaky coordinator: prepare")
        super().prepare(rows, epoch)

    def commit_prepared(self, epoch):
        if self.fail_commits > 0:
            self.fail_commits -= 1
            self.faults += 1
            raise TransientStoreError("flaky coordinator: commit")
        super().commit_prepared(epoch)


from risingwave_tpu.resilience import (  # noqa: E402
    RetryBudgetExceeded,
    RetryPolicy,
    TransientStoreError,
)

_FAST = RetryPolicy(
    max_attempts=6, base_backoff_s=1e-4, max_backoff_s=1e-3, deadline_s=5.0
)


def test_flaky_coordinator_exactly_once(tmp_path):
    """Satellite: a flaky coordinator (transient prepare AND commit
    failures mid-drain) must still yield exactly-once sink output after
    retry — no duplicate, no lost commit."""
    log = KvLogStore(MemObjectStore(), "s_flaky")
    sink = FlakyTwoPhaseSink(
        str(tmp_path), fail_prepares=2, fail_commits=2
    )
    coord = SinkCoordinator(log, sink, retry_policy=_FAST)
    for e in (1, 2, 3):
        log.append(e << 16, _batch(e))
    n = coord.run_once(up_to=3 << 16)
    assert sink.faults == 4  # both phases actually flaked
    assert n == 3  # delivered across retries, counted once each
    assert sink.committed_epochs() == [1 << 16, 2 << 16, 3 << 16]
    for e in (1, 2, 3):
        assert sink.read_committed(e << 16) == _batch(e)
    assert log.committed_offset() == 3 << 16
    # idempotent rerun: nothing pending, nothing re-published
    assert coord.run_once(up_to=3 << 16) == 0


def test_flaky_coordinator_bounded_giveup(tmp_path):
    """A coordinator that stays down exhausts the retry budget and
    surfaces — having delivered nothing externally visible."""
    log = KvLogStore(MemObjectStore(), "s_down")
    sink = FlakyTwoPhaseSink(str(tmp_path), fail_commits=10**6)
    coord = SinkCoordinator(
        log, sink,
        retry_policy=RetryPolicy(
            max_attempts=3, base_backoff_s=1e-4, deadline_s=1.0
        ),
    )
    log.append(1 << 16, _batch(1))
    with pytest.raises(RetryBudgetExceeded):
        coord.run_once(up_to=1 << 16)
    assert sink.committed_epochs() == []  # nothing published
    assert log.committed_offset() == 0  # offset never ran ahead
    # heal -> the SAME epoch delivers exactly once
    sink.fail_commits = 0
    assert coord.run_once(up_to=1 << 16) == 1
    assert sink.committed_epochs() == [1 << 16]


def _fold_delivered(log_reader, epochs):
    """Apply delivered batches in epoch order -> final pk->row view
    (the externally observable state, independent of epoch numbering —
    runs with different barrier boundaries must still agree here)."""
    state = {}
    for e in epochs:
        for pk, row, _op in log_reader(e):
            if row is None:
                state.pop(pk, None)
            else:
                state[pk] = row
    return state


def test_actor_crash_partial_recovery_exactly_once(tmp_path):
    """Satellite: an ACTOR crash (not a store crash) mid-epoch, healed
    by fragment-scoped partial recovery — sink delivery stays exactly-
    once and the log's offset frontier never double-counts after the
    subtree replays."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.connectors.log_store import LogStoreSinkExecutor
    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.ops.agg import AggCall
    from risingwave_tpu.runtime.fragmenter import GraphPipeline
    from risingwave_tpu.runtime.graph import FragmentSpec
    from risingwave_tpu.runtime.runtime import StreamingRuntime
    from risingwave_tpu.sim import CrashingExecutor
    from risingwave_tpu.storage.object_store import MemObjectStore

    def run(crashing: bool, root: str):
        rt = StreamingRuntime(
            MemObjectStore(), async_checkpoint=False, auto_recover=True
        )
        crash = CrashingExecutor("sink_mv")
        log = KvLogStore(MemObjectStore(), "s_actor")
        sink = FileTwoPhaseSink(root)
        coord = SinkCoordinator(log, sink, retry_policy=_FAST)

        def chain_of(name, with_crash, with_sink):
            agg = HashAggExecutor(
                group_keys=("k",),
                calls=(AggCall("sum", "v", "s"),),
                schema_dtypes={"k": jnp.int64, "v": jnp.int64},
                capacity=1 << 8,
                table_id=f"{name}.agg",
            )
            mv = MaterializeExecutor(
                pk=("k",), columns=("s",), table_id=f"{name}.mview"
            )
            chain = ([crash] if with_crash else []) + [agg, mv]
            if with_sink:
                chain.append(
                    LogStoreSinkExecutor(log, pk=("k",), columns=("s",))
                )
            specs = [
                FragmentSpec("src", lambda i: []),
                FragmentSpec(
                    "work", lambda i, c=tuple(chain): list(c),
                    inputs=[("src", 0)],
                ),
            ]
            gp = GraphPipeline(
                specs, {"single": "src"}, "work", chain,
                ckpt_fragments=["work"] * len(chain),
            )
            return gp, mv

        gpa, _mva = chain_of("other", False, False)
        gpb, mvb = chain_of("sunk", crashing, True)
        rt.register("other", gpa)
        rt.register("sunk", gpb)
        rng = np.random.default_rng(17)
        for i in range(5):
            n = int(rng.integers(4, 10))
            c = StreamChunk.from_numpy(
                {"k": rng.integers(0, 4, n).astype(np.int64),
                 "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
            )
            if crashing and i == 2:
                crash.arm("apply", after=1)  # mid-epoch actor murder
            rt.push("other", c)
            rt.push("sunk", c)
            before = rt.mgr.max_committed_epoch
            rt.barrier()
            if rt.mgr.max_committed_epoch == before:
                assert rt.last_recovery_mode == "partial"
                rt.barrier()  # the replayed subtree rejoins + commits
            # drain only up to the DURABLE frontier, like a production
            # sinker loop
            coord.run_once(up_to=rt.mgr.max_committed_epoch)
        rt.wait_checkpoints()
        coord.run_once(up_to=rt.mgr.max_committed_epoch)
        if crashing:
            assert crash.kills == 1
            assert rt.partial_recoveries == 1
        epochs = sink.committed_epochs()
        folded = _fold_delivered(sink.read_committed, epochs)
        gpa.close()
        gpb.close()
        return epochs, folded, dict(mvb.snapshot()), log

    epochs, folded, mv_snap, log = run(True, str(tmp_path / "chaos"))
    _epochs2, folded2, mv_snap2, _log2 = run(False, str(tmp_path / "clean"))

    # exactly-once: every epoch published at most once, the fold of
    # what was EXTERNALLY delivered equals the fault-free run's fold
    # AND the MV itself (nothing lost, nothing doubled)
    assert len(epochs) == len(set(epochs))
    assert epochs == sorted(epochs)
    assert folded == folded2
    assert folded == {k: v for k, v in mv_snap.items()}
    assert mv_snap == mv_snap2
    # the offset frontier never double-counts: nothing left pending,
    # and a re-drain delivers zero
    assert log.pending_epochs() == []
    assert log.committed_offset() == max(epochs)


def test_crash_between_prepare_and_commit_with_flaky_replay(tmp_path):
    """Satellite: crash lands BETWEEN prepare and commit; the replaying
    coordinator is itself flaky — recovery aborts the stage, the
    retried replay re-prepares and publishes exactly once."""
    log = KvLogStore(MemObjectStore(), "s_crash")
    sink = FlakyTwoPhaseSink(str(tmp_path))
    coord = SinkCoordinator(log, sink, retry_policy=_FAST)
    log.append(1 << 16, _batch(1))
    sink.prepare(log.read(1 << 16), 1 << 16)
    # -- crash here: staged, never committed, offset never advanced --
    sink2 = FlakyTwoPhaseSink(str(tmp_path), fail_prepares=1, fail_commits=1)
    coord2 = SinkCoordinator(log, sink2, retry_policy=_FAST)
    coord2.recover()  # aborts the staged epoch
    import os

    assert not os.path.exists(sink2._staging(1 << 16))
    assert coord2.run_once(up_to=1 << 16) == 1
    assert sink2.committed_epochs() == [1 << 16]
    assert sink2.read_committed(1 << 16) == _batch(1)
    # a second replay after the publish is a no-op (no duplicates)
    coord2.recover()
    assert coord2.run_once(up_to=1 << 16) == 0
    assert sink2.committed_epochs() == [1 << 16]
