"""Coordinated 2PC sinks: exactly-once external delivery across every
crash window (VERDICT r4 missing #10; reference:
src/meta/src/manager/sink_coordination/)."""

import pytest

from risingwave_tpu.connectors.log_store import KvLogStore
from risingwave_tpu.connectors.sink2pc import (
    FileTwoPhaseSink,
    SinkCoordinator,
)
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke


def _mk(tmp_path):
    log = KvLogStore(MemObjectStore(), "s1")
    sink = FileTwoPhaseSink(str(tmp_path))
    return log, sink, SinkCoordinator(log, sink)


def _batch(epoch):
    return [((epoch,), (epoch * 10,), 0)]


def test_exactly_once_across_crash_windows(tmp_path):
    log, sink, coord = _mk(tmp_path)
    for e in (1, 2, 3):
        log.append(e << 16, _batch(e))

    # window A: crash AFTER prepare, BEFORE commit (epoch 1)
    rows = log.read(1 << 16)
    sink.prepare(rows, 1 << 16)
    # "crash" -> recovery aborts staged epochs, replay re-runs
    coord.recover()
    coord.run_once(up_to=3 << 16)
    assert sink.committed_epochs() == [1 << 16, 2 << 16, 3 << 16]
    assert sink.read_committed(1 << 16) == [((1,), (10,), 0)]

    # window B: crash AFTER commit, BEFORE offset advance (epoch 4)
    log.append(4 << 16, _batch(4))
    rows = log.read(4 << 16)
    sink.prepare(rows, 4 << 16)
    sink.commit_prepared(4 << 16)
    # offset NOT advanced: a rerun must not duplicate the publish
    coord.recover()
    n = coord.run_once(up_to=4 << 16)
    assert n == 1  # the replayed epoch delivers once
    assert sink.committed_epochs().count(4 << 16) == 1
    assert log.committed_offset() == 4 << 16

    # idempotent rerun: nothing pending, nothing re-published
    assert coord.run_once(up_to=4 << 16) == 0
    assert sink.committed_epochs() == [
        1 << 16, 2 << 16, 3 << 16, 4 << 16,
    ]


def test_rolled_back_epoch_never_published(tmp_path):
    log, sink, coord = _mk(tmp_path)
    log.append(1 << 16, _batch(1))
    log.append(2 << 16, _batch(2))  # NOT durable yet
    coord.run_once(up_to=1 << 16)  # durable frontier = epoch 1
    assert sink.committed_epochs() == [1 << 16]
    # epoch 2 rolls back; replay regenerates it with different content
    log.discard_above(1 << 16)
    log.append(2 << 16, [((9,), (99,), 0)])
    coord.run_once(up_to=2 << 16)
    assert sink.read_committed(2 << 16) == [((9,), (99,), 0)]
    assert sink.committed_epochs() == [1 << 16, 2 << 16]
