"""Shape-stability hardening (runtime/bucketing.py, PR 9): the pow2
bucket allocator's grow-eager/shrink-lazy hysteresis, emission
bucketing mask correctness at exactly-full/one-over boundaries, the
bucket-boundary-oscillation recompile bound (one trace per bucket,
never per shape), RW-E806 lattice validation + strict-fusion DDL
refusal, the recompile-storm ShapeGovernor (budget + SLOW-device
proactive throttle, runtime-wired), and the q7 bucketed-vs-unbucketed
bit-identical twin. The adversarial q7 soak rides the slow tier."""

import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor, Watermark
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    ShapeGovernor,
    emission_bucket,
    lattice_between,
    padding_stats,
    pow2_at_least,
    validate_lattice,
)

pytestmark = pytest.mark.smoke

I64 = jnp.int64


def _chunk(ws, ps, cap=None):
    ws = np.asarray(ws, np.int64)
    ps = np.asarray(ps, np.int64)
    return StreamChunk.from_numpy(
        {"w": ws, "p": ps}, cap or pow2_at_least(max(len(ws), 2))
    )


# ---------------------------------------------------------------------------
# lattice + allocator units
# ---------------------------------------------------------------------------


def test_pow2_lattice_helpers():
    assert pow2_at_least(1) == 1
    assert pow2_at_least(5) == 8
    assert pow2_at_least(64) == 64
    assert lattice_between(16, 128) == (16, 32, 64, 128)
    assert lattice_between(10, 10) == (16,)
    assert emission_bucket(0) == 2
    assert emission_bucket(4) == 4  # exactly-full: no extra padding
    assert emission_bucket(5) == 8  # one-over: next bucket
    assert validate_lattice((4, 8, 16)) is None
    assert "power of two" in validate_lattice((3, 8))
    assert "empty" in validate_lattice(())
    assert "increasing" in validate_lattice((8, 8))
    assert "increasing" in validate_lattice((16, 8))
    assert validate_lattice("nope") is not None
    assert "bound" in validate_lattice((1 << 30,))


def test_policy_from_capacity_and_env(monkeypatch):
    p = BucketPolicy.from_capacity(1 << 10)
    assert p.min_cap == 1 << 10
    assert p.lattice()[0] == 1 << 10
    assert p.lattice()[-1] == p.max_cap
    assert validate_lattice(p.lattice()) is None
    monkeypatch.setenv("RW_BUCKET_MAX_STEPS", "2")
    p2 = BucketPolicy.from_capacity(1 << 10)
    assert p2.lattice() == (1 << 10, 1 << 11, 1 << 12)
    with pytest.raises(ValueError):
        BucketPolicy(min_cap=24, max_cap=48)  # not pow2
    with pytest.raises(ValueError):
        BucketPolicy(min_cap=16, max_cap=64, shrink_at=0.6)  # >= grow_at


def test_allocator_grows_eagerly_and_clamps_at_max():
    a = BucketAllocator(BucketPolicy(min_cap=16, max_cap=128))
    # under the load factor: no plan needed
    assert not a.should_plan(16, 4, 2)
    # over it: plan fires and returns the smallest fitting bucket NOW
    assert a.should_plan(16, 6, 4)
    assert a.plan(16, incoming=4, claimed=6, survivors=6) == 32
    # demand beyond max_cap clamps (the overflow latch then reports)
    assert a.plan(32, incoming=200, claimed=20, survivors=20) == 128
    assert a.high_water == 128


def test_allocator_shrinks_lazily_with_hysteresis():
    pol = BucketPolicy(min_cap=16, max_cap=256, patience=3)
    a = BucketAllocator(pol)
    # occupancy far below shrink_at*cap, but only patience barriers in
    # a row earn a pending shrink
    a.note_barrier(128, 4)
    a.note_barrier(128, 4)
    assert not a.should_plan(128, 4, 2)
    a.note_barrier(128, 4)  # patience reached
    assert a.should_plan(128, 4, 2)
    got = a.plan(128, incoming=2, claimed=4, survivors=4)
    assert got is not None and got < 128 and got >= 16
    # oscillation at a bucket boundary NEVER flaps: one loaded barrier
    # resets the streak
    b = BucketAllocator(pol)
    for _ in range(10):
        b.note_barrier(128, 4)  # idle...
        b.note_barrier(128, 100)  # ...then loaded again
        assert not b.should_plan(128, 4, 2)
    # a pending shrink still respects what the next chunk needs
    c = BucketAllocator(pol)
    for _ in range(3):
        c.note_barrier(256, 8)
    assert c.plan(256, incoming=100, claimed=8, survivors=8) == 256 or (
        c.plan(256, incoming=100, claimed=8, survivors=8) is None
    )


def test_allocator_saturation_stops_per_chunk_replanning():
    """Demand beyond the lattice max must NOT degenerate into a
    blocking read + same-capacity rebuild per chunk: plan() returns
    None once saturated, should_plan() goes quiet until the next
    barrier re-check (the overflow latch owns genuine overflow)."""
    a = BucketAllocator(BucketPolicy(min_cap=16, max_cap=64))
    assert a.plan(16, 40, 10, 10) == 64  # legitimate growth to max
    # survivors alone exceed max_cap * grow_at: nothing to rebuild
    assert a.plan(64, 40, 60, 60) is None
    assert not a.should_plan(64, 60, 40)  # quiet until note_barrier
    a.note_barrier(64, 60)  # barrier re-check re-arms the trigger
    assert a.should_plan(64, 60, 40)
    # a genuine tombstone compaction (survivors fit) still returns cap
    b = BucketAllocator(BucketPolicy(min_cap=16, max_cap=64))
    assert b.plan(64, 8, 60, 10) == 64


def test_unbucketed_twin_keeps_legacy_emission_shapes():
    """The bucketed=False twin must reproduce the LEGACY max(2, n)
    emission capacities — it is the RW-E803 baseline the soak and the
    detection tests compare against."""
    from risingwave_tpu.executors.top_n_plain import TopNExecutor

    tn = TopNExecutor(
        "p", 5, ("k",), {"k": I64, "p": I64}, desc=True, capacity=64,
        bucketed=False,
    )
    tn.apply(
        StreamChunk.from_numpy(
            {
                "k": np.arange(9, dtype=np.int64),
                "p": np.arange(9, dtype=np.int64),
            },
            16,
        )
    )
    outs = tn.on_barrier(None)
    assert len(outs) == 1 and outs[0].capacity == 5  # max(2, 5), not 8
    assert tn.trace_contract()["emission"] == "data_dependent"


def test_allocator_pin_freezes_high_water():
    a = BucketAllocator(BucketPolicy(min_cap=16, max_cap=256, patience=1))
    assert a.plan(16, 20, 10, 10) == 64
    assert a.pin() == 64
    # pinned: below-high-water capacity jumps straight back up
    assert a.should_plan(16, 0, 0)
    assert a.plan(16, 0, 0, 0) == 64
    # pinned: no shrink, ever
    for _ in range(5):
        a.note_barrier(64, 1)
    assert not a.should_plan(64, 1, 1)
    snap = a.snapshot()
    assert snap["pinned"] and snap["high_water"] == 64


# ---------------------------------------------------------------------------
# executor integration: lattice-confined capacities + recompile bound
# ---------------------------------------------------------------------------


def test_bucket_boundary_oscillation_one_trace_per_bucket():
    """Satellite 3: drive the q7 pre-filter's window state across
    EVERY pow2 boundary of its declared lattice (growth + churn) —
    total traces of the hot step stay <= lattice size (one per bucket,
    never one per shape), capacities never leave the lattice, and the
    result matches the unbucketed twin exactly."""
    from risingwave_tpu.executors import dynamic_filter as df

    pol = BucketPolicy(min_cap=16, max_cap=128, patience=2)
    mk = lambda **kw: df.DynamicMaxFilterExecutor(
        "w", "p", {"w": I64, "p": I64}, capacity=16,
        window_key=("w", 0), **kw
    )
    ex = mk(bucket_policy=pol)
    lattice = ex._buckets.lattice
    assert lattice == (16, 32, 64, 128)

    # pre-generate the seeded script: window-key domain sweeps upward
    # across every bucket boundary, then churns after an expiry
    rng = np.random.default_rng(7)
    script = []
    for target in (8, 24, 56, 120):
        for _ in range(6):
            script.append(
                (
                    "chunk",
                    _chunk(
                        rng.integers(0, target, size=8),
                        rng.integers(0, 100, size=8),
                        cap=8,
                    ),
                )
            )
    script.append(("wm", 100))
    for _ in range(6):
        script.append(
            (
                "chunk",
                _chunk(
                    rng.integers(0, 140, size=8),
                    rng.integers(0, 100, size=8),
                    cap=8,
                ),
            )
        )

    def drive(executor):
        out, caps = [], set()
        for kind, payload in script:
            if kind == "wm":
                executor.on_watermark(Watermark("w", payload))
                continue
            out.extend(x.to_numpy() for x in executor.apply(payload))
            executor.on_barrier(None)
            caps.add(executor.table.capacity)
        return out, caps

    # trace accounting brackets ONLY the bucketed run (the jit cache
    # is shared process-wide; the unbounded twin would pollute it)
    base = df._filter_step._cache_size()
    out_b, caps_seen = drive(ex)
    traces = df._filter_step._cache_size() - base
    assert caps_seen <= set(lattice), caps_seen
    assert traces <= len(lattice), (
        f"{traces} traces of _filter_step > lattice size {len(lattice)}"
    )
    # bit-identical to the unbucketed twin, row for row
    out_t, _ = drive(mk(bucketed=False))
    assert len(out_b) == len(out_t)
    for got, want in zip(out_b, out_t):
        assert set(got) == set(want)
        for k in got:
            np.testing.assert_array_equal(got[k], want[k])


def test_emission_mask_exactly_full_and_one_over():
    """Bucketed host-diff emissions: a delta of exactly 2^k rows rides
    a 2^k-capacity chunk (all lanes valid), 2^k+1 rides the next
    bucket with the padding masked out — visible rows exact both
    ways."""
    from risingwave_tpu.executors.dynamic_filter import (
        DynamicFilterExecutor,
    )
    from risingwave_tpu.types import Op

    def flip_rows(n):
        """Store n rows passing, then move the rv so ALL n flip."""
        ex = DynamicFilterExecutor(
            "p", "<", ("k",), {"k": I64, "p": I64}, capacity=64
        )
        ks = np.arange(n, dtype=np.int64)
        ps = np.full(n, 10, np.int64)
        ex.apply_left(
            StreamChunk.from_numpy(
                {"k": ks, "p": ps}, pow2_at_least(max(n, 2))
            )
        )
        # rv=100: all pass (10 < 100)
        ex.apply_right(
            StreamChunk.from_numpy(
                {"k": np.zeros(1, np.int64), "p": np.asarray([100], np.int64)},
                2,
                ops=np.asarray([int(Op.INSERT)], np.int32),
            )
        )
        ex.on_barrier(None)
        # rv=5: all n retract in ONE barrier diff
        ex.apply_right(
            StreamChunk.from_numpy(
                {"k": np.zeros(1, np.int64), "p": np.asarray([5], np.int64)},
                2,
                ops=np.asarray([int(Op.INSERT)], np.int32),
            )
        )
        outs = ex.on_barrier(None)
        assert len(outs) == 1
        return outs[0]

    # exactly-full boundary: 4 flipped rows -> capacity 4, no padding
    out4 = flip_rows(4)
    assert out4.capacity == 4
    assert int(np.asarray(out4.valid).sum()) == 4
    assert sorted(out4.to_numpy()["k"].tolist()) == [0, 1, 2, 3]
    # one-over boundary: 5 flipped rows -> capacity 8, 3 masked lanes
    out5 = flip_rows(5)
    assert out5.capacity == 8
    assert int(np.asarray(out5.valid).sum()) == 5
    assert sorted(out5.to_numpy()["k"].tolist()) == [0, 1, 2, 3, 4]


def test_padding_stats_accounting():
    from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor

    ex = AppendOnlyDedupExecutor(("w",), {"w": I64}, capacity=32)
    ex.apply(
        StreamChunk.from_numpy(
            {"w": np.arange(5, dtype=np.int64)}, 8
        )
    )
    ex.on_barrier(None)
    st = padding_stats([ex, object()])  # non-participants skipped
    assert st["capacity_lanes"] == 32
    assert st["live_lanes"] == 5
    assert 0.0 <= st["wasted_lane_frac"] <= 1.0
    per = st["per_executor"]["AppendOnlyDedupExecutor"]
    assert per["live"] == 5 and per["capacity"] == 32


# ---------------------------------------------------------------------------
# RW-E806 + strict-fusion DDL refusal
# ---------------------------------------------------------------------------


class _BadLatticeExecutor(Executor):
    """Window-keyed, declares a lattice the bucketing layer cannot
    satisfy (not pow2)."""

    window_key = ("w", 1000)

    def lint_info(self):
        return {"window_key": "w"}

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: c,
            "state": None,
            "donate": True,
            "emission": "passthrough",
            "window_buckets": (3, 5),
        }


def test_e806_unsatisfiable_lattice_flags_and_refuses(monkeypatch):
    from risingwave_tpu.analysis.fusion_analyzer import classify_executor
    from risingwave_tpu.analysis.diagnostics import PlanLintError
    from risingwave_tpu.analysis.lint import fusion_findings_for_ddl
    from risingwave_tpu.analysis.shape_domain import ChunkSpec
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime import Pipeline, StreamingRuntime
    from risingwave_tpu.sql import Catalog

    spec = ChunkSpec.from_schema({"w": "int64", "p": "int64"})
    ec = classify_executor(_BadLatticeExecutor(), spec, "f", 0)
    codes = [d.code for d in ec.blockers]
    assert "RW-E806" in codes
    assert "RW-E803" not in codes  # declared, just unsatisfiable
    assert not ec.fusible

    class Shim:
        name = "bad"
        pipeline = Pipeline([_BadLatticeExecutor()])

    diags = fusion_findings_for_ddl(Shim())
    assert diags and all(d.code == "RW-E806" for d in diags)
    session = SqlSession(Catalog({}), StreamingRuntime(store=None))
    monkeypatch.delenv("RW_STRICT_FUSION", raising=False)
    # strict-fusion default is ON: the vacuous lattice is refused
    with pytest.raises(PlanLintError):
        session._fusion_lint(Shim(), strict=True)
    monkeypatch.setenv("RW_STRICT_FUSION", "0")
    session._fusion_lint(Shim(), strict=True)  # report-only escape


def test_valid_lattices_do_not_flag_e806():
    from risingwave_tpu.analysis.fusion_analyzer import classify_executor
    from risingwave_tpu.analysis.shape_domain import ChunkSpec
    from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor

    ex = AppendOnlyDedupExecutor(
        ("w",), {"w": I64}, capacity=32, window_key=("w", 0)
    )
    spec = ChunkSpec.from_schema({"w": "int64"})
    ec = classify_executor(ex, spec, "f", 0)
    codes = {d.code for d in ec.blockers}
    assert "RW-E803" not in codes and "RW-E806" not in codes


# ---------------------------------------------------------------------------
# recompile-storm governor
# ---------------------------------------------------------------------------


def _observe_capacities(watch, ex, caps):
    for cap in caps:
        watch.observe(
            ex,
            StreamChunk.from_numpy(
                {"w": np.arange(2, dtype=np.int64)}, cap
            ),
        )


def test_governor_pins_over_budget_and_records():
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.event_log import EVENT_LOG
    from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
    from risingwave_tpu.metrics import REGISTRY

    ex = AppendOnlyDedupExecutor(("w",), {"w": I64}, capacity=32)
    gov = ShapeGovernor(budget=2)
    SIGNATURES.start()
    try:
        _observe_capacities(SIGNATURES, ex, [8])  # warmup shape
        SIGNATURES.mark_stable()
        _observe_capacities(SIGNATURES, ex, [16, 64])  # 2 hazards
        assert gov.observe_barrier([ex]) == []  # == budget: no pin yet
        assert not ex._buckets.pinned
        _observe_capacities(SIGNATURES, ex, [128])  # 3rd: over budget
        acted = gov.observe_barrier([ex])
        assert acted == ["AppendOnlyDedupExecutor"]
        assert ex._buckets.pinned
        info = gov.pinned["AppendOnlyDedupExecutor"]
        assert info["reason"] == "budget_exceeded"
        assert info["action"] == "pin_max_bucket"
        # idempotent: further hazards never re-pin
        _observe_capacities(SIGNATURES, ex, [256])
        assert gov.observe_barrier([ex]) == []
        # surfaces: event + metric + snapshot
        evs = EVENT_LOG.events(kind="shape_governor")
        assert evs and evs[-1]["executor"] == "AppendOnlyDedupExecutor"
        assert (
            REGISTRY.counter("shape_governor_actions_total").get(
                executor="AppendOnlyDedupExecutor",
                action="pin_max_bucket",
                reason="budget_exceeded",
            )
            >= 1
        )
        assert gov.snapshot()["hazards"]["AppendOnlyDedupExecutor"] >= 3
    finally:
        SIGNATURES.stop()


def test_governor_slow_device_throttles_proactively(monkeypatch):
    """A SLOW sentinel heartbeat drops the budget to zero: the FIRST
    hazard pins, before the device degrades to WEDGED."""
    from risingwave_tpu import blackbox
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor

    ex = AppendOnlyDedupExecutor(("w",), {"w": I64}, capacity=32)
    gov = ShapeGovernor(budget=1000)  # budget alone would never trip
    monkeypatch.setattr(blackbox.SENTINEL, "state", blackbox.SLOW)
    SIGNATURES.start()
    try:
        _observe_capacities(SIGNATURES, ex, [8])
        SIGNATURES.mark_stable()
        _observe_capacities(SIGNATURES, ex, [16])  # ONE hazard
        assert gov.observe_barrier([ex]) == ["AppendOnlyDedupExecutor"]
        assert gov.pinned["AppendOnlyDedupExecutor"]["reason"] == (
            "slow_device"
        )
        assert ex._buckets.pinned
    finally:
        SIGNATURES.stop()


def test_governor_disabled_and_disarmed_paths(monkeypatch):
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES

    assert ShapeGovernor(enabled=False).observe_barrier([]) == []
    monkeypatch.setenv("RW_SHAPE_GOVERNOR", "0")
    assert not ShapeGovernor().enabled
    monkeypatch.delenv("RW_SHAPE_GOVERNOR")
    # SignatureWatch disarmed: the hook is a no-op attribute check
    assert not SIGNATURES.enabled
    assert ShapeGovernor().observe_barrier([]) == []


def test_runtime_barrier_drives_governor(monkeypatch):
    """End to end through StreamingRuntime: shape-unstable pushes pin
    the offender via the runtime's own per-barrier hook."""
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
    from risingwave_tpu.runtime import Pipeline, StreamingRuntime

    monkeypatch.setenv("RW_FUSION_RECOMPILE_BUDGET", "1")
    rt = StreamingRuntime(store=None)
    ex = AppendOnlyDedupExecutor(("w",), {"w": I64}, capacity=32)
    rt.register("f", Pipeline([ex]))
    SIGNATURES.start()
    try:
        rt.push("f", _chunk([1, 2], [0, 0], cap=8))
        rt.barrier()
        SIGNATURES.mark_stable()
        rt.push("f", _chunk([3], [0], cap=16))  # hazard 1
        rt.barrier()
        assert not ex._buckets.pinned  # == budget
        rt.push("f", _chunk([4], [0], cap=64))  # hazard 2 > budget
        rt.barrier()
        assert ex._buckets.pinned
        assert "AppendOnlyDedupExecutor" in rt.shape_governor.pinned
    finally:
        SIGNATURES.stop()


def test_runtime_shape_watch_warmup_env(monkeypatch):
    """RW_SHAPE_WATCH_WARMUP=N arms SignatureWatch at construction and
    flips it stable after N barriers."""
    from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES
    from risingwave_tpu.runtime import Pipeline, StreamingRuntime

    monkeypatch.setenv("RW_SHAPE_WATCH_WARMUP", "2")
    rt = StreamingRuntime(store=None)
    try:
        assert SIGNATURES.enabled and not SIGNATURES._stable
        rt.register("f", Pipeline([]))
        rt.barrier()
        assert not SIGNATURES._stable
        rt.barrier()
        assert SIGNATURES._stable
    finally:
        SIGNATURES.stop()


# ---------------------------------------------------------------------------
# q7: bucketed vs unbucketed twin, bit-identical (tier-1 size)
# ---------------------------------------------------------------------------


def _drive_q7(q7, epochs, rng_seed=11, windows=(4, 20, 4, 24)):
    """Seeded bid stream whose open-window count sweeps across pow2
    bucket boundaries, with watermark-driven expiry between epochs."""
    rng = np.random.default_rng(rng_seed)
    window_ms = 10_000
    ts0 = 0
    for ep in range(epochs):
        n_w = windows[ep % len(windows)]
        n = 32
        ts = ts0 + rng.integers(0, n_w * window_ms, size=n)
        cols = {
            "auction": rng.integers(0, 50, size=n).astype(np.int64),
            "bidder": rng.integers(0, 50, size=n).astype(np.int64),
            "price": rng.integers(1, 200, size=n).astype(np.int64),
            "date_time": ts.astype(np.int64),
        }
        c = StreamChunk.from_numpy(cols, 32)
        q7.pipeline.push_left(c)
        q7.pipeline.push_right(c)
        q7.pipeline.barrier()
        q7.pipeline.watermark("date_time", int(ts.max()))
        if ep % 4 == 3:
            ts0 += 2 * window_ms  # windows close; fresh ones mint


def test_q7_bucketed_bit_identical_to_unbucketed_twin():
    from risingwave_tpu.queries.nexmark_q import build_q7

    mk = lambda **kw: build_q7(
        capacity=1 << 6,
        fanout=8,
        out_cap=1 << 10,
        agg_capacity=1 << 4,
        filter_capacity=1 << 4,
        **kw,
    )
    dev, twin = mk(), mk(bucketed=False)
    _drive_q7(dev, 8)
    _drive_q7(twin, 8)
    got, want = dev.mview.snapshot(), twin.mview.snapshot()
    assert got == want and len(got) > 0
    # and the shipped plan's shapes stayed on the declared lattice
    lat = set(dev.join.trace_contract()["window_buckets"])
    assert dev.join.left.capacity in lat
    assert dev.join.right.capacity in lat


# ---------------------------------------------------------------------------
# adversarial q7 soak (slow tier): zero hazards, zero wedges,
# bit-identical under sustained bucket-boundary churn
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_q7_soak_bucket_boundary_churn_unwedgeable():
    """The PR-9 acceptance soak: a seeded generator oscillates q7's
    open-window population across every pow2 bucket boundary for many
    epochs. After warmup (one full oscillation cycle, visiting the
    buckets) the steady phase must show ZERO recompile hazards and
    ZERO kernel-cache growth (no re-tracing — the wedge mechanism is
    gone), the armed device sentinel must never classify WEDGED, and
    the MV must stay bit-identical to the legacy unbucketed twin."""
    from risingwave_tpu import blackbox
    from risingwave_tpu.analysis.jax_sanitizer import (
        SIGNATURES,
        RecompileWatch,
    )
    from risingwave_tpu.queries.nexmark_q import build_q7

    mk = lambda **kw: build_q7(
        capacity=1 << 8,
        fanout=8,
        out_cap=1 << 12,
        agg_capacity=1 << 5,
        filter_capacity=1 << 5,
        **kw,
    )
    dev, twin = mk(), mk(bucketed=False)
    sentinel = blackbox.DeviceSentinel()
    sentinel.start(interval_s=0.1, slow_ms=5_000, deadline_s=30)
    SIGNATURES.start()
    try:
        execs = (
            list(dev.pipeline.left)
            + list(dev.pipeline.right)
            + [dev.join]
            + list(dev.pipeline.tail)
        )
        gov = ShapeGovernor()
        windows = (4, 40, 8, 64, 4, 48)
        # -- warmup: one full oscillation cycle visits every bucket --
        _drive_q7(dev, len(windows), rng_seed=23, windows=windows)
        _drive_q7(twin, len(windows), rng_seed=23, windows=windows)
        SIGNATURES.mark_stable()
        watch = RecompileWatch()
        watch.snapshot()
        # -- steady phase: 4 more full cycles of the SAME churn ------
        for cycle in range(4):
            _drive_q7(
                dev, len(windows), rng_seed=100 + cycle, windows=windows
            )
            _drive_q7(
                twin, len(windows), rng_seed=100 + cycle, windows=windows
            )
            gov.observe_barrier(execs)
        # zero recompile hazards after warmup (acceptance bar) ...
        assert SIGNATURES.hazard_total() == 0, SIGNATURES.report()
        # ... zero fresh kernel traces (nothing re-traced mid-soak) ...
        deltas = watch.deltas(record=False)
        assert deltas == {}, deltas
        # ... the governor never had to act ...
        assert gov.pinned == {}
        # ... the device never wedged ...
        assert sentinel.wedges == 0
        assert sentinel.wedged_error() is None
        assert sentinel.state != blackbox.WEDGED
        # ... and the result is bit-identical to the unpadded twin
        got, want = dev.mview.snapshot(), twin.mview.snapshot()
        assert got == want and len(got) > 0
    finally:
        SIGNATURES.stop()
        sentinel.stop()
