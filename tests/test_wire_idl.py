"""Wire IDL (layer 0): every cluster frame round-trips through the
protobuf codec (proto/stream_service.proto), and the two-process
cluster runs over it end to end."""

import pytest

from risingwave_tpu.cluster.proto_codec import decode_header, encode_header

pytestmark = pytest.mark.smoke

FRAMES = [
    {"type": "ddl", "sql": "CREATE TABLE t (a BIGINT)"},
    {"type": "chunk", "table": "t", "capacity": 128, "rows": 7},
    {"type": "barrier"},
    {"type": "query", "sql": "SELECT * FROM t"},
    {"type": "status"},
    {"type": "shutdown"},
    {"type": "ok", "tag": "CREATE_TABLE"},
    {"type": "ack", "permits": 42},
    {"type": "barrier_complete", "epoch": 7 << 16, "committed": 6 << 16},
    {"type": "barrier_failed", "committed": 5 << 16},
    {"type": "rows", "tag": "SELECT 2", "data": {"a": [1, None, "x"]}},
    {"type": "status", "committed": 9 << 16},
    {"type": "error", "message": "KeyError('zzz')"},
]


@pytest.mark.parametrize("frame", FRAMES, ids=lambda f: f["type"])
def test_round_trip(frame):
    got = decode_header(encode_header(frame))
    for k, v in frame.items():
        assert got[k] == v, (k, got)


def test_request_response_field_numbers_disjoint():
    """An Ok(tag=...) must NEVER decode as Ddl(sql=...) — response
    oneof fields are offset so the directions cannot alias."""
    got = decode_header(encode_header({"type": "ok", "tag": "CREATE_TABLE"}))
    assert got["type"] == "ok"
    got = decode_header(encode_header({"type": "ddl", "sql": "SELECT 1"}))
    assert got["type"] == "ddl"
