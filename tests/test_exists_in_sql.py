"""EXISTS / NOT EXISTS / IN / NOT IN subqueries from SQL (VERDICT r4
missing #3 remainder): decorrelated into left-semi/anti joins
(binder/expr/subquery.rs), maintained with retractions."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _s():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE orders (oid BIGINT, cust BIGINT, amt BIGINT)")
    s.execute("CREATE TABLE vips (vid BIGINT)")
    return s


def test_exists_semi_join():
    s = _s()
    s.execute(
        "CREATE MATERIALIZED VIEW vo AS SELECT oid, amt FROM orders "
        "WHERE EXISTS (SELECT vid FROM vips WHERE vips.vid = orders.cust)"
    )
    s.execute("INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200)")
    s.execute("INSERT INTO vips VALUES (10)")
    out, _ = s.execute("SELECT oid, amt FROM vo ORDER BY oid")
    assert list(out["oid"]) == [1]
    # a NEW vip retroactively admits order 2 (semi-join maintenance)
    s.execute("INSERT INTO vips VALUES (11)")
    out, _ = s.execute("SELECT oid, amt FROM vo ORDER BY oid")
    assert list(out["oid"]) == [1, 2]


def test_not_exists_anti_join():
    s = _s()
    s.execute(
        "CREATE MATERIALIZED VIEW nv AS SELECT oid FROM orders "
        "WHERE NOT EXISTS (SELECT vid FROM vips WHERE vips.vid = orders.cust)"
    )
    s.execute("INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200)")
    s.execute("INSERT INTO vips VALUES (10)")
    out, _ = s.execute("SELECT oid FROM nv ORDER BY oid")
    assert list(out["oid"]) == [2]
    # order 2's cust becomes a vip -> RETRACTED from the anti join
    s.execute("INSERT INTO vips VALUES (11)")
    out, _ = s.execute("SELECT oid FROM nv ORDER BY oid")
    assert list(out["oid"]) == []


def test_in_and_not_in_subquery():
    s = _s()
    s.execute(
        "CREATE MATERIALIZED VIEW iv AS SELECT oid FROM orders "
        "WHERE cust IN (SELECT vid FROM vips)"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW niv AS SELECT oid FROM orders "
        "WHERE cust NOT IN (SELECT vid FROM vips)"
    )
    s.execute("INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200)")
    s.execute("INSERT INTO vips VALUES (10)")
    out, _ = s.execute("SELECT oid FROM iv ORDER BY oid")
    assert list(out["oid"]) == [1]
    out, _ = s.execute("SELECT oid FROM niv ORDER BY oid")
    assert list(out["oid"]) == [2]


def test_exists_with_residual_predicate():
    s = _s()
    s.execute(
        "CREATE MATERIALIZED VIEW big AS SELECT oid FROM orders "
        "WHERE amt > 150 AND EXISTS "
        "(SELECT vid FROM vips WHERE vips.vid = orders.cust AND vid > 5)"
    )
    s.execute(
        "INSERT INTO orders VALUES (1, 10, 100), (2, 10, 900), (3, 3, 900)"
    )
    s.execute("INSERT INTO vips VALUES (10), (3)")
    out, _ = s.execute("SELECT oid FROM big ORDER BY oid")
    # oid 1 fails amt, oid 3's vip fails vid > 5
    assert list(out["oid"]) == [2]


def test_not_in_value_list_still_works():
    s = _s()
    s.execute("INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200)")
    out, _ = s.execute(
        "SELECT oid FROM orders WHERE cust NOT IN (11, 12) ORDER BY oid"
    )
    assert list(out["oid"]) == [1]


def test_prefix_not_in_subquery():
    s = _s()
    s.execute(
        "CREATE MATERIALIZED VIEW pni AS SELECT oid FROM orders "
        "WHERE NOT cust IN (SELECT vid FROM vips)"
    )
    s.execute("INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200)")
    s.execute("INSERT INTO vips VALUES (10)")
    out, _ = s.execute("SELECT oid FROM pni ORDER BY oid")
    assert list(out["oid"]) == [2]


def test_two_exists_conjuncts_chain_semi_joins():
    """TPC-H q21 shape: multiple EXISTS predicates chain as nested
    semi joins lowered through hidden MVs."""
    s = _s()
    s.execute("CREATE TABLE bans (bid BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW both2 AS SELECT oid FROM orders "
        "WHERE EXISTS (SELECT vid FROM vips WHERE vips.vid = orders.cust) "
        "AND EXISTS (SELECT bid FROM bans WHERE bans.bid = orders.cust)"
    )
    s.execute(
        "INSERT INTO orders VALUES (1, 10, 100), (2, 11, 200), (3, 12, 300)"
    )
    s.execute("INSERT INTO vips VALUES (10), (11)")
    s.execute("INSERT INTO bans VALUES (11), (12)")
    out, _ = s.execute("SELECT oid FROM both2 ORDER BY oid")
    assert list(out["oid"]) == [2]  # cust 11 is both vip and banned
    s.execute("INSERT INTO bans VALUES (10)")
    out, _ = s.execute("SELECT oid FROM both2 ORDER BY oid")
    assert list(out["oid"]) == [1, 2]
