"""Logical optimizer: IR build, predicate pushdown, outer-join
simplification, constant folding, EXPLAIN, and end-to-end neutrality
(optimized plans produce identical MV results).

Reference test model: planner tests comparing plan dumps
(src/frontend/planner_test/) + e2e result checks.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog, parser as P
from risingwave_tpu.sql.optimizer import (
    LFilter,
    LJoin,
    build,
    explain_sql,
    optimize,
    optimize_select,
)
from risingwave_tpu.types import DataType, Schema


def _catalog():
    return Catalog(
        {
            "t": Schema([("k", DataType.INT64), ("x", DataType.INT64)]),
            "u": Schema([("kk", DataType.INT64), ("y", DataType.INT64)]),
        }
    )


_JOIN = (
    "SELECT l.k, l.xs, r.ys FROM "
    "(SELECT k, sum(x) AS xs FROM t GROUP BY k) AS l "
    "{jt} JOIN "
    "(SELECT kk, sum(y) AS ys FROM u GROUP BY kk) AS r "
    "ON l.k = r.kk {where}"
)


def _ir(sql, catalog=None):
    return optimize(build(P.parse(sql), catalog=catalog or _catalog()))


def test_pushdown_into_derived_table():
    """WHERE l.k > 5 routes into the left subquery (below its agg —
    k is a group key), leaving no filter at the join."""
    ir = _ir(_JOIN.format(jt="", where="WHERE l.k > 5"))
    join = ir.input
    assert isinstance(join, LJoin), f"residual filter at join: {join}"
    left = join.left
    # the conjunct sits under the left LAggProject, above its scan
    inner_filter = left.input
    assert isinstance(inner_filter, LFilter)
    pred = inner_filter.conjuncts[0]
    assert isinstance(pred, P.BinaryOp) and pred.op == ">"
    # and the RIGHT side is untouched
    assert not isinstance(join.right, LFilter)


def test_pushdown_blocked_on_aggregate_output():
    """WHERE l.xs > 5 references an aggregate output: must stay above."""
    ir = _ir(_JOIN.format(jt="", where="WHERE l.xs > 5"))
    assert isinstance(ir.input, LFilter)
    assert isinstance(ir.input.input, LJoin)


def test_outer_join_simplifies_to_inner():
    """LEFT JOIN + null-rejecting predicate on the right side -> INNER."""
    ir = _ir(_JOIN.format(jt="LEFT OUTER", where="WHERE r.ys > 0"))
    node = ir.input
    while isinstance(node, LFilter):
        node = node.input
    assert isinstance(node, LJoin)
    assert node.join_type == "inner"


def test_outer_join_kept_without_null_rejection():
    ir = _ir(_JOIN.format(jt="LEFT OUTER", where=""))
    node = ir.input
    while isinstance(node, LFilter):
        node = node.input
    assert node.join_type == "left"


def test_outer_join_kept_under_non_strict_predicate():
    """CASE WHEN r.ys IS NULL THEN 1 ELSE r.ys END = 1 is satisfied by
    NULL-padded rows, so it must NOT reduce the LEFT join to INNER
    (advisor r3: null-rejection requires NULL-strict operands)."""
    ir = _ir(
        _JOIN.format(
            jt="LEFT OUTER",
            where=(
                "WHERE CASE WHEN r.ys IS NULL THEN 1 ELSE r.ys END = 1"
            ),
        )
    )
    node = ir.input
    while isinstance(node, LFilter):
        node = node.input
    assert isinstance(node, LJoin)
    assert node.join_type == "left"


def test_constant_folding_drops_true_conjuncts():
    ir = _ir("SELECT k FROM t WHERE 1 = 1")
    assert not isinstance(ir.input, LFilter)  # folded away entirely
    ir = _ir("SELECT k FROM t WHERE 1 = 1 AND k > 2")
    assert isinstance(ir.input, LFilter)
    assert len(ir.input.conjuncts) == 1  # only k > 2 survives


def test_emit_roundtrip_is_plannable():
    """Optimized AST feeds the planner without loss (items/group_by/
    order/limit preserved)."""
    sql = "SELECT k, x FROM t WHERE k > 1 ORDER BY x DESC LIMIT 3"
    out = optimize_select(P.parse(sql), catalog=_catalog())
    assert isinstance(out, P.Select)
    assert out.limit == 3 and out.order_by[0][1] is True
    assert out.where is not None


def test_explain_shows_both_plans():
    txt = explain_sql(
        _JOIN.format(jt="LEFT OUTER", where="WHERE r.ys > 0"),
        catalog=_catalog(),
    )
    assert "LogicalJoin type=left" in txt  # before
    assert "LogicalJoin type=inner" in txt  # after
    assert "LogicalScan t" in txt


def test_optimized_mv_results_identical():
    """End to end: the join MV over two tables (exercises pushdown +
    simplification) returns the same rows with the optimizer in the
    planner path."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    s.execute("CREATE TABLE u (kk BIGINT, y BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20), (7, 70)")
    s.execute("INSERT INTO u VALUES (1, 5), (7, 7)")
    s.execute(
        "CREATE MATERIALIZED VIEW j AS "
        + _JOIN.format(jt="", where="WHERE l.k > 1")
    )
    out, _ = s.execute("SELECT k, xs, ys FROM j ORDER BY k")
    assert list(out["k"]) == [7]
    assert list(out["xs"]) == [70] and list(out["ys"]) == [7]

    rows, tag = s.execute("EXPLAIN " + _JOIN.format(jt="", where="WHERE l.k > 1"))
    assert tag == "EXPLAIN"
    assert any("LogicalJoin" in ln for ln in rows["QUERY PLAN"])


def test_no_pushdown_below_limit_or_order_by():
    """A TopN subquery selects rows FIRST; the outer WHERE must not
    move below it (that would pick different rows)."""
    sql = (
        "SELECT k FROM (SELECT k, x FROM t ORDER BY x DESC LIMIT 3) "
        "AS sq WHERE k > 5"
    )
    ir = _ir(sql)
    assert isinstance(ir.input, LFilter)  # stayed above the subquery
    sub = ir.input.input
    assert not isinstance(sub.input, LFilter)  # nothing pushed inside


def test_null_literal_comparison_not_folded():
    ir = _ir("SELECT k FROM t WHERE 1 <> NULL")
    # SQL: 1 <> NULL is NULL (filters out); Python would fold to True
    assert isinstance(ir.input, LFilter)
    assert len(ir.input.conjuncts) == 1


def test_decimal_literal_scaled_in_where():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE pay (uid BIGINT, amount DECIMAL(10,2))")
    s.execute("INSERT INTO pay VALUES (1, 0.01), (2, 0.60), (3, 2.00)")
    out, _ = s.execute("SELECT uid FROM pay WHERE amount > 0.5 ORDER BY uid")
    # raw-lane comparison would keep uid=1 too (1 > 0.5 on scaled ints)
    assert list(out["uid"]) == [2, 3]
    # and through a streaming MV filter
    s.execute(
        "CREATE MATERIALIZED VIEW big AS "
        "SELECT uid, amount FROM pay WHERE amount >= 1.5"
    )
    out, _ = s.execute("SELECT uid FROM big")
    assert list(out["uid"]) == [3]


def test_varchar_collation_operations_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE ev (name VARCHAR, n BIGINT)")
    s.execute("INSERT INTO ev VALUES ('zebra', 1), ('apple', 2)")
    with pytest.raises(NotImplementedError, match="collation"):
        s.execute("SELECT min(name) FROM ev")
    with pytest.raises(NotImplementedError, match="collation"):
        s.execute("SELECT name, n FROM ev ORDER BY name")
    # equality-complete operations still work
    out, _ = s.execute("SELECT name FROM ev WHERE name = 'apple'")
    assert list(out["name"]) == ["apple"]
    # range comparisons on dictionary codes would compare insertion
    # order, not collation: rejected loudly (advisor r3)
    with pytest.raises(NotImplementedError, match="collation"):
        s.execute("SELECT n FROM ev WHERE name > 'a'")
    with pytest.raises(NotImplementedError, match="collation"):
        s.execute("SELECT n FROM ev WHERE name BETWEEN 'a' AND 'c'")
