"""Two-process cluster: a compute-node role behind a real TCP wire
(VERDICT r4 missing #2 / next #5).

The driver (this test = the meta + frontend roles) ships DDL as SQL,
streams Nexmark bid chunks as Arrow IPC frames with permit acks, ticks
the barrier clock over the wire, and — after a kill -9 mid-stream —
respawns the node, which restores DDL + state from the SHARED object
store; the driver replays exactly the chunks beyond the restored
commit frontier. Final MV must equal an uninterrupted in-process run.

Reference: compute_node_serve (src/compute/src/server.rs:85), control
stream (proto/stream_service.proto:116-122), exchange permits
(exchange/permit.rs:35-90), recovery (barrier/recovery.rs:353).
"""

import numpy as np
import pytest

from risingwave_tpu.cluster import ComputeClient
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator

DDL = [
    "CREATE TABLE bid (auction BIGINT, bidder BIGINT, price BIGINT, "
    "date_time BIGINT)",
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start",
]


def _bid_cols(n_chunks, events=600, cap=1 << 10):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n_chunks:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            cols = c.to_numpy()
            out.append(
                {
                    k: v
                    for k, v in cols.items()
                    if k in ("auction", "bidder", "price", "date_time")
                }
            )
    return out


def _oracle(chunks_cols, cap=1 << 10):
    """Uninterrupted in-process run of the same chunks."""
    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    s = SqlSession(Catalog({}), capacity=1 << 12)
    for sql in DDL:
        s.execute(sql)
    for cols in chunks_cols:
        chunk = StreamChunk.from_numpy(cols, cap)
        for frag, side in s.dml._targets.get("bid", ()):
            s.runtime.push(frag, chunk, side)
        s.runtime.barrier()
    out, _ = s.execute(
        "SELECT auction, window_start, num FROM q5 ORDER BY auction"
    )
    return out


def _rows(out):
    return sorted(
        zip(
            [int(x) for x in out["auction"]],
            [int(x) for x in out["window_start"]],
            [int(x) for x in out["num"]],
        )
    )


@pytest.mark.slow
def test_two_process_q5_parity_and_kill9_recovery(tmp_path):
    chunks = _bid_cols(6)
    want = _rows(_oracle(chunks))
    assert want

    cn = ComputeClient.spawn(str(tmp_path / "state"))
    try:
        for sql in DDL:
            cn.ddl(sql)
        # stream the first half, one barrier per chunk
        for cols in chunks[:3]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        # chunk 4 lands but its epoch is NOT sealed when the node dies
        cn.push_chunk("bid", chunks[3], 1 << 10)
        cn.kill9()
        # meta-side recovery: respawn, node restores from the store,
        # driver replays past the restored frontier
        cn.recover()
        cn.barrier()
        for cols in chunks[4:]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        got = _rows(cn.query(
            "SELECT auction, window_start, num FROM q5 ORDER BY auction"
        ))
        assert got == want
    finally:
        cn.close()


@pytest.mark.slow
def test_failed_multi_target_push_rolls_back_whole_epoch(tmp_path):
    """A table feeding q5 AND q7 fans every chunk out through the
    subscription edges; if a later subscriber's push fails after an
    earlier one absorbed the rows, the node must roll the whole epoch
    back (not keep it half-applied) and report barrier_failed so the
    driver replays the epoch's earlier chunks. Fault injection: the
    RW_TPU_FAULT failpoint raises at the 2nd push into q7 — chunk 1
    lands everywhere, chunk 2 dies after bid + q5 absorbed it."""
    chunks = _bid_cols(2)
    q7_sql = (
        "CREATE MATERIALIZED VIEW q7 AS "
        "SELECT b.auction, b.bidder, b.price, b.wstart FROM "
        "(SELECT auction, bidder, price, window_start AS wstart "
        " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)) AS b "
        "JOIN "
        "(SELECT max(price) AS maxprice, window_start AS mwstart "
        " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        " GROUP BY window_start) AS m "
        "ON b.wstart = m.mwstart AND b.price = m.maxprice"
    )
    ddl2 = DDL + [q7_sql]

    def _query_both(run):
        q5 = run("SELECT auction, window_start, num FROM q5")
        q7 = run("SELECT auction, price FROM q7")
        return _rows(q5), sorted(
            zip([int(x) for x in q7["auction"]], [int(x) for x in q7["price"]])
        )

    def _oracle2():
        from risingwave_tpu.array.chunk import StreamChunk
        from risingwave_tpu.frontend.session import SqlSession
        from risingwave_tpu.sql import Catalog

        s = SqlSession(Catalog({}), capacity=1 << 12)
        for sql in ddl2:
            s.execute(sql)
        for cols in chunks:
            chunk = StreamChunk.from_numpy(cols, 1 << 10)
            for frag, side in s.dml._targets.get("bid", ()):
                s.runtime.push(frag, chunk, side)
            s.runtime.barrier()
        return _query_both(lambda q: s.execute(q)[0])

    want_q5, want_q7 = _oracle2()
    assert want_q5 and want_q7

    cn = ComputeClient.spawn(
        str(tmp_path / "state"),
        env={"RW_TPU_FAULT": "push_into:q7:both:2"},
    )
    try:
        for sql in ddl2:
            cn.ddl(sql)
        cn.push_chunk("bid", chunks[0], 1 << 10)  # q7 hit 1: absorbed
        from risingwave_tpu.cluster.client import ComputeError

        with pytest.raises(ComputeError, match="injected fault"):
            # dies at q7 hit 2 — AFTER bid's table fragment and q5
            # already absorbed the rows (the half-applied window)
            cn.push_chunk("bid", chunks[1], 1 << 10)
        # the rollback erased chunk 0 too; the failed barrier makes the
        # client replay it, then the retried barrier seals the epoch
        cn.barrier()
        cn.push_chunk("bid", chunks[1], 1 << 10)  # clean retry (hit 3)
        cn.barrier()
        got_q5, got_q7 = _query_both(cn.query)
        assert got_q5 == want_q5
        assert got_q7 == want_q7
    finally:
        cn.close()


@pytest.mark.slow
def test_varchar_over_the_wire(tmp_path):
    """String lanes cross the wire as Arrow strings: the client encodes
    its numpy str/object columns through a client-side dictionary, the
    payload decodes them back to strings, and the node re-encodes into
    the session's ONE shared dictionary (wire.SharedDictionaries)."""
    cn = ComputeClient.spawn(str(tmp_path / "state"))
    try:
        cn.ddl(
            "CREATE TABLE ev (name VARCHAR, v BIGINT, date_time BIGINT)"
        )
        cn.ddl(
            "CREATE MATERIALIZED VIEW byname AS "
            "SELECT name, count(*) AS num FROM "
            "TUMBLE(ev, date_time, INTERVAL '10' SECOND) "
            "GROUP BY name, window_start"
        )
        cols = {
            "name": np.array(["a", "b", "a", "c"], dtype=object),
            "v": np.arange(4, dtype=np.int64),
            "date_time": np.array([1000, 2000, 3000, 4000], np.int64),
        }
        cn.push_chunk("ev", cols, 8)
        cn.barrier()
        out = cn.query("SELECT name, num FROM byname")
        got = sorted(zip(out["name"], [int(x) for x in out["num"]]))
        assert got == [("a", 2), ("b", 1), ("c", 1)]
    finally:
        cn.close()


@pytest.mark.slow
def test_kill_between_commit_and_reply_does_not_double_apply(tmp_path):
    """kill -9 landing AFTER the node committed epoch E but BEFORE the
    barrier_complete reply reaches the driver: the driver still holds
    E's chunks as unsealed, but the restored frontier proves the
    in-flight barrier committed — replaying them would double-apply.
    (White-box: the commit happens normally; the client's view is then
    rewound to 'reply lost'.)"""
    chunks = _bid_cols(4)
    want = _rows(_oracle(chunks))

    cn = ComputeClient.spawn(str(tmp_path / "state"))
    try:
        for sql in DDL:
            cn.ddl(sql)
        for cols in chunks[:3]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        prev_committed = cn._last_committed
        pending_before = [(None, "bid", c, 1 << 10) for c in [chunks[3]]]
        cn.push_chunk("bid", chunks[3], 1 << 10)
        cn.barrier()  # the node commits AND replies...
        # ...but pretend the reply was lost: rewind the client's view
        cn._pending = list(pending_before)
        cn._barrier_inflight = True
        cn._last_committed = prev_committed
        cn.kill9()
        cn.recover()  # frontier advanced past prev_committed -> no replay
        cn.barrier()
        got = _rows(cn.query(
            "SELECT auction, window_start, num FROM q5 ORDER BY auction"
        ))
        assert got == want
    finally:
        cn.close()
