"""Two-process cluster: a compute-node role behind a real TCP wire
(VERDICT r4 missing #2 / next #5).

The driver (this test = the meta + frontend roles) ships DDL as SQL,
streams Nexmark bid chunks as Arrow IPC frames with permit acks, ticks
the barrier clock over the wire, and — after a kill -9 mid-stream —
respawns the node, which restores DDL + state from the SHARED object
store; the driver replays exactly the chunks beyond the restored
commit frontier. Final MV must equal an uninterrupted in-process run.

Reference: compute_node_serve (src/compute/src/server.rs:85), control
stream (proto/stream_service.proto:116-122), exchange permits
(exchange/permit.rs:35-90), recovery (barrier/recovery.rs:353).
"""

import numpy as np
import pytest

from risingwave_tpu.cluster import ComputeClient
from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)

DDL = [
    "CREATE TABLE bid (auction BIGINT, bidder BIGINT, price BIGINT, "
    "date_time BIGINT)",
    "CREATE MATERIALIZED VIEW q5 AS "
    "SELECT auction, window_start, count(*) AS num "
    "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
    "GROUP BY auction, window_start",
]


def _bid_cols(n_chunks, events=600, cap=1 << 10):
    gen = NexmarkGenerator(NexmarkConfig())
    out = []
    while len(out) < n_chunks:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            cols = c.to_numpy()
            out.append(
                {
                    k: v
                    for k, v in cols.items()
                    if k in ("auction", "bidder", "price", "date_time")
                }
            )
    return out


def _oracle(chunks_cols, cap=1 << 10):
    """Uninterrupted in-process run of the same chunks."""
    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    s = SqlSession(Catalog({}), capacity=1 << 12)
    for sql in DDL:
        s.execute(sql)
    for cols in chunks_cols:
        chunk = StreamChunk.from_numpy(cols, cap)
        for frag, side in s.dml._targets.get("bid", ()):
            s.runtime.push(frag, chunk, side)
        s.runtime.barrier()
    out, _ = s.execute(
        "SELECT auction, window_start, num FROM q5 ORDER BY auction"
    )
    return out


def _rows(out):
    return sorted(
        zip(
            [int(x) for x in out["auction"]],
            [int(x) for x in out["window_start"]],
            [int(x) for x in out["num"]],
        )
    )


@pytest.mark.slow
def test_two_process_q5_parity_and_kill9_recovery(tmp_path):
    chunks = _bid_cols(6)
    want = _rows(_oracle(chunks))
    assert want

    cn = ComputeClient.spawn(str(tmp_path / "state"))
    try:
        for sql in DDL:
            cn.ddl(sql)
        # stream the first half, one barrier per chunk
        for cols in chunks[:3]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        # chunk 4 lands but its epoch is NOT sealed when the node dies
        cn.push_chunk("bid", chunks[3], 1 << 10)
        cn.kill9()
        # meta-side recovery: respawn, node restores from the store,
        # driver replays past the restored frontier
        cn.recover()
        cn.barrier()
        for cols in chunks[4:]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        got = _rows(cn.query(
            "SELECT auction, window_start, num FROM q5 ORDER BY auction"
        ))
        assert got == want
    finally:
        cn.close()


@pytest.mark.slow
def test_kill_between_commit_and_reply_does_not_double_apply(tmp_path):
    """kill -9 landing AFTER the node committed epoch E but BEFORE the
    barrier_complete reply reaches the driver: the driver still holds
    E's chunks as unsealed, but the restored frontier proves the
    in-flight barrier committed — replaying them would double-apply.
    (White-box: the commit happens normally; the client's view is then
    rewound to 'reply lost'.)"""
    chunks = _bid_cols(4)
    want = _rows(_oracle(chunks))

    cn = ComputeClient.spawn(str(tmp_path / "state"))
    try:
        for sql in DDL:
            cn.ddl(sql)
        for cols in chunks[:3]:
            cn.push_chunk("bid", cols, 1 << 10)
            cn.barrier()
        prev_committed = cn._last_committed
        pending_before = [(None, "bid", c, 1 << 10) for c in [chunks[3]]]
        cn.push_chunk("bid", chunks[3], 1 << 10)
        cn.barrier()  # the node commits AND replies...
        # ...but pretend the reply was lost: rewind the client's view
        cn._pending = list(pending_before)
        cn._barrier_inflight = True
        cn._last_committed = prev_committed
        cn.kill9()
        cn.recover()  # frontier advanced past prev_committed -> no replay
        cn.barrier()
        got = _rows(cn.query(
            "SELECT auction, window_start, num FROM q5 ORDER BY auction"
        ))
        assert got == want
    finally:
        cn.close()
