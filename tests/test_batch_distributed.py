"""Distributed batch mode: vnode-partitioned scan tasks + two-phase
aggregation match local-mode results exactly.

Reference: BatchPlanFragmenter stage DAG (plan_fragmenter.rs:137),
BatchTaskExecution (task_execution.rs:300), hash-shuffle channels.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog


@pytest.fixture
def session():
    s = SqlSession(Catalog({}), capacity=1 << 12)
    s.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    rows = ", ".join(
        f"({i % 17}, {i})" for i in range(500)
    )
    s.execute(f"INSERT INTO t VALUES {rows}")
    return s


def _both_modes(session, sql):
    session.batch.distributed_tasks = 0
    local, _ = session.execute(sql)
    session.batch.distributed_tasks = 4
    dist, _ = session.execute(sql)
    session.batch.distributed_tasks = 0
    return local, dist


def _as_rowset(out):
    names = sorted(out)
    n = len(out[names[0]]) if names else 0
    return sorted(
        tuple(out[c][i] for c in names) for i in range(n)
    )


def test_distributed_group_agg_matches_local(session):
    local, dist = _both_modes(
        session,
        "SELECT k, count(*) AS c, sum(x) AS s FROM t GROUP BY k",
    )
    assert _as_rowset(local) == _as_rowset(dist)
    assert len(local["k"]) == 17


def test_distributed_scalar_agg_combines_partials(session):
    local, dist = _both_modes(
        session,
        "SELECT count(*) AS c, sum(x) AS s, min(x) AS lo, max(x) AS hi "
        "FROM t",
    )
    for col in ("c", "s", "lo", "hi"):
        assert local[col][0] == dist[col][0]


def test_distributed_filter_scan_matches_local(session):
    local, dist = _both_modes(
        session, "SELECT k, x FROM t WHERE x % 7 = 0"
    )
    assert _as_rowset(local) == _as_rowset(dist)


def test_order_by_falls_back_to_local(session):
    """ORDER BY/LIMIT need a root-side sort: distributed mode declines
    and local mode serves (the reference's local/distributed split)."""
    session.batch.distributed_tasks = 4
    out, _ = session.execute("SELECT k, x FROM t ORDER BY x DESC LIMIT 3")
    session.batch.distributed_tasks = 0
    assert list(out["x"]) == [499, 498, 497]


def test_distributed_scalar_agg_skips_null_partials(session):
    """A partition whose surviving rows are all NULL emits a NULL
    partial (value fill 0 + __null companion); the merge must skip it,
    not fold the 0 into min/sum (review r5: silent corruption)."""
    session.execute("CREATE TABLE nv (k BIGINT, v BIGINT)")
    session.execute(
        "INSERT INTO nv VALUES (1, NULL), (2, NULL), (3, 5), (4, 7)"
    )
    session.batch.distributed_tasks = 4
    try:
        out, _ = session.execute(
            "SELECT min(v) AS m, sum(v) AS s, count(v) AS c FROM nv"
        )
    finally:
        session.batch.distributed_tasks = 0
    assert out["m"][0] == 5 and out["s"][0] == 12 and out["c"][0] == 2
    # all partitions NULL -> SQL NULL result
    session.batch.distributed_tasks = 4
    try:
        out, _ = session.execute("SELECT max(v) AS m FROM nv WHERE k <= 2")
    finally:
        session.batch.distributed_tasks = 0
    assert out["m"][0] is None
