"""WatermarkFilterExecutor — generated watermarks + late-row filtering
(VERDICT r2 weak #8; reference watermark_filter.rs:39): the pipeline
cleans state without the driver ever calling pipeline.watermark()."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import (
    HashAggExecutor,
    HopWindowExecutor,
    MaterializeExecutor,
    WatermarkFilterExecutor,
)
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline

CAP = 64


def _chunk(ts_vals):
    n = len(ts_vals)
    return StreamChunk.from_numpy(
        {
            "k": np.arange(n, dtype=np.int64) % 3,
            "date_time": np.asarray(ts_vals, np.int64),
        },
        CAP,
    )


def test_late_rows_dropped_and_watermark_advances():
    wf = WatermarkFilterExecutor("date_time", lag_ms=1000)
    outs = wf.apply(_chunk([5000, 6000, 7000]))
    assert int(np.asarray(outs[0].valid).sum()) == 3
    assert wf.emit_watermark().value == 6000  # 7000 - 1000

    # rows below wm=6000 are now late and dropped
    outs = wf.apply(_chunk([5999, 6000, 10_000]))
    d = outs[0].to_numpy(False)
    assert sorted(d["date_time"].tolist()) == [6000, 10_000]
    assert wf.emit_watermark().value == 9000
    assert wf.emit_watermark() is None  # monotonic: no re-emit


def test_pipeline_self_cleaning_without_driver_watermarks():
    """hop -> agg(window_key, EOWC) fed via a generating filter: closed
    windows are finalized (state freed) with NO driver watermark call,
    and the MV keeps their final counts."""
    W, S = 10_000, 10_000
    agg = HashAggExecutor(
        group_keys=("k", "window_start"),
        calls=(AggCall("count_star", None, "cnt"),),
        schema_dtypes={"k": jnp.int64, "window_start": jnp.int64},
        capacity=1 << 8,
        out_cap=1 << 7,
        window_key=("window_start", 0, False),  # EOWC finalize
    )
    mv = MaterializeExecutor(pk=("k", "window_start"), columns=("cnt",))
    pipe = Pipeline(
        [
            WatermarkFilterExecutor("date_time", lag_ms=0),
            HopWindowExecutor("date_time", W, S, out_start="window_start"),
            agg,
            mv,
        ]
    )
    # window 0 rows, then jump 3 windows ahead: wm = 40_000 closes w0
    pipe.push(_chunk([1000, 2000, 3000]))
    pipe.barrier()
    occupied_before = int(jnp.sum(agg.table.live.astype(jnp.int32)))
    assert occupied_before == 3  # 3 keys in window 0

    pipe.push(_chunk([40_000, 41_000]))
    pipe.barrier()
    live_after = int(jnp.sum(agg.table.live.astype(jnp.int32)))
    # window-0 groups were finalized and freed; only window-40000 live
    assert live_after == 2
    snap = mv.snapshot()
    # final counts for window 0 survive in the MV
    assert snap[(0, 0)] == (1,) and snap[(1, 0)] == (1,) and snap[(2, 0)] == (1,)
