"""CDC backfill: snapshot + change-stream switchover correctness.

Reference: src/stream/src/executor/backfill/cdc/ — the merge rule
(events beyond the backfill frontier drop; the snapshot covers them)
and per-table progress state that survives recovery.
"""

import pytest

from risingwave_tpu.connectors.cdc import CdcBackfillExecutor, ExternalTable
from risingwave_tpu.connectors.framework import (
    DebeziumJsonParser,
    FileLogSource,
)
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.types import DataType, Field, Schema

pytestmark = pytest.mark.smoke


def _schema():
    return Schema([Field("id", DataType.INT64), Field("v", DataType.INT64)])


def _mv_pipe():

    mv = MaterializeExecutor(pk=("id",), columns=("v",), table_id="c.mv")
    return Pipeline([mv]), mv


def test_snapshot_then_stream_converges(tmp_path):
    d = str(tmp_path)
    schema = _schema()
    tbl = ExternalTable(schema, "id")
    for pk in range(1, 7):
        tbl.upsert((pk, pk * 10))
    ex = CdcBackfillExecutor(
        tbl, FileLogSource(d), DebeziumJsonParser(schema), table_id="c"
    )
    pipe, mv = _mv_pipe()
    # round 1: backfill 3 rows; a change arrives for ALREADY-backfilled
    # pk 2 (applies) and for NOT-yet pk 5 (drops — snapshot covers it)
    for c in ex.poll(snapshot_rows=3):
        pipe.push(c)
    assert ex.pk_pos == 3 and not ex.done
    tbl.upsert((2, 999))   # upstream change, mirrored into the log
    tbl.upsert((5, 555))
    FileLogSource.append(d, 0, [
        '{"op": "u", "before": {"id": 2, "v": 20}, "after": {"id": 2, "v": 999}}',
        '{"op": "u", "before": {"id": 5, "v": 50}, "after": {"id": 5, "v": 555}}',
    ])
    ex.connector.list_splits() or None
    for c in ex.poll(snapshot_rows=3):
        pipe.push(c)
    # drain to done
    for _ in range(3):
        for c in ex.poll(snapshot_rows=3):
            pipe.push(c)
    pipe.barrier()
    assert ex.done
    snap = {k[0]: v[0] for k, v in mv.snapshot().items()}
    # pk 2 via change event, pk 5 via the (post-change) snapshot read —
    # exactly once each
    assert snap == {1: 10, 2: 999, 3: 30, 4: 40, 5: 555, 6: 60}


def test_post_backfill_streaming_deletes(tmp_path):
    d = str(tmp_path)
    schema = _schema()
    tbl = ExternalTable(schema, "id")
    tbl.upsert((1, 10))
    tbl.upsert((2, 20))
    ex = CdcBackfillExecutor(
        tbl, FileLogSource(d), DebeziumJsonParser(schema), table_id="c"
    )
    pipe, mv = _mv_pipe()
    for _ in range(3):
        for c in ex.poll(snapshot_rows=8):
            pipe.push(c)
    assert ex.done
    tbl.delete(1)
    FileLogSource.append(d, 0, ['{"op": "d", "before": {"id": 1, "v": 10}}'])
    for c in ex.poll():
        pipe.push(c)
    pipe.barrier()
    snap = {k[0]: v[0] for k, v in mv.snapshot().items()}
    assert snap == {2: 20}


def test_progress_checkpoints_and_restores(tmp_path):
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    d = str(tmp_path)
    schema = _schema()
    tbl = ExternalTable(schema, "id")
    for pk in range(1, 9):
        tbl.upsert((pk, pk))
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = CdcBackfillExecutor(
        tbl, FileLogSource(d), DebeziumJsonParser(schema), table_id="c"
    )
    FileLogSource.append(d, 0, ['{"op": "c", "after": {"id": 100, "v": 1}}'])
    chunks1 = ex.poll(snapshot_rows=4)
    mgr.commit_epoch(1, [ex])
    assert ex.pk_pos == 4
    # cold restart: a fresh executor resumes mid-scan, not from zero
    ex2 = CdcBackfillExecutor(
        tbl, FileLogSource(d), DebeziumJsonParser(schema), table_id="c"
    )
    keys, vals = mgr.read_table("c")
    ex2.restore_state("c", keys, vals)
    assert ex2.pk_pos == 4 and not ex2.done
    assert ex2.offsets  # change-log offset resumed too
    rows = []
    for c in ex2.poll(snapshot_rows=100):
        got = c.to_numpy()
        rows.extend(int(x) for x in got["id"])
    assert sorted(rows) == [5, 6, 7, 8]  # no re-read of pks 1..4
