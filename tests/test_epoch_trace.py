"""Barrier-lifecycle observability: EpochTrace stage attribution,
stall dumps (await-tree analogue) on wedged barriers, and the meta
event log (reference: src/utils/runtime tracing + await-tree dumps,
meta event_log.rs)."""

import glob
import json
import time

import pytest

from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore


@pytest.fixture(autouse=True)
def _clean():
    EVENT_LOG.clear()
    yield
    sync_point.reset()


def _rt_with_q5(**kw):
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False, **kw)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    return rt, q5


def _push_epoch(rt, gen, events=2_000):
    c = gen.next_chunks(events, 1 << 11)["bid"]
    if c is not None:
        rt.push("q5", c.select(["auction", "date_time"]))


def test_epoch_trace_stage_sums_approx_wall_time():
    """Every barrier gets an EpochTrace whose per-stage attribution
    accounts for (most of) the barrier wall time — no large unexplained
    gap, no stage exceeding the wall it is part of."""
    rt, q5 = _rt_with_q5()
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    for _ in range(3):
        _push_epoch(rt, gen)
        rt.barrier()
    tr = rt.last_epoch_trace
    assert tr is not None and tr.checkpoint
    # the full lifecycle is attributed
    for stage in ("ingest", "dispatch", "checkpoint_stage", "upload",
                  "manifest_commit"):
        assert stage in tr.stages_ms, tr.stages_ms
    # ingest is charged to the epoch but happens BEFORE the barrier;
    # the in-barrier stages must sum to ≈ the barrier wall
    in_barrier = sum(
        v for k, v in tr.stages_ms.items() if k != "ingest"
    )
    assert in_barrier <= tr.wall_ms * 1.2 + 5.0
    assert in_barrier >= tr.wall_ms * 0.2  # attribution, not decoration
    assert tr.wall_ms > 0 and len(rt.epoch_traces) == 3
    # device telemetry: bytes moved are accounted and the roofline
    # fraction is a sane measured number
    assert tr.chunk_bytes > 0
    assert tr.hbm_bytes_touched >= tr.chunk_bytes
    assert 0.0 <= tr.achieved_bw_frac
    d = tr.to_dict()
    assert d["stages_ms"] and d["achieved_bw_frac"] == tr.achieved_bw_frac
    # the prometheus surface carries the same attribution
    from risingwave_tpu.epoch_trace import stage_breakdown

    bd = stage_breakdown()
    assert any("stage=dispatch" in k for k in bd)


def test_stall_dump_fires_on_injected_slow_barrier(tmp_path, monkeypatch):
    """The q7-wedge case: an actor held inside barrier processing makes
    the graph blow its collection deadline — the dump artifact must
    land BEFORE the epoch is abandoned and must name the stuck actor."""
    monkeypatch.setenv("RW_STALL_DIR", str(tmp_path))
    from risingwave_tpu.runtime.graph import FragmentSpec, GraphRuntime

    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec(
                "agg", lambda i: list(q5.pipeline.executors),
                inputs=[("src", 0)],
            ),
        ]
    ).start()
    try:
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
        c = gen.next_chunks(1_000, 1 << 10)["bid"]
        g.inject_chunk("src", c.select(["auction", "date_time"]))
        g.inject_barrier()  # healthy epoch first
        sync_point.activate("actor_barrier:agg#0", lambda: time.sleep(1.5))
        with pytest.raises(TimeoutError, match="agg#0"):
            g.inject_barrier(timeout=0.4)
        dumps = sorted(glob.glob(str(tmp_path / "STALL_DUMP_*.json")))
        assert dumps, "no stall-dump artifact written"
        doc = json.loads(open(dumps[-1]).read())
        assert "agg#0" in doc["reason"]
        pend = list(doc["graph"]["epochs_pending"].values())
        assert pend and "agg#0" in pend[0]["stuck"]
        # the healthy actor collected; per-actor lag is attributable
        actors = {a["actor"]: a for a in doc["graph"]["actors"]}
        assert actors["src#0"]["last_collected_epoch"] > \
            actors["agg#0"]["last_collected_epoch"]
        # the dump is cluster history too
        assert EVENT_LOG.events(kind="stall_dump")
    finally:
        sync_point.reset()
        time.sleep(1.2)  # let the held actor wake before teardown
        g.stop(timeout=5.0)


def test_runtime_watchdog_dumps_on_deadline(tmp_path, monkeypatch):
    """The StreamingRuntime-side watchdog: a barrier exceeding its
    deadline produces an artifact while the barrier is still stuck."""
    monkeypatch.setenv("RW_STALL_DIR", str(tmp_path))
    rt, q5 = _rt_with_q5()
    rt.stall_dump_after_s = 0.15
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    _push_epoch(rt, gen)
    sync_point.activate("before_manifest_commit", lambda: time.sleep(0.5))
    rt.barrier()  # slow but completes; the watchdog fired mid-commit
    for _ in range(50):
        dumps = glob.glob(str(tmp_path / "STALL_DUMP_*.json"))
        if dumps:
            break
        time.sleep(0.05)
    assert dumps, "watchdog never dumped"
    doc = json.loads(open(dumps[-1]).read())
    assert "deadline" in doc["reason"]
    assert "q5" in doc["runtime"]["fragments"]
    # a healthy (fast) barrier must NOT dump: the timer is canceled
    sync_point.reset()
    for p in dumps:
        import os

        os.remove(p)
    rt.stall_dump_after_s = 5.0
    _push_epoch(rt, gen)
    rt.barrier()
    time.sleep(0.3)
    assert not glob.glob(str(tmp_path / "STALL_DUMP_*.json"))


def test_event_log_records_ddl_and_recovery():
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    sess = SqlSession(Catalog({}), rt)
    sess.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    sess.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, count(*) AS c FROM t GROUP BY k"
    )
    ddl = EVENT_LOG.events(kind="ddl")
    assert [e["tag"] for e in ddl] == ["CREATE_TABLE",
                                      "CREATE_MATERIALIZED_VIEW"]
    assert "CREATE TABLE t" in ddl[0]["sql"]
    sess.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    rt.barrier()
    commits = EVENT_LOG.events(kind="barrier_commit")
    assert commits and commits[-1]["epoch"] == rt.epoch
    rt.recover()
    rec = EVENT_LOG.events(kind="recovery")
    assert rec and rec[-1]["mode"] == "restore"
    # ring bound: the log never grows past its capacity
    for i in range(EVENT_LOG._events.maxlen + 10):
        EVENT_LOG.record("noise", i=i)
    assert len(EVENT_LOG.events()) == EVENT_LOG._events.maxlen


def test_event_log_jsonl_spill(tmp_path):
    path = str(tmp_path / "events.jsonl")
    EVENT_LOG.set_spill(path)
    try:
        EVENT_LOG.record("ddl", tag="X")
        EVENT_LOG.record("recovery", mode="auto")
    finally:
        EVENT_LOG.set_spill(None)
    lines = [json.loads(l) for l in open(path)]
    assert [l["kind"] for l in lines] == ["ddl", "recovery"]


def test_sharded_query_guard_rejects_non_distribution_key_mv():
    """cluster/multi_node: an MV grouping by something other than the
    distribution column holds PARTIAL groups per node — query() must
    refuse instead of returning duplicated groups (VERDICT weak #5).
    Exercised against the classifier directly (no real nodes)."""
    from risingwave_tpu.cluster.multi_node import ShardedClusterClient

    cc = ShardedClusterClient.__new__(ShardedClusterClient)
    cc.nodes = [object()]  # never touched by the classifier
    cc.dist = {"bid": "auction"}
    cc._unsafe_mv = {}
    cc._classify_mv(
        "CREATE MATERIALIZED VIEW ok AS SELECT auction, count(*) AS c "
        "FROM bid GROUP BY auction"
    )
    assert cc.dist["ok"] == "auction" and "ok" not in cc._unsafe_mv
    cc._classify_mv(
        "CREATE MATERIALIZED VIEW bad AS SELECT bidder, count(*) AS c "
        "FROM bid GROUP BY bidder"
    )
    assert "bad" in cc._unsafe_mv
    with pytest.raises(ValueError, match="duplicated|distribution"):
        cc.query("SELECT bidder, c FROM bad")
    # an MV stacked on the unsafe one inherits the rejection
    cc._classify_mv(
        "CREATE MATERIALIZED VIEW worse AS SELECT bidder FROM bad"
    )
    assert "worse" in cc._unsafe_mv
    # row-preserving MV keeps the contract
    cc._classify_mv("CREATE MATERIALIZED VIEW rows AS SELECT * FROM bid")
    assert cc.dist["rows"] == "auction"
    # DROP + re-CREATE with a safe key must clear the stale refusal
    cc._classify_mv(
        "CREATE MATERIALIZED VIEW bad AS SELECT auction, count(*) AS c "
        "FROM bid GROUP BY auction"
    )
    assert "bad" not in cc._unsafe_mv and cc.dist["bad"] == "auction"
