"""Troublemaker chaos: injected stream corruption must be CAUGHT by
the consistency machinery, never silently absorbed.

Reference: executor/troublemaker.rs:28 + the insane-mode contract —
the corrupted stream exercises update checks / differential stores.
"""

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.troublemaker import TroublemakerExecutor
from risingwave_tpu.types import Op

pytestmark = pytest.mark.smoke


def _chunk(vals, cap=8):
    return StreamChunk.from_numpy(
        {"k": np.asarray(vals, np.int64), "v": np.asarray(vals, np.int64)},
        cap,
    )


def test_faults_are_logged_and_visible():
    tm = TroublemakerExecutor(seed=3, rate=1.0)
    out = []
    n_chunks = 30
    for i in range(n_chunks):
        out.extend(tm.apply(_chunk([i * 3, i * 3 + 1, i * 3 + 2])))
    assert len(tm.log) == n_chunks  # rate=1: every chunk corrupted
    # EVERY fault class fired (a vacuous subset check would let a
    # broken mode go untested — review finding r5)
    modes = {m for m, _, _ in tm.log}
    assert modes == {"corrupt_value", "flip_op", "dup_row"}
    # and every corruption is REAL: each output differs from its input
    clean = [c.to_numpy(with_ops=True) for c in out]
    diffs = 0
    for i, got in enumerate(clean):
        want = [i * 3, i * 3 + 1, i * 3 + 2]
        ids = [int(x) for x in got["k"]]
        ops = [int(x) for x in got["__op__"]]
        if ids != want or any(o != int(Op.INSERT) for o in ops) or (
            sorted(int(x) for x in got["v"]) != want
        ):
            diffs += 1
    assert diffs == n_chunks


def test_rate_zero_is_identity():
    tm = TroublemakerExecutor(seed=1, rate=0.0)
    c = _chunk([1, 2, 3])
    (out,) = tm.apply(c)
    assert out is c and tm.log == []


def test_corruption_visible_in_downstream_mv():
    """A troublemaker-corrupted stream produces a DIFFERENT MV than
    the clean stream — the divergence the insane-mode machinery (and
    the chaos suite's differential oracles) must be able to catch."""
    import jax.numpy as jnp

    from risingwave_tpu.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.executors.materialize import MaterializeExecutor
    from risingwave_tpu.ops.agg import AggCall
    from risingwave_tpu.runtime.pipeline import Pipeline

    def run(with_chaos: bool):
        agg = HashAggExecutor(
            ("k",), (AggCall("count_star", None, "n"),),
            {"k": jnp.int64, "v": jnp.int64}, capacity=1 << 8,
            table_id=f"tm{int(with_chaos)}.agg",
        )
        mv = MaterializeExecutor(
            pk=("k",), columns=("n",), table_id=f"tm{int(with_chaos)}.mv"
        )
        chain = [agg, mv]
        if with_chaos:
            chain.insert(0, TroublemakerExecutor(seed=9, rate=1.0))
        pipe = Pipeline(chain)
        for i in range(4):
            pipe.push(_chunk([i, i + 1]))
        pipe.barrier()
        return mv.snapshot()

    clean = run(False)
    dirty = run(True)
    assert clean != dirty, "chaos was silently absorbed"
