"""Mesh-parallel retractable GroupTopN (§2.11 'every fragment
parallelizes'): exchange by group key, per-shard top-k, shared diff —
oracle-checked against the single-chip executor with retractions, and
cross-layout checkpoint/restore."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.top_n_plain import RetractableGroupTopNExecutor
from risingwave_tpu.parallel import ShardedGroupTopN, make_mesh
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager

N = 8
DT = {"g": jnp.int64, "o": jnp.int64, "id": jnp.int64}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _mv(snap, chunks):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            row = (int(d["g"][i]), int(d["o"][i]), int(d["id"][i]))
            if int(d["__op__"][i]) in (1, 3):
                snap.discard(row)
            else:
                snap.add(row)
    return snap


def _mk_sharded(mesh, table_id="stn"):
    return ShardedGroupTopN(
        mesh, ("g",), "o", 3, ("id",), DT, capacity=1 << 9,
        table_id=table_id,
    )


def _mk_single(table_id="stn1"):
    return RetractableGroupTopNExecutor(
        ("g",), "o", 3, ("id",), DT, capacity=1 << 10, table_id=table_id,
    )


def _streams(rng, epochs):
    """Per-epoch: (per-shard chunk list for the sharded exec, flat
    chunk list for the oracle) of mixed inserts/deletes."""
    live = {}
    nid = 0
    out = []
    for _ in range(epochs):
        rows = []
        for _ in range(int(rng.integers(8, 30))):
            if live and rng.random() < 0.3:
                rid = int(rng.choice(list(live)))
                g, o = live.pop(rid)
                rows.append((g, o, rid, 1))
            else:
                g = int(rng.integers(0, 6))
                o = int(rng.integers(0, 100))
                live[nid] = (g, o)
                rows.append((g, o, nid, 0))
                nid += 1
        # split rows round-robin across shards (source splits)
        per_shard = [[] for _ in range(N)]
        for j, r in enumerate(rows):
            per_shard[j % N].append(r)

        def chunk(rs):
            return StreamChunk.from_numpy(
                {
                    "g": np.asarray([r[0] for r in rs], np.int64),
                    "o": np.asarray([r[1] for r in rs], np.int64),
                    "id": np.asarray([r[2] for r in rs], np.int64),
                },
                16,
                ops=np.asarray([r[3] for r in rs], np.int32),
            )

        out.append(
            (
                stack_chunks([chunk(p) for p in per_shard]),
                [chunk(p) for p in per_shard if p],
            )
        )
    return out


def test_sharded_group_top_n_matches_single_chip(mesh):
    sharded = _mk_sharded(mesh)
    single = _mk_single()
    rng = np.random.default_rng(13)
    s_snap, o_snap = set(), set()
    for stacked, flat in _streams(rng, 10):
        sharded.apply(stacked)
        for c in flat:
            single.apply(c)
        _mv(s_snap, sharded.on_barrier(None))
        _mv(o_snap, single.on_barrier(None))
        assert s_snap == o_snap
    assert len(s_snap) > 5


@pytest.mark.slow
def test_sharded_group_top_n_checkpoint_cross_layout(mesh):
    store = MemObjectStore()
    mgr = CheckpointManager(store)
    sharded = _mk_sharded(mesh, table_id="stx")
    rng = np.random.default_rng(29)
    s_snap = set()
    streams = _streams(rng, 8)
    for stacked, _ in streams[:5]:
        sharded.apply(stacked)
        _mv(s_snap, sharded.on_barrier(None))
    mgr.commit_epoch(1 << 16, [sharded])

    # restore into a FRESH sharded executor: continuing matches the
    # uninterrupted run
    sharded2 = _mk_sharded(mesh, table_id="stx")
    CheckpointManager(store).recover([sharded2])
    twin = _mk_sharded(mesh, table_id="stx2")
    # (twin replays all 8 epochs for the expected final state)
    t_snap = set()
    for stacked, _ in streams:
        twin.apply(stacked)
        _mv(t_snap, twin.on_barrier(None))
    s2 = set(s_snap)
    for stacked, _ in streams[5:]:
        sharded2.apply(stacked)
        _mv(s2, sharded2.on_barrier(None))
    assert s2 == t_snap

    # cross-layout: the SAME checkpoint restores into the single-chip
    # executor (shared lane naming)
    single = _mk_single(table_id="stx")
    CheckpointManager(store).recover([single])
    s1 = set(s_snap)
    for _, flat in streams[5:]:
        for c in flat:
            single.apply(c)
        _mv(s1, single.on_barrier(None))
    assert s1 == t_snap
