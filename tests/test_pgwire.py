"""pgwire server (pg_server.rs analogue): drive it with a raw
protocol-v3 client — startup, simple queries, DML, errors."""

import socket
import struct

import pytest

from risingwave_tpu.frontend import PgServer, SqlSession
from risingwave_tpu.sql import Catalog
from risingwave_tpu.types import DataType, Schema


class PgClient:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        params = b"user\0test\0database\0dev\0\0"
        body = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self._drain_until_ready()

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            got = self.sock.recv(n - len(buf))
            assert got, "server closed"
            buf += got
        return buf

    def _read_msg(self):
        head = self._recv_exact(5)
        tag = head[:1]
        (length,) = struct.unpack("!I", head[1:])
        return tag, self._recv_exact(length - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._read_msg()
            msgs.append((tag, body))
            if tag == b"Z":
                return msgs

    def query(self, sql):
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, names, tagline, err = [], [], None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                for _ in range(ncols):
                    end = body.index(b"\0", at)
                    names.append(body[at:end].decode())
                    at = end + 1 + 18
            elif tag == b"D":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                row = []
                for _ in range(ncols):
                    (ln,) = struct.unpack("!i", body[at : at + 4])
                    at += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[at : at + ln].decode())
                        at += ln
                rows.append(tuple(row))
            elif tag == b"C":
                tagline = body.rstrip(b"\0").decode()
            elif tag == b"E":
                err = body
        return names, rows, tagline, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


@pytest.fixture
def server():
    catalog = Catalog(
        {"t": Schema([("k", DataType.INT64), ("v", DataType.INT64)])}
    )
    srv = PgServer(SqlSession(catalog, capacity=1 << 8)).start()
    yield srv
    srv.shutdown()


def test_pgwire_end_to_end(server):
    c = PgClient(server.port)
    _, _, tag, err = c.query(
        "CREATE MATERIALIZED VIEW s AS SELECT k, sum(v) AS total "
        "FROM t GROUP BY k"
    )
    assert err is None and tag == "CREATE_MATERIALIZED_VIEW"

    _, _, tag, err = c.query(
        "INSERT INTO t VALUES (1, 10), (2, 5), (1, 32)"
    )
    assert err is None and tag == "INSERT 0 3"

    names, rows, tag, err = c.query("SELECT k, total FROM s ORDER BY k")
    assert err is None and tag == "SELECT 2"
    assert names == ["k", "total"]
    assert rows == [("1", "42"), ("2", "5")]

    # errors surface as ErrorResponse and the session stays usable
    _, _, _, err = c.query("SELECT nope FROM s")
    assert err is not None and b"nope" in err
    names, rows, tag, err = c.query("SELECT k FROM s ORDER BY k")
    assert err is None and [r[0] for r in rows] == ["1", "2"]
    c.close()


def test_pgwire_create_table_full_workflow(server):
    """The psql workflow with no pre-seeded catalog: CREATE TABLE ->
    INSERT -> SELECT the table -> CREATE MV over it (backfilled) ->
    more INSERTs -> MV stays exact."""
    c = PgClient(server.port)
    _, _, tag, err = c.query(
        "CREATE TABLE orders (uid BIGINT, amount BIGINT)"
    )
    assert err is None and tag == "CREATE_TABLE"
    _, _, tag, err = c.query(
        "INSERT INTO orders VALUES (1, 10), (2, 20), (1, 5)"
    )
    assert err is None and tag == "INSERT 0 3"
    names, rows, tag, _ = c.query(
        "SELECT uid, amount FROM orders ORDER BY amount"
    )
    assert [r[1] for r in rows] == ["5", "10", "20"]

    # MV over the table backfills the 3 existing rows
    _, _, tag, err = c.query(
        "CREATE MATERIALIZED VIEW spend AS "
        "SELECT uid, sum(amount) AS total FROM orders GROUP BY uid"
    )
    assert err is None
    names, rows, _, err = c.query("SELECT uid, total FROM spend ORDER BY uid")
    assert err is None and rows == [("1", "15"), ("2", "20")]

    c.query("INSERT INTO orders VALUES (2, 1)")
    names, rows, _, err = c.query("SELECT uid, total FROM spend ORDER BY uid")
    assert err is None and rows == [("1", "15"), ("2", "21")]
    c.close()


def test_pgwire_concurrent_clients(server):
    a, b = PgClient(server.port), PgClient(server.port)
    a.query("CREATE MATERIALIZED VIEW m AS SELECT k, count(*) AS n FROM t GROUP BY k")
    b.query("INSERT INTO t VALUES (7, 1)")
    names, rows, _, err = a.query("SELECT k, n FROM m")
    assert err is None and rows == [("7", "1")]
    a.close()
    b.close()


class ExtendedClient(PgClient):
    """Extended-protocol helper: Parse/Bind/Describe/Execute/Sync."""

    def _send(self, tag, body=b""):
        self.sock.sendall(tag + struct.pack("!I", len(body) + 4) + body)

    def prepare(self, name, sql):
        self._send(
            b"P", name.encode() + b"\0" + sql.encode() + b"\0"
            + struct.pack("!h", 0)
        )

    def bind(self, portal, stmt, params):
        body = portal.encode() + b"\0" + stmt.encode() + b"\0"
        body += struct.pack("!h", 0)  # all-text param formats
        body += struct.pack("!h", len(params))
        for p in params:
            if p is None:
                body += struct.pack("!i", -1)
            else:
                b = str(p).encode()
                body += struct.pack("!i", len(b)) + b
        body += struct.pack("!h", 0)  # result formats
        self._send(b"B", body)

    def run(self, portal=""):
        self._send(b"D", b"P" + portal.encode() + b"\0")
        self._send(b"E", portal.encode() + b"\0" + struct.pack("!i", 0))
        self._send(b"S")
        rows, names, tagline = [], [], None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                for _ in range(ncols):
                    end = body.index(b"\0", at)
                    names.append(body[at:end].decode())
                    at = end + 1 + 18
            elif tag == b"D":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                row = []
                for _ in range(ncols):
                    (ln,) = struct.unpack("!i", body[at : at + 4])
                    at += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[at : at + ln].decode())
                        at += ln
                rows.append(tuple(row))
            elif tag == b"C":
                tagline = body.rstrip(b"\0").decode()
        return names, rows, tagline


def test_pgwire_extended_protocol(server):
    c = ExtendedClient(server.port)
    c.query("CREATE TABLE e (k BIGINT, v BIGINT)")
    c.query("INSERT INTO e VALUES (1, 10), (2, 20), (3, 30)")
    # prepared statement with parameters, executed twice
    c.prepare("s1", "SELECT k, v FROM e WHERE v > $1 ORDER BY k")
    c.bind("p1", "s1", [15])
    names, rows, tagline = c.run("p1")
    assert names == ["k", "v"]
    assert rows == [("2", "20"), ("3", "30")]
    assert tagline.startswith("SELECT")
    c.bind("p2", "s1", [25])
    _, rows2, _ = c.run("p2")
    assert rows2 == [("3", "30")]
    # parameterized INSERT through the extended path
    c.prepare("ins", "INSERT INTO e VALUES ($1, $2)")
    c.bind("p3", "ins", [9, 90])
    _, _, tag3 = c.run("p3")
    assert tag3.startswith("INSERT")
    _, rows3, _, _ = c.query("SELECT v FROM e WHERE k = 9")
    assert rows3 == [("90",)]
    # NULL parameter
    c.prepare("s2", "SELECT count(*) AS n FROM e WHERE v > $1")
    c.bind("p4", "s2", [None])
    _, rows4, _ = c.run("p4")
    assert rows4 == [("0",)]  # NULL comparison filters everything
    c.close()


def test_pgwire_extended_string_param(server):
    c = ExtendedClient(server.port)
    c.query("CREATE TABLE s (name VARCHAR, v BIGINT)")
    c.query("INSERT INTO s VALUES ('ann', 1), ('bob', 2)")
    c.prepare("q", "SELECT v FROM s WHERE name = $1")
    c.bind("", "q", ["ann"])
    _, rows, _ = c.run("")
    assert rows == [("1",)]
    # quoting: a value with an embedded quote must not break out
    c.bind("", "q", ["o'brien"])
    _, rows2, _ = c.run("")
    assert rows2 == []
    c.close()


def test_pgwire_extended_error_skips_to_sync(server):
    """An error mid-pipeline discards queued messages until Sync
    (review finding r5: the server used to keep processing)."""
    c = ExtendedClient(server.port)
    # Bind against an unknown statement, then pipeline D+E+S: exactly
    # ONE ErrorResponse must arrive before ReadyForQuery
    c.bind("px", "nope", [1])
    c._send(b"D", b"Ppx\0")
    c._send(b"E", b"px\0" + struct.pack("!i", 0))
    c._send(b"S")
    errs = sum(
        1 for tag, _ in c._drain_until_ready() if tag == b"E"
    )
    assert errs == 1
    # the connection is healthy again
    _, rows, _, err = c.query("SELECT 1 AS one FROM (SELECT 1 AS o) AS d")
    c.close()


def test_pgwire_param_value_with_dollar(server):
    """A parameter VALUE containing '$1' must never have another
    parameter substituted inside it (review finding r5)."""
    c = ExtendedClient(server.port)
    c.query("CREATE TABLE dz (a VARCHAR, b VARCHAR)")
    c.query("INSERT INTO dz VALUES ('x', 'keep$1keep')")
    c.prepare("q", "SELECT b FROM dz WHERE a = $1 AND b = $2")
    c.bind("", "q", ["x", "keep$1keep"])
    _, rows, _ = c.run("")
    assert rows == [("keep$1keep",)]
    c.close()
