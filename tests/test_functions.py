"""Scalar function library (src/expr/impl/src/scalar/ analogue):
numeric/temporal kernels vs numpy+datetime oracles, NULL policy, and
SQL wiring (EXTRACT special form, date_trunc, coalesce)."""

import datetime as dt

import numpy as np
import pytest

from risingwave_tpu.array.chunk import DataChunk, StreamChunk
from risingwave_tpu.expr import expr as E
from risingwave_tpu.expr import functions as F


def _chunk(**cols):
    n = len(next(iter(cols.values())))
    nulls = {
        k[:-7]: np.asarray(v, bool)
        for k, v in cols.items()
        if k.endswith("__nulls")
    }
    data = {
        k: np.asarray(v) for k, v in cols.items() if not k.endswith("__nulls")
    }
    return DataChunk.from_numpy(data, 1 << int(np.ceil(np.log2(max(2, n)))),
                                nulls=nulls or None)


def _eval(e, chunk):
    v, n = e.eval(chunk)
    v = np.asarray(v)[: None]
    return np.asarray(v), (None if n is None else np.asarray(n))


def test_numeric_functions():
    c = _chunk(x=[-3, 0, 5, 9], y=[2, 0, 3, 4])
    v, n = _eval(F.Func("abs", (E.col("x"),)), c)
    assert v[:4].tolist() == [3, 0, 5, 9]
    v, n = _eval(F.Func("mod", (E.col("x"), E.col("y"))), c)
    assert n is not None and n[:4].tolist() == [False, True, False, False]
    assert v[[0, 2, 3]].tolist() == [1, 2, 1]
    v, _ = _eval(F.Func("greatest", (E.col("x"), E.col("y"))), c)
    assert v[:4].tolist() == [2, 0, 5, 9]
    v, n = _eval(F.Func("sqrt", (E.col("x"),)), c)
    assert n[:4].tolist() == [True, False, False, False]
    assert v[[1, 2, 3]].tolist() == pytest.approx([0, 5 ** 0.5, 3.0])


@pytest.mark.parametrize("field", F._EXTRACT_FIELDS)
def test_extract_matches_datetime(field):
    rng = np.random.default_rng(1)
    ts = rng.integers(0, 2_000_000_000_000, 64)  # 1970..2033
    ts = np.concatenate([ts, np.asarray([0, 86_399_999, 951_868_800_000])])
    c = _chunk(t=ts.astype(np.int64))
    got, _ = _eval(F.Extract(field, E.col("t")), c)
    got = got[: len(ts)]
    for i, ms in enumerate(ts.tolist()):
        d = dt.datetime.fromtimestamp(ms / 1000, dt.timezone.utc)
        want = {
            "epoch": ms // 1000,
            "millisecond": ms % 1000,
            "second": d.second,
            "minute": d.minute,
            "hour": d.hour,
            "day": d.day,
            "month": d.month,
            "year": d.year,
            "dow": (d.weekday() + 1) % 7,
            "doy": d.timetuple().tm_yday,
        }[field]
        assert got[i] == want, (field, ms)


@pytest.mark.parametrize(
    "field", ["second", "minute", "hour", "day", "week", "month", "year"]
)
def test_date_trunc_matches_datetime(field):
    rng = np.random.default_rng(2)
    ts = rng.integers(0, 2_000_000_000_000, 64).astype(np.int64)
    c = _chunk(t=ts)
    got, _ = _eval(F.DateTrunc(field, E.col("t")), c)
    for i, ms in enumerate(ts.tolist()):
        d = dt.datetime.fromtimestamp(ms / 1000, dt.timezone.utc)
        if field == "second":
            w = d.replace(microsecond=0)
        elif field == "minute":
            w = d.replace(second=0, microsecond=0)
        elif field == "hour":
            w = d.replace(minute=0, second=0, microsecond=0)
        elif field == "day":
            w = d.replace(hour=0, minute=0, second=0, microsecond=0)
        elif field == "week":
            day0 = d.replace(hour=0, minute=0, second=0, microsecond=0)
            w = day0 - dt.timedelta(days=d.weekday())
        elif field == "month":
            w = d.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        else:
            w = d.replace(month=1, day=1, hour=0, minute=0, second=0,
                          microsecond=0)
        assert got[i] == int(w.timestamp() * 1000), (field, ms)


def test_coalesce_nullif():
    c = _chunk(
        a=[1, 2, 3, 4], a__nulls=[True, False, True, False],
        b=[10, 20, 30, 40], b__nulls=[False, False, True, False],
    )
    v, n = _eval(F.Coalesce((E.col("a"), E.col("b"))), c)
    assert n[:4].tolist() == [False, False, True, False]  # both-NULL stays
    assert v[[0, 1, 3]].tolist() == [10, 2, 4]  # value under NULL is free
    v, n = _eval(F.NullIf(E.col("b"), E.lit(20)), c)
    assert n[:4].tolist() == [False, True, True, False]


def test_string_funcs_over_dictionary():
    from risingwave_tpu.array.dictionary import StringDictionary

    d = StringDictionary()
    codes = d.encode(["Hello", "WORLD", "tpu"])
    c = _chunk(s=codes.astype(np.int32))
    v, _ = _eval(F.StringFunc("length", E.col("s"), d), c)
    assert v[:3].tolist() == [5, 5, 3]
    v, _ = _eval(F.StringFunc("upper", E.col("s"), d), c)
    assert [d.decode_one(int(x)) for x in v[:3]] == ["HELLO", "WORLD", "TPU"]


def test_sql_functions_end_to_end():

    from risingwave_tpu.sql import Catalog, StreamPlanner
    from risingwave_tpu.types import DataType, Schema

    cat = Catalog(
        {"t": Schema([("k", DataType.INT64), ("ts", DataType.TIMESTAMP),
                      ("v", DataType.INT64)])}
    )
    planner = StreamPlanner(cat, capacity=1 << 8)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW m AS SELECT k, "
        "EXTRACT(HOUR FROM ts) AS h, date_trunc('day', ts) AS day0, "
        "abs(v) AS av, coalesce(v, 0) AS cv FROM t"
    )
    ts = np.asarray(
        [1_700_000_000_000, 1_700_003_600_000, 86_399_999], np.int64
    )
    chunk = StreamChunk.from_numpy(
        {"k": np.arange(3, dtype=np.int64), "ts": ts,
         "v": np.asarray([-5, 7, -1], np.int64)},
        8,
    )
    mv.pipeline.push(chunk)
    mv.pipeline.barrier()
    # pk = hidden _row_id; values ordered (k, h, day0, av, cv)
    snap = {v[0]: v for v in mv.mview.snapshot().values()}
    for i in range(3):
        d = dt.datetime.fromtimestamp(ts[i] / 1000, dt.timezone.utc)
        day0 = int(
            d.replace(hour=0, minute=0, second=0, microsecond=0).timestamp()
            * 1000
        )
        _, h, got_day0, av, cv = snap[i][:5]
        assert (h, got_day0, av) == (d.hour, day0, abs([-5, 7, -1][i]))
