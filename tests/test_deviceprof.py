"""Device-level observability (deviceprof.py + the fused telemetry
lanes): the compiled-artifact roofline must return sane figures on CPU
for every Nexmark query, the in-program telemetry must match the
interpreted twin's per-member counts bit-for-bit at ZERO added
dispatches, the named-scope trace parse must recover all four fused
stages, EpochTrace must prefer modeled bytes over the legacy host
guess (keeping the legacy sum for artifact continuity), recovery must
re-arm deviceprof without orphaned capture windows, and every bench
artifact must carry provenance. CPU-only, tier-1."""

import json

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.deviceprof import (
    DEVICEPROF,
    FUSED_STAGES,
    analyze_nexmark,
    parse_fused_stages,
)
from risingwave_tpu.epoch_trace import EpochTrace
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.runtime.bucketing import padding_fraction
from risingwave_tpu.runtime.fused_step import fuse_pipeline

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def _clean_deviceprof():
    DEVICEPROF.reset()
    DEVICEPROF.disarm()
    yield
    DEVICEPROF.reset()
    DEVICEPROF.disarm()


def _chunks(epochs, chunks_per_epoch=2, n=400, cap=512):
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=20_000))
    out = []
    for _ in range(epochs):
        ep = []
        while len(ep) < chunks_per_epoch:
            c = gen.next_chunks(n, cap)["bid"]
            if c is not None:
                ep.append(c.select(["auction", "date_time"]))
        out.append(ep)
    return out


# ---------------------------------------------------------------------------
# telemetry lanes: fused vs interpreted twin, bit-for-bit
# ---------------------------------------------------------------------------


def test_fused_telemetry_matches_interpreted_twin_exactly():
    """Per-member telemetry (rows applied, dirty groups, MV rows,
    occupancies) from the fused program's packed lane must equal the
    counts the interpreted twin produces for the same epochs."""
    epochs = _chunks(3)
    fused = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    (wrapper,) = fuse_pipeline(fused.pipeline, label="q5")
    interp = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    # count the rows the interpreted MV actually receives (flush
    # deltas walking the chain at the barrier)
    mv_rows_seen = []
    orig_apply = interp.mview.apply

    def counting_apply(chunk):
        mv_rows_seen.append(int(jnp.sum(chunk.valid.astype(jnp.int32))))
        return orig_apply(chunk)

    interp.mview.apply = counting_apply
    for ep in epochs:
        rows_pushed = 0
        for c in ep:
            fused.pipeline.push(c)
            interp.pipeline.push(c)
            rows_pushed += int(jnp.sum(c.valid.astype(jnp.int32)))
        # interpreted applies landed at push time: the dirty-group
        # count pending at the barrier is the twin of the fused
        # program's pre-flush sample
        interp_dirty = int(jnp.sum(interp.agg.state.dirty.astype(jnp.int32)))
        mv_rows_seen.clear()
        fused.pipeline.barrier()
        interp.pipeline.barrier()
        tel = wrapper._telemetry
        assert tel is not None
        assert tel["rows_in"] == rows_pushed
        assert tel["dirty_groups"] == interp_dirty
        assert tel["mv_rows"] == sum(mv_rows_seen)
        assert tel["occupancy"]["agg"] == int(interp.agg.table.occupancy())
        assert tel["occupancy"]["mv"] == int(interp.mview.table.occupancy())
        # member attribution: pure prefix sees the input rows, the MV
        # sees the flush rows
        rows = tel["member_rows"]
        assert rows["0:HopWindowExecutor"] == rows_pushed
        assert rows["1:HashAggExecutor"] == rows_pushed
        assert rows["2:DeviceMaterializeExecutor"] == sum(mv_rows_seen)
        assert 0.0 < tel["lane_fill_frac"] <= 1.0
        assert 0.0 <= tel["padding_bytes_frac"] < 1.0
    # and the twins stayed bit-identical (the precondition of the
    # comparison above)
    assert fused.mview.snapshot() == interp.mview.snapshot()


def test_telemetry_armed_adds_zero_dispatches_and_syncs():
    """Telemetry + deviceprof armed: the steady fused barrier still
    costs exactly ONE device dispatch (the telemetry rides the
    existing program and the existing staged-scalar read)."""
    DEVICEPROF.arm()
    q5 = build_q5_lite(capacity=1 << 11, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    bid = gen.next_chunks(1500, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )

    def epoch():
        q5.pipeline.push(bid)
        q5.pipeline.barrier()

    epoch()
    epoch()  # warm: compiles + analyses land before counting
    PROFILER.reset()
    PROFILER.enable(fence=False)
    try:
        per = []
        for _ in range(3):
            base = PROFILER.total_dispatches()
            epoch()
            per.append(PROFILER.total_dispatches() - base)
    finally:
        PROFILER.disable()
        PROFILER.reset()
    assert per == [1.0, 1.0, 1.0], per
    # the roofline model populated without touching the dispatch count
    # (analyses are deferred off the dispatch path; flush runs them)
    DEVICEPROF.flush_analyses()
    model = DEVICEPROF.steady_model()
    assert model["modeled_bytes"] > 0
    assert 0.0 <= model["padding_frac"] < 1.0


# ---------------------------------------------------------------------------
# compiled-artifact roofline: sane figures on CPU, all four queries
# ---------------------------------------------------------------------------


def test_cost_memory_analysis_sane_for_all_four_queries():
    rep = analyze_nexmark()
    assert set(rep) == {"q5", "q5u", "q7", "q8"}
    for q, entries in rep.items():
        assert entries, f"{q}: no traceable executors analyzed"
        sane = [
            v
            for v in entries.values()
            if "error" not in v
            and v["flops"] > 0
            and v["bytes_accessed"] > 0
            and v["compile_ms"] > 0
        ]
        assert sane, f"{q}: no sane cost/memory analysis: {entries}"
        errors = {k: v for k, v in entries.items() if "error" in v}
        assert not errors, f"{q}: analysis errors: {errors}"


def test_fused_program_analysis_populates_gauges():
    from risingwave_tpu.metrics import REGISTRY

    DEVICEPROF.arm()
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    fuse_pipeline(q5.pipeline, label="q5")
    for ep in _chunks(2):
        for c in ep:
            q5.pipeline.push(c)
        q5.pipeline.barrier()
    progs = DEVICEPROF.report()["programs"]
    assert any(k.startswith("fused:q5|") for k in progs)
    for p in progs.values():
        assert "error" not in p, p
        assert p["bytes_accessed"] > 0 and p["compile_ms"] > 0
        assert p["argument_bytes"] > 0
    # the ISSUE's metric surface: compile_ms{fn,bucket},
    # executable_bytes{fn,bucket}, fused_modeled_bytes{fragment}
    assert REGISTRY.gauges["fused_modeled_bytes"].get(fragment="q5") > 0
    assert any(
        dict(k).get("fn", "").startswith("fused:q5")
        for k in REGISTRY.gauges["compile_ms"]._values
    )
    assert "executable_bytes" in REGISTRY.gauges


# ---------------------------------------------------------------------------
# fused-stage attribution: named-scope capture parse
# ---------------------------------------------------------------------------


def test_trace_parse_produces_all_four_stages(tmp_path):
    trace = {
        "traceEvents": [
            {"name": "jit_fn/fused/apply/reduce", "ph": "X", "dur": 500},
            {"name": "fused/flush", "ph": "X", "dur": 300},
            {"name": "x/fused/mv_write/scatter", "ph": "X", "dur": 120},
            {"name": "fused/scalar_pack", "ph": "B", "ts": 1000},
            {"name": "fused/scalar_pack", "ph": "E", "ts": 1080},
            {"name": "fused:q5", "ph": "X", "dur": 1100},
            {"name": "unrelated_op", "ph": "X", "dur": 999},
        ]
    }
    parsed = parse_fused_stages(trace)
    assert parsed["fragment"] == "q5"
    assert set(parsed["stages_ms"]) == set(FUSED_STAGES)
    assert parsed["stages_ms"]["apply"] == pytest.approx(0.5)
    assert parsed["stages_ms"]["scalar_pack"] == pytest.approx(0.08)
    # gzip'd TensorBoard layout parses identically
    import gzip

    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)
    parsed2 = parse_fused_stages(str(tmp_path))
    assert parsed2["stages_ms"] == parsed["stages_ms"]
    # the metric surface
    from risingwave_tpu.metrics import REGISTRY

    h = REGISTRY.histograms.get("fused_stage_ms")
    assert h is not None
    assert h.count(fragment="q5", stage="apply") >= 2


def test_fused_program_traces_with_named_scopes():
    """The four stage scopes actually appear in the fused program's
    jaxpr/HLO (the precondition for a device capture segmenting it)."""
    q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
    (w,) = fuse_pipeline(q5.pipeline, label="q5")
    for c in _chunks(1)[0]:
        q5.pipeline.push(c)
    q5.pipeline.barrier()
    from risingwave_tpu.runtime.fused_step import _fused_barrier_step

    # lower the flush-bearing bucket and look for the scope names in
    # the stable HLO text
    states = (w._agg_state(), w._mv_state())
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), states
    )
    # scope names live in op metadata, which survives into the
    # compiled executable's HLO (exactly what a device trace reports)
    txt = (
        _fused_barrier_step.lower(
            abstract, None, None, w.plan, 1, (256,), False
        )
        .compile()
        .as_text()
    )
    for stage in ("flush", "mv_write", "scalar_pack"):
        assert f"fused/{stage}" in txt, f"named scope fused/{stage} lost"


# ---------------------------------------------------------------------------
# EpochTrace byte accounting + flight-recorder tail
# ---------------------------------------------------------------------------


def _seed_model(modeled=10_000_000, pad=0.9):
    DEVICEPROF.fragments["q5"] = {
        "fn": "fused:q5",
        "bucket": "b",
        "modeled_bytes": modeled,
    }
    DEVICEPROF.telemetry["q5"] = {"padding_bytes_frac": pad}
    # the model is dispatch-gated: only fragments that ran since the
    # last consumed barrier count toward that barrier's bytes
    DEVICEPROF._dispatched.add("q5")


def test_epoch_trace_prefers_modeled_bytes_keeps_legacy():
    _seed_model()
    tr = EpochTrace(7, 1, True)
    tr.chunk_bytes = 1000
    tr.finalize(5000, 4000)
    d = tr.to_dict()
    assert d["hbm_bytes_touched_legacy"] == 2000  # delta 1000 + chunks
    assert d["modeled_bytes"] == 10_000_000
    assert d["hbm_bytes_touched"] == 10_000_000
    assert d["padding_bytes_frac"] == pytest.approx(0.9)
    assert d["useful_bytes"] + d["padding_bytes"] == d["hbm_bytes_touched"]
    assert d["useful_bw_frac"] == pytest.approx(
        d["achieved_bw_frac"] * 0.1, rel=1e-3
    )


def test_idle_barrier_models_zero_traffic():
    """Regression (review finding): the model is consumed per barrier
    — a barrier with NO fused dispatch must model zero bytes, not
    re-report the last program's traffic as phantom bandwidth."""
    _seed_model()
    tr1 = EpochTrace(1, 1, True)
    tr1.finalize(1000, 0)
    assert tr1.modeled_bytes == 10_000_000
    assert tr1.telemetry == {"q5": {"rows": {}, "dirty": 0}}
    # idle barrier: nothing dispatched since tr1 consumed the model
    tr2 = EpochTrace(2, 2, False)
    tr2.chunk_bytes = 64
    tr2.finalize(1000, 1000)
    assert tr2.modeled_bytes == 0
    assert tr2.hbm_bytes_touched == tr2.hbm_bytes_touched_legacy == 64
    assert tr2.telemetry == {}


def test_epoch_trace_falls_back_to_legacy_without_model():
    tr = EpochTrace(8, 1, False)
    tr.chunk_bytes = 500
    tr.finalize(4000, 4000)
    assert tr.modeled_bytes == 0
    assert tr.hbm_bytes_touched == tr.hbm_bytes_touched_legacy == 500


def test_flight_recorder_carries_roofline_tail(tmp_path):
    from risingwave_tpu.blackbox import FlightRecorder, read_segment

    _seed_model()
    DEVICEPROF.telemetry["q5"].update(
        {"member_rows": {"1:HashAggExecutor": 42}, "dirty_groups": 7}
    )
    tr = EpochTrace(1, 1, True)
    tr.finalize(1000, 0)
    rec = FlightRecorder()
    rec.configure(dir=str(tmp_path))
    rec.record_barrier(tr)
    rec.close()
    doc = read_segment(str(tmp_path))
    (r,) = doc["records"]
    assert r["modeled_bytes"] == 10_000_000
    assert r["padding_bytes_frac"] == pytest.approx(0.9)
    assert r["telemetry"]["q5"]["dirty"] == 7
    assert r["telemetry"]["q5"]["rows"]["1:HashAggExecutor"] == 42


def test_blackbox_cli_roofline_column(tmp_path):
    import subprocess
    import sys

    from risingwave_tpu.blackbox import FlightRecorder

    _seed_model()
    tr = EpochTrace(1, 1, True)
    tr.finalize(1000, 0)
    rec = FlightRecorder()
    rec.configure(dir=str(tmp_path))
    rec.record_barrier(tr)
    rec.close()
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "risingwave_tpu",
            "blackbox",
            str(tmp_path),
            "--roofline",
        ],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "blackbox roofline:" in out.stdout
    assert "modeled" in out.stdout and "padding" in out.stdout
    assert "model=10.0MB" in out.stdout


# ---------------------------------------------------------------------------
# recovery / rebuild re-arms deviceprof; no orphaned captures
# ---------------------------------------------------------------------------


def test_rebuild_rearms_deviceprof_without_orphans():
    from risingwave_tpu.connectors.nexmark import BID_SCHEMA
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv
    from risingwave_tpu.sql import Catalog, StreamPlanner

    DEVICEPROF.arm()
    factory = lambda: StreamPlanner(
        Catalog({"bid": BID_SCHEMA}), capacity=1 << 11
    )
    mv = graph_planned_mv(
        factory,
        "CREATE MATERIALIZED VIEW q5 AS SELECT auction, window_start, "
        "count(*) AS num FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
        "INTERVAL '10' SECOND) GROUP BY auction, window_start",
        parallelism=1,
    )
    try:
        (bid,) = _chunks(1, chunks_per_epoch=1)[0]
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        assert DEVICEPROF.telemetry, "fused barrier produced no telemetry"
        programs_before = set(DEVICEPROF.report()["programs"])
        assert programs_before
        # recovery hygiene: telemetry drops (stale), analyses survive
        # (the rebuilt fragment re-fuses into the same programs), and
        # no capture window exists to orphan
        DEVICEPROF.on_recovery()
        assert DEVICEPROF.telemetry == {}
        mv.pipeline.rebuild()
        mv.pipeline.push(bid)
        mv.pipeline.barrier()
        assert DEVICEPROF.telemetry, "rebuilt fragment lost telemetry"
        assert set(DEVICEPROF.report()["programs"]) >= programs_before
        assert DEVICEPROF.report()["analysis_errors"] == 0
        assert PROFILER.active_captures == []
    finally:
        mv.pipeline.close()


# ---------------------------------------------------------------------------
# padding accounting + provenance
# ---------------------------------------------------------------------------


def test_padding_fraction_weighted():
    assert padding_fraction([]) == 0.0
    assert padding_fraction([(100, 100, 8)]) == 0.0
    assert padding_fraction([(100, 0, 8)]) == 1.0
    # weighting: the wide table's waste dominates
    got = padding_fraction([(100, 50, 30), (100, 100, 10)])
    assert got == pytest.approx(0.375)
    # live beyond capacity clamps (occupancy counts tombstones)
    assert padding_fraction([(64, 1000, 8)]) == 0.0


def test_provenance_stamp_and_generation_warning():
    from risingwave_tpu.provenance import ENGINE_GENERATION, stamp

    s = stamp()
    assert s["engine_generation"] == ENGINE_GENERATION >= 11
    assert isinstance(s["git_sha"], str) and s["git_sha"]
    assert isinstance(s["pr_tag"], str)
    import sys

    sys.path.insert(0, "scripts")
    try:
        from perf_gate import generation_warnings
    finally:
        sys.path.pop(0)
    assert generation_warnings(dict(s), "x") == []
    old = dict(s, engine_generation=ENGINE_GENERATION - 1)
    assert any("generation" in w for w in generation_warnings(old, "x"))
    assert any(
        "no engine_generation" in w for w in generation_warnings({}, "x")
    )
    # fusion-report shape: provenance under the "_"-prefixed key
    nested = {"_provenance": dict(s), "q5": {}}
    assert generation_warnings(nested, "x") == []
