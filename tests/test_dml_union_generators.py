"""DML (INSERT INTO), UNION-ALL subscription edges, VALUES and NOW
generator executors (reference: dml.rs, union.rs, values.rs, now.rs)."""

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import (
    MaterializeExecutor,
    NowExecutor,
    ValuesExecutor,
)
from risingwave_tpu.runtime import DmlManager, Pipeline, StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import DataType, Schema

T_SCHEMA = Schema([("k", DataType.INT64), ("v", DataType.INT64)])


def test_insert_parse_and_route():
    catalog = Catalog({"t": T_SCHEMA})
    planner = StreamPlanner(catalog, capacity=1 << 8)
    runtime = StreamingRuntime(store=None)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW s AS SELECT k, sum(v) AS s FROM t GROUP BY k"
    )
    runtime.register("s", mv.pipeline)
    dml = DmlManager(runtime, catalog)
    dml.attach(mv)

    stmt = P.parse("INSERT INTO t (k, v) VALUES (1, 10), (2, -5), (1, 3)")
    assert isinstance(stmt, P.InsertValues)
    assert stmt.rows == ((1, 10), (2, -5), (1, 3))

    n = dml.execute("INSERT INTO t (k, v) VALUES (1, 10), (2, -5), (1, 3)")
    assert n == 3
    runtime.barrier()
    assert mv.mview.snapshot() == {(1,): (13,), (2,): (-5,)}

    dml.execute("INSERT INTO t VALUES (2, 5)")
    runtime.barrier()
    assert mv.mview.snapshot() == {(1,): (13,), (2,): (0,)}


def test_union_all_via_subscriptions():
    """Two upstream MVs feeding one downstream = UNION ALL (union.rs)."""
    catalog = Catalog({"a": T_SCHEMA, "b": T_SCHEMA})
    planner = StreamPlanner(catalog, capacity=1 << 8)
    runtime = StreamingRuntime(store=None)
    mva = planner.plan(
        "CREATE MATERIALIZED VIEW ma AS SELECT k, v FROM a GROUP BY k, v"
    )
    mvb = planner.plan(
        "CREATE MATERIALIZED VIEW mb AS SELECT k, v FROM b GROUP BY k, v"
    )
    runtime.register("ma", mva.pipeline)
    runtime.register("mb", mvb.pipeline)
    catalog.add_mv(mva)

    un = planner.plan(
        "CREATE MATERIALIZED VIEW u AS SELECT k, sum(v) AS s FROM ma GROUP BY k"
    )
    runtime.register("u", un.pipeline, upstream="ma")
    runtime.subscribe("mb", "u", backfill=False)  # the second union input

    def push(name, rows):
        chunk = StreamChunk.from_numpy(
            {
                "k": np.asarray([r[0] for r in rows], np.int64),
                "v": np.asarray([r[1] for r in rows], np.int64),
            },
            8,
        )
        runtime.push(name, chunk)

    push("ma", [(1, 5), (2, 7)])
    push("mb", [(1, 100), (3, 9)])
    runtime.barrier()
    assert un.mview.snapshot() == {(1,): (105,), (2,): (7,), (3,): (9,)}


def test_values_and_now_executors():
    vals = ValuesExecutor({"x": np.asarray([3, 1, 4], np.int64)})
    mv = MaterializeExecutor(pk=("_row_id",), columns=("x",))
    pipe = Pipeline([vals, mv])
    pipe.barrier()
    assert {v[0] for v in mv.snapshot().values()} == {3, 1, 4}
    pipe.barrier()  # emits once, not per barrier
    assert len(mv.snapshot()) == 3

    now = NowExecutor()
    mvn = MaterializeExecutor(pk=(), columns=("now",))
    pipe = Pipeline([now, mvn])
    pipe.barrier(epoch=1000 << 16)
    assert mvn.snapshot() == {(): (1000,)}
    pipe.barrier(epoch=2000 << 16)
    assert mvn.snapshot() == {(): (2000,)}


def test_over_window_matches_pandas():
    import pandas as pd
    import jax.numpy as jnp

    from risingwave_tpu.executors.over_window import (
        OverWindowExecutor,
        WindowCall,
    )

    rng = np.random.default_rng(9)
    ex = OverWindowExecutor(
        ("p",),
        (
            WindowCall("row_number", None, "rn"),
            WindowCall("sum", "v", "rsum"),
        ),
        {"p": jnp.int64, "v": jnp.int64},
        capacity=64,  # forces growth across chunks
    )
    all_p, all_v, got = [], [], {"rn": [], "rsum": []}
    for _ in range(6):
        p = rng.integers(0, 40, 50).astype(np.int64)
        v = rng.integers(-20, 20, 50).astype(np.int64)
        all_p.extend(p.tolist())
        all_v.extend(v.tolist())
        chunk = StreamChunk.from_numpy({"p": p, "v": v}, 64)
        for out in ex.apply(chunk):
            d = out.to_numpy(False)
            got["rn"].extend(d["rn"].tolist())
            got["rsum"].extend(d["rsum"].tolist())
        ex.on_barrier(None)

    df = pd.DataFrame({"p": all_p, "v": all_v})
    want_rn = df.groupby("p").cumcount() + 1
    want_rsum = df.groupby("p")["v"].cumsum()
    assert got["rn"] == want_rn.tolist()
    assert got["rsum"] == want_rsum.tolist()
