"""NotificationHub + multi-session observation + DROP DDL.

Reference: src/meta/src/manager/notification.rs (versioned catalog
push) + observer_manager.rs (frontend applies deltas after a snapshot
catch-up) + handler/drop_*.rs (dependency-guarded drops).
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.runtime import NotificationHub, StreamingRuntime
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_hub_versioned_catchup():
    hub = NotificationHub()
    hub.publish("add", "table", "a")
    hub.publish("add", "mv", "b")
    seen = []
    hub.subscribe(lambda n: seen.append((n.version, n.op, n.name)),
                  from_version=1)
    assert seen == [(2, "add", "b")]  # snapshot-then-deltas: v1 skipped
    hub.publish("drop", "mv", "b")
    assert seen[-1] == (3, "drop", "b")


def test_cross_session_observation():
    """Session B sees A's DDL: reads A's MV and writes A's table
    through the SHARED runtime — no double registration."""
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    a.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    a.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, sum(v) AS sv FROM t GROUP BY k"
    )
    a.execute("INSERT INTO t VALUES (1, 10)")
    # B reads the MV it never created
    out, _ = b.execute("SELECT k, sv FROM m")
    assert list(out["sv"]) == [10]
    # B writes the table; A sees the effect
    b.execute("INSERT INTO t VALUES (1, 5)")
    out, _ = a.execute("SELECT k, sv FROM m")
    assert list(out["sv"]) == [15]


def test_late_subscriber_snapshot():
    """A session created AFTER the DDL still catches up (the
    snapshot-then-deltas contract)."""
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    a.execute("CREATE TABLE t (v BIGINT)")
    a.execute("INSERT INTO t VALUES (7)")
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    out, _ = b.execute("SELECT v FROM t")
    assert list(out["v"]) == [7]


def test_drop_mv_and_table():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
    # the table is depended on: refuse
    with pytest.raises(ValueError, match="depend"):
        s.execute("DROP TABLE t")
    _, tag = s.execute("DROP MATERIALIZED VIEW m")
    assert tag == "DROP_MV"
    with pytest.raises(Exception):
        s.execute("SELECT n FROM m")
    _, tag = s.execute("DROP TABLE t")  # now free
    assert tag == "DROP_TABLE"
    # name is reusable after drop
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (1)")
    out, _ = s.execute("SELECT v FROM t")
    assert list(out["v"]) == [1]


def test_drop_source_guarded(tmp_path):
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"v": 1}'])
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute("CREATE MATERIALIZED VIEW c AS SELECT count(*) AS n FROM g")
    with pytest.raises(ValueError, match="depend"):
        s.execute("DROP SOURCE g")
    s.execute("DROP MATERIALIZED VIEW c")
    _, tag = s.execute("DROP SOURCE g")
    assert tag == "DROP_SOURCE"


def test_drop_notifies_peers():
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    a.execute("CREATE TABLE t (v BIGINT)")
    a.execute("DROP TABLE t")
    with pytest.raises(Exception):
        b.execute("SELECT v FROM t")


def test_drop_survives_ddl_log_restore():
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = MemObjectStore()
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute("CREATE TABLE keepme (v BIGINT)")
    s.execute("CREATE TABLE dropme (v BIGINT)")
    s.execute("DROP TABLE dropme")
    s.execute("INSERT INTO keepme VALUES (3)")
    rt.wait_checkpoints()
    s2 = SqlSession.restore(StreamingRuntime(store))
    out, _ = s2.execute("SELECT v FROM keepme")
    assert list(out["v"]) == [3]
    with pytest.raises(Exception):
        s2.execute("SELECT v FROM dropme")


def test_peer_mv_over_notified_source(tmp_path):
    """Session B creates an MV over a source A announced: B's pump
    must work (review finding r5: KeyError in B's source_mgr)."""
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"v": 5}'])
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    a.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    b.execute("CREATE MATERIALIZED VIEW m AS SELECT sum(v) AS s FROM g")
    b.pump_sources()
    b.runtime.barrier()
    out, _ = b.execute("SELECT s FROM m")
    assert list(out["s"]) == [5]


def test_subscribe_ordering_under_concurrent_publish():
    """The reorder buffer applies strictly in version order even when
    a live publish races the backlog replay (review finding r5)."""
    import threading

    hub = NotificationHub()
    for i in range(50):
        hub.publish("add", "table", f"t{i}")
    seen = []
    barrier = threading.Barrier(2)

    def subscriber():
        barrier.wait()
        hub.subscribe(lambda n: seen.append(n.version))

    def publisher():
        barrier.wait()
        for i in range(50):
            hub.publish("add", "table", f"u{i}")

    ts = [threading.Thread(target=subscriber), threading.Thread(target=publisher)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert seen == sorted(seen), "out-of-order delivery"
    assert seen == list(range(1, 101))  # exactly once, no gaps


def test_closed_session_stops_observing():
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    b.close()
    a.execute("CREATE TABLE t (v BIGINT)")
    assert "t" not in b.catalog.tables


def test_drop_frees_hub_payload_refs():
    hub = NotificationHub()
    rt = StreamingRuntime(store=None)
    a = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    a.execute("CREATE TABLE t (v BIGINT)")
    a.execute("DROP TABLE t")
    _, log = hub.snapshot()
    adds = [n for n in log if n.op == "add" and n.name == "t"]
    assert all(not n.payload for n in adds), "dropped refs retained"
    # late subscriber: empty-payload add + drop nets to nothing
    b = SqlSession(Catalog({}), rt, capacity=1 << 10, hub=hub)
    assert "t" not in b.catalog.tables


def test_drop_source_leaves_checkpoint_cycle(tmp_path):
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"v": 1}'])
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    src = s.sources["g"]
    assert src in s.runtime._aux_state
    s.execute("DROP SOURCE g")
    assert src not in s.runtime._aux_state
