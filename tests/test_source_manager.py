"""SourceManager: split-to-worker assignment, periodic discovery,
minimal-move rebalancing, exact offsets across reassignment.

Reference: src/meta/src/stream/source_manager.rs — meta discovers
splits on a tick, diff-assigns new ones, and ships SourceChangeSplit
mutations; offsets travel with the split (exactly-once across moves).
"""

import pytest

from risingwave_tpu.connectors.framework import (
    FileLogSource,
    GenericSourceExecutor,
    JsonParser,
)
from risingwave_tpu.runtime import SourceManager
from risingwave_tpu.types import DataType, Field, Schema

pytestmark = pytest.mark.smoke


def _src(tmp_path):
    schema = Schema([Field("v", DataType.INT64)])
    return GenericSourceExecutor(
        FileLogSource(str(tmp_path)), JsonParser(schema), table_id="s"
    )


def _rows(chunks):
    out = []
    for c in chunks:
        d = c.to_numpy()
        out.extend(int(x) for x in d["v"])
    return out


def test_assignment_partitions_splits(tmp_path):
    d = str(tmp_path)
    for p in range(4):
        FileLogSource.append(d, p, [f'{{"v": {p * 10 + i}}}' for i in range(3)])
    src = _src(tmp_path)
    src.discover()
    mgr = SourceManager()
    mgr.register("s", src, parallelism=2)
    a = mgr.assignment("s")
    assert len(a) == 4
    assert sorted(set(a.values())) == [0, 1]  # both workers used
    # disjoint polls: union of workers == everything, no double-reads
    rows0 = _rows(mgr.poll("s", 0, 64, 16))
    rows1 = _rows(mgr.poll("s", 1, 64, 16))
    assert sorted(rows0 + rows1) == sorted(
        p * 10 + i for p in range(4) for i in range(3)
    )
    assert not (set(rows0) & set(rows1))


def test_discovery_assigns_new_split_least_loaded(tmp_path):
    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"v": 1}'])
    src = _src(tmp_path)
    src.discover()
    mgr = SourceManager()
    mgr.register("s", src, parallelism=2)
    assert len(mgr.assignment("s")) == 1
    FileLogSource.append(d, 1, ['{"v": 2}'])
    fresh = mgr.discover("s")
    assert fresh == ["1"]
    a = mgr.assignment("s")
    # the new split lands on the OTHER (empty) worker
    assert a["0"] != a["1"]


def test_rebalance_preserves_offsets_exactly(tmp_path):
    """A reassigned split resumes at its committed offset: no loss, no
    double-read (the reference moves offsets WITH the split)."""
    d = str(tmp_path)
    for p in range(3):
        FileLogSource.append(d, p, [f'{{"v": {p * 100 + i}}}' for i in range(2)])
    src = _src(tmp_path)
    src.discover()
    mgr = SourceManager()
    mgr.register("s", src, parallelism=3)
    seen = []
    for w in range(3):
        seen += _rows(mgr.poll("s", w, 64, 16))
    # shrink to 1 worker: every split moves to slot 0
    moves = mgr.set_parallelism("s", 1)
    assert all(w == 0 for w in mgr.assignment("s").values())
    # append more rows; slot 0 must read ONLY the new rows
    for p in range(3):
        FileLogSource.append(d, p, [f'{{"v": {p * 100 + 50}}}'])
    more = _rows(mgr.poll("s", 0, 64, 16))
    assert sorted(more) == [50, 150, 250]
    assert sorted(seen) == sorted(
        p * 100 + i for p in range(3) for i in range(2)
    )


def test_grow_parallelism_moves_minimum(tmp_path):
    d = str(tmp_path)
    for p in range(4):
        FileLogSource.append(d, p, ['{"v": 0}'])
    src = _src(tmp_path)
    src.discover()
    mgr = SourceManager()
    mgr.register("s", src, parallelism=1)
    assert set(mgr.assignment("s").values()) == {0}
    moves = mgr.set_parallelism("s", 2)
    a = mgr.assignment("s")
    loads = [list(a.values()).count(w) for w in (0, 1)]
    assert sorted(loads) == [2, 2]  # balanced
    assert len(moves) == 2  # minimal movement: only 2 of 4 moved


def test_session_parallel_source_end_to_end(tmp_path):
    """CREATE SOURCE under a parallelism-2 session: pump reads every
    split exactly once per poll through the worker slots."""
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"uid": 1, "amt": 10}'])
    FileLogSource.append(d, 1, ['{"uid": 2, "amt": 20}'])
    s = SqlSession(Catalog({}), capacity=1 << 10, parallelism=2)
    s.execute(
        f"CREATE SOURCE pay (uid BIGINT, amt BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW spend AS "
        "SELECT uid, sum(amt) AS total FROM pay GROUP BY uid"
    )
    assert s.source_mgr.parallelism("pay") == 2
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [10, 20]
    # a THIRD partition appears mid-stream; discovery picks it up
    FileLogSource.append(d, 2, ['{"uid": 3, "amt": 30}'])
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [10, 20, 30]


def test_source_rate_limit_throttles_polls(tmp_path):
    """Token-bucket throttle (Mutation::Throttle analogue): a poll
    never reads more records than the bucket holds; refill follows
    wall time."""
    d = str(tmp_path)
    FileLogSource.append(d, 0, [f'{{"v": {i}}}' for i in range(100)])
    src = _src(tmp_path)
    src.discover()
    src.set_rate_limit(5)
    rows = _rows(src.poll(64, 16))
    assert len(rows) == 5  # burst = one second's allowance
    assert rows == [0, 1, 2, 3, 4]
    # bucket empty: an immediate second poll reads ~nothing
    assert len(_rows(src.poll(64, 16))) <= 1
    # simulate 1s elapsing: shift the refill clock back
    src._bucket_t -= 1.0
    rows2 = _rows(src.poll(64, 16))
    assert 4 <= len(rows2) <= 6  # ~5 more, offset-contiguous
    assert rows2[0] in (5, 6)
    # lift the throttle: everything else arrives
    src.set_rate_limit(None)
    rest = _rows(src.poll(1000, 1 << 10))
    assert sorted(rows + _rows([]) + rows2 + rest) == list(range(100))


def test_alter_source_rate_limit_sql(tmp_path):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.sql import Catalog

    d = str(tmp_path)
    FileLogSource.append(d, 0, [f'{{"v": {i}}}' for i in range(50)])
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute("CREATE MATERIALIZED VIEW c AS SELECT count(*) AS n FROM g")
    s.execute("ALTER SOURCE g SET rate_limit = 10")
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT n FROM c")
    assert out["n"][0] == 10  # throttled to one second's burst
    s.execute("ALTER SOURCE g SET rate_limit = DEFAULT")
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT n FROM c")
    assert out["n"][0] == 50


def test_throttle_rotates_fairly_across_splits(tmp_path):
    """A busy early split must not starve later splits under a rate
    limit: the poll start rotates (review finding r5)."""
    d = str(tmp_path)
    FileLogSource.append(d, 0, [f'{{"v": {i}}}' for i in range(1000)])
    FileLogSource.append(d, 1, [f'{{"v": {1000 + i}}}' for i in range(5)])
    src = _src(tmp_path)
    src.discover()
    src.set_rate_limit(5)
    seen = set(_rows(src.poll(64, 16)))
    for _ in range(6):
        src._bucket_t -= 1.0  # refill deterministically
        seen |= set(_rows(src.poll(64, 16)))
    assert any(v >= 1000 for v in seen), "split 1 starved"


def test_alter_source_rate_limit_survives_restore(tmp_path):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.storage.object_store import MemObjectStore

    d = str(tmp_path)
    FileLogSource.append(d, 0, ['{"v": 1}'])
    store = MemObjectStore()
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute(
        f"CREATE SOURCE g (v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='json')"
    )
    s.execute("ALTER SOURCE g SET rate_limit = 7")
    rt.wait_checkpoints()
    s2 = SqlSession.restore(StreamingRuntime(store))
    assert s2.sources["g"].rate_limit == 7
