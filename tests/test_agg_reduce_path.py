"""Epoch pre-reduction agg path (ops/agg.reduce_by_key +
hash_agg._agg_epoch_reduced) — differential vs the lax.scan path and
the numpy oracle, plus a bench-shape tier so the suite exercises the
shapes bench.py runs (VERDICT r2 #1: the suite was green while the
bench crashed at untested shapes)."""

import jax
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.parallel.sharded_agg import stack_chunks


CALLS = (
    AggCall("count_star", None, "cnt"),
    AggCall("count", "v", "cv"),
    AggCall("sum", "v", "s"),
    AggCall("min", "v", "mn"),
    AggCall("max", "f", "mx"),
)
DTYPES = {"k": np.int64, "v": np.int64, "f": np.float64}


def _mk_chunks(rng, n_chunks, cap, nkeys=40, with_nulls=True):
    chunks = []
    for _ in range(n_chunks):
        n = int(rng.integers(cap // 2, cap + 1))
        cols = {
            "k": rng.integers(0, nkeys, n).astype(np.int64),
            "v": rng.integers(-50, 100, n).astype(np.int64),
            "f": rng.normal(size=n),
        }
        nulls = (
            {"v": rng.random(n) < 0.2, "f": rng.random(n) < 0.2}
            if with_nulls
            else None
        )
        chunks.append(StreamChunk.from_numpy(cols, cap, nulls=nulls))
    return chunks


def _state_snapshot(ex):
    live = np.asarray(ex.table.live)
    k = np.asarray(ex.table.keys[0])[live]
    out = {}
    for name in ("cnt", "cv", "s", "mn", "mx"):
        out[name] = dict(
            zip(k.tolist(), np.asarray(ex.state.accums[name])[live].tolist())
        )
    for name in ("s", "mn", "mx"):
        out[f"nn_{name}"] = dict(
            zip(k.tolist(), np.asarray(ex.state.nonnull[name])[live].tolist())
        )
    return out


def _run(mode, seed, epochs=3, n_chunks=4, cap=128):
    rng = np.random.default_rng(seed)
    ex = HashAggExecutor(
        ["k"], CALLS, DTYPES, capacity=1 << 10, out_cap=1 << 9
    )
    for _ in range(epochs):
        chunks = _mk_chunks(rng, n_chunks, cap)
        ex.apply_stacked(stack_chunks(chunks), mode=mode)
        ex.on_barrier(None)
        ex.finish_barrier()
    return _state_snapshot(ex)


def test_reduce_matches_scan():
    assert _run("reduce", 3) == _run("scan", 3)


def test_reduce_matches_oracle_append_only():
    rng = np.random.default_rng(11)
    ex = HashAggExecutor(
        ["k"], CALLS, DTYPES, capacity=1 << 10, out_cap=1 << 9
    )
    cnt, cv, s = {}, {}, {}
    rng2 = np.random.default_rng(11)
    for _ in range(2):
        chunks = _mk_chunks(rng, 3, 64)
        ex.apply_stacked(stack_chunks(chunks), mode="reduce")
        ex.on_barrier(None)
        ex.finish_barrier()
        for c in _mk_chunks(rng2, 3, 64):
            d = c.to_numpy(with_ops=True)
            valid_n = len(d["k"])
            for i in range(valid_n):
                key = int(d["k"][i])
                cnt[key] = cnt.get(key, 0) + 1
                if not d.get("v__null", np.zeros(valid_n, bool))[i]:
                    cv[key] = cv.get(key, 0) + 1
                    s[key] = s.get(key, 0) + int(d["v"][i])
    got = _state_snapshot(ex)
    assert got["cnt"] == cnt
    assert got["cv"] == cv
    assert got["s"] == s


def test_reduce_with_retractions_sum_count():
    """Mixed +/- rows on sum/count only (min/max absent) — exact."""
    calls = (AggCall("count_star", None, "cnt"), AggCall("sum", "v", "s"))
    ex = HashAggExecutor(
        ["k"], calls, {"k": np.int64, "v": np.int64}, capacity=256
    )
    from risingwave_tpu.types import Op

    cols = {
        "k": np.array([1, 1, 2, 2, 1], np.int64),
        "v": np.array([10, 20, 5, 7, 10], np.int64),
    }
    ops = np.array(
        [Op.INSERT, Op.INSERT, Op.INSERT, Op.DELETE, Op.DELETE], np.int32
    )
    c = StreamChunk.from_numpy(cols, 8, ops=ops)
    ex.apply_stacked(stack_chunks([c]), mode="reduce")
    ex.on_barrier(None)
    snap_live = np.asarray(ex.table.live)
    keys = np.asarray(ex.table.keys[0])[snap_live].tolist()
    cnts = np.asarray(ex.state.accums["cnt"])[snap_live].tolist()
    sums = np.asarray(ex.state.accums["s"])[snap_live].tolist()
    got = dict(zip(keys, zip(cnts, sums)))
    assert got == {1: (1, 20)}  # k=2 netted to zero rows -> dead group


def test_reduce_minmax_retraction_latches():
    ex = HashAggExecutor(
        ["k"], (AggCall("min", "v", "mn"),),
        {"k": np.int64, "v": np.int64}, capacity=256,
    )
    from risingwave_tpu.types import Op

    c = StreamChunk.from_numpy(
        {"k": np.array([1, 1], np.int64), "v": np.array([5, 5], np.int64)},
        4,
        ops=np.array([Op.INSERT, Op.DELETE], np.int32),
    )
    ex.apply_stacked(stack_chunks([c]), mode="reduce")
    with pytest.raises(RuntimeError, match="materialized-input"):
        ex.on_barrier(None)
        ex.finish_barrier()


def test_fingerprint_collision_keys_not_merged(monkeypatch):
    """Two different keys forced onto the SAME fingerprint must stay
    separate groups (the raw key lanes split the sorted segment)."""

    real_hash128 = None
    from risingwave_tpu.ops import hashing

    real_hash128 = hashing.hash128

    def colliding(key_cols):
        h1, h2 = real_hash128(key_cols)
        return jax.numpy.zeros_like(h1) + 7, jax.numpy.zeros_like(h2) + 9

    monkeypatch.setattr(hashing, "hash128", colliding)
    try:
        from risingwave_tpu.ops.agg import reduce_by_key

        keys = (jax.numpy.asarray(np.array([3, 5, 3, 5, 5], np.int64)),)
        signs = jax.numpy.ones(5, jax.numpy.int64)
        sorted_keys, rep_valid, w, reduced, _ = reduce_by_key(
            keys, signs, (AggCall("count_star", None, "c"),), {}, {}
        )
        # colliding fingerprints may split one key into several
        # segments (unstable sort interleaves) — each hits the SAME
        # table slot downstream, so the invariant is that per-key
        # contributions SUM correctly and never cross keys
        reps = np.asarray(sorted_keys[0])[np.asarray(rep_valid)]
        ws = np.asarray(w)[np.asarray(rep_valid)]
        got = {}
        for k, v in zip(reps.tolist(), ws.tolist()):
            got[k] = got.get(k, 0) + v
        assert got == {3: 2, 5: 3}
    finally:
        monkeypatch.undo()


def test_bench_shape_q5_epoch_compiles_and_runs():
    """The exact q5 bench configuration (capacity 2^18, stacked epoch,
    hop pre-fusion) must be exercised by the suite — a green suite with
    a crashing bench is how round 2 ended."""
    import functools

    from risingwave_tpu.executors.hop_window import hop_step_fn
    from risingwave_tpu.queries.nexmark_q import (
        Q5_SLIDE_MS,
        Q5_WINDOW_MS,
        build_q5_lite,
    )
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )

    pre = functools.partial(
        hop_step_fn,
        ts_col="date_time",
        size_ms=Q5_WINDOW_MS,
        slide_ms=Q5_SLIDE_MS,
        out_start="window_start",
    )
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=10_000))
    cap = 8_192
    chunks = []
    done = 0
    while done < 60_000:  # a few full-size chunks, not the whole epoch
        ev = gen.next_events(cap)
        done += cap
        b = ev["bid"]
        if b and len(b["auction"]):
            chunks.append(
                StreamChunk.from_numpy(
                    {"auction": b["auction"], "date_time": b["date_time"]},
                    cap,
                )
            )
    q5 = build_q5_lite(capacity=1 << 18, state_cleaning=False)
    q5.agg.apply_stacked(stack_chunks(chunks), pre=pre, mode="reduce")
    q5.pipeline.barrier()
    assert len(q5.mview.snapshot()) > 0
