"""Nexmark q7 from SQL, end to end (VERDICT r4 missing #3).

The q7 shape — bids joined against their own per-window MAX — plans
from SQL as a SELF-join of two derived tables over one base stream.
The planner collapses the duplicate source to input side "both" and
the runtime feeds every source chunk to both join inputs.

Reference: e2e_test/nexmark/ q7 (join formulation), retracting agg
side through the join's delete/insert path.
"""

import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (
    BID_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.queries.nexmark_q import build_q7
from risingwave_tpu.sql import Catalog, StreamPlanner

pytestmark = pytest.mark.smoke

Q7_SQL = (
    "CREATE MATERIALIZED VIEW q7 AS "
    "SELECT b.auction, b.bidder, b.price, b.wstart FROM "
    "(SELECT auction, bidder, price, window_start AS wstart "
    " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)) AS b "
    "JOIN "
    "(SELECT max(price) AS maxprice, window_start AS mwstart "
    " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    " GROUP BY window_start) AS m "
    "ON b.wstart = m.mwstart AND b.price = m.maxprice"
)


def _bid_chunks(n, events=1500, cap=2048, rate=1000):
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=rate))
    out = []
    while len(out) < n:
        c = gen.next_chunks(events, cap)["bid"]
        if c is not None:
            out.append(c)
    return out


def _rows(mview):
    cols = mview.to_numpy()
    names = ("wstart", "auction", "bidder")
    price = cols.get("price", cols.get("maxprice"))
    return sorted(
        zip(*(np.asarray(cols[n]).tolist() for n in names), price.tolist())
    )


def test_q7_sql_matches_hand_built():
    """Several windows' worth of bids; each new window max retracts the
    previous max's join matches — SQL plan must land on exactly the
    hand-built pipeline's MV."""
    planner = StreamPlanner(Catalog({"bid": BID_SCHEMA}), capacity=1 << 14)
    mv = planner.plan(Q7_SQL)
    assert mv.inputs == {"bid": "both"}
    hand = build_q7(capacity=1 << 14, state_cleaning=False)
    for c in _bid_chunks(8):
        mv.pipeline.push_left(c)
        mv.pipeline.push_right(c)
        hand.pipeline.push_left(c)
        hand.pipeline.push_right(c)
        mv.pipeline.barrier()
        hand.pipeline.barrier()
    got, want = _rows(mv.mview), _rows(hand.mview)
    assert want  # multiple windows, non-trivial
    assert got == want


def test_q7_via_session_insert_routing():
    """Session-level: one INSERT into the base table reaches BOTH join
    sides (side='both' routing through the DML targets)."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE bid (auction BIGINT, bidder BIGINT, "
              "price BIGINT, date_time BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW q7 AS "
        "SELECT b.auction, b.bidder, b.price, b.wstart FROM "
        "(SELECT auction, bidder, price, window_start AS wstart "
        " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)) AS b "
        "JOIN "
        "(SELECT max(price) AS maxprice, window_start AS mwstart "
        " FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        " GROUP BY window_start) AS m "
        "ON b.wstart = m.mwstart AND b.price = m.maxprice"
    )
    s.execute(
        "INSERT INTO bid VALUES (1, 10, 100, 1000), (2, 11, 250, 2000), "
        "(3, 12, 250, 11000)"
    )
    out, _ = s.execute(
        "SELECT auction, price FROM q7 ORDER BY auction"
    )
    # window [0,10s): max 250 -> auction 2; window [10s,20s): auction 3
    assert list(out["auction"]) == [2, 3]
    assert list(out["price"]) == [250, 250]
    # a new max in window 0 RETRACTS auction 2's row
    s.execute("INSERT INTO bid VALUES (4, 13, 300, 3000)")
    out, _ = s.execute("SELECT auction, price FROM q7 ORDER BY auction")
    assert list(out["auction"]) == [3, 4]
    assert list(out["price"]) == [250, 300]
