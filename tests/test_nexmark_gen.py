"""Nexmark generator tests — spec invariants the queries rely on.

Reference semantics: src/connector/src/source/nexmark/source/reader.rs
wrapping the public Nexmark generator (1:3:46 proportions, chained ids,
hot-key skew, rate-driven timestamps).
"""

import numpy as np

from risingwave_tpu.connectors.nexmark import (
    AUCTION_PROPORTION,
    BID_PROPORTION,
    PERSON_PROPORTION,
    PROPORTION_DENOMINATOR,
    NexmarkConfig,
    NexmarkGenerator,
)


def test_proportions():
    g = NexmarkGenerator()
    ev = g.next_events(PROPORTION_DENOMINATOR * 100)
    assert len(ev["person"]["id"]) == PERSON_PROPORTION * 100
    assert len(ev["auction"]["id"]) == AUCTION_PROPORTION * 100
    assert len(ev["bid"]["auction"]) == BID_PROPORTION * 100


def test_determinism_and_continuity():
    a = NexmarkGenerator(seed=7)
    b = NexmarkGenerator(seed=7)
    e1, e2 = a.next_events(500), b.next_events(500)
    for stream in ("person", "auction", "bid"):
        for col in e1[stream]:
            np.testing.assert_array_equal(e1[stream][col], e2[stream][col])
    # continuing the stream differs from restarting it
    n1 = a.next_events(500)
    assert not np.array_equal(n1["bid"]["auction"], e1["bid"]["auction"])


def test_referential_integrity():
    """Every bid's auction id must already exist; every auction's seller
    must be an existing person id — the property q8/q20 joins rely on."""
    g = NexmarkGenerator()
    ev = g.next_events(50_000)
    auctions = set(ev["auction"]["id"].tolist())
    persons = set(ev["person"]["id"].tolist())
    # bids reference auctions generated so far (ids chain off event no.)
    assert set(ev["bid"]["auction"].tolist()) <= auctions
    assert set(ev["auction"]["seller"].tolist()) <= persons


def test_hot_key_skew():
    # the CURRENT hot auction moves with the stream (skew is temporally
    # local); the mechanism puts hot bids on ids divisible by the hot
    # ratio: P(multiple of 2) = 1/2 hot + 1/4 cold ~= 0.75 vs 0.5 uniform
    from risingwave_tpu.connectors.nexmark import FIRST_AUCTION_ID

    cfg = NexmarkConfig(hot_auction_ratio=2)
    g = NexmarkGenerator(cfg)
    ev = g.next_events(100_000)
    base0 = ev["bid"]["auction"] - FIRST_AUCTION_ID
    frac = np.mean(base0 % cfg.hot_auction_ratio == 0)
    assert frac > 0.65, f"hot mechanism absent: {frac:.3f}"


def test_timestamps_monotone_and_rate():
    cfg = NexmarkConfig(first_event_rate=1000)
    g = NexmarkGenerator(cfg)
    ev = g.next_events(10_000)
    ts = ev["bid"]["date_time"]
    assert (np.diff(ts) >= 0).all()
    # 10_000 events at 1000 events/s spans ~10s of event time
    span = max(
        ev[s]["date_time"].max() for s in ("person", "auction", "bid")
    ) - cfg.base_time_ms
    assert 9_000 <= span <= 10_100


def test_splits_partition_event_space():
    whole = NexmarkGenerator(seed=9)
    shared = NexmarkGenerator.make_dictionaries()
    parts = [
        NexmarkGenerator(seed=9, split_index=i, split_num=4, dictionaries=shared)
        for i in range(4)
    ]
    ev = whole.next_events(2000)
    split_events = [p.next_events(500) for p in parts]
    whole_bids = np.sort(ev["bid"]["date_time"])
    merged = np.sort(np.concatenate([e["bid"]["date_time"] for e in split_events]))
    np.testing.assert_array_equal(whole_bids, merged)


def test_chunk_edge():
    g = NexmarkGenerator()
    chunks = g.next_chunks(500, capacity=512)
    bids = chunks["bid"]
    out = bids.to_numpy()
    assert len(out["auction"]) == 460  # 46/50 * 500
    assert out["price"].dtype == np.int64
    assert (out["__op__"] == 0).all()  # source emits inserts
    # channel decodes through the shared dictionary
    names = g.dicts["channel"].decode(out["channel"][:10])
    assert set(names) <= {"Google", "Facebook", "Baidu", "Apple"}
