"""Batch depth: nested-loop (non-equi) joins, residual ON predicates,
and OVER() window functions in batch SELECT.

Reference: src/batch/src/executor/join/nested_loop_join.rs +
src/batch/src/executor/over_window.rs.
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _sess():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE lo (lk BIGINT, lv BIGINT)")
    s.execute("CREATE TABLE hi (hk BIGINT, hv BIGINT)")
    s.execute("INSERT INTO lo VALUES (1, 10), (2, 20), (3, 30)")
    s.execute("INSERT INTO hi VALUES (1, 15), (2, 5)")
    return s


def test_nl_inner_join_non_equi():
    s = _sess()
    out, _ = s.execute(
        "SELECT lv, hv FROM lo JOIN hi ON lo.lv < hi.hv ORDER BY lv, hv"
    )
    # 10 < 15 only
    assert list(out["lv"]) == [10]
    assert list(out["hv"]) == [15]


def test_nl_left_join_pads_nulls():
    s = _sess()
    out, _ = s.execute(
        "SELECT lv, hv FROM lo LEFT JOIN hi ON lo.lv < hi.hv "
        "ORDER BY lv"
    )
    assert list(out["lv"]) == [10, 20, 30]
    assert out["hv"][0] == 15
    assert out["hv"][1] is None or np.isnan(float(out["hv"][1]))
    assert out["hv"][2] is None or np.isnan(float(out["hv"][2]))


def test_equi_join_with_residual_predicate():
    s = _sess()
    out, _ = s.execute(
        "SELECT lv, hv FROM lo JOIN hi ON lo.lk = hi.hk AND lo.lv > hi.hv"
    )
    # keys match (1,1) lv=10>15 no; (2,2) 20>5 yes
    assert list(out["lv"]) == [20]
    assert list(out["hv"]) == [5]


def test_batch_over_window_rank_family():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    s.execute(
        "INSERT INTO t VALUES (1, 10), (1, 30), (1, 20), (2, 7), (2, 7)"
    )
    out, _ = s.execute(
        "SELECT g, v, row_number() OVER (PARTITION BY g ORDER BY v) "
        "AS rn, rank() OVER (PARTITION BY g ORDER BY v) AS rk "
        "FROM t ORDER BY g, v"
    )
    assert list(out["rn"]) == [1, 2, 3, 1, 2]
    assert list(out["rk"]) == [1, 2, 3, 1, 1]  # ties share rank


def test_batch_over_window_agg_and_lag():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (1, 30), (2, 5)")
    out, _ = s.execute(
        "SELECT g, v, sum(v) OVER (PARTITION BY g) AS sv, "
        "lag(v) OVER (PARTITION BY g ORDER BY v) AS pv "
        "FROM t ORDER BY g, v"
    )
    assert list(out["sv"]) == [40, 40, 5]
    assert out["pv"][0] is None or bool(out.get("pv__null", [0])[0]) or np.isnan(float(out["pv"][0]))
    assert out["pv"][1] == 10


def test_batch_over_trailing_rows_frame():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 1), (1, 2), (1, 3), (1, 4)")
    out, _ = s.execute(
        "SELECT v, sum(v) OVER (PARTITION BY g ORDER BY v "
        "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s2 "
        "FROM t ORDER BY v"
    )
    assert list(out["s2"]) == [1, 3, 5, 7]


def test_batch_over_desc_rank():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (10), (30), (20)")
    out, _ = s.execute(
        "SELECT v, rank() OVER (PARTITION BY v ORDER BY v) AS r1 FROM t "
        "ORDER BY v"
    )
    assert list(out["r1"]) == [1, 1, 1]


def test_running_sum_default_frame():
    """ORDER BY without a frame = RANGE UNBOUNDED..CURRENT: a running
    aggregate where peers share the frame end (review finding r5)."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (1, 20), (1, 30)")
    out, _ = s.execute(
        "SELECT v, sum(v) OVER (PARTITION BY g ORDER BY v) AS rs "
        "FROM t ORDER BY v"
    )
    # peers (the two 20s) both see 10+20+20 = 50
    assert list(out["rs"]) == [10, 50, 50, 80]


def test_null_partition_keys_form_their_own_partition():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE src (g BIGINT, v BIGINT)")
    s.execute("CREATE TABLE pad (pk BIGINT, w BIGINT)")
    s.execute("INSERT INTO src VALUES (1, 5), (2, 6)")
    s.execute("INSERT INTO pad VALUES (1, 100)")
    # LEFT JOIN makes w NULL-able (NaN lane) for g=2
    out, _ = s.execute(
        "SELECT v, row_number() OVER (PARTITION BY w ORDER BY v) AS rn "
        "FROM src LEFT JOIN pad ON src.g = pad.pk ORDER BY v"
    )
    assert list(out["rn"]) == [1, 1]  # NULL w rows form a partition


def test_lag_with_default_value():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (1), (2), (3)")
    out, _ = s.execute(
        "SELECT v, lag(v, 1, 0) OVER (PARTITION BY v ORDER BY v) AS p "
        "FROM t ORDER BY v"
    )
    assert list(out["p"]) == [0, 0, 0]  # default fills, no NULLs
    assert "p__null" not in out


def test_count_star_over_counts_rows():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 1), (1, 2), (2, 3)")
    out, _ = s.execute(
        "SELECT g, count(*) OVER (PARTITION BY g) AS c FROM t ORDER BY g"
    )
    assert list(out["c"]) == [2, 2, 1]


def test_same_side_equality_goes_residual():
    s = _sess()
    out, _ = s.execute(
        "SELECT lv, hv FROM lo JOIN hi ON lo.lk = hi.hk AND lo.lk = lo.lk"
    )
    assert sorted(out["lv"]) == [10, 20]


def test_distributed_window_falls_back_to_local():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (3), (1), (2), (4)")
    s.batch.distributed_tasks = 4
    out, _ = s.execute(
        "SELECT v, row_number() OVER (PARTITION BY v ORDER BY v) AS rn FROM t"
    )
    assert sorted(out["rn"]) == [1, 1, 1, 1]
    s.batch.distributed_tasks = 0
