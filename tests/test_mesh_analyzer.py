"""Mesh-readiness analyzer (analysis/mesh_analyzer.py): seeded RW-E9xx
violations classify with exact code + file:line provenance, a
hand-built shard_map-clean fragment earns a positive SPMD proof, the
blocker ranking uses the measured meshprof costs, the CLI emits JSON
on every exit path, and the shallow DDL pass stays inside its budget.
"""

import inspect
import json
import os
import time
from types import SimpleNamespace

import pytest

import risingwave_tpu  # noqa: F401 — installs the jax.shard_map shim
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from risingwave_tpu.analysis.diagnostics import PlanLintError
from risingwave_tpu.analysis.mesh_analyzer import (
    analyze_mesh_chain,
    attach_mesh_costs,
    classify_mesh_executor,
    _ranking,
    _top_cost,
)
from risingwave_tpu.analysis.shape_domain import ChunkSpec

N = 8
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS_FILE = "tests/test_mesh_analyzer.py"

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < N, reason=f"needs {N} (virtual) devices"
)


def _line_in(fn, marker: str) -> int:
    src, start = inspect.getsourcelines(fn)
    return start + next(i for i, ln in enumerate(src) if marker in ln)


def _contract(**over):
    base = {
        "axis": "shard",
        "n_shards": N,
        "state": {"t": "sharded"},
        "updates": ("t",),
        "dispatch": {
            "fn": "dest_shard",
            "keys": ("k",),
            "vnode_axis": "shard",
        },
        "exchange": "all_to_all",
        "donate": True,
        "order_insensitive": True,
        "trace_steps": None,
        "barrier_methods": ("on_barrier",),
        "emission": "stacked",
    }
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# seeded violations, one archetype per code
# ---------------------------------------------------------------------------


class _HostRoutedTwin:
    """The host-routed exchange archetype: barrier drain through
    np.asarray (E901) + one host-driven device pull per shard (E907)."""

    n_shards = N

    def mesh_contract(self):
        return _contract()

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self):
        outs = self._drain()
        rows = np.asarray(outs)  # <- E901: host flatten
        parts = []
        for s in range(self.n_shards):  # <- E907: per-dest fan-out
            parts.append(jax.device_get(outs))
        return rows, parts


def test_e901_host_routed_exchange_twin():
    ec = classify_mesh_executor(_HostRoutedTwin(), None, "t", 0, deep=False)
    e901 = [b for b in ec.blockers if b.code == "RW-E901"]
    assert e901, [b.code for b in ec.blockers]
    want = _line_in(_HostRoutedTwin.on_barrier, "np.asarray")
    assert any(
        b.file == THIS_FILE and b.line == want for b in e901
    ), [(b.file, b.line) for b in e901]


def test_e907_per_destination_fanout_twin():
    ec = classify_mesh_executor(_HostRoutedTwin(), None, "t", 0, deep=False)
    e907 = [b for b in ec.blockers if b.code == "RW-E907"]
    assert e907
    want = _line_in(_HostRoutedTwin.on_barrier, "for s in range")
    assert any(
        b.file == THIS_FILE and b.line == want for b in e907
    ), [(b.file, b.line) for b in e907]
    assert all(b.phase == "exchange_route" for b in e907)


class _MisKeyedAgg:
    """E902 archetype: dispatch outside the consistent-hash dest_shard
    path, axis mismatch, and no declared keys."""

    def mesh_contract(self):
        return _contract(
            dispatch={"fn": "my_hash", "keys": (), "vnode_axis": "x"}
        )

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self):
        return []


def test_e902_miskeyed_sharded_agg():
    ec = classify_mesh_executor(_MisKeyedAgg(), None, "t", 0, deep=False)
    assert {b.code for b in ec.blockers} == {"RW-E902"}
    assert len(ec.blockers) == 3  # fn, axis, keys
    want = inspect.getsourcelines(_MisKeyedAgg)[1]
    assert all(
        b.file == THIS_FILE and b.line == want for b in ec.blockers
    )
    assert any("dest_shard" in b.message for b in ec.blockers)


class _UnbucketedShardWindow:
    """E903 archetype: the per-shard step branches on a traced value
    (a data-dependent window extent) — shard_map cannot trace it."""

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            cap = int(abs_chunk.valid.shape[-1])

            def step(x):
                if x.sum() > 0:  # concretizes a tracer
                    return x
                return x * 2

            return [
                (
                    "apply",
                    step,
                    (jax.ShapeDtypeStruct((N, cap), jnp.int32),),
                )
            ]

        return _contract(trace_steps=trace_steps, barrier_methods=())

    def apply(self, chunk):
        return [chunk]


def test_e903_untraceable_per_shard_window():
    ec = classify_mesh_executor(
        _UnbucketedShardWindow(), None, "t", 0, deep=True
    )
    e903 = [b for b in ec.blockers if b.code == "RW-E903"]
    assert e903, [b.code for b in ec.blockers]
    assert not ec.spmd_proven and not ec.traced
    want = inspect.getsourcelines(_UnbucketedShardWindow)[1]
    assert e903[0].file == THIS_FILE and e903[0].line == want
    assert "Tracer" in e903[0].message or "Concretization" in e903[0].message


class _ReplicatedWriter:
    """E904 archetype: replicated state leaf in the update set."""

    def mesh_contract(self):
        return _contract(
            state={"t": "sharded", "cfg": "replicated"},
            updates=("t", "cfg"),
            barrier_methods=(),
        )

    def apply(self, chunk):
        return [chunk]


class _OrderSensitive:
    """E906 archetype: merge order not declared order-insensitive."""

    def mesh_contract(self):
        return _contract(order_insensitive=False, barrier_methods=())

    def apply(self, chunk):
        return [chunk]


def test_e904_replicated_state_written():
    ec = classify_mesh_executor(_ReplicatedWriter(), None, "t", 0, deep=False)
    assert {b.code for b in ec.blockers} == {"RW-E904"}
    assert ec.blockers[0].line == inspect.getsourcelines(_ReplicatedWriter)[1]
    assert "cfg" in ec.blockers[0].message


def test_e906_order_sensitive_merge():
    ec = classify_mesh_executor(_OrderSensitive(), None, "t", 0, deep=False)
    assert {b.code for b in ec.blockers} == {"RW-E906"}
    assert ec.blockers[0].file == THIS_FILE


class _RecountFlush:
    """E905 archetype: the flush drain loop's exit is gated by a device
    read — the exchange/flush output shape is data-dependent."""

    def mesh_contract(self):
        return _contract()

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self):
        outs = []
        for _ in range(4):
            delta = self._flush()
            outs.append(delta)
            if not bool(jnp.any(delta)):  # <- E905: host recount
                break
        return outs


def test_e905_data_dependent_flush_shape():
    ec = classify_mesh_executor(_RecountFlush(), None, "t", 0, deep=False)
    e905 = [b for b in ec.blockers if b.code == "RW-E905"]
    assert e905, [b.code for b in ec.blockers]
    want = _line_in(_RecountFlush.on_barrier, "if not bool")
    assert e905[0].file == THIS_FILE and e905[0].line == want
    assert e905[0].phase == "host_recount"


def test_boundary_executor_is_e901_edge():
    from risingwave_tpu.runtime.fragmenter import StackSplitExecutor

    ec = classify_mesh_executor(StackSplitExecutor(N), None, "t", 0)
    assert ec.kind == "boundary"
    assert [b.code for b in ec.blockers] == ["RW-E901"]
    b = ec.blockers[0]
    assert b.file == "risingwave_tpu/runtime/fragmenter.py"
    assert b.line == inspect.getsourcelines(StackSplitExecutor.apply)[1]


# ---------------------------------------------------------------------------
# positive proof: a hand-built shard_map-clean fragment
# ---------------------------------------------------------------------------


class _CleanSpmd:
    """Honest contract + a step that traces under shard_map over the
    8-device mesh with a real collective and no host routing."""

    def __init__(self):
        from risingwave_tpu.analysis.mesh_domain import virtual_mesh

        self.mesh = virtual_mesh(N, "shard")
        self.state = jnp.zeros((N, 1), jnp.int64)

    def apply(self, chunk):
        return [chunk]

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            from risingwave_tpu.analysis.mesh_domain import abstract_tree

            def local(state, vals):
                total = jax.lax.psum(jnp.sum(vals), "shard")
                return state + total

            step = jax.jit(
                jax.shard_map(
                    local,
                    mesh=self.mesh,
                    in_specs=(P("shard"), P("shard")),
                    out_specs=P("shard"),
                )
            )
            return [
                (
                    "apply",
                    step,
                    (abstract_tree(self.state), abs_chunk.columns["v"]),
                )
            ]

        return _contract(
            state={"state": "sharded"},
            updates=("state",),
            dispatch={
                "fn": "dest_shard",
                "keys": ("v",),
                "vnode_axis": "shard",
            },
            trace_steps=trace_steps,
            barrier_methods=(),
        )


def test_positive_proof_on_clean_fragment():
    spec = ChunkSpec.from_schema({"v": "int64"})
    rep = analyze_mesh_chain([_CleanSpmd()], spec, "clean", deep=True)
    assert not rep.blockers
    assert rep.executors[0].spmd_proven
    assert rep.executors[0].signatures >= 1
    assert rep.spmd_fusible and rep.proof is not None
    assert "psum" in rep.proof["collectives"]


def test_shallow_pass_never_mints_a_proof():
    spec = ChunkSpec.from_schema({"v": "int64"})
    rep = analyze_mesh_chain([_CleanSpmd()], spec, "clean", deep=False)
    assert not rep.blockers
    assert not rep.spmd_fusible and rep.proof is None


# ---------------------------------------------------------------------------
# measured-cost ranking
# ---------------------------------------------------------------------------


def test_ranking_uses_meshprof_costs():
    rep = analyze_mesh_chain([_HostRoutedTwin()], None, "t:frag", deep=False)
    mesh_block = {
        "phases_ms": {
            "host_split": 5.0,
            "host_flatten": 3.0,
            "host_other": 2.0,
        }
    }
    attach_mesh_costs([rep], mesh_block, n_shards=N)
    route = [b for b in rep.blockers if b.phase == "exchange_route"]
    assert route
    share = round(10.0 / len(route), 3)
    assert all(b.est_exchange_ms == share for b in route)
    assert all(
        b.est_dispatches_saved == N - 1
        for b in route
        if b.code == "RW-E907"
    )
    rows = _ranking({"q": [rep]})
    assert rows[0]["rank"] == 1 and rows[0]["est_exchange_ms"] == share
    top = _top_cost(rows)
    assert top["phase"] == "exchange_route"
    assert top["est_ms"] == pytest.approx(10.0, abs=0.01)


def test_committed_mesh_report_ranks_exchange_route():
    """The committed baseline satisfies the acceptance bar: every
    sharded fragment proves or carries provenance-bearing blockers,
    and the static ranking names the exchange route as top cost."""
    with open(os.path.join(ROOT, "MESH_REPORT.json")) as f:
        rep = json.load(f)
    assert rep["top_cost"]["phase"] == "exchange_route"
    for q in ("q5", "q7", "q8"):
        assert rep[q]["fragments"]
        for fr in rep[q]["fragments"]:
            assert fr["spmd_fusible"] or fr["blockers"]
            for b in fr["blockers"]:
                assert b["code"].startswith("RW-E")
                assert b["file"] and b["line"] > 0
    assert any(r["est_exchange_ms"] for r in rep["ranking"])


# ---------------------------------------------------------------------------
# the sharded corpus + DDL surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def q5_sharded():
    from risingwave_tpu.analysis.lint import build_sharded_nexmark_corpus

    corpus = build_sharded_nexmark_corpus(N, only="q5")
    yield corpus["q5"]
    corpus["q5"].pipeline.close()


def test_sharded_q5_classifies_with_blockers(q5_sharded):
    from risingwave_tpu.analysis.mesh_analyzer import (
        analyze_sharded_pipeline,
    )
    from risingwave_tpu.analysis.lint import NEXMARK_SOURCE_SCHEMAS

    reports = analyze_sharded_pipeline(
        q5_sharded.pipeline, NEXMARK_SOURCE_SCHEMAS["q5"], "q5", deep=False
    )
    assert reports
    for rep in reports:
        assert rep.spmd_fusible or rep.blockers
        for b in rep.blockers:
            assert b.file and b.line > 0
    codes = {b.code for rep in reports for b in rep.blockers}
    assert "RW-E901" in codes  # the stack/flatten boundary edges


def test_sharded_executors_declare_mesh_and_fallback_contracts(q5_sharded):
    from risingwave_tpu.runtime.fragmenter import (
        is_mesh_executor,
        sharded_chains,
    )

    mesh_exs = [
        ex
        for secs in sharded_chains(q5_sharded.pipeline).values()
        for chain in secs.values()
        for ex in chain
        if is_mesh_executor(ex)
    ]
    assert mesh_exs
    for ex in mesh_exs:
        tc = ex.trace_contract()
        assert tc["kind"] == "host"
        assert tc["fallback_syncs"], type(ex).__name__
        mc = ex.mesh_contract()
        assert mc["n_shards"] == N
        assert mc["dispatch"]["fn"] == "dest_shard"
        assert callable(mc["trace_steps"])


def test_boundary_lint_info_threads_schema():
    from risingwave_tpu.analysis.fusion_analyzer import (
        _lint_info,
        _thread_spec,
    )
    from risingwave_tpu.runtime.fragmenter import (
        FlattenExecutor,
        StackSplitExecutor,
    )

    spec = ChunkSpec.from_schema({"a": "int64"})
    for ex in (StackSplitExecutor(N), FlattenExecutor()):
        assert _thread_spec(spec, ex, _lint_info(ex)) == spec


def test_shallow_ddl_pass_budget(q5_sharded):
    from risingwave_tpu.analysis.lint import mesh_findings_for_ddl

    diags = mesh_findings_for_ddl(q5_sharded)  # warm the scan memo
    assert diags and all(d.severity == "warning" for d in diags)
    assert all(d.code.startswith("RW-E9") for d in diags)
    t0 = time.perf_counter()
    mesh_findings_for_ddl(q5_sharded)
    assert (time.perf_counter() - t0) < 0.1  # the <100ms/plan budget


def test_ddl_hook_noop_for_unsharded_plans():
    from risingwave_tpu.analysis.lint import (
        build_nexmark_corpus,
        mesh_findings_for_ddl,
    )

    q5 = build_nexmark_corpus(only="q5")["q5"]
    assert mesh_findings_for_ddl(q5) == []


def test_session_mesh_hook_reports_then_refuses(q5_sharded, monkeypatch):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog

    session = SqlSession(
        Catalog({}), StreamingRuntime(store=None), strict_lint=False
    )
    monkeypatch.delenv("RW_STRICT_MESH", raising=False)
    session._mesh_lint(q5_sharded, strict=True)  # report-only default
    codes = {d.code for _name, d in session.lint_findings}
    assert any(c.startswith("RW-E9") for c in codes)
    monkeypatch.setenv("RW_STRICT_MESH", "1")
    with pytest.raises(PlanLintError):
        session._mesh_lint(q5_sharded, strict=True)
    # replay-safe: strict=False (the replay path) records, never raises
    session._mesh_lint(q5_sharded, strict=False)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _cli_args(**over):
    base = dict(sharing_report=False, mesh_report=True, json=True)
    base.update(over)
    return SimpleNamespace(**base)


def test_cli_exits_2_when_mesh_unavailable(monkeypatch, capsys):
    from risingwave_tpu.analysis import mesh_domain
    from risingwave_tpu.analysis.lint import run_cli

    def _boom(n):
        raise mesh_domain.MeshUnavailable("jax already initialized")

    monkeypatch.setattr(mesh_domain, "ensure_virtual_devices", _boom)
    rc = run_cli(_cli_args())
    assert rc == 2
    out = json.loads(capsys.readouterr().out)  # JSON on EVERY exit path
    assert "already initialized" in out["error"]


@pytest.mark.slow
def test_cli_mesh_report_json(capsys):
    from risingwave_tpu.analysis.lint import run_cli

    rc = run_cli(_cli_args())
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert set(rep) >= {"q5", "q7", "q8", "ranking", "top_cost"}
    assert rep["top_cost"]["phase"] == "exchange_route"
    for q in ("q5", "q7", "q8"):
        assert rep[q]["summary"]["fragments"] >= 1
